"""Benchmark: the BASELINE ladder metric — committed reqs/sec with all
consensus crypto on the accelerator — plus honest kernel throughput.

Two measurements, one JSON line:

1. Ladder run (BASELINE.md rung 2 scale: 16 nodes f=5, 64 clients,
   BatchSize=200): a full testengine consensus run where every digest is
   computed by the batched SHA-256 kernel via the async crypto plane
   (testengine/crypto_plane.py — per-bucket chunks launched proactively so
   device work overlaps the event loop).  ``value`` is distinct committed
   reqs/sec wall-clock; ``vs_baseline`` compares against the identical run
   with the reference-style inline host hasher (reference:
   processor.go:133-143, testengine/recorder.go:445-455).
   ``p99_batch_digest_ms`` is the p99 blocking time of a crypto-plane
   chunk (launch + forced readback) — the Actions→Results round trip the
   consumer actually experiences.

2. Kernel throughput: chained compressions inside a single launch with a
   scalar-checksum readback and distinct inputs per call (see
   ops.sha256.sha256_chain_checksum for why — through an RPC-tunneled
   device, plain `block_until_ready` loops measure launch enqueue, not
   compute; earlier rounds' digests/s figures were inflated by exactly
   that).  Both the XLA scan kernel and the Pallas kernel
   (ops/sha256_pallas.py, full-VPU-tile layout) are measured; the Pallas
   digest path is additionally bit-exactness-gated against hashlib before
   its number counts.  Digests/s is derived for the 640-byte message
   shape (11 SHA-256 blocks), compared against single-thread hashlib.

Artifacts are crash-proof: besides the final JSON line on stdout, every
completed rung is immediately appended (fsynced) as one JSON line to
``$BENCH_STREAM_PATH`` (default ``BENCH_stream.jsonl``), so a SIGKILL or
driver timeout on the newest rung cannot erase the rungs that passed.
Compile-heavy rungs run an untimed warmup first and report ``compile_s``
separately; the ``soak`` rung samples RSS/fds/threads/disk under load
and emits leak verdicts that ``obsv --diff`` gates.
"""

import json
import os
import signal
import sys
import threading
import time

import numpy as np

# Wall-clock budget for the whole bench (seconds).  Must stay comfortably
# under the driver's hard timeout (870s): a run that trips the external
# timeout emits NO JSON at all, which is strictly worse than a run that
# skips its tail stages and reports what it measured.  Sized so the live
# rungs (incl. the app-KV and capacity-knee clusters) and the device
# ladder both fit: 600 + watchdog grace + margin still clears 870.
DEFAULT_BUDGET_S = 600.0

# The external harness kills the process outright at this wall time
# (override with BENCH_HARNESS_TIMEOUT_S).  The soft budget is clamped so
# budget + watchdog grace + margin always lands under it — an oversized
# BENCH_BUDGET_S must degrade to skipped tail stages, never to an rc=124
# kill that erases the final JSON (BENCH_r05's failure mode).
HARNESS_TIMEOUT_S = 870.0
HARNESS_MARGIN_S = 60.0

# Retrace budget for the whole-run device capture (override with
# BENCH_RETRACE_BUDGET).  The ladder legitimately sweeps batch shapes —
# every rung size is a distinct jit signature — so the bench budget is
# far looser than obsv.device.DEFAULT_RETRACE_BUDGET, which is sized
# for steady-state capture where shapes should be bucket-stable.
BENCH_RETRACE_BUDGET = 32

# Runway past the budget before the hard watchdog fires.  The StageRunner
# already times stages out cooperatively; the watchdog exists for the
# stage that CANNOT be timed out — a native call wedged while holding the
# GIL-adjacent resources join() needs — and must still leave comfortable
# margin under the driver's 870s kill.
WATCHDOG_GRACE_S = 60.0


def _enable_compile_cache():
    """Persistent XLA compilation cache: the Pallas Ed25519 ladder alone
    is minutes of Mosaic compile per shape — across bench runs (and test
    sessions) each shape should compile once per machine, ever."""
    import os

    import jax

    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

CHAIN_BATCH = 32768
CHAIN_ITERS = 4096  # 134M compressions/launch: compute well above RTT noise
CHAIN_REPS = 4
MSG_BYTES = 640  # 20 request acks x 32-byte digests -> 11 blocks
MSG_BLOCKS = 11

NODES = 16
CLIENTS = 64
REQS_PER_CLIENT = 100
BATCH_SIZE = 200

# Live rung: real Nodes over loopback TCP with on-disk WAL/reqstore, run
# once per executor kind.  Small batches on purpose — the serial ladder
# pays two fsyncs per Actions batch, so many small batches is exactly the
# regime the pipelined executor's group commit is built to amortize.
#
# The rung is deliberately durability-bound: the container's ext4 fsync
# (~0.15ms, virtualized page cache) is far cheaper than a production disk
# with real flush barriers, so each store's pre-fsync fault seam adds a
# fixed LIVE_FSYNC_FLOOR_S sleep — identically for both executor kinds —
# emulating commodity flush latency.  The serial ladder pays that floor
# inline on every Actions batch; the pipelined executor's group commit
# pays it once per coalesced group off the critical path.
#
# Measurement is time-to-target: clients propose a thin surplus
# (LIVE_CLIENTS * LIVE_REQS_PER_CLIENT > LIVE_TARGET_COMMITS) and the
# clock stops when any node has committed LIVE_TARGET_COMMITS requests.
# The surplus keeps batch formation fed through the tail, so the rate
# measures steady-state ordering throughput rather than the last
# half-filled batch.  Epoch rotation (checkpoint_interval) and suspect
# timeouts are pushed past the run so the rung measures the commit path,
# not view change — chaos/live.py owns the fault schedule.
LIVE_NODES = 4
LIVE_CLIENTS = 16
LIVE_REQS_PER_CLIENT = 110
LIVE_TARGET_COMMITS = 1600
LIVE_BATCH_SIZE = 10
LIVE_TICK_S = 0.5
LIVE_CHECKPOINT_INTERVAL = 50
LIVE_SUSPECT_TICKS = 10_000
LIVE_FSYNC_FLOOR_S = 0.040
LIVE_DEADLINE_S = 120.0

# Multi-process rung: the same 4-node consensus, but one real OS process
# per node (cluster/ supervisor + workers) under open-loop Poisson load
# from the loadgen package, stepped through LIVE_MP_RATE_STEPS offered
# rates.  Unlike the time-to-target live rung above, this one measures
# the latency *distribution* under a fixed offered rate — the SLO view —
# and emits a mirbft-loadgen-slo artifact under the payload's "loadgen"
# key that `obsv --diff` gates run-to-run.
LIVE_MP_NODES = 4
LIVE_MP_RATE_STEPS = (25.0, 50.0, 100.0)
LIVE_MP_STEP_DURATION_S = 2.0
LIVE_MP_DRAIN_S = 25.0
LIVE_MP_BATCH_SIZE = 4

# Reconfig A/B inside the mp rung (docs/RECONFIG.md): one Poisson rate
# measured twice on the same cluster — steady state, then again while a
# committed add-node reconfiguration adopts at the checkpoint boundary
# and the joiner boots via snapshot transfer.  The delta (goodput down,
# p95 up) prices the adoption reinitialize + epoch roll + joiner
# catch-up; both steps ride the same SLO artifact obsv --diff gates.
LIVE_MP_RECONFIG_RATE = 25.0
LIVE_MP_RECONFIG_STEP_S = 4.0
LIVE_MP_RECONFIG_ADMIN_CLIENT = 9
LIVE_MP_RECONFIG_CI = 5

# App rung: the replicated KV service's user-visible read/write SLOs
# (docs/APP.md) on an 8-process cluster — every op goes through the
# socket service: writes pay propose → consensus → apply → waiter
# wakeup, committed reads pay the read-index barrier plus a local state
# read.  Sessions run closed-loop under the loadgen KV client-model mix
# (uniform + Zipf hot-set keys, mixed payload sizes); the rung's
# read/write p50/p95/p99 + goodput ride under the payload's
# "loadgen_app" key so `obsv --diff` gates them run-to-run.
APP_NODES = 8
APP_SESSIONS = 4
APP_OPS_PER_SESSION = 40
# Closed-loop sessions keep at most APP_SESSIONS writes outstanding, so
# larger batches would never fill (there is no partial-batch cut timer);
# one request per batch measures the per-op path, not batch formation.
APP_BATCH_SIZE = 1
# Eight worker processes can outnumber the machine's cores; protocol
# timeouts are tick-denominated, so a generous tick keeps CPU-starvation
# scheduling gaps from reading as epoch suspicion (at 0.04s ticks a
# single-core box livelocks in perpetual epoch change and commits
# nothing).
APP_TICK_S = 0.25
APP_READ_RATIO = 0.5
APP_OP_TIMEOUT_S = 20.0

# Knee rung: max-sustainable-rate-at-SLO capacity search (loadgen/knee.py)
# on a real KNEE_NODES-process cluster.  A geometric rate ramp brackets
# the p95 cliff, then a binary search pins the knee; the traced config
# joins loadgen submit/commit records with the workers' clock-aligned
# trace.json milestones (obsv/critpath.py) to attribute which phase —
# ingress/hash/transmit/quorum/commit/apply — dominates each latency
# band at the knee, and on which node.  The mirbft-capacity/1 artifact
# rides under the payload's "capacity" key; `obsv --diff` gates
# knee_rate_per_sec like any other per_sec headline.  Honest clients
# only: retry-storm fanout inflates offered load past the nominal rate
# and smears the knee.  Tick follows APP_TICK_S — same 8-process
# CPU-starvation lesson as the app rung.
KNEE_NODES = 8
# One request per batch, same rationale as APP_BATCH_SIZE: there is no
# partial-batch cut timer, so larger batches add a fill-wait that reads
# as "ingress" latency at low rates and buries the congestion signal.
KNEE_BATCH_SIZE = 1
# Calibrated against the 8-process/0.25s-tick curve on a starved box
# (eight workers share whatever cores CI grants): near-idle p95 wanders
# 1-6s run to run, then commits collapse outright by ~96 req/s.  The
# SLO sits above the idle noise band so the *goodput* criterion — a
# probe must also commit KNEE_MIN_GOODPUT_RATIO of its offered rate —
# pins the knee at the collapse, which is the stable signal here.
KNEE_SLO_P95_MS = 8000.0
KNEE_MIN_GOODPUT_RATIO = 0.6
KNEE_START_RATE = 16.0
KNEE_MAX_RATE = 256.0
KNEE_STEP_DURATION_S = 2.0
KNEE_DRAIN_S = 12.0
# (name, processor, profile, traced, max_steps): the traced serial
# config is the headline and pays for per-phase attribution — on a
# starved box the serial processor's one worker thread per node keeps
# committing where the pipelined processor's extra stage threads (×8
# processes) starve each other into epoch suspicion, and the attribution
# source must be the config that reliably reaches its knee.  The
# pipelined config reuses the search under a tighter probe budget.
KNEE_CONFIGS = (
    ("serial-lan", "serial", "lan", True, 7),
    ("pipelined-lan", "pipelined", "lan", False, 4),
)

# Attack rung: the paper's request-duplication flood at the client seam
# — every submission delivered (1 + copies) times to every node.  The
# dedup tax is the goodput/p95 delta against a clean A/B baseline run in
# the same stage (not against live_serial, whose run doesn't record
# per-commit timestamps).
LIVE_ATTACK_COPIES = 3

# Soak rung: the resource-leak gate's evidence.  A small live cluster
# (pipelined executor, no fsync floor — the soak watches resources, not
# latency) runs under continuous client traffic for BENCH_SOAK_S seconds
# while obsv.resources samples RSS, fd count, thread count, and
# WAL/reqstore on-disk bytes; the rung reports least-squares leak
# verdicts that `obsv --diff` turns into a PR gate alongside the p95
# gates.
SOAK_NODES = 4
# Deliberately light load: all four consumers share one GIL, and pushing
# the cluster to saturation starves whichever node loses the scheduling
# race until transport queues overflow and it wedges — the soak measures
# resource *trends* under steady traffic, not peak throughput.
SOAK_CLIENTS = 4
SOAK_BATCH_SIZE = 10
SOAK_WINDOW = 4  # outstanding reqs per client, below the client width
SOAK_PUSH_S = 0.25
DEFAULT_SOAK_S = 30.0


# Ackplane rung: host vs device ack/quorum plane at >=100k clients (the
# docs/DEVICE_TRACKER.md rung).  Identical seeded ack storms — every
# node acks every client's req 0 in a shuffled order — are absorbed by a
# host-plane tracker (step_ack_many: scalar fallbacks + the _FastAcks
# columnar path) and by the device plane's column-native ingest
# (submit_columns + flush).  The frame size divides the client count so
# every device batch pads to one power-of-two bucket (one jit signature
# for the whole storm).  Each side's first frame is its untimed
# build/compile window; events/s compares steady state only.  Boundary
# drain (materializing adoptions/crossings back into the host objects)
# is device-plane-only cost and is reported separately.
ACKPLANE_CLIENTS = int(os.environ.get("BENCH_ACKPLANE_CLIENTS", "100000"))
ACKPLANE_FRAME = ACKPLANE_CLIENTS // 8
ACKPLANE_SOURCES = (1, 2, 3)
ACKPLANE_SEED = 0xACC5
ACKPLANE_AUDIT_SLOTS = 2048


def _ackplane_tracker(n_clients, ack_plane):
    """A standalone ClientTracker at bench scale (no engine): genesis
    checkpoint with n_clients width-1 windows, 4 nodes f=1."""
    from mirbft_tpu import pb
    from mirbft_tpu.core.client_tracker import ClientTracker
    from mirbft_tpu.core.msgbuffers import NodeBuffers
    from mirbft_tpu.core.persisted import Persisted

    state = pb.NetworkState(
        config=pb.NetworkConfig(
            nodes=[0, 1, 2, 3],
            f=1,
            number_of_buckets=4,
            checkpoint_interval=5,
            max_epoch_length=50,
        ),
        clients=[
            pb.NetworkClient(id=cid, width=1, low_watermark=0)
            for cid in range(n_clients)
        ],
    )
    persisted = Persisted()
    persisted.add_c_entry(
        pb.CEntry(
            seq_no=0, checkpoint_value=b"genesis", network_state=state
        )
    )
    my = pb.InitialParameters(id=0, buffer_size=1 << 20)
    ct = ClientTracker(persisted, NodeBuffers(my), my, ack_plane=ack_plane)
    ct.reinitialize()
    return ct


def ackplane_run(registry=None):
    """Host vs device ack plane under the same seeded ack storm.

    Returns the rung dict merged into the payload under ackplane_* keys:
    steady-state ack events/s per plane (and the device/host ratio),
    committed (strong-certified) reqs/s on the device plane, the
    boundary drain cost, the sampled divergence-oracle verdict, and a
    sampled cross-plane object-parity check.  Divergences are also
    recorded as ``mirbft_divergence_total`` so the standard device gate
    (``obsv --diff``) fails the run on any of them."""
    from mirbft_tpu import pb
    from mirbft_tpu.obsv import hooks, shadow

    n_clients = ACKPLANE_CLIENTS
    rng = np.random.default_rng(ACKPLANE_SEED)
    dig_mat = rng.integers(0, 256, size=(n_clients, 32), dtype=np.uint8)
    orders = {s: rng.permutation(n_clients) for s in ACKPLANE_SOURCES}

    if registry is not None:
        hooks.enable(registry=registry)
    try:
        # -- host plane ------------------------------------------------------
        host = _ackplane_tracker(n_clients, "host")
        host_build_s = host_steady_s = 0.0
        host_steady_events = 0
        first = True
        for s in ACKPLANE_SOURCES:
            order = orders[s]
            for lo in range(0, n_clients, ACKPLANE_FRAME):
                idx = order[lo : lo + ACKPLANE_FRAME]
                msgs = [
                    pb.Msg(
                        type=pb.RequestAck(
                            client_id=int(c),
                            req_no=0,
                            digest=dig_mat[c].tobytes(),
                        )
                    )
                    for c in idx.tolist()
                ]
                t0 = time.perf_counter()
                host.step_ack_many(s, msgs)
                dt = time.perf_counter() - t0
                if first:
                    host_build_s, first = dt, False
                else:
                    host_steady_s += dt
                    host_steady_events += len(msgs)

        # -- device plane ----------------------------------------------------
        devt = _ackplane_tracker(n_clients, "device")
        t0 = time.perf_counter()
        plane = devt._build_device() if devt._device_ok else None
        dev_build_s = time.perf_counter() - t0
        if plane is None:
            return {
                "host_events_per_sec": _round(
                    host_steady_events / host_steady_s
                    if host_steady_s
                    else None
                ),
                "device_events_per_sec": None,
                "detail": "device plane unavailable (no jax device)",
            }
        zeros = np.zeros(ACKPLANE_FRAME, dtype=np.int64)
        dev_compile_s = dev_steady_s = 0.0
        dev_steady_events = 0
        out_of_window = 0
        first = True
        for s in ACKPLANE_SOURCES:
            order = orders[s]
            for lo in range(0, n_clients, ACKPLANE_FRAME):
                idx = order[lo : lo + ACKPLANE_FRAME].astype(np.int64)
                t0 = time.perf_counter()
                out = plane.submit_columns(
                    s, idx, zeros[: len(idx)], dig_mat[idx]
                )
                plane.flush(drain=None)
                dt = time.perf_counter() - t0
                out_of_window += len(out)
                if first:
                    dev_compile_s, first = dt, False
                else:
                    dev_steady_s += dt
                    dev_steady_events += len(idx)

        # Boundary drain: adoptions, weak/strong crossings, ready marks
        # materialize into the host objects (column-only ingest, so any
        # fallback row raises — the zero-fallback gate).
        t0 = time.perf_counter()
        plane.drain_events(devt)
        drain_s = time.perf_counter() - t0
        # Quorum-certificate tally across every (client, window) bucket
        # in one device pass.
        t0 = time.perf_counter()
        certs = plane.quorum_sweep()
        sweep_s = time.perf_counter() - t0

        # Sampled divergence audit (the same oracle the chaos invariant
        # runs); any finding lands in mirbft_divergence_total and fails
        # the standard device gate.
        sample = rng.choice(
            n_clients,
            size=min(ACKPLANE_AUDIT_SLOTS, n_clients),
            replace=False,
        )
        slots = [int(c) * plane.w_pad for c in sample.tolist()]
        divs = shadow.audit_tracker(devt, slots=slots)
        if registry is not None:
            for d in divs:
                registry.counter(
                    "mirbft_divergence_total", component=d["component"]
                ).inc()

        # Cross-plane parity on the same sampled clients: both trackers
        # absorbed the identical storm, so the host objects must match
        # the device plane's authoritative state slot for slot (the
        # device-side *objects* hold stale lower bounds by contract, so
        # voter masks read from the device snapshot).
        from mirbft_tpu.core.device_tracker import _combine_limbs

        dev_snap = plane.host_snapshot()
        parity_mismatches = 0
        for c in sample.tolist():
            h = host.clients[c].req_no_map[0]
            d = devt.clients[c].req_no_map[0]
            slot = int(c) * plane.w_pad
            if (
                set(h.strong_requests) != set(d.strong_requests)
                or set(h.weak_requests) != set(d.weak_requests)
                or h.non_null_voters
                != _combine_limbs(dev_snap["nonnull"][slot])
            ):
                parity_mismatches += 1

        total_events = len(ACKPLANE_SOURCES) * n_clients
        host_rate = (
            host_steady_events / host_steady_s if host_steady_s else None
        )
        dev_rate = (
            dev_steady_events / dev_steady_s if dev_steady_s else None
        )
        committed_rate = (
            certs["strong_certs"] / (dev_steady_s + dev_compile_s + sweep_s)
            if dev_steady_s + dev_compile_s + sweep_s > 0
            else None
        )
        counters = {}
        if registry is not None:
            snap = registry.snapshot().get("mirbft_ack_events_total") or {}
            for series in snap.get("series", ()):
                plane_label = dict(series["labels"]).get("plane")
                counters[plane_label] = series["value"]
        return {
            "clients": n_clients,
            "events_total": total_events,
            "host_events_per_sec": _round(host_rate),
            "host_build_s": _round(host_build_s, 3),
            "device_events_per_sec": _round(dev_rate),
            "device_build_s": _round(dev_build_s, 3),
            "device_compile_s": _round(dev_compile_s, 3),
            "device_vs_host": (
                round(dev_rate / host_rate, 3)
                if dev_rate and host_rate
                else None
            ),
            "committed_reqs_per_sec": _round(committed_rate),
            "strong_certs": certs["strong_certs"],
            "weak_certs": certs["weak_certs"],
            "drain_seconds": _round(drain_s, 3),
            "sweep_seconds": _round(sweep_s, 3),
            "fallback_rows": plane.acks_fallback,
            "dropped_rows": plane.acks_dropped + out_of_window,
            "divergences": len(divs),
            "parity_mismatches": parity_mismatches,
            "ack_events_counter": counters,
        }
    finally:
        if registry is not None:
            hooks.disable()


def sha256_microbench_warmup():
    """Compile both chain kernels and the Pallas digest shape before the
    timed microbench: the stage's ``compile_s`` is this function's wall,
    its ``seconds`` the steady-state reps alone."""
    import jax

    from mirbft_tpu.ops.batching import pack_preimages
    from mirbft_tpu.ops.sha256 import sha256_chain_checksum
    from mirbft_tpu.ops.sha256_pallas import (
        sha256_chain_checksum_pallas,
        sha256_digest_words_pallas,
    )

    rng = np.random.default_rng(1)
    block = jax.device_put(
        rng.integers(0, 2**32, size=(CHAIN_BATCH, 16), dtype=np.uint32)
    )
    np.asarray(sha256_chain_checksum(block, iters=CHAIN_ITERS))
    np.asarray(sha256_chain_checksum_pallas(block, iters=CHAIN_ITERS))
    packed = pack_preimages([rng.bytes(MSG_BYTES)], batch_floor=1024)
    np.asarray(
        sha256_digest_words_pallas(
            packed.blocks, packed.n_blocks, interpret=False
        )
    )


def kernel_microbench():
    import hashlib

    import jax

    from mirbft_tpu.ops.batching import pack_preimages
    from mirbft_tpu.ops.sha256 import sha256_chain_checksum
    from mirbft_tpu.ops.sha256_pallas import (
        sha256_chain_checksum_pallas,
        sha256_digest_words_pallas,
    )

    rng = np.random.default_rng(0)

    def fresh_block():
        return jax.device_put(
            rng.integers(
                0, 2**32, size=(CHAIN_BATCH, 16), dtype=np.uint32
            )
        )

    def chained_rate(fn):
        np.asarray(fn(fresh_block(), iters=CHAIN_ITERS))  # compile
        times = []
        for _ in range(CHAIN_REPS):
            block = fresh_block()
            np.asarray(jax.numpy.sum(block, dtype=jax.numpy.uint32))
            start = time.perf_counter()
            np.asarray(fn(block, iters=CHAIN_ITERS))
            times.append(time.perf_counter() - start)
        return CHAIN_BATCH * CHAIN_ITERS / min(times)

    xla_rate = chained_rate(sha256_chain_checksum)
    pallas_rate = chained_rate(
        lambda block, iters: sha256_chain_checksum_pallas(block, iters=iters)
    )
    # The Pallas digest path must agree with hashlib before its rate
    # counts.  batch_floor=1024 (one full VPU tile) matters: smaller
    # batches take the sub-tile XLA fallback and the gate would
    # silently validate the wrong kernel.
    sample = [rng.bytes(MSG_BYTES) for _ in range(64)]
    packed = pack_preimages(sample, batch_floor=1024)
    words = np.asarray(
        sha256_digest_words_pallas(
            packed.blocks, packed.n_blocks, interpret=False
        )
    )
    for i, m in enumerate(sample):
        assert (
            words[i].astype(">u4").tobytes() == hashlib.sha256(m).digest()
        ), "pallas digest mismatch!"

    compressions_rate = max(xla_rate, pallas_rate)
    kernel_digest_rate = compressions_rate / MSG_BLOCKS

    messages = [rng.bytes(MSG_BYTES) for _ in range(8192)]
    start = time.perf_counter()
    for m in messages:
        hashlib.sha256(m).digest()
    host_rate = len(messages) / (time.perf_counter() - start)

    return xla_rate, pallas_rate, kernel_digest_rate, host_rate


READY_LATENCY_MS = 400  # modeled Actions→Results crypto-plane RTT


def ladder_run(hash_plane=None):
    from mirbft_tpu.testengine.engine import BasicRecorder, RuntimeParameters

    start = time.perf_counter()
    rec = BasicRecorder(
        NODES,
        CLIENTS,
        REQS_PER_CLIENT,
        batch_size=BATCH_SIZE,
        # ready_latency models the crypto plane's round trip (the reference
        # models 50ms for an in-process hasher, recorder.go:649-656; a
        # device round trip is honestly slower).  Applied identically to
        # both the kernel and the host-baseline run, it also gives the
        # async plane a realistic pipelining window: results are not
        # consumed the instant they are submitted.
        params=RuntimeParameters(ready_latency=READY_LATENCY_MS),
        hash_plane=hash_plane,
        # Steady-state timing: the in-memory recorded-events list is not
        # consensus work and dominates the wall now that the event count
        # is small (an interceptor-based recorder would be the production
        # path at this scale).
        record=False,
    )
    events = rec.drain_clients(max_steps=20_000_000)
    wall = time.perf_counter() - start
    chains = {rec.node_states[n].app_chain for n in range(NODES)}
    assert len(chains) == 1, "nodes diverged!"
    return wall, events, chains.pop(), rec.now


def warm_kernel_shapes(plane):
    """Compile every launch shape the ladder run can produce (request/ack
    preimages pad to the 1-block bucket, full BatchSize-200 batch preimages
    — 200 acks x 32B = 101 blocks — to the 128-block bucket, and partially
    filled batches to any bucket between) so the timed run measures steady
    state rather than XLA compile time."""
    import jax.numpy as jnp

    from mirbft_tpu.ops.sha256 import sha256_digest_words

    for bucket in (1, 2, 4, 8, 16, 32, 64, 128):
        rows = plane.rows_for(bucket)
        blocks = jnp.zeros((rows, bucket, 16), dtype=jnp.uint32)
        n = jnp.ones((rows,), dtype=jnp.int32)
        np.asarray(sha256_digest_words(blocks, n))


def ed25519_microbench_warmup(batch: int = 4096):
    """Compile the Pallas verify pipeline for the microbench's batch
    shape (a minutes-scale Mosaic compile on a cold cache) outside the
    timed window."""
    from mirbft_tpu.crypto import ed25519_host as ed_host
    from mirbft_tpu.ops.ed25519_pallas import verify_batch_pallas

    seed = (0).to_bytes(32, "little")
    msg = b"bench-warmup"
    pk, sig = ed_host.public_key(seed), ed_host.sign(seed, msg)
    assert all(verify_batch_pallas([pk] * batch, [msg] * batch, [sig] * batch))


def ed25519_microbench(batch: int = 4096):
    """Batched signature verification (ladder rung 3): the full Pallas
    pipeline (device point decompression + 4-bit windowed Shamir ladder,
    ops/ed25519_pallas.py) vs the pure-Python host oracle (the only host
    verifier in this environment — no libsodium).  Distinct signatures per
    timed call; validity is cross-checked so a broken kernel cannot post a
    number."""
    from mirbft_tpu.crypto import ed25519_host as ed_host
    from mirbft_tpu.ops.ed25519_pallas import verify_batch_pallas

    corpus = []
    for i in range(batch):
        seed = i.to_bytes(32, "little")
        msg = b"bench-request-%d" % i
        corpus.append((ed_host.public_key(seed), msg, ed_host.sign(seed, msg)))
    pks, msgs, sigs = map(list, zip(*corpus))

    got = verify_batch_pallas(pks, msgs, sigs)  # compile + warm the shape
    assert all(got)
    times = []
    for rep in (b"!", b"?"):  # distinct inputs per timed call; best-of-2
        flipped = [m + rep for m in msgs]
        start = time.perf_counter()
        got = verify_batch_pallas(pks, flipped, sigs)
        times.append(time.perf_counter() - start)
        assert not any(got)  # every flipped message must be rejected
    kernel_rate = batch / min(times)

    sample = 64
    start = time.perf_counter()
    for pk, msg, sig in corpus[:sample]:
        assert ed_host.verify(pk, msg, sig)
    host_rate = sample / (time.perf_counter() - start)
    return kernel_rate, host_rate


RUNG3_NODES = 64
RUNG3_CLIENTS = 1024
RUNG3_REQS = 8


def rung3_run():
    """BASELINE ladder rung 3: 64 nodes f=21, 1024 Ed25519-signed clients,
    speculative batched ingress verification (docs/CRYPTO.md).

    Clients pre-sign their streams before the clock starts (client-side
    work, not replica throughput).  Requests are admitted optimistically
    and their signatures verify in chunk-bounded bursts off the critical
    path — through the accelerator kernel when the device holds verify
    authority, else the host RLC batch authority — so the rung runs on
    any backend.  Returns (committed reqs/s, verify p99 ms, events,
    verified count)."""
    from mirbft_tpu import pb
    from mirbft_tpu.crypto import ed25519_host as ed_host
    from mirbft_tpu.testengine.engine import BasicRecorder
    from mirbft_tpu.testengine.signing import (
        SpeculativeSignaturePlane,
        client_seed,
        register_pk,
        signing_message,
    )

    client_ids = [RUNG3_NODES + i for i in range(RUNG3_CLIENTS)]
    state = pb.NetworkState(
        config=pb.NetworkConfig(
            nodes=list(range(RUNG3_NODES)),
            f=(RUNG3_NODES - 1) // 3,
            # Few buckets / short checkpoint interval: tames the
            # O(buckets * n^2) heartbeat traffic at pod scale (same
            # prescription as the engine's 128/256-node configs).
            number_of_buckets=8,
            checkpoint_interval=40,
            max_epoch_length=400,
        ),
        clients=[
            pb.NetworkClient(id=c, width=8, low_watermark=0)
            for c in client_ids
        ],
    )

    presigned = {}
    for cid in client_ids:
        seed = client_seed(cid)
        pk = ed_host.public_key(seed)
        # Client setup registers its key with the replicas (configuration,
        # like the network state) — replica-side verification must never
        # pay the pure-Python key derivation.
        register_pk(cid, pk)
        for rn in range(RUNG3_REQS):
            payload = b"%d:%d" % (cid, rn)
            sig = ed_host.sign(seed, signing_message(cid, rn, payload))
            presigned[(cid, rn)] = payload + sig + pk

    # Authority-gated: device kernel bursts on TPU/GPU, host RLC bursts
    # on CPU (kernel_authority()).  No warmup needed — the host batch
    # authority has no compile step, and on device the breaker absorbs a
    # cold first burst.
    plane = SpeculativeSignaturePlane()

    start = time.perf_counter()
    rec = BasicRecorder(
        RUNG3_NODES,
        RUNG3_CLIENTS,
        RUNG3_REQS,
        batch_size=200,
        network_state=state,
        signer=lambda cid, rn, _payload: presigned[(cid, rn)],
        signature_plane=plane,
        record=False,
    )
    events = rec.drain_clients(max_steps=50_000_000)
    wall = time.perf_counter() - start
    chains = {rec.node_states[n].app_chain for n in range(RUNG3_NODES)}
    assert len(chains) == 1, "rung-3 nodes diverged!"
    total = RUNG3_CLIENTS * RUNG3_REQS
    assert all(rec.committed_at(n) == total for n in range(RUNG3_NODES))
    flush_ms = sorted(1e3 * s for s in plane.flush_wall_s)
    p99_ms = flush_ms[min(len(flush_ms) - 1, int(0.99 * len(flush_ms)))]
    stats = {
        "rung3_speculative_admits": plane.admitted,
        "rung3_speculative_evictions": plane.speculative_evictions,
        "rung3_forced_joins": plane.forced_joins,
        "rung3_device_verifies": plane.device_verifies,
        "rung3_host_verifies": plane.host_verifies,
    }
    return total / wall, p99_ms, events, sum(plane.flush_sizes), stats, rec.now


RUNG4_NODES = 128
RUNG4_CLIENTS = 32
RUNG4_REQS = 16


def rung4_run():
    """BASELINE ladder rung 4: 128-node WAN (30ms frame jitter + an
    early-window targeted drop mangler), 4 rotating leader buckets, BLS
    checkpoint quorum certificates aggregated on device.

    Returns (reqs/s, events, cert count, aggregate wall ms)."""
    from mirbft_tpu import pb
    from mirbft_tpu.testengine.certs import CheckpointCertPlane
    from mirbft_tpu.testengine.engine import BasicRecorder, RuntimeParameters
    from mirbft_tpu.testengine.manglers import (
        from_source,
        is_step,
        percent,
        rule,
        until_time,
    )

    f = (RUNG4_NODES - 1) // 3
    client_ids = [RUNG4_NODES + i for i in range(RUNG4_CLIENTS)]
    state = pb.NetworkState(
        config=pb.NetworkConfig(
            nodes=list(range(RUNG4_NODES)),
            f=f,
            number_of_buckets=4,
            checkpoint_interval=20,
            max_epoch_length=200,
        ),
        clients=[
            pb.NetworkClient(id=c, width=16, low_watermark=0)
            for c in client_ids
        ],
    )
    certs = CheckpointCertPlane(quorum=2 * f + 1, use_device=True)
    start = time.perf_counter()
    rec = BasicRecorder(
        RUNG4_NODES,
        RUNG4_CLIENTS,
        RUNG4_REQS,
        batch_size=20,
        network_state=state,
        record=False,
        checkpoint_certs=certs,
        params=RuntimeParameters(link_jitter=30),
        # Targeted fault: half of node 120's frames die in the first two
        # simulated seconds (cheap to fold, recovers via rebroadcast).
        manglers=[
            rule(
                from_source(120), is_step(), percent(50), until_time(2000)
            ).drop()
        ],
    )
    rec.drain_clients(max_steps=20_000_000)
    # Run on until at least one checkpoint quorum has formed.
    extra = 0
    while not (certs._pending or certs._certs) and extra < 2_000_000:
        rec.step()
        extra += 1
    wall = time.perf_counter() - start
    chains = {rec.node_states[n].app_chain for n in range(RUNG4_NODES)}
    assert len(chains) == 1, "rung-4 nodes diverged!"
    total = RUNG4_CLIENTS * RUNG4_REQS
    start = time.perf_counter()
    certificates = certs.certificates()
    agg_ms = 1e3 * (time.perf_counter() - start)
    assert certificates, "no checkpoint certificates formed"
    (seq, value), (signers, asig) = sorted(certificates.items())[0]
    assert CheckpointCertPlane.verify(seq, value, signers, asig)
    assert not CheckpointCertPlane.verify(seq, value + b"!", signers, asig)
    return total / wall, rec.event_count, len(certificates), agg_ms, rec.now


RUNG5_NODES = 256
RUNG5_CLIENTS = 1024
RUNG5_REQS = 1


def rung5_run():
    """BASELINE ladder rung 5, scaled to the single-process Python
    budget: 256 nodes f=85 under WAN jitter, 1024 clients, and a
    state-transfer storm ingredient (a follower crashes mid-run, stays
    down past checkpoint GC, restarts, and must recover).  The full
    10k-client + forced-epoch-change storm runs as the HEAVY-gated
    correctness tests (tests/test_testengine.py): a 256-node epoch
    change is ~n^3 messages and exceeds any reasonable bench budget on
    the host event loop.

    Returns (reqs/s, events)."""
    from mirbft_tpu import pb
    from mirbft_tpu.testengine.engine import BasicRecorder, RuntimeParameters

    f = (RUNG5_NODES - 1) // 3
    client_ids = [RUNG5_NODES + i for i in range(RUNG5_CLIENTS)]
    state = pb.NetworkState(
        config=pb.NetworkConfig(
            nodes=list(range(RUNG5_NODES)),
            f=f,
            number_of_buckets=4,
            checkpoint_interval=20,
            max_epoch_length=200,
        ),
        clients=[
            pb.NetworkClient(id=c, width=2, low_watermark=0)
            for c in client_ids
        ],
    )
    start = time.perf_counter()
    rec = BasicRecorder(
        RUNG5_NODES,
        RUNG5_CLIENTS,
        RUNG5_REQS,
        batch_size=200,
        network_state=state,
        record=False,
        params=RuntimeParameters(link_jitter=20),
    )
    # Storm ingredient: a follower dies mid-run, misses checkpoint GC,
    # and must state-transfer back in.
    for _ in range(20_000):
        rec.step()
    rec.crash(200)
    for _ in range(40_000):
        rec.step()
    rec.schedule_restart(200, delay=0)
    events = rec.drain_clients(max_steps=50_000_000)
    wall = time.perf_counter() - start
    chains = {rec.node_states[n].app_chain for n in range(RUNG5_NODES)}
    assert len(chains) == 1, "rung-5 nodes diverged!"
    total = RUNG5_CLIENTS * RUNG5_REQS
    assert all(
        rec.committed_at(n) == total for n in range(RUNG5_NODES)
    ), "rung-5 missing commits"
    return total / wall, events, rec.now


class _MemChainLog:
    """In-memory hash-chain application for the live rung: the commit
    stage's own cost (one fsync per apply in the chaos harness) would
    mask the persist/transmit overlap this rung measures, so the bench
    app hashes but never touches disk — durability is the WAL's job."""

    def __init__(self):
        import hashlib

        self._hashlib = hashlib
        self.chain = b""
        self.commits: set = set()  # {(client_id, req_no)}
        # First-commit instants, for the attack rung's p95 (perf_counter).
        self.commit_times: dict = {}  # {(client_id, req_no): when}

    def apply(self, q_entry) -> None:
        for ack in q_entry.requests:
            h = self._hashlib.sha256()
            h.update(self.chain)
            h.update(ack.digest)
            self.chain = h.digest()
            key = (ack.client_id, ack.req_no)
            if key not in self.commits:
                self.commits.add(key)
                self.commit_times[key] = time.perf_counter()

    def snap(self, network_config, clients_state) -> bytes:
        return self.chain


class _SoakChainLog:
    """Chain log for the soak rung with O(outstanding-window) commit
    accounting: per client, the contiguous committed prefix (``floor`` =
    next uncommitted req_no) plus the sparse set of out-of-order commits
    above it.  _MemChainLog's ever-growing commit set/latency map is fine
    for a fixed-size rung but would itself read as an RSS leak over a long
    soak — the harness must not trip the gate it implements."""

    def __init__(self, clients):
        import hashlib

        self._hashlib = hashlib
        self.chain = b""
        self.total = 0
        self._floor = {cid: 0 for cid in clients}
        self._above = {cid: set() for cid in clients}

    def apply(self, q_entry) -> None:
        for ack in q_entry.requests:
            h = self._hashlib.sha256()
            h.update(self.chain)
            h.update(ack.digest)
            self.chain = h.digest()
            floor = self._floor.get(ack.client_id)
            if floor is None or ack.req_no < floor:
                continue
            above = self._above[ack.client_id]
            if ack.req_no in above:
                continue
            above.add(ack.req_no)
            self.total += 1
            while floor in above:
                above.discard(floor)
                floor += 1
            self._floor[ack.client_id] = floor

    def snap(self, network_config, clients_state) -> bytes:
        return self.chain

    def committed(self, cid: int) -> int:
        return self._floor[cid] + len(self._above[cid])

    def missing(self, cid: int, below: int) -> list:
        """Uncommitted req_nos < ``below``, O(outstanding window)."""
        above = self._above[cid]
        return [
            rn for rn in range(self._floor[cid], below) if rn not in above
        ]


def live_cluster_rate(kind: str, flood_copies: int = 0, detailed: bool = False):
    """Committed reqs/sec on a real loopback TCP cluster under executor
    ``kind``: LIVE_NODES real Nodes (serializer threads, real sockets,
    on-disk WAL/reqstore with real fsyncs plus the emulated flush-latency
    floor), one consumer thread per node driving ``build_processor(kind)``,
    measured from first proposal until any node has committed
    LIVE_TARGET_COMMITS requests.

    ``flood_copies`` > 0 turns the client seam hostile: every submission
    is delivered (1 + copies) times to every node — the paper's
    request-duplication attack; dedup absorbs the echoes and the rung
    prices what that costs.  With ``detailed`` the return value is
    ``(rate, p95_commit_ms, flooded)`` — per-request commit latency from
    first submission to first commit on the winning node — instead of the
    bare rate."""
    import shutil
    import tempfile

    from mirbft_tpu import pb
    from mirbft_tpu.runtime import (
        Config,
        FileRequestStore,
        FileWal,
        Node,
        TcpTransport,
        build_processor,
    )
    from mirbft_tpu.runtime.node import (
        NodeStopped,
        standard_initial_network_state,
    )

    root = tempfile.mkdtemp(prefix=f"mirbft-bench-live-{kind}-")
    clients = list(range(1, LIVE_CLIENTS + 1))
    state = standard_initial_network_state(LIVE_NODES, clients)
    # Defer planned epoch rotation past the run: rotation triggers state
    # transfer on lagging nodes, which this throughput rung has no
    # business measuring (the chaos campaign covers it).
    state.config.checkpoint_interval = LIVE_CHECKPOINT_INTERVAL
    state.config.max_epoch_length = 10 * LIVE_CHECKPOINT_INTERVAL
    nodes, transports, processors = [], [], []
    wals, stores, logs = [], [], []
    stop = threading.Event()
    threads = []
    failures: list = []

    def consume(node, processor, tick_s=LIVE_TICK_S):
        last_tick = time.monotonic()
        try:
            while not stop.is_set():
                actions = node.ready(timeout=0.01)
                if actions is not None:
                    results = processor.process(actions)
                    if results.digests or results.checkpoints:
                        node.add_results(results)
                now = time.monotonic()
                if now - last_tick >= tick_s:
                    last_tick = now
                    node.tick()
        except NodeStopped:
            pass
        except Exception as exc:  # noqa: BLE001 — surfaced as stage error
            failures.append(exc)

    try:
        for n in range(LIVE_NODES):
            node_dir = os.path.join(root, f"node{n}")
            os.makedirs(node_dir)
            wal = FileWal(os.path.join(node_dir, "wal"))
            store = FileRequestStore(os.path.join(node_dir, "reqs"))
            # Emulated flush-barrier latency on every fsync, via the
            # stores' pre-fsync fault seam (identical for both kinds).
            wal.fault_hook = lambda: time.sleep(LIVE_FSYNC_FLOOR_S)
            store.fault_hook = lambda: time.sleep(LIVE_FSYNC_FLOOR_S)
            app_log = _MemChainLog()
            node = Node.start_new(
                Config(
                    id=n,
                    batch_size=LIVE_BATCH_SIZE,
                    processor=kind,
                    suspect_ticks=LIVE_SUSPECT_TICKS,
                ),
                state,
            )
            transport = TcpTransport(
                n, backoff_base=0.02, backoff_cap=0.25, dial_timeout=1.0
            )
            transport.serve(node)
            processor = build_processor(
                node, transport.link(), app_log, wal, store
            )
            nodes.append(node)
            transports.append(transport)
            processors.append(processor)
            wals.append(wal)
            stores.append(store)
            logs.append(app_log)
        for n in range(LIVE_NODES):
            for m in range(LIVE_NODES):
                if n != m:
                    transports[n].connect(m, transports[m].address)
        for n in range(LIVE_NODES):
            thread = threading.Thread(
                target=consume,
                args=(nodes[n], processors[n]),
                name=f"bench-live-consumer-{n}",
                daemon=True,
            )
            threads.append(thread)
            thread.start()

        expected = {
            (client_id, req_no)
            for client_id in clients
            for req_no in range(LIVE_REQS_PER_CLIENT)
        }

        propose_times: dict = {}  # first-submission instants
        flood_count = [0]

        def propose(pending):
            for client_id, req_no in sorted(pending):
                request = pb.Request(
                    client_id=client_id, req_no=req_no, data=b"%d" % req_no
                )
                propose_times.setdefault(
                    (client_id, req_no), time.perf_counter()
                )
                for node in nodes:
                    for _copy in range(1 + flood_copies):
                        try:
                            node.propose(request)
                        except (NodeStopped, ValueError):
                            pass
                    flood_count[0] += flood_copies

        start = time.perf_counter()
        deadline = start + LIVE_DEADLINE_S
        propose(expected)
        elapsed = None
        last_retry = time.monotonic()
        while time.perf_counter() < deadline:
            if failures:
                raise failures[0]
            if max(len(log.commits) for log in logs) >= LIVE_TARGET_COMMITS:
                elapsed = time.perf_counter() - start
                break
            now = time.monotonic()
            if now - last_retry >= 0.5:
                # Re-propose stragglers (below-watermark acks are dropped
                # as PAST, so duplicates are harmless).
                last_retry = now
                propose(expected - min(logs, key=lambda l: len(l.commits)).commits)
            time.sleep(0.005)
        if elapsed is None:
            commits = [len(log.commits) for log in logs]
            raise RuntimeError(
                f"live rung ({kind}) did not reach {LIVE_TARGET_COMMITS} "
                f"commits within {LIVE_DEADLINE_S:.0f}s "
                f"(per-node commits: {commits})"
            )
        rate = LIVE_TARGET_COMMITS / elapsed
        if not detailed:
            return rate
        winner = max(logs, key=lambda l: len(l.commits))
        latencies = sorted(
            1e3 * (when - propose_times[key])
            for key, when in winner.commit_times.items()
            if key in propose_times
        )
        p95_ms = (
            latencies[min(len(latencies) - 1, int(0.95 * len(latencies)))]
            if latencies
            else None
        )
        return rate, p95_ms, flood_count[0]
    finally:
        stop.set()
        for thread in threads:
            thread.join(timeout=10)
        for processor in processors:
            closer = getattr(processor, "close", None)
            if closer is not None:
                closer()  # graceful: drain in-flight, flush group syncers
        for transport in transports:
            transport.close(0)
        for node in nodes:
            node.stop()
        for wal in wals:
            wal.close()
        for store in stores:
            store.close()
        shutil.rmtree(root, ignore_errors=True)


def live_attack_run():
    """Clean-vs-flood A/B on the live TCP cluster: the same serial
    executor first under honest clients, then under a client-seam
    duplication flood (every submission delivered 1+LIVE_ATTACK_COPIES
    times to every node — the Mir paper's request-duplication attack).
    Both halves record per-commit latency, so the rung prices the dedup
    tax in goodput *and* tail latency rather than just surviving the
    flood (the chaos campaign owns the correctness half)."""
    clean_rate, clean_p95, _ = live_cluster_rate("serial", detailed=True)
    attack_rate, attack_p95, flooded = live_cluster_rate(
        "serial", flood_copies=LIVE_ATTACK_COPIES, detailed=True
    )
    return clean_rate, clean_p95, attack_rate, attack_p95, flooded


def live_mp_run(kind: str):
    """One open-loop load run against a real multi-process cluster
    under executor ``kind``: LIVE_MP_NODES worker processes, Poisson
    arrivals stepped through LIVE_MP_RATE_STEPS, the standard hostile
    client mix (honest / slow+mixed-size / retry-storm).  Returns
    ``(steps, goodput_at_top_rate, p95_at_top_rate)`` where ``steps``
    are loadgen StepResults ready for the SLO artifact."""
    from mirbft_tpu import loadgen
    from mirbft_tpu.cluster import ClusterSupervisor

    client_ids = [1, 2, 3]
    supervisor = ClusterSupervisor(
        node_count=LIVE_MP_NODES,
        client_ids=client_ids,
        batch_size=LIVE_MP_BATCH_SIZE,
        processor=kind,
    )
    try:
        supervisor.start()
        generator = loadgen.LoadGenerator(
            supervisor,
            loadgen.standard_client_models(client_ids),
            seed=11,
        )
        steps = []
        for rate in LIVE_MP_RATE_STEPS:
            steps.append(
                generator.run_step(
                    f"{kind}-poisson-{int(rate)}",
                    loadgen.PoissonArrivals(rate, seed=int(rate)),
                    duration_s=LIVE_MP_STEP_DURATION_S,
                    drain_s=LIVE_MP_DRAIN_S,
                )
            )
        top = steps[-1]
        return steps, top.goodput_per_sec, top.p95_ms
    finally:
        supervisor.teardown()


def reconfig_run():
    """Membership-change A/B on the mp cluster (docs/RECONFIG.md): the
    same open-loop Poisson step measured twice — in steady state, then
    while an admin client's committed ``pb.NetworkConfig`` grows the
    node set 4 -> 5, the incumbents adopt at the checkpoint boundary,
    and the joiner boots with the committed target config and catches
    up via snapshot transfer.  Returns ``(steps, evidence)`` where
    ``steps`` are the two loadgen StepResults (they join the mp SLO
    artifact) and ``evidence`` carries adoption/join counters so the
    A/B cannot pass vacuously."""
    from mirbft_tpu import loadgen, pb
    from mirbft_tpu.cluster import ClusterSupervisor
    from mirbft_tpu.cluster.worker import read_json
    from mirbft_tpu.runtime.reconfig import encode_reconfig_request

    client_ids = [1, 2, 3]
    admin = LIVE_MP_RECONFIG_ADMIN_CLIENT
    incumbent = {
        "nodes": [0, 1, 2, 3],
        "f": 1,
        "number_of_buckets": 4,
        "checkpoint_interval": LIVE_MP_RECONFIG_CI,
        "max_epoch_length": 10 * LIVE_MP_RECONFIG_CI,
    }
    target = dict(incumbent, nodes=[0, 1, 2, 3, 4])
    reconfig_payload = encode_reconfig_request(
        [pb.Reconfiguration(type=pb.NetworkConfig(**target))]
    )
    supervisor = ClusterSupervisor(
        node_count=5,
        client_ids=client_ids + [admin],
        batch_size=LIVE_MP_BATCH_SIZE,
        processor="serial",
        deferred_nodes=(4,),
        network_config=incumbent,
    )
    evidence = {"adoptions": 0, "joined": False}
    stop = threading.Event()

    def reconfigure():
        # Submit (resubmitting until adoption — client-window dedup
        # absorbs duplicates), then spawn the joiner with the committed
        # target config the moment any incumbent reports adoption.
        request = pb.Request(client_id=admin, req_no=0, data=reconfig_payload)
        last_submit = 0.0
        while not stop.is_set():
            adopted = 0
            for node in incumbent["nodes"]:
                doc = read_json(
                    os.path.join(supervisor.nodes[node].dir, "reconfig.json")
                )
                adopted += int((doc or {}).get("adopted", 0) or 0)
            evidence["adoptions"] = adopted
            if adopted > 0:
                supervisor.join_node(4, network_config=target)
                evidence["joined"] = True
                return
            if time.monotonic() - last_submit >= 1.0:
                for node_id in supervisor.alive_nodes():
                    supervisor.submit(node_id, request)
                last_submit = time.monotonic()
            time.sleep(0.2)

    try:
        supervisor.start()
        generator = loadgen.LoadGenerator(
            supervisor,
            loadgen.standard_client_models(client_ids),
            seed=13,
        )
        steady = generator.run_step(
            "reconfig-steady",
            loadgen.PoissonArrivals(LIVE_MP_RECONFIG_RATE, seed=7),
            duration_s=LIVE_MP_RECONFIG_STEP_S,
            drain_s=LIVE_MP_DRAIN_S,
        )
        worker = threading.Thread(target=reconfigure, daemon=True)
        worker.start()
        during = generator.run_step(
            "reconfig-add-node",
            loadgen.PoissonArrivals(LIVE_MP_RECONFIG_RATE, seed=8),
            duration_s=LIVE_MP_RECONFIG_STEP_S,
            # Longer drain than the steady arm: the adoption epoch roll
            # can spiral on a starved CPU and commit resumption then
            # takes tens of seconds; a timed-out tail here would report
            # a liveness failure as a latency number.
            drain_s=4 * LIVE_MP_DRAIN_S,
        )
        worker.join(timeout=90.0)
        assert evidence["adoptions"] > 0, (
            "reconfig A/B is vacuous: no incumbent adopted the "
            "reconfiguration within the measurement window"
        )
        return [steady, during], evidence
    finally:
        stop.set()
        supervisor.teardown()


def app_run():
    """KV service SLO rung: APP_SESSIONS closed-loop sessions drive
    mixed reads/writes through the replicated KV service's sockets on an
    APP_NODES-process cluster.  Returns loadgen ``KvStepResult``s ready
    for the SLO artifact (read/write latency split included)."""
    from mirbft_tpu import loadgen
    from mirbft_tpu.app.service import KvClient
    from mirbft_tpu.cluster import ClusterSupervisor

    client_ids = list(range(1, APP_SESSIONS + 1))
    supervisor = ClusterSupervisor(
        node_count=APP_NODES,
        client_ids=client_ids,
        batch_size=APP_BATCH_SIZE,
        processor="pipelined",
        tick_seconds=APP_TICK_S,
        app="kv",
    )
    sessions: dict = {}
    try:
        supervisor.start()
        # Every worker publishes its service port at boot; wait for the
        # full mesh so session homes spread across all eight nodes.
        deadline = time.monotonic() + 30.0
        addresses = supervisor.app_addresses()
        while len(addresses) < APP_NODES and time.monotonic() < deadline:
            time.sleep(0.1)
            addresses = supervisor.app_addresses()
        if not addresses:
            raise RuntimeError("no KV service endpoint was published")
        homes = sorted(addresses)
        sessions = {
            cid: KvClient(addresses, cid, home=homes[i % len(homes)])
            for i, cid in enumerate(client_ids)
        }
        workload = loadgen.KvWorkload(
            sessions,
            loadgen.kv_client_models(client_ids, read_ratio=APP_READ_RATIO),
            seed=7,
        )
        return [
            workload.run_step(
                "app-kv-mixed",
                ops_per_session=APP_OPS_PER_SESSION,
                op_timeout_s=APP_OP_TIMEOUT_S,
            )
        ]
    finally:
        for session in sessions.values():
            session.close()
        supervisor.teardown()


def knee_run():
    """Capacity-knee rung: per KNEE_CONFIGS entry, boot a KNEE_NODES
    worker cluster, hand ``loadgen.knee.find_knee`` a real
    ``LoadGenerator.run_step`` closure, and locate the max sustainable
    rate whose p95 still meets KNEE_SLO_P95_MS.  The traced config's
    workers dump clock_sync-stamped trace.json on teardown; those are
    joined with the knee probe's per-request records into a critpath
    ledger for the per-phase attribution at the knee.  Returns the
    ``mirbft-capacity/1`` artifact."""
    import shutil

    from mirbft_tpu import loadgen
    from mirbft_tpu.cluster import ClusterSupervisor
    from mirbft_tpu.loadgen import knee as kneemod
    from mirbft_tpu.loadgen.clients import ClientModel
    from mirbft_tpu.obsv import critpath

    configs = []
    for name, kind, profile, traced, max_steps in KNEE_CONFIGS:
        client_ids = [1, 2, 3, 4]
        supervisor = ClusterSupervisor(
            node_count=KNEE_NODES,
            client_ids=client_ids,
            batch_size=KNEE_BATCH_SIZE,
            processor=kind,
            profile=profile,
            tick_seconds=APP_TICK_S,
            trace=traced,
            # Teardown must not delete the traced root: the workers
            # write trace.json during the SIGTERM handshake and we read
            # them back after the processes exit.
            keep_root=traced,
        )
        root = supervisor.root
        records_by_rate: dict = {}
        try:
            supervisor.start()
            generator = loadgen.LoadGenerator(
                supervisor,
                {cid: ClientModel() for cid in client_ids},
                seed=13,
            )
            # Discarded warm step: the first commits after boot pay
            # epoch setup and cold caches, which would contaminate the
            # lowest-rate probe's percentiles.
            generator.run_step(
                f"{name}-warm",
                loadgen.PoissonArrivals(KNEE_START_RATE / 2, seed=5),
                duration_s=KNEE_STEP_DURATION_S,
                drain_s=KNEE_DRAIN_S / 2,
            )

            def measure(rate):
                step = generator.run_step(
                    f"{name}-knee-{rate:.1f}",
                    loadgen.PoissonArrivals(rate, seed=int(rate * 8) or 1),
                    duration_s=KNEE_STEP_DURATION_S,
                    drain_s=KNEE_DRAIN_S,
                )
                records_by_rate[float(rate)] = step.records
                return step

            # Coarse resolution on purpose: a probe past saturation can
            # wedge the starved cluster in epoch suspicion for longer
            # than the drain window, so refinement probes after the
            # first failure mostly measure the wedge.  One bisection
            # narrows the bracket enough; fine-grained bisection would
            # just time out step after step.
            result = kneemod.find_knee(
                measure,
                KNEE_START_RATE,
                KNEE_SLO_P95_MS,
                max_rate=KNEE_MAX_RATE,
                max_steps=max_steps,
                resolution=0.25,
                min_goodput_ratio=KNEE_MIN_GOODPUT_RATIO,
            )
        finally:
            supervisor.teardown()
        attribution = None
        if traced:
            try:
                traces = []
                for n in range(KNEE_NODES):
                    path = os.path.join(root, f"node{n}", "trace.json")
                    if os.path.exists(path):
                        with open(path) as fh:
                            traces.append(json.load(fh))
                # Attribute the knee probe itself (the highest passing
                # rate); fall back to every record when the search never
                # passed so the artifact still carries an attribution.
                records = records_by_rate.get(
                    result.knee_rate_per_sec
                    if result.knee_rate_per_sec is not None
                    else -1.0
                ) or [
                    record
                    for step_records in records_by_rate.values()
                    for record in step_records
                ]
                if traces:
                    ledger = critpath.build_ledger(traces, records)
                    if ledger:
                        attribution = critpath.attribute(ledger)
            finally:
                shutil.rmtree(root, ignore_errors=True)
        configs.append(
            kneemod.config_doc(
                name,
                result,
                profile=profile,
                processor=kind,
                attribution=attribution,
                nodes=KNEE_NODES,
                clients=len(client_ids),
            )
        )
    return kneemod.artifact(
        configs,
        nodes=KNEE_NODES,
        tick_seconds=APP_TICK_S,
        step_duration_s=KNEE_STEP_DURATION_S,
        drain_s=KNEE_DRAIN_S,
        client_model="honest",
    )


def soak_run(duration_s=None, sample_interval_s=0.5, registry=None):
    """Resource-leak soak: SOAK_NODES real Nodes over loopback TCP with
    on-disk WAL/reqstore (pipelined executor, no emulated fsync floor)
    under continuous windowed client traffic for ``duration_s``, while an
    obsv ResourceSampler tracks RSS, open fds, thread count, and the
    WAL/reqstore tree sizes.

    Returns ``{"seconds", "commits", "samples", "leak": {metric:
    verdict}}`` where each verdict is obsv.resources.leak_verdict's
    least-squares ``flat``/``growing`` call.  The settle-in head of every
    series is dropped before the fit: ramping from an empty store to
    steady state reads as growth that isn't a leak."""
    import shutil
    import tempfile

    from mirbft_tpu import pb
    from mirbft_tpu.obsv.metrics import Registry
    from mirbft_tpu.obsv.resources import ResourceSampler, leak_verdict
    from mirbft_tpu.runtime import (
        Config,
        FileRequestStore,
        FileWal,
        Node,
        TcpTransport,
        build_processor,
    )
    from mirbft_tpu.runtime.node import (
        NodeStopped,
        standard_initial_network_state,
    )

    if duration_s is None:
        duration_s = float(os.environ.get("BENCH_SOAK_S", DEFAULT_SOAK_S))
    if registry is None:
        registry = Registry()
    root = tempfile.mkdtemp(prefix="mirbft-bench-soak-")
    clients = list(range(1, SOAK_CLIENTS + 1))
    state = standard_initial_network_state(SOAK_NODES, clients)
    # Frequent stable checkpoints on purpose: WAL truncation and client
    # GC are part of steady state — without them disk growth is by
    # design, and the leak fit would (correctly) flag it.  Planned epoch
    # rotation stays deferred past the soak (the chaos campaign owns
    # rotation); only max_epoch_length moves, so rotation noise cannot
    # masquerade as a resource trend.
    state.config.checkpoint_interval = 10
    state.config.max_epoch_length = 100 * state.config.checkpoint_interval
    nodes, transports, processors = [], [], []
    wals, stores, logs = [], [], []
    stop = threading.Event()
    threads = []
    failures: list = []

    def consume(node, processor, tick_s=LIVE_TICK_S):
        last_tick = time.monotonic()
        try:
            while not stop.is_set():
                actions = node.ready(timeout=0.01)
                if actions is not None:
                    results = processor.process(actions)
                    if results.digests or results.checkpoints:
                        node.add_results(results)
                now = time.monotonic()
                if now - last_tick >= tick_s:
                    last_tick = now
                    node.tick()
        except NodeStopped:
            pass
        except Exception as exc:  # noqa: BLE001 — surfaced as stage error
            failures.append(exc)

    sampler = ResourceSampler(
        registry=registry,
        interval_s=sample_interval_s,
        dirs={
            "wal": os.path.join(root, "wal"),
            "reqstore": os.path.join(root, "reqs"),
        },
        node="bench-soak",
    )
    try:
        for n in range(SOAK_NODES):
            # All WALs under one parent (ditto reqstores) so each family
            # is one sampled disk series.
            wal = FileWal(os.path.join(root, "wal", f"node{n}"))
            store = FileRequestStore(os.path.join(root, "reqs", f"node{n}"))
            # Small reclamation quanta: the default 4MB segment/compaction
            # thresholds never trip inside a seconds-scale soak, which
            # would read as monotone disk growth.  Sized to the soak's
            # ~0.7KB/s per-node write rate so rotation/compaction fire
            # every few seconds and steady state is a sawtooth the
            # least-squares fit sees as flat.
            wal.segment_target = 4 * 1024
            store.compact_min_bytes = 8 * 1024
            app_log = _SoakChainLog(clients)
            node = Node.start_new(
                Config(
                    id=n,
                    batch_size=SOAK_BATCH_SIZE,
                    processor="pipelined",
                    suspect_ticks=LIVE_SUSPECT_TICKS,
                ),
                state,
            )
            transport = TcpTransport(
                n, backoff_base=0.02, backoff_cap=0.25, dial_timeout=1.0
            )
            transport.serve(node)
            processor = build_processor(
                node, transport.link(), app_log, wal, store
            )
            nodes.append(node)
            transports.append(transport)
            processors.append(processor)
            wals.append(wal)
            stores.append(store)
            logs.append(app_log)
        for n in range(SOAK_NODES):
            for m in range(SOAK_NODES):
                if n != m:
                    transports[n].connect(m, transports[m].address)
        for n in range(SOAK_NODES):
            thread = threading.Thread(
                target=consume,
                args=(nodes[n], processors[n]),
                name=f"bench-soak-consumer-{n}",
                daemon=True,
            )
            threads.append(thread)
            thread.start()

        def propose_all(cid, rn):
            request = pb.Request(client_id=cid, req_no=rn, data=b"%d" % rn)
            for node in nodes:
                try:
                    node.propose(request)
                except (NodeStopped, ValueError):
                    pass

        sampler.start()
        start = time.perf_counter()
        end = start + duration_s
        next_req = {cid: 0 for cid in clients}
        last_push = 0.0
        last_retry = 0.0
        while time.perf_counter() < end:
            if failures:
                raise failures[0]
            now = time.monotonic()
            winner = max(logs, key=lambda l: l.total)
            if now - last_push >= SOAK_PUSH_S:
                # Sliding-window open loop: keep SOAK_WINDOW fresh
                # requests outstanding past each client's commit count on
                # the fastest node.
                last_push = now
                for cid in clients:
                    while next_req[cid] < winner.committed(cid) + SOAK_WINDOW:
                        propose_all(cid, next_req[cid])
                        next_req[cid] += 1
            if now - last_retry >= 0.5:
                # Straggler repair, as in the live rung: acks lost in the
                # startup connect races (or any drop) would wedge a node
                # forever — re-propose every req_no any log is still
                # missing below the proposed mark (below-watermark
                # duplicates are deduplicated as PAST).
                last_retry = now
                for cid in clients:
                    gaps = set()
                    for log in logs:
                        gaps.update(log.missing(cid, next_req[cid]))
                    for rn in sorted(gaps):
                        propose_all(cid, rn)
            time.sleep(0.02)
        elapsed = time.perf_counter() - start
        sampler.stop()
        series = sampler.snapshot_series()
        # device.* series ride the sampler cadence but are excluded from
        # the leak fit (live-buffer counts track jit-cache churn, not
        # process growth — same policy as ResourceSampler.verdicts).
        leak = {
            name: leak_verdict(samples[len(samples) // 5 :])
            for name, samples in series.items()
            if not name.startswith("device.")
        }
        # End-of-soak divergence sweep: every node runs the scalar/vector
        # shadow oracle on its serializer thread; any nonzero count fails
        # obsv --diff (apply_device_gate).
        divergence = 0
        for node in nodes:
            divs = node.audit_divergence(timeout=5.0)
            if divs:
                divergence += len(divs)
        return {
            "seconds": round(elapsed, 1),
            "commits": max((log.total for log in logs), default=0),
            "samples": max((len(s) for s in series.values()), default=0),
            "leak": leak,
            "divergence": divergence,
        }
    finally:
        sampler.stop()
        stop.set()
        for thread in threads:
            thread.join(timeout=10)
        for processor in processors:
            closer = getattr(processor, "close", None)
            if closer is not None:
                closer()
        for transport in transports:
            transport.close(0)
        for node in nodes:
            node.stop()
        for wal in wals:
            wal.close()
        for store in stores:
            store.close()
        shutil.rmtree(root, ignore_errors=True)


class BenchStream:
    """Crash-proof rung journal: one fsynced JSON line the moment each
    stage finishes, so a SIGKILL (or the driver's rc=124 timeout) on the
    newest rung cannot erase the rungs that already passed.

    Line kinds: ``header`` (schema + pid), one ``stage`` line per stage
    with its status/seconds/compile_s, and a trailing ``final`` line
    carrying the aggregated payload.  Consumers that find no ``final``
    line reconstruct the run from the stage lines.  Every write is
    best-effort: a full disk must not take the bench down with it."""

    SCHEMA = "mirbft-bench-stream/1"

    def __init__(self, path):
        self.path = path
        self._fh = None
        try:
            self._fh = open(path, "w", encoding="utf-8")
        except OSError:
            return
        self._line({"schema": self.SCHEMA, "kind": "header", "pid": os.getpid()})

    def _line(self, obj) -> None:
        if self._fh is None:
            return
        try:
            self._fh.write(json.dumps(obj) + "\n")
            self._fh.flush()
            os.fsync(self._fh.fileno())
        except (OSError, ValueError, TypeError):
            pass

    def stage(self, name, entry, registry) -> None:
        seconds = registry.gauge(
            "mirbft_bench_stage_seconds", stage=name
        ).value
        self._line({"kind": "stage", "stage": name, "seconds": seconds, **entry})

    def final(self, payload) -> None:
        self._line({"kind": "final", "payload": payload})

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None


class StageRunner:
    """Time-boxed stage executor under one monotonic deadline.

    Each stage runs on a daemon thread joined against the remaining
    budget; a stage that overruns is marked ``timeout`` (its thread is
    abandoned — main exits via os._exit so it cannot wedge the process),
    and every subsequent stage is ``skipped`` because the budget is gone.
    Per-stage wall time is recorded as a ``mirbft_bench_stage_seconds``
    gauge, which the final payload reads back — the registry is the
    single source of truth for the timings.

    ``stage_budget_s`` (env ``BENCH_STAGE_BUDGET_S``) additionally caps
    each individual stage, so one pathological stage times out on its
    own sub-budget instead of eating every later stage's runway.

    A stage may carry a ``warmup`` callable: it runs on the same worker
    thread immediately before ``fn`` so JAX/Mosaic compiles land outside
    the timed window — its cost is reported separately as ``compile_s``
    (gauge ``mirbft_bench_stage_compile_seconds``) while the
    stage-seconds gauge times ``fn`` alone.  When a ``stream`` is wired,
    every finished stage is journaled to it immediately."""

    # Don't bother starting a stage with less runway than this.
    MIN_RUNWAY_S = 5.0

    def __init__(self, budget_s: float, registry, stage_budget_s=None,
                 stream=None):
        self.deadline = time.monotonic() + budget_s
        self.registry = registry
        self.stage_budget_s = stage_budget_s
        self.stream = stream
        self.status: dict = {}  # stage -> {"status": ..., ["detail": ...]}
        # The stage currently executing (None between stages): the hard
        # watchdog reads this to name the culprit when join() itself is
        # wedged by a stage that never yields.
        self.current = None

    def remaining(self) -> float:
        return self.deadline - time.monotonic()

    def run(self, name: str, fn, enabled: bool = True, detail: str = "",
            warmup=None):
        """Run one stage; returns fn() or None (skipped/timeout/error)."""
        try:
            return self._run(name, fn, enabled, detail, warmup)
        finally:
            if self.stream is not None:
                self.stream.stage(
                    name, self.status.get(name, {}), self.registry
                )

    def _run(self, name, fn, enabled, detail, warmup):
        entry: dict = {"status": "skipped"}
        if detail:
            entry["detail"] = detail
        self.status[name] = entry
        self.registry.gauge("mirbft_bench_stage_seconds", stage=name)
        if not enabled:
            return None
        runway = self.remaining()
        if runway < self.MIN_RUNWAY_S:
            entry["detail"] = "budget exhausted"
            return None
        if self.stage_budget_s is not None:
            runway = min(runway, self.stage_budget_s)
        box: dict = {}

        def work():
            try:
                if warmup is not None:
                    warm_start = time.perf_counter()
                    warmup()
                    box["compile_s"] = round(
                        time.perf_counter() - warm_start, 3
                    )
                fn_start = time.perf_counter()
                box["result"] = fn()
                box["fn_s"] = round(time.perf_counter() - fn_start, 3)
            except BaseException as exc:  # report, never crash the bench
                box["error"] = f"{type(exc).__name__}: {exc}"

        thread = threading.Thread(
            target=work, daemon=True, name=f"bench-{name}"
        )
        start = time.perf_counter()
        self.current = name
        try:
            thread.start()
            thread.join(timeout=runway)
        finally:
            self.current = None
        if "compile_s" in box:
            entry["compile_s"] = box["compile_s"]
            self.registry.gauge(
                "mirbft_bench_stage_compile_seconds", stage=name
            ).set(box["compile_s"])
        self.registry.gauge("mirbft_bench_stage_seconds", stage=name).set(
            box.get("fn_s", round(time.perf_counter() - start, 3))
        )
        if thread.is_alive():
            entry["status"] = "timeout"
            return None
        if "error" in box:
            entry["status"] = "error"
            entry["detail"] = box["error"]
            return None
        entry["status"] = "ok"
        entry.pop("detail", None)  # the skip reason no longer applies
        return box["result"]

    def stage_report(self) -> dict:
        """Status + seconds per stage, timings read from the registry."""
        return {
            name: {
                **info,
                "seconds": self.registry.gauge(
                    "mirbft_bench_stage_seconds", stage=name
                ).value,
            }
            for name, info in self.status.items()
        }


class Watchdog:
    """The last line of the bench's one contract: a final JSON line on
    stdout no matter what.

    The StageRunner's cooperative timeouts handle a stage that overruns
    while the main thread can still run — ``join(timeout)`` expires and
    the run continues.  They do NOT handle a stage wedged inside a native
    call that starves the interpreter (observed as BENCH_r05: rc=124 from
    the outer ``timeout``, zero output): then the main thread never
    returns from ``join`` and the final print is unreachable.  This
    daemon-thread timer needs only a brief scheduling window to fire —
    it marks the in-flight stage ``timeout``, emits the final JSON with
    ``watchdog_fired: true``, and hard-exits, all before the driver's
    870s kill would have produced nothing.

    ``emit``/``exit_fn`` are injectable so the regression test can run a
    deliberately wedged stage without killing the test process."""

    def __init__(self, runner, deadline_s, emit=None, exit_fn=None,
                 stream=None):
        self.runner = runner
        self.deadline_s = deadline_s
        self.emit = emit if emit is not None else print
        self.exit_fn = exit_fn if exit_fn is not None else os._exit
        self.stream = stream
        self.fired = threading.Event()
        self._cancelled = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="bench-watchdog", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def cancel(self) -> None:
        self._cancelled.set()

    def _run(self) -> None:
        if self._cancelled.wait(self.deadline_s):
            return
        self.fire("hard watchdog fired")

    def fire(self, reason: str) -> None:
        """Emit the guaranteed final JSON line and exit.  Idempotent —
        also the SIGALRM backstop's landing point."""
        if self.fired.is_set() or self._cancelled.is_set():
            return
        self.fired.set()
        wedged = self.runner.current
        if wedged is not None:
            entry = self.runner.status.get(wedged)
            if entry is not None:
                entry["status"] = "timeout"
                entry["detail"] = reason
        try:
            stages = self.runner.stage_report()
        except Exception:  # never let reporting block the exit
            stages = {}
        payload = {
            "metric": "committed_reqs_per_sec_per_chip",
            "value": None,
            "watchdog_fired": True,
            "wedged_stage": wedged,
            "stages": stages,
        }
        try:
            if self.stream is not None:
                self.stream.final(payload)
            self.emit(json.dumps(payload))
            sys.stdout.flush()
        finally:
            self.exit_fn(1)


def _round(value, digits=1):
    return None if value is None else round(value, digits)


def _fold_engine(registry, stage, events, sim_ms):
    """Record one engine-driving stage's Recorder outcome as
    ``mirbft_engine_*`` gauges/counters labeled by stage; the payload's
    ``engine_gauges`` key is read back from the registry snapshot so the
    diff gate sees the same numbers a scrape would."""
    if events is not None:
        registry.counter("mirbft_engine_events_total", stage=stage).inc(events)
    if sim_ms is not None:
        registry.gauge("mirbft_engine_sim_ms", stage=stage).set(sim_ms)


def _engine_gauges(registry) -> dict:
    """{stage: {events, sim_ms}} from the registry snapshot."""
    snap = registry.snapshot()
    out: dict = {}
    for metric, key in (
        ("mirbft_engine_events_total", "events"),
        ("mirbft_engine_sim_ms", "sim_ms"),
    ):
        for series in snap.get(metric, {}).get("series", []):
            stage = series["labels"].get("stage")
            if stage is not None:
                out.setdefault(stage, {})[key] = series["value"]
    return out


def effective_budget_s(environ=None) -> float:
    """The stage budget actually used: ``BENCH_BUDGET_S`` clamped so
    budget + watchdog grace always lands inside the harness timeout
    (``BENCH_HARNESS_TIMEOUT_S``) with margin to spare.  An oversized
    budget must yield a truncated-but-parseable run, never an rc=124
    kill with no artifact (the BENCH_r05 failure mode)."""
    env = os.environ if environ is None else environ
    budget_s = float(env.get("BENCH_BUDGET_S", DEFAULT_BUDGET_S))
    harness_s = float(env.get("BENCH_HARNESS_TIMEOUT_S", HARNESS_TIMEOUT_S))
    ceiling = harness_s - WATCHDOG_GRACE_S - HARNESS_MARGIN_S
    if ceiling > 0:
        budget_s = min(budget_s, ceiling)
    return budget_s


def main() -> int:
    budget_s = effective_budget_s()
    stage_budget = os.environ.get("BENCH_STAGE_BUDGET_S")
    from mirbft_tpu.obsv import device as device_obsv
    from mirbft_tpu.obsv.metrics import Registry

    registry = Registry()
    # Device-plane capture spans the whole run (independent of the hooks
    # switchboard, which individual stages toggle): kernel histograms,
    # retrace counts, and transfer bytes land in the "device" payload
    # section that obsv --diff gates.
    device_obsv.reset()
    device_obsv.start_capture(
        registry,
        retrace_budget=int(
            os.environ.get("BENCH_RETRACE_BUDGET", BENCH_RETRACE_BUDGET)
        ),
    )
    stream = BenchStream(
        os.environ.get("BENCH_STREAM_PATH", "BENCH_stream.jsonl")
    )
    runner = StageRunner(
        budget_s,
        registry,
        stage_budget_s=float(stage_budget) if stage_budget else None,
        stream=stream,
    )
    watchdog = Watchdog(
        runner, deadline_s=budget_s + WATCHDOG_GRACE_S, stream=stream
    )
    watchdog.start()
    if threading.current_thread() is threading.main_thread() and hasattr(
        signal, "SIGALRM"
    ):
        # Backstop for the backstop: if even the watchdog thread is
        # starved, SIGALRM interrupts the main thread at the next
        # interpreter checkpoint and lands on the same exit path.
        signal.signal(
            signal.SIGALRM,
            lambda _sig, _frm: watchdog.fire("SIGALRM backstop fired"),
        )
        signal.alarm(int(budget_s + WATCHDOG_GRACE_S + 30))

    # The live rungs run first: they need sockets and fsyncs, not jax, so
    # they cannot be starved by a pathological compile stage upstream.
    live_serial = runner.run(
        "live_serial", lambda: live_cluster_rate("serial")
    )
    live_pipelined = runner.run(
        "live_pipelined", lambda: live_cluster_rate("pipelined")
    )
    soak_s = float(os.environ.get("BENCH_SOAK_S", DEFAULT_SOAK_S))
    soak = runner.run(
        "soak",
        lambda: soak_run(duration_s=soak_s, registry=registry),
        enabled=soak_s > 0,
        detail="BENCH_SOAK_S=0",
    )
    attack = runner.run("live_under_attack", live_attack_run)
    (
        attack_clean_rate,
        attack_clean_p95,
        attack_rate,
        attack_p95,
        attack_flooded,
    ) = attack if attack is not None else (None,) * 5
    mp_serial = runner.run("live_mp_serial", lambda: live_mp_run("serial"))
    mp_pipelined = runner.run(
        "live_mp_pipelined", lambda: live_mp_run("pipelined")
    )
    mp_steps = []
    mp_serial_goodput = mp_serial_p95 = None
    if mp_serial is not None:
        steps, mp_serial_goodput, mp_serial_p95 = mp_serial
        mp_steps.extend(steps)
    mp_pipelined_goodput = mp_pipelined_p95 = None
    if mp_pipelined is not None:
        steps, mp_pipelined_goodput, mp_pipelined_p95 = mp_pipelined
        mp_steps.extend(steps)
    mp_reconfig = runner.run("live_mp_reconfig", reconfig_run)
    reconfig_steady = reconfig_during = None
    reconfig_evidence = {}
    if mp_reconfig is not None:
        (reconfig_steady, reconfig_during), reconfig_evidence = mp_reconfig
        mp_steps.extend([reconfig_steady, reconfig_during])
    app_steps = runner.run("app_kv", app_run) or []
    app_top = app_steps[-1] if app_steps else None
    capacity = runner.run("knee", knee_run)

    def warm_calibrate():
        _enable_compile_cache()
        from mirbft_tpu.testengine.crypto_plane import AsyncKernelHashPlane

        plane = AsyncKernelHashPlane()
        warm_kernel_shapes(plane)
        # Offload break-even calibration: through the tunneled dev device
        # the round trip is tens of ms and digests stay host-side (the
        # plane is opportunistic — it never stalls the loop on the
        # device); on directly attached hardware the threshold drops and
        # waves offload.
        rtt_s = plane.calibrate()
        return plane, rtt_s

    # Ladder first: the microbench's queued device work must not bleed
    # into the timed consensus run.
    warm = runner.run("warm_calibrate", warm_calibrate)
    plane, rtt_s = warm if warm is not None else (None, None)

    ladder = runner.run(
        "ladder_kernel",
        lambda: ladder_run(hash_plane=plane),
        enabled=plane is not None,
        detail="needs warm_calibrate",
    )
    tpu_wall, events, chain, ladder_sim = (
        ladder if ladder is not None else (None,) * 4
    )
    _fold_engine(registry, "ladder_kernel", events, ladder_sim)
    host = runner.run("ladder_host", ladder_run)
    host_wall, host_events, host_chain, host_sim = (
        host if host is not None else (None,) * 4
    )
    _fold_engine(registry, "ladder_host", host_events, host_sim)
    # Bit-exactness gate: the kernel run must replay the host run exactly
    # (same event count, same app chain).  Only checkable when both ran.
    consistent = None
    if ladder is not None and host is not None:
        consistent = events == host_events and chain == host_chain

    micro = runner.run(
        "sha256_microbench",
        kernel_microbench,
        warmup=sha256_microbench_warmup,
    )
    xla_rate, pallas_rate, kernel_digest_rate, host_rate = (
        micro if micro is not None else (None,) * 4
    )
    ed = runner.run(
        "ed25519_microbench",
        ed25519_microbench,
        warmup=ed25519_microbench_warmup,
    )
    ed_kernel_rate, ed_host_rate = ed if ed is not None else (None, None)
    # Rung 3 runs on any backend: speculative ingress verification
    # picks the device kernel or the host RLC batch authority by
    # kernel_authority(), so a CPU host no longer skips the rung.
    r3 = runner.run("rung3", rung3_run)
    rung3_rate, rung3_p99, rung3_events, rung3_verified, rung3_stats, r3_sim = (
        r3 if r3 is not None else (None, None, None, None, {}, None)
    )
    _fold_engine(registry, "rung3", rung3_events, r3_sim)
    r4 = runner.run("rung4", rung4_run)
    rung4_rate, rung4_events, rung4_certs, rung4_agg_ms, r4_sim = (
        r4 if r4 is not None else (None,) * 5
    )
    _fold_engine(registry, "rung4", rung4_events, r4_sim)
    # The ackplane rung runs before rung5: it is cheap (~1 min), it is
    # the device-plane evidence the ROADMAP asks every bench artifact to
    # carry, and rung5 has a history of eating the remaining budget.
    ackplane = runner.run("ackplane", lambda: ackplane_run(registry))
    r5 = runner.run("rung5", rung5_run)
    rung5_rate, rung5_events, r5_sim = (
        r5 if r5 is not None else (None, None, None)
    )
    _fold_engine(registry, "rung5", rung5_events, r5_sim)

    total_reqs = CLIENTS * REQS_PER_CLIENT
    committed_rate = total_reqs / tpu_wall if tpu_wall else None
    p99_ms = None
    if plane is not None and ladder is not None:
        flush_ms = sorted(1e3 * s for s in plane.flush_wall_s)
        # Inline-bypass mode (device below break-even) has no deferred
        # flushes; the blocking digest latency is then one hashlib call.
        p99_ms = (
            flush_ms[min(len(flush_ms) - 1, int(0.99 * len(flush_ms)))]
            if flush_ms
            else 0.0
        )

    payload = {
        "metric": "committed_reqs_per_sec_per_chip",
        "value": _round(committed_rate),
        # Live TCP rung: same consensus, real sockets + real fsyncs, one
        # run per executor; the speedup is the pipelined commit path's
        # whole case (group-commit fsyncs + coalesced writes + overlap).
        "live_reqs_per_sec_serial": _round(live_serial),
        "live_reqs_per_sec_pipelined": _round(live_pipelined),
        "live_pipelined_speedup": (
            round(live_pipelined / live_serial, 3)
            if live_serial and live_pipelined
            else None
        ),
        "live_config": (
            f"{LIVE_NODES} nodes f={(LIVE_NODES - 1) // 3}, "
            f"{LIVE_CLIENTS} clients, "
            f"first {LIVE_TARGET_COMMITS} of "
            f"{LIVE_CLIENTS * LIVE_REQS_PER_CLIENT} reqs, "
            f"batch_size={LIVE_BATCH_SIZE}, loopback TCP, on-disk "
            "WAL/reqstore, emulated flush latency "
            f"{LIVE_FSYNC_FLOOR_S * 1e3:.0f}ms/fsync"
        ),
        # Attack rung: the duplication-flood A/B — goodput and commit
        # p95 under 4x client-seam duplication vs a clean baseline run
        # in the same stage; `obsv --diff` gates these top-level numbers
        # run-to-run like any other headline metric.
        "live_attack_goodput_per_sec": _round(attack_rate),
        "live_attack_commit_p95_ms": _round(attack_p95, 2),
        "live_attack_clean_goodput_per_sec": _round(attack_clean_rate),
        "live_attack_clean_commit_p95_ms": _round(attack_clean_p95, 2),
        "live_attack_goodput_ratio": (
            round(attack_rate / attack_clean_rate, 3)
            if attack_rate and attack_clean_rate
            else None
        ),
        "live_attack_flooded_submissions": attack_flooded,
        "live_attack_config": (
            f"duplication flood: every submission x{1 + LIVE_ATTACK_COPIES} "
            f"to every node, serial executor, same cluster shape as "
            "live_config; p95 is first-submission to first-commit on the "
            "winning node"
        ),
        # Multi-process rung: real worker processes under stepped
        # open-loop Poisson load; headline numbers are the top rate
        # step's goodput and p95 latency, and the full per-step SLO
        # artifact rides under "loadgen" (obsv --diff flattens it to
        # loadgen.step.* series and gates p95/goodput regressions).
        "live_mp_goodput_per_sec_serial": _round(mp_serial_goodput),
        "live_mp_p95_ms_serial": _round(mp_serial_p95, 2),
        "live_mp_goodput_per_sec_pipelined": _round(mp_pipelined_goodput),
        "live_mp_p95_ms_pipelined": _round(mp_pipelined_p95, 2),
        "live_mp_config": (
            f"{LIVE_MP_NODES} worker processes, open-loop Poisson at "
            f"{'/'.join(str(int(r)) for r in LIVE_MP_RATE_STEPS)} req/s "
            f"x {LIVE_MP_STEP_DURATION_S:.0f}s, "
            f"batch_size={LIVE_MP_BATCH_SIZE}, client mix: honest + "
            "slow/mixed-size + retry-storm"
        ),
        # Reconfig A/B (docs/RECONFIG.md): the same Poisson rate in
        # steady state vs while an add-node reconfiguration commits,
        # adopts, and the joiner catches up; the dip is the price of
        # membership change under load.  Both steps also ride the
        # "loadgen" SLO artifact as reconfig-steady / reconfig-add-node.
        "reconfig_steady_goodput_per_sec": _round(
            reconfig_steady.goodput_per_sec if reconfig_steady else None
        ),
        "reconfig_steady_p95_ms": _round(
            reconfig_steady.p95_ms if reconfig_steady else None, 2
        ),
        "reconfig_window_goodput_per_sec": _round(
            reconfig_during.goodput_per_sec if reconfig_during else None
        ),
        "reconfig_window_p95_ms": _round(
            reconfig_during.p95_ms if reconfig_during else None, 2
        ),
        "reconfig_adoptions": reconfig_evidence.get("adoptions"),
        "reconfig_joiner_booted": reconfig_evidence.get("joined"),
        "reconfig_config": (
            f"4 -> 5 nodes via a committed pb.NetworkConfig from admin "
            f"client {LIVE_MP_RECONFIG_ADMIN_CLIENT}, ci="
            f"{LIVE_MP_RECONFIG_CI}, Poisson "
            f"{int(LIVE_MP_RECONFIG_RATE)} req/s x "
            f"{LIVE_MP_RECONFIG_STEP_S:.0f}s per arm"
        ),
        # App rung: the replicated KV service's user-visible SLOs — the
        # read/write latency split and goodput through the app sockets
        # on an 8-process cluster; the full artifact rides under
        # "loadgen_app" (obsv --diff flattens it to loadgen_app.step.*
        # series and gates the split percentiles like any other *_ms).
        "app_goodput_per_sec": _round(
            app_top.goodput_per_sec if app_top else None
        ),
        "app_read_p50_ms": _round(app_top.read_p50_ms if app_top else None, 2),
        "app_read_p95_ms": _round(app_top.read_p95_ms if app_top else None, 2),
        "app_read_p99_ms": _round(app_top.read_p99_ms if app_top else None, 2),
        "app_write_p50_ms": _round(
            app_top.write_p50_ms if app_top else None, 2
        ),
        "app_write_p95_ms": _round(
            app_top.write_p95_ms if app_top else None, 2
        ),
        "app_write_p99_ms": _round(
            app_top.write_p99_ms if app_top else None, 2
        ),
        "app_config": (
            f"{APP_NODES} worker processes with the KV service, "
            f"{APP_SESSIONS} closed-loop sessions x "
            f"{APP_OPS_PER_SESSION} ops, read_ratio={APP_READ_RATIO}, "
            "uniform + Zipf keys, mixed payload sizes, committed-mode "
            "reads (read-index barrier)"
        ),
        # Knee rung: the headline is the minimum located knee across
        # configs; the full mirbft-capacity/1 artifact (per-config
        # rate→latency curves + per-phase attribution at the knee) rides
        # under "capacity" and obsv --diff gates its per_sec series.
        "knee_rate_per_sec": _round(
            capacity.get("knee_rate_per_sec") if capacity else None, 1
        ),
        "knee_config": (
            f"{KNEE_NODES} worker processes, honest open-loop Poisson "
            f"probes x {KNEE_STEP_DURATION_S:.0f}s, SLO p95 <= "
            f"{KNEE_SLO_P95_MS:.0f}ms + goodput >= "
            f"{KNEE_MIN_GOODPUT_RATIO:.0%} of offered, geometric ramp "
            f"from {KNEE_START_RATE:.0f} req/s + binary search; configs: "
            + ", ".join(c[0] for c in KNEE_CONFIGS)
        ),
        "unit": "reqs/s",
        "vs_baseline": (
            round(host_wall / tpu_wall, 3) if tpu_wall and host_wall else None
        ),
        "ladder_consistent": consistent,
        "config": (
            f"{NODES} nodes f={(NODES - 1) // 3}, {CLIENTS} clients, "
            f"batch_size={BATCH_SIZE}, {total_reqs} reqs, "
            f"ready_latency={READY_LATENCY_MS}ms, "
            "digests via async SHA-256 kernel plane (adaptive "
            "host fallback below the device threshold)"
        ),
        "p99_batch_digest_ms": _round(p99_ms, 2),
        "engine_events": events,
        "kernel_compressions_per_sec": (
            round(max(xla_rate, pallas_rate), 1) if micro else None
        ),
        "kernel_compressions_per_sec_xla": _round(xla_rate),
        "kernel_compressions_per_sec_pallas": _round(pallas_rate),
        "kernel_digests_per_sec_640B": _round(kernel_digest_rate),
        "kernel_vs_hashlib": (
            round(kernel_digest_rate / host_rate, 3) if micro else None
        ),
        "ed25519_verifies_per_sec": _round(ed_kernel_rate),
        "ed25519_vs_host_python": (
            round(ed_kernel_rate / ed_host_rate, 3) if ed else None
        ),
        # BASELINE ladder rung 3 (64 nodes f=21, 1024 signed clients,
        # speculative batched ingress verification).
        "rung3_committed_reqs_per_sec": _round(rung3_rate),
        "rung3_verify_p99_ms": _round(rung3_p99, 2),
        "rung3_config": (
            f"{RUNG3_NODES} nodes f={(RUNG3_NODES - 1) // 3}, "
            f"{RUNG3_CLIENTS} ed25519-signed clients, "
            f"{RUNG3_CLIENTS * RUNG3_REQS} reqs, batch_size=200, "
            "speculative batched ingress verification"
        ),
        "rung3_engine_events": rung3_events,
        "rung3_verified_requests": rung3_verified,
        **rung3_stats,
        # BASELINE ladder rung 4: 128-node WAN (frame jitter + targeted
        # drop mangler), BLS quorum certs on device.
        "rung4_committed_reqs_per_sec": _round(rung4_rate),
        "rung4_config": (
            f"{RUNG4_NODES} nodes f={(RUNG4_NODES - 1) // 3}, "
            f"{RUNG4_CLIENTS} clients, 30ms WAN jitter + drop "
            "mangler, BLS checkpoint certs aggregated on device"
        ),
        "rung4_engine_events": rung4_events,
        "rung4_bls_certificates": rung4_certs,
        "rung4_bls_aggregate_ms": _round(rung4_agg_ms, 2),
        # BASELINE ladder rung 5 (scaled; see rung5_run docstring):
        # 256-node WAN + follower crash/state-transfer recovery.
        "rung5_committed_reqs_per_sec": _round(rung5_rate),
        "rung5_config": (
            f"{RUNG5_NODES} nodes f={(RUNG5_NODES - 1) // 3}, "
            f"{RUNG5_CLIENTS} clients, 20ms WAN jitter, follower "
            "crash + checkpoint-GC + state-transfer recovery "
            "(10k-client epoch-change storm runs as the "
            "HEAVY-gated correctness tier)"
        ),
        "rung5_engine_events": rung5_events,
        # Ackplane rung: host vs device ack/quorum plane (see
        # docs/DEVICE_TRACKER.md).  Flattened to top-level ackplane_*
        # keys so obsv --diff gates events/s and the device/host ratio
        # like any other headline number; divergences found by the
        # sampled oracle audit also land in device.divergence_total.
        **{
            f"ackplane_{k}": v
            for k, v in (ackplane or {}).items()
            if k != "ack_events_counter"
        },
        "ackplane_config": (
            f"{ACKPLANE_CLIENTS} clients (width-1 windows), 4 nodes f=1, "
            f"{len(ACKPLANE_SOURCES)} sources acking every req 0 in "
            f"seeded shuffled frames of {ACKPLANE_FRAME}; events/s is "
            "steady state (each plane's first frame is its build/compile "
            "window); committed = strong-certified slots per second of "
            "device ingest + quorum sweep; boundary drain reported "
            "separately"
        ),
        # Soak rung: resource series + least-squares leak verdicts;
        # `obsv --diff` fails the run when any verdict is "growing" —
        # RSS/fd/disk regressions gate PRs exactly like p95 regressions.
        "soak": soak,
        "soak_config": (
            f"{SOAK_NODES} nodes f={(SOAK_NODES - 1) // 3}, "
            f"{SOAK_CLIENTS} clients, sliding window {SOAK_WINDOW}, "
            f"pipelined executor, {soak_s:.0f}s "
            "(BENCH_SOAK_S), on-disk WAL/reqstore, obsv resource "
            "sampler @0.5s"
        ),
        "bench_budget_s": budget_s,
        "bench_stage_budget_s": runner.stage_budget_s,
        "stages": runner.stage_report(),
        "engine_gauges": _engine_gauges(registry),
        # Device plane: kernel timings, retrace counts (+ budget
        # breaches), transfer bytes, and the shadow-oracle divergence
        # total — obsv --diff fails on a breach or any divergence.
        "device": device_obsv.report(registry),
    }
    device_obsv.stop_capture()
    if mp_steps:
        from mirbft_tpu import loadgen

        payload["loadgen"] = loadgen.artifact(
            mp_steps,
            cluster="mp",
            nodes=LIVE_MP_NODES,
            rate_steps=list(LIVE_MP_RATE_STEPS),
        )
    if capacity is not None:
        payload["capacity"] = capacity
    if app_steps:
        from mirbft_tpu import loadgen

        payload["loadgen_app"] = loadgen.artifact(
            app_steps,
            cluster="mp-app",
            nodes=APP_NODES,
            sessions=APP_SESSIONS,
            read_ratio=APP_READ_RATIO,
        )
    if plane is not None:
        payload.update(
            {
                "crypto_plane_digests": sum(plane.flush_sizes),
                # Flush-overlap breakdown: device launches all dispatch
                # proactively at wave boundaries (device + D2H copy
                # overlap engine progress); a resolve miss forces a
                # synchronous host-hash flush instead of a device launch.
                "crypto_plane_overlapped_launches": plane.overlapped_launches,
                "crypto_plane_demand_host_flushes": plane.demand_flushes,
                "crypto_plane_device_digests": plane.device_digests,
                "crypto_plane_host_digests": plane.host_digests,
                "crypto_plane_rescued_digests": plane.rescued_digests,
                "crypto_plane_device_rtt_ms": _round(
                    1e3 * rtt_s if rtt_s is not None else None, 2
                ),
                "crypto_plane_min_device_rows": plane.min_device_rows,
            }
        )

    # The one contract that must survive every failure mode above: a
    # single parseable JSON line on stdout.  Per-stage errors (e.g. a
    # backend without compiled-Pallas support) are reported in "stages"
    # but are not fatal; only a ladder consistency violation — a
    # correctness failure, not an environment limitation — fails the rc.
    watchdog.cancel()
    stream.final(payload)
    stream.close()
    print(json.dumps(payload))
    return 1 if consistent is False else 0


def recover_main(argv) -> int:
    """``python bench.py --recover [journal]``: print the final JSON
    recovered from a BENCH_stream.jsonl journal (the ``final`` line when
    the run completed, a reduced stage-only artifact when it was killed).
    Lets the driver salvage a parseable artifact from an rc=124 run."""
    from mirbft_tpu.obsv.diff import recover_stream

    path = argv[0] if argv else os.environ.get(
        "BENCH_STREAM_PATH", "BENCH_stream.jsonl"
    )
    try:
        payload = recover_stream(path)
    except OSError as exc:
        print(json.dumps({"error": f"{type(exc).__name__}: {exc}"}))
        return 1
    print(json.dumps(payload))
    return 0


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--recover":
        sys.exit(recover_main(sys.argv[2:]))
    try:
        rc = main()
    except BaseException as exc:  # noqa: BLE001 — the contract is one
        # JSON line on stdout even when payload assembly itself is the
        # bug; the stages dict is gone here, but the error isn't.
        print(
            json.dumps(
                {
                    "metric": "committed_reqs_per_sec_per_chip",
                    "value": None,
                    "error": f"{type(exc).__name__}: {exc}",
                }
            )
        )
        rc = 1
    sys.stdout.flush()
    sys.stderr.flush()
    # Abandoned timeout-stage daemon threads may still be inside a JAX
    # call; a plain return from main can hang in interpreter teardown.
    os._exit(rc)
