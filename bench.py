"""Benchmark: batched SHA-256 digest throughput on the accelerator.

This is the BASELINE.md ladder's core metric — the consensus hot path
(reference: processor.go:133-143) expressed as digests/sec for
batch-of-20-acks preimages (640 bytes each, the shape a 4-node BatchSize=20
network produces).  ``vs_baseline`` compares against single-thread hashlib
on the same host, i.e. the reference's serial Hasher executor.

Prints exactly one JSON line.
"""

import json
import time

import jax
import numpy as np


BATCH = 8192
MSG_BYTES = 640  # 20 request acks x 32-byte digests
ROUNDS = 5


def main():
    import hashlib

    from mirbft_tpu.ops.batching import pack_preimages
    from mirbft_tpu.ops.sha256 import sha256_digest_words

    rng = np.random.default_rng(0)
    messages = [rng.bytes(MSG_BYTES) for _ in range(BATCH)]

    packed = pack_preimages(messages)
    blocks = jax.device_put(packed.blocks)
    n_blocks = jax.device_put(packed.n_blocks)

    # Warmup / compile.
    out = sha256_digest_words(blocks, n_blocks)
    out.block_until_ready()

    start = time.perf_counter()
    for _ in range(ROUNDS):
        out = sha256_digest_words(blocks, n_blocks)
    out.block_until_ready()
    kernel_secs = (time.perf_counter() - start) / ROUNDS
    kernel_rate = BATCH / kernel_secs

    # Single-thread hashlib on the same workload (ref-style serial hasher).
    start = time.perf_counter()
    for m in messages:
        hashlib.sha256(m).digest()
    host_secs = time.perf_counter() - start
    host_rate = BATCH / host_secs

    # Spot-check bit-exactness on a sample so the number is honest.
    words = np.asarray(out)
    sample = words[0].astype(">u4").tobytes()
    assert sample == hashlib.sha256(messages[0]).digest(), "digest mismatch!"

    print(
        json.dumps(
            {
                "metric": "batch_digests_per_sec",
                "value": round(kernel_rate, 1),
                "unit": "digests/s",
                "vs_baseline": round(kernel_rate / host_rate, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
