"""Saturation attribution: critpath ledger, knee finder, capacity gate.

Covers the PR-18 observability contract end to end on synthetic
fixtures with *known* answers:

- the per-request critical-path ledger joins multi-node milestone
  traces (including skewed per-node clocks) into exact phase
  residencies and per-band dominant-phase attributions;
- loadgen records resolve the two join phases (ingress/apply);
- ``find_knee`` locates a knee on a synthetic latency curve, reports
  the honest ``located=False`` when the SLO never breaks, and the
  goodput criterion fails a collapsed probe whose tiny surviving
  sample has a lucky p95;
- an injected knee regression in a ``mirbft-capacity/1`` artifact makes
  ``obsv --diff`` exit nonzero;
- the ``--critpath DIR`` CLI renders the attribution for a run dir.
"""

import json
import subprocess
import sys
from pathlib import Path

from mirbft_tpu.loadgen.knee import (
    SCHEMA,
    artifact,
    config_doc,
    find_knee,
)
from mirbft_tpu.obsv.critpath import (
    attribute,
    attribution_table,
    build_ledger,
    ledger_from_dir,
)

REPO = Path(__file__).resolve().parents[1]

# Every synthetic node's clock is skewed differently; the offsets below
# make (t0_ns + offset_ns) identical across nodes, so an event's local
# ``ts`` (µs since its own t0) doubles as its absolute time after
# alignment — fixtures can state timelines in one shared µs domain.
_T0 = {0: 1_000_000_000, 1: 500_000_000, 2: 2_000_000_000}
_REF_OFFSETS = {"1": 500_000_000, "2": -1_000_000_000}
_BASE_US = 1_000_000.0  # (t0 + offset) / 1000 for every node


def _node_trace(node, instants):
    """One node's Chrome trace: clock_sync metadata + milestone
    instants ``(ts_us, name, args)`` (ts relative to the node's t0)."""
    events = [
        {
            "name": "clock_sync",
            "ph": "M",
            "pid": 0,
            "tid": 0,
            "args": {
                "node": node,
                "t0_ns": _T0[node],
                "offsets_ns": _REF_OFFSETS if node == 0 else {},
            },
        }
    ]
    for ts, name, args in instants:
        events.append(
            {
                "name": name,
                "cat": "consensus",
                "ph": "i",
                "pid": 0,
                "tid": node,
                "ts": float(ts),
                "args": args,
            }
        )
    return {"traceEvents": events}


def _milestones(seq, *, alloc, pp, cq, committed, epoch=1, bucket=0):
    """Per-node instant lists for one sequence.

    ``pp``/``committed`` map node -> ts_us; ``alloc``/``cq`` are
    ``(ts_us, node)``.  Returns {node: [(ts, name, args), ...]}.
    """
    def args(node, with_meta=False):
        a = {"node": node, "seq": seq, "sim_ms": 0}
        if with_meta:
            a.update(epoch=epoch, bucket=bucket)
        return a

    out = {0: [], 1: [], 2: []}
    ts, node = alloc
    out[node].append((ts, "seq.allocated", args(node, with_meta=True)))
    for node, ts in pp.items():
        out[node].append((ts, "seq.preprepared", args(node, with_meta=True)))
    ts, node = cq
    out[node].append((ts, "seq.commit_quorum", args(node)))
    for node, ts in committed.items():
        out[node].append((ts, "seq.committed", args(node)))
    return out


def _merge_instants(*per_seq):
    traces = []
    for node in (0, 1, 2):
        instants = []
        for seq_map in per_seq:
            instants.extend(seq_map[node])
        traces.append(_node_trace(node, instants))
    return traces


def _transmit_bound_seq(seq, t):
    """hash 500, transmit 3000 (node 2 closes), quorum 500, commit 200."""
    return _milestones(
        seq,
        alloc=(t + 1000, 0),
        pp={0: t + 1500, 1: t + 2500, 2: t + 4500},
        cq=(t + 5000, 0),
        committed={0: t + 5200, 1: t + 5300, 2: t + 6000},
    )


def _quorum_bound_seq(seq, t):
    """hash 100, transmit 200, quorum 900 (node 1 closes cq), commit 50."""
    return _milestones(
        seq,
        alloc=(t + 100, 0),
        pp={0: t + 200, 1: t + 300, 2: t + 400},
        cq=(t + 1300, 1),
        committed={0: t + 1400, 1: t + 1350, 2: t + 1500},
    )


def test_ledger_exact_phases_across_skewed_clocks():
    """Three nodes with wildly different t0 anchors produce the exact
    phase residencies once the reference offsets are applied."""
    traces = _merge_instants(_transmit_bound_seq(5, 0))
    ledger = build_ledger(traces)
    assert len(ledger) == 1
    row = ledger[0]
    assert row.seq == 5
    assert row.epoch == 1 and row.bucket == 0
    assert row.phases == {
        "hash": 500.0,
        "transmit": 3000.0,
        "quorum": 500.0,
        "commit": 200.0,
    }
    # The straggler (node 2) closes transmit; node 0 closes the rest.
    assert row.phase_nodes["transmit"] == 2
    assert row.phase_nodes["hash"] == 0
    assert row.phase_nodes["commit"] == 0
    # total = first committed - first allocated.
    assert row.total_us == 4200.0


def test_ledger_joins_loadgen_records_for_ingress_and_apply():
    traces = _merge_instants(_transmit_bound_seq(5, 0))
    # Submit 400 µs after the base instant; commit observed (by loadgen,
    # via node 1's commit record) 500 µs after node 1 applied.
    records = [
        {
            "client_id": 7,
            "req_no": 3,
            "seq": 5,
            "node": 1,
            "submit_ns": int((_BASE_US + 400) * 1000),
            "commit_ns": int((_BASE_US + 5800) * 1000),
        }
    ]
    ledger = build_ledger(traces, records)
    assert len(ledger) == 1
    row = ledger[0]
    assert row.client_id == 7 and row.req_no == 3
    assert row.phases["ingress"] == 600.0  # alloc@1000 - submit@400
    assert row.phases["apply"] == 500.0  # obs@5800 - node1 committed@5300
    assert row.phase_nodes["apply"] == 1
    assert row.total_us == 5400.0  # commit - submit
    # Records without trace evidence are skipped, not fabricated.
    assert build_ledger(traces, [dict(records[0], seq=999)]) == []


def test_attribution_bands_pick_dominant_phase_and_node():
    """Two fast quorum-bound requests and two slow transmit-bound ones:
    the lower band attributes to quorum, the upper to transmit, each
    with the node that closed the dominant edge."""
    traces = _merge_instants(
        _quorum_bound_seq(10, 0),
        _quorum_bound_seq(11, 10_000),
        _transmit_bound_seq(20, 20_000),
        _transmit_bound_seq(21, 30_000),
    )
    ledger = build_ledger(traces)
    assert [r.seq for r in ledger] == [10, 11, 20, 21]  # sorted by total
    bands = attribute(ledger, bands=((0.0, 0.5), (0.5, 1.0)))
    assert [b["band"] for b in bands] == ["p0-p50", "p50-p100"]
    fast, slow = bands
    assert fast["count"] == 2 and slow["count"] == 2
    assert fast["dominant_phase"] == "quorum"
    assert fast["dominant_node"] == 1
    assert fast["phase_us"]["quorum"] == 900.0
    assert slow["dominant_phase"] == "transmit"
    assert slow["dominant_node"] == 2
    assert slow["phase_us"]["transmit"] == 3000.0
    # The ASCII rendering names every phase column and the dominants.
    table = attribution_table(bands)
    assert "transmit" in table and "quorum" in table
    assert "p50-p100" in table


def test_ledger_from_dir_reads_cluster_layout(tmp_path):
    """trace files one level down in node*/ (the supervisor root) and a
    records.json are both picked up."""
    traces = _merge_instants(_transmit_bound_seq(5, 0))
    for i, trace in enumerate(traces):
        node_dir = tmp_path / f"node{i}"
        node_dir.mkdir()
        (node_dir / "trace.json").write_text(json.dumps(trace))
    (tmp_path / "records.json").write_text(
        json.dumps(
            [
                {
                    "client_id": 7,
                    "req_no": 3,
                    "seq": 5,
                    "node": 1,
                    "submit_ns": int((_BASE_US + 400) * 1000),
                    "commit_ns": int((_BASE_US + 5800) * 1000),
                }
            ]
        )
    )
    ledger, n_traces = ledger_from_dir(str(tmp_path))
    assert n_traces == 3
    assert len(ledger) == 1 and ledger[0].phases["ingress"] == 600.0


def test_critpath_cli_renders_attribution(tmp_path):
    traces = _merge_instants(
        _quorum_bound_seq(10, 0), _transmit_bound_seq(20, 20_000)
    )
    for i, trace in enumerate(traces):
        (tmp_path / f"trace{i}.json").write_text(json.dumps(trace))
    proc = subprocess.run(
        [sys.executable, "-m", "mirbft_tpu.obsv", "--critpath", str(tmp_path)],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "2 committed flow(s)" in proc.stdout
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert verdict["bands"]
    assert verdict["bands"][0]["dominant_phase"] in (
        "ingress",
        "hash",
        "transmit",
        "quorum",
        "commit",
        "apply",
    )
    # Empty/missing dirs are a distinct, nonzero exit.
    empty = tmp_path / "empty"
    empty.mkdir()
    proc = subprocess.run(
        [sys.executable, "-m", "mirbft_tpu.obsv", "--critpath", str(empty)],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 2


# ---------------------------------------------------------------------------
# Knee finder
# ---------------------------------------------------------------------------


class _Step:
    def __init__(self, rate, p95_ms, committed=None, duration_s=1.0):
        self.committed = int(rate if committed is None else committed)
        self.p95_ms = p95_ms
        self.p50_ms = p95_ms / 2
        self.p99_ms = p95_ms * 1.2
        self.goodput_per_sec = self.committed / duration_s


def _synthetic_curve(capacity=450.0, base_ms=40.0):
    """Latency gently rising below capacity, a cliff past it."""

    def measure(rate):
        if rate <= capacity:
            return _Step(rate, base_ms + rate / 50.0)
        return _Step(rate, base_ms * 50.0)

    return measure


def test_find_knee_brackets_synthetic_capacity():
    result = find_knee(
        _synthetic_curve(capacity=450.0),
        50.0,
        slo_p95_ms=100.0,
        max_steps=12,
        resolution=0.05,
    )
    assert result.located
    # The knee is the highest *probed* passing rate: within resolution
    # of the true 450/s capacity and never above it.
    assert 400.0 <= result.knee_rate_per_sec <= 450.0
    assert result.knee_rate_per_sec == result.max_measured_ok
    # The ramp is geometric until the first failure, then bisection.
    rates = [s["rate_per_sec"] for s in result.steps]
    assert rates[:4] == [50.0, 100.0, 200.0, 400.0]
    assert all(s["ok"] for s in result.steps[:4])
    assert not result.steps[4]["ok"]  # 800 broke the SLO


def test_find_knee_no_knee_within_budget_is_honest():
    result = find_knee(
        _synthetic_curve(capacity=10_000.0),
        50.0,
        slo_p95_ms=1_000.0,
        max_rate=200.0,  # budget cleared before the SLO ever breaks
        max_steps=12,
    )
    assert not result.located
    assert result.knee_rate_per_sec is None
    assert all(s["ok"] for s in result.steps)
    assert result.max_measured_ok == 200.0


def test_find_knee_all_fail_is_not_a_located_zero_knee():
    """A cluster that never meets the SLO at any probed rate (wedged,
    starved, or broken) must report located=False, not a located knee
    of 0.0 — a zero would poison the artifact's min-across-configs
    headline with a number that is not a capacity."""
    result = find_knee(
        lambda rate: _Step(rate, 50_000.0),  # SLO never holds
        16.0,
        slo_p95_ms=8_000.0,
        max_steps=7,
    )
    assert not result.located
    assert result.knee_rate_per_sec is None
    assert result.max_measured_ok == 0.0
    assert not any(s["ok"] for s in result.steps)
    # And the artifact headline ignores the unlocated config entirely.
    doc = artifact([config_doc("wedged", result)])
    assert doc["knee_rate_per_sec"] is None


def test_find_knee_goodput_criterion_fails_collapsed_probe():
    """Past hard saturation almost nothing commits; the few survivors
    can show a lucky p95 under the SLO.  The goodput floor must fail
    the probe anyway."""

    def measure(rate):
        if rate <= 100.0:
            return _Step(rate, 50.0)
        return _Step(rate, 60.0, committed=1)  # collapse, lucky p95

    loose = find_knee(measure, 50.0, slo_p95_ms=100.0, max_steps=4)
    assert not loose.located  # p95 alone never breaks: no knee found

    strict = find_knee(
        measure,
        50.0,
        slo_p95_ms=100.0,
        max_steps=8,
        min_goodput_ratio=0.5,
    )
    assert strict.located
    assert strict.knee_rate_per_sec <= 100.0


# ---------------------------------------------------------------------------
# Capacity artifact + diff gate
# ---------------------------------------------------------------------------


def _capacity_artifact(knee_rate):
    measure = _synthetic_curve(capacity=knee_rate)
    result = find_knee(
        measure, 50.0, slo_p95_ms=100.0, max_steps=12, resolution=0.05
    )
    return artifact(
        [
            config_doc(
                "pipelined-lan",
                result,
                profile="lan",
                processor="pipelined",
            )
        ],
        nodes=8,
    )


def test_capacity_artifact_schema_and_headline():
    doc = _capacity_artifact(450.0)
    assert doc["schema"] == SCHEMA
    assert doc["knee_rate_per_sec"] == doc["configs"][0]["knee_rate_per_sec"]
    assert doc["configs"][0]["located"]


def test_diff_gates_injected_knee_regression(tmp_path):
    """A knee that moves down >= threshold must fail ``obsv --diff``
    (exit 1), both for a bare capacity artifact and for a bench payload
    embedding one under "capacity"."""
    good = _capacity_artifact(450.0)
    bad = _capacity_artifact(220.0)  # injected regression: knee halved
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(good))
    b.write_text(json.dumps(bad))

    def run_diff(x, y):
        return subprocess.run(
            [
                sys.executable,
                "-m",
                "mirbft_tpu.obsv",
                "--diff",
                str(x),
                str(y),
                "--threshold",
                "10",
            ],
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=120,
        )

    proc = run_diff(a, b)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert any(
        "knee_rate_per_sec" in r["series"] for r in verdict["regressions"]
    )

    # Equal artifacts pass.
    proc = run_diff(a, a)
    assert proc.returncode == 0, proc.stdout + proc.stderr

    # Embedded in a bench payload under "capacity", same verdict.
    pa = tmp_path / "pa.json"
    pb = tmp_path / "pb.json"
    pa.write_text(json.dumps({"metric": "x", "value": 1.0, "capacity": good}))
    pb.write_text(json.dumps({"metric": "x", "value": 1.0, "capacity": bad}))
    proc = run_diff(pa, pb)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert any(
        r["series"].startswith("capacity.") for r in verdict["regressions"]
    )
