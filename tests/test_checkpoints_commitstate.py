"""Gates for core.checkpoints (value agreement, windows, buffering) and
core.commitstate (ring buffers, checkpoint pipelining, stop throttle,
state-transfer resume) — VERDICT r2 item 5."""

import pytest

from mirbft_tpu import pb
from mirbft_tpu.core.actions import Actions
from mirbft_tpu.core.checkpoints import (
    Checkpoint,
    CheckpointDivergenceError,
    CheckpointTracker,
)
from mirbft_tpu.core.commitstate import CommitState, next_network_config
from mirbft_tpu.core.msgbuffers import NodeBuffers
from mirbft_tpu.core.persisted import Persisted


def network_config(n=4, f=1, ci=5):
    return pb.NetworkConfig(
        nodes=list(range(n)),
        f=f,
        number_of_buckets=n,
        checkpoint_interval=ci,
        max_epoch_length=10 * ci,
    )


def network_state(n=4, f=1, ci=5, reconfigs=()):
    return pb.NetworkState(
        config=network_config(n, f, ci),
        clients=[],
        pending_reconfigurations=list(reconfigs),
    )


def centry(seq, value=b"cp", state=None):
    return pb.CEntry(
        seq_no=seq,
        checkpoint_value=value,
        network_state=state if state is not None else network_state(),
    )


MY = pb.InitialParameters(id=0, buffer_size=1 << 20)


# ---------------------------------------------------------------------------
# Checkpoint value agreement
# ---------------------------------------------------------------------------


def test_checkpoint_agreement_rules():
    cp = Checkpoint(20, network_config(), my_id=0)
    cp.apply_checkpoint_msg(1, b"v")
    assert cp.committed_value is None
    cp.apply_checkpoint_msg(2, b"v")  # f+1 = 2 -> committed
    assert cp.committed_value == b"v"
    assert not cp.stable
    cp.apply_checkpoint_msg(0, b"v")  # own value + 3 >= 2f+1 -> stable
    assert cp.stable


def test_checkpoint_votes_deduped():
    cp = Checkpoint(20, network_config(), my_id=0)
    cp.apply_checkpoint_msg(1, b"v")
    cp.apply_checkpoint_msg(1, b"v")
    assert cp.committed_value is None  # still one vote, not f+1


def test_checkpoint_divergence_raises():
    cp = Checkpoint(20, network_config(), my_id=0)
    cp.apply_checkpoint_msg(1, b"net")
    cp.apply_checkpoint_msg(2, b"net")
    with pytest.raises(CheckpointDivergenceError):
        cp.apply_checkpoint_msg(0, b"mine")


# ---------------------------------------------------------------------------
# CheckpointTracker
# ---------------------------------------------------------------------------


def make_tracker(*c_entries):
    persisted = Persisted()
    for e in c_entries:
        persisted.add_c_entry(e)
    tracker = CheckpointTracker(persisted, NodeBuffers(MY), MY)
    tracker.reinitialize()
    return tracker


def test_tracker_reinitialize_extends_to_three_windows():
    t = make_tracker(centry(0, b"genesis"))
    assert t.low_watermark() == 0
    assert t.high_watermark() == 10  # 0, 5, 10 with ci=5
    assert [cp.seq_no for cp in t.active] == [0, 5, 10]
    assert t.active[0].stable


def test_tracker_step_to_stable_and_gc():
    t = make_tracker(centry(0, b"genesis"))
    msg = pb.Msg(type=pb.Checkpoint(seq_no=5, value=b"cp5"))
    for node in (1, 2):
        t.step(node, msg)
    assert not t.garbage_collectable
    t.step(0, msg)  # own vote arrives via loopback send
    assert t.garbage_collectable
    new_low = t.garbage_collect()
    assert new_low == 5
    assert [cp.seq_no for cp in t.active] == [5, 10, 15]
    assert not t.garbage_collectable


def test_tracker_buffers_future_and_replays_after_slide():
    t = make_tracker(centry(0, b"genesis"))
    future = pb.Msg(type=pb.Checkpoint(seq_no=15, value=b"cp15"))
    for node in (0, 1, 2):
        t.step(node, future)  # above high watermark 10: buffered + tallied
    assert t.checkpoint_map[15].votes[b"cp15"] == {0, 1, 2}
    # Slide to 5: cp15 now in-window; replay is deduped, no double count.
    msg5 = pb.Msg(type=pb.Checkpoint(seq_no=5, value=b"cp5"))
    for node in (0, 1, 2):
        t.step(node, msg5)
    t.garbage_collect()
    assert t.checkpoint_map[15].votes[b"cp15"] == {0, 1, 2}
    # cp15 became stable during replay (own + 2f+1 votes, in window now).
    assert t.checkpoint_map[15].stable


def test_tracker_past_msgs_dropped():
    t = make_tracker(centry(0), centry(5, b"cp5"))
    # Window starts at the *first* CEntry; seq 0 votes are current, then
    # after GC to 5, seq 0 is past.
    msg5 = pb.Msg(type=pb.Checkpoint(seq_no=5, value=b"cp5"))
    for node in (0, 1, 2):
        t.step(node, msg5)
    t.garbage_collect()
    assert t.low_watermark() == 5
    msg0 = pb.Msg(type=pb.Checkpoint(seq_no=0, value=b"x"))
    t.step(3, msg0)  # silently dropped
    assert 0 not in t.checkpoint_map


# ---------------------------------------------------------------------------
# CommitState
# ---------------------------------------------------------------------------


class StubClientTracker:
    def __init__(self):
        self.committed = []

    def drain(self):
        return Actions()

    def commits_completed_for_checkpoint_window(self, seq_no):
        return [pb.NetworkClient(id=1, width=10)]

    def mark_committed(self, client_id, req_no, seq_no):
        self.committed.append((client_id, req_no, seq_no))


def make_commit_state(*entries, ci=5):
    persisted = Persisted()
    for e in entries:
        if isinstance(e, pb.CEntry):
            persisted.add_c_entry(e)
        elif isinstance(e, pb.TEntry):
            persisted.add_t_entry(e)
    cs = CommitState(persisted, StubClientTracker())
    boot_actions = cs.reinitialize()
    return cs, boot_actions


def qentry(seq, digest=b"d", reqs=()):
    return pb.QEntry(seq_no=seq, digest=digest, requests=list(reqs))


def test_commit_state_reinitialize():
    cs, actions = make_commit_state(centry(0, b"genesis"))
    assert actions.is_empty()
    assert cs.low_watermark == 0
    assert cs.stop_at_seq_no == 10  # 2 * ci
    assert not cs.transferring


def test_commit_drain_in_order_with_checkpoint_request():
    cs, _ = make_commit_state(centry(0, b"genesis"))
    # Commit seqs 1..5 out of order; drain only returns in-order prefix.
    cs.commit(qentry(1))
    cs.commit(qentry(2))
    drained = cs.drain()
    assert [c.batch.seq_no for c in drained] == [1, 2]
    cs.commit(qentry(3))
    cs.commit(qentry(4))
    with pytest.raises(AssertionError):
        cs.commit(qentry(6))  # gap: commits reach commit state in order
    drained = cs.drain()
    assert [c.batch.seq_no for c in drained] == [3, 4]
    cs.commit(qentry(5))
    drained = cs.drain()
    # Seq 5 commits, then the checkpoint request for seq 5 fires on the
    # *next* drain pass... actually within the same drain: batch 5 then
    # checkpoint once last_applied == low+ci.
    kinds = [
        ("cp" if c.checkpoint is not None else c.batch.seq_no) for c in drained
    ]
    assert kinds == [5, "cp"]
    cp_req = drained[-1].checkpoint
    assert cp_req.seq_no == 5
    assert cp_req.clients_state[0].id == 1
    # Commits continue into the upper half while the checkpoint computes.
    cs.commit(qentry(6))
    assert [c.batch.seq_no for c in cs.drain()] == [6]


def test_checkpoint_result_slides_window():
    cs, _ = make_commit_state(centry(0, b"genesis"))
    for s in range(1, 7):
        cs.commit(qentry(s))
    cs.drain()
    result = pb.CheckpointResult(
        seq_no=5, value=b"cp5", network_state=network_state()
    )
    actions = cs.apply_checkpoint_result(None, result)
    # CEntry persisted + Checkpoint broadcast.
    assert any(
        isinstance(w.append.data.type, pb.CEntry) for w in actions.write_ahead
    )
    [send] = actions.sends
    assert send.msg == pb.Msg(type=pb.Checkpoint(seq_no=5, value=b"cp5"))
    assert cs.low_watermark == 5
    assert cs.stop_at_seq_no == 15
    # Seq 6 (committed into upper half) survives the slide into lower half.
    drained = cs.drain()
    assert drained == []  # 6 already applied before the slide
    cs.commit(qentry(7))
    assert [c.batch.seq_no for c in cs.drain()] == [7]


def test_stop_at_seq_no_enforced():
    cs, _ = make_commit_state(centry(0, b"genesis"))
    with pytest.raises(AssertionError):
        cs.commit(qentry(11))  # beyond stop at 10


def test_pending_reconfiguration_shortens_stop():
    state = network_state(
        reconfigs=[pb.Reconfiguration(type=pb.ReconfigNewClient(id=9, width=5))]
    )
    cs, _ = make_commit_state(centry(0, b"genesis", state=state))
    assert cs.stop_at_seq_no == 5  # 1 * ci, not 2


def test_next_network_config_applies_reconfigs():
    state = network_state(
        reconfigs=[
            pb.Reconfiguration(type=pb.ReconfigNewClient(id=9, width=5)),
            pb.Reconfiguration(type=pb.ReconfigRemoveClient(client_id=1)),
        ]
    )
    clients = [pb.NetworkClient(id=1, width=10), pb.NetworkClient(id=2, width=10)]
    config, next_clients = next_network_config(state, clients)
    assert [c.id for c in next_clients] == [2, 9]
    assert config == state.config


def test_crash_mid_transfer_resumes():
    cs, actions = make_commit_state(
        centry(0, b"genesis"), pb.TEntry(seq_no=20, value=b"target")
    )
    assert cs.transferring
    assert actions.state_transfer.seq_no == 20
    assert actions.state_transfer.value == b"target"


def test_commit_marks_client_requests():
    cs, _ = make_commit_state(centry(0, b"genesis"))
    cs.commit(
        qentry(1, reqs=[pb.RequestAck(client_id=7, req_no=3, digest=b"d")])
    )
    cs.drain()
    assert cs.client_tracker.committed == [(7, 3, 1)]


def test_duplicate_commit_same_digest_ok_different_raises():
    cs, _ = make_commit_state(centry(0, b"genesis"))
    cs.commit(qentry(1, digest=b"d"))
    cs.commit(qentry(1, digest=b"d"))  # idempotent
    with pytest.raises(AssertionError):
        cs.commit(qentry(1, digest=b"other"))


def test_reconfigured_checkpoint_certification_first_sight():
    """Adoption boundary (PR 19): genesis carries a pending reconfiguration
    (stop shortened to one window); the window's checkpoint result — whose
    network state has drained the pending list — marks the commit state
    ``reconfigured`` (the signal for the full tracker reinitialize), extends
    the stop watermark again, and persists exactly one CEntry.  A recompute
    of the same seq_no after the reinitialize must not re-trigger."""
    pending = network_state(
        reconfigs=[pb.Reconfiguration(type=pb.ReconfigNewClient(id=9, width=5))]
    )
    cs, _ = make_commit_state(centry(0, b"genesis", state=pending))
    assert cs.stop_at_seq_no == 5  # allocation halted one window out
    for s in range(1, 6):
        cs.commit(qentry(s))
    cs.drain()
    result = pb.CheckpointResult(
        seq_no=5, value=b"cp5", network_state=network_state()
    )
    actions = cs.apply_checkpoint_result(None, result)
    assert cs.reconfigured, "adoption checkpoint did not mark reconfigured"
    assert cs.stop_at_seq_no == 15  # pending drained -> full two windows
    c_entries = [
        w for w in actions.write_ahead
        if isinstance(w.append.data.type, pb.CEntry)
    ]
    assert len(c_entries) == 1, "adoption must persist exactly one CEntry"


def test_reconfigured_checkpoint_not_reactivated_when_already_persisted():
    """First-sight guard: when the adoption checkpoint's CEntry is already
    durable (a recompute after the reconfiguration reinitialize), applying
    the result again must neither re-trigger activation nor duplicate the
    CEntry — only the Checkpoint broadcast goes out."""
    pending = network_state(
        reconfigs=[pb.Reconfiguration(type=pb.ReconfigNewClient(id=9, width=5))]
    )
    cs, _ = make_commit_state(centry(0, b"genesis", state=pending))
    for s in range(1, 6):
        cs.commit(qentry(s))
    cs.drain()
    cs.highest_persisted_checkpoint = 5  # the CEntry is already in the log
    actions = cs.apply_checkpoint_result(
        None,
        pb.CheckpointResult(seq_no=5, value=b"cp5", network_state=network_state()),
    )
    assert not cs.reconfigured, "recompute must not re-trigger activation"
    assert not any(
        isinstance(w.append.data.type, pb.CEntry) for w in actions.write_ahead
    )
    [send] = actions.sends
    assert send.msg == pb.Msg(type=pb.Checkpoint(seq_no=5, value=b"cp5"))


def test_checkpoint_result_with_pending_reconfig_does_not_extend_stop():
    """A checkpoint result that still carries pending reconfigurations
    leaves the stop watermark where it was: ordering may finish the current
    window but must not be granted the next one until adoption."""
    cs, _ = make_commit_state(centry(0, b"genesis"))
    assert cs.stop_at_seq_no == 10
    for s in range(1, 6):
        cs.commit(qentry(s))
    cs.drain()
    still_pending = network_state(
        reconfigs=[pb.Reconfiguration(type=pb.ReconfigNewClient(id=9, width=5))]
    )
    cs.apply_checkpoint_result(
        None,
        pb.CheckpointResult(seq_no=5, value=b"cp5", network_state=still_pending),
    )
    assert cs.stop_at_seq_no == 10, "stop must not extend while pending"
    assert not cs.reconfigured  # the *previous* state had nothing pending
    for s in range(6, 11):
        cs.commit(qentry(s))  # finishing the granted window is fine
    with pytest.raises(AssertionError):
        cs.commit(qentry(11))  # but not one batch more
