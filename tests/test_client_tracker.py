"""Gates for the client tracker (VERDICT r2 item 6): windows, weak/strong
certs, ready gating, null-request fallback, fetch/rebroadcast ticks,
checkpoint-boundary window advance, and window rebuild from CEntry pairs."""

from mirbft_tpu import pb
from mirbft_tpu.core.client_tracker import (
    ClientTracker,
    StableList,
)
from mirbft_tpu.core.msgbuffers import NodeBuffers
from mirbft_tpu.core.persisted import Persisted
from mirbft_tpu.core.preimage import host_digest, request_hash_data


def network_config(n=4, f=1, ci=5):
    return pb.NetworkConfig(
        nodes=list(range(n)),
        f=f,
        number_of_buckets=n,
        checkpoint_interval=ci,
        max_epoch_length=50,
    )


def network_state(clients=((7, 20),), n=4, f=1, ci=5):
    return pb.NetworkState(
        config=network_config(n, f, ci),
        clients=[
            pb.NetworkClient(id=cid, width=width, low_watermark=0)
            for cid, width in clients
        ],
    )


def make_tracker(state=None):
    persisted = Persisted()
    persisted.add_c_entry(
        pb.CEntry(
            seq_no=0,
            checkpoint_value=b"genesis",
            network_state=state if state is not None else network_state(),
        )
    )
    my = pb.InitialParameters(id=0, buffer_size=1 << 20)
    ct = ClientTracker(persisted, NodeBuffers(my), my)
    ct.reinitialize()
    return ct


def req(client_id=7, req_no=0, data=b"tx"):
    r = pb.Request(client_id=client_id, req_no=req_no, data=data)
    digest = host_digest(request_hash_data(r))
    return r, pb.RequestAck(client_id=client_id, req_no=req_no, digest=digest)


def ack_msg(ack):
    return pb.Msg(type=ack)


# ---------------------------------------------------------------------------


def test_stable_list_iterators_survive_removal():
    sl = StableList()
    for v in "abcd":
        sl.push_back(v)
    it1 = sl.iterator()
    assert it1.next() == "a"
    it2 = sl.iterator()
    assert it2.next() == "a"
    assert it2.next() == "b"
    it2.remove_last()  # removes "b"
    # it1 is positioned on "a"; it keeps walking and skips the tombstone.
    assert it1.next() == "c"
    fresh = sl.iterator()
    seen = []
    while fresh.has_next():
        seen.append(fresh.next())
    assert seen == ["a", "c", "d"]


def test_propose_path_stores_and_acks():
    ct = make_tracker()
    r, ack = req()
    actions = ct.apply_request_digest(ack, r.data)
    [stored] = actions.store_requests
    assert stored.request_ack == ack and stored.request_data == r.data
    [send] = actions.sends
    assert send.targets == [0, 1, 2, 3]
    assert send.msg == pb.Msg(type=ack)


def test_weak_strong_and_ready_progression():
    ct = make_tracker()
    r, ack = req()
    ct.apply_request_digest(ack, r.data)  # we hold + acked it

    client = ct.client(7)
    crn = client.req_no(0)
    # Our own ack comes back via loopback.
    ct.step(0, ack_msg(ack))
    assert not crn.weak_requests
    ct.step(1, ack_msg(ack))  # f+1 = 2 -> weak
    assert ack.digest in crn.weak_requests
    assert not crn.strong_requests
    ct.step(2, ack_msg(ack))  # 2f+1 = 3 -> strong
    assert ack.digest in crn.strong_requests

    # Strong + held locally -> ready list.
    it = ct.ready_list.iterator()
    assert it.has_next() and it.next() is crn
    assert client.next_ready_mark == 1


def test_ready_requires_local_copy():
    ct = make_tracker()
    _, ack = req()
    # Strong cert without our local copy: not ready.
    for node in (1, 2, 3):
        ct.step(node, ack_msg(ack))
    crn = ct.client(7).req_no(0)
    assert ack.digest in crn.strong_requests
    assert not ct.ready_list.iterator().has_next()


def test_available_list_on_weak_quorum():
    ct = make_tracker()
    _, ack = req()
    ct.step(1, ack_msg(ack))
    assert not ct.available_list.iterator().has_next()
    ct.step(2, ack_msg(ack))
    it = ct.available_list.iterator()
    assert it.has_next()
    assert it.next().ack == ack


def test_non_null_vote_spam_guard():
    ct = make_tracker()
    _, ack_a = req(data=b"a")
    _, ack_b = req(data=b"b")
    ct.step(1, ack_msg(ack_a))
    ct.step(1, ack_msg(ack_b))  # second distinct non-null vote: ignored
    crn = ct.client(7).req_no(0)
    assert ack_b.digest not in crn.requests
    # Re-ack of the same digest is idempotent.
    ct.step(1, ack_msg(ack_a))
    assert crn.requests[ack_a.digest].agreements == 1 << 1  # node 1's bit


def test_conflicting_local_requests_promote_null():
    ct = make_tracker()
    r_a, ack_a = req(data=b"a")
    r_b, ack_b = req(data=b"b")
    ct.apply_request_digest(ack_a, r_a.data)
    actions = ct.apply_request_digest(ack_b, r_b.data)
    # Second distinct persisted request → null request acked + stored.
    null_sends = [
        s for s in actions.sends if s.msg.type.digest == b""
    ]
    assert null_sends, "null request must be advocated"
    crn = ct.client(7).req_no(0)
    assert b"" in crn.my_requests


def test_tick_fetches_lone_correct_missing_request():
    ct = make_tracker()
    _, ack = req()
    ct.step(1, ack_msg(ack))
    ct.step(2, ack_msg(ack))  # weak, but not stored locally
    crn = ct.client(7).req_no(0)
    actions_list = [crn.tick() for _ in range(6)]
    fetches = [a for a in actions_list if a.sends]
    assert len(fetches) == 1  # exactly one fetch after the patience window
    [send] = fetches[0].sends
    assert send.targets == [1, 2]  # the ackers
    assert isinstance(send.msg.type, pb.FetchRequest)
    # Fetch timeout: 4 more ticks of grace, then refetch.
    refetches = [crn.tick() for _ in range(6)]
    assert any(a.sends for a in refetches)


def test_tick_ack_rebroadcast_linear_backoff():
    ct = make_tracker()
    r, ack = req()
    ct.apply_request_digest(ack, r.data)
    crn = ct.client(7).req_no(0)
    sends_at = []
    for t in range(205):
        if crn.tick().sends:
            sends_at.append(t)
    # Linear backoff: resend after ~20 ticks, then ~40 more, then ~60 more.
    assert sends_at == [20, 61, 122, 203]


def test_committed_requests_stop_ticking():
    ct = make_tracker()
    r, ack = req()
    ct.apply_request_digest(ack, r.data)
    ct.mark_committed(7, 0, 3)
    crn = ct.client(7).req_no(0)
    assert all(crn.tick().is_empty() for _ in range(30))


def test_checkpoint_window_advance_partial_commit():
    ct = make_tracker()
    # Commit req_nos 0, 1, and 3 (2 uncommitted).
    for rn in (0, 1, 3):
        ct.mark_committed(7, rn, rn + 1)
    states = ct.commits_completed_for_checkpoint_window(5)
    [state] = states
    assert state.low_watermark == 2
    assert state.width_consumed_last_checkpoint == 2
    # Mask indexed from first uncommitted (2): bit 1 set (req 3).
    assert state.committed_mask == b"\x40"
    client = ct.client(7)
    # Window extended by 2 newly usable reqs, gated on the next checkpoint.
    assert client.high_watermark == 22
    assert client.req_no(22).valid_after_seq_no == 10  # 5 + ci


def test_checkpoint_window_advance_nothing_committed():
    ct = make_tracker()
    states = ct.commits_completed_for_checkpoint_window(5)
    assert states == [ct.client_states[0]]
    assert ct.client(7).high_watermark == 20


def test_checkpoint_window_advance_fully_committed():
    ct = make_tracker(network_state(clients=((7, 3),)))
    for rn in range(4):  # full window 0..3 inclusive
        ct.mark_committed(7, rn, rn + 1)
    [state] = ct.commits_completed_for_checkpoint_window(5)
    assert state.low_watermark == 4
    assert state.width_consumed_last_checkpoint == 3
    client = ct.client(7)
    # Reference stalls here; we re-extend, fully gated on next checkpoint.
    assert client.high_watermark == 7
    assert all(
        client.req_no(rn).valid_after_seq_no == 10 for rn in range(4, 8)
    )


def test_garbage_collect_slides_client_window():
    ct = make_tracker()
    r, ack = req()
    ct.apply_request_digest(ack, r.data)
    for node in (1, 2, 3):
        ct.step(node, ack_msg(ack))
    ct.mark_committed(7, 0, 1)
    ct.commits_completed_for_checkpoint_window(5)
    ct.garbage_collect(5)
    client = ct.client(7)
    assert client.low_watermark == 1
    assert 0 not in client.req_no_map
    # The committed request is gone from ready list.
    assert not ct.ready_list.iterator().has_next()
    # Its requests were tombstoned from the available list.
    assert not ct.available_list.iterator().has_next()


def test_window_rebuild_from_centry_pair():
    # Low CEntry: client at lwm 0, width 10.  High CEntry: lwm 4 with
    # req 5 (mask bit 1) also committed.
    low_state = network_state(clients=((7, 10),))
    high_state = pb.NetworkState(
        config=network_config(),
        clients=[
            pb.NetworkClient(
                id=7,
                width=10,
                width_consumed_last_checkpoint=4,
                low_watermark=4,
                committed_mask=b"\x40",
            )
        ],
    )
    persisted = Persisted()
    persisted.add_c_entry(
        pb.CEntry(seq_no=0, checkpoint_value=b"g", network_state=low_state)
    )
    persisted.add_c_entry(
        pb.CEntry(seq_no=5, checkpoint_value=b"c5", network_state=high_state)
    )
    my = pb.InitialParameters(id=0, buffer_size=1 << 20)
    ct = ClientTracker(persisted, NodeBuffers(my), my)
    ct.reinitialize()
    client = ct.client(7)
    # The tracker rebuilds windows from the latest (high) CEntry's client
    # states (reference: client_tracker.go:324-351 — its low/high state
    # parameters receive the same high-CEntry state).
    assert client.low_watermark == 4
    assert client.high_watermark == 14
    committed = {
        rn for rn in range(4, 15) if client.req_no(rn).committed is not None
    }
    assert committed == {5}  # mask bit 1 relative to lwm 4
    # Tail gated by width consumed (4): last 4 slots wait for the next cp.
    assert client.req_no(10).valid_after_seq_no == 0
    assert client.req_no(11).valid_after_seq_no == 5  # 0 + ci
    assert client.req_no(14).valid_after_seq_no == 5


def test_forward_request_triggers_verify_hash():
    ct = make_tracker()
    r, ack = req()
    # Weak quorum of acks establishes the digest as correct.
    ct.step(1, ack_msg(ack))
    ct.step(2, ack_msg(ack))
    fwd = pb.Msg(
        type=pb.ForwardRequest(request_ack=ack, request_data=r.data)
    )
    actions = ct.step(3, fwd)
    [hr] = actions.hashes
    assert isinstance(hr.origin.type, pb.HashOriginVerifyRequest)
    assert hr.origin.type.source == 3
    assert hr.data == request_hash_data(r)


def test_forward_request_for_unknown_digest_dropped():
    ct = make_tracker()
    r, ack = req()
    fwd = pb.Msg(type=pb.ForwardRequest(request_ack=ack, request_data=r.data))
    assert ct.step(3, fwd).is_empty()


def test_fetch_request_replied_when_stored():
    ct = make_tracker()
    r, ack = req()
    ct.apply_request_digest(ack, r.data)
    msg = pb.Msg(
        type=pb.FetchRequest(client_id=7, req_no=0, digest=ack.digest)
    )
    # We hold the request but haven't acked it into agreements yet... the
    # loopback ack records our agreement.
    ct.step(0, ack_msg(ack))
    actions = ct.step(2, msg)
    [fwd] = actions.forward_requests
    assert fwd.targets == [2]
    assert fwd.request_ack.digest == ack.digest


def test_future_acks_buffered_and_drained():
    ct = make_tracker()
    _, ack_future = req(req_no=21)  # just above window high (20)
    ct.step(1, ack_msg(ack_future))
    assert len(ct.msg_buffers[1]) == 1
    crn_before = ct.client(7).req_no_map.get(21)
    assert crn_before is None
    # Committing req 0 advances the window: high becomes 21.
    ct.mark_committed(7, 0, 1)
    ct.commits_completed_for_checkpoint_window(5)
    assert ct.client(7).high_watermark == 21
    ct.drain()
    # The buffered ack was applied to the newly allocated req_no.
    crn = ct.client(7).req_no(21)
    assert ack_future.digest in crn.requests
    assert len(ct.msg_buffers[1]) == 0


# -- forward-request quorum bookkeeping (regression) ------------------------


def test_forward_request_agreement_crosses_weak_quorum():
    """A ForwardRequest's out-of-band agreement bump must run the same
    quorum bookkeeping as an ack: a crossing it causes may never be
    skipped, because nothing retries it later (regression: the bump set
    the bit but never promoted the certificate)."""
    ct = make_tracker()
    r, ack = req()
    # Node 1's ack creates the request entry with one agreement.
    ct.step(1, ack_msg(ack))
    client = ct.client(7)
    crn = client.req_no(0)
    assert ack.digest not in crn.weak_requests
    # Node 2's ForwardRequest bumps agreements to 2 == f+1: the weak
    # certificate must form right here.
    fwd = pb.Msg(
        type=pb.ForwardRequest(request_ack=ack, request_data=r.data)
    )
    actions = ct.step(2, fwd)
    assert actions.hashes, "forward data must still be hash-verified"
    assert ack.digest in crn.weak_requests
    assert ack.digest not in crn.strong_requests
    # The newly-weak request is on the availability list.
    it = ct.available_list.iterator()
    seen = []
    while it.has_next():
        seen.append(it.next())
    assert any(req_obj.ack.digest == ack.digest for req_obj in seen)


def test_forward_request_agreement_crosses_strong_quorum():
    ct = make_tracker()
    r, ack = req()
    ct.step(1, ack_msg(ack))
    ct.step(2, ack_msg(ack))  # 2 == f+1 -> weak via the ack path
    crn = ct.client(7).req_no(0)
    assert ack.digest in crn.weak_requests
    assert ack.digest not in crn.strong_requests
    fwd = pb.Msg(
        type=pb.ForwardRequest(request_ack=ack, request_data=r.data)
    )
    ct.step(3, fwd)  # 3 == 2f+1: the strong certificate must form
    assert ack.digest in crn.strong_requests


# -- small-frame ack deliveries with a live vector mirror (regression) ------


def test_small_ack_frames_refresh_the_live_mirror():
    """Once a large frame has built the _FastAcks mirror, small frames
    (< 32 acks) take the python loop — which must refresh every touched
    slot, or the mirror's tick classification goes stale (regression: a
    newly-weak unstored request stayed TICK_INERT and its fetch
    machinery never ticked)."""
    from mirbft_tpu.core.client_tracker import _FastAcks

    ct = make_tracker(network_state(clients=((7, 100),)))
    assert ct._fast_ok
    acks = [req(req_no=i)[1] for i in range(40)]
    # One large frame from node 1 builds the mirror (first-vote rows fall
    # back to step_ack per row, which itself refreshes each slot).
    ct.step_ack_many(1, [ack_msg(a) for a in acks])
    fast = ct._fast
    assert fast is not None
    slot = fast.slot_of(7, 0)
    assert fast.tick_class[slot] == _FastAcks.TICK_INERT  # one vote, no certs

    # A small frame from node 2 (loop path) crosses the weak quorum for
    # req_nos 0..2: unstored newly-weak requests need fetch ticks, so the
    # mirror slots must reclassify.
    ct.step_ack_many(2, [ack_msg(a) for a in acks[:3]])
    for req_no in range(3):
        crn = ct.client(7).req_no(req_no)
        assert acks[req_no].digest in crn.weak_requests
        s = fast.slot_of(7, req_no)
        assert fast.tick_class[s] == fast._classify_tick(crn)
        assert fast.tick_class[s] == _FastAcks.TICK_PYTHON
    # Untouched slots keep their old class.
    assert fast.tick_class[fast.slot_of(7, 10)] == _FastAcks.TICK_INERT

    # The reclassified slots actually tick: the fetch machinery for an
    # unstored weak request emits FetchRequest sends within its backoff.
    fetched = False
    for _ in range(64):
        actions = ct.tick()
        if any(
            isinstance(send.msg.type, pb.FetchRequest)
            for send in actions.sends
        ):
            fetched = True
            break
    assert fetched, "newly-weak unstored request never fetched after small frame"
