"""bench.py's one contract: a final JSON line on stdout no matter what.

Exercises the StageRunner's cooperative per-stage timeout and the hard
Watchdog that covers the case a stage wedges the interpreter — both with
a deliberately Event-blocked stage, using the injectable emit/exit seams
so no test ever hard-exits the pytest process."""

import json
import threading
import time

import bench
from mirbft_tpu.obsv.metrics import Registry


def test_stage_runner_marks_wedged_stage_timeout():
    runner = bench.StageRunner(budget_s=60.0, registry=Registry(), stage_budget_s=0.2)
    release = threading.Event()
    try:
        result = runner.run("wedged", lambda: release.wait(timeout=30.0))
        assert result is None
        assert runner.status["wedged"]["status"] == "timeout"
        # Later stages still run on the remaining budget.
        assert runner.run("after", lambda: "ok") == "ok"
        assert runner.status["after"]["status"] == "ok"
    finally:
        release.set()


def test_stage_runner_records_errors_without_crashing():
    runner = bench.StageRunner(budget_s=60.0, registry=Registry())

    def boom():
        raise RuntimeError("stage blew up")

    assert runner.run("bad", boom) is None
    entry = runner.status["bad"]
    assert entry["status"] == "error"
    assert "stage blew up" in entry["detail"]
    report = runner.stage_report()
    assert report["bad"]["seconds"] is not None


def test_stage_runner_skips_disabled_and_exhausted_stages():
    runner = bench.StageRunner(budget_s=60.0, registry=Registry())
    assert runner.run("off", lambda: 1, enabled=False, detail="why") is None
    assert runner.status["off"] == {"status": "skipped", "detail": "why"}
    runner.deadline = time.monotonic()  # no runway left
    assert runner.run("late", lambda: 1) is None
    assert runner.status["late"]["detail"] == "budget exhausted"


def test_watchdog_emits_final_json_and_names_wedged_stage():
    """A stage that never yields: the watchdog must still get the final
    JSON line out, mark the stage timeout, and exit(1)."""
    runner = bench.StageRunner(budget_s=60.0, registry=Registry())
    lines = []
    codes = []
    dog = bench.Watchdog(
        runner, deadline_s=0.1, emit=lines.append, exit_fn=codes.append
    )
    dog.start()
    release = threading.Event()
    try:
        # Large stage budget: only the hard watchdog can catch this one.
        # run() returns after join times out at ~30s normally, but the
        # watchdog fires at 0.1s while `current` still names the stage.
        t = threading.Thread(
            target=lambda: runner.run("stuck", lambda: release.wait(timeout=30.0)),
            daemon=True,
        )
        t.start()
        assert dog.fired.wait(timeout=5.0), "watchdog never fired"
    finally:
        release.set()
    assert codes == [1]
    payload = json.loads(lines[0])
    assert payload["watchdog_fired"] is True
    assert payload["wedged_stage"] == "stuck"
    assert payload["stages"]["stuck"]["status"] == "timeout"
    assert payload["metric"] == "committed_reqs_per_sec_per_chip"
    assert payload["value"] is None


def test_watchdog_cancel_prevents_firing():
    runner = bench.StageRunner(budget_s=60.0, registry=Registry())
    lines = []
    codes = []
    dog = bench.Watchdog(
        runner, deadline_s=0.05, emit=lines.append, exit_fn=codes.append
    )
    dog.start()
    dog.cancel()
    time.sleep(0.15)
    assert not dog.fired.is_set()
    assert lines == [] and codes == []
    # fire() after cancel is also a no-op (clean-exit race).
    dog.fire("too late")
    assert lines == [] and codes == []


def test_watchdog_fire_is_idempotent():
    runner = bench.StageRunner(budget_s=60.0, registry=Registry())
    lines = []
    codes = []
    dog = bench.Watchdog(
        runner, deadline_s=60.0, emit=lines.append, exit_fn=codes.append
    )
    dog.fire("first")
    dog.fire("second")
    assert len(lines) == 1 and codes == [1]


def test_live_payload_keys_present_in_main_schema():
    """The acceptance keys must be spelled exactly as the driver greps
    for them — guard the literal strings in bench.main's payload."""
    import inspect

    src = inspect.getsource(bench.main)
    assert '"live_reqs_per_sec_serial"' in src
    assert '"live_reqs_per_sec_pipelined"' in src
    assert '"live_pipelined_speedup"' in src
    # Attack rung: the duplication-flood A/B keys obsv --diff gates.
    assert '"live_attack_goodput_per_sec"' in src
    assert '"live_attack_commit_p95_ms"' in src
    assert '"live_attack_clean_goodput_per_sec"' in src
    assert '"live_attack_clean_commit_p95_ms"' in src
    assert '"live_attack_goodput_ratio"' in src


def test_bench_stream_journals_stages_as_they_finish(tmp_path):
    """Every finished stage lands in the JSONL immediately — the
    crash-proofing contract the SIGKILL test below relies on."""
    path = str(tmp_path / "stream.jsonl")
    stream = bench.BenchStream(path)
    registry = Registry()
    runner = bench.StageRunner(budget_s=60.0, registry=registry,
                               stream=stream)
    assert runner.run("fast", lambda: 41 + 1) == 42
    # The stage line is durable before any later stage runs.
    lines = [json.loads(l) for l in open(path)]
    assert lines[0]["kind"] == "header"
    assert lines[0]["schema"] == bench.BenchStream.SCHEMA
    assert lines[-1] == {
        "kind": "stage",
        "stage": "fast",
        "seconds": lines[-1]["seconds"],
        "status": "ok",
    }
    try:
        runner.run("boom", lambda: 1 / 0)
    except ZeroDivisionError:
        pass
    stream.final({"metric": "m", "value": 1.0})
    stream.close()
    lines = [json.loads(l) for l in open(path)]
    kinds = [l["kind"] for l in lines]
    assert kinds == ["header", "stage", "stage", "final"]
    boom = lines[2]
    assert boom["stage"] == "boom" and boom["status"] == "error"
    assert lines[3]["payload"]["value"] == 1.0


def test_bench_stream_swallows_unwritable_path(tmp_path):
    stream = bench.BenchStream(str(tmp_path / "no" / "such" / "dir.jsonl"))
    stream.final({"x": 1})  # must not raise
    stream.close()


def test_stage_runner_warmup_excluded_from_timed_window():
    registry = Registry()
    runner = bench.StageRunner(budget_s=60.0, registry=registry)
    result = runner.run(
        "warm",
        lambda: time.sleep(0.02) or "done",
        warmup=lambda: time.sleep(0.15),
    )
    assert result == "done"
    entry = runner.status["warm"]
    assert entry["status"] == "ok"
    assert entry["compile_s"] >= 0.15
    timed = registry.gauge("mirbft_bench_stage_seconds", stage="warm").value
    compile_s = registry.gauge(
        "mirbft_bench_stage_compile_seconds", stage="warm"
    ).value
    assert compile_s >= 0.15
    assert timed < compile_s  # compile cost stayed out of the fn timing


def test_stream_survives_sigkill_mid_rung(tmp_path):
    """Acceptance: SIGKILL while a rung is mid-flight leaves a valid
    JSONL carrying every rung that already completed."""
    import pathlib
    import signal
    import subprocess
    import sys

    repo = pathlib.Path(bench.__file__).resolve().parent
    path = str(tmp_path / "BENCH_stream.jsonl")
    script = (
        "import threading, bench\n"
        "from mirbft_tpu.obsv.metrics import Registry\n"
        f"stream = bench.BenchStream({path!r})\n"
        "runner = bench.StageRunner(budget_s=600.0, registry=Registry(),\n"
        "                           stream=stream)\n"
        "runner.run('first', lambda: 'ok')\n"
        "runner.run('second', lambda: 'ok')\n"
        "print('RUNGS-DONE', flush=True)\n"
        "runner.run('wedged', threading.Event().wait)\n"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", script],
        cwd=str(repo),
        stdout=subprocess.PIPE,
        text=True,
    )
    try:
        assert proc.stdout.readline().strip() == "RUNGS-DONE"
        proc.kill()  # SIGKILL: no atexit, no flush handlers
        proc.wait(timeout=30)
        assert proc.returncode == -signal.SIGKILL
    finally:
        if proc.poll() is None:
            proc.kill()
    lines = [json.loads(l) for l in open(path)]  # every line parses
    assert [l["kind"] for l in lines] == ["header", "stage", "stage"]
    assert [l["stage"] for l in lines[1:]] == ["first", "second"]
    assert all(l["status"] == "ok" for l in lines[1:])
