"""Unit gates for the dynamic-membership runtime seams (PR 19): the
reconfiguration request codec, the shared checkpoint-result network-state
helper, the config-agreement invariant, the status surface, and the metric
catalog rows.  The protocol-level behavior is covered end to end in
test_reconfiguration.py; these pin the seams the drivers and workers share."""

import json

import pytest

from mirbft_tpu import pb
from mirbft_tpu.chaos.invariants import InvariantViolation, check_config_agreement
from mirbft_tpu.core import actions as act
from mirbft_tpu.obsv import metrics as metrics_mod
from mirbft_tpu.runtime.reconfig import (
    RECONFIG_MAGIC,
    checkpoint_network_state,
    decode_reconfig_request,
    encode_reconfig_request,
    is_reconfig_request,
    reconfig_kind,
)
from mirbft_tpu.status import state_machine_status
from mirbft_tpu.testengine import BasicRecorder


# ---------------------------------------------------------------------------
# Request codec
# ---------------------------------------------------------------------------


def _sample_reconfigs():
    return [
        pb.Reconfiguration(type=pb.ReconfigNewClient(id=7, width=50)),
        pb.Reconfiguration(type=pb.ReconfigRemoveClient(client_id=3)),
        pb.Reconfiguration(
            type=pb.NetworkConfig(
                nodes=[0, 1, 2, 3, 4],
                f=1,
                number_of_buckets=4,
                checkpoint_interval=8,
                max_epoch_length=16,
            )
        ),
    ]


def test_reconfig_request_round_trip():
    payload = encode_reconfig_request(_sample_reconfigs())
    assert is_reconfig_request(payload)
    decoded = decode_reconfig_request(payload)
    assert [pb.encode(r) for r in decoded] == [
        pb.encode(r) for r in _sample_reconfigs()
    ]


def test_reconfig_request_empty_list_is_still_marked():
    payload = encode_reconfig_request([])
    assert payload == RECONFIG_MAGIC
    assert decode_reconfig_request(payload) == []


def test_non_reconfig_payload_decodes_to_none():
    # Ordinary app payloads — including ones that merely *contain* the
    # magic somewhere inside — are not reconfiguration requests.
    assert decode_reconfig_request(b"set k v") is None
    assert decode_reconfig_request(b"x" + RECONFIG_MAGIC) is None
    assert not is_reconfig_request(b"")


def test_malformed_reconfig_payload_is_same_everywhere_noop():
    """A payload carrying the magic but truncated mid-entry must decode to
    [] (not raise, not None): the request committed in the same order at
    every correct node, so all must draw the identical conclusion."""
    good = encode_reconfig_request(_sample_reconfigs())
    for cut in (len(RECONFIG_MAGIC) + 2, len(good) - 3):
        assert decode_reconfig_request(good[:cut]) == []
    # Length prefix pointing past the buffer.
    assert decode_reconfig_request(RECONFIG_MAGIC + b"\xff\xff\xff\xff") == []


def test_reconfig_kind_arms():
    new_client, remove_client, network = _sample_reconfigs()
    assert reconfig_kind(new_client) == "new_client"
    assert reconfig_kind(remove_client) == "remove_client"
    assert reconfig_kind(network) == "network_config"
    assert reconfig_kind(pb.Reconfiguration(type=None)) == "unknown"


# ---------------------------------------------------------------------------
# Shared checkpoint-result -> NetworkState helper
# ---------------------------------------------------------------------------


def test_checkpoint_network_state_threads_pending_reconfigs():
    config = pb.NetworkConfig(
        nodes=[0, 1, 2, 3], f=1, number_of_buckets=4,
        checkpoint_interval=5, max_epoch_length=50,
    )
    clients = [pb.NetworkClient(id=9, width=10, low_watermark=2)]
    cr = act.CheckpointResult(
        checkpoint=act.CheckpointReq(
            seq_no=15, network_config=config, clients_state=clients
        ),
        value=b"cp",
        reconfigurations=_sample_reconfigs(),
    )
    state = checkpoint_network_state(cr)
    assert state.config == config
    assert state.clients == clients
    assert [pb.encode(r) for r in state.pending_reconfigurations] == [
        pb.encode(r) for r in _sample_reconfigs()
    ]
    # No reconfigurations in the window -> an empty pending list, never None.
    bare = act.CheckpointResult(
        checkpoint=cr.checkpoint, value=b"cp", reconfigurations=[]
    )
    assert checkpoint_network_state(bare).pending_reconfigurations == []


# ---------------------------------------------------------------------------
# Config-agreement invariant
# ---------------------------------------------------------------------------


_CFG_A = pb.encode(
    pb.NetworkConfig(nodes=[0, 1, 2, 3], f=1, number_of_buckets=4,
                     checkpoint_interval=5, max_epoch_length=50)
)
_CFG_B = pb.encode(
    pb.NetworkConfig(nodes=[0, 1, 2, 3, 4], f=1, number_of_buckets=4,
                     checkpoint_interval=5, max_epoch_length=50)
)


def test_config_agreement_vacuity_guard():
    with pytest.raises(InvariantViolation, match="vacuous"):
        check_config_agreement(
            {0: {5: _CFG_A}}, {0: _CFG_A}, adoptions=0
        )


def test_config_agreement_detects_checkpoint_fork():
    checkpoint_configs = {
        0: {5: _CFG_A, 10: _CFG_B},
        1: {5: _CFG_A, 10: _CFG_A},  # node 1 certified a different config at 10
    }
    with pytest.raises(InvariantViolation):
        check_config_agreement(
            checkpoint_configs, {0: _CFG_B, 1: _CFG_B}, adoptions=2
        )


def test_config_agreement_detects_final_divergence():
    checkpoint_configs = {0: {5: _CFG_A}, 1: {5: _CFG_A}}
    with pytest.raises(InvariantViolation):
        check_config_agreement(
            checkpoint_configs, {0: _CFG_A, 1: _CFG_B}, adoptions=1
        )


def test_config_agreement_happy_path_tallies():
    checkpoint_configs = {
        0: {5: _CFG_A, 10: _CFG_B},
        1: {10: _CFG_B},  # sparse evidence (e.g. a late joiner) is fine
    }
    tally = check_config_agreement(
        checkpoint_configs, {0: _CFG_B, 1: _CFG_B}, adoptions=2
    )
    assert tally["adoptions"] == 2
    # Only cross-node re-sightings count as comparisons: seq 5 has a single
    # witness, seq 10 two -> one genuine byte-equality check performed.
    assert tally["checkpoints_compared"] == 1
    assert tally["survivors"] == 2


# ---------------------------------------------------------------------------
# Status surface + metric catalog
# ---------------------------------------------------------------------------


def test_status_exposes_network_config_section():
    rec = BasicRecorder(node_count=4, client_count=1, reqs_per_client=8)
    rec.drain_clients(max_steps=500_000)
    status = state_machine_status(rec.machines[0])
    section = status.network_config
    assert section is not None
    assert section.nodes == [0, 1, 2, 3]
    assert section.f == 1
    assert section.pending_reconfigurations == 0
    assert section.reconfigs_adopted == 0
    assert section.retired is False
    blob = json.loads(status.to_json())
    assert blob["network_config"]["nodes"] == [0, 1, 2, 3]
    assert "reconfigs_adopted" in blob["network_config"]
    assert "nodes=[0, 1, 2, 3]" in status.pretty() or "nodes" in status.pretty()


def test_removed_node_retires_and_counts_adoption():
    """After a node-set shrink activates, the excluded node's machine is
    ``retired`` and every member's status counts the adoption."""
    state = pb.NetworkState(
        config=pb.NetworkConfig(
            nodes=[0, 1, 2, 3, 4], f=1, number_of_buckets=4,
            checkpoint_interval=8, max_epoch_length=16,
        ),
        clients=[
            pb.NetworkClient(id=cid, width=48, low_watermark=0)
            for cid in (10, 11)
        ],
    )
    four_node = pb.NetworkConfig(
        nodes=[0, 1, 2, 3], f=1, number_of_buckets=4,
        checkpoint_interval=8, max_epoch_length=16,
    )
    rec = BasicRecorder(
        node_count=5, client_count=2, reqs_per_client=40, batch_size=2,
        network_state=state,
    )
    rec.reconfig_on_commit[(11, 2)] = [pb.Reconfiguration(type=four_node)]
    rec.drain_until(lambda r: r.machines[4].retired, max_steps=1_000_000)
    retired_status = state_machine_status(rec.machines[4])
    assert retired_status.network_config.retired is True
    rec.crash(4)
    rec.drain_clients(max_steps=2_000_000)
    for n in range(4):
        section = state_machine_status(rec.machines[n]).network_config
        assert section.nodes == [0, 1, 2, 3]
        assert section.reconfigs_adopted >= 1
        assert section.retired is False


def test_reconfig_metrics_cataloged_and_budgeted():
    for name in (
        "mirbft_reconfig_committed_total",
        "mirbft_reconfig_adopted_total",
    ):
        assert name in metrics_mod.CATALOG
        assert name in metrics_mod.CATALOG_LABELS
    assert metrics_mod.CATALOG_LABELS["mirbft_reconfig_committed_total"] == (
        "kind",
    )
    assert metrics_mod.CATALOG_LABELS["mirbft_reconfig_adopted_total"] == ()
    # The kind label is a closed four-arm set; the budget must match so a
    # typo'd kind is rejected rather than silently growing a series.
    assert metrics_mod.CARDINALITY["mirbft_reconfig_committed_total"] == 4
