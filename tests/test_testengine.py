"""Determinism + liveness gates for the testengine (SURVEY §4 tier 1/2).

The anchors mirror the reference's methodology (exact event counts and
identical final app hash chains, reference: testengine/recorder_test.go):
fixed seed ⇒ fixed event count ⇒ fixed app chain, identical on every node.
"""

import os

import pytest

from mirbft_tpu import pb
from mirbft_tpu.testengine import BasicRecorder
from mirbft_tpu.testengine.engine import RuntimeParameters


def chains(recorder):
    return {
        n: recorder.node_states[n].app_chain.hex()
        for n in range(recorder.node_count)
        if not recorder.node_states[n].crashed
    }


def test_single_node_network():
    r = BasicRecorder(node_count=1, client_count=1, reqs_per_client=3)
    count = r.drain_clients(max_steps=20000)
    # Exact-count regression anchor (the reference pins 63 for its engine,
    # recorder_test.go:95-99; ours is its own engine with its own constant).
    assert count == 19
    assert len(r.node_states[0].committed_reqs) == 3


def test_four_node_network_commits_identically():
    r = BasicRecorder(node_count=4, client_count=4, reqs_per_client=5)
    r.drain_clients(max_steps=100000)
    assert len(set(chains(r).values())) == 1
    # Exactly-once per node.
    for n in range(4):
        committed = [
            (c, rn) for (c, rn, _s) in r.node_states[n].committed_reqs
        ]
        assert len(committed) == len(set(committed)) == 20


def test_determinism_fixed_seed_fixed_count():
    runs = []
    for _ in range(2):
        r = BasicRecorder(node_count=4, client_count=4, reqs_per_client=20)
        count = r.drain_clients(max_steps=200000)
        runs.append((count, tuple(sorted(chains(r).values()))))
    assert runs[0] == runs[1]


def test_kernel_crypto_run_identical_to_host():
    """SURVEY §7's determinism-carries-over property: a run whose every
    digest comes off the SHA-256 kernel produces the same event count and
    app chains as the host-hashlib run (VERDICT r2 item 2)."""
    from mirbft_tpu.ops.sha256 import sha256_chunked

    host = BasicRecorder(node_count=4, client_count=2, reqs_per_client=10,
                         batch_size=2)
    host_count = host.drain_clients(max_steps=100000)

    kernel = BasicRecorder(node_count=4, client_count=2, reqs_per_client=10,
                           batch_size=2, hash_executor=sha256_chunked)
    kernel_count = kernel.drain_clients(max_steps=100000)

    assert kernel_count == host_count
    assert chains(kernel) == chains(host)
    assert len(set(chains(kernel).values())) == 1


def test_batching_run():
    r = BasicRecorder(
        node_count=4, client_count=4, reqs_per_client=25, batch_size=5
    )
    r.drain_clients(max_steps=200000)
    assert len(set(chains(r).values())) == 1


@pytest.mark.slow
def test_reference_anchor_scale():
    # The reference's 4x4x200 determinism anchor scale
    # (recorder_test.go:69-71).
    r = BasicRecorder(node_count=4, client_count=4, reqs_per_client=200)
    count = r.drain_clients(max_steps=500000)
    assert count == 3152  # regression anchor for our engine
    assert len(set(chains(r).values())) == 1


def test_coalescing_plane_identical_to_inline():
    """The crypto plane defers digests to result-delivery time and flushes
    everything pending across all nodes in one batch; values, event counts,
    and app chains must match inline hashing exactly (crypto_plane.py)."""
    from mirbft_tpu.testengine.crypto_plane import CoalescingHashPlane

    inline = BasicRecorder(node_count=4, client_count=2, reqs_per_client=10,
                           batch_size=2)
    inline_count = inline.drain_clients(max_steps=100000)

    plane = CoalescingHashPlane()  # host digests; coalescing only
    deferred = BasicRecorder(node_count=4, client_count=2, reqs_per_client=10,
                             batch_size=2, hash_plane=plane)
    deferred_count = deferred.drain_clients(max_steps=100000)

    assert deferred_count == inline_count
    assert chains(deferred) == chains(inline)
    # The point of the plane: flushes must actually coalesce across nodes —
    # strictly fewer kernel calls than hash actions.
    assert sum(plane.flush_sizes) > len(plane.flush_sizes)
    assert max(plane.flush_sizes) >= 4


def test_coalescing_plane_with_kernel_digests():
    """Plane + accelerator digests: the full bench configuration, at toy
    scale, still bit-identical to the host run."""
    from mirbft_tpu.ops.sha256 import sha256_many
    from mirbft_tpu.testengine.crypto_plane import CoalescingHashPlane

    host = BasicRecorder(node_count=4, client_count=2, reqs_per_client=6,
                         batch_size=2)
    host_count = host.drain_clients(max_steps=100000)

    plane = CoalescingHashPlane(digest_many=sha256_many)
    kernel = BasicRecorder(node_count=4, client_count=2, reqs_per_client=6,
                           batch_size=2, hash_plane=plane)
    kernel_count = kernel.drain_clients(max_steps=100000)

    assert kernel_count == host_count
    assert chains(kernel) == chains(host)


def test_async_kernel_plane_identical_to_inline():
    """The bench's production plane (fixed launch shapes, lazy forcing of
    async-dispatched chunks) is still bit-identical to inline hashing."""
    from mirbft_tpu.testengine.crypto_plane import AsyncKernelHashPlane

    host = BasicRecorder(node_count=4, client_count=2, reqs_per_client=6,
                         batch_size=2)
    host_count = host.drain_clients(max_steps=100000)

    plane = AsyncKernelHashPlane(chunk_rows=16, min_device_rows=16)
    kernel = BasicRecorder(node_count=4, client_count=2, reqs_per_client=6,
                           batch_size=2, hash_plane=plane)
    kernel_count = kernel.drain_clients(max_steps=100000)

    assert kernel_count == host_count
    assert chains(kernel) == chains(host)
    # Chunking must have kicked in: every launch is exactly chunk_rows or
    # a padded tail, and there were strictly fewer launches than digests.
    assert all(size <= 16 for size in plane.flush_sizes)
    assert sum(plane.flush_sizes) > len(plane.flush_sizes)


@pytest.mark.slow
def test_sixteen_node_anchor():
    """BASELINE ladder rung 2 at its stated scale parameters (16 nodes,
    f=5, 64 clients, BatchSize=200; VERDICT r2 item 7) — reduced request
    stream, exact-count determinism anchor."""
    r = BasicRecorder(node_count=16, client_count=64, reqs_per_client=25,
                      batch_size=200)
    count = r.drain_clients(max_steps=1_000_000)
    assert count == 2320  # regression anchor for our engine
    assert len(set(chains(r).values())) == 1
    assert all(r.committed_at(n) == 16 * 100 for n in range(16))


@pytest.mark.slow
def test_sixty_four_node_network():
    """64-node smoke at BASELINE rung-3 node count: full commitment with a
    single chain and an exact-count determinism anchor."""
    r = BasicRecorder(node_count=64, client_count=4, reqs_per_client=3,
                      batch_size=10)
    count = r.drain_clients(max_steps=2_000_000)
    assert count == 37894  # regression anchor for our engine
    assert len(set(chains(r).values())) == 1
    assert all(r.committed_at(n) == 12 for n in range(64))


@pytest.mark.slow
def test_one_hundred_twenty_eight_node_wan():
    """BASELINE rung-4 node count under WAN jitter: 128 nodes, 4 leader
    buckets (explicit network_state tames the O(buckets*n^2) heartbeat
    traffic), 30ms jitter on every delivery.  The epoch-change ack scheme
    is ~n^3 messages; the value-keyed digest memo and post-strong-cert
    skip (epoch_target.apply_epoch_change_ack) plus frame coalescing keep
    the run under a minute in the default suite (was HEAVY-gated at ~3
    min before round 4)."""
    from mirbft_tpu.testengine.manglers import is_step, rule

    nodes = 128
    clients = [nodes, nodes + 1]
    state = pb.NetworkState(
        config=pb.NetworkConfig(
            nodes=list(range(nodes)),
            f=(nodes - 1) // 3,
            number_of_buckets=4,
            checkpoint_interval=20,
            max_epoch_length=200,
        ),
        clients=[
            pb.NetworkClient(id=c, width=100, low_watermark=0)
            for c in clients
        ],
    )
    r = BasicRecorder(
        nodes, 2, 2, batch_size=10, network_state=state,
        manglers=[rule(is_step()).jitter(30)],
    )
    r.drain_clients(max_steps=8_000_000)
    assert len(set(chains(r).values())) == 1


@pytest.mark.slow
def test_two_hundred_fifty_six_node_wan():
    """BASELINE rung-5 node count under WAN delay variance (frame-level
    link_jitter — per-msg jitter manglers tear every coalesced frame
    into ~34.5M individual events and needed a ~23-minute HEAVY gate;
    frame jitter models the same packet-delay variance at ~0.6M events,
    in the default slow tier).  record=False keeps memory proportional
    to live state, not history."""
    nodes = 256
    clients = [nodes, nodes + 1]
    state = pb.NetworkState(
        config=pb.NetworkConfig(
            nodes=list(range(nodes)),
            f=(nodes - 1) // 3,
            number_of_buckets=4,
            checkpoint_interval=20,
            max_epoch_length=200,
        ),
        clients=[
            pb.NetworkClient(id=c, width=100, low_watermark=0)
            for c in clients
        ],
    )
    r = BasicRecorder(
        nodes, 2, 2, batch_size=10, network_state=state, record=False,
        params=RuntimeParameters(link_jitter=30),
    )
    r.drain_clients(max_steps=60_000_000)
    assert len(set(chains(r).values())) == 1


@pytest.mark.skipif(
    not os.environ.get("MIRBFT_TPU_HEAVY"),
    reason="the full rung-5 storm (256 nodes, 10k clients, forced epoch "
    "change + state transfer) takes tens of minutes on the host event "
    "loop (a 256-node epoch change is ~n^3 messages); set "
    "MIRBFT_TPU_HEAVY=1 to run",
)
@pytest.mark.slow
def test_rung5_storm_full_scale():
    """BASELINE rung-5 at its stated scale: 256 nodes, 10,000 clients,
    WAN jitter, a silenced leader forcing an epoch change, and a
    follower recovering via state transfer after checkpoint GC."""
    from mirbft_tpu.testengine.manglers import (
        from_source,
        is_step,
        rule,
        until_time,
    )

    nodes = 256
    client_ids = [nodes + i for i in range(10_000)]
    state = pb.NetworkState(
        config=pb.NetworkConfig(
            nodes=list(range(nodes)),
            f=(nodes - 1) // 3,
            number_of_buckets=4,
            checkpoint_interval=20,
            max_epoch_length=200,
        ),
        clients=[
            pb.NetworkClient(id=c, width=2, low_watermark=0)
            for c in client_ids
        ],
    )
    r = BasicRecorder(
        nodes, 10_000, 1, batch_size=200, network_state=state,
        record=False,
        params=RuntimeParameters(link_jitter=20),
        manglers=[rule(from_source(1), is_step(), until_time(4000)).drop()],
    )
    for _ in range(50_000):
        r.step()
    r.crash(200)
    for _ in range(100_000):
        r.step()
    r.schedule_restart(200, delay=0)
    r.drain_clients(max_steps=400_000_000)
    assert len(set(chains(r).values())) == 1
    total = 10_000
    assert all(r.committed_at(n) == total for n in range(nodes))
    epochs = {
        r.machines[n].epoch_tracker.current_epoch.number for n in range(nodes)
    }
    assert min(epochs) >= 1  # the silenced leader forced an epoch change


def test_epoch_change_storm():
    """Consecutive forced epoch changes (the rung-4/5 storm ingredient):
    silence a rotating leader in three back-to-back windows; the network
    must climb through multiple epochs and still converge on one chain."""
    from mirbft_tpu.testengine.manglers import (
        after_time,
        from_source,
        is_step,
        rule,
        until_time,
    )

    manglers = [
        rule(from_source(0), is_step(), until_time(8_000)).drop(),
        rule(
            from_source(1), is_step(), after_time(8_000), until_time(16_000)
        ).drop(),
        rule(
            from_source(2), is_step(), after_time(16_000), until_time(24_000)
        ).drop(),
    ]
    r = BasicRecorder(
        node_count=4, client_count=2, reqs_per_client=8, manglers=manglers
    )
    r.drain_clients(max_steps=600000)
    assert len(set(chains(r).values())) == 1

    epochs = {
        n: r.machines[n].epoch_tracker.current_epoch.number for n in range(4)
    }
    assert len(set(epochs.values())) == 1, epochs
    # Three silenced-leader windows must have forced repeated epoch
    # changes, not just one.
    assert min(epochs.values()) >= 2, epochs


def test_combined_storm_crash_and_transfer():
    """Rung-5 ingredients in one run: a silenced leader forces an epoch
    change while another node crashes, stays down past garbage
    collection, and restarts — it must come back via WAL replay and/or
    state transfer while the epoch machinery churns, and everyone must
    end on one chain."""
    from mirbft_tpu.testengine.manglers import (
        from_source,
        is_step,
        rule,
        until_time,
    )

    manglers = [
        # Leader 0 silent for the first 6 simulated seconds.
        rule(from_source(0), is_step(), until_time(6_000)).drop(),
    ]
    r = BasicRecorder(
        node_count=4, client_count=2, reqs_per_client=40, batch_size=2,
        manglers=manglers,
    )
    # Let the run get going, crash node 2, run far past GC (ci=20), then
    # restart it.
    for _ in range(3000):
        r.step()
    r.crash(2)
    for _ in range(120000):
        if r.fully_committed():
            break
        r.step()
    r.restart(2)
    # The restart enqueues node 2's boot; make sure it actually boots even
    # if the survivors already hold full commitment (drain_clients may
    # otherwise return before the queued Initialize applies).
    r.drain_until(
        lambda rr: rr.machines[2].epoch_tracker is not None
        and rr.machines[2].epoch_tracker.current_epoch is not None,
        max_steps=600000,
    )
    r.drain_clients(max_steps=600000)
    # The survivors went through at least one epoch change.
    epochs = {
        n: r.machines[n].epoch_tracker.current_epoch.number
        for n in range(4)
        if not r.node_states[n].crashed
    }
    assert min(epochs.values()) >= 1, epochs
    # Everyone converges; give node 2 a grace period to finish catch-up.
    for _ in range(200000):
        if len(set(chains(r).values())) == 1:
            break
        if not r.step():
            break
    assert len(set(chains(r).values())) == 1, chains(r)


def test_message_loss_mangler():
    """2% random message loss (reference scenario: mirbft_test.go:171-183):
    retransmission ticks must still drive the network to full commitment."""

    def drop_2pct(recorder, when, node, event):
        if isinstance(event.type, pb.EventStep):
            if recorder.rng.random() < 0.02:
                return None
        return when, node, event

    r = BasicRecorder(
        node_count=4,
        client_count=2,
        reqs_per_client=10,
        manglers=[drop_2pct],
    )
    r.drain_clients(max_steps=400000)
    assert len(set(chains(r).values())) == 1


def test_silenced_node_liveness():
    """Silence node 3 entirely: with f=1 the other three must still make
    progress (reference scenario: mirbft_test.go:140-156)."""

    def mute_node_3(recorder, when, node, event):
        if isinstance(event.type, pb.EventStep) and event.type.source == 3:
            return None
        return when, node, event

    r = BasicRecorder(
        node_count=4,
        client_count=2,
        reqs_per_client=5,
        manglers=[mute_node_3],
    )
    def epoch_of(n):
        tracker = r.machines[n].epoch_tracker
        if tracker is None or tracker.current_epoch is None:
            return 0  # pre-initialization: the bootstrap epoch
        return tracker.current_epoch.number

    initial_epochs = {n: epoch_of(n) for n in range(3)}
    # Node 3 never sends, so it cannot itself commit; check the other three.
    total = 2 * 5
    for _ in range(400000):
        done = all(
            sum(
                len(c.committed_by_node.get(n, ()))
                for c in r.clients.values()
            )
            >= total
            for n in range(3)
        )
        if done:
            break
        assert r.step()
    live = {n: r.node_states[n].app_chain.hex() for n in range(3)}
    assert len(set(live.values())) == 1
    # Progress past a silent leader is only possible through an epoch
    # change: assert it actually happened rather than inferring it from
    # liveness (reference: mirbft_test.go:140-156 relies on the same
    # mechanism; VERDICT r2 weak-item 6 asked for the explicit check).
    final_epochs = {n: epoch_of(n) for n in range(3)}
    assert all(
        final_epochs[n] > initial_epochs[n] for n in range(3)
    ), (initial_epochs, final_epochs)
    assert len(set(final_epochs.values())) == 1, final_epochs


def test_crash_and_restart_node():
    """Crash a follower mid-run and restart it: the network continues, and
    the restarted node rejoins from its WAL (reference scenario:
    mirbft_test.go:97-139)."""
    r = BasicRecorder(node_count=4, client_count=2, reqs_per_client=10)
    # Run a while, crash node 3, keep going, restart, finish.
    for _ in range(400):
        r.step()
    r.crash(3)
    for _ in range(400):
        r.step()
    r.restart(3)
    r.drain_clients(max_steps=400000)
    # The three always-up nodes must agree.
    stable = {n: r.node_states[n].app_chain.hex() for n in range(3)}
    assert len(set(stable.values())) == 1
    # Give the restarted node time to finish applying its catch-up suffix,
    # then require full agreement including node 3.
    for _ in range(5000):
        r.step()
        if len(set(chains(r).values())) == 1:
            break
    assert len(set(chains(r).values())) == 1, chains(r)
