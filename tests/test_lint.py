"""Static-analysis gate (reference CI discipline: .travis.yml:16-18 runs
staticcheck + the race detector; this repo's equivalent is tools/lint.py
over every source tree — the suite fails on any finding)."""

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))


def test_repo_is_lint_clean():
    import lint

    findings = lint.lint(
        [
            REPO / "mirbft_tpu",
            REPO / "tests",
            REPO / "tools",
            REPO / "bench.py",
            REPO / "__graft_entry__.py",
        ]
    )
    assert not findings, "\n".join(findings)


def test_linter_catches_the_defect_classes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import os\n"
        "try:\n"
        "    pass\n"
        "except:\n"
        "    pass\n"
        "assert (1, 'always true')\n"
        "x = 1\n"
        "y = x is 'nope'\n"
        "def f(a=[]):\n"
        "    return a\n"
        "z = f'no placeholders'\n"
    )
    import lint

    findings = lint.lint([bad])
    codes = {line.split()[1] for line in findings}
    assert codes == {"W1", "W2", "W3", "W4", "W5", "W6"}, findings
