"""Static-analysis gate (reference CI discipline: .travis.yml:16-18 runs
staticcheck + the race detector; this repo's equivalent is tools/lint.py
over every source tree — the suite fails on any finding)."""

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))


def test_repo_is_lint_clean():
    import lint

    findings = lint.lint(
        [
            REPO / "mirbft_tpu",
            REPO / "tests",
            REPO / "tools",
            REPO / "bench.py",
            REPO / "__graft_entry__.py",
        ]
    )
    assert not findings, "\n".join(findings)


def test_linter_catches_the_defect_classes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import os\n"
        "try:\n"
        "    pass\n"
        "except:\n"
        "    pass\n"
        "assert (1, 'always true')\n"
        "x = 1\n"
        "y = x is 'nope'\n"
        "def f(a=[]):\n"
        "    return a\n"
        "z = f'no placeholders'\n"
    )
    import lint

    findings = lint.lint([bad])
    codes = {line.split()[1] for line in findings}
    assert codes == {"W1", "W2", "W3", "W4", "W5", "W6"}, findings


def test_linter_forbids_wall_clock_in_monotonic_scope(tmp_path):
    """W7: time.time() (either spelling) is banned in span/metric code
    paths; it is scoped, so the same file outside the scope is clean."""
    import lint

    bad = tmp_path / "timed.py"
    bad.write_text(
        "import time\n"
        "start = time.time()\n"
        "elapsed = time.time() - start\n"
    )
    findings = lint.check_file(bad, monotonic_only=True)
    assert len(findings) == 2
    assert all("W7" in line for line in findings)
    # Outside the monotonic scope (auto-detect: tmp_path is not in any
    # MONOTONIC_ONLY_TREES fragment) the same file is clean.
    assert lint.check_file(bad) == []

    sneaky = tmp_path / "sneaky.py"
    sneaky.write_text("from time import time\nx = time()\n")
    findings = lint.check_file(sneaky, monotonic_only=True)
    assert any("W7" in line for line in findings)

    clean = tmp_path / "clean.py"
    clean.write_text(
        "import time\nstart = time.perf_counter()\nnow = time.monotonic()\n"
    )
    assert lint.check_file(clean, monotonic_only=True) == []


def test_monotonic_scope_covers_obsv_and_hot_paths():
    import lint

    assert lint._in_monotonic_scope(
        REPO / "mirbft_tpu" / "obsv" / "trace.py"
    )
    assert lint._in_monotonic_scope(
        REPO / "mirbft_tpu" / "runtime" / "storage.py"
    )
    assert lint._in_monotonic_scope(
        REPO / "mirbft_tpu" / "testengine" / "crypto_plane.py"
    )
    # eventlog run-metadata timestamps legitimately use the wall clock.
    assert not lint._in_monotonic_scope(
        REPO / "mirbft_tpu" / "testengine" / "eventlog.py"
    )


def test_every_cataloged_metric_is_documented():
    """docs/OBSERVABILITY.md is the human-facing metric catalog; a metric
    registered in code but absent from the docs cannot ship."""
    from mirbft_tpu.obsv.metrics import CATALOG

    doc = (REPO / "docs" / "OBSERVABILITY.md").read_text()
    missing = [name for name in CATALOG if name not in doc]
    assert not missing, f"undocumented metrics: {missing}"


def test_every_label_and_budget_is_documented():
    """Every declared label name (backtick-quoted) and every explicit
    cardinality budget must appear in docs/OBSERVABILITY.md, alongside
    the default budget — the documented bound is the contract the
    registry enforces."""
    from mirbft_tpu.obsv.metrics import (
        CARDINALITY,
        CATALOG,
        CATALOG_LABELS,
        DEFAULT_CARDINALITY,
    )

    assert set(CATALOG_LABELS) == set(CATALOG), (
        "CATALOG and CATALOG_LABELS must declare the same metric names"
    )
    doc = (REPO / "docs" / "OBSERVABILITY.md").read_text()
    labels = {label for names in CATALOG_LABELS.values() for label in names}
    missing = [label for label in sorted(labels) if f"`{label}`" not in doc]
    assert not missing, f"undocumented label names: {missing}"
    assert str(DEFAULT_CARDINALITY) in doc, "default cardinality budget undocumented"
    for name, budget in CARDINALITY.items():
        assert name in doc and str(budget) in doc, (
            f"cardinality budget for {name} ({budget}) undocumented"
        )


def test_linter_bans_http_server_outside_obsv(tmp_path):
    """W8: only obsv/ may touch http.server; everything else in
    mirbft_tpu must expose through the exporter."""
    import lint

    outside = tmp_path / "mirbft_tpu" / "runtime" / "sneaky.py"
    outside.parent.mkdir(parents=True)
    outside.write_text("import http.server as hs\nx = hs\n")
    findings = lint.check_file(outside)
    assert any("W8" in line for line in findings), findings

    fromstyle = tmp_path / "mirbft_tpu" / "core" / "sneaky2.py"
    fromstyle.parent.mkdir(parents=True)
    fromstyle.write_text(
        "from http.server import BaseHTTPRequestHandler\n"
        "x = BaseHTTPRequestHandler\n"
    )
    assert any("W8" in line for line in lint.check_file(fromstyle))

    inside = tmp_path / "mirbft_tpu" / "obsv" / "fine.py"
    inside.parent.mkdir(parents=True)
    inside.write_text("import http.server as hs\nx = hs\n")
    assert not any("W8" in line for line in lint.check_file(inside))

    # The real exporter is the one sanctioned http.server user.
    assert not any(
        "W8" in line
        for line in lint.check_file(
            REPO / "mirbft_tpu" / "obsv" / "exporter.py"
        )
    )


def test_linter_bans_raw_sockets_outside_transport_and_live(tmp_path):
    """W9: all wire I/O goes through runtime/transport.py or the live
    chaos driver's partition proxies; a raw socket anywhere else in
    mirbft_tpu bypasses framing, reconnect, counters, and fault seams."""
    import lint

    outside = tmp_path / "mirbft_tpu" / "core" / "sneaky.py"
    outside.parent.mkdir(parents=True)
    outside.write_text("import socket\nx = socket\n")
    findings = lint.check_file(outside)
    assert any("W9" in line for line in findings), findings

    fromstyle = tmp_path / "mirbft_tpu" / "runtime" / "sneaky2.py"
    fromstyle.parent.mkdir(parents=True)
    fromstyle.write_text("from socket import create_server\nx = create_server\n")
    assert any("W9" in line for line in lint.check_file(fromstyle))

    # The two sanctioned socket users, checked against the real files.
    assert not any(
        "W9" in line
        for line in lint.check_file(
            REPO / "mirbft_tpu" / "runtime" / "transport.py"
        )
    )
    assert not any(
        "W9" in line
        for line in lint.check_file(REPO / "mirbft_tpu" / "chaos" / "live.py")
    )

    # ``socketserver`` or tests are out of scope entirely.
    tests_ok = tmp_path / "tests" / "test_whatever.py"
    tests_ok.parent.mkdir(parents=True)
    tests_ok.write_text("import socket\nx = socket\n")
    assert not any("W9" in line for line in lint.check_file(tests_ok))


def test_linter_confines_fsync_to_storage(tmp_path):
    """W10a: os.fsync belongs to the stores' group-commit machinery (and
    the live chaos driver's durable app log); a stray fsync anywhere
    else silently reintroduces the per-batch sync cost the pipelined
    commit path amortizes away."""
    import lint

    outside = tmp_path / "mirbft_tpu" / "runtime" / "sneaky.py"
    outside.parent.mkdir(parents=True)
    outside.write_text("import os\n\ndef f(fd):\n    os.fsync(fd)\n")
    findings = lint.check_file(outside)
    assert any("W10" in line for line in findings), findings

    fromstyle = tmp_path / "mirbft_tpu" / "core" / "sneaky2.py"
    fromstyle.parent.mkdir(parents=True)
    fromstyle.write_text("from os import fsync\nx = fsync\n")
    assert any("W10" in line for line in lint.check_file(fromstyle))

    # The sanctioned fsync users, checked against the real files.
    assert not any(
        "W10" in line
        for line in lint.check_file(
            REPO / "mirbft_tpu" / "runtime" / "storage.py"
        )
    )
    assert not any(
        "W10" in line
        for line in lint.check_file(REPO / "mirbft_tpu" / "chaos" / "live.py")
    )

    # Tests and tools are out of scope entirely.
    tests_ok = tmp_path / "tests" / "test_whatever.py"
    tests_ok.parent.mkdir(parents=True)
    tests_ok.write_text("import os\n\ndef f(fd):\n    os.fsync(fd)\n")
    assert not any("W10" in line for line in lint.check_file(tests_ok))


def test_linter_bans_raw_threads_in_processor_outside_spawn_stage(tmp_path):
    """W10b: runtime/processor.py creates stage threads only through
    _spawn_stage, so naming, daemonization, and the leak gate stay
    uniform."""
    import lint

    rogue = tmp_path / "mirbft_tpu" / "runtime" / "processor.py"
    rogue.parent.mkdir(parents=True)
    rogue.write_text(
        "import threading\n"
        "\n"
        "def _spawn_stage(name, fn):\n"
        "    return threading.Thread(target=fn, name=name, daemon=True)\n"
        "\n"
        "def rogue(fn):\n"
        "    return threading.Thread(target=fn)\n"
    )
    findings = lint.check_file(rogue)
    assert any("W10" in line and ":7:" in line for line in findings), findings
    # The helper itself is the sanctioned creation point.
    assert not any(":4:" in line for line in findings), findings

    fromstyle = tmp_path / "mirbft_tpu" / "runtime" / "sub" / "processor.py"
    fromstyle.parent.mkdir(parents=True)
    fromstyle.write_text(
        "from threading import Thread\n"
        "\n"
        "def rogue(fn):\n"
        "    return Thread(target=fn)\n"
    )
    assert not any(
        "Thread" in line for line in lint.check_file(fromstyle)
    ), "sub/processor.py is not the processor module"

    # Thread creation in *other* runtime modules is out of W10's scope
    # (the transport legitimately owns its reader/writer threads).
    other = tmp_path / "mirbft_tpu" / "runtime" / "transport2.py"
    other.write_text(
        "import threading\n\ndef f(fn):\n    return threading.Thread(target=fn)\n"
    )
    assert not any(
        "W10" in line and "Thread" in line
        for line in lint.check_file(other)
    )

    # The real processor module stays clean.
    assert not any(
        "W10" in line
        for line in lint.check_file(
            REPO / "mirbft_tpu" / "runtime" / "processor.py"
        )
    )


def test_linter_confines_process_management_to_cluster(tmp_path):
    """W11: subprocess/multiprocessing imports belong to the cluster
    supervisor; a stray Popen elsewhere forks workers that escape the
    supervisor's lifecycle, log capture, and teardown sweep."""
    import lint

    outside = tmp_path / "mirbft_tpu" / "runtime" / "sneaky.py"
    outside.parent.mkdir(parents=True)
    outside.write_text("import subprocess\nx = subprocess\n")
    findings = lint.check_file(outside)
    assert any("W11" in line for line in findings), findings

    fromstyle = tmp_path / "mirbft_tpu" / "chaos" / "sneaky2.py"
    fromstyle.parent.mkdir(parents=True)
    fromstyle.write_text("from multiprocessing import Process\nx = Process\n")
    assert any("W11" in line for line in lint.check_file(fromstyle))

    submodule = tmp_path / "mirbft_tpu" / "core" / "sneaky3.py"
    submodule.parent.mkdir(parents=True)
    submodule.write_text(
        "from multiprocessing.connection import Client\nx = Client\n"
    )
    assert any("W11" in line for line in lint.check_file(submodule))

    inside = tmp_path / "mirbft_tpu" / "cluster" / "fine.py"
    inside.parent.mkdir(parents=True)
    inside.write_text("import subprocess\nx = subprocess\n")
    assert not any("W11" in line for line in lint.check_file(inside))

    # The real supervisor is the sanctioned Popen user.
    assert not any(
        "W11" in line
        for line in lint.check_file(
            REPO / "mirbft_tpu" / "cluster" / "supervisor.py"
        )
    )

    # Tests and tools are out of scope entirely.
    tests_ok = tmp_path / "tests" / "test_whatever.py"
    tests_ok.parent.mkdir(parents=True)
    tests_ok.write_text("import subprocess\nx = subprocess\n")
    assert not any("W11" in line for line in lint.check_file(tests_ok))
