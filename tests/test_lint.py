"""Static-analysis gate (reference CI discipline: .travis.yml:16-18 runs
staticcheck + the race detector; this repo's equivalent is tools/lint.py
over every source tree — the suite fails on any finding)."""

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))


def test_repo_is_lint_clean():
    import lint

    findings = lint.lint(
        [
            REPO / "mirbft_tpu",
            REPO / "tests",
            REPO / "tools",
            REPO / "bench.py",
            REPO / "__graft_entry__.py",
        ]
    )
    assert not findings, "\n".join(findings)


def test_linter_catches_the_defect_classes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import os\n"
        "try:\n"
        "    pass\n"
        "except:\n"
        "    pass\n"
        "assert (1, 'always true')\n"
        "x = 1\n"
        "y = x is 'nope'\n"
        "def f(a=[]):\n"
        "    return a\n"
        "z = f'no placeholders'\n"
    )
    import lint

    findings = lint.lint([bad])
    codes = {line.split()[1] for line in findings}
    assert codes == {"W1", "W2", "W3", "W4", "W5", "W6"}, findings


def test_linter_forbids_wall_clock_in_monotonic_scope(tmp_path):
    """W7: time.time() (either spelling) is banned in span/metric code
    paths; it is scoped, so the same file outside the scope is clean."""
    import lint

    bad = tmp_path / "timed.py"
    bad.write_text(
        "import time\n"
        "start = time.time()\n"
        "elapsed = time.time() - start\n"
    )
    findings = lint.check_file(bad, monotonic_only=True)
    assert len(findings) == 2
    assert all("W7" in line for line in findings)
    # Outside the monotonic scope (auto-detect: tmp_path is not in any
    # MONOTONIC_ONLY_TREES fragment) the same file is clean.
    assert lint.check_file(bad) == []

    sneaky = tmp_path / "sneaky.py"
    sneaky.write_text("from time import time\nx = time()\n")
    findings = lint.check_file(sneaky, monotonic_only=True)
    assert any("W7" in line for line in findings)

    clean = tmp_path / "clean.py"
    clean.write_text(
        "import time\nstart = time.perf_counter()\nnow = time.monotonic()\n"
    )
    assert lint.check_file(clean, monotonic_only=True) == []


def test_monotonic_scope_covers_obsv_and_hot_paths():
    import lint

    assert lint._in_monotonic_scope(
        REPO / "mirbft_tpu" / "obsv" / "trace.py"
    )
    assert lint._in_monotonic_scope(
        REPO / "mirbft_tpu" / "runtime" / "storage.py"
    )
    assert lint._in_monotonic_scope(
        REPO / "mirbft_tpu" / "testengine" / "crypto_plane.py"
    )
    # eventlog run-metadata timestamps legitimately use the wall clock.
    assert not lint._in_monotonic_scope(
        REPO / "mirbft_tpu" / "testengine" / "eventlog.py"
    )


def test_every_cataloged_metric_is_documented():
    """docs/OBSERVABILITY.md is the human-facing metric catalog; a metric
    registered in code but absent from the docs cannot ship."""
    from mirbft_tpu.obsv.metrics import CATALOG

    doc = (REPO / "docs" / "OBSERVABILITY.md").read_text()
    missing = [name for name in CATALOG if name not in doc]
    assert not missing, f"undocumented metrics: {missing}"


def test_every_label_and_budget_is_documented():
    """Every declared label name (backtick-quoted) and every explicit
    cardinality budget must appear in docs/OBSERVABILITY.md, alongside
    the default budget — the documented bound is the contract the
    registry enforces."""
    from mirbft_tpu.obsv.metrics import (
        CARDINALITY,
        CATALOG,
        CATALOG_LABELS,
        DEFAULT_CARDINALITY,
    )

    assert set(CATALOG_LABELS) == set(CATALOG), (
        "CATALOG and CATALOG_LABELS must declare the same metric names"
    )
    doc = (REPO / "docs" / "OBSERVABILITY.md").read_text()
    labels = {label for names in CATALOG_LABELS.values() for label in names}
    missing = [label for label in sorted(labels) if f"`{label}`" not in doc]
    assert not missing, f"undocumented label names: {missing}"
    assert str(DEFAULT_CARDINALITY) in doc, "default cardinality budget undocumented"
    for name, budget in CARDINALITY.items():
        assert name in doc and str(budget) in doc, (
            f"cardinality budget for {name} ({budget}) undocumented"
        )


def test_linter_bans_http_server_outside_obsv(tmp_path):
    """W8: only obsv/ may touch http.server; everything else in
    mirbft_tpu must expose through the exporter."""
    import lint

    outside = tmp_path / "mirbft_tpu" / "runtime" / "sneaky.py"
    outside.parent.mkdir(parents=True)
    outside.write_text("import http.server as hs\nx = hs\n")
    findings = lint.check_file(outside)
    assert any("W8" in line for line in findings), findings

    fromstyle = tmp_path / "mirbft_tpu" / "core" / "sneaky2.py"
    fromstyle.parent.mkdir(parents=True)
    fromstyle.write_text(
        "from http.server import BaseHTTPRequestHandler\n"
        "x = BaseHTTPRequestHandler\n"
    )
    assert any("W8" in line for line in lint.check_file(fromstyle))

    inside = tmp_path / "mirbft_tpu" / "obsv" / "fine.py"
    inside.parent.mkdir(parents=True)
    inside.write_text("import http.server as hs\nx = hs\n")
    assert not any("W8" in line for line in lint.check_file(inside))

    # The real exporter is the one sanctioned http.server user.
    assert not any(
        "W8" in line
        for line in lint.check_file(
            REPO / "mirbft_tpu" / "obsv" / "exporter.py"
        )
    )


def test_linter_confines_core_jax_to_device_tracker(tmp_path):
    """W16: mirbft_tpu/core/ is pure deterministic Python; jax/jnp
    imports are confined to core/device_tracker.py, the single
    sanctioned accelerator boundary of the protocol."""
    import lint

    outside = tmp_path / "mirbft_tpu" / "core" / "sneaky.py"
    outside.parent.mkdir(parents=True)
    outside.write_text("import jax\nx = jax\n")
    findings = lint.check_file(outside)
    assert any("W16" in line for line in findings), findings

    fromstyle = tmp_path / "mirbft_tpu" / "core" / "sneaky2.py"
    fromstyle.write_text("import jax.numpy as jnp\nx = jnp\n")
    assert any("W16" in line for line in lint.check_file(fromstyle))

    fromimport = tmp_path / "mirbft_tpu" / "core" / "sneaky3.py"
    fromimport.write_text("from jax.sharding import Mesh\nx = Mesh\n")
    assert any("W16" in line for line in lint.check_file(fromimport))

    # The sanctioned boundary file is exempt — even a tmp copy.
    allowed = tmp_path / "mirbft_tpu" / "core" / "device_tracker.py"
    allowed.write_text("import jax\nx = jax\n")
    assert not any("W16" in line for line in lint.check_file(allowed))

    # The ban is scoped to core/: ops/ kernels import jax freely.
    elsewhere = tmp_path / "mirbft_tpu" / "ops" / "kernel.py"
    elsewhere.parent.mkdir(parents=True)
    elsewhere.write_text("import jax\nx = jax\n")
    assert not any("W16" in line for line in lint.check_file(elsewhere))

    # The real boundary file stays clean against the real rule, and the
    # purity auditor knows it as a boundary module (D101 stops there
    # rather than descending into jax internals).
    assert not any(
        "W16" in line
        for line in lint.check_file(
            REPO / "mirbft_tpu" / "core" / "device_tracker.py"
        )
    )
    from analysis import rules_d

    assert "mirbft_tpu.core.device_tracker" in rules_d.BOUNDARY_MODULES


def test_linter_bans_raw_sockets_outside_transport_and_live(tmp_path):
    """W9: all wire I/O goes through runtime/transport.py or the live
    chaos driver's partition proxies; a raw socket anywhere else in
    mirbft_tpu bypasses framing, reconnect, counters, and fault seams."""
    import lint

    outside = tmp_path / "mirbft_tpu" / "core" / "sneaky.py"
    outside.parent.mkdir(parents=True)
    outside.write_text("import socket\nx = socket\n")
    findings = lint.check_file(outside)
    assert any("W9" in line for line in findings), findings

    fromstyle = tmp_path / "mirbft_tpu" / "runtime" / "sneaky2.py"
    fromstyle.parent.mkdir(parents=True)
    fromstyle.write_text("from socket import create_server\nx = create_server\n")
    assert any("W9" in line for line in lint.check_file(fromstyle))

    # The two sanctioned socket users, checked against the real files.
    assert not any(
        "W9" in line
        for line in lint.check_file(
            REPO / "mirbft_tpu" / "runtime" / "transport.py"
        )
    )
    assert not any(
        "W9" in line
        for line in lint.check_file(REPO / "mirbft_tpu" / "chaos" / "live.py")
    )

    # ``socketserver`` or tests are out of scope entirely.
    tests_ok = tmp_path / "tests" / "test_whatever.py"
    tests_ok.parent.mkdir(parents=True)
    tests_ok.write_text("import socket\nx = socket\n")
    assert not any("W9" in line for line in lint.check_file(tests_ok))


def test_linter_confines_fsync_to_storage(tmp_path):
    """W10a: os.fsync belongs to the stores' group-commit machinery (and
    the live chaos driver's durable app log); a stray fsync anywhere
    else silently reintroduces the per-batch sync cost the pipelined
    commit path amortizes away."""
    import lint

    outside = tmp_path / "mirbft_tpu" / "runtime" / "sneaky.py"
    outside.parent.mkdir(parents=True)
    outside.write_text("import os\n\ndef f(fd):\n    os.fsync(fd)\n")
    findings = lint.check_file(outside)
    assert any("W10" in line for line in findings), findings

    fromstyle = tmp_path / "mirbft_tpu" / "core" / "sneaky2.py"
    fromstyle.parent.mkdir(parents=True)
    fromstyle.write_text("from os import fsync\nx = fsync\n")
    assert any("W10" in line for line in lint.check_file(fromstyle))

    # The sanctioned fsync users, checked against the real files.
    assert not any(
        "W10" in line
        for line in lint.check_file(
            REPO / "mirbft_tpu" / "runtime" / "storage.py"
        )
    )
    assert not any(
        "W10" in line
        for line in lint.check_file(REPO / "mirbft_tpu" / "chaos" / "live.py")
    )

    # Tests and tools are out of scope entirely.
    tests_ok = tmp_path / "tests" / "test_whatever.py"
    tests_ok.parent.mkdir(parents=True)
    tests_ok.write_text("import os\n\ndef f(fd):\n    os.fsync(fd)\n")
    assert not any("W10" in line for line in lint.check_file(tests_ok))


def test_linter_bans_raw_threads_in_processor_outside_spawn_stage(tmp_path):
    """W10b: runtime/processor.py creates stage threads only through
    _spawn_stage, so naming, daemonization, and the leak gate stay
    uniform."""
    import lint

    rogue = tmp_path / "mirbft_tpu" / "runtime" / "processor.py"
    rogue.parent.mkdir(parents=True)
    rogue.write_text(
        "import threading\n"
        "\n"
        "def _spawn_stage(name, fn):\n"
        "    return threading.Thread(target=fn, name=name, daemon=True)\n"
        "\n"
        "def rogue(fn):\n"
        "    return threading.Thread(target=fn)\n"
    )
    findings = lint.check_file(rogue)
    assert any("W10" in line and ":7:" in line for line in findings), findings
    # The helper itself is the sanctioned creation point.
    assert not any(":4:" in line for line in findings), findings

    fromstyle = tmp_path / "mirbft_tpu" / "runtime" / "sub" / "processor.py"
    fromstyle.parent.mkdir(parents=True)
    fromstyle.write_text(
        "from threading import Thread\n"
        "\n"
        "def rogue(fn):\n"
        "    return Thread(target=fn)\n"
    )
    assert not any(
        "Thread" in line for line in lint.check_file(fromstyle)
    ), "sub/processor.py is not the processor module"

    # Thread creation in *other* runtime modules is out of W10's scope
    # (the transport legitimately owns its reader/writer threads).
    other = tmp_path / "mirbft_tpu" / "runtime" / "transport2.py"
    other.write_text(
        "import threading\n\ndef f(fn):\n    return threading.Thread(target=fn)\n"
    )
    assert not any(
        "W10" in line and "Thread" in line
        for line in lint.check_file(other)
    )

    # The real processor module stays clean.
    assert not any(
        "W10" in line
        for line in lint.check_file(
            REPO / "mirbft_tpu" / "runtime" / "processor.py"
        )
    )


def test_linter_confines_process_management_to_cluster(tmp_path):
    """W11: subprocess/multiprocessing imports belong to the cluster
    supervisor; a stray Popen elsewhere forks workers that escape the
    supervisor's lifecycle, log capture, and teardown sweep."""
    import lint

    outside = tmp_path / "mirbft_tpu" / "runtime" / "sneaky.py"
    outside.parent.mkdir(parents=True)
    outside.write_text("import subprocess\nx = subprocess\n")
    findings = lint.check_file(outside)
    assert any("W11" in line for line in findings), findings

    fromstyle = tmp_path / "mirbft_tpu" / "chaos" / "sneaky2.py"
    fromstyle.parent.mkdir(parents=True)
    fromstyle.write_text("from multiprocessing import Process\nx = Process\n")
    assert any("W11" in line for line in lint.check_file(fromstyle))

    submodule = tmp_path / "mirbft_tpu" / "core" / "sneaky3.py"
    submodule.parent.mkdir(parents=True)
    submodule.write_text(
        "from multiprocessing.connection import Client\nx = Client\n"
    )
    assert any("W11" in line for line in lint.check_file(submodule))

    inside = tmp_path / "mirbft_tpu" / "cluster" / "fine.py"
    inside.parent.mkdir(parents=True)
    inside.write_text("import subprocess\nx = subprocess\n")
    assert not any("W11" in line for line in lint.check_file(inside))

    # The real supervisor is the sanctioned Popen user.
    assert not any(
        "W11" in line
        for line in lint.check_file(
            REPO / "mirbft_tpu" / "cluster" / "supervisor.py"
        )
    )

    # Tests and tools are out of scope entirely.
    tests_ok = tmp_path / "tests" / "test_whatever.py"
    tests_ok.parent.mkdir(parents=True)
    tests_ok.write_text("import subprocess\nx = subprocess\n")
    assert not any("W11" in line for line in lint.check_file(tests_ok))


def test_linter_confines_resource_introspection_to_obsv(tmp_path):
    """W14: resource/psutil process-introspection imports belong to the
    obsv ResourceSampler; ad-hoc sampling elsewhere fragments the
    cadence, the mirbft_resource_* gauge names, and the leak fits."""
    import lint

    outside = tmp_path / "mirbft_tpu" / "runtime" / "sneaky.py"
    outside.parent.mkdir(parents=True)
    outside.write_text("import resource\nx = resource\n")
    findings = lint.check_file(outside)
    assert any("W14" in line for line in findings), findings

    fromstyle = tmp_path / "mirbft_tpu" / "chaos" / "sneaky2.py"
    fromstyle.parent.mkdir(parents=True)
    fromstyle.write_text("from psutil import Process\nx = Process\n")
    assert any("W14" in line for line in lint.check_file(fromstyle))

    inside = tmp_path / "mirbft_tpu" / "obsv" / "resources.py"
    inside.parent.mkdir(parents=True)
    inside.write_text("import resource\nx = resource\n")
    assert not any("W14" in line for line in lint.check_file(inside))

    # The real sampler is the sanctioned importer.
    assert not any(
        "W14" in line
        for line in lint.check_file(
            REPO / "mirbft_tpu" / "obsv" / "resources.py"
        )
    )

    # Tests, tools, and bench are out of scope entirely.
    tests_ok = tmp_path / "tests" / "test_whatever.py"
    tests_ok.parent.mkdir(parents=True)
    tests_ok.write_text("import resource\nx = resource\n")
    assert not any("W14" in line for line in lint.check_file(tests_ok))


def test_linter_confines_device_sync_to_kernel_layer(tmp_path):
    """W15: jax.profiler and block_until_ready belong to obsv/device.py
    and ops/; a stray device sync in protocol code serializes the
    pipeline and scattered profiler sessions fight over the trace
    backend."""
    import lint

    outside = tmp_path / "mirbft_tpu" / "core" / "sneaky.py"
    outside.parent.mkdir(parents=True)
    outside.write_text("def f(x):\n    return x.block_until_ready()\n")
    findings = lint.check_file(outside)
    assert any("W15" in line for line in findings), findings

    profiled = tmp_path / "mirbft_tpu" / "runtime" / "sneaky2.py"
    profiled.parent.mkdir(parents=True)
    profiled.write_text("import jax.profiler\nx = jax.profiler\n")
    assert any("W15" in line for line in lint.check_file(profiled))

    fromstyle = tmp_path / "mirbft_tpu" / "chaos" / "sneaky3.py"
    fromstyle.parent.mkdir(parents=True)
    fromstyle.write_text(
        "from jax.profiler import start_trace\nx = start_trace\n"
    )
    assert any("W15" in line for line in lint.check_file(fromstyle))

    # The kernel layer and the instrumentation wrapper are sanctioned.
    ops_ok = tmp_path / "mirbft_tpu" / "ops" / "kernel.py"
    ops_ok.parent.mkdir(parents=True)
    ops_ok.write_text("def f(x):\n    return x.block_until_ready()\n")
    assert not any("W15" in line for line in lint.check_file(ops_ok))

    device_ok = tmp_path / "mirbft_tpu" / "obsv" / "device.py"
    device_ok.parent.mkdir(parents=True)
    device_ok.write_text("def f(x):\n    return x.block_until_ready()\n")
    assert not any("W15" in line for line in lint.check_file(device_ok))

    # The real wrapper is the sanctioned caller.
    assert not any(
        "W15" in line
        for line in lint.check_file(
            REPO / "mirbft_tpu" / "obsv" / "device.py"
        )
    )

    # Tests, tools, and bench are out of scope entirely.
    tests_ok = tmp_path / "tests" / "test_whatever.py"
    tests_ok.parent.mkdir(parents=True)
    tests_ok.write_text("def f(x):\n    return x.block_until_ready()\n")
    assert not any("W15" in line for line in lint.check_file(tests_ok))


def test_linter_confines_adversary_tooling_to_harness(tmp_path):
    """W13: core/ and runtime/ must not import mirbft_tpu.testengine or
    mirbft_tpu.chaos in any spelling — payload mutation and frame
    rewriting belong to the harness, which wraps the protocol, never the
    reverse."""
    import lint

    spellings = (
        "from mirbft_tpu.testengine.manglers import rule\nx = rule\n",
        "import mirbft_tpu.chaos.live\nx = mirbft_tpu\n",
        "from mirbft_tpu import chaos\nx = chaos\n",
        "from ..testengine import manglers\nx = manglers\n",
        "from ..chaos.live import AdversaryProxy\nx = AdversaryProxy\n",
        "from .. import testengine\nx = testengine\n",
    )
    for tree in ("core", "runtime"):
        for index, source in enumerate(spellings):
            bad = tmp_path / "mirbft_tpu" / tree / f"sneaky{index}.py"
            bad.parent.mkdir(parents=True, exist_ok=True)
            bad.write_text(source)
            findings = lint.check_file(bad)
            assert any("W13" in line for line in findings), (
                tree,
                source,
                findings,
            )

    # The harness trees import each other freely.
    inside = tmp_path / "mirbft_tpu" / "chaos" / "fine.py"
    inside.parent.mkdir(parents=True)
    inside.write_text("from ..testengine.manglers import rule\nx = rule\n")
    assert not any("W13" in line for line in lint.check_file(inside))

    # Protocol-internal relative imports stay clean in scope.
    honest = tmp_path / "mirbft_tpu" / "runtime" / "honest.py"
    honest.write_text("from ..core import serializer\nx = serializer\n")
    assert not any("W13" in line for line in lint.check_file(honest))

    # The real protocol trees are clean today; keep them that way.
    for tree in ("core", "runtime"):
        for path in sorted((REPO / "mirbft_tpu" / tree).glob("*.py")):
            assert not any(
                "W13" in line for line in lint.check_file(path)
            ), path

    # Tests and tools are out of scope entirely.
    tests_ok = tmp_path / "tests" / "test_whatever.py"
    tests_ok.parent.mkdir(parents=True)
    tests_ok.write_text("from mirbft_tpu.chaos import run_campaign\nx = run_campaign\n")
    assert not any("W13" in line for line in lint.check_file(tests_ok))


def test_linter_confines_snapshot_io_to_storage_and_transfer(tmp_path):
    """W17: staged-snapshot file I/O (write/read/remove_snapshot_file)
    is confined to runtime/storage.py (the atomic primitives) and
    runtime/transfer.py (their single caller, the TransferEngine's
    crash-resume staging); a third call site would fork the staged-blob
    crash contract."""
    import lint

    outside = tmp_path / "mirbft_tpu" / "chaos" / "sneaky.py"
    outside.parent.mkdir(parents=True)
    outside.write_text(
        "from ..runtime.storage import write_snapshot_file\n"
        "write_snapshot_file('p', b'x')\n"
    )
    findings = lint.check_file(outside)
    assert any("W17" in line for line in findings), findings

    attr = tmp_path / "mirbft_tpu" / "cluster" / "sneaky2.py"
    attr.parent.mkdir(parents=True)
    attr.write_text(
        "from ..runtime import storage\n"
        "blob = storage.read_snapshot_file('p')\n"
        "x = blob\n"
    )
    assert any("W17" in line for line in lint.check_file(attr))

    cleanup = tmp_path / "mirbft_tpu" / "runtime" / "sneaky3.py"
    cleanup.parent.mkdir(parents=True)
    cleanup.write_text(
        "from .storage import remove_snapshot_file\n"
        "remove_snapshot_file('p')\n"
    )
    assert any("W17" in line for line in lint.check_file(cleanup))

    # The two sanctioned files, checked against the real sources.
    assert not any(
        "W17" in line
        for line in lint.check_file(
            REPO / "mirbft_tpu" / "runtime" / "storage.py"
        )
    )
    assert not any(
        "W17" in line
        for line in lint.check_file(
            REPO / "mirbft_tpu" / "runtime" / "transfer.py"
        )
    )

    # Tests and tools are out of scope entirely.
    tests_ok = tmp_path / "tests" / "test_whatever.py"
    tests_ok.parent.mkdir(parents=True)
    tests_ok.write_text(
        "from mirbft_tpu.runtime.storage import read_snapshot_file\n"
        "x = read_snapshot_file('p')\n"
    )
    assert not any("W17" in line for line in lint.check_file(tests_ok))


def test_linter_confines_app_state_io_to_storage_and_app(tmp_path):
    """W18: app-state file I/O (write/read/remove_app_state) is confined
    to runtime/storage.py (the atomic applied-index + snapshot blob
    primitives) and mirbft_tpu/app/ (their single consumer, the
    CommitStream's persistence); a third call site could persist app
    state without the applied-index coupling and break exactly-once
    apply across restart."""
    import lint

    outside = tmp_path / "mirbft_tpu" / "cluster" / "sneaky.py"
    outside.parent.mkdir(parents=True)
    outside.write_text(
        "from ..runtime.storage import write_app_state\n"
        "write_app_state('p', b'x')\n"
    )
    findings = lint.check_file(outside)
    assert any("W18" in line for line in findings), findings

    attr = tmp_path / "mirbft_tpu" / "runtime" / "sneaky2.py"
    attr.parent.mkdir(parents=True)
    attr.write_text(
        "from . import storage\n"
        "blob = storage.read_app_state('p')\n"
        "x = blob\n"
    )
    assert any("W18" in line for line in lint.check_file(attr))

    cleanup = tmp_path / "mirbft_tpu" / "chaos" / "sneaky3.py"
    cleanup.parent.mkdir(parents=True)
    cleanup.write_text(
        "from ..runtime.storage import remove_app_state\n"
        "remove_app_state('p')\n"
    )
    assert any("W18" in line for line in lint.check_file(cleanup))

    # The sanctioned owners, checked against the real sources.
    assert not any(
        "W18" in line
        for line in lint.check_file(
            REPO / "mirbft_tpu" / "runtime" / "storage.py"
        )
    )
    assert not any(
        "W18" in line
        for line in lint.check_file(REPO / "mirbft_tpu" / "app" / "stream.py")
    )

    # Tests and tools are out of scope entirely.
    tests_ok = tmp_path / "tests" / "test_whatever.py"
    tests_ok.parent.mkdir(parents=True)
    tests_ok.write_text(
        "from mirbft_tpu.runtime.storage import read_app_state\n"
        "x = read_app_state('p')\n"
    )
    assert not any("W18" in line for line in lint.check_file(tests_ok))


# ---------------------------------------------------------------------------
# rule engine (tools/analysis/engine.py)
# ---------------------------------------------------------------------------


def test_cli_json_runs_the_full_suite_repo_wide():
    """Acceptance gate: ``python tools/lint.py --json`` runs the W+D+C
    suite over every source tree and exits 0 with zero findings."""
    import json
    import subprocess

    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint.py"), "--json"],
        capture_output=True,
        text=True,
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["version"] == 1
    assert doc["total"] == 0, doc["findings"]
    assert doc["findings"] == []


def test_rule_ids_unique_and_documented():
    """Every registered rule id is unique (the registry enforces it at
    import), carries a title and doc, and appears in docs/ANALYSIS.md."""
    from analysis.engine import all_rules

    rules = all_rules()
    ids = [rule.id for rule in rules]
    assert len(ids) == len(set(ids)), ids
    doc = (REPO / "docs" / "ANALYSIS.md").read_text()
    for rule in rules:
        assert rule.title and rule.doc, f"{rule.id} lacks title/doc"
        assert rule.id in doc, f"{rule.id} undocumented in docs/ANALYSIS.md"


def test_suppression_honored_only_with_reason(tmp_path):
    """A reasoned same-line suppression drops the finding; a reason-less
    one keeps it AND emits S1 ('a suppression without a reason is a
    finding').  S1 itself cannot be suppressed away."""
    import analysis.engine as engine

    reasoned = tmp_path / "reasoned.py"
    reasoned.write_text(
        "x = 1\n"
        "y = x is 'nope'  # lint: allow W4 exercising the identity check\n"
    )
    assert engine.run([reasoned]).findings == []

    bare = tmp_path / "bare.py"
    bare.write_text("x = 1\ny = x is 'nope'  # lint: allow W4\n")
    codes = {f.rule for f in engine.run([bare]).findings}
    assert codes == {"W4", "S1"}, codes

    meta = tmp_path / "meta.py"
    meta.write_text("pass  # lint: allow S1\n")
    codes = {f.rule for f in engine.run([meta]).findings}
    assert codes == {"S1"}, codes


def test_baseline_masks_old_findings_not_new_ones(tmp_path):
    """The committed baseline lets a new rule land strict: pre-existing
    findings are masked (by line-number-free key, so unrelated edits
    don't churn it) while anything new stays red."""
    import analysis.engine as engine

    f = tmp_path / "old.py"
    f.write_text("def f(a=[]):\n    return a\n")
    first = engine.run([f], repo_root=tmp_path)
    assert {x.rule for x in first.findings} == {"W5"}
    doc = engine.dump_baseline(first.findings, tmp_path)
    baseline = {e["key"]: e["count"] for e in doc["findings"]}

    masked = engine.run([f], repo_root=tmp_path, baseline=baseline)
    assert masked.findings == [] and masked.baselined == 1

    # A new instance of the same defect class is NOT covered.
    f.write_text("def f(a=[]):\n    return a\n\n\ndef g(b=[]):\n    return b\n")
    again = engine.run([f], repo_root=tmp_path, baseline=baseline)
    assert again.baselined == 1
    assert len(again.findings) == 1 and again.findings[0].rule == "W5"
    assert again.findings[0].line == 5


def test_json_schema_round_trips(tmp_path):
    import json

    import analysis.engine as engine

    f = tmp_path / "bad.py"
    f.write_text("import os\nx = 1\ny = x is 'nope'\n")
    res = engine.run([f], repo_root=tmp_path)
    assert res.findings, "fixture should produce findings"
    doc = json.loads(json.dumps(engine.to_json(res, tmp_path)))
    back = engine.from_json(doc)
    assert [(x.rule, x.line, x.message) for x in back.findings] == [
        (x.rule, x.line, x.message) for x in res.findings
    ]
    assert doc["total"] == len(res.findings)
    assert sum(doc["counts"].values()) == doc["total"]
    try:
        engine.from_json({"version": 99, "findings": []})
    except ValueError:
        pass
    else:
        raise AssertionError("unsupported schema version must be rejected")


def test_committed_baseline_is_empty():
    """The repo swept clean under the full suite: the baseline ships
    empty and must only ever shrink (docs/ANALYSIS.md)."""
    import json

    doc = json.loads(
        (REPO / "tools" / "analysis" / "baseline.json").read_text()
    )
    assert doc["findings"] == []


# ---------------------------------------------------------------------------
# W12: unseeded randomness
# ---------------------------------------------------------------------------


def test_linter_bans_unseeded_global_randomness(tmp_path):
    """W12: random.* module-global functions and numpy.random legacy
    state are banned inside mirbft_tpu/ — fault schedules, manglers, and
    jitter must replay from explicit seeds."""
    import lint

    bad = tmp_path / "mirbft_tpu" / "chaos" / "sneaky.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "import random\n"
        "x = random.random()\n"
        "random.seed(7)\n"
        "from random import randint\n"
    )
    findings = [line for line in lint.check_file(bad) if "W12" in line]
    assert len(findings) == 3, findings

    legacy = tmp_path / "mirbft_tpu" / "ops" / "sneaky2.py"
    legacy.parent.mkdir(parents=True)
    legacy.write_text(
        "import numpy as np\n"
        "y = np.random.rand(3)\n"
        "import numpy.random\n"
        "from numpy.random import default_rng\n"
    )
    findings = [line for line in lint.check_file(legacy) if "W12" in line]
    assert len(findings) == 3, findings

    seeded = tmp_path / "mirbft_tpu" / "chaos" / "fine.py"
    seeded.write_text(
        "import random\nrng = random.Random(7)\nx = rng.random()\n"
    )
    assert not any("W12" in line for line in lint.check_file(seeded))

    # Tests, tools, and bench may use ambient randomness freely.
    outside = tmp_path / "tests" / "test_whatever.py"
    outside.parent.mkdir(parents=True)
    outside.write_text("import random\nx = random.random()\n")
    assert not any("W12" in line for line in lint.check_file(outside))


# ---------------------------------------------------------------------------
# D1xx: determinism purity auditor
# ---------------------------------------------------------------------------


def _package(tmp_path, files):
    """Materialize a synthetic mirbft_tpu package and return its root."""
    root = tmp_path / "mirbft_tpu"
    for rel, src in files.items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)
    for d in root.rglob("*"):
        if d.is_dir() and not (d / "__init__.py").exists():
            (d / "__init__.py").write_text("")
    if not (root / "__init__.py").exists():
        (root / "__init__.py").write_text("")
    return root


def _d_findings(root):
    import analysis.engine as engine

    res = engine.run([root])
    return [f for f in res.findings if f.rule.startswith("D")]


def test_purity_auditor_flags_impure_import_in_core(tmp_path):
    root = _package(
        tmp_path, {"core/evil.py": "import threading\nx = threading\n"}
    )
    found = _d_findings(root)
    assert any(
        f.rule == "D101" and "threading" in f.message for f in found
    ), found


def test_purity_auditor_follows_transitive_imports(tmp_path):
    """core/ must stay pure through every module it reaches, not just its
    own imports: core -> util -> socket is a finding, with the chain."""
    root = _package(
        tmp_path,
        {
            "core/a.py": "from ..util import helper\nx = helper\n",
            "util.py": "import socket\n\n\ndef helper():\n    return socket\n",
        },
    )
    found = _d_findings(root)
    chained = [
        f
        for f in found
        if f.rule == "D101" and "socket" in f.message and "via" in f.message
    ]
    assert chained, found


def test_purity_auditor_flags_direct_effects(tmp_path):
    root = _package(
        tmp_path,
        {
            "core/fx.py": (
                "def load(p):\n"
                "    return open(p).read()\n"
                "\n"
                "\n"
                "def tag(x):\n"
                "    return id(x)\n"
            ),
        },
    )
    rules = {f.rule for f in _d_findings(root)}
    assert "D102" in rules and "D103" in rules, rules


def test_purity_auditor_catches_set_iteration_ordering(tmp_path):
    """D104 regression for the epoch_tracker defect this suite caught:
    iterating a set into ordered protocol state is trace-visible
    nondeterminism; sorted(set(...)) is the sanctioned spelling."""
    root = _package(
        tmp_path,
        {
            "core/scan.py": (
                "def scan(d):\n"
                "    out = []\n"
                "    for v in set(d.values()):\n"
                "        out.append(v)\n"
                "    return out\n"
            ),
        },
    )
    found = _d_findings(root)
    assert any(f.rule == "D104" for f in found), found

    fixed = _package(
        tmp_path / "fixed",
        {
            "core/scan.py": (
                "def scan(d):\n"
                "    out = []\n"
                "    for v in sorted(set(d.values())):\n"
                "        out.append(v)\n"
                "    return out\n"
            ),
        },
    )
    assert not _d_findings(fixed)


def test_purity_auditor_ignores_modules_outside_the_roots(tmp_path):
    """Impure imports in non-root, non-reached modules are fine — the
    auditor proves the purity roots' transitive closure, nothing more."""
    root = _package(
        tmp_path,
        {
            "core/pure.py": "X = 1\n",
            "runtime/io_stuff.py": "import socket\nx = socket\n",
        },
    )
    assert not _d_findings(root)


# ---------------------------------------------------------------------------
# C2xx: guarded-by checker
# ---------------------------------------------------------------------------


def _c_findings(tmp_path, src, name="guarded.py"):
    import lint

    f = tmp_path / name
    f.write_text(src)
    return [line for line in lint.check_file(f) if " C2" in line]


def test_guarded_by_checker_flags_unlocked_access(tmp_path):
    found = _c_findings(
        tmp_path,
        "import threading\n"
        "\n"
        "\n"
        "class Box:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._cv = threading.Condition(self._lock)\n"
        "        self.items = 0  # guarded-by: _lock\n"
        "\n"
        "    def good(self):\n"
        "        with self._lock:\n"
        "            self.items += 1\n"
        "\n"
        "    def good_cv(self):\n"
        "        with self._cv:\n"
        "            return self.items\n"
        "\n"
        "    def bad(self):\n"
        "        return self.items\n",
    )
    assert len(found) == 1 and "C201" in found[0], found
    assert ":19:" in found[0], found  # bad()'s read, not the guarded ones


def test_guarded_by_checker_init_is_exempt(tmp_path):
    found = _c_findings(
        tmp_path,
        "import threading\n"
        "\n"
        "\n"
        "class Box:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.items = 0  # guarded-by: _lock\n"
        "        self.items += 1\n",
    )
    assert found == [], found


def test_guarded_by_checker_nested_defs_do_not_inherit_with(tmp_path):
    """A closure runs later on an arbitrary thread: the enclosing with
    does not protect its body."""
    found = _c_findings(
        tmp_path,
        "import threading\n"
        "\n"
        "\n"
        "class Box:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.items = 0  # guarded-by: _lock\n"
        "\n"
        "    def handed_off(self):\n"
        "        with self._lock:\n"
        "            def cb():\n"
        "                return self.items\n"
        "            return cb\n",
    )
    assert len(found) == 1 and "C201" in found[0], found


def test_holds_annotation_checks_call_sites(tmp_path):
    found = _c_findings(
        tmp_path,
        "import threading\n"
        "\n"
        "\n"
        "class Box:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.items = 0  # guarded-by: _lock\n"
        "\n"
        "    def _bump(self):  # holds: _lock\n"
        "        self.items += 1\n"
        "\n"
        "    def calls_held(self):\n"
        "        with self._lock:\n"
        "            self._bump()\n"
        "\n"
        "    def calls_bare(self):\n"
        "        self._bump()\n",
    )
    assert len(found) == 1 and "C202" in found[0], found
    assert ":17:" in found[0], found


def test_guarded_by_unknown_lock_is_flagged(tmp_path):
    found = _c_findings(
        tmp_path,
        "class Box:\n"
        "    def __init__(self):\n"
        "        self.items = 0  # guarded-by: _mutex\n",
    )
    assert len(found) == 1 and "C203" in found[0], found


# ---------------------------------------------------------------------------
# lock-order harness (tools/analysis/lockorder.py)
# ---------------------------------------------------------------------------


def test_lock_monitor_passes_consistent_order():
    from analysis.lockorder import LockMonitor

    mon = LockMonitor()
    a = mon.Lock()
    b = mon.Lock()
    for _ in range(3):
        with a:
            with b:
                pass
    mon.assert_no_cycles()


def test_lock_monitor_detects_order_inversion():
    import threading

    import pytest

    from analysis.lockorder import LockMonitor, LockOrderViolation

    mon = LockMonitor()
    a = mon.Lock()
    b = mon.Lock()
    with a:
        with b:
            pass

    def inverted():
        with b:
            with a:
                pass

    t = threading.Thread(target=inverted)
    t.start()
    t.join()
    with pytest.raises(LockOrderViolation):
        mon.assert_no_cycles()


def test_lock_monitor_condition_wait_is_not_an_inversion():
    """Condition.wait releases and reacquires its lock; the reacquire
    must not be recorded as acquiring under whatever the waiter's peers
    held meanwhile."""
    import threading

    from analysis.lockorder import LockMonitor

    mon = LockMonitor()
    lock = mon.Lock()
    cv = mon.Condition(lock)
    other = mon.Lock()
    done = []

    def waiter():
        with cv:
            cv.wait_for(lambda: done, timeout=5.0)

    def kicker():
        with other:
            with cv:
                done.append(1)
                cv.notify_all()

    t1 = threading.Thread(target=waiter)
    t1.start()
    t2 = threading.Thread(target=kicker)
    t2.start()
    t1.join()
    t2.join()
    mon.assert_no_cycles()


def test_lock_monitor_threading_proxy_forwards():
    import threading

    from analysis.lockorder import LockMonitor, _InstrumentedLock

    mon = LockMonitor()
    proxy = mon.threading_proxy()
    assert isinstance(proxy.Lock(), _InstrumentedLock)
    event = proxy.Event()
    assert isinstance(event, threading.Event)
    assert proxy.current_thread() is threading.current_thread()


def test_w19_queue_series_confined_to_bqueue_shim(tmp_path):
    """W19: ``mirbft_queue_*`` series names are confined to
    obsv/bqueue.py (the BoundedQueue/QueueTelemetry shim) and the
    metrics catalog — an ad-hoc gauge elsewhere would bypass the
    uniform depth/wait/saturation accounting the capacity rung's
    attribution leans on."""
    import lint

    sneaky = tmp_path / "mirbft_tpu" / "runtime" / "sneaky_queue.py"
    sneaky.parent.mkdir(parents=True)
    sneaky.write_text(
        "def emit(registry, n):\n"
        "    registry.gauge('mirbft_queue_depth', queue='x').set(n)\n"
    )
    findings = lint.check_file(sneaky)
    assert any("W19" in line for line in findings), findings

    # Any literal carrying the prefix trips it, not just gauge calls.
    renamed = tmp_path / "mirbft_tpu" / "app" / "sneaky2.py"
    renamed.parent.mkdir(parents=True)
    renamed.write_text("NAME = 'mirbft_queue_saturated_total'\n")
    assert any("W19" in line for line in lint.check_file(renamed))

    # The sanctioned owners, checked against the real sources.
    for allowed in ("bqueue.py", "metrics.py"):
        assert not any(
            "W19" in line
            for line in lint.check_file(
                REPO / "mirbft_tpu" / "obsv" / allowed
            )
        ), allowed

    # Outside the package tree (tests, tools) the rule does not apply.
    harness = tmp_path / "tests" / "test_queues.py"
    harness.parent.mkdir(parents=True)
    harness.write_text("SERIES = 'mirbft_queue_depth'\n")
    assert not any("W19" in line for line in lint.check_file(harness))


def test_w20_config_mutation_confined_to_adoption_seam(tmp_path):
    """W20: in-place writes through NetworkConfig/NetworkState objects
    are confined to core/commitstate.py + core/actions.py (the
    checkpoint-boundary adoption seam); every other layer must build a
    fresh object, so the committed Reconfiguration stays the single
    membership authority."""
    import lint

    sneaky = tmp_path / "mirbft_tpu" / "runtime" / "sneaky_cfg.py"
    sneaky.parent.mkdir(parents=True)
    sneaky.write_text(
        "def shrink(state, ci):\n"
        "    state.config.checkpoint_interval = ci\n"
        "    state.network_config.nodes[0] = 9\n"
        "    machine.active_state.reconfigured = True\n"
    )
    findings = [line for line in lint.check_file(sneaky) if "W20" in line]
    assert len(findings) == 3, findings

    # Rebinding a plain attribute to a *fresh* object is the sanctioned
    # way to change configuration outside the seam.
    fine = tmp_path / "mirbft_tpu" / "runtime" / "fine_cfg.py"
    fine.write_text(
        "def adopt(self, fresh):\n"
        "    self.network_state = fresh\n"
        "    config = fresh.config\n"
    )
    assert not any("W20" in line for line in lint.check_file(fine))

    # The adoption seam itself, checked against the real sources.
    for allowed in ("commitstate.py", "actions.py"):
        assert not any(
            "W20" in line
            for line in lint.check_file(
                REPO / "mirbft_tpu" / "core" / allowed
            )
        ), allowed

    # Outside the package tree (tests, tools, bench) the rule is off.
    harness = tmp_path / "tests" / "test_cfg.py"
    harness.parent.mkdir(parents=True)
    harness.write_text("state.config.f = 0\n")
    assert not any("W20" in line for line in lint.check_file(harness))


def test_linter_confines_raw_crypto_primitives(tmp_path):
    """W21: key material and raw verify/MAC primitives (hmac,
    ed25519_host, bls_host, ed25519_batch) are confined to
    mirbft_tpu/crypto/, mirbft_tpu/ops/, and testengine/signing.py;
    every other layer authenticates through the audited seams
    (crypto.mac, crypto.qc, the signing planes)."""
    import lint

    # Stdlib hmac in a runtime module: a second truncation/tag choice.
    sneaky = tmp_path / "mirbft_tpu" / "runtime" / "sneaky_mac.py"
    sneaky.parent.mkdir(parents=True)
    sneaky.write_text(
        "import hmac\n"
        "tag = hmac.new(b'k', b'm', 'sha256').digest()[:8]\n"
    )
    assert any("W21" in line for line in lint.check_file(sneaky)), (
        lint.check_file(sneaky)
    )

    # Raw host-math primitives via every import spelling.
    for i, text in enumerate(
        (
            "from ..crypto import ed25519_host\nx = ed25519_host\n",
            "from mirbft_tpu.crypto.ed25519_host import verify\nx = verify\n",
            "import mirbft_tpu.crypto.bls_host as b\nx = b\n",
            "from ..crypto import ed25519_batch\nx = ed25519_batch\n",
        )
    ):
        bad = tmp_path / "mirbft_tpu" / "chaos" / f"sneaky_{i}.py"
        bad.parent.mkdir(parents=True, exist_ok=True)
        bad.write_text(text)
        assert any("W21" in line for line in lint.check_file(bad)), text

    # The sanctioned seams are importable from anywhere in the package.
    fine = tmp_path / "mirbft_tpu" / "runtime" / "fine_mac.py"
    fine.write_text(
        "from ..crypto.mac import TAG_LEN\n"
        "from ..crypto import qc\n"
        "x = (TAG_LEN, qc)\n"
    )
    assert not any("W21" in line for line in lint.check_file(fine))

    # The confinement's own homes, checked against the real sources.
    for allowed in (
        REPO / "mirbft_tpu" / "crypto" / "mac.py",
        REPO / "mirbft_tpu" / "crypto" / "ed25519_batch.py",
        REPO / "mirbft_tpu" / "ops" / "ed25519.py",
        REPO / "mirbft_tpu" / "testengine" / "signing.py",
    ):
        assert not any(
            "W21" in line for line in lint.check_file(allowed)
        ), allowed

    # Outside the package tree (tests, tools, bench) the rule is off.
    harness = tmp_path / "tests" / "test_mac.py"
    harness.parent.mkdir(parents=True)
    harness.write_text("import hmac\nx = hmac\n")
    assert not any("W21" in line for line in lint.check_file(harness))
