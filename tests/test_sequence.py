"""Gate for the sequence 3-phase FSM: a live port of the reference's disabled
spec (reference: sequence_test.go:39-281) with exact expected Actions, plus
full happy paths for owner and follower roles on a 4-node f=1 network."""

import pytest

from mirbft_tpu import pb
from mirbft_tpu.core.persisted import Persisted
from mirbft_tpu.core.sequence import Sequence, SeqState


NODES = [0, 1, 2, 3]


def make_seq(my_id=1, owner=0, epoch=4, seq_no=5):
    nc = pb.NetworkConfig(nodes=list(NODES), f=1, number_of_buckets=4)
    persisted = Persisted()
    return Sequence(
        owner=owner,
        epoch=epoch,
        seq_no=seq_no,
        persisted=persisted,
        network_config=nc,
        my_config=pb.InitialParameters(id=my_id),
    )


ACKS = [
    pb.RequestAck(client_id=9, req_no=7, digest=b"msg1-digest"),
    pb.RequestAck(client_id=9, req_no=8, digest=b"msg2-digest"),
]


def test_allocate_emits_batch_hash_request():
    s = make_seq()
    actions = s.allocate(list(ACKS), None)
    assert len(actions.hashes) == 1
    hr = actions.hashes[0]
    assert hr.data == [b"msg1-digest", b"msg2-digest"]
    assert hr.origin.digest == b""
    origin = hr.origin.type
    assert isinstance(origin, pb.HashOriginBatch)
    assert origin.source == 0 and origin.seq_no == 5 and origin.epoch == 4
    assert origin.request_acks == ACKS
    assert not actions.sends and not actions.write_ahead
    # PENDING_REQUESTS advances immediately to READY with no outstanding reqs.
    assert s.state == SeqState.READY
    assert s.batch == ACKS


def test_allocate_twice_raises():
    s = make_seq()
    s.allocate(list(ACKS), None)
    with pytest.raises(AssertionError):
        s.allocate(list(ACKS), None)


def test_follower_hash_result_sends_prepare_and_persists_qentry():
    s = make_seq(my_id=1, owner=0)
    s.allocate(list(ACKS), None)
    actions = s.apply_batch_hash_result(b"digest")

    assert s.state == SeqState.PREPREPARED
    assert s.digest == b"digest"
    assert s.q_entry == pb.QEntry(seq_no=5, digest=b"digest", requests=ACKS)

    [send] = actions.sends
    assert send.targets == NODES
    assert send.msg == pb.Msg(
        type=pb.Prepare(seq_no=5, epoch=4, digest=b"digest")
    )
    [write] = actions.write_ahead
    assert write.append.data == pb.Persistent(
        type=pb.QEntry(seq_no=5, digest=b"digest", requests=ACKS)
    )


def test_owner_hash_result_sends_preprepare():
    s = make_seq(my_id=0, owner=0)

    class CR:
        def __init__(self, ack, agreements):
            self.ack = ack
            self.agreements = agreements

    # Node 3 hasn't ACKed msg2: it must receive a forward.
    crs = [CR(ACKS[0], 0b1111), CR(ACKS[1], 0b0111)]  # node-id bitmasks
    s.allocate_as_owner(crs)
    actions = s.apply_batch_hash_result(b"digest")

    [send] = actions.sends
    assert send.msg == pb.Msg(
        type=pb.Preprepare(seq_no=5, epoch=4, batch=ACKS)
    )
    assert [(f.targets, f.request_ack) for f in actions.forward_requests] == [
        ([], ACKS[0]),
        ([3], ACKS[1]),
    ]


def test_prepare_quorum_sends_commit_and_persists_pentry():
    s = make_seq(my_id=1, owner=0)
    s.allocate(list(ACKS), None)
    # The owner's preprepare counts as its prepare (count 1).
    s.apply_batch_hash_result(b"digest")
    # Our own Prepare was broadcast to all nodes *including self*; the
    # executor loops it back (count 2, and unlocks the own-vote gate).
    s.apply_prepare_msg(1, b"digest")
    actions = s.apply_prepare_msg(2, b"digest")  # 3rd prepare → quorum

    assert s.state == SeqState.PREPARED
    [send] = actions.sends
    assert send.msg == pb.Msg(
        type=pb.Commit(seq_no=5, epoch=4, digest=b"digest")
    )
    [write] = actions.write_ahead
    assert write.append.data == pb.Persistent(
        type=pb.PEntry(seq_no=5, digest=b"digest")
    )


def test_wrong_digest_prepares_do_not_count():
    s = make_seq(my_id=1, owner=0)
    s.allocate(list(ACKS), None)
    s.apply_batch_hash_result(b"digest")
    s.apply_prepare_msg(1, b"digest")
    s.apply_prepare_msg(2, b"evil")
    s.apply_prepare_msg(3, b"evil")
    assert s.state == SeqState.PREPREPARED  # no quorum on our digest


def test_equivocating_prepare_ignored():
    s = make_seq(my_id=1, owner=0)
    s.allocate(list(ACKS), None)
    s.apply_batch_hash_result(b"digest")
    s.apply_prepare_msg(2, b"digest")
    # Node 2 equivocates with a second prepare: ignored.
    s.apply_prepare_msg(2, b"digest")
    assert s._prepares[b"digest"] == 2  # owner + node 2, not 3
    assert s.state == SeqState.PREPREPARED


def test_full_happy_path_to_committed():
    s = make_seq(my_id=1, owner=0)
    s.allocate(list(ACKS), None)
    s.apply_batch_hash_result(b"digest")  # owner's implicit prepare
    s.apply_prepare_msg(1, b"digest")  # own prepare, self-delivered
    s.apply_prepare_msg(2, b"digest")  # quorum → Commit sent
    assert s.state == SeqState.PREPARED
    s.apply_commit_msg(1, b"digest")  # own commit, self-delivered
    s.apply_commit_msg(0, b"digest")
    actions = s.apply_commit_msg(2, b"digest")
    assert s.state == SeqState.COMMITTED
    assert actions.is_empty()


def test_commit_quorum_requires_own_commit():
    s = make_seq(my_id=1, owner=0)
    s.allocate(list(ACKS), None)
    s.apply_batch_hash_result(b"digest")
    s.apply_prepare_msg(1, b"digest")
    s.apply_prepare_msg(2, b"digest")
    assert s.state == SeqState.PREPARED
    # Three remote commits but not our own: must not commit.
    s.apply_commit_msg(0, b"digest")
    s.apply_commit_msg(2, b"digest")
    s.apply_commit_msg(3, b"digest")
    assert s.state == SeqState.PREPARED
    s.apply_commit_msg(1, b"digest")
    assert s.state == SeqState.COMMITTED


def test_null_batch_skips_hash():
    s = make_seq(my_id=1, owner=0)
    actions = s.allocate([], None)
    # No hash request; a Prepare with empty digest and a QEntry are emitted.
    assert not actions.hashes
    assert s.state == SeqState.PREPREPARED
    [send] = actions.sends
    assert send.msg == pb.Msg(type=pb.Prepare(seq_no=5, epoch=4, digest=b""))
    assert s.q_entry == pb.QEntry(seq_no=5, digest=b"", requests=[])


def test_outstanding_requests_gate_readiness():
    s = make_seq(my_id=1, owner=0)
    outstanding = {b"msg2-digest"}
    actions = s.allocate(list(ACKS), outstanding)
    assert len(actions.hashes) == 1
    assert s.state == SeqState.PENDING_REQUESTS

    # Digest arrives while a request is still missing: stays pending.
    actions = s.apply_batch_hash_result(b"digest")
    assert s.state == SeqState.PENDING_REQUESTS
    assert not actions.sends

    actions = s.satisfy_outstanding(ACKS[1])
    assert s.state == SeqState.PREPREPARED
    [send] = actions.sends
    assert send.msg == pb.Msg(
        type=pb.Prepare(seq_no=5, epoch=4, digest=b"digest")
    )
