"""The three-tier authentication model (docs/CRYPTO.md): client-request
Ed25519 batch verification (crypto/ed25519_batch.py), per-link MAC
authenticators for the replica plane (crypto/mac.py), and BLS aggregate
quorum certificates (crypto/qc.py) — plus the speculative admission
planes that overlap verification with consensus
(testengine/signing.py:SpeculativeSignaturePlane, runtime/ingress.py:
SpeculativeIngress) and the deterministic-engine MAC model
(testengine/signing.py:MacSealPlane)."""

from mirbft_tpu.crypto import ed25519_batch, ed25519_host, mac, qc
from mirbft_tpu.obsv import hooks
from mirbft_tpu.obsv.metrics import Registry
from mirbft_tpu.testengine import signing


# ---------------------------------------------------------------------------
# crypto/ed25519_batch.py — RLC batch verification vs the host oracle
# ---------------------------------------------------------------------------


def _signed_items(n, forge=()):
    """n (pk, message, signature) triples; indices in ``forge`` carry a
    signature over a different message (a genuine-looking forgery)."""
    items = []
    for i in range(n):
        seed = b"batch-seed-%02d" % i + bytes(17)
        pk = ed25519_host.public_key(seed)
        message = b"stmt-%d" % i
        if i in forge:
            sig = ed25519_host.sign(seed, message + b"-tampered")
        else:
            sig = ed25519_host.sign(seed, message)
        items.append((pk, message, sig))
    return items


def test_batch_verify_matches_host_oracle():
    items = _signed_items(6, forge={1, 4})
    verdicts = ed25519_batch.verify_batch(items, chunk=4)
    oracle = [
        ed25519_host.verify(pk, message, sig) for pk, message, sig in items
    ]
    assert verdicts == oracle
    assert verdicts == [True, False, True, True, False, True]


def test_batch_verify_all_valid_and_empty():
    items = _signed_items(5)
    assert ed25519_batch.verify_batch(items) == [True] * 5
    assert ed25519_batch.verify_batch([]) == []


def test_batch_verify_descent_isolates_single_forgery():
    """One forged item must not poison the rest of its burst: the
    binary-split descent re-accepts every honest sibling."""
    items = _signed_items(8, forge={3})
    verdicts = ed25519_batch.verify_batch(items, chunk=8)
    assert verdicts == [i != 3 for i in range(8)]


def test_batch_verify_rejects_unparseable_material():
    items = _signed_items(3)
    pk, message, _sig = items[0]
    items[0] = (pk, message, b"\x00" * 64)  # not a curve point encoding
    items[2] = (b"\xff" * 32, items[2][1], items[2][2])
    verdicts = ed25519_batch.verify_batch(items)
    assert verdicts[0] is False and verdicts[2] is False
    assert verdicts[1] is True


# ---------------------------------------------------------------------------
# crypto/mac.py — pairwise link keys and frame tags
# ---------------------------------------------------------------------------


def test_link_key_symmetric_and_distinct():
    secret = b"cluster-secret"
    assert mac.link_key(secret, 0, 3) == mac.link_key(secret, 3, 0)
    assert mac.link_key(secret, 0, 3) != mac.link_key(secret, 0, 2)
    assert mac.link_key(secret, 0, 3) != mac.link_key(b"other", 0, 3)


def test_seal_open_roundtrip_between_peers():
    alice = mac.LinkAuthenticator(0, b"s")
    bob = mac.LinkAuthenticator(1, b"s")
    sealed = alice.seal(1, b"prepare-frame")
    assert len(sealed) == len(b"prepare-frame") + mac.TAG_LEN
    assert bob.open(0, sealed) == b"prepare-frame"
    # The same tag does not open under a different link's key.
    assert bob.open(2, sealed) is None


def test_open_rejects_tampered_tag_and_body():
    alice = mac.LinkAuthenticator(0, b"s")
    bob = mac.LinkAuthenticator(1, b"s")
    sealed = bytearray(alice.seal(1, b"payload"))
    sealed[-1] ^= 0x01  # tag bit flip
    assert bob.open(0, bytes(sealed)) is None
    sealed = bytearray(alice.seal(1, b"payload"))
    sealed[0] ^= 0x01  # body bit flip
    assert bob.open(0, bytes(sealed)) is None


def test_open_rejects_short_frames():
    bob = mac.LinkAuthenticator(1, b"s")
    assert bob.open(0, b"") is None
    assert bob.open(0, b"x" * mac.TAG_LEN) is None


def test_mismatched_secret_fails():
    alice = mac.LinkAuthenticator(0, b"secret-a")
    bob = mac.LinkAuthenticator(1, b"secret-b")
    assert bob.open(0, alice.seal(1, b"frame")) is None


# ---------------------------------------------------------------------------
# crypto/qc.py — aggregate quorum certificates
# ---------------------------------------------------------------------------


def _votes(statement, n=4):
    seeds = [b"qc-seed-%02d" % i for i in range(n)]
    pks = [qc.public_key(seed) for seed in seeds]
    sigs = [qc.sign_vote(seed, statement) for seed in seeds]
    return seeds, pks, sigs


def test_vote_sign_verify():
    stmt = b"checkpoint:12:abc"
    seeds, pks, sigs = _votes(stmt, n=2)
    assert qc.verify_vote(pks[0], stmt, sigs[0])
    assert not qc.verify_vote(pks[0], stmt, sigs[1])
    assert not qc.verify_vote(pks[0], b"other", sigs[0])


def test_aggregate_cert_verifies_once():
    stmt = b"checkpoint:40:deadbeef"
    _seeds, pks, sigs = _votes(stmt, n=4)
    asig = qc.aggregate(sigs, use_device=False)
    assert qc.verify_cert(pks, stmt, asig)


def test_aggregate_cert_rejects_forgeries():
    stmt = b"checkpoint:40:deadbeef"
    _seeds, pks, sigs = _votes(stmt, n=4)
    asig = qc.aggregate(sigs, use_device=False)
    # Mismatched statement under a valid aggregate.
    assert not qc.verify_cert(pks, b"checkpoint:41:deadbeef", asig)
    # Wrong signer set: the aggregate excludes a claimed voter.
    other_pk = qc.public_key(b"qc-seed-99")
    assert not qc.verify_cert(pks[:-1] + [other_pk], stmt, asig)
    # Aggregate missing one vote share.
    partial = qc.aggregate(sigs[:-1], use_device=False)
    assert not qc.verify_cert(pks, stmt, partial)


def test_cert_verify_outcomes_are_metered():
    stmt = b"checkpoint:7:cafe"
    _seeds, pks, sigs = _votes(stmt, n=3)
    asig = qc.aggregate(sigs, use_device=False)
    metrics, _ = hooks.enable(registry=Registry(strict=True), trace=False)
    try:
        assert qc.verify_cert(pks, stmt, asig)
        assert not qc.verify_cert(pks, b"forged", asig)
        snap = metrics.snapshot()["mirbft_cert_aggregate_verifies_total"]
        by_outcome = {
            series["labels"]["outcome"]: series["value"]
            for series in snap["series"]
        }
        assert by_outcome == {"ok": 1, "rejected": 1}
    finally:
        hooks.disable()


# ---------------------------------------------------------------------------
# SpeculativeSignaturePlane — admit optimistically, join before commit
# ---------------------------------------------------------------------------


def test_speculative_plane_admits_then_judges_at_boundary():
    signer = signing.make_signer()
    plane = signing.SpeculativeSignaturePlane(use_kernel=False)
    data = signer(1, 0, b"w")
    plane.submit(1, 0, data)
    assert plane.speculative_depth == 1  # parked, not yet judged
    plane.on_time(1)  # wave boundary: the burst verifies
    assert plane.speculative_depth == 0
    assert plane.valid(1, 0, data)
    assert plane.forced_joins == 0
    assert plane.host_verifies == 1


def test_speculative_plane_evicts_bad_signatures():
    signer = signing.make_signer()
    plane = signing.SpeculativeSignaturePlane(use_kernel=False)
    good = signer(1, 0, b"w")
    bad = bytearray(signer(2, 0, b"w"))
    bad[0] ^= 0xFF  # payload tampered after signing
    plane.submit(1, 0, good)
    plane.submit(2, 0, bytes(bad))
    plane.on_time(1)
    assert plane.valid(1, 0, good)
    assert not plane.valid(2, 0, bytes(bad))
    assert plane.speculative_evictions == 1


def test_speculative_plane_forced_join_before_boundary():
    """A delivery demanding a verdict before the wave boundary forces the
    join early instead of reading an unjudged request."""
    signer = signing.make_signer()
    plane = signing.SpeculativeSignaturePlane(use_kernel=False)
    data = signer(3, 1, b"x")
    plane.submit(3, 1, data)
    assert plane.valid(3, 1, data)  # no on_time yet
    assert plane.forced_joins == 1


def test_speculative_plane_rejects_wrong_client_key_at_admission():
    signer = signing.make_signer()
    plane = signing.SpeculativeSignaturePlane(use_kernel=False)
    data = signer(1, 0, b"w")
    plane.submit(9, 0, data)  # client 9 presenting client 1's key
    assert plane.speculative_depth == 0  # structurally rejected, not parked
    assert not plane.valid(9, 0, data)


def test_speculative_plane_matches_synchronous_plane():
    signer = signing.make_signer()
    spec = signing.SpeculativeSignaturePlane(use_kernel=False)
    sync = signing.SignaturePlane()
    items = []
    for i in range(4):
        data = signer(i, 0, b"p%d" % i)
        if i == 2:
            data = data[:-1] + bytes([data[-1] ^ 1])  # corrupt pk byte
        items.append((i, 0, data))
    for item in items:
        spec.submit(*item)
    spec.on_time(1)
    assert [spec.valid(*item) for item in items] == [
        sync.valid(*item) for item in items
    ]


# ---------------------------------------------------------------------------
# runtime/ingress.py — the live speculative verify stage
# ---------------------------------------------------------------------------


class _Req:
    def __init__(self, client_id, req_no, data):
        self.client_id = client_id
        self.req_no = req_no
        self.data = data


def test_ingress_delivers_survivors_and_evicts_failures():
    from mirbft_tpu.runtime.ingress import SpeculativeIngress

    delivered = []
    verdict = {b"good": True, b"bad": False}

    def verify_batch_fn(items):
        return [verdict[data] for _c, _r, data in items]

    stage = SpeculativeIngress(delivered.append, verify_batch_fn)
    try:
        assert stage.submit(_Req(1, 0, b"good"))
        assert stage.submit(_Req(1, 1, b"bad"))
        assert stage.flush(timeout=10)
        assert [r.data for r in delivered] == [b"good"]
        assert stage.delivered == 1
        assert stage.evicted == 1
        assert stage.depth == 0
    finally:
        stage.close()


def test_ingress_fails_closed_when_verifier_dies():
    from mirbft_tpu.runtime.ingress import SpeculativeIngress

    delivered = []

    def broken(items):
        raise RuntimeError("verifier down")

    stage = SpeculativeIngress(delivered.append, broken)
    try:
        stage.submit(_Req(1, 0, b"x"))
        assert stage.flush(timeout=10)
        assert delivered == []
        assert stage.evicted == 1
    finally:
        stage.close()


def test_ingress_sheds_load_past_queue_depth():
    from mirbft_tpu.runtime.ingress import SpeculativeIngress

    gate = __import__("threading").Event()

    def slow(items):
        gate.wait(timeout=10)
        return [True] * len(items)

    stage = SpeculativeIngress(lambda r: None, slow, queue_depth=2)
    try:
        for i in range(8):
            stage.submit(_Req(1, i, b"p"))
        assert stage.dropped_overflow > 0
        gate.set()
        assert stage.flush(timeout=10)
        assert stage.admitted + stage.dropped_overflow == 8
    finally:
        gate.set()
        stage.close()


# ---------------------------------------------------------------------------
# MacSealPlane — the deterministic engine's MAC model
# ---------------------------------------------------------------------------


def test_mac_seal_plane_admits_sealed_rejects_fresh():
    plane = signing.MacSealPlane()
    msg = object()
    plane.seal(msg)
    assert plane.admit(msg)
    assert plane.admit(msg)  # duplicates of a sealed frame are replay,
    # which dedup owns — the MAC model admits them
    assert not plane.admit(object())  # a mangler's fresh rewrite
    assert plane.sealed == 1
    assert plane.rejections == 1


def test_mac_seal_plane_rejections_are_metered():
    plane = signing.MacSealPlane()
    metrics, _ = hooks.enable(registry=Registry(strict=True), trace=False)
    try:
        plane.seal(msg := object())
        assert plane.admit(msg)
        assert not plane.admit(object())
        snap = metrics.snapshot()["mirbft_mac_rejections_total"]
        assert snap["series"] == [
            {"labels": {"kind": "unsealed"}, "value": 1}
        ]
    finally:
        hooks.disable()


# ---------------------------------------------------------------------------
# runtime/msgfilter.py + transport framing — live MAC ingress
# ---------------------------------------------------------------------------


def test_check_frame_mac_kinds():
    from mirbft_tpu.runtime.msgfilter import check_frame_mac

    alice = mac.LinkAuthenticator(0, b"s")
    bob = mac.LinkAuthenticator(1, b"s")
    sealed = alice.seal(1, b"frame-bytes")
    body, kind = check_frame_mac(bob, 0, sealed)
    assert (body, kind) == (b"frame-bytes", None)
    forged = sealed[:-1] + bytes([sealed[-1] ^ 1])
    assert check_frame_mac(bob, 0, forged) == (None, "bad_mac")
    assert check_frame_mac(bob, 0, b"xy") == (None, "short_frame")
    # A forged source claim selects the wrong link key and fails the tag.
    assert check_frame_mac(bob, 2, sealed) == (None, "bad_mac")


def test_transport_rejects_forged_mac_frames():
    """Two live transports under link_auth: honest node frames flow,
    while a tag-flipped frame injected straight at the receiver's socket
    is counted into mac_rejections and never delivered."""
    import socket
    import struct
    import time as _time

    from mirbft_tpu import pb
    from mirbft_tpu.runtime.transport import TcpTransport
    from mirbft_tpu.wire import encode_varint

    received = []

    class _Sink:
        def step(self, source, msg):
            received.append((source, type(msg.type).__name__))

    secret = b"unit-auth"
    receiver = TcpTransport(1, link_auth=mac.LinkAuthenticator(1, secret))
    sender = TcpTransport(0, link_auth=mac.LinkAuthenticator(0, secret))
    try:
        sender.connect(1, receiver.address)
        receiver.serve(_Sink())
        msg = pb.Msg(type=pb.Suspect(epoch=3))
        sender.link().send(1, msg)
        deadline = _time.monotonic() + 10
        while not received and _time.monotonic() < deadline:
            _time.sleep(0.01)
        assert received == [(0, "Suspect")]

        # Forge: a well-formed sealed frame with one tag byte flipped,
        # written raw to the receiver's listener.
        auth = mac.LinkAuthenticator(0, secret)
        payload = auth.seal(1, encode_varint(0) + pb.encode(msg))
        forged = payload[:-1] + bytes([payload[-1] ^ 1])
        with socket.create_connection(
            tuple(receiver.address), timeout=5
        ) as raw:
            raw.sendall(struct.pack("<I", len(forged)) + forged)
            deadline = _time.monotonic() + 10
            while _time.monotonic() < deadline:
                if receiver.mac_rejections.get("bad_mac"):
                    break
                _time.sleep(0.05)
        assert receiver.mac_rejections.get("bad_mac", 0) >= 1
        assert received == [(0, "Suspect")]  # the forgery never delivered
    finally:
        sender.close()
        receiver.close()


# ---------------------------------------------------------------------------
# Regression: a speculatively-admitted bad-signature request never commits,
# even when a replica crashes and restarts while the request is in flight.
# ---------------------------------------------------------------------------


def test_speculative_eviction_survives_crash_restart():
    from mirbft_tpu import pb
    from mirbft_tpu.testengine.engine import BasicRecorder
    from mirbft_tpu.testengine.manglers import rule

    victim = 5  # client ids start at node_count: clients are 4, 5, 6

    def victim_req0(_recorder, _when, _node, event):
        inner = event.type
        return (
            isinstance(inner, pb.EventPropose)
            and inner.request is not None
            and inner.request.client_id == victim
            and inner.request.req_no == 0
        )

    corrupt = rule(victim_req0).corrupt()
    plane = signing.SpeculativeSignaturePlane(use_kernel=False)
    r = BasicRecorder(
        4,
        3,
        6,
        signer=signing.make_signer(),
        signature_plane=plane,
        manglers=[corrupt],
        record=False,
    )
    for _ in range(3000):
        r.step()
    r.crash(3)  # mid-flight: the eviction verdict must survive the reboot
    for _ in range(3000):
        r.step()
    r.schedule_restart(3, delay=0)
    # Client streams are strictly ordered, so evicting every delivered
    # copy of the victim's req 0 stalls that client entirely; the other
    # two clients' streams must still commit everywhere.
    total = 2 * 6
    r.drain_until(
        lambda rec: all(
            rec.committed_at(n) >= total
            for n in range(4)
            if not rec.node_states[n].crashed
        ),
        max_steps=2_000_000,
    )
    assert corrupt.corrupted_proposes >= 4  # one rewrite per replica
    assert plane.speculative_evictions >= 4
    assert r.byzantine_rejections == corrupt.corrupted_proposes
    for n in range(4):
        committed = {(c, q) for c, q, _s in r.node_states[n].committed_reqs}
        assert not any(c == victim for c, _q in committed), (
            f"evicted request ordered at {n}"
        )
        assert {(c, q) for c, q in committed if c != victim} == {
            (c, q) for c in (4, 6) for q in range(6)
        }
    assert len({r.node_states[n].app_chain for n in range(4)}) == 1
