"""Observability subsystem (mirbft_tpu/obsv): registry semantics, the
Prometheus/JSON expositions, Chrome trace validity, the consensus
timeline profiler on a seeded run, and the chaos-metrics integration.

Every test that enables the process-global hooks disables them in a
``finally`` — a leaked enabled state would silently instrument (and
slow) every later test in the session.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from mirbft_tpu.obsv import hooks
from mirbft_tpu.obsv.metrics import (
    NULL_COUNTER,
    NULL_GAUGE,
    NULL_HISTOGRAM,
    Registry,
    null_registry,
)
from mirbft_tpu.obsv.timeline import PHASES, TimelineProfiler
from mirbft_tpu.obsv.trace import Tracer

REPO = Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------------
# Registry semantics
# ---------------------------------------------------------------------------


def test_counter_gauge_histogram_basics():
    reg = Registry(strict=False)
    c = reg.counter("c_total")
    c.inc()
    c.inc(4)
    assert c.value == 5
    assert reg.counter("c_total") is c  # same series, same handle

    g = reg.gauge("g")
    g.set(2.5)
    assert g.value == 2.5

    h = reg.histogram("h_seconds", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 50.0):
        h.observe(v)
    assert h.count == 3
    assert h.sum == pytest.approx(50.55)
    # 0.05 <= 0.1; 0.5 <= 1.0; 50 lands only in +Inf (count/sum).
    assert h.bucket_counts == [1, 1]


def test_labels_key_distinct_series():
    reg = Registry(strict=False)
    a = reg.counter("x_total", path="device")
    b = reg.counter("x_total", path="host")
    a.inc(3)
    b.inc(1)
    assert a is not b
    # kwarg order must not matter for series identity.
    assert reg.counter("x_total", path="device") is a
    snap = reg.snapshot()["x_total"]
    assert snap["kind"] == "counter"
    values = {
        s["labels"]["path"]: s["value"] for s in snap["series"]
    }
    assert values == {"device": 3, "host": 1}


def test_strict_registry_rejects_uncataloged_names():
    reg = Registry()  # strict by default
    with pytest.raises(KeyError):
        reg.counter("mirbft_not_a_real_metric_total")
    # Catalog names pass.
    reg.counter("mirbft_wal_appends_total").inc()


def test_kind_mismatch_raises():
    reg = Registry(strict=False)
    reg.counter("m")
    with pytest.raises(TypeError):
        reg.gauge("m")


def test_null_registry_is_shared_noop_singletons():
    reg = null_registry()
    assert reg is null_registry()
    assert reg.counter("anything", a="b") is NULL_COUNTER
    assert reg.gauge("anything") is NULL_GAUGE
    assert reg.histogram("anything") is NULL_HISTOGRAM
    # No-ops: nothing accumulates, nothing raises.
    NULL_COUNTER.inc(10)
    NULL_GAUGE.set(3)
    NULL_HISTOGRAM.observe(1.0)
    assert NULL_COUNTER.value == 0
    assert reg.snapshot() == {}
    assert reg.prometheus_text() == ""


def test_prometheus_exposition_format():
    reg = Registry(strict=False)
    reg.counter("req_total", path="device").inc(7)
    h = reg.histogram("lat_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = reg.prometheus_text()
    lines = text.splitlines()
    assert "# TYPE req_total counter" in lines
    assert 'req_total{path="device"} 7' in lines
    assert "# TYPE lat_seconds histogram" in lines
    # Buckets are cumulative and +Inf equals the count.
    assert 'lat_seconds_bucket{le="0.1"} 1' in lines
    assert 'lat_seconds_bucket{le="1.0"} 2' in lines
    assert 'lat_seconds_bucket{le="+Inf"} 3' in lines
    assert "lat_seconds_count 3" in lines
    assert any(line.startswith("lat_seconds_sum ") for line in lines)
    assert text.endswith("\n")


def test_json_dump_round_trips():
    reg = Registry(strict=False)
    reg.gauge("g", scenario="a b\"c").set(1)
    parsed = json.loads(reg.to_json())
    assert parsed["g"]["series"][0]["labels"] == {"scenario": 'a b"c'}


# ---------------------------------------------------------------------------
# Tracer / Chrome trace validity
# ---------------------------------------------------------------------------


def _assert_well_nested(events):
    """Per tid, any two X spans either nest or are disjoint."""
    by_tid = {}
    for e in events:
        if e["ph"] == "X":
            by_tid.setdefault(e["tid"], []).append(
                (e["ts"], e["ts"] + e["dur"])
            )
    for spans in by_tid.values():
        for i, (s1, e1) in enumerate(spans):
            for s2, e2 in spans[i + 1 :]:
                overlap = max(s1, s2) < min(e1, e2)
                contained = (s1 <= s2 and e2 <= e1) or (
                    s2 <= s1 and e1 <= e2
                )
                assert not overlap or contained, (spans,)


def test_chrome_trace_is_valid_and_nested(tmp_path):
    tracer = Tracer()
    tracer.name_thread(0, "node 0")
    with tracer.span("outer", cat="t", tid=0):
        with tracer.span("inner", cat="t", tid=0):
            pass
        tracer.instant("mark", cat="consensus", tid=0, args={"seq": 1})
    with tracer.span("later", cat="t", tid=0):
        pass

    out = tmp_path / "trace.json"
    tracer.write(str(out))
    trace = json.loads(out.read_text())
    events = trace["traceEvents"]

    meta = [e for e in events if e["ph"] == "M"]
    assert meta and meta[0]["args"]["name"] == "node 0"
    xs = [e for e in events if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"outer", "inner", "later"}
    for e in xs:
        assert e["ts"] >= 0 and e["dur"] >= 0
    inst = [e for e in events if e["ph"] == "i"]
    assert inst[0]["s"] == "t" and inst[0]["args"]["seq"] == 1
    # Monotonic source: 'later' starts after 'outer' ends.
    by_name = {e["name"]: e for e in xs}
    assert (
        by_name["later"]["ts"]
        >= by_name["outer"]["ts"] + by_name["outer"]["dur"]
    )
    _assert_well_nested(events)


def test_complete_records_backdated_span():
    tracer = Tracer()
    tracer._t0_ns -= 300_000_000  # pretend 300ms of tracer lifetime
    tracer.complete("flush", cat="crypto", tid=-1, dur_s=0.25)
    (e,) = tracer.events
    assert e["ph"] == "X"
    assert e["dur"] == pytest.approx(250_000, rel=0.01)  # µs
    assert e["ts"] >= 0


def test_complete_clamps_to_tracer_birth():
    tracer = Tracer()
    # A duration longer than the tracer has been alive must not produce
    # a negative ts (invalid Chrome trace); it is clamped to birth.
    tracer.complete("early", dur_s=10.0)
    (e,) = tracer.events
    assert e["ts"] >= 0
    assert e["dur"] < 10.0 * 1e6


# ---------------------------------------------------------------------------
# Timeline profiler
# ---------------------------------------------------------------------------


def test_timeline_profiler_synthetic_edges():
    def inst(name, node, seq, t):
        return {
            "ph": "i",
            "name": name,
            "args": {"node": node, "seq": seq, "sim_ms": t},
        }

    events = [
        inst("seq.allocated", 0, 1, 0),
        inst("seq.preprepared", 0, 1, 10),
        inst("seq.prepared", 0, 1, 40),
        inst("seq.commit_quorum", 0, 1, 70),
        inst("ckpt.stable", 0, 20, 500),
        # Second node: no checkpoint, partial lifecycle.
        inst("seq.allocated", 1, 1, 5),
        inst("seq.preprepared", 1, 1, 25),
    ]
    prof = TimelineProfiler.from_events(events)
    stats = {s.phase: s for s in prof.stats()}
    assert stats["preprepare"].count == 2
    assert sorted(prof.phase_samples()["preprepare"]) == [10, 20]
    assert stats["prepare"].p50 == 30
    assert stats["commit"].p50 == 30
    assert stats["checkpoint"].count == 1
    assert stats["checkpoint"].p50 == 430  # 500 - 70


def test_timeline_profiler_on_seeded_run():
    from mirbft_tpu.testengine.engine import BasicRecorder

    metrics, tracer = hooks.enable(trace=True)
    try:
        rec = BasicRecorder(4, 4, 30, batch_size=2, seed=0, record=False)
        rec.drain_clients(max_steps=2_000_000)
    finally:
        hooks.disable()

    prof = TimelineProfiler.from_tracer(tracer)
    stats = {s.phase: s for s in prof.stats()}
    # 4 clients x 30 reqs / batch 2 = 60 seqs = 3 checkpoint windows
    # (ci=20): enough that stable checkpoints must circulate, so every
    # phase — including checkpoint — collects samples.
    assert set(stats) == set(PHASES)
    for s in stats.values():
        assert s.count > 0
        assert 0 <= s.p50 <= s.p95 <= s.p99
    # The instrumented state machine fed the registry too.
    snap = metrics.snapshot()
    assert snap["mirbft_sm_events_total"]["series"]
    assert snap["mirbft_sm_apply_seconds"]["series"][0]["count"] > 0
    # And the trace round-trips through the Chrome JSON shape.
    prof2 = TimelineProfiler.from_chrome_trace(tracer.chrome_trace())
    assert {s.phase: s.count for s in prof2.stats()} == {
        s.phase: s.count for s in prof.stats()
    }


def test_disabled_hooks_leave_no_trace():
    from mirbft_tpu.testengine.engine import BasicRecorder

    assert not hooks.enabled
    rec = BasicRecorder(4, 2, 4, batch_size=2, seed=0, record=False)
    rec.drain_clients(max_steps=500_000)
    assert hooks.metrics is None and hooks.tracer is None


# ---------------------------------------------------------------------------
# Status fold + chaos integration
# ---------------------------------------------------------------------------


def test_metrics_status_fold():
    from mirbft_tpu.status import metrics_status

    assert metrics_status().enabled is False
    reg = Registry(strict=False)
    reg.counter("mirbft_demo_total").inc(2)
    status = metrics_status(reg)
    assert status.enabled
    assert "mirbft_demo_total" in status.pretty()
    assert json.loads(status.to_json())["enabled"] is True


def test_chaos_recovery_metric_matches_report():
    from mirbft_tpu.chaos.runner import run_scenario
    from mirbft_tpu.chaos.scenarios import smoke_matrix

    scenario = smoke_matrix()[0]  # partition-minority
    reg = Registry()
    result = run_scenario(scenario, seed=0, registry=reg)
    assert result.passed, result.violation
    gauge = reg.gauge("mirbft_chaos_recovery_ms", scenario=scenario.name)
    assert gauge.value == result.counters["recovery_ms"]
    assert 0 < gauge.value <= scenario.recovery_bound_ms
    dropped = reg.counter(
        "mirbft_chaos_dropped_total", scenario=scenario.name
    )
    assert dropped.value == result.counters["partition_drops"] > 0


def test_mangler_drop_and_duplicate_counters():
    from mirbft_tpu.testengine.engine import BasicRecorder
    from mirbft_tpu.testengine.manglers import is_step, percent, rule

    dropper = rule(is_step(), percent(20)).drop()
    doubler = rule(is_step(), percent(20)).duplicate(100)
    rec = BasicRecorder(
        4, 2, 4, batch_size=2, seed=3, record=False,
        manglers=[dropper, doubler],
    )
    rec.drain_clients(max_steps=500_000)
    assert dropper.dropped > 0
    assert doubler.duplicated > 0


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def test_cli_smoke_writes_trace(tmp_path):
    out = tmp_path / "trace.json"
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "mirbft_tpu.obsv",
            "--nodes",
            "4",
            "--clients",
            "2",
            "--reqs",
            "6",
            "--trace",
            str(out),
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr
    assert "phase" in proc.stdout and "p99_ms" in proc.stdout
    trace = json.loads(out.read_text())
    assert any(e["ph"] == "i" for e in trace["traceEvents"])


# ---------------------------------------------------------------------------
# Flow events, per-node traces, merge
# ---------------------------------------------------------------------------


def _flow_records(events):
    return [
        e for e in events if e.get("cat") == "flow" and e["ph"] in ("s", "t", "f")
    ]


def _run_four_node_traces():
    """4-node seeded engine run -> (per-node traces dict, merged trace)."""
    from mirbft_tpu.obsv.merge import merge_traces, split_node_traces
    from mirbft_tpu.testengine.engine import BasicRecorder

    _, tracer = hooks.enable(trace=True)
    try:
        rec = BasicRecorder(4, 4, 30, batch_size=2, seed=0, record=False)
        rec.drain_clients(max_steps=2_000_000)
    finally:
        hooks.disable()
    per_node = split_node_traces(tracer, range(4))
    return per_node, merge_traces(per_node.values())


@pytest.fixture(scope="module")
def four_node_traces():
    return _run_four_node_traces()


def test_flow_events_well_formed_per_node(four_node_traces):
    """Every seq flow a node opens (s) it also finishes (f), and the id
    encodes a unique (epoch, seq_no, bucket) triple."""
    per_node, _ = four_node_traces
    assert set(per_node) == {0, 1, 2, 3}
    for node, trace in per_node.items():
        flows = _flow_records(trace["traceEvents"])
        assert flows, f"node {node} recorded no flow events"
        by_id = {}
        for record in flows:
            by_id.setdefault(record["id"], []).append(record)
        triples = set()
        for flow_id, records in by_id.items():
            if flow_id.startswith(("c.", "e.")):
                # Checkpoint ("c.<seq>") and epoch-change ("e.<epoch>")
                # step flows are promoted at merge, not per-seq triples.
                continue
            epoch, seq, bucket = (int(x) for x in flow_id.split("."))
            assert (epoch, seq, bucket) not in triples
            triples.add((epoch, seq, bucket))
            phases = [r["ph"] for r in records]
            assert phases.count("s") == 1, (node, flow_id, phases)
            assert phases.count("f") == 1, (node, flow_id, phases)
            # The flow id triple matches the milestone metadata.
            assert seq % 4 == bucket  # 4 nodes -> 4 buckets, seq % buckets


def test_merged_trace_connects_three_plus_lanes(four_node_traces):
    """Acceptance: the merged trace is valid Chrome JSON and at least one
    committed seq's flow touches >= 3 distinct node lanes."""
    _, merged = four_node_traces
    # Valid Chrome trace JSON: serializes, every event has the core keys.
    events = json.loads(json.dumps(merged))["traceEvents"]
    for e in events:
        assert "ph" in e and "pid" in e and "ts" in e or e["ph"] == "M"
    flows = _flow_records(events)
    by_id = {}
    for record in flows:
        by_id.setdefault(record["id"], []).append(record)
    assert by_id, "merged trace lost its flow records"
    spanning = [
        flow_id
        for flow_id, records in by_id.items()
        if not flow_id.startswith(("c.", "e."))
        and len({r["pid"] for r in records}) >= 3
    ]
    assert spanning, "no committed seq flow connects >= 3 node lanes"
    # Merged flow hygiene: exactly one s and one f per id, s first f last.
    for flow_id, records in by_id.items():
        records.sort(key=lambda r: r["ts"])
        phases = [r["ph"] for r in records]
        assert phases.count("s") == 1 and phases.count("f") == 1, (
            flow_id,
            phases,
        )
        assert phases[0] == "s" and phases[-1] == "f", (flow_id, phases)
    # Checkpoint step flows got promoted into full s..f flows.
    assert any(flow_id.startswith("c.") for flow_id in by_id)


_MILESTONE_ORDER = {
    "seq.allocated": 0,
    "seq.preprepared": 1,
    "seq.prepared": 2,
    "seq.commit_quorum": 3,
    "seq.committed": 4,
}


def test_merged_trace_milestones_monotonic_per_lane(four_node_traces):
    """On every node lane, each seq's milestones appear in protocol order
    with non-decreasing merged timestamps."""
    _, merged = four_node_traces
    per_lane_seq = {}
    for e in merged["traceEvents"]:
        if e.get("ph") == "i" and e["name"] in _MILESTONE_ORDER:
            key = (e["pid"], e["args"]["seq"])
            per_lane_seq.setdefault(key, []).append(e)
    assert per_lane_seq
    for (pid, seq), events in per_lane_seq.items():
        assert all(e["ts"] >= 0 for e in events)
        ordered = sorted(events, key=lambda e: _MILESTONE_ORDER[e["name"]])
        times = [e["ts"] for e in ordered]
        assert times == sorted(times), (pid, seq, [
            (e["name"], e["ts"]) for e in ordered
        ])


def test_merge_aligns_clock_offsets():
    """Two traces whose events mark the same physical instant in
    different monotonic domains land on the same merged timestamp once
    the reference node's hello-estimated offsets are applied."""
    from mirbft_tpu.obsv.merge import merge_traces

    t0_a = 50_000_000_000
    t0_b = 2_000_000  # a different monotonic domain entirely
    # Physical instant: t0_a + 10ms on A's clock; B's clock reads
    # t0_b + 3ms at that same instant, so A's offset for B is the gap.
    offset_ab = (t0_a + 10_000_000) - (t0_b + 3_000_000)

    def trace(node, t0, ts_us, offsets):
        return {
            "traceEvents": [
                {
                    "name": "clock_sync",
                    "ph": "M",
                    "pid": 0,
                    "tid": 0,
                    "args": {"node": node, "t0_ns": t0, "offsets_ns": offsets},
                },
                {
                    "name": "seq.prepared",
                    "cat": "flow",
                    "ph": "t",
                    "id": "1.5.0",
                    "pid": 0,
                    "tid": node,
                    "ts": ts_us,
                },
            ]
        }

    merged = merge_traces(
        [
            trace(0, t0_a, 10_000.0, {"1": offset_ab}),
            trace(1, t0_b, 3_000.0, {}),
        ]
    )
    flows = _flow_records(merged["traceEvents"])
    assert len(flows) == 2
    assert abs(flows[0]["ts"] - flows[1]["ts"]) < 1e-6
    assert {f["pid"] for f in flows} == {0, 1}
    # The shared-id step pair was promoted to one s and one f.
    assert sorted(f["ph"] for f in flows) == ["f", "s"]


# ---------------------------------------------------------------------------
# Span sampling
# ---------------------------------------------------------------------------


def test_span_sampling_is_deterministic_and_spares_milestones():
    from mirbft_tpu.obsv.trace import SpanSampler

    def spans_kept(seed):
        tracer = Tracer(sampler=SpanSampler(0.25, seed=seed))
        kept = []
        for i in range(100):
            with tracer.span(f"s{i}", tid=0):
                pass
        for e in tracer.events:
            if e["ph"] == "X":
                kept.append(e["name"])
        return kept

    kept_a = spans_kept(seed=0)
    assert len(kept_a) == 25  # stride 4 over 100 spans
    assert kept_a == spans_kept(seed=0)  # reproducible
    assert kept_a != spans_kept(seed=1)  # seed-derived phase

    # Milestones and flow records are never thinned.
    tracer = Tracer(sampler=SpanSampler(0.01, seed=0))
    for seq in range(50):
        tracer.instant("seq.allocated", cat="consensus", tid=0)
        tracer.flow_milestone("seq.allocated", 0, seq, epoch=1, bucket=0)
    assert sum(e["ph"] == "i" for e in tracer.events) == 50
    assert len(_flow_records(tracer.events)) == 50


def test_hooks_expose_sample_rate():
    try:
        _, tracer = hooks.enable(trace=True, sample_rate=0.5, sample_seed=3)
        assert hooks.sample_rate == 0.5
        assert tracer._sampler is not None and tracer._sampler.stride == 2
    finally:
        hooks.disable()
    assert hooks.sample_rate is None


# ---------------------------------------------------------------------------
# Label catalog + cardinality budget
# ---------------------------------------------------------------------------


def test_strict_registry_rejects_undeclared_labels():
    reg = Registry()
    with pytest.raises(KeyError):
        reg.counter("mirbft_wal_appends_total", bogus="x")
    # Declared labels (and subsets) pass.
    reg.counter(
        "mirbft_seq_milestones_total", milestone="seq.prepared",
        epoch="1", bucket="0",
    ).inc()
    reg.counter(
        "mirbft_seq_milestones_total", milestone="seq.committed"
    ).inc()


def test_cardinality_budget_rejects_registration():
    from mirbft_tpu.obsv.metrics import DEFAULT_CARDINALITY, CardinalityError

    reg = Registry()
    for i in range(DEFAULT_CARDINALITY):
        reg.counter("mirbft_chaos_dropped_total", scenario=f"s{i}").inc()
    with pytest.raises(CardinalityError):
        reg.counter("mirbft_chaos_dropped_total", scenario="one-too-many")
    # Existing series stay reachable at the bound.
    assert reg.counter("mirbft_chaos_dropped_total", scenario="s0").value == 1


def test_milestone_degrades_gracefully_over_budget():
    """An epoch/bucket storm past the budget must not crash consensus:
    the counter inc is skipped, the trace instant still lands."""
    from mirbft_tpu.obsv import metrics as metrics_mod

    saved = metrics_mod.CARDINALITY.get("mirbft_seq_milestones_total")
    metrics_mod.CARDINALITY["mirbft_seq_milestones_total"] = 1
    try:
        reg, tracer = hooks.enable(trace=True)
        hooks.milestone("seq.prepared", 0, 1, epoch=1, bucket=0)
        hooks.milestone("seq.prepared", 0, 2, epoch=2, bucket=1)  # over budget
        snap = reg.snapshot()["mirbft_seq_milestones_total"]["series"]
        assert len(snap) == 1
        assert sum(e["ph"] == "i" for e in tracer.events) == 2
    finally:
        hooks.disable()
        metrics_mod.CARDINALITY["mirbft_seq_milestones_total"] = saved


# ---------------------------------------------------------------------------
# Live HTTP endpoints on the runtime node
# ---------------------------------------------------------------------------


def _get(url, timeout=5):
    from urllib.request import urlopen

    with urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode("utf-8")


def test_node_endpoints_round_trip():
    from mirbft_tpu.runtime.config import Config
    from mirbft_tpu.runtime.node import Node, standard_initial_network_state

    metrics, _ = hooks.enable()
    node = None
    try:
        metrics.counter("mirbft_wal_appends_total").inc(3)
        node = Node.start_new(
            Config(id=0, metrics_port=0),
            standard_initial_network_state(1, [0]),
        )
        host, port = node.metrics_address
        base = f"http://{host}:{port}"

        status_code, text = _get(base + "/metrics")
        assert status_code == 200
        assert "# TYPE mirbft_wal_appends_total counter" in text
        assert "mirbft_wal_appends_total 3" in text

        status_code, text = _get(base + "/status")
        assert status_code == 200
        parsed = json.loads(text)
        assert parsed  # valid, non-empty state machine status JSON

        status_code, text = _get(base + "/healthz")
        assert status_code == 200
        assert json.loads(text) == {"ok": True, "node_id": 0, "ready": True}

        from urllib.error import HTTPError

        with pytest.raises(HTTPError) as err:
            _get(base + "/nope")
        assert err.value.code == 404
    finally:
        hooks.disable()
        if node is not None:
            node.stop()
    # Clean shutdown: the port no longer accepts connections.
    import socket as socket_mod

    with pytest.raises(OSError):
        socket_mod.create_connection((host, port), timeout=1).close()


def test_node_endpoint_off_by_default():
    from mirbft_tpu.runtime.config import Config
    from mirbft_tpu.runtime.node import Node, standard_initial_network_state

    node = Node.start_new(
        Config(id=0), standard_initial_network_state(1, [0])
    )
    try:
        assert node.metrics_address is None
        assert node._exporter is None
    finally:
        node.stop()


def test_metrics_endpoint_reports_disabled_hooks():
    from mirbft_tpu.obsv.exporter import ObsvExporter

    assert not hooks.enabled
    exporter = ObsvExporter(
        registry_fn=lambda: hooks.metrics if hooks.enabled else None
    )
    try:
        host, port = exporter.address
        status_code, text = _get(f"http://{host}:{port}/metrics")
        assert status_code == 200
        assert "disabled" in text
    finally:
        exporter.close()


# ---------------------------------------------------------------------------
# Timeline-diff regression gate
# ---------------------------------------------------------------------------


def _milestone_trace(prepare_ms, seqs=40):
    events = []
    for seq in range(1, seqs + 1):
        base = seq * 1000
        for name, offset in (
            ("seq.allocated", 0),
            ("seq.preprepared", 10),
            ("seq.prepared", 10 + prepare_ms),
            ("seq.commit_quorum", 15 + prepare_ms),
        ):
            events.append(
                {
                    "name": name,
                    "ph": "i",
                    "s": "t",
                    "pid": 0,
                    "tid": 0,
                    "ts": 0,
                    "args": {"node": 0, "seq": seq, "sim_ms": base + offset},
                }
            )
    return {"traceEvents": events}


def test_diff_flags_p95_regression_on_traces():
    from mirbft_tpu.obsv.diff import diff_series, extract_series

    a = extract_series(_milestone_trace(prepare_ms=50))
    b = extract_series(_milestone_trace(prepare_ms=100))
    assert a["phase.prepare.p95_ms"] == 50
    report = diff_series(a, b, threshold_pct=10.0)
    assert not report["ok"]
    regressed = {r["series"] for r in report["regressions"]}
    assert "phase.prepare.p95_ms" in regressed

    equal = diff_series(a, dict(a), threshold_pct=10.0)
    assert equal["ok"] and not equal["regressions"]


def test_diff_direction_heuristics():
    from mirbft_tpu.obsv.diff import diff_series

    a = {"committed_reqs_per_sec": 100.0, "rung3_verify_p99_ms": 10.0}
    # Throughput dropped 50%, latency doubled: both regress.
    b = {"committed_reqs_per_sec": 50.0, "rung3_verify_p99_ms": 20.0}
    report = diff_series(a, b, threshold_pct=10.0)
    assert {r["series"] for r in report["regressions"]} == set(a)
    # The same deltas in the *good* direction do not gate.
    report = diff_series(b, a, threshold_pct=10.0)
    assert report["ok"]


def test_diff_cli_verdicts(tmp_path):
    """--diff exits 1 on a >= threshold p95 regression, 0 on an equal
    pair, and emits a machine-readable JSON verdict line."""
    base = {
        "metric": "committed_reqs_per_sec_per_chip",
        "value": 900.0,
        "prepare_p95_ms": 40.0,
        "stages": {"ladder_host": {"status": "ok", "seconds": 12.0}},
        "engine_gauges": {"ladder_host": {"events": 5000, "sim_ms": 800}},
    }
    regressed = dict(base)
    regressed["prepare_p95_ms"] = 80.0
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    eq = tmp_path / "eq.json"
    a.write_text(json.dumps(base))
    b.write_text(json.dumps(regressed))
    eq.write_text(json.dumps(base))

    def run_diff(x, y):
        return subprocess.run(
            [
                sys.executable,
                "-m",
                "mirbft_tpu.obsv",
                "--diff",
                str(x),
                str(y),
                "--threshold",
                "25",
            ],
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=120,
        )

    bad = run_diff(a, b)
    assert bad.returncode == 1, bad.stdout + bad.stderr
    verdict = json.loads(bad.stdout.strip().splitlines()[-1])
    assert verdict["ok"] is False
    assert any(
        r["series"] == "prepare_p95_ms" for r in verdict["regressions"]
    )

    good = run_diff(a, eq)
    assert good.returncode == 0, good.stdout + good.stderr
    verdict = json.loads(good.stdout.strip().splitlines()[-1])
    assert verdict["ok"] is True and not verdict["regressions"]


# ---------------------------------------------------------------------------
# Bounded-queue backpressure telemetry (obsv/bqueue.py)
# ---------------------------------------------------------------------------


def test_bounded_queue_emits_uniform_series():
    """Depth/wait/saturation land under the three mirbft_queue_* names
    with the queue label, and the queue keeps stdlib semantics."""
    import queue as stdlib_queue

    from mirbft_tpu.obsv.bqueue import BoundedQueue

    try:
        metrics, _ = hooks.enable()
        q = BoundedQueue("test.stage", maxsize=2)
        q.put("a")
        q.put("b")
        with pytest.raises(stdlib_queue.Full):
            q.put("c", block=False)  # saturated attempt, still Full
        assert q.get() == "a"
        assert q.get_nowait() == "b"
        with pytest.raises(stdlib_queue.Empty):
            q.get_nowait()

        snap = metrics.snapshot()
        depth = {
            tuple(s["labels"].items()): s["value"]
            for s in snap["mirbft_queue_depth"]["series"]
        }
        assert depth[(("queue", "test.stage"),)] == 0  # after both gets
        wait = snap["mirbft_queue_wait_seconds"]["series"][0]
        assert wait["labels"] == {"queue": "test.stage"}
        assert wait["count"] == 2  # both dequeues observed residency
        sat = snap["mirbft_queue_saturated_total"]["series"][0]
        assert sat["labels"] == {"queue": "test.stage"}
        assert sat["value"] == 1
    finally:
        hooks.disable()


def test_bounded_queue_disabled_is_silent_and_unstamped():
    """With hooks off the queue must not touch any registry, and items
    enqueued while off must not pollute the wait histogram after a
    later enable (their residency spans the enable edge)."""
    from mirbft_tpu.obsv.bqueue import BoundedQueue

    assert not hooks.enabled
    q = BoundedQueue("test.cold", maxsize=4)
    q.put("cold")  # stamp 0.0: no clock read, no series
    try:
        metrics, _ = hooks.enable()
        assert q.get() == "cold"
        snap = metrics.snapshot()
        waits = snap.get("mirbft_queue_wait_seconds", {}).get("series", [])
        assert not any(
            s["labels"] == {"queue": "test.cold"} and s["count"]
            for s in waits
        )
        # The dequeue still updated depth — that is an honest instant.
        depths = {
            s["labels"]["queue"]: s["value"]
            for s in snap["mirbft_queue_depth"]["series"]
        }
        assert depths.get("test.cold") == 0
    finally:
        hooks.disable()


def test_queue_telemetry_rebinds_across_enable_cycles():
    """A long-lived queue's handles follow the registry that hooks
    currently carries (enable/disable/enable with a fresh registry)."""
    from mirbft_tpu.obsv.bqueue import QueueTelemetry

    telemetry = QueueTelemetry("test.longlived")
    try:
        first, _ = hooks.enable()
        telemetry.saturated()
        hooks.disable()
        telemetry.saturated()  # off: dropped, no error
        second, _ = hooks.enable()
        telemetry.saturated()
        get = lambda reg: [
            s["value"]
            for s in reg.snapshot()
            .get("mirbft_queue_saturated_total", {})
            .get("series", [])
            if s["labels"] == {"queue": "test.longlived"}
        ]
        assert get(first) == [1]
        assert get(second) == [1]
    finally:
        hooks.disable()


def test_queue_telemetry_cardinality_degrades_not_crashes():
    """A queue past the documented cardinality budget loses its series
    (all three, atomically) but keeps queueing."""
    from mirbft_tpu.obsv import metrics as metrics_mod
    from mirbft_tpu.obsv.bqueue import BoundedQueue

    saved = {
        name: metrics_mod.CARDINALITY.get(name)
        for name in (
            "mirbft_queue_depth",
            "mirbft_queue_wait_seconds",
            "mirbft_queue_saturated_total",
        )
    }
    metrics_mod.CARDINALITY["mirbft_queue_depth"] = 1
    try:
        metrics, _ = hooks.enable()
        q_ok = BoundedQueue("test.within", maxsize=2)
        q_over = BoundedQueue("test.over", maxsize=2)
        q_ok.put(1)
        q_over.put(2)  # over budget: series dropped, queue works
        assert q_over.get() == 2
        labels = {
            s["labels"]["queue"]
            for s in metrics.snapshot()["mirbft_queue_depth"]["series"]
        }
        assert labels == {"test.within"}
    finally:
        hooks.disable()
        for name, value in saved.items():
            metrics_mod.CARDINALITY[name] = value


def test_hot_path_queues_ride_the_shim():
    """Every bounded hot-path queue goes through the shim: the four
    processor stage queues and the CommitStream apply queue are
    BoundedQueues; the transport peer lanes and the device staging
    buffer (whose data structures cannot be swapped) hold a bare
    QueueTelemetry handle."""
    import inspect

    from mirbft_tpu import app, runtime
    from mirbft_tpu.core import device_tracker
    from mirbft_tpu.runtime import transport as transport_mod

    proc_src = inspect.getsource(runtime.processor)
    for stage in (
        "proc.persist",
        "proc.barrier",
        "proc.transmit",
        "proc.commit",
    ):
        assert f'BoundedQueue("{stage}"' in proc_src, stage
    stream_src = inspect.getsource(app.stream)
    assert 'BoundedQueue("app.apply"' in stream_src
    transport_src = inspect.getsource(transport_mod)
    assert "QueueTelemetry(" in transport_src
    device_src = inspect.getsource(device_tracker)
    assert 'QueueTelemetry("device.ack_stage")' in device_src


# ---------------------------------------------------------------------------
# Tracer open-flow table bound (the flow_milestone leak regression)
# ---------------------------------------------------------------------------


def test_tracer_flow_table_bounded_eviction():
    """Flows that never reach a terminal milestone (censored/dropped
    requests) must not grow the open-flow table without bound; evictions
    are counted on the tracer and the registry."""
    try:
        metrics, tracer = hooks.enable(trace=True)
        tracer._max_open_flows = 4  # small bound for the test
        for seq in range(10):
            tracer.flow_milestone(
                "seq.allocated", 0, seq, epoch=1, bucket=0
            )
        assert len(tracer._flows) == 4
        assert tracer.abandoned_flows == 6
        snap = metrics.snapshot()
        assert (
            snap["mirbft_flow_abandoned_total"]["series"][0]["value"] == 6
        )
        # Terminal milestones still close surviving flows normally.
        tracer.flow_milestone("seq.committed", 0, 9)
        assert (0, 9) not in tracer._flows
    finally:
        hooks.disable()


def test_tracer_flow_eviction_without_registry_still_counts():
    tracer = Tracer(max_open_flows=2)
    for seq in range(5):
        tracer.flow_milestone("seq.allocated", 0, seq, epoch=1, bucket=0)
    assert len(tracer._flows) == 2
    assert tracer.abandoned_flows == 3


# ---------------------------------------------------------------------------
# Bucket backlog gauges + imbalance in status
# ---------------------------------------------------------------------------


def test_imbalance_ratio_exact():
    from mirbft_tpu.status import _imbalance_ratio

    assert _imbalance_ratio([]) == 0.0
    assert _imbalance_ratio([0, 0, 0, 0]) == 0.0
    assert _imbalance_ratio([2, 2, 2, 2]) == 1.0
    assert _imbalance_ratio([1, 2, 3, 10]) == 4.0  # median 2.5, max 10
    assert _imbalance_ratio([0, 0, 0, 6]) == 6.0  # median floored at 1


def test_bucket_backlog_gauges_and_status_fold():
    """A seeded run exports mirbft_bucket_backlog per bucket, and the
    status fold reports the backlog vector + imbalance ratio."""
    from mirbft_tpu.status import state_machine_status
    from mirbft_tpu.testengine.engine import BasicRecorder

    try:
        metrics, _ = hooks.enable()
        rec = BasicRecorder(4, 2, 6, batch_size=2, seed=0, record=False)
        rec.drain_clients(max_steps=1_000_000)
        snap = metrics.snapshot()
        series = snap["mirbft_bucket_backlog"]["series"]
        assert series, "no bucket backlog gauges exported"
        buckets = {s["labels"]["bucket"] for s in series}
        assert len(buckets) == len(series)  # one series per bucket
    finally:
        hooks.disable()

    status = state_machine_status(rec.machines[0])
    assert status.bucket_backlog  # vector present (all committed -> 0s)
    assert all(n == 0 for n in status.bucket_backlog)
    assert status.bucket_imbalance == 0.0
    assert "backlog:" in status.pretty()
    assert "imbalance max/median" in status.pretty()
