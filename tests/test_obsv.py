"""Observability subsystem (mirbft_tpu/obsv): registry semantics, the
Prometheus/JSON expositions, Chrome trace validity, the consensus
timeline profiler on a seeded run, and the chaos-metrics integration.

Every test that enables the process-global hooks disables them in a
``finally`` — a leaked enabled state would silently instrument (and
slow) every later test in the session.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from mirbft_tpu.obsv import hooks
from mirbft_tpu.obsv.metrics import (
    NULL_COUNTER,
    NULL_GAUGE,
    NULL_HISTOGRAM,
    Registry,
    null_registry,
)
from mirbft_tpu.obsv.timeline import PHASES, TimelineProfiler
from mirbft_tpu.obsv.trace import Tracer

REPO = Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------------
# Registry semantics
# ---------------------------------------------------------------------------


def test_counter_gauge_histogram_basics():
    reg = Registry(strict=False)
    c = reg.counter("c_total")
    c.inc()
    c.inc(4)
    assert c.value == 5
    assert reg.counter("c_total") is c  # same series, same handle

    g = reg.gauge("g")
    g.set(2.5)
    assert g.value == 2.5

    h = reg.histogram("h_seconds", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 50.0):
        h.observe(v)
    assert h.count == 3
    assert h.sum == pytest.approx(50.55)
    # 0.05 <= 0.1; 0.5 <= 1.0; 50 lands only in +Inf (count/sum).
    assert h.bucket_counts == [1, 1]


def test_labels_key_distinct_series():
    reg = Registry(strict=False)
    a = reg.counter("x_total", path="device")
    b = reg.counter("x_total", path="host")
    a.inc(3)
    b.inc(1)
    assert a is not b
    # kwarg order must not matter for series identity.
    assert reg.counter("x_total", path="device") is a
    snap = reg.snapshot()["x_total"]
    assert snap["kind"] == "counter"
    values = {
        s["labels"]["path"]: s["value"] for s in snap["series"]
    }
    assert values == {"device": 3, "host": 1}


def test_strict_registry_rejects_uncataloged_names():
    reg = Registry()  # strict by default
    with pytest.raises(KeyError):
        reg.counter("mirbft_not_a_real_metric_total")
    # Catalog names pass.
    reg.counter("mirbft_wal_appends_total").inc()


def test_kind_mismatch_raises():
    reg = Registry(strict=False)
    reg.counter("m")
    with pytest.raises(TypeError):
        reg.gauge("m")


def test_null_registry_is_shared_noop_singletons():
    reg = null_registry()
    assert reg is null_registry()
    assert reg.counter("anything", a="b") is NULL_COUNTER
    assert reg.gauge("anything") is NULL_GAUGE
    assert reg.histogram("anything") is NULL_HISTOGRAM
    # No-ops: nothing accumulates, nothing raises.
    NULL_COUNTER.inc(10)
    NULL_GAUGE.set(3)
    NULL_HISTOGRAM.observe(1.0)
    assert NULL_COUNTER.value == 0
    assert reg.snapshot() == {}
    assert reg.prometheus_text() == ""


def test_prometheus_exposition_format():
    reg = Registry(strict=False)
    reg.counter("req_total", path="device").inc(7)
    h = reg.histogram("lat_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = reg.prometheus_text()
    lines = text.splitlines()
    assert "# TYPE req_total counter" in lines
    assert 'req_total{path="device"} 7' in lines
    assert "# TYPE lat_seconds histogram" in lines
    # Buckets are cumulative and +Inf equals the count.
    assert 'lat_seconds_bucket{le="0.1"} 1' in lines
    assert 'lat_seconds_bucket{le="1.0"} 2' in lines
    assert 'lat_seconds_bucket{le="+Inf"} 3' in lines
    assert "lat_seconds_count 3" in lines
    assert any(line.startswith("lat_seconds_sum ") for line in lines)
    assert text.endswith("\n")


def test_json_dump_round_trips():
    reg = Registry(strict=False)
    reg.gauge("g", scenario="a b\"c").set(1)
    parsed = json.loads(reg.to_json())
    assert parsed["g"]["series"][0]["labels"] == {"scenario": 'a b"c'}


# ---------------------------------------------------------------------------
# Tracer / Chrome trace validity
# ---------------------------------------------------------------------------


def _assert_well_nested(events):
    """Per tid, any two X spans either nest or are disjoint."""
    by_tid = {}
    for e in events:
        if e["ph"] == "X":
            by_tid.setdefault(e["tid"], []).append(
                (e["ts"], e["ts"] + e["dur"])
            )
    for spans in by_tid.values():
        for i, (s1, e1) in enumerate(spans):
            for s2, e2 in spans[i + 1 :]:
                overlap = max(s1, s2) < min(e1, e2)
                contained = (s1 <= s2 and e2 <= e1) or (
                    s2 <= s1 and e1 <= e2
                )
                assert not overlap or contained, (spans,)


def test_chrome_trace_is_valid_and_nested(tmp_path):
    tracer = Tracer()
    tracer.name_thread(0, "node 0")
    with tracer.span("outer", cat="t", tid=0):
        with tracer.span("inner", cat="t", tid=0):
            pass
        tracer.instant("mark", cat="consensus", tid=0, args={"seq": 1})
    with tracer.span("later", cat="t", tid=0):
        pass

    out = tmp_path / "trace.json"
    tracer.write(str(out))
    trace = json.loads(out.read_text())
    events = trace["traceEvents"]

    meta = [e for e in events if e["ph"] == "M"]
    assert meta and meta[0]["args"]["name"] == "node 0"
    xs = [e for e in events if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"outer", "inner", "later"}
    for e in xs:
        assert e["ts"] >= 0 and e["dur"] >= 0
    inst = [e for e in events if e["ph"] == "i"]
    assert inst[0]["s"] == "t" and inst[0]["args"]["seq"] == 1
    # Monotonic source: 'later' starts after 'outer' ends.
    by_name = {e["name"]: e for e in xs}
    assert (
        by_name["later"]["ts"]
        >= by_name["outer"]["ts"] + by_name["outer"]["dur"]
    )
    _assert_well_nested(events)


def test_complete_records_backdated_span():
    tracer = Tracer()
    tracer._t0_ns -= 300_000_000  # pretend 300ms of tracer lifetime
    tracer.complete("flush", cat="crypto", tid=-1, dur_s=0.25)
    (e,) = tracer.events
    assert e["ph"] == "X"
    assert e["dur"] == pytest.approx(250_000, rel=0.01)  # µs
    assert e["ts"] >= 0


def test_complete_clamps_to_tracer_birth():
    tracer = Tracer()
    # A duration longer than the tracer has been alive must not produce
    # a negative ts (invalid Chrome trace); it is clamped to birth.
    tracer.complete("early", dur_s=10.0)
    (e,) = tracer.events
    assert e["ts"] >= 0
    assert e["dur"] < 10.0 * 1e6


# ---------------------------------------------------------------------------
# Timeline profiler
# ---------------------------------------------------------------------------


def test_timeline_profiler_synthetic_edges():
    def inst(name, node, seq, t):
        return {
            "ph": "i",
            "name": name,
            "args": {"node": node, "seq": seq, "sim_ms": t},
        }

    events = [
        inst("seq.allocated", 0, 1, 0),
        inst("seq.preprepared", 0, 1, 10),
        inst("seq.prepared", 0, 1, 40),
        inst("seq.commit_quorum", 0, 1, 70),
        inst("ckpt.stable", 0, 20, 500),
        # Second node: no checkpoint, partial lifecycle.
        inst("seq.allocated", 1, 1, 5),
        inst("seq.preprepared", 1, 1, 25),
    ]
    prof = TimelineProfiler.from_events(events)
    stats = {s.phase: s for s in prof.stats()}
    assert stats["preprepare"].count == 2
    assert sorted(prof.phase_samples()["preprepare"]) == [10, 20]
    assert stats["prepare"].p50 == 30
    assert stats["commit"].p50 == 30
    assert stats["checkpoint"].count == 1
    assert stats["checkpoint"].p50 == 430  # 500 - 70


def test_timeline_profiler_on_seeded_run():
    from mirbft_tpu.testengine.engine import BasicRecorder

    metrics, tracer = hooks.enable(trace=True)
    try:
        rec = BasicRecorder(4, 4, 30, batch_size=2, seed=0, record=False)
        rec.drain_clients(max_steps=2_000_000)
    finally:
        hooks.disable()

    prof = TimelineProfiler.from_tracer(tracer)
    stats = {s.phase: s for s in prof.stats()}
    # 4 clients x 30 reqs / batch 2 = 60 seqs = 3 checkpoint windows
    # (ci=20): enough that stable checkpoints must circulate, so every
    # phase — including checkpoint — collects samples.
    assert set(stats) == set(PHASES)
    for s in stats.values():
        assert s.count > 0
        assert 0 <= s.p50 <= s.p95 <= s.p99
    # The instrumented state machine fed the registry too.
    snap = metrics.snapshot()
    assert snap["mirbft_sm_events_total"]["series"]
    assert snap["mirbft_sm_apply_seconds"]["series"][0]["count"] > 0
    # And the trace round-trips through the Chrome JSON shape.
    prof2 = TimelineProfiler.from_chrome_trace(tracer.chrome_trace())
    assert {s.phase: s.count for s in prof2.stats()} == {
        s.phase: s.count for s in prof.stats()
    }


def test_disabled_hooks_leave_no_trace():
    from mirbft_tpu.testengine.engine import BasicRecorder

    assert not hooks.enabled
    rec = BasicRecorder(4, 2, 4, batch_size=2, seed=0, record=False)
    rec.drain_clients(max_steps=500_000)
    assert hooks.metrics is None and hooks.tracer is None


# ---------------------------------------------------------------------------
# Status fold + chaos integration
# ---------------------------------------------------------------------------


def test_metrics_status_fold():
    from mirbft_tpu.status import metrics_status

    assert metrics_status().enabled is False
    reg = Registry(strict=False)
    reg.counter("mirbft_demo_total").inc(2)
    status = metrics_status(reg)
    assert status.enabled
    assert "mirbft_demo_total" in status.pretty()
    assert json.loads(status.to_json())["enabled"] is True


def test_chaos_recovery_metric_matches_report():
    from mirbft_tpu.chaos.runner import run_scenario
    from mirbft_tpu.chaos.scenarios import smoke_matrix

    scenario = smoke_matrix()[0]  # partition-minority
    reg = Registry()
    result = run_scenario(scenario, seed=0, registry=reg)
    assert result.passed, result.violation
    gauge = reg.gauge("mirbft_chaos_recovery_ms", scenario=scenario.name)
    assert gauge.value == result.counters["recovery_ms"]
    assert 0 < gauge.value <= scenario.recovery_bound_ms
    dropped = reg.counter(
        "mirbft_chaos_dropped_total", scenario=scenario.name
    )
    assert dropped.value == result.counters["partition_drops"] > 0


def test_mangler_drop_and_duplicate_counters():
    from mirbft_tpu.testengine.engine import BasicRecorder
    from mirbft_tpu.testengine.manglers import is_step, percent, rule

    dropper = rule(is_step(), percent(20)).drop()
    doubler = rule(is_step(), percent(20)).duplicate(100)
    rec = BasicRecorder(
        4, 2, 4, batch_size=2, seed=3, record=False,
        manglers=[dropper, doubler],
    )
    rec.drain_clients(max_steps=500_000)
    assert dropper.dropped > 0
    assert doubler.duplicated > 0


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def test_cli_smoke_writes_trace(tmp_path):
    out = tmp_path / "trace.json"
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "mirbft_tpu.obsv",
            "--nodes",
            "4",
            "--clients",
            "2",
            "--reqs",
            "6",
            "--trace",
            str(out),
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr
    assert "phase" in proc.stdout and "p99_ms" in proc.stdout
    trace = json.loads(out.read_text())
    assert any(e["ph"] == "i" for e in trace["traceEvents"])
