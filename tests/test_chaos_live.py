"""Live-cluster chaos tier (chaos/live.py): the fault campaign against
the real TCP runtime — real Nodes, real sockets, real fsyncs.

Three layers:

- A tier-1 smoke pass over LIVE_SMOKE_NAMES (one crash+restart, one
  partition+heal) under a hard wall-clock budget, so every CI run
  exercises a real cluster surviving a real fault.
- A tier-1 teardown-leak gate: 100 boot/teardown cycles of Node +
  TcpTransport on fixed ports.  Node.stop() joins the serializer and
  TcpTransport.close() joins accept/read/sender threads; this test is
  the regression net — before those joins existed, each cycle leaked a
  daemon thread parked in recv and the 100th cycle ran alongside 100
  zombies.
- The full live matrix (epoch-change-targeted leader isolation, signed
  mode, failing fsyncs) behind ``-m chaos`` with the long tail behind
  ``slow``, mirroring tests/test_chaos.py's deterministic campaign.
"""

import dataclasses
import threading
import time

import pytest

from mirbft_tpu import pb
from mirbft_tpu.chaos import (
    LIVE_SMOKE_NAMES,
    live_adversary_matrix,
    live_matrix,
    run_live_campaign,
    run_live_scenario,
)
from mirbft_tpu.runtime import Config, Node, TcpTransport
from mirbft_tpu.runtime.node import standard_initial_network_state

BY_NAME = {s.name: s for s in live_matrix()}
ADV_BY_NAME = {s.name: s for s in live_adversary_matrix()}

# Every thread the runtime plane spawns carries one of these name
# prefixes (node.py / transport.py / live.py / processor.py /
# storage.py); the leak gate counts them.
RUNTIME_THREAD_PREFIXES = (
    "mirbft-serializer-",
    "tcp-",
    "live-consumer-",
    "proc-pipe-",
    "storage-sync-",
)


def _runtime_threads() -> list:
    return [
        t
        for t in threading.enumerate()
        if t.name.startswith(RUNTIME_THREAD_PREFIXES)
    ]


def _bind_retrying(node_id: int, port: int) -> TcpTransport:
    """Bind a transport, retrying through TIME_WAIT on a fixed port (the
    same discipline live.py's LiveReplica._bind uses for restarts)."""
    deadline = time.monotonic() + 10
    while True:
        try:
            return TcpTransport(node_id, port=port, dial_timeout=1.0)
        except OSError:
            if port == 0 or time.monotonic() >= deadline:
                raise
            time.sleep(0.02)


# ---------------------------------------------------------------------------
# Tier-1: live smoke under a wall-clock budget
# ---------------------------------------------------------------------------


@pytest.mark.chaos
@pytest.mark.parametrize("name", LIVE_SMOKE_NAMES)
def test_live_smoke_scenario_survives_real_fault(name):
    """A real loopback cluster absorbs the fault, recovers within the
    scenario's bound, and the whole run fits a hard wall-clock budget —
    the tier-1 proof that the campaign works against real sockets, not
    just the simulator."""
    start = time.monotonic()
    result = run_live_scenario(BY_NAME[name], seed=0, budget_s=60.0)
    elapsed = time.monotonic() - start
    assert result.passed, f"{name}: {result.violation}"
    assert result.commits > 0
    # Real TCP connections were dialed — this ran on sockets.
    assert result.counters["tcp_connects"] > 0
    assert elapsed < 75.0, f"{name} blew the wall-clock budget: {elapsed:.1f}s"


@pytest.mark.chaos
def test_live_smoke_leaves_no_runtime_threads():
    """After a live scenario tears down, no serializer/transport/consumer
    threads may linger — the smoke pass doubles as a teardown audit."""
    run_live_scenario(BY_NAME["partition-minority"], seed=1, budget_s=60.0)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and _runtime_threads():
        time.sleep(0.05)
    leaked = _runtime_threads()
    assert not leaked, f"leaked threads: {[t.name for t in leaked]}"


# ---------------------------------------------------------------------------
# Tier-1: 100 start/stop cycles leak nothing and rebind their ports
# ---------------------------------------------------------------------------


def test_hundred_node_transport_cycles_leak_free():
    """100 boot/teardown cycles of a real two-node cluster: every cycle
    re-binds the SAME ports (teardown must release them all the way to
    the kernel) and the thread census at the end matches the start
    (Node.stop() joins the serializer; TcpTransport.close() joins the
    accept, read, and sender threads — a daemon thread parked in recv
    would otherwise survive and accumulate, 1 zombie per cycle)."""
    baseline = len(_runtime_threads())
    state = standard_initial_network_state(2, [1])
    port_a = port_b = 0
    for cycle in range(100):
        ta = _bind_retrying(0, port_a)
        tb = _bind_retrying(1, port_b)
        port_a, port_b = ta.address[1], tb.address[1]
        node_a = Node.start_new(Config(id=0), state)
        node_b = Node.start_new(Config(id=1), state)
        ta.serve(node_a)
        tb.serve(node_b)
        ta.connect(1, tb.address)
        tb.connect(0, ta.address)
        # One real frame each way: forces a dial, an accept, and a read
        # thread on both sides, so teardown has the full thread set to
        # reap.
        ta.link().send(1, pb.Msg(type=pb.Suspect(epoch=0)))
        tb.link().send(0, pb.Msg(type=pb.Suspect(epoch=0)))
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            sent_a = ta.counters()["peers"].get(1, {}).get("sent", 0)
            sent_b = tb.counters()["peers"].get(0, {}).get("sent", 0)
            if sent_a >= 1 and sent_b >= 1:
                break
            time.sleep(0.005)
        node_a.stop()
        node_b.stop()
        ta.close()
        tb.close()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and len(_runtime_threads()) > baseline:
        time.sleep(0.05)
    residue = _runtime_threads()
    assert len(residue) <= baseline, (
        f"thread leak after 100 cycles: {[t.name for t in residue]}"
    )


# ---------------------------------------------------------------------------
# Chaos tier: epoch-change-targeted and signed-mode live scenarios
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_live_leader_isolation_forces_real_epoch_change():
    """Isolating leader 0 at the socket level past the suspect timeout
    must drive the surviving trio through a real epoch change — proven
    by the obsv epoch.active milestone, not just by liveness."""
    result = run_live_scenario(
        BY_NAME["leader-isolation-epoch-change"], seed=1, budget_s=60.0
    )
    assert result.passed, result.violation
    assert result.counters["epoch"] >= 1
    assert result.counters["epoch_active_events"] >= 1
    assert result.commits > 0


@pytest.mark.chaos
def test_live_signed_mode_verifier_death_recovers():
    """Signed mode over real sockets: the verifier device dies mid-run,
    the breaker trips to the host oracle, commits continue, and the
    forged-request probe is still rejected (asserted inside the run)."""
    result = run_live_scenario(
        BY_NAME["signed-verifier-dies"], seed=2, budget_s=60.0
    )
    assert result.passed, result.violation
    assert result.counters["sig_device_errors"] >= 1
    assert result.counters["sig_fallbacks"] >= 1
    assert result.commits > 0


# ---------------------------------------------------------------------------
# Byzantine adversaries over real sockets (frame-rewriting proxies)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_live_adversary_corruption_rejected_on_real_sockets():
    """Corrupted proposal deliveries over real TCP: signed ingress must
    reject every rewrite (rejections == corruptions, the 100% bar the
    corruption invariant enforces) while honest copies still commit."""
    result = run_live_scenario(
        ADV_BY_NAME["corrupt-propose-signed"], seed=0, budget_s=60.0
    )
    assert result.passed, result.violation
    assert result.counters["corrupted"] > 0
    assert result.counters["rejections"] == result.counters["corrupted"]
    assert result.commits > 0


@pytest.mark.chaos
def test_live_adversary_flood_absorbed_on_real_sockets():
    """Duplication flood through the wire proxies and the client seam:
    dedup must commit exactly once with a bounded request store (audited
    inside the live driver's flood check)."""
    result = run_live_scenario(
        ADV_BY_NAME["flood-duplicate-proposes"], seed=0, budget_s=60.0
    )
    assert result.passed, result.violation
    assert result.counters["flooded"] > 0
    assert result.commits > 0


@pytest.mark.chaos
def test_live_expect_epoch_change_rejects_boot_epoch():
    """Live regression for the epoch-baseline hole: live clusters also
    boot into epoch 1 and fire epoch.active milestones for it, so a quiet
    run must FAIL an expect_epoch_change scenario rather than pass on
    boot telemetry."""
    quiet = dataclasses.replace(
        BY_NAME["partition-minority"],
        name="quiet-expect-epoch-change",
        partitions=(),
        expect_epoch_change=True,
    )
    result = run_live_scenario(quiet, seed=0, budget_s=60.0)
    assert not result.passed
    assert "boot epoch" in result.violation


@pytest.mark.chaos
@pytest.mark.slow
def test_live_full_campaign():
    """The whole live matrix — crash, partition, loss, leader isolation,
    signed mode, failing fsyncs — against real clusters."""
    campaign = run_live_campaign(seed=0)
    assert campaign.passed, campaign.report()


@pytest.mark.chaos
@pytest.mark.slow
def test_live_adversary_campaign():
    """All four attack families against real TCP clusters: corrupting,
    equivocating, censoring, and flooding leaders behind frame-rewriting
    socket proxies (``python -m mirbft_tpu.chaos --live --adversary``)."""
    campaign = run_live_campaign(live_adversary_matrix(), seed=0)
    assert campaign.passed, campaign.report()
