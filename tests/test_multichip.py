"""Gate for the multi-chip crypto plane: the sharded digest + quorum-tally
step compiles and runs on the 8-device virtual CPU mesh (conftest), and the
graft entry points work (VERDICT r1 item 3)."""

import hashlib
import sys

import jax
import numpy as np
import pytest

from mirbft_tpu.ops.batching import pack_preimages
from mirbft_tpu.parallel.sharding import (
    make_mesh,
    sharded_quorum_tally,
    sharded_sha256,
)


needs_8 = pytest.mark.skipif(
    len(jax.devices("cpu")) < 8, reason="needs 8 virtual cpu devices"
)


@needs_8
def test_sharded_sha256_matches_hashlib():
    mesh = make_mesh(8)
    messages = [bytes([i]) * (i + 1) for i in range(16)]
    packed = pack_preimages(messages, batch_floor=8)
    digest = sharded_sha256(mesh)
    words = np.asarray(digest(packed.blocks, packed.n_blocks))
    for i, msg in enumerate(messages):
        assert words[i].astype(">u4").tobytes() == hashlib.sha256(msg).digest()


@needs_8
def test_sharded_quorum_tally():
    mesh = make_mesh(8)
    tally = sharded_quorum_tally(mesh)
    votes = np.zeros((8, 4), dtype=np.int8)
    votes[:6, 0] = 1  # 6 votes -> quorum at threshold 6
    votes[:5, 1] = 1  # 5 votes -> no quorum
    votes[:, 2] = 1  # unanimous
    mask = np.asarray(tally(votes, threshold=6))
    assert list(mask) == [True, False, True, False]


@needs_8
@pytest.mark.slow
def test_sharded_ed25519_verify_matches_single_device():
    """Signature verification sharded over the 8-device mesh: shard results
    must equal the single-device ladder, accepting valid and rejecting
    corrupted signatures."""
    from mirbft_tpu.crypto import ed25519_host as host
    from mirbft_tpu.ops import ed25519 as k
    from mirbft_tpu.parallel.sharding import sharded_ed25519_verify

    rows = []
    for i in range(8):
        seed = bytes([i]) * 32
        msg = b"multichip-%d" % i
        pk, sig = host.public_key(seed), host.sign(seed, msg)
        if i % 2:
            msg = msg + b"!"  # corrupt half of them
        row = k.marshal_signature(pk, msg, sig)
        assert row is not None
        rows.append(row)
    s_bits, k_bits, neg_a, r_aff = k.pack_rows(rows, batch_floor=8)

    mesh = make_mesh(8)
    sharded = sharded_ed25519_verify(mesh)
    got = np.asarray(sharded(s_bits, k_bits, neg_a, r_aff))
    single = np.asarray(k._ladder(s_bits, k_bits, neg_a, r_aff))
    assert got.tolist() == single.tolist() == [i % 2 == 0 for i in range(8)]


@needs_8
def test_dryrun_multichip_entry_point():
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as graft

    graft.dryrun_multichip(8)


def test_entry_point_compiles():
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as graft

    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (args[0].shape[0], 8)
