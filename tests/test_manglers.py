"""Mangler DSL + the reference's fault-scenario matrix (VERDICT r2 item 4;
reference: testengine/manglers.go, mirbft_test.go:68-222): jitter at 30 and
1000 ms, 75% duplication, 70% RequestAck loss from two nodes, targeted
drops, crash-and-restart, and the DSL's matcher/temporal semantics."""

import pytest

from mirbft_tpu import pb
from mirbft_tpu.testengine import BasicRecorder
from mirbft_tpu.testengine.manglers import (
    after_events,
    event_type,
    from_client,
    from_source,
    is_step,
    msg_type,
    once,
    percent,
    rule,
    to_node,
    until_events,
    with_seq_no,
)


def chains(r):
    return {n: r.node_states[n].app_chain for n in range(r.node_count)}


def all_agree(r, nodes=None):
    values = {
        r.node_states[n].app_chain
        for n in (nodes if nodes is not None else range(r.node_count))
    }
    return len(values) == 1


# ---------------------------------------------------------------------------
# DSL unit semantics
# ---------------------------------------------------------------------------


def test_predicates_match_expected_events():
    r = BasicRecorder(node_count=2, client_count=1, reqs_per_client=1)
    step = pb.StateEvent(
        type=pb.EventStep(
            source=1,
            msg=pb.Msg(
                type=pb.Prepare(seq_no=7, epoch=0, digest=b"\xcc" * 32)
            ),
        )
    )
    tick = pb.StateEvent(type=pb.EventTick())

    assert is_step()(r, 0, 0, step) and not is_step()(r, 0, 0, tick)
    assert msg_type("Prepare")(r, 0, 0, step)
    assert not msg_type("Commit")(r, 0, 0, step)
    assert event_type("EventTick")(r, 0, 0, tick)
    assert from_source(1)(r, 0, 0, step) and not from_source(0)(r, 0, 0, step)
    assert to_node(0)(r, 0, 0, step) and not to_node(1)(r, 0, 0, step)
    assert with_seq_no(5, 8)(r, 0, 0, step)
    assert not with_seq_no(8, 9)(r, 0, 0, step)

    ack = pb.StateEvent(
        type=pb.EventStep(
            source=0,
            msg=pb.Msg(type=pb.RequestAck(client_id=4, req_no=0, digest=b"d")),
        )
    )
    assert from_client(4)(r, 0, 0, ack) and not from_client(5)(r, 0, 0, ack)


def test_temporal_combinators():
    r = BasicRecorder(node_count=1, client_count=1, reqs_per_client=1)
    event = pb.StateEvent(type=pb.EventTick())

    pred = after_events(2)
    assert [pred(r, 0, 0, event) for _ in range(4)] == [
        False, False, True, True,
    ]
    pred = until_events(2)
    assert [pred(r, 0, 0, event) for _ in range(4)] == [
        True, True, False, False,
    ]
    pred = once()
    assert [pred(r, 0, 0, event) for _ in range(3)] == [True, False, False]


def test_drop_delay_duplicate_verdicts():
    r = BasicRecorder(node_count=1, client_count=1, reqs_per_client=1)
    step = pb.StateEvent(
        type=pb.EventStep(source=0, msg=pb.Msg(type=pb.Suspect(epoch=0)))
    )
    tick = pb.StateEvent(type=pb.EventTick())

    drop = rule(is_step()).drop()
    assert drop(r, 5, 0, step) is None
    assert drop(r, 5, 0, tick) == (5, 0, tick)

    delay = rule(is_step()).delay(100)
    assert delay(r, 5, 0, step) == (105, 0, step)

    dup = rule(is_step()).duplicate(50)
    verdict = dup(r, 5, 0, step)
    assert isinstance(verdict, list) and len(verdict) == 2
    (w1, _, e1), (w2, _, e2) = verdict
    assert w1 == 5 and 6 <= w2 <= 55 and e1 is e2 is step

    jit = rule(is_step()).jitter(30)
    w, _, _ = jit(r, 5, 0, step)
    assert 5 <= w <= 35


# ---------------------------------------------------------------------------
# Reference scenario matrix
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("jitter_ms", [30, 1000], ids=["30ms", "1000ms"])
def test_jitter(jitter_ms):
    """Reference: mirbft_test.go's 30ms and 1000ms jitter runs."""
    r = BasicRecorder(
        node_count=4,
        client_count=2,
        reqs_per_client=10,
        manglers=[rule(is_step()).jitter(jitter_ms)],
    )
    r.drain_clients(max_steps=600000)
    assert all_agree(r)


def test_75pct_duplication():
    """Reference: 75% of messages duplicated (delayed echo)."""
    r = BasicRecorder(
        node_count=4,
        client_count=2,
        reqs_per_client=10,
        manglers=[rule(is_step(), percent(75)).duplicate(300)],
    )
    r.drain_clients(max_steps=600000)
    assert all_agree(r)
    for n in range(4):
        committed = [(c, q) for (c, q, _s) in r.node_states[n].committed_reqs]
        assert len(committed) == len(set(committed)), "duplicate commit!"


def test_70pct_ack_loss_from_two_nodes():
    """Reference: 70% RequestAck loss from nodes 1 and 2 — fetch/forward
    machinery must still complete every request."""
    r = BasicRecorder(
        node_count=4,
        client_count=2,
        reqs_per_client=10,
        manglers=[
            rule(
                msg_type("RequestAck"), from_source(1, 2), percent(70)
            ).drop()
        ],
    )
    r.drain_clients(max_steps=600000)
    assert all_agree(r)


def test_crash_and_restart_dsl():
    """Crash node 1 after 30 messages reach it; reboot from its durable
    state 5s later; the network converges with it."""
    r = BasicRecorder(
        node_count=4,
        client_count=2,
        reqs_per_client=10,
        manglers=[
            rule(to_node(1), is_step(), after_events(30), once())
            .crash_and_restart_after(5000)
        ],
    )
    r.drain_clients(max_steps=600000)
    assert all_agree(r)


def test_restart_boot_sequence_immune_to_manglers():
    """Boot lifecycle events bypass manglers: a node-scoped jitter (which
    would reorder Initialize/Load/Complete) combined with crash-and-restart
    must not corrupt the reboot."""
    r = BasicRecorder(
        node_count=4,
        client_count=1,
        reqs_per_client=10,
        manglers=[
            rule(to_node(1), is_step(), after_events(30), once())
            .crash_and_restart_after(5000),
            rule(to_node(1)).jitter(30),
        ],
    )
    r.drain_clients(max_steps=600000)
    assert all_agree(r)


# ---------------------------------------------------------------------------
# Adversary verbs: predicate composition + campaign determinism
# ---------------------------------------------------------------------------


def exactly_once(r):
    for n in range(r.node_count):
        committed = [(c, q) for (c, q, _s) in r.node_states[n].committed_reqs]
        assert len(committed) == len(set(committed)), "duplicate commit!"


def test_corrupt_composes_with_percent():
    """corrupt() rewrites only the sampled subset: 15% of Prepare/Commit
    digests are bit-flipped in flight, and quorum redundancy absorbs every
    one without a fork or duplicate commit."""
    mangler = rule(msg_type("Prepare", "Commit"), percent(15)).corrupt()
    r = BasicRecorder(
        node_count=4,
        client_count=2,
        reqs_per_client=10,
        manglers=[mangler],
    )
    r.drain_clients(max_steps=600000)
    assert all_agree(r)
    assert mangler.corrupted > 0
    exactly_once(r)


def test_equivocate_composes_with_seq_no():
    """equivocate() scoped by with_seq_no forges only the windowed
    Preprepares toward the victim; the honest majority commits the real
    batches and the victim catches up without ever committing a variant."""
    mangler = rule(msg_type("Preprepare"), with_seq_no(1, 3)).equivocate((3,))
    r = BasicRecorder(
        node_count=4,
        client_count=2,
        reqs_per_client=10,
        manglers=[mangler],
    )
    r.drain_clients(max_steps=600000)
    assert all_agree(r)
    assert mangler.equivocated > 0
    assert all(1 <= seq <= 3 for (_epoch, seq) in mangler.variants)
    exactly_once(r)


def test_censor_composes_with_from_client():
    """censor() scoped by to_node + from_client suppresses only the victim
    client's request traffic into the censoring node — and every censored
    (client, req_no) pair still commits once the window expires (the fetch
    machinery retries past it; a censoring *leader* needs bucket rotation,
    which the chaos censor scenarios exercise).  The temporal predicate
    composes left to right: until_events counts only events the
    to_node/from_client predicates already matched."""
    mangler = rule(to_node(1), from_client(4), until_events(8)).censor()
    r = BasicRecorder(
        node_count=4,
        client_count=2,
        reqs_per_client=6,
        manglers=[mangler],
    )
    r.drain_clients(max_steps=600000)
    assert all_agree(r)
    assert mangler.censored > 0
    assert mangler.censored_pairs
    assert all(cid == 4 for (cid, _q) in mangler.censored_pairs)
    for n in range(4):
        committed = {(c, q) for (c, q, _s) in r.node_states[n].committed_reqs}
        assert mangler.censored_pairs <= committed
    exactly_once(r)


def _scenario_recorder(scenario, seed):
    """Mirror chaos.runner.run_scenario's recorder construction, but with
    record=True so two runs' logs can be compared event for event."""
    signer = signature_plane = None
    if scenario.signed:
        from mirbft_tpu.testengine.signing import SignaturePlane, make_signer

        signer = make_signer()
        signature_plane = (
            scenario.signature_plane()
            if scenario.signature_plane
            else SignaturePlane()
        )
    return BasicRecorder(
        node_count=scenario.node_count,
        client_count=scenario.client_count,
        reqs_per_client=scenario.reqs_per_client,
        batch_size=scenario.batch_size,
        seed=seed,
        manglers=scenario.build_manglers(),
        hash_plane=scenario.hash_plane() if scenario.hash_plane else None,
        signer=signer,
        signature_plane=signature_plane,
        network_state=(
            scenario.network_state() if scenario.network_state else None
        ),
        record=True,
    )


def _adversary_names():
    from mirbft_tpu.chaos.scenarios import adversary_matrix

    return [s.name for s in adversary_matrix()]


@pytest.mark.parametrize("name", _adversary_names())
def test_adversary_runs_are_deterministic(name):
    """Same seed -> byte-identical recorder log under every adversary: the
    corrupt/equivocate/censor/flood verbs draw only from the recorder's
    seeded rng, so any failing campaign seed replays exactly."""
    from mirbft_tpu.chaos.scenarios import adversary_matrix

    scenario = {s.name: s for s in adversary_matrix()}[name]

    def run(seed):
        rec = _scenario_recorder(scenario, seed)
        rec.drain_clients(max_steps=150000)
        return repr((rec.now, rec.event_count, rec.recorded_events))

    assert run(7) == run(7)


def test_targeted_seqno_drop_recovers():
    """Dropping the first Preprepares for a seqno window only delays those
    sequences (retransmit/epoch machinery recovers)."""
    r = BasicRecorder(
        node_count=4,
        client_count=2,
        reqs_per_client=6,
        manglers=[
            rule(msg_type("Preprepare"), with_seq_no(1, 4), until_events(6))
            .drop()
        ],
    )
    r.drain_clients(max_steps=600000)
    assert all_agree(r)
