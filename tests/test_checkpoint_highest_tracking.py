"""Above-window highest-checkpoint tracking: the map must follow each
node's *newest* claim (lagging-node / state-transfer detection) and stay
bounded to one above-window entry per node."""

from mirbft_tpu import pb
from mirbft_tpu.core.checkpoints import CheckpointTracker
from mirbft_tpu.core.msgbuffers import NodeBuffers
from mirbft_tpu.core.persisted import Persisted


def _tracker():
    persisted = Persisted()
    persisted.add_c_entry(
        pb.CEntry(
            seq_no=0,
            checkpoint_value=b"genesis",
            network_state=pb.NetworkState(
                config=pb.NetworkConfig(
                    nodes=[0, 1, 2, 3],
                    f=1,
                    number_of_buckets=4,
                    checkpoint_interval=5,
                    max_epoch_length=50,
                )
            ),
        )
    )
    my = pb.InitialParameters(id=0, buffer_size=1 << 20)
    t = CheckpointTracker(persisted, NodeBuffers(my), my)
    t.reinitialize()
    return t


def test_highest_tracks_newest_claim():
    t = _tracker()
    t.step(3, pb.Msg(type=pb.Checkpoint(seq_no=40, value=b"c40")))
    assert t.highest_checkpoints[3] == 40
    t.step(3, pb.Msg(type=pb.Checkpoint(seq_no=60, value=b"c60")))
    assert t.highest_checkpoints[3] == 60
    # A replayed older above-window claim must not move the map down.
    t.step(3, pb.Msg(type=pb.Checkpoint(seq_no=35, value=b"c35")))
    assert t.highest_checkpoints[3] == 60


def test_above_window_map_stays_bounded():
    t = _tracker()
    for seq in (40, 60, 80, 100):
        t.step(3, pb.Msg(type=pb.Checkpoint(seq_no=seq, value=b"x")))
    # Only the active windows plus node 3's newest claim survive.
    above_window = [s for s in t.checkpoint_map if s > t.high_watermark()]
    assert above_window == [100]
