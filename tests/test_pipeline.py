"""Pipelined commit path: group-commit coalescing and the ordering
contract's fault seams.

The contract under test (docs/Processor.md): no send for a batch may
happen before that batch's request-store AND WAL data are durable.  The
pipelined executor enforces it with a barrier stage redeeming group-commit
tickets; these tests kill the disk (or the transmit stage) at the seam
between the two syncs and between sync and transmit, and assert that no
premature send escaped and that a restart replays the WAL cleanly."""

import threading
import time

import pytest

from mirbft_tpu import pb
from mirbft_tpu.core import actions as act
from mirbft_tpu.runtime import Config, FileRequestStore, FileWal
from mirbft_tpu.runtime.processor import PipelinedProcessor, ProcessorClosed


# -- harness -----------------------------------------------------------------


class _FakeNode:
    """Just enough Node for a processor: a config, a self-send sink, and
    an add_results recorder."""

    def __init__(self):
        self.config = Config(id=0)
        self.stepped = []
        self.results = []

    def step(self, replica, msg):
        self.stepped.append((replica, msg))

    def add_results(self, results):
        self.results.append(results)


class _RecordingLink:
    def __init__(self):
        self.sent = []

    def send(self, dest, msg):
        self.sent.append((dest, msg))


class _NullLog:
    def __init__(self):
        self.applied = []

    def apply(self, q_entry):
        self.applied.append(q_entry)

    def snap(self, network_config, clients_state):
        return b"snap"


def _persist_send_actions(index=1):
    """One batch exercising the full contract: a stored request, a WAL
    append, and a send that must not escape before both are durable."""
    ack = pb.RequestAck(client_id=1, req_no=index, digest=b"\x07" * 32)
    actions = act.Actions()
    actions.store_request(
        pb.ForwardRequest(request_ack=ack, request_data=b"payload")
    )
    actions.persist(index, pb.Persistent(type=pb.ECEntry(epoch_number=index)))
    actions.send([1], pb.Msg(type=pb.Suspect(epoch=index)))
    return actions


def _build(tmp_path, wal=None, store=None):
    node = _FakeNode()
    link = _RecordingLink()
    wal = wal if wal is not None else FileWal(str(tmp_path / "wal"))
    store = (
        store
        if store is not None
        else FileRequestStore(str(tmp_path / "reqs"))
    )
    proc = PipelinedProcessor(node, link, _NullLog(), wal, store)
    return node, link, wal, store, proc


def _await_park(proc, deadline_s=5.0):
    """Wait until a stage error parks the pipeline; return the error."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        with proc._mutex:
            if proc._error is not None:
                return proc._error
        time.sleep(0.01)
    raise AssertionError("pipeline never parked on the injected fault")


# -- group commit ------------------------------------------------------------


def test_group_commit_coalesces_fsyncs(tmp_path):
    """k tickets redeemed across one gated sync window must cost far
    fewer than k fsyncs (the whole point of sync_token/wait)."""
    wal = FileWal(str(tmp_path / "wal"))
    syncs = []
    gate = threading.Event()

    def hook():
        syncs.append(time.monotonic())
        gate.wait(timeout=5.0)

    try:
        for i in range(5):
            wal.write(i, pb.Persistent(type=pb.ECEntry(epoch_number=i)))
        wal.fault_hook = hook
        tokens = [wal.sync_token() for _ in range(5)]
        gate.set()
        for token in tokens:
            assert wal.wait(token, timeout=5.0)
        # First sync may cover only the tickets issued before the syncer
        # snapshotted; one more covers the rest.  Five would mean no
        # coalescing at all.
        assert len(syncs) <= 2, f"{len(syncs)} fsyncs for 5 tickets"
    finally:
        wal.fault_hook = None
        wal.close()


def test_group_commit_token_covers_earlier_writes(tmp_path):
    """A single token taken after the last write covers every earlier
    write — the invariant that lets the pipeline persist a whole group
    under one ticket pair."""
    wal = FileWal(str(tmp_path / "wal"))
    for i in range(10):
        wal.write(i, pb.Persistent(type=pb.ECEntry(epoch_number=i)))
    token = wal.sync_token()
    assert wal.wait(token, timeout=5.0)
    wal.crash()  # skip the close-time sync: durability came from the ticket

    wal2 = FileWal(str(tmp_path / "wal"))
    loaded = []
    wal2.load_all(lambda i, e: loaded.append(i))
    assert loaded == list(range(10))
    wal2.close()


def test_group_commit_propagates_disk_errors_to_waiters(tmp_path):
    """A failing fsync must surface on wait() (and poison later tokens),
    never silently report durability."""
    store = FileRequestStore(str(tmp_path / "reqs"))
    ack = pb.RequestAck(client_id=1, req_no=1, digest=b"\x01" * 32)
    store.store(ack, b"data")

    def dying_disk():
        raise OSError("injected: disk died")

    store.fault_hook = dying_disk
    token = store.sync_token()
    with pytest.raises(OSError, match="disk died"):
        store.wait(token, timeout=5.0)
    with pytest.raises(OSError):
        store.sync_token()
    store.fault_hook = None
    store.crash()


def test_group_commit_crash_close_fails_uncovered_tickets(tmp_path):
    """crash() must leave outstanding tickets uncovered (waiters get an
    error, not a durability lie); clean close() covers them."""
    wal = FileWal(str(tmp_path / "wal"))
    wal.write(1, pb.Persistent(type=pb.ECEntry(epoch_number=1)))
    # The sync must provably not cover the ticket, whichever side wins
    # the scheduling race: if the syncer reaches the fsync first, the
    # armed fault seam kills it (disk died — wait() raises the syncer's
    # error); if crash() wins, the ticket is left uncovered by stop()
    # (wait() raises the closed-before-sync error).  A fixed-length
    # block here instead would flake on a loaded box — a sync that wins
    # such a race really is durable, and wait() saying so is correct.
    def dying_disk():
        raise OSError("injected: disk died at fsync")

    wal.fault_hook = dying_disk
    token = wal.sync_token()
    wal.crash()
    with pytest.raises(OSError):
        wal.wait(token, timeout=5.0)

    wal2 = FileWal(str(tmp_path / "wal"))
    wal2.write(2, pb.Persistent(type=pb.ECEntry(epoch_number=2)))
    token = wal2.sync_token()
    wal2.close()  # clean close: final sync covers the ticket
    assert wal2.wait(token, timeout=5.0)


# -- pipeline ordering-contract fault seams ----------------------------------


def test_no_send_escapes_when_wal_sync_fails(tmp_path):
    """Disk dies at the WAL sync (after the request store persisted):
    the barrier must hold every send of that batch, the error must
    surface from a later process() call, and a fresh WAL on the same
    directory must replay a clean prefix."""
    wal = FileWal(str(tmp_path / "wal"))
    node, link, wal, store, proc = _build(tmp_path, wal=wal)

    def dying_disk():
        raise OSError("injected: WAL disk died")

    try:
        wal.fault_hook = dying_disk
        proc.process(_persist_send_actions(1))
        err = _await_park(proc)
        assert "WAL disk died" in str(err)
        # The contract: nothing was sent for the un-durable batch.
        assert link.sent == []
        assert node.stepped == []
        with pytest.raises(OSError, match="WAL disk died"):
            proc.process(_persist_send_actions(2))
    finally:
        proc.close(wait=False)
        wal.fault_hook = None
        store.crash()
        wal.crash()

    # Restart replays cleanly: whatever prefix survived parses.
    wal2 = FileWal(str(tmp_path / "wal"))
    loaded = []
    wal2.load_all(lambda i, e: loaded.append(i))
    wal2.close()
    store2 = FileRequestStore(str(tmp_path / "reqs"))
    uncommitted = []
    store2.uncommitted(uncommitted.append)
    store2.close()


def test_no_send_escapes_when_reqstore_sync_fails(tmp_path):
    """Disk dies at the request-store sync (before the WAL's): same
    contract — the batch's sends never leave the barrier."""
    store = FileRequestStore(str(tmp_path / "reqs"))
    node, link, wal, store, proc = _build(tmp_path, store=store)

    def dying_disk():
        raise OSError("injected: reqstore disk died")

    try:
        store.fault_hook = dying_disk
        proc.process(_persist_send_actions(1))
        err = _await_park(proc)
        assert "reqstore disk died" in str(err)
        assert link.sent == []
        assert node.stepped == []
        with pytest.raises(OSError, match="reqstore disk died"):
            proc.process(_persist_send_actions(2))
    finally:
        proc.close(wait=False)
        store.fault_hook = None
        store.crash()
        wal.crash()

    wal2 = FileWal(str(tmp_path / "wal"))
    loaded = []
    wal2.load_all(lambda i, e: loaded.append(i))
    wal2.close()


def test_crash_between_wal_sync_and_transmit_replays(tmp_path):
    """Process dies between the durability barrier and the sends: the
    WAL must already hold the batch (it was durable before transmit was
    ever attempted), and zero sends escaped — exactly the window WAL
    replay exists for."""
    node, link, wal, store, proc = _build(tmp_path)

    def crashing_transmit(actions):
        raise RuntimeError("injected: crashed before transmit")

    proc._transmit = crashing_transmit
    try:
        proc.process(_persist_send_actions(1))
        err = _await_park(proc)
        assert "crashed before transmit" in str(err)
        assert link.sent == []
        assert node.stepped == []
    finally:
        proc.close(wait=False)
        store.crash()
        wal.crash()

    # The batch IS in the WAL: durability preceded the crash point.
    wal2 = FileWal(str(tmp_path / "wal"))
    loaded = []
    wal2.load_all(lambda i, e: loaded.append(i))
    assert loaded == [1]
    wal2.close()


def test_send_happens_only_after_both_stores_durable(tmp_path):
    """Happy path: the send arrives, and only after both group-commit
    tickets were redeemable (observed via gated fault hooks)."""
    node, link, wal, store, proc = _build(tmp_path)
    sync_times = {}

    def observing(name):
        def hook():
            sync_times.setdefault(name, time.monotonic())

        return hook

    wal.fault_hook = observing("wal")
    store.fault_hook = observing("store")
    try:
        proc.process(_persist_send_actions(1))
        deadline = time.monotonic() + 5.0
        while not link.sent and time.monotonic() < deadline:
            time.sleep(0.005)
        send_time = time.monotonic()
        assert link.sent, "send never happened"
        assert {"wal", "store"} <= set(sync_times), (
            f"send escaped without both syncs: {sorted(sync_times)}"
        )
        assert max(sync_times.values()) <= send_time
    finally:
        wal.fault_hook = None
        store.fault_hook = None
        proc.close()
        store.close()
        wal.close()


def test_pipeline_delivers_results_internally(tmp_path):
    """process() returns empty results; digests (hash worker) and
    checkpoint values (commit stage) arrive via node.add_results, and
    the on_results seam sees them first."""
    node, link, wal, store, proc = _build(tmp_path)
    seen = []
    proc.on_results = seen.append
    try:
        actions = act.Actions()
        actions.hash([b"preimage"], None)
        actions.commits.append(
            act.CommitAction(
                checkpoint=act.CheckpointReq(
                    seq_no=10, network_config=None, clients_state=[]
                )
            )
        )
        out = proc.process(actions)
        assert not out.digests and not out.checkpoints

        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            digests = [d for r in node.results for d in r.digests]
            ckpts = [c for r in node.results for c in r.checkpoints]
            if digests and ckpts:
                break
            time.sleep(0.005)
        assert len(digests) == 1 and len(digests[0].digest) == 32
        assert ckpts[0].value == b"snap"
        assert seen, "on_results seam never fired"
    finally:
        proc.close()
        store.close()
        wal.close()


def test_closed_processor_rejects_new_batches(tmp_path):
    node, link, wal, store, proc = _build(tmp_path)
    proc.close()
    with pytest.raises(ProcessorClosed):
        proc.process(_persist_send_actions(1))
    store.close()
    wal.close()


def test_pipeline_lock_acquisition_graph_is_acyclic(tmp_path, monkeypatch):
    """Dynamic lock-order harness (docs/ANALYSIS.md): run real batches
    through the pipelined processor and the group-commit stores with
    every threading primitive instrumented; the cross-thread
    (held-lock, acquired-lock) graph must stay cycle-free — a cycle is
    a potential deadlock even if this run never interleaved into it."""
    import sys
    from pathlib import Path

    sys.path.insert(
        0, str(Path(__file__).resolve().parent.parent / "tools")
    )
    from analysis.lockorder import LockMonitor, _InstrumentedLock

    from mirbft_tpu.runtime import processor as processor_mod
    from mirbft_tpu.runtime import storage as storage_mod

    monitor = LockMonitor()
    proxy = monitor.threading_proxy()
    monkeypatch.setattr(processor_mod, "threading", proxy)
    monkeypatch.setattr(storage_mod, "threading", proxy)

    node, link, wal, store, proc = _build(tmp_path)
    # The wiring is real: the primitives under test are instrumented.
    assert isinstance(proc._mutex, _InstrumentedLock)
    assert isinstance(wal._lock, _InstrumentedLock)
    assert isinstance(store._lock, _InstrumentedLock)
    try:
        for i in range(1, 6):
            proc.process(_persist_send_actions(i))
        actions = act.Actions()
        actions.hash([b"preimage"], None)
        proc.process(actions)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and len(link.sent) < 5:
            time.sleep(0.005)
        assert len(link.sent) == 5, link.sent
    finally:
        proc.close()
        store.close()
        wal.close()
    monitor.assert_no_cycles()
