"""Ingress frame bounds: msgfilter.pre_process rejects oversized batches,
payloads, and digests against Config limits, with a taxonomy ``kind`` on
every MalformedMessage so rejections are countable by cause."""

import pytest

from mirbft_tpu import pb
from mirbft_tpu.obsv import hooks
from mirbft_tpu.runtime import Config
from mirbft_tpu.runtime.msgfilter import MalformedMessage, pre_process


def _ack(digest=b"d" * 32, client=4, req_no=0):
    return pb.RequestAck(client_id=client, req_no=req_no, digest=digest)


def _msg(inner):
    return pb.Msg(type=inner)


def _kind(call):
    with pytest.raises(MalformedMessage) as excinfo:
        call()
    return excinfo.value.kind


def test_honest_messages_pass_default_limits():
    pre_process(_msg(pb.Preprepare(seq_no=1, epoch=1, batch=[_ack()])))
    pre_process(_msg(pb.Prepare(seq_no=1, epoch=1, digest=b"d" * 32)))
    pre_process(_msg(pb.Commit(seq_no=1, epoch=1, digest=b"d" * 32)))
    pre_process(_msg(_ack()))
    pre_process(
        _msg(pb.ForwardRequest(request_ack=_ack(), request_data=b"x" * 64))
    )
    pre_process(
        _msg(
            pb.ForwardBatch(
                seq_no=1, request_acks=[_ack()], digest=b"d" * 32
            )
        )
    )


def test_structural_rejections_keep_malformed_kind():
    assert _kind(lambda: pre_process(pb.Msg(type=None))) == "malformed"
    assert (
        _kind(lambda: pre_process(_msg(pb.ForwardRequest(request_ack=None))))
        == "malformed"
    )
    assert (
        _kind(lambda: pre_process(_msg(pb.NewEpoch(new_config=None))))
        == "malformed"
    )


def test_oversized_preprepare_batch_rejected():
    batch = [_ack(req_no=i) for i in range(300)]
    kind = _kind(
        lambda: pre_process(_msg(pb.Preprepare(seq_no=1, epoch=1, batch=batch)))
    )
    assert kind == "oversized_batch"


def test_oversized_forward_batch_rejected():
    acks = [_ack(req_no=i) for i in range(300)]
    kind = _kind(
        lambda: pre_process(
            _msg(pb.ForwardBatch(seq_no=1, request_acks=acks, digest=b""))
        )
    )
    assert kind == "oversized_batch"


def test_oversized_payload_rejected():
    inner = pb.ForwardRequest(
        request_ack=_ack(), request_data=b"x" * (1024 * 1024 + 1)
    )
    assert _kind(lambda: pre_process(_msg(inner))) == "oversized_payload"


@pytest.mark.parametrize(
    "inner",
    [
        pb.Prepare(seq_no=1, epoch=1, digest=b"d" * 65),
        pb.Commit(seq_no=1, epoch=1, digest=b"d" * 65),
        pb.RequestAck(client_id=4, req_no=0, digest=b"d" * 65),
        pb.FetchBatch(seq_no=1, digest=b"d" * 65),
        pb.FetchRequest(client_id=4, req_no=0, digest=b"d" * 65),
        pb.ForwardBatch(seq_no=1, request_acks=[], digest=b"d" * 65),
        pb.Preprepare(seq_no=1, epoch=1, batch=[_ack(digest=b"d" * 65)]),
        pb.ForwardRequest(request_ack=_ack(digest=b"d" * 65)),
    ],
)
def test_oversized_digest_rejected_everywhere(inner):
    assert _kind(lambda: pre_process(_msg(inner))) == "oversized_digest"


def test_config_limits_override_defaults():
    config = Config(
        id=0, max_batch_acks=2, max_request_bytes=16, max_digest_bytes=32
    )
    pre_process(
        _msg(pb.Preprepare(seq_no=1, epoch=1, batch=[_ack(), _ack(req_no=1)])),
        config,
    )
    kind = _kind(
        lambda: pre_process(
            _msg(
                pb.Preprepare(
                    seq_no=1,
                    epoch=1,
                    batch=[_ack(req_no=i) for i in range(3)],
                )
            ),
            config,
        )
    )
    assert kind == "oversized_batch"
    kind = _kind(
        lambda: pre_process(
            _msg(pb.ForwardRequest(request_ack=_ack(), request_data=b"x" * 17)),
            config,
        )
    )
    assert kind == "oversized_payload"
    kind = _kind(
        lambda: pre_process(_msg(pb.Prepare(digest=b"d" * 33)), config)
    )
    assert kind == "oversized_digest"


def test_node_step_counts_rejections_by_kind():
    """Node.step enforces its Config bounds and labels the rejection
    metric with the taxonomy kind before the transport drops the frame."""
    from mirbft_tpu.runtime import Node
    from mirbft_tpu.runtime.node import standard_initial_network_state

    metrics, _ = hooks.enable()
    node = None
    try:
        node = Node.start_new(
            config=Config(id=0, max_batch_acks=4),
            initial_network_state=standard_initial_network_state(4, [4]),
        )
        with pytest.raises(MalformedMessage):
            node.step(
                1,
                _msg(
                    pb.Preprepare(
                        seq_no=1,
                        epoch=1,
                        batch=[_ack(req_no=i) for i in range(5)],
                    )
                ),
            )
        counter = metrics.counter(
            "mirbft_byzantine_rejections_total", kind="oversized_batch"
        )
        assert counter.value == 1
    finally:
        if node is not None:
            node.stop()
        hooks.disable()
