"""Stress-tier gate: real threads, real files, real (in-process) transport
(SURVEY §4 tier 3; reference: stress_test.go).  Asserts each request commits
exactly once per node, and that a node restarted from its WAL resumes."""

import hashlib
import queue
import threading
import time

import pytest

from mirbft_tpu import pb
from mirbft_tpu.core.preimage import host_digest
from mirbft_tpu.runtime import (
    Config,
    FileRequestStore,
    FileWal,
    Node,
    PipelinedProcessor,
    PoolProcessor,
    SerialProcessor,
    TpuPipelinedProcessor,
    TpuPoolProcessor,
    TpuProcessor,
)
from mirbft_tpu.runtime.node import NodeStopped, standard_initial_network_state
from mirbft_tpu.runtime.processor import Link, Log


class ThreadTransport:
    """Channel-matrix fake transport (reference: stress_test.go:68-151)."""

    def __init__(self):
        self.nodes = {}
        self.replicas = {}  # node_id -> Replica, for out-of-band state fetch
        self.lock = threading.Lock()

    def register(self, node_id, node):
        with self.lock:
            self.nodes[node_id] = node

    def unregister(self, node_id):
        with self.lock:
            self.nodes.pop(node_id, None)
            self.replicas.pop(node_id, None)

    def link(self, source: int) -> Link:
        transport = self

        class _Link(Link):
            def send(self, dest, msg):
                with transport.lock:
                    node = transport.nodes.get(dest)
                if node is None:
                    return  # dropped: dest down
                try:
                    node.step(source, msg)
                except NodeStopped:
                    pass  # dest halted concurrently: dropped, like a dead TCP
                # Anything else (e.g. a validation crash) propagates — a bug
                # must fail the run, not masquerade as an unreliable link.

        return _Link()


class HashChainLog(Log):
    def __init__(self):
        self.chain = b""
        self.commits = []  # [(client_id, req_no, seq_no)]
        self.commit_events = queue.Queue()

    def apply(self, q_entry):
        for ack in q_entry.requests:
            h = hashlib.sha256()
            h.update(self.chain)
            h.update(ack.digest)
            self.chain = h.digest()
            self.commits.append((ack.client_id, ack.req_no, q_entry.seq_no))
            self.commit_events.put((ack.client_id, ack.req_no))

    def snap(self, network_config, clients_state):
        return self.chain


class Replica:
    """One node: serializer + consumer loop thread + storage."""

    def __init__(self, node_id, transport, tmp_path, initial_state=None,
                 tick_seconds=0.05, processor_cls=SerialProcessor,
                 event_interceptor=None):
        self.node_id = node_id
        self.transport = transport
        self.dir = tmp_path / f"node{node_id}"
        self.tick_seconds = tick_seconds
        self.app_log = HashChainLog()
        self.wal = FileWal(str(self.dir / "wal"))
        self.reqstore = FileRequestStore(str(self.dir / "reqs"))
        if event_interceptor is None:
            # Always leave a replayable per-node event log behind — a failed
            # stress run's post-mortem artifact (reference: mirbft_test.go:52-65,
            # replayed with python -m mirbft_tpu.cat).  Unique name per
            # start: a restart must not truncate the pre-crash log.
            from mirbft_tpu.eventlog import Recorder as EventRecorder

            self.dir.mkdir(parents=True, exist_ok=True)
            run = len(list(self.dir.glob("events-*.gz")))
            self.recorder = EventRecorder(str(self.dir / f"events-{run}.gz"))
            event_interceptor = self.recorder.interceptor(node_id)
        else:
            self.recorder = None
        config = Config(id=node_id, event_interceptor=event_interceptor)
        if initial_state is not None:
            self.node = Node.start_new(config, initial_state)
        else:
            self.node = Node.restart(config, self.wal, self.reqstore)
        self.processor = processor_cls(
            self.node, transport.link(node_id), self.app_log, self.wal,
            self.reqstore,
        )
        # Checkpoint snapshots for serving peers' state transfers out of
        # band (the reference consumer's job, mirbft.go:426-459).
        self.checkpoints = {}  # seq_no -> (value, pb.NetworkState)
        # Pipelined executors deliver results internally (the consumer
        # loop sees empty ActionResults), so checkpoint capture routes
        # through the processor's on_results seam instead.
        if hasattr(self.processor, "on_results"):
            self.processor.on_results = self._capture_checkpoints
        transport.register(node_id, self.node)
        transport.replicas[node_id] = self
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._consume, name=f"consumer-{node_id}", daemon=True
        )
        self._thread.start()

    def _capture_checkpoints(self, results):
        for cr in results.checkpoints:
            self.checkpoints[cr.checkpoint.seq_no] = (
                cr.value,
                pb.NetworkState(
                    config=cr.checkpoint.network_config,
                    clients=cr.checkpoint.clients_state,
                    pending_reconfigurations=list(cr.reconfigurations),
                ),
            )

    def _consume(self):
        last_tick = time.monotonic()
        while not self._stop.is_set():
            actions = self.node.ready(timeout=0.01)
            if actions is not None:
                results = self.processor.process(actions)
                self._capture_checkpoints(results)
                if results.digests or results.checkpoints:
                    try:
                        self.node.add_results(results)
                    except NodeStopped:
                        return
            now = time.monotonic()
            if now - last_tick >= self.tick_seconds:
                last_tick = now
                try:
                    self.node.tick()
                except NodeStopped:
                    return
                # Serve any state-transfer requests out of band.
                # (Transfer actions are handled via actions.state_transfer.)
            if actions is not None and actions.state_transfer is not None:
                self._serve_transfer(actions.state_transfer)

    def _serve_transfer(self, target):
        """Out-of-band state fetch (the reference consumer's job): find a
        peer holding the agreed checkpoint, adopt its app state, and report
        completion; failure reports trigger a protocol-level retry."""
        with self.transport.lock:
            peers = [
                r for n, r in self.transport.replicas.items()
                if n != self.node_id
            ]
        for peer in peers:
            entry = peer.checkpoints.get(target.seq_no)
            if entry is None or entry[0] != target.value:
                continue
            value, network_state = entry
            self.app_log.chain = value  # adopt the app state wholesale
            self.node.state_transfer_complete(target, network_state)
            return
        self.node.state_transfer_failed(target)

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)
        self.transport.unregister(self.node_id)
        self.node.stop()
        if hasattr(self.processor, "close"):
            self.processor.close()
        self.wal.close()
        self.reqstore.close()
        if self.recorder is not None:
            self.recorder.close()


def await_commits(replicas, expected, timeout=60.0):
    """Wait until each replica has committed at least `expected` (a replica
    restarted from a WAL may additionally replay commits made after its
    last stable checkpoint — that is correct protocol behavior)."""
    deadline = time.monotonic() + timeout
    for replica in replicas:
        got = set()
        while not expected <= got:
            remaining = deadline - time.monotonic()
            assert remaining > 0, (
                f"node {replica.node_id} timed out with "
                f"{len(got & expected)}/{len(expected)} commits; "
                f"exit={replica.node.exit_error!r}; "
                f"event logs for replay under {replica.dir.parent}"
            )
            try:
                got.add(replica.app_log.commit_events.get(timeout=min(remaining, 1)))
            except queue.Empty:
                continue


def make_requests(client_id, count):
    out = []
    for req_no in range(count):
        request = pb.Request(
            client_id=client_id, req_no=req_no, data=b"%d" % req_no
        )
        out.append(request)
    return out


def test_single_node_runtime(tmp_path):
    transport = ThreadTransport()
    state = standard_initial_network_state(1, [1])
    replica = Replica(0, transport, tmp_path, initial_state=state)
    try:
        proposer = replica.node.client_proposer(1)
        requests = make_requests(1, 20)
        for request in requests:
            proposer.propose(request)
        await_commits([replica], {(1, r.req_no) for r in requests})
        # Exactly once (no restarts in this test, so no replays either).
        commits = [(c, r) for c, r, _s in replica.app_log.commits]
        assert len(commits) == len(set(commits))
    finally:
        replica.stop()
    assert replica.node.exit_error is None


class _AlwaysDeviceProcessor(TpuProcessor):
    """TpuProcessor with the device path forced for every batch size, so a
    small stress run still sends all its digests through the kernel."""

    min_batch_for_device = 1


class _AlwaysDevicePoolProcessor(TpuPoolProcessor):
    """TpuPoolProcessor with the device path forced: parallel lanes AND
    every digest off the kernel (reference: the work pool's hash pool,
    processor.go:396-470, with the accelerator as the pool)."""

    min_batch_for_device = 1


class _AlwaysDevicePipelinedProcessor(TpuPipelinedProcessor):
    """TpuPipelinedProcessor with the device path forced: the overlapped
    stage pipeline with every digest off the kernel."""

    min_batch_for_device = 1


@pytest.mark.parametrize(
    "processor_cls",
    [
        SerialProcessor,
        _AlwaysDeviceProcessor,
        PoolProcessor,
        _AlwaysDevicePoolProcessor,
        PipelinedProcessor,
        _AlwaysDevicePipelinedProcessor,
    ],
    ids=["serial", "tpu-kernel", "pool", "tpu-pool", "pipelined", "tpu-pipelined"],
)
def test_four_node_runtime(tmp_path, processor_cls):
    """4-node exactly-once commitment with agreeing chains; the tpu-kernel
    variant is the flagship e2e — every request/batch digest computed by the
    accelerator kernel (VERDICT r2 item 2; reference seam:
    processor.go:129-143); the pool variants run the reference's parallel
    lane structure (persist→send ∥ forwards ∥ hash ∥ commit)."""
    if issubclass(
        processor_cls,
        (TpuProcessor, TpuPoolProcessor, TpuPipelinedProcessor),
    ):
        # Warm every (batch-bucket, block-bucket) kernel shape the run can
        # produce, outside the commit deadline: a cold CPU XLA compile of
        # the compression program costs ~10s+, and several of them inside
        # await_commits' deadline made this test flaky under full-suite load.
        from mirbft_tpu.ops.sha256 import sha256_digest_words
        from mirbft_tpu.ops.batching import pack_preimages

        for batch in (1, 9, 17):  # -> batch buckets 8, 16, 32
            for msg_len in (20, 60):  # -> 1-block and 2-block shapes
                packed = pack_preimages([b"x" * msg_len] * batch)
                sha256_digest_words(packed.blocks, packed.n_blocks)
    transport = ThreadTransport()
    state = standard_initial_network_state(4, [7, 8])
    replicas = [
        Replica(i, transport, tmp_path, initial_state=state,
                processor_cls=processor_cls)
        for i in range(4)
    ]
    try:
        requests = []
        for client_id in (7, 8):
            proposer = replicas[0].node.client_proposer(client_id)
            for request in make_requests(client_id, 10):
                requests.append(request)
                # Clients submit to every replica.
                for replica in replicas:
                    replica.node.propose(request)
        expected = {(r.client_id, r.req_no) for r in requests}
        await_commits(replicas, expected, timeout=240)
        for replica in replicas:
            commits = [(c, r) for c, r, _s in replica.app_log.commits]
            assert len(commits) == len(set(commits)), "duplicate commit!"
        # All chains agree.
        chains = {r.app_log.chain for r in replicas}
        assert len(chains) == 1
    finally:
        for replica in replicas:
            replica.stop()
    assert all(r.node.exit_error is None for r in replicas)


def test_tpu_processor_device_and_host_paths_agree():
    """min_batch_for_device covered on both sides: the same hash batch
    digested via the kernel dispatch path and the host path must be
    identical bit-for-bit."""
    from mirbft_tpu.core import actions as act

    hashes = [
        act.HashRequest(
            data=[b"chunk-a-%d" % i, b"chunk-b", bytes([i]) * (i + 1)],
            origin=pb.HashResult(digest=b"", type=pb.HashOriginRequest()),
        )
        for i in range(7)
    ]
    proc = TpuProcessor.__new__(TpuProcessor)  # hash paths need no node/wal
    actions = act.Actions()
    actions.hashes = hashes

    host_results = proc._hash(actions)
    pending = proc._dispatch_device(hashes)
    device_results = proc._collect_device(hashes, pending)

    assert [r.digest for r in host_results] == [
        r.digest for r in device_results
    ]
    assert host_results[0].digest == host_digest(hashes[0].data)


def test_pool_processor_under_preemption_storm(tmp_path):
    """The closest Python gets to the reference's race-detector tier
    (.travis.yml:17 runs the stress suite under -race): shrink the
    interpreter's thread switch interval 1000x so every shared-state
    window between the serializer, consumer, and pool lanes gets hit by
    preemption, then require exactly-once commits and agreeing chains."""
    import sys

    old_interval = sys.getswitchinterval()
    sys.setswitchinterval(1e-5)
    try:
        transport = ThreadTransport()
        state = standard_initial_network_state(4, [7])
        replicas = [
            Replica(i, transport, tmp_path, initial_state=state,
                    processor_cls=PoolProcessor)
            for i in range(4)
        ]
        try:
            requests = make_requests(7, 30)
            for request in requests:
                for replica in replicas:
                    replica.node.propose(request)
            await_commits(
                replicas, {(7, r.req_no) for r in requests}, timeout=240
            )
            for replica in replicas:
                commits = [(c, r) for c, r, _s in replica.app_log.commits]
                assert len(commits) == len(set(commits)), "duplicate commit!"
            assert len({r.app_log.chain for r in replicas}) == 1
        finally:
            for replica in replicas:
                replica.stop()
        assert all(r.node.exit_error is None for r in replicas)
    finally:
        sys.setswitchinterval(old_interval)


def test_wal_restart_resumes(tmp_path):
    """Kill a 1-node network after commits; restart from the durable WAL
    and verify it continues from its checkpoint."""
    transport = ThreadTransport()
    state = standard_initial_network_state(1, [1])
    replica = Replica(0, transport, tmp_path, initial_state=state)
    requests = make_requests(1, 12)
    try:
        proposer = replica.node.client_proposer(1)
        for request in requests[:6]:
            proposer.propose(request)
        await_commits([replica], {(1, r.req_no) for r in requests[:6]})
    finally:
        replica.stop()

    # Restart from the same directory (no initial_state → restart path).
    replica2 = Replica(0, transport, tmp_path)
    try:
        deadline = time.monotonic() + 60
        while replica2.node.status() is None:
            assert time.monotonic() < deadline
        proposer = replica2.node.client_proposer(1)
        for request in requests[6:]:
            proposer.propose(request)
        await_commits([replica2], {(1, r.req_no) for r in requests[6:]})
    finally:
        replica2.stop()
    assert replica2.node.exit_error is None


def test_late_starting_replica_state_transfers(tmp_path):
    """The reference's late-start stress scenario (mirbft_test.go:157-170):
    three replicas commit past garbage collection, then the fourth boots
    from scratch — it must adopt a peer checkpoint via the out-of-band
    transfer path and then commit new requests on the common chain."""
    transport = ThreadTransport()
    state = standard_initial_network_state(4, [7])
    replicas = [
        Replica(i, transport, tmp_path, initial_state=state) for i in range(3)
    ]
    late = None
    try:
        # Wave 1: 80 seqnos = 4 checkpoint windows (ci=20) — past GC.
        wave1 = make_requests(7, 80)
        for request in wave1:
            for replica in replicas:
                replica.node.propose(request)
        await_commits(replicas, {(7, r.req_no) for r in wave1}, timeout=240)

        # The fourth replica starts from its bootstrap state only now.
        late = Replica(3, transport, tmp_path, initial_state=state)
        replicas.append(late)

        # Wave 2: the established nodes commit these normally; the late
        # node absorbs whatever landed before its transfer checkpoint via
        # the adopted snapshot and replays the rest through the protocol.
        wave2 = make_requests(7, 90)[80:]
        for request in wave2:
            for replica in replicas:
                replica.node.propose(request)
        await_commits(replicas[:3], {(7, r.req_no) for r in wave2}, timeout=240)

        # The late node adopted a checkpoint (its consumer reported a
        # completed transfer) and converges to the common chain.
        deadline = time.monotonic() + 120
        target = replicas[0].app_log.chain
        while late.app_log.chain != target:
            assert time.monotonic() < deadline, (
                f"late node chain {late.app_log.chain.hex()[:12]} never "
                f"reached {target.hex()[:12]}; "
                f"exit={late.node.exit_error!r}"
            )
            time.sleep(0.05)
        assert late.checkpoints, "late node never computed a checkpoint"
        assert min(late.checkpoints) > 20, (
            "late node started checkpointing inside the bootstrap window — "
            "it replayed instead of transferring"
        )
        assert all(
            r.app_log.chain == target for r in replicas
        )
    finally:
        for replica in replicas:
            replica.stop()
    assert all(r.node.exit_error is None for r in replicas)


def test_storage_roundtrip(tmp_path):
    wal = FileWal(str(tmp_path / "wal"))
    entries = [
        pb.Persistent(type=pb.ECEntry(epoch_number=i)) for i in range(50)
    ]
    for i, entry in enumerate(entries):
        wal.write(i, entry)
    wal.sync()
    wal.truncate(20)
    wal.close()

    wal2 = FileWal(str(tmp_path / "wal"))
    loaded = []
    wal2.load_all(lambda i, e: loaded.append((i, e)))
    assert [i for i, _ in loaded] == list(range(20, 50))
    assert loaded[0][1].type.epoch_number == 20
    wal2.close()

    store = FileRequestStore(str(tmp_path / "reqs"))
    acks = [
        pb.RequestAck(client_id=1, req_no=i, digest=bytes([i]) * 32)
        for i in range(10)
    ]
    for i, ack in enumerate(acks):
        store.store(ack, b"data%d" % i)
    store.sync()
    for ack in acks[:5]:
        store.commit(ack)
    store.sync()
    assert store.get(acks[7]) == b"data7"
    assert store.get(acks[2]) is None
    store.close()

    store2 = FileRequestStore(str(tmp_path / "reqs"))
    uncommitted = []
    store2.uncommitted(uncommitted.append)
    assert {a.req_no for a in uncommitted} == {5, 6, 7, 8, 9}
    store2.close()


def test_wal_detects_torn_tail(tmp_path):
    wal = FileWal(str(tmp_path / "wal"))
    for i in range(5):
        wal.write(i, pb.Persistent(type=pb.ECEntry(epoch_number=i)))
    wal.sync()
    wal.close()
    # Corrupt the tail.
    seg = next(
        (tmp_path / "wal" / "segments").glob("*.wal")
    )
    data = seg.read_bytes()
    seg.write_bytes(data[:-3])
    wal2 = FileWal(str(tmp_path / "wal"))
    loaded = []
    wal2.load_all(lambda i, e: loaded.append(i))
    assert loaded == [0, 1, 2, 3]  # the torn record is discarded
    wal2.close()


def test_wal_torn_tail_recovery_is_clean_prefix_and_appendable(tmp_path):
    """The crash contract end to end: tear the active segment mid-record
    (a crash during a non-synced append), reopen, and confirm the log
    recovers exactly the clean prefix AND keeps working — subsequent
    appends continue from the recovered tail and survive another reopen."""
    wal = FileWal(str(tmp_path / "wal"))
    for i in range(8):
        wal.write(i, pb.Persistent(type=pb.ECEntry(epoch_number=i)))
    wal.sync()
    wal.close()

    seg = next((tmp_path / "wal" / "segments").glob("*.wal"))
    data = seg.read_bytes()
    # Tear inside the LAST record's payload (past its header), the shape a
    # torn write actually takes.
    seg.write_bytes(data[: len(data) - 2])

    wal2 = FileWal(str(tmp_path / "wal"))
    loaded = []
    wal2.load_all(lambda i, e: loaded.append(i))
    assert loaded == list(range(7))  # clean prefix, torn record dropped

    # The recovered log accepts the contiguous continuation (re-writing
    # the lost index) and persists it.
    wal2.write(7, pb.Persistent(type=pb.ECEntry(epoch_number=77)))
    wal2.write(8, pb.Persistent(type=pb.ECEntry(epoch_number=88)))
    wal2.sync()
    wal2.close()

    wal3 = FileWal(str(tmp_path / "wal"))
    final = []
    wal3.load_all(lambda i, e: final.append((i, e.type.epoch_number)))
    assert [i for i, _ in final] == list(range(9))
    assert dict(final)[7] == 77 and dict(final)[8] == 88
    wal3.close()


def test_wal_mid_segment_corruption_discards_suffix(tmp_path):
    """A flipped byte in the middle of a segment (CRC mismatch) must not
    poison recovery: everything before the corrupt record loads, the rest
    of that segment is discarded."""
    wal = FileWal(str(tmp_path / "wal"))
    for i in range(6):
        wal.write(i, pb.Persistent(type=pb.ECEntry(epoch_number=i)))
    wal.sync()
    wal.close()

    seg = next((tmp_path / "wal" / "segments").glob("*.wal"))
    data = bytearray(seg.read_bytes())
    # Corrupt a payload byte roughly mid-file.
    data[len(data) // 2] ^= 0xFF
    seg.write_bytes(bytes(data))

    wal2 = FileWal(str(tmp_path / "wal"))
    loaded = []
    wal2.load_all(lambda i, e: loaded.append(i))
    assert loaded == list(range(len(loaded)))  # a contiguous clean prefix
    assert 0 < len(loaded) < 6
    wal2.close()


def test_reqstore_torn_tail_recovery(tmp_path):
    """FileRequestStore replay stops at a torn record and compaction
    rewrites the clean prefix durably."""
    store = FileRequestStore(str(tmp_path / "reqs"))
    acks = [
        pb.RequestAck(client_id=3, req_no=i, digest=bytes([i]) * 32)
        for i in range(6)
    ]
    for i, ack in enumerate(acks):
        store.store(ack, b"payload%d" % i)
    store.sync()
    store.close()

    log = tmp_path / "reqs" / "requests.log"
    log.write_bytes(log.read_bytes()[:-5])  # tear the last record

    store2 = FileRequestStore(str(tmp_path / "reqs"))
    uncommitted = []
    store2.uncommitted(uncommitted.append)
    assert {a.req_no for a in uncommitted} == {0, 1, 2, 3, 4}
    assert store2.get(acks[2]) == b"payload2"
    store2.close()
