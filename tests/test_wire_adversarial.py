"""Adversarial codec gate.

The canonical-encoding property the framework's replay/test methodology rests
on (see mirbft_tpu/wire.py docstring) is: the set of accepted encodings is
exactly the set of produced encodings.  These probes attack that property:
truncation at every prefix, single-bit flips (any accepted mutation must
re-encode byte-identically), non-canonical presence/bool/varint forms,
unknown oneof tags, and length/count claims exceeding the buffer or the
64-bit value space.
"""

import pytest

from mirbft_tpu import pb, wire
from tests.test_wire import SAMPLES, sample_id


@pytest.mark.parametrize("sample", SAMPLES, ids=sample_id)
def test_every_strict_prefix_rejected(sample):
    enc = pb.encode(sample)
    for cut in range(len(enc)):
        with pytest.raises(ValueError):
            pb.decode(type(sample), enc[:cut])


@pytest.mark.parametrize("sample", SAMPLES, ids=sample_id)
def test_accepted_bit_flips_are_canonical(sample):
    """Flipping any single bit either fails to decode or decodes to a value
    whose canonical encoding is byte-identical to the mutated buffer — i.e.
    no mutation lands in accepted-but-non-canonical territory."""
    enc = pb.encode(sample)
    cls = type(sample)
    for byte_i in range(len(enc)):
        for bit in range(8):
            mutated = bytearray(enc)
            mutated[byte_i] ^= 1 << bit
            mutated = bytes(mutated)
            try:
                dec = pb.decode(cls, mutated)
            except (ValueError, TypeError):
                continue
            assert pb.encode(dec) == mutated, (
                f"byte {byte_i} bit {bit}: accepted non-canonical mutation"
            )


def test_unknown_oneof_tag_rejected():
    # Persistent oneof has tags 1..8; tag 9 with an empty body must fail.
    with pytest.raises(ValueError):
        pb.decode(pb.Persistent, b"\x09\x00")


def test_unset_oneof_rejected_for_critical_oneofs():
    # Tag 0 (unset) is never legitimate for wire msgs, WAL entries, events,
    # or reconfigurations.
    for cls in (pb.Msg, pb.Persistent, pb.StateEvent, pb.Reconfiguration):
        with pytest.raises(ValueError):
            pb.decode(cls, b"\x00")
        with pytest.raises(ValueError):
            pb.encode(cls())


def test_presence_byte_above_one_rejected():
    # EventLoadRequest: presence byte for the nested RequestAck.
    good = pb.encode(pb.EventLoadRequest(request_ack=pb.RequestAck(digest=b"d")))
    assert good[0] == 1
    bad = b"\x02" + good[1:]
    with pytest.raises(ValueError):
        pb.decode(pb.EventLoadRequest, bad)


def test_bool_byte_above_one_rejected():
    ns = pb.NetworkState(config=pb.NetworkConfig(nodes=[0]), reconfigured=True)
    enc = pb.encode(ns)
    assert enc[-1] == 1  # reconfigured bool is the final byte
    with pytest.raises(ValueError):
        pb.decode(pb.NetworkState, enc[:-1] + b"\x02")


def test_huge_length_claim_rejected():
    # bytes field claiming 2^32 bytes with a 1-byte body.
    claim = wire.encode_varint(2**32)
    with pytest.raises(ValueError):
        pb.decode(pb.RequestAck, b"\x01\x01" + claim + b"x")


def test_huge_count_claim_rejected():
    # NetworkConfig.nodes (repeated) claiming 2^40 items then ending.
    with pytest.raises(ValueError):
        pb.decode(pb.NetworkConfig, wire.encode_varint(2**40))


def test_varint_above_64_bits_rejected_everywhere():
    # 2^64 exactly: 10 bytes, final byte 0x02.  Must be rejected even at raw
    # length/count/tag positions where no typed range check applies.
    overflow = b"\x80" * 9 + b"\x02"
    v_max = b"\xff" * 9 + b"\x01"
    assert wire.decode_varint(v_max, 0)[0] == 2**64 - 1
    with pytest.raises(ValueError):
        wire.decode_varint(overflow, 0)
    # At a length position (RequestAck.digest).
    with pytest.raises(ValueError):
        pb.decode(pb.RequestAck, b"\x01\x01" + overflow)
    # At a count position (NetworkConfig.nodes).
    with pytest.raises(ValueError):
        pb.decode(pb.NetworkConfig, overflow)
    # At a oneof-tag position.
    with pytest.raises(ValueError):
        pb.decode(pb.Msg, overflow)


def test_wrong_class_decode_rejected():
    # A Prepare encoding fed to Commit decodes fine (same shape) — but a
    # Prepare fed to NetworkState must fail somewhere in the field walk.
    enc = pb.encode(pb.Prepare(seq_no=1, epoch=2, digest=b"\xff" * 32))
    with pytest.raises((ValueError, TypeError)):
        pb.decode(pb.NetworkState, enc)
