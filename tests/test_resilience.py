"""Resilience primitives (mirbft_tpu/resilience.py) and the fault-hardened
crypto planes: circuit breaker lifecycle, backoff bounds, device-failure
fallback to the host oracle, and the status.py snapshots that surface it."""

import random

from mirbft_tpu.chaos.faults import FlakyDigestBackend
from mirbft_tpu.resilience import CLOSED, HALF_OPEN, OPEN, Backoff, CircuitBreaker
from mirbft_tpu.status import crypto_plane_status
from mirbft_tpu.testengine.crypto_plane import (
    AsyncKernelHashPlane,
    CoalescingHashPlane,
    DevicePlaneError,
    _host_digest_many,
)
from mirbft_tpu.testengine.signing import (
    AsyncSignaturePlane,
    SignaturePlane,
    host_verifier,
    make_signer,
)


# ---------------------------------------------------------------------------
# CircuitBreaker
# ---------------------------------------------------------------------------


def test_breaker_trips_after_consecutive_failures():
    b = CircuitBreaker(failure_threshold=3, probe_interval=4)
    assert b.state == CLOSED
    b.record_failure()
    b.record_success()  # success resets the consecutive count
    b.record_failure()
    b.record_failure()
    assert b.state == CLOSED
    b.record_failure()
    assert b.state == OPEN and b.trips == 1


def test_breaker_probes_and_recloses():
    b = CircuitBreaker(failure_threshold=1, probe_interval=3)
    b.record_failure()
    assert b.state == OPEN
    # Denied calls accumulate until the probe_interval-th becomes a probe.
    assert [b.allow() for _ in range(3)] == [False, False, True]
    assert b.state == HALF_OPEN
    assert not b.allow()  # probe in flight: others keep falling back
    b.record_success()
    assert b.state == CLOSED and b.allow()


def test_breaker_failed_probe_reopens():
    b = CircuitBreaker(failure_threshold=1, probe_interval=1)
    b.record_failure()
    assert b.allow()  # immediately converted to a probe
    b.record_failure()
    assert b.state == OPEN and b.trips == 1  # re-open, not a fresh trip
    assert b.probes == 1


def test_backoff_grows_to_cap_with_jitter():
    b = Backoff(base=0.1, factor=2.0, cap=1.0, rng=random.Random(7))
    delays = [b.next() for _ in range(8)]
    ceilings = [0.1, 0.2, 0.4, 0.8, 1.0, 1.0, 1.0, 1.0]
    for delay, ceiling in zip(delays, ceilings):
        assert 0.5 * ceiling <= delay <= ceiling
    b.reset()
    assert b.next() <= 0.1


def test_backoff_stays_bounded_over_thousands_of_failures():
    """A peer down for hours produces thousands of consecutive dial
    failures; the exponent must not overflow and every delay must stay
    within [0, cap] — the transport's sender threads call next() in an
    unbounded retry loop."""
    b = Backoff(base=0.05, factor=2.0, cap=2.0, rng=random.Random(3))
    delays = [b.next() for _ in range(5000)]
    assert all(0.0 <= d <= 2.0 for d in delays)
    # Deep into the failure run the delays still hover near the cap
    # (full jitter: uniform in [cap/2, cap]), not collapsed or inf.
    tail = delays[-100:]
    assert all(1.0 <= d <= 2.0 for d in tail)
    b.reset()
    assert b.next() <= 0.05


# ---------------------------------------------------------------------------
# Digest plane: device failure degrades to the host oracle
# ---------------------------------------------------------------------------


def _expected_digests(msgs):
    return _host_digest_many(msgs)


def _drain_plane(plane, preimages):
    """Submit preimages and pull every digest through the resolve path
    (what resolve_event does for a delivered EventActionResults)."""
    handles = plane.submit([[p] for p in preimages])
    return [plane._resolve(h.index) for h in handles]


def test_coalescing_plane_rescues_dead_device_batches():
    flaky = FlakyDigestBackend(fail_from=0, fail_until=2, mode="die")
    plane = CoalescingHashPlane(
        digest_many=flaky,
        breaker=CircuitBreaker(failure_threshold=1, probe_interval=1),
    )
    msgs = [b"m%d" % i for i in range(4)]
    got = _drain_plane(plane, msgs)
    assert got == _expected_digests(msgs)  # values correct despite failure
    assert plane.device_errors == 1 and plane.fallback_digests == 4
    assert plane.breaker.state == OPEN

    # Next wave: breaker open, first call becomes a probe; backend is
    # still failing (call 1 < fail_until) so it re-opens, after which the
    # following wave's probe (call 2) succeeds and re-closes.
    more = [b"n%d" % i for i in range(3)]
    assert _drain_plane(plane, more) == _expected_digests(more)
    last = [b"o%d" % i for i in range(2)]
    assert _drain_plane(plane, last) == _expected_digests(last)
    assert plane.breaker.state == CLOSED


def test_coalescing_plane_short_read_detected():
    plane = CoalescingHashPlane(
        digest_many=lambda msgs: _host_digest_many(msgs)[:-1]
    )
    msgs = [b"a", b"b", b"c"]
    assert _drain_plane(plane, msgs) == _expected_digests(msgs)
    assert plane.device_errors == 1


def test_coalescing_plane_timeout_counts_against_breaker():
    plane = CoalescingHashPlane(timeout_s=0.0)  # every call "times out"
    msgs = [b"x", b"y"]
    assert _drain_plane(plane, msgs) == _expected_digests(msgs)
    assert plane.device_timeouts == 1
    assert plane.breaker.consecutive_failures == 1


def test_async_plane_launch_failure_host_rescues():
    def exploding_kernel(_blocks, _n_blocks):
        raise DevicePlaneError("injected launch failure")

    plane = AsyncKernelHashPlane(
        kernel_fn=exploding_kernel, min_device_rows=1, chunk_rows=256
    )
    msgs = [b"wave%d" % i for i in range(8)]
    handles = plane.submit([[m] for m in msgs])
    plane.on_time(1)  # wave boundary: launches, explodes, host-rescues
    got = [plane._resolve(h.index) for h in handles]
    assert got == _expected_digests(msgs)
    assert plane.device_errors >= 1 and plane.host_digests == len(msgs)


# ---------------------------------------------------------------------------
# Signature plane: verifier failure degrades to the host oracle
# ---------------------------------------------------------------------------


def _signed_items(n):
    signer = make_signer()
    return [
        (7, req_no, signer(7, req_no, b"payload%d" % req_no))
        for req_no in range(n)
    ]


def test_signature_plane_verifier_failure_falls_back_to_host():
    calls = []

    def dying_verifier(batch):
        calls.append(len(batch))
        raise DevicePlaneError("injected verify failure")

    plane = SignaturePlane(
        verifier=dying_verifier,
        breaker=CircuitBreaker(failure_threshold=1, probe_interval=1),
    )
    items = _signed_items(3)
    for client_id, req_no, data in items:
        plane.submit(client_id, req_no, data)
    assert all(plane.valid(*item) for item in items)
    assert calls == [3]  # one device attempt, then host fallback
    assert plane.device_errors == 1 and plane.fallback_verifies == 3
    assert plane.breaker.state == OPEN

    # Tampered data still rejected through the fallback path.
    client_id, req_no, data = _signed_items(1)[0]
    assert not plane.valid(client_id, req_no, data[:-1] + b"\x00")


def test_signature_plane_short_verdicts_detected():
    plane = SignaturePlane(verifier=lambda batch: host_verifier(batch)[:-1])
    items = _signed_items(2)
    for item in items:
        plane.submit(*item)
    assert all(plane.valid(*item) for item in items)
    assert plane.device_errors == 1


def test_async_signature_plane_launch_failure_host_verifies_wave():
    def exploding_launch(_rows, sublanes):
        raise DevicePlaneError("injected launch failure")

    plane = AsyncSignaturePlane(
        chunk=4, min_device_rows=1, launch_fn=exploding_launch
    )
    items = _signed_items(4)  # == chunk: submit triggers the launch
    for item in items:
        plane.submit(*item)
    assert all(plane.valid(*item) for item in items)
    assert plane.device_errors == 1
    assert plane.host_verifies == 4 and plane.fallback_verifies == 4


def test_async_signature_plane_readback_failure_host_rescues():
    class PoisonArray:
        def __len__(self):
            raise DevicePlaneError("injected readback failure")

        def __iter__(self):
            raise DevicePlaneError("injected readback failure")

    plane = AsyncSignaturePlane(
        chunk=3, min_device_rows=1, launch_fn=lambda rows, sublanes: PoisonArray()
    )
    items = _signed_items(3)
    for item in items:
        plane.submit(*item)
    assert all(plane.valid(*item) for item in items)
    assert plane.device_errors == 1
    assert plane.breaker.consecutive_failures == 1
    assert plane.fallback_verifies == 3


def test_async_signature_plane_undemanded_chunks_stay_bounded():
    """Regression for the chunk leak: under manglers a submitted request
    may never be demanded (drops, redirects, crashed recipients), and
    launched chunks used to pin their wave material in _chunks/_chunk_of
    for the whole run.  Stale chunks must now retire at wave boundaries
    and the outstanding-chunk cap must hold over a long faulted run."""
    import numpy as np

    plane = AsyncSignaturePlane(
        chunk=4,
        min_device_rows=1,
        max_outstanding=3,
        stale_boundaries=2,
        launch_fn=lambda rows, sublanes: np.ones(len(rows), dtype=bool),
    )
    signer = make_signer()
    first = signer(7, 0, b"payload0")
    req_no = 0
    for boundary in range(30):
        for _ in range(4):  # one full chunk per boundary, never demanded
            plane.submit(7, req_no, signer(7, req_no, b"payload%d" % req_no))
            req_no += 1
        plane.on_time(boundary)
        assert len(plane._chunks) <= plane.max_outstanding
        assert len(plane._chunk_of) <= plane.max_outstanding * plane.chunk
    assert plane.forced_retirements > 0
    # Retired chunks resolved into real verdicts: only the most recent
    # (still legitimately in flight) chunks may remain pending.
    pending = sum(1 for v in plane._verdicts.values() if v is None)
    assert pending <= plane.max_outstanding * plane.chunk
    # A retired-without-demand verdict is still served from the cache.
    assert plane.valid(7, 0, first) is True


# ---------------------------------------------------------------------------
# status.py snapshots
# ---------------------------------------------------------------------------


def test_crypto_plane_status_snapshot():
    flaky = FlakyDigestBackend(fail_from=0, fail_until=1, mode="die")
    plane = CoalescingHashPlane(
        digest_many=flaky,
        breaker=CircuitBreaker(failure_threshold=1, probe_interval=1),
    )
    _drain_plane(plane, [b"p", b"q"])
    snap = crypto_plane_status(plane)
    assert snap.plane == "CoalescingHashPlane"
    assert snap.device_errors == 1 and snap.fallback_work == 2
    assert snap.breaker.state == OPEN and snap.breaker.trips == 1
    assert "breaker: open" in snap.pretty()
    assert '"device_errors": 1' in snap.to_json()
