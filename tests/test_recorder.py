"""Flight recorder & resource telemetry: the black-box postmortem path.

Covers the four load-bearing guarantees: the ring stays bounded no
matter how much is recorded; an invariant failure leaves a parseable
dump on disk; per-node dumps merge into one causal timeline via
``obsv --postmortem``; and the least-squares leak verdict separates
genuine growth from sawtooth/noise so the ``obsv --diff`` gate can fail
PRs on it.  Ends with a seconds-scale smoke of the bench soak rung
(real nodes, real sockets, on-disk stores)."""

import dataclasses
import json
import os

import pytest

from mirbft_tpu.obsv.recorder import (
    SCHEMA,
    SEGMENT_KEEP,
    FlightRecorder,
    annotate_dump,
    dump_to_trace,
    load_dumps,
    postmortem,
)
from mirbft_tpu.obsv.resources import leak_verdict, sample_process


# ----------------------------------------------------------------------
# Ring buffer bounds
# ----------------------------------------------------------------------


def test_ring_stays_bounded_under_load():
    rec = FlightRecorder("load", capacity=64, autoflush_every=0)
    for i in range(10_000):
        rec.record_event(f"ev{i % 7}", args={"i": i})
    dump = rec.snapshot()
    assert dump["schema"] == SCHEMA
    assert len(dump["entries"]) == 64
    assert dump["recorded"] == 10_000
    assert dump["overwritten"] == 10_000 - 64
    # Oldest-first, and the tail is the newest record.
    ts = [e["ts_us"] for e in dump["entries"]]
    assert ts == sorted(ts)
    assert dump["entries"][-1]["args"]["i"] == 9_999


def test_segments_rotate_in_place(tmp_path):
    rec = FlightRecorder(
        3, dump_dir=str(tmp_path), capacity=32, autoflush_every=8
    )
    for i in range(100):
        rec.record_milestone("m", args={"i": i})
    names = sorted(os.listdir(tmp_path))
    assert len(names) <= SEGMENT_KEEP
    assert all(n.endswith(".flight.json") for n in names)
    # load_dumps keeps the newest committed segment for the node.
    dumps = load_dumps(str(tmp_path))
    assert set(dumps) == {3}
    _path, dump = dumps[3]
    assert dump["entries"][-1]["args"]["i"] == 95  # last autoflush at 96
    # A torn/garbage file is skipped, not fatal.
    (tmp_path / "nodeX-0.flight.json").write_text("{torn")
    assert set(load_dumps(str(tmp_path))) == {3}


def test_annotate_dump_adds_keys_atomically(tmp_path):
    rec = FlightRecorder(0, dump_dir=str(tmp_path), autoflush_every=0)
    rec.record_event("boot")
    path = rec.flush("exit")
    assert annotate_dump(path, reason="sigkill-reaped", rc=-9)
    dump = json.loads(open(path).read())
    assert dump["reason"] == "sigkill-reaped"
    assert dump["rc"] == -9
    assert dump["entries"]  # payload intact


# ----------------------------------------------------------------------
# Invariant failure -> dump on disk
# ----------------------------------------------------------------------


def test_chaos_invariant_failure_leaves_parseable_dump(monkeypatch, tmp_path):
    from mirbft_tpu.chaos.runner import run_scenario
    from mirbft_tpu.chaos.scenarios import smoke_matrix

    monkeypatch.setenv("MIRBFT_CHAOS_DUMP_DIR", str(tmp_path))
    # Starve the engine of steps: convergence is impossible, the
    # no-convergence invariant fires, and the recorder must flush.
    scenario = dataclasses.replace(smoke_matrix()[0], max_steps=3)
    result = run_scenario(scenario, seed=7)
    assert result.violation
    assert result.dump
    dump = json.loads(open(result.dump).read())
    assert dump["schema"] == SCHEMA
    assert dump["reason"] == "invariant-failure"
    notes = [e for e in dump["entries"] if e["kind"] == "note"]
    assert any(
        e["name"] == "invariant.violation"
        and e["args"]["scenario"] == scenario.name
        and e["args"]["seed"] == 7
        for e in notes
    )
    # The machine-readable scenario record carries the same path.
    assert result.to_dict()["dump"] == result.dump


# ----------------------------------------------------------------------
# Postmortem merge round-trip
# ----------------------------------------------------------------------


@pytest.fixture()
def four_node_dumps(tmp_path):
    for node in range(4):
        rec = FlightRecorder(
            node, dump_dir=str(tmp_path), autoflush_every=0
        )
        # Node n thinks every peer's clock reads n*1000ns behind.
        rec.set_clock_offsets(
            {peer: node * 1000 for peer in range(4) if peer != node}
        )
        for i in range(10):
            rec.record_event("commit", args={"seq": i})
        rec.record_milestone("checkpoint.stable", args={"seq": 9})
        rec.flush("exit")
    return str(tmp_path)


def test_postmortem_merges_four_nodes(four_node_dumps, tmp_path):
    out = str(tmp_path / "merged.json")
    result = postmortem(four_node_dumps, out_path=out)
    assert result["nodes"] == [0, 1, 2, 3]
    merged = json.loads(open(out).read())
    instants = [
        ev
        for ev in merged["traceEvents"]
        if ev.get("ph") == "i" and ev.get("cat", "").startswith("flight.")
    ]
    # 4 nodes x (10 events + 1 milestone), all preserved by the merge.
    assert len(instants) == 44
    assert {ev["pid"] for ev in instants} == {0, 1, 2, 3}
    # The rendered timeline ends at the latest instant.
    assert result["timeline"].splitlines()
    assert "checkpoint.stable" in result["timeline"]


def test_postmortem_cli_round_trip(four_node_dumps, tmp_path, capsys):
    from mirbft_tpu.obsv.__main__ import main as obsv_main

    out = str(tmp_path / "cli-merged.json")
    rc = obsv_main(["--postmortem", four_node_dumps, "--out", out])
    assert rc == 0
    assert json.loads(open(out).read())["traceEvents"]
    text = capsys.readouterr().out
    assert "4 node dump(s)" in text


def test_postmortem_empty_dir_is_distinct_error(tmp_path, capsys):
    from mirbft_tpu.obsv.__main__ import main as obsv_main

    assert obsv_main(["--postmortem", str(tmp_path)]) == 2


def test_dump_to_trace_carries_clock_sync():
    rec = FlightRecorder(2)
    rec.set_clock_offsets({0: -500, 1: 250})
    rec.record_event("x")
    trace = dump_to_trace(rec.snapshot())
    sync = [
        ev for ev in trace["traceEvents"] if ev["name"] == "clock_sync"
    ]
    assert sync and sync[0]["args"]["offsets_ns"] == {"0": -500, "1": 250}


# ----------------------------------------------------------------------
# Leak verdicts
# ----------------------------------------------------------------------


def test_leak_verdict_growing_on_linear_series():
    series = [(t * 1.0, 1_000_000 + t * 5_000) for t in range(60)]
    v = leak_verdict(series)
    assert v["verdict"] == "growing"
    assert v["confidence"] > 0.9
    assert v["rel_pct_per_min"] > 5.0
    assert v["n"] == 60


def test_leak_verdict_flat_on_constant_and_noisy_series():
    flat = leak_verdict([(t * 1.0, 1_000_000) for t in range(60)])
    assert flat["verdict"] == "flat"
    assert flat["confidence"] == 1.0
    # Zero-mean noise: slope ~0, stays flat.
    noisy = leak_verdict(
        [(t * 1.0, 1_000_000 + (7 * t % 13 - 6) * 1_000) for t in range(60)]
    )
    assert noisy["verdict"] == "flat"


def test_leak_verdict_sawtooth_is_confident_flat():
    # Disk between compactions: steep nominal slope the fit can't
    # explain (r^2 ~ 0) must read as flat with high confidence.
    series = [(t * 1.0, 1_000_000 + (t % 10) * 400_000) for t in range(60)]
    v = leak_verdict(series)
    assert v["verdict"] == "flat"
    assert v["r2"] < 0.5
    assert v["confidence"] > 0.5


def test_leak_verdict_short_series_stays_flat():
    v = leak_verdict([(t * 1.0, 100 + t * 50) for t in range(5)])
    assert v["verdict"] == "flat"  # n < min_samples, however steep


def test_sample_process_reports_real_resources(tmp_path):
    (tmp_path / "blob").write_bytes(b"x" * 4096)
    sample = sample_process(dirs={"store": str(tmp_path)})
    assert sample["rss_bytes"] > 1_000_000
    assert sample["open_fds"] > 0
    assert sample["threads"] >= 1
    assert sample["disk.store"] >= 4096


# ----------------------------------------------------------------------
# Diff gate consumes soak verdicts
# ----------------------------------------------------------------------


def _bench_artifact(leak):
    return {
        "schema": "mirbft-bench/1",
        "stages": {},
        "soak": {"seconds": 30.0, "commits": 100, "leak": leak},
    }


def test_diff_leak_gate_fails_on_growing(tmp_path):
    from mirbft_tpu.obsv.diff import diff_files, render_report

    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(_bench_artifact({})))
    b.write_text(
        json.dumps(
            _bench_artifact(
                {
                    "rss_bytes": {
                        "verdict": "growing",
                        "confidence": 0.97,
                        "rel_pct_per_min": 12.0,
                        "first": 1e6,
                        "last": 2e6,
                    },
                    "open_fds": {"verdict": "flat", "confidence": 1.0},
                }
            )
        )
    )
    report = diff_files(str(a), str(b))
    assert not report["ok"]
    assert [f["series"] for f in report["leak_failures"]] == [
        "soak.rss_bytes"
    ]
    assert "LEAK" in render_report(report)

    # CLI contract: leak regression exits nonzero like a p95 regression.
    from mirbft_tpu.obsv.__main__ import main as obsv_main

    assert obsv_main(["--diff", str(a), str(b)]) == 1
    # Flat-only verdicts pass.
    b.write_text(
        json.dumps(
            _bench_artifact({"rss_bytes": {"verdict": "flat",
                                           "confidence": 0.9}})
        )
    )
    assert obsv_main(["--diff", str(a), str(b)]) == 0


# ----------------------------------------------------------------------
# Soak smoke (tier-1, seconds-scale)
# ----------------------------------------------------------------------


def test_soak_smoke_commits_and_emits_verdicts():
    import bench

    out = bench.soak_run(duration_s=6.0, sample_interval_s=0.25)
    assert out["commits"] > 0
    assert out["samples"] >= 8
    assert set(out["leak"]) == {
        "rss_bytes",
        "open_fds",
        "threads",
        "disk.reqstore",
        "disk.wal",
    }
    for verdict in out["leak"].values():
        assert verdict["verdict"] in ("flat", "growing")
    # fd/thread leaks have no warm-up excuse even at smoke scale.
    assert out["leak"]["open_fds"]["verdict"] == "flat"
    assert out["leak"]["threads"]["verdict"] == "flat"
