"""Schema-layer gate: golden vectors + round-trip of every message type.

Mirrors the reference's reliance on protobuf round-tripping (the event-log
reader/writer tests at reference eventlog/interceptor_test.go:48-49 assert
exact byte sizes); here we assert exact golden bytes for a few messages and
round-trip stability for all of them.
"""

import random

import pytest

from mirbft_tpu import pb, wire


def test_varint_golden():
    assert wire.encode_varint(0) == b"\x00"
    assert wire.encode_varint(1) == b"\x01"
    assert wire.encode_varint(127) == b"\x7f"
    assert wire.encode_varint(128) == b"\x80\x01"
    assert wire.encode_varint(300) == b"\xac\x02"
    assert wire.encode_varint(2**64 - 1) == b"\xff" * 9 + b"\x01"


def test_varint_roundtrip_fuzz():
    rng = random.Random(7)
    for _ in range(2000):
        v = rng.getrandbits(rng.randrange(1, 64))
        enc = wire.encode_varint(v)
        dec, pos = wire.decode_varint(enc, 0)
        assert dec == v and pos == len(enc)


def test_varint_rejects_noncanonical():
    with pytest.raises(ValueError):
        wire.decode_varint(b"\x80\x00", 0)  # over-long zero


def test_request_ack_golden():
    ack = pb.RequestAck(client_id=1, req_no=300, digest=b"\xaa\xbb")
    enc = pb.encode(ack)
    assert enc == b"\x01" + b"\xac\x02" + b"\x02\xaa\xbb"
    assert pb.decode(pb.RequestAck, enc) == ack


def test_msg_oneof_roundtrip():
    msg = pb.Msg(
        type=pb.Preprepare(
            seq_no=5,
            epoch=2,
            batch=[pb.RequestAck(client_id=9, req_no=1, digest=b"\x01" * 32)],
        )
    )
    enc = pb.encode(msg)
    assert pb.decode(pb.Msg, enc) == msg


def test_oneof_distinguishes_echo_and_ready():
    cfg = pb.NewEpochConfig(
        config=pb.EpochConfig(number=3, leaders=[0, 1, 2], planned_expiration=50),
        starting_checkpoint=pb.Checkpoint(seq_no=20, value=b"v"),
        final_preprepares=[b"", b"\x02" * 32],
    )
    echo = pb.Msg(type=pb.NewEpochEcho(new_epoch_config=cfg))
    ready = pb.Msg(type=pb.NewEpochReady(new_epoch_config=cfg))
    assert pb.encode(echo) != pb.encode(ready)
    assert pb.decode(pb.Msg, pb.encode(echo)) == echo
    assert pb.decode(pb.Msg, pb.encode(ready)) == ready


def _sample_network_state():
    return pb.NetworkState(
        config=pb.NetworkConfig(
            nodes=[0, 1, 2, 3],
            checkpoint_interval=20,
            max_epoch_length=200,
            number_of_buckets=4,
            f=1,
        ),
        clients=[
            pb.NetworkClient(
                id=7,
                width=100,
                width_consumed_last_checkpoint=3,
                low_watermark=12,
                committed_mask=b"\x0f",
            )
        ],
        pending_reconfigurations=[
            pb.Reconfiguration(type=pb.ReconfigNewClient(id=8, width=50)),
            pb.Reconfiguration(type=pb.ReconfigRemoveClient(client_id=7)),
        ],
        reconfigured=True,
    )


SAMPLES = [
    pb.Request(client_id=1, req_no=2, data=b"hello"),
    pb.RequestAck(client_id=1, req_no=2, digest=b"\x00" * 32),
    _sample_network_state(),
    pb.Persistent(
        type=pb.QEntry(
            seq_no=9,
            digest=b"\x03" * 32,
            requests=[pb.RequestAck(client_id=1, req_no=2, digest=b"d")],
        )
    ),
    pb.Persistent(type=pb.PEntry(seq_no=9, digest=b"\x04" * 32)),
    pb.Persistent(
        type=pb.CEntry(
            seq_no=20, checkpoint_value=b"cp", network_state=_sample_network_state()
        )
    ),
    pb.Persistent(
        type=pb.NEntry(
            seq_no=21,
            epoch_config=pb.EpochConfig(number=1, leaders=[0, 1], planned_expiration=99),
        )
    ),
    pb.Persistent(type=pb.FEntry(ends_epoch_config=pb.EpochConfig(number=1))),
    pb.Persistent(type=pb.ECEntry(epoch_number=2)),
    pb.Persistent(type=pb.TEntry(seq_no=40, value=b"t")),
    pb.Persistent(type=pb.Suspect(epoch=1)),
    pb.Msg(type=pb.Prepare(seq_no=1, epoch=0, digest=b"x")),
    pb.Msg(type=pb.Commit(seq_no=1, epoch=0, digest=b"x")),
    pb.Msg(type=pb.Checkpoint(seq_no=20, value=b"v")),
    pb.Msg(type=pb.Suspect(epoch=3)),
    pb.Msg(
        type=pb.EpochChange(
            new_epoch=4,
            checkpoints=[pb.Checkpoint(seq_no=20, value=b"v")],
            p_set=[pb.EpochChangeSetEntry(epoch=3, seq_no=21, digest=b"p")],
            q_set=[pb.EpochChangeSetEntry(epoch=3, seq_no=21, digest=b"q")],
        )
    ),
    pb.Msg(
        type=pb.EpochChangeAck(
            originator=2, epoch_change=pb.EpochChange(new_epoch=4)
        )
    ),
    pb.Msg(
        type=pb.NewEpoch(
            new_config=pb.NewEpochConfig(
                config=pb.EpochConfig(number=4, leaders=[1, 2]),
                starting_checkpoint=pb.Checkpoint(seq_no=20, value=b"v"),
                final_preprepares=[b"", b"d"],
            ),
            epoch_changes=[pb.RemoteEpochChange(node_id=1, digest=b"e")],
        )
    ),
    pb.Msg(type=pb.FetchBatch(seq_no=5, digest=b"b")),
    pb.Msg(
        type=pb.ForwardBatch(
            seq_no=5,
            request_acks=[pb.RequestAck(client_id=1, req_no=1, digest=b"d")],
            digest=b"b",
        )
    ),
    pb.Msg(type=pb.FetchRequest(client_id=1, req_no=1, digest=b"d")),
    pb.Msg(
        type=pb.ForwardRequest(
            request_ack=pb.RequestAck(client_id=1, req_no=1, digest=b"d"),
            request_data=b"payload",
        )
    ),
    pb.Msg(type=pb.RequestAck(client_id=1, req_no=1, digest=b"d")),
    pb.StateEvent(
        type=pb.EventInitialize(
            initial_parms=pb.InitialParameters(
                id=3,
                batch_size=10,
                heartbeat_ticks=2,
                suspect_ticks=4,
                new_epoch_timeout_ticks=8,
                buffer_size=5 * 1024 * 1024,
            )
        )
    ),
    pb.StateEvent(
        type=pb.EventLoadEntry(
            index=1, data=pb.Persistent(type=pb.ECEntry(epoch_number=1))
        )
    ),
    pb.StateEvent(
        type=pb.EventLoadRequest(
            request_ack=pb.RequestAck(client_id=1, req_no=1, digest=b"d")
        )
    ),
    pb.StateEvent(type=pb.EventCompleteInitialization()),
    pb.StateEvent(
        type=pb.EventActionResults(
            digests=[
                pb.HashResult(
                    digest=b"\x05" * 32,
                    type=pb.HashOriginRequest(
                        source=1, request=pb.Request(client_id=1, req_no=1, data=b"x")
                    ),
                ),
                pb.HashResult(
                    digest=b"\x06" * 32,
                    type=pb.HashOriginBatch(
                        source=1,
                        epoch=0,
                        seq_no=1,
                        request_acks=[pb.RequestAck(client_id=1, req_no=1, digest=b"d")],
                    ),
                ),
                pb.HashResult(
                    digest=b"\x07" * 32,
                    type=pb.HashOriginEpochChange(
                        source=1, origin=2, epoch_change=pb.EpochChange(new_epoch=1)
                    ),
                ),
                pb.HashResult(
                    digest=b"\x08" * 32,
                    type=pb.HashOriginVerifyBatch(
                        source=1,
                        seq_no=2,
                        request_acks=[],
                        expected_digest=b"\x08" * 32,
                    ),
                ),
                pb.HashResult(
                    digest=b"\x09" * 32,
                    type=pb.HashOriginVerifyRequest(
                        source=1,
                        request_ack=pb.RequestAck(client_id=1, req_no=1, digest=b"d"),
                        request_data=b"x",
                    ),
                ),
            ],
            checkpoints=[
                pb.CheckpointResult(
                    seq_no=20,
                    value=b"v",
                    network_state=_sample_network_state(),
                    reconfigured=True,
                )
            ],
        )
    ),
    pb.StateEvent(
        type=pb.EventTransfer(c_entry=pb.CEntry(seq_no=20, checkpoint_value=b"v"))
    ),
    pb.StateEvent(
        type=pb.EventPropose(request=pb.Request(client_id=1, req_no=1, data=b"x"))
    ),
    pb.StateEvent(
        type=pb.EventStep(
            source=2, msg=pb.Msg(type=pb.Prepare(seq_no=1, epoch=0, digest=b"x"))
        )
    ),
    pb.StateEvent(type=pb.EventTick()),
    pb.StateEvent(type=pb.EventActionsReceived()),
]


def sample_id(s):
    if hasattr(s, "type") and s.type is not None:
        return type(s.type).__name__
    return type(s).__name__


@pytest.mark.parametrize("sample", SAMPLES, ids=sample_id)
def test_roundtrip_all(sample):
    enc = pb.encode(sample)
    dec = pb.decode(type(sample), enc)
    assert dec == sample
    # Stability: re-encoding the decoded value is byte-identical.
    assert pb.encode(dec) == enc


def test_trailing_bytes_rejected():
    enc = pb.encode(pb.RequestAck(client_id=1, req_no=1, digest=b"d"))
    with pytest.raises(ValueError):
        pb.decode(pb.RequestAck, enc + b"\x00")
