"""TCP transport tier: real sockets between replicas (the DCN path).

A 4-node network where every protocol message crosses a localhost TCP
connection must still commit exactly once per node with agreeing chains —
and a mid-run connection teardown must be absorbed as ordinary message
loss (the protocol's retransmit ticks recover)."""

import hashlib
import queue
import threading
import time

from mirbft_tpu import pb
from mirbft_tpu.runtime import (
    Config,
    Node,
    TcpTransport,
)
from mirbft_tpu.runtime.node import NodeStopped, standard_initial_network_state
from mirbft_tpu.runtime.processor import Log, SerialProcessor


class _ChainLog(Log):
    def __init__(self):
        self.chain = b""
        self.commits = []
        self.commit_events = queue.Queue()

    def apply(self, q_entry):
        for ack in q_entry.requests:
            h = hashlib.sha256()
            h.update(self.chain)
            h.update(ack.digest)
            self.chain = h.digest()
            self.commits.append((ack.client_id, ack.req_no))
            self.commit_events.put((ack.client_id, ack.req_no))

    def snap(self, network_config, clients_state):
        return self.chain


class _MemWal:
    def __init__(self):
        self.entries = []

    def write(self, index, entry):
        self.entries.append((index, entry))

    def truncate(self, index):
        self.entries = [(i, e) for i, e in self.entries if i >= index]

    def sync(self):
        pass


class _MemReqStore:
    def __init__(self):
        self.reqs = {}

    def store(self, ack, data):
        self.reqs[ack.digest] = data

    def get(self, ack):
        return self.reqs.get(ack.digest)

    def commit(self, ack):
        self.reqs.pop(ack.digest, None)

    def sync(self):
        pass


class _TcpReplica:
    def __init__(self, node_id, initial_state, registry):
        self.transport = TcpTransport(node_id)
        self.node = Node.start_new(Config(id=node_id), initial_state)
        self.transport.serve(self.node)
        self.app_log = _ChainLog()
        self.processor = SerialProcessor(
            self.node,
            self.transport.link(),
            self.app_log,
            _MemWal(),
            _MemReqStore(),
        )
        # Out-of-band state fetch registry (the consumer's job; a real
        # deployment fetches snapshots over its own channel).
        self.registry = registry
        registry[node_id] = self
        self.checkpoints = {}  # seq_no -> (value, pb.NetworkState)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._consume, daemon=True)

    def start(self):
        self._thread.start()

    def _consume(self):
        last_tick = time.monotonic()
        while not self._stop.is_set():
            actions = self.node.ready(timeout=0.01)
            if actions is not None:
                results = self.processor.process(actions)
                for cr in results.checkpoints:
                    self.checkpoints[cr.checkpoint.seq_no] = (
                        cr.value,
                        pb.NetworkState(
                            config=cr.checkpoint.network_config,
                            clients=cr.checkpoint.clients_state,
                            pending_reconfigurations=list(
                                cr.reconfigurations
                            ),
                        ),
                    )
                if results.digests or results.checkpoints:
                    try:
                        self.node.add_results(results)
                    except NodeStopped:
                        return
                if actions.state_transfer is not None:
                    self._serve_transfer(actions.state_transfer)
            if time.monotonic() - last_tick >= 0.05:
                last_tick = time.monotonic()
                try:
                    self.node.tick()
                except NodeStopped:
                    return

    def _serve_transfer(self, target):
        for node_id, peer in list(self.registry.items()):
            if node_id == self.node.config.id:
                continue
            entry = peer.checkpoints.get(target.seq_no)
            if entry is None or entry[0] != target.value:
                continue
            value, network_state = entry
            self.app_log.chain = value  # adopt the app state wholesale
            try:
                self.node.state_transfer_complete(target, network_state)
            except NodeStopped:
                pass
            return
        try:
            self.node.state_transfer_failed(target)
        except NodeStopped:
            pass

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)
        self.node.stop()
        self.transport.close()


def test_four_node_consensus_over_tcp():
    state = standard_initial_network_state(4, [9])
    registry = {}
    replicas = [_TcpReplica(i, state, registry) for i in range(4)]
    try:
        # Full mesh: everyone knows everyone's listening address.
        for a in replicas:
            for b in replicas:
                if a is not b:
                    a.transport.connect(b.node.config.id, b.transport.address)
        for replica in replicas:
            replica.start()

        requests = [
            pb.Request(client_id=9, req_no=i, data=b"%d" % i)
            for i in range(12)
        ]
        for request in requests[:6]:
            for replica in replicas:
                replica.node.propose(request)

        # Mid-run teardown of one node's outbound connections: the frames
        # in flight die with the sockets; retransmission must recover.
        time.sleep(0.3)
        with replicas[0].transport._lock:
            conns = [c for c, _lock in replicas[0].transport._conns.values()]
            replicas[0].transport._conns.clear()
        for conn in conns:
            conn.close()

        for request in requests[6:]:
            for replica in replicas:
                replica.node.propose(request)

        # Convergence: a replica that fell behind the teardown may adopt a
        # peer checkpoint via state transfer, in which case the skipped
        # requests land in its app state without individual commit events —
        # so the gate is chain equality across all four, with at least one
        # replica having observed every commit directly.
        expected = {(9, r.req_no) for r in requests}
        deadline = time.monotonic() + 120
        while True:
            full = [
                r
                for r in replicas
                if expected <= {(c, n) for c, n in r.app_log.commits}
            ]
            chains = {r.app_log.chain for r in replicas}
            if full and len(chains) == 1 and b"" not in chains:
                break
            assert time.monotonic() < deadline, (
                f"no convergence: {[len(set(r.app_log.commits)) for r in replicas]} "
                f"commits, {len(chains)} chains; "
                f"exits={[r.node.exit_error for r in replicas]}"
            )
            time.sleep(0.05)

        for replica in replicas:
            assert len(replica.app_log.commits) == len(
                set(replica.app_log.commits)
            ), "duplicate commit!"
    finally:
        for replica in replicas:
            replica.stop()
    assert all(r.node.exit_error is None for r in replicas)


# -- transport failure paths (VERDICT r3 item 9) -----------------------------


def _rebind(node_id, addr, timeout=10.0):
    """Re-create a transport on a just-closed address (the OS may hold the
    port briefly; retry until it frees)."""
    deadline = time.monotonic() + timeout
    while True:
        try:
            return TcpTransport(node_id, host=addr[0], port=addr[1])
        except OSError:
            if time.monotonic() >= deadline:
                raise
            time.sleep(0.1)


def test_send_to_down_peer_drops_silently():
    """A send to a registered peer with nothing listening is dropped (the
    Link contract is fire-and-forget; retransmit ticks recover)."""
    import socket as socketlib

    t = TcpTransport(0)
    # A bound-but-not-listening port refuses connections deterministically
    # (a freed ephemeral port can be self-connected to on localhost).
    dead = socketlib.socket()
    dead.bind(("127.0.0.1", 0))
    dead_addr = dead.getsockname()
    try:
        t.connect(1, dead_addr)
        t.link().send(1, pb.Msg(type=pb.Suspect(epoch=3)))  # must not raise
        assert 1 not in t._conns  # no connection was cached
    finally:
        dead.close()
        t.close()


def test_peer_death_mid_stream_and_reconnect():
    """Killing the receiving transport mid-stream drops frames; a new
    transport on the same port is reconnected to lazily and receives."""
    received = []

    class _Sink:
        def step(self, source, msg):
            received.append((source, type(msg.type).__name__))

    sender = TcpTransport(0)
    receiver = TcpTransport(1)
    try:
        sender.connect(1, receiver.address)
        receiver.serve(_Sink())
        sender.link().send(1, pb.Msg(type=pb.Suspect(epoch=1)))
        deadline = time.monotonic() + 5
        while not received and time.monotonic() < deadline:
            time.sleep(0.01)
        assert received == [(0, "Suspect")]

        # Peer dies: the established connection breaks.  Sends during the
        # outage drop (possibly after one failed write flushes the stale
        # connection).
        addr = receiver.address
        receiver.close()
        time.sleep(0.05)
        for _ in range(3):
            sender.link().send(1, pb.Msg(type=pb.Suspect(epoch=2)))
            time.sleep(0.02)

        # Peer restarts on the same address: the next send reconnects.
        receiver = _rebind(1, addr)
        receiver.serve(_Sink())
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            sender.link().send(1, pb.Msg(type=pb.Suspect(epoch=9)))
            if any(m == (0, "Suspect") and len(received) > 1 for m in received):
                break
            time.sleep(0.05)
        assert len(received) > 1, "no delivery after peer restart"
    finally:
        sender.close()
        receiver.close()


def test_no_delivery_after_close():
    """A frame sent after close() must NOT reach the sink: close() tears
    down accepted inbound connections (shutdown+close) and _deliver gates
    on the closed flag, so a "dead" replica cannot keep consuming messages
    (VERDICT r4 weak #1)."""
    received = []

    class _Sink:
        def step(self, source, msg):
            received.append((source, type(msg.type).__name__))

    sender = TcpTransport(0)
    receiver = TcpTransport(1)
    try:
        sender.connect(1, receiver.address)
        receiver.serve(_Sink())
        sender.link().send(1, pb.Msg(type=pb.Suspect(epoch=1)))
        deadline = time.monotonic() + 5
        while not received and time.monotonic() < deadline:
            time.sleep(0.01)
        assert received == [(0, "Suspect")]

        receiver.close()
        # The sender still holds an ESTABLISHED connection; with the leak,
        # these frames arrived at the closed receiver's sink.
        for _ in range(5):
            sender.link().send(1, pb.Msg(type=pb.Suspect(epoch=2)))
            time.sleep(0.02)
        time.sleep(0.2)
        assert received == [(0, "Suspect")], (
            f"closed transport delivered frames: {received[1:]}"
        )
        # And the receiver's read threads actually exited (close() clears
        # _accepted itself, so inspect the threads, not the set).
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            readers = [
                t for t in threading.enumerate()
                if t.name == "tcp-read-1" and t.is_alive()
            ]
            if not readers:
                break
            time.sleep(0.02)
        assert not readers, "read threads still blocked in recv after close()"
    finally:
        sender.close()
        receiver.close()


def test_partial_and_corrupt_frames():
    """Dribbled frames are reassembled; truncated frames die with their
    connection; oversized or zero length headers drop the connection; a
    well-formed frame with garbage payload is dropped without crashing."""
    import socket as socketlib
    import struct

    from mirbft_tpu import wire

    received = []

    class _Sink:
        def step(self, source, msg):
            received.append((source, type(msg.type).__name__))

    t = TcpTransport(7)
    t.serve(_Sink())
    try:
        payload = wire.encode_varint(3) + pb.encode(
            pb.Msg(type=pb.Suspect(epoch=5))
        )
        frame = struct.pack("<I", len(payload)) + payload

        # 1. One byte at a time: must reassemble.
        s = socketlib.create_connection(t.address)
        for b in frame:
            s.sendall(bytes([b]))
            time.sleep(0.001)
        deadline = time.monotonic() + 5
        while not received and time.monotonic() < deadline:
            time.sleep(0.01)
        assert received == [(3, "Suspect")]

        # 2. Truncated frame then close: dropped, no delivery, no crash.
        s2 = socketlib.create_connection(t.address)
        s2.sendall(frame[: len(frame) // 2])
        s2.close()

        # 3. Oversized length header: connection dropped immediately.
        s3 = socketlib.create_connection(t.address)
        s3.sendall(struct.pack("<I", 1 << 31))
        # 4. Garbage payload in a well-formed frame: dropped.
        s4 = socketlib.create_connection(t.address)
        junk = b"\xff" * 40
        s4.sendall(struct.pack("<I", len(junk)) + junk)
        time.sleep(0.2)
        assert received == [(3, "Suspect")]  # nothing else got through

        # The transport still works after all of that.
        s5 = socketlib.create_connection(t.address)
        s5.sendall(frame)
        deadline = time.monotonic() + 5
        while len(received) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert received[-1] == (3, "Suspect")
        for sock in (s, s3, s4, s5):
            sock.close()
    finally:
        t.close()


def test_reconnect_backoff_resumes_delivery_without_redial():
    """Kill a peer's listener mid-stream, keep sending, restart the
    listener: the sender's per-peer channel retries with backoff and the
    queued frames arrive WITHOUT any further send() calls — the chaos
    acceptance gate for transport reconnect/backoff."""
    received = []

    class _Sink:
        def step(self, source, msg):
            received.append(msg.type.epoch)

    sender = TcpTransport(0, backoff_base=0.02, backoff_cap=0.2)
    receiver = TcpTransport(1)
    try:
        sender.connect(1, receiver.address)
        receiver.serve(_Sink())
        sender.link().send(1, pb.Msg(type=pb.Suspect(epoch=0)))
        deadline = time.monotonic() + 5
        while not received and time.monotonic() < deadline:
            time.sleep(0.01)
        assert received == [0]

        # Listener dies.  Frames sent during the outage queue on the
        # sender's channel while it re-dials with backoff.
        addr = receiver.address
        receiver.close()
        time.sleep(0.05)
        for epoch in range(1, 6):
            sender.link().send(1, pb.Msg(type=pb.Suspect(epoch=epoch)))
        time.sleep(0.3)  # several failed dial attempts accumulate
        counters = sender.counters()["peers"][1]
        assert counters["connect_failures"] + counters["send_failures"] > 0

        # Listener restarts on the same address.  NO further sends: the
        # still-queued frames must flush via the channel's own reconnect.
        # (Frames written into the dead-but-undetected connection before
        # the first send error are ordinary fire-and-forget loss — the
        # protocol's retransmit ticks own that case.)
        receiver = _rebind(1, addr)
        receiver.serve(_Sink())
        deadline = time.monotonic() + 10
        while 5 not in received and time.monotonic() < deadline:
            time.sleep(0.02)
        assert 5 in received, (
            f"queued frames not redelivered after restart: {received}"
        )
        assert received == sorted(received), f"reordered: {received}"
        counters = sender.counters()["peers"][1]
        assert counters["connects"] >= 2, "no automatic re-dial happened"
    finally:
        sender.close()
        receiver.close()


def test_outbound_queue_overflow_drops_oldest_with_accounting():
    """A peer that is down long enough overflows its bounded queue; the
    oldest frames drop and the drop counter reflects exactly how many."""
    sender = TcpTransport(0, queue_depth=4, backoff_base=0.05)
    import socket as socketlib

    dead = socketlib.socket()
    dead.bind(("127.0.0.1", 0))
    try:
        sender.connect(1, dead.getsockname())
        for epoch in range(10):
            sender.link().send(1, pb.Msg(type=pb.Suspect(epoch=epoch)))
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            c = sender.counters()["peers"][1]
            if c["enqueued"] == 10 and c["dropped_overflow"] >= 5:
                break
            time.sleep(0.01)
        c = sender.counters()["peers"][1]
        assert c["enqueued"] == 10
        # Depth 4 of 10: at least 5 oldest frames dropped (6 unless the
        # sender thread had already popped one into flight).
        assert c["dropped_overflow"] in (5, 6)
        assert c["queue_depth"] <= 4 and c["sent"] == 0
    finally:
        dead.close()
        sender.close()


def test_consensus_survives_transport_kill_and_restore():
    """A replica's entire transport dies mid-run and is replaced (same
    port); the network keeps committing and the revived replica converges
    (VERDICT r3 item 9's liveness gate)."""
    state = standard_initial_network_state(4, [9])
    registry = {}
    replicas = [_TcpReplica(i, state, registry) for i in range(4)]
    try:
        for a in replicas:
            for b in replicas:
                if a is not b:
                    a.transport.connect(b.node.config.id, b.transport.address)
        for replica in replicas:
            replica.start()

        requests = [
            pb.Request(client_id=9, req_no=i, data=b"%d" % i)
            for i in range(10)
        ]
        for request in requests[:5]:
            for replica in replicas:
                replica.node.propose(request)
        time.sleep(0.3)

        # Node 3's transport dies wholesale and is replaced on the same
        # port; peers reconnect lazily on their next sends.
        victim = replicas[3]
        addr = victim.transport.address
        victim.transport.close()
        time.sleep(0.1)
        victim.transport = _rebind(3, addr)
        victim.transport.serve(victim.node)
        for b in replicas:
            if b is not victim:
                victim.transport.connect(b.node.config.id, b.transport.address)
        # The processor holds the old link object; swap in the new one.
        victim.processor.link = victim.transport.link()

        for request in requests[5:]:
            for replica in replicas:
                replica.node.propose(request)

        expected = {(9, r.req_no) for r in requests}
        deadline = time.monotonic() + 120
        while True:
            full = [
                r for r in replicas
                if expected <= {(c, n) for c, n in r.app_log.commits}
            ]
            chains = {r.app_log.chain for r in replicas}
            if full and len(chains) == 1 and b"" not in chains:
                break
            assert time.monotonic() < deadline, (
                f"no convergence after transport restore: "
                f"{[len(set(r.app_log.commits)) for r in replicas]}"
            )
            time.sleep(0.05)
    finally:
        for replica in replicas:
            replica.stop()
    assert all(r.node.exit_error is None for r in replicas)


def test_dial_timeout_bounds_blackholed_connects(monkeypatch):
    """A peer that black-holes SYNs (firewall, dead VM) must not pin the
    sender thread: every dial attempt carries the transport's
    ``dial_timeout`` and a TimeoutError walks the normal backoff."""
    from mirbft_tpu.runtime import transport as transport_module

    seen_timeouts = []

    def _blackhole(address, timeout=None, **_kw):
        seen_timeouts.append(timeout)
        raise TimeoutError("SYN black-holed")

    sender = TcpTransport(0, dial_timeout=0.123, backoff_base=0.01,
                          backoff_cap=0.05)
    try:
        monkeypatch.setattr(
            transport_module.socket, "create_connection", _blackhole
        )
        sender.connect(1, ("127.0.0.1", 1))
        sender.link().send(1, pb.Msg(type=pb.Suspect(epoch=0)))
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            c = sender.counters()["peers"].get(1, {})
            if c.get("connect_failures", 0) >= 2:
                break
            time.sleep(0.01)
        assert sender.counters()["peers"][1]["connect_failures"] >= 2
        assert seen_timeouts and all(t == 0.123 for t in seen_timeouts)
    finally:
        monkeypatch.undo()
        sender.close()


def test_transport_fault_seam_injects_send_and_dial_loss():
    """The TransportFault seam is the chaos driver's hook: on_send=False
    frames vanish with ``dropped_fault`` accounting, on_dial=False fails
    dials into the ordinary backoff path — and clearing the fault
    restores delivery with no other intervention."""
    from mirbft_tpu.runtime.transport import TransportFault

    received = []

    class _Sink:
        def step(self, source, msg):
            received.append(msg.type.epoch)

    class _DropSends(TransportFault):
        def on_send(self, peer_id, frame):
            return False

    class _FailDials(TransportFault):
        def on_dial(self, peer_id):
            return False

    sender = TcpTransport(0, backoff_base=0.01, backoff_cap=0.05)
    receiver = TcpTransport(1)
    try:
        sender.connect(1, receiver.address)
        receiver.serve(_Sink())

        sender.fault = _DropSends()
        for epoch in range(3):
            sender.link().send(1, pb.Msg(type=pb.Suspect(epoch=epoch)))
        assert sender.counters()["dropped_fault"] == 3
        time.sleep(0.1)
        assert received == []

        # Dial faults: the frame enqueues but no connection can form.
        sender.fault = _FailDials()
        sender.link().send(1, pb.Msg(type=pb.Suspect(epoch=7)))
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if sender.counters()["peers"][1]["connect_failures"] >= 2:
                break
            time.sleep(0.01)
        assert sender.counters()["peers"][1]["connect_failures"] >= 2
        assert received == []

        # Fault cleared: the queued frame flushes via the normal re-dial.
        sender.fault = None
        deadline = time.monotonic() + 5
        while received != [7] and time.monotonic() < deadline:
            time.sleep(0.01)
        assert received == [7]
    finally:
        sender.close()
        receiver.close()


def test_clock_sync_hello_records_offset():
    """The first frame on a fresh dial is the clock-sync hello: the
    receiver learns the dialer's monotonic anchor and exposes the
    (local - peer) offset for trace alignment.  Same host, same
    CLOCK_MONOTONIC: the offset is bounded by the hello's in-flight
    latency, not by clock skew."""
    received = []

    class _Sink:
        def step(self, source, msg):
            received.append((source, type(msg.type).__name__))

    sender = TcpTransport(0)
    receiver = TcpTransport(1)
    try:
        sender.connect(1, receiver.address)
        receiver.serve(_Sink())
        sender.link().send(1, pb.Msg(type=pb.Suspect(epoch=1)))
        deadline = time.monotonic() + 5
        while not received and time.monotonic() < deadline:
            time.sleep(0.01)
        # The hello is transparent to the protocol stream...
        assert received == [(0, "Suspect")]
        # ...but the receiver learned the dialer's clock offset.
        deadline = time.monotonic() + 5
        while 0 not in receiver.clock_offsets() and time.monotonic() < deadline:
            time.sleep(0.01)
        offsets = receiver.clock_offsets()
        assert 0 in offsets, "no clock offset learned from hello"
        # Shared monotonic domain: offset ~ one-way latency (< 1s by miles).
        assert 0 <= offsets[0] < 1_000_000_000
        # The sender never dialed back, so it learned nothing.
        assert receiver.node_id not in sender.clock_offsets()
    finally:
        sender.close()
        receiver.close()


def test_nodelay_set_on_both_directions_of_established_pair():
    """TCP_NODELAY must hold on the dialed socket AND the accepted one:
    Nagle is per-direction, so a sender-only option still leaves the
    accept side delaying its ACK-piggybacked writes."""
    import socket as socketlib

    received = []

    class _Sink:
        def step(self, source, msg):
            received.append(source)

    sender = TcpTransport(0)
    receiver = TcpTransport(1)
    try:
        receiver.serve(_Sink())
        sender.connect(1, receiver.address)
        sender.link().send(1, pb.Msg(type=pb.Suspect(epoch=1)))
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and (
            1 not in sender._conns or not receiver._accepted
        ):
            time.sleep(0.01)
        assert 1 in sender._conns, "dial never completed"
        assert receiver._accepted, "accept never completed"

        dialed, _lock = sender._conns[1]
        accepted = next(iter(receiver._accepted))
        for sock, which in ((dialed, "dialed"), (accepted, "accepted")):
            assert (
                sock.getsockopt(socketlib.IPPROTO_TCP, socketlib.TCP_NODELAY)
                != 0
            ), f"TCP_NODELAY not set on the {which} socket"
    finally:
        sender.close()
        receiver.close()


def test_frame_encoder_scratch_matches_naive_encoding_and_is_not_slower():
    """The bytearray-scratch frame encoder must emit byte-identical
    frames to the naive two-allocation spelling, and the reuse must not
    lose to it (micro-benchmark with generous slack — the point is to
    catch an accidental O(n^2) or per-call reallocation regression, not
    to assert microseconds)."""
    import struct

    t = TcpTransport(0)
    try:
        _len = struct.Struct("<I")  # must match transport._LEN
        msgs = [
            pb.Msg(type=pb.Suspect(epoch=e)) for e in range(8)
        ]

        def naive(msg):
            payload = t._src_prefix + pb.encode(msg)
            return _len.pack(len(payload)) + payload

        for msg in msgs:
            assert t._encode_frame(msg) == naive(msg)

        n = 3000
        start = time.perf_counter()
        for _ in range(n):
            for msg in msgs:
                naive(msg)
        naive_s = time.perf_counter() - start
        start = time.perf_counter()
        for _ in range(n):
            for msg in msgs:
                t._encode_frame(msg)
        scratch_s = time.perf_counter() - start
        # 2x slack: CI boxes are noisy; the scratch encoder losing by
        # more than that means the reuse regressed into fresh copies.
        assert scratch_s < naive_s * 2.0, (
            f"scratch encoder {scratch_s:.4f}s vs naive {naive_s:.4f}s"
        )
    finally:
        t.close()
