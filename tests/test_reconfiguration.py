"""Reconfiguration end-to-end (VERDICT r2 item 6; reference: the WIP hole —
commitstate.go:192-226 computes the next config but nothing ever activates
it; epoch_target.go:282-300 panics at the boundary.  This rebuild closes
both): a committed reconfiguration rides the next checkpoint, activates via
a full tracker reinitialize, the epoch rolls, and the new client commits."""

from mirbft_tpu import pb
from mirbft_tpu.core.epoch_change import parse_epoch_change
from mirbft_tpu.core.persisted import Persisted
from mirbft_tpu.testengine import BasicRecorder

NEW = 99


def _new_client_reconfig():
    return [pb.Reconfiguration(type=pb.ReconfigNewClient(id=NEW, width=100))]


def test_new_client_reconfiguration_end_to_end():
    r = BasicRecorder(node_count=4, client_count=1, reqs_per_client=30)
    # The app requests adding client 99 when (client 4, req 10) commits.
    r.reconfig_on_commit[(4, 10)] = _new_client_reconfig()
    r.drain_clients(max_steps=1_000_000)

    # Activation: every node's client tracker learns the new client.
    r.drain_until(
        lambda rec: all(
            rec.machines[n].client_tracker.client(NEW) is not None
            for n in range(4)
        ),
        max_steps=1_000_000,
    )

    # The epoch was forced to roll (reinitialize resumes with a Suspect).
    epochs = {r.machines[n].epoch_tracker.current_epoch.number for n in range(4)}
    assert all(e >= 1 for e in epochs), epochs

    # The new client's requests commit at every node on the common chain.
    r.add_client(NEW, 5)
    r.drain_clients(max_steps=1_000_000)
    for n in range(4):
        mine = [x for x in r.node_states[n].committed_reqs if x[0] == NEW]
        assert len(mine) == 5, f"node {n} committed {len(mine)} of client 99"
    chains = {r.node_states[n].app_chain for n in range(4)}
    assert len(chains) == 1

    # The active network state carries the new client everywhere.
    for n in range(4):
        clients = r.machines[n].commit_state.active_state.clients
        assert any(c.id == NEW for c in clients)


def test_remove_client_reconfiguration():
    """Two clients; a committed reconfiguration removes the second.  Its
    window disappears from every tracker while the first client keeps
    committing."""
    r = BasicRecorder(node_count=4, client_count=2, reqs_per_client=30)
    second = sorted(r.clients)[1]
    # Shorten the second client's run so its requests finish early.
    r.set_client_total(second, 5)
    r.reconfig_on_commit[(sorted(r.clients)[0], 25)] = [
        pb.Reconfiguration(type=pb.ReconfigRemoveClient(client_id=second))
    ]
    r.drain_clients(max_steps=1_000_000)

    def removed_everywhere(rec):
        return all(
            rec.machines[n].client_tracker.client(second) is None
            and all(
                c.id != second
                for c in rec.machines[n].commit_state.active_state.clients
            )
            for n in range(4)
        )

    r.drain_until(removed_everywhere, max_steps=1_000_000)
    chains = {r.node_states[n].app_chain for n in range(4)}
    assert len(chains) == 1


def test_reconfig_survives_crash_at_boundary():
    """A node crashing right around the activation checkpoint replays the
    C(pending)+C(new) pair from its WAL and rejoins under the new config."""
    r = BasicRecorder(node_count=4, client_count=1, reqs_per_client=30)
    r.reconfig_on_commit[(4, 10)] = _new_client_reconfig()

    # Crash node 1 once 15 requests committed there (the reconfig commits
    # around req 10, so the boundary machinery is mid-flight), reboot 5s in.
    r.drain_until(lambda rec: rec.committed_at(1) >= 15, max_steps=1_000_000)
    r.crash(1)
    r.schedule_restart(1, 5_000)
    r.drain_clients(max_steps=1_000_000)

    r.drain_until(
        lambda rec: all(
            rec.machines[n].client_tracker.client(NEW) is not None
            for n in range(4)
        ),
        max_steps=1_000_000,
    )
    r.add_client(NEW, 3)
    r.drain_clients(max_steps=1_000_000)
    chains = {r.node_states[n].app_chain for n in range(4)}
    assert len(chains) == 1


def test_construct_epoch_change_dedups_checkpoints():
    """Defense in depth: duplicate CEntries for one seq_no (recomputed
    checkpoints) must not produce a malformed epoch change (the reference's
    parse-side dup check is a no-op bug, epoch_change.go:70-78)."""
    persisted = Persisted()
    state = pb.NetworkState(
        config=pb.NetworkConfig(
            nodes=[0], f=0, number_of_buckets=1, checkpoint_interval=5,
            max_epoch_length=50,
        ),
        clients=[],
    )
    persisted.add_c_entry(
        pb.CEntry(seq_no=0, checkpoint_value=b"a", network_state=state)
    )
    persisted.add_n_entry(
        pb.NEntry(seq_no=1, epoch_config=pb.EpochConfig(number=0, leaders=[0]))
    )
    persisted.add_c_entry(
        pb.CEntry(seq_no=5, checkpoint_value=b"b", network_state=state)
    )
    persisted.add_c_entry(
        pb.CEntry(seq_no=5, checkpoint_value=b"b2", network_state=state)
    )
    change = persisted.construct_epoch_change(1)
    assert [c.seq_no for c in change.checkpoints] == [0, 5]
    assert change.checkpoints[-1].value == b"b2"  # newest wins
    parse_epoch_change(change)  # must not raise


# -- node-set reconfiguration (grow / shrink the replica set) ---------------


def _grow_state(ci=8):
    """4 active members (0..3) in a 5-node simulated universe, small
    epochs so a provisioned node integrates at the next rollover."""
    return pb.NetworkState(
        config=pb.NetworkConfig(
            nodes=[0, 1, 2, 3],
            f=1,
            number_of_buckets=4,
            checkpoint_interval=ci,
            max_epoch_length=2 * ci,
        ),
        clients=[
            # Width covers the whole request stream: the engine submits
            # each request exactly once, so out-of-window proposals would
            # be dropped forever (real clients resubmit on window slides).
            pb.NetworkClient(id=cid, width=48, low_watermark=0)
            for cid in (10, 11)
        ],
    )


_FIVE_NODE_CONFIG = pb.NetworkConfig(
    nodes=[0, 1, 2, 3, 4],
    f=1,
    number_of_buckets=4,
    checkpoint_interval=8,
    max_epoch_length=16,
)


def _active_nodes(rec, node):
    cs = rec.machines[node].commit_state
    if cs is None or cs.active_state is None:
        return ()
    return cs.active_state.config.nodes


def _reconfig_checkpoint(rec, node, want_member):
    """Newest checkpoint at ``node`` whose network state includes (or
    excludes) the grown member."""
    best = None
    for seq, (_v, state, _snap) in rec.node_states[node].checkpoints.items():
        member = 4 in state.config.nodes
        if member == want_member and (best is None or seq > best):
            best = seq
    return best


def test_node_set_reconfiguration_grow():
    """Grow 4 -> 5 nodes via a pb.NetworkConfig reconfiguration riding a
    committed request: the network quiesces into the 5-node config at the
    checkpoint boundary, the new replica is provisioned from a member's
    stable checkpoint, and it commits the tail of the workload as a full
    member (reference: commitstate.go:192-226; README.md:35 admits this
    'does not entirely work' there — this drives it end to end)."""
    rec = BasicRecorder(
        node_count=5,
        client_count=2,
        reqs_per_client=40,
        batch_size=2,
        network_state=_grow_state(),
        deferred_nodes=(4,),
    )
    rec.reconfig_on_commit[(10, 2)] = [
        pb.Reconfiguration(type=_FIVE_NODE_CONFIG)
    ]

    # Run until the 5-node config is ACTIVE at a member (the second
    # checkpoint after the reconfiguration committed).
    rec.drain_until(
        lambda r: 4 in _active_nodes(r, 0),
        max_steps=500_000,
    )
    seq = _reconfig_checkpoint(rec, 0, want_member=True)
    assert seq is not None
    rec.provision_node(4, from_node=0, seq_no=seq, delay=50)

    rec.drain_clients(max_steps=2_000_000)

    # A second wave after the join: the new member must order it as a
    # full participant, not merely adopt a snapshot.
    for cid in (10, 11):
        rec.set_client_total(cid, 48)
        client = rec.clients[cid]
        for _ in range(8):
            rec._submit_next_request(client)
    rec.drain_clients(max_steps=2_000_000)

    chains = {rec.node_states[n].app_chain for n in range(5)}
    assert len(chains) == 1, "grown network diverged"
    total = 2 * 48
    for n in range(5):
        assert rec.committed_at(n) == total
    # The new member genuinely executed batches (not only the snapshot).
    assert rec.node_states[4].committed_reqs


def test_node_set_reconfiguration_shrink():
    """Shrink 5 -> 4 nodes: after the reconfiguration activates, the
    remaining members commit the rest of the workload among themselves,
    and the removed node's messages are dropped at ingress rather than
    corrupting per-source state."""
    state = pb.NetworkState(
        config=pb.NetworkConfig(
            nodes=[0, 1, 2, 3, 4],
            f=1,
            number_of_buckets=4,
            checkpoint_interval=8,
            max_epoch_length=16,
        ),
        clients=[
            pb.NetworkClient(id=cid, width=48, low_watermark=0)
            for cid in (10, 11)
        ],
    )
    four_node = pb.NetworkConfig(
        nodes=[0, 1, 2, 3],
        f=1,
        number_of_buckets=4,
        checkpoint_interval=8,
        max_epoch_length=16,
    )
    rec = BasicRecorder(
        node_count=5,
        client_count=2,
        reqs_per_client=40,
        batch_size=2,
        network_state=state,
    )
    rec.reconfig_on_commit[(11, 2)] = [pb.Reconfiguration(type=four_node)]

    rec.drain_until(
        lambda r: _active_nodes(r, 0) and 4 not in _active_nodes(r, 0),
        max_steps=500_000,
    )
    # The removed node is no longer addressed by members; retire it.
    rec.crash(4)

    rec.drain_clients(max_steps=2_000_000)
    chains = {rec.node_states[n].app_chain for n in range(4)}
    assert len(chains) == 1, "shrunk network diverged"
    total = 2 * 40
    for n in range(4):
        assert rec.committed_at(n) == total


def test_node_set_reconfiguration_grow_with_crash_at_boundary():
    """A member crashes right as the grow reconfiguration activates and
    restarts from its WAL: the replayed log re-applies the reconfiguration
    idempotently and the node rejoins the 5-node network."""
    rec = BasicRecorder(
        node_count=5,
        client_count=2,
        reqs_per_client=40,
        batch_size=2,
        network_state=_grow_state(),
        deferred_nodes=(4,),
    )
    rec.reconfig_on_commit[(10, 2)] = [
        pb.Reconfiguration(type=_FIVE_NODE_CONFIG)
    ]

    rec.drain_until(
        lambda r: 4 in _active_nodes(r, 1),
        max_steps=500_000,
    )
    # Node 1 dies at the activation boundary and comes back later.
    rec.crash(1)
    rec.schedule_restart(1, delay=400)

    seq = _reconfig_checkpoint(rec, 0, want_member=True)
    assert seq is not None
    rec.provision_node(4, from_node=0, seq_no=seq, delay=50)

    rec.drain_clients(max_steps=2_000_000)
    chains = {rec.node_states[n].app_chain for n in range(5)}
    assert len(chains) == 1, "network diverged after crash at boundary"
    total = 2 * 40
    for n in range(5):
        assert rec.committed_at(n) == total


# -- seed-pinned determinism matrix ------------------------------------------
#
# Every reconfiguration shape the protocol supports, replayed twice per seed
# with an event interceptor hashing the full (node, time, event) stream: the
# two logs must be byte-identical.  Reconfiguration rides the deterministic
# simulation like any other commit — if adoption ever consulted wall-clock,
# iteration order, or anything else outside the event stream, these pins
# would catch it as a one-byte divergence.

import pytest


def _drive_add_client(seed, interceptor):
    rec = BasicRecorder(
        node_count=4, client_count=1, reqs_per_client=30,
        seed=seed, interceptor=interceptor,
    )
    rec.reconfig_on_commit[(4, 10)] = _new_client_reconfig()
    rec.drain_clients(max_steps=1_000_000)
    rec.drain_until(
        lambda r: all(
            r.machines[n].client_tracker.client(NEW) is not None
            for n in range(4)
        ),
        max_steps=1_000_000,
    )
    rec.add_client(NEW, 3)
    rec.drain_clients(max_steps=1_000_000)
    assert len({rec.node_states[n].app_chain for n in range(4)}) == 1
    return rec


def _drive_add_node(seed, interceptor):
    rec = BasicRecorder(
        node_count=5, client_count=2, reqs_per_client=40, batch_size=2,
        network_state=_grow_state(), deferred_nodes=(4,),
        seed=seed, interceptor=interceptor,
    )
    rec.reconfig_on_commit[(10, 2)] = [pb.Reconfiguration(type=_FIVE_NODE_CONFIG)]
    rec.drain_until(lambda r: 4 in _active_nodes(r, 0), max_steps=500_000)
    seq = _reconfig_checkpoint(rec, 0, want_member=True)
    assert seq is not None
    rec.provision_node(4, from_node=0, seq_no=seq, delay=50)
    rec.drain_clients(max_steps=2_000_000)
    assert len({rec.node_states[n].app_chain for n in range(5)}) == 1
    return rec


def _drive_remove_node(seed, interceptor):
    state = pb.NetworkState(
        config=_FIVE_NODE_CONFIG,
        clients=[
            pb.NetworkClient(id=cid, width=48, low_watermark=0)
            for cid in (10, 11)
        ],
    )
    four_node = pb.NetworkConfig(
        nodes=[0, 1, 2, 3], f=1, number_of_buckets=4,
        checkpoint_interval=8, max_epoch_length=16,
    )
    rec = BasicRecorder(
        node_count=5, client_count=2, reqs_per_client=40, batch_size=2,
        network_state=state, seed=seed, interceptor=interceptor,
    )
    rec.reconfig_on_commit[(11, 2)] = [pb.Reconfiguration(type=four_node)]
    rec.drain_until(
        lambda r: _active_nodes(r, 0) and 4 not in _active_nodes(r, 0),
        max_steps=500_000,
    )
    rec.crash(4)
    rec.drain_clients(max_steps=2_000_000)
    assert len({rec.node_states[n].app_chain for n in range(4)}) == 1
    return rec


def _drive_shrink_then_grow(seed, interceptor):
    """Shrink 5 -> 4, then grow back 4 -> 5 and re-provision the node
    that was removed: the second reconfiguration is registered only once
    the first has activated (a deterministic point in the event stream),
    so the two node-set changes ride distinct checkpoint windows."""
    state = pb.NetworkState(
        config=_FIVE_NODE_CONFIG,
        clients=[
            pb.NetworkClient(id=cid, width=160, low_watermark=0)
            for cid in (10, 11)
        ],
    )
    four_node = pb.NetworkConfig(
        nodes=[0, 1, 2, 3], f=1, number_of_buckets=4,
        checkpoint_interval=8, max_epoch_length=16,
    )
    rec = BasicRecorder(
        node_count=5, client_count=2, reqs_per_client=120, batch_size=2,
        network_state=state, seed=seed, interceptor=interceptor,
    )
    rec.reconfig_on_commit[(11, 2)] = [pb.Reconfiguration(type=four_node)]
    rec.drain_until(
        lambda r: _active_nodes(r, 0) and 4 not in _active_nodes(r, 0),
        max_steps=500_000,
    )
    rec.crash(4)
    # Grow back: registered post-activation, keyed to the first request no
    # node has applied yet (ordering runs ahead of activation by up to a
    # stop-watermark's worth of batches, so a fixed req_no could already
    # be applied at some nodes but not others — a forked trigger).
    peak = max(
        (max(s) for s in rec.clients[10].committed_by_node.values() if s),
        default=-1,
    )
    trigger = peak + 1
    assert trigger < 120, f"workload exhausted before re-grow ({trigger})"
    rec.reconfig_on_commit[(10, trigger)] = [
        pb.Reconfiguration(type=_FIVE_NODE_CONFIG)
    ]
    rec.drain_until(lambda r: 4 in _active_nodes(r, 0), max_steps=2_000_000)
    seq = _reconfig_checkpoint(rec, 0, want_member=True)
    assert seq is not None
    rec.provision_node(4, from_node=0, seq_no=seq, delay=50)
    rec.drain_clients(max_steps=2_000_000)
    assert len({rec.node_states[n].app_chain for n in range(5)}) == 1
    return rec


def _drive_reconfig_with_epoch_change(seed, interceptor):
    """A reconfiguration committing in the same window as a crash-induced
    epoch change: adoption and the epoch roll must serialize identically
    on every run."""
    rec = BasicRecorder(
        node_count=4, client_count=1, reqs_per_client=30,
        seed=seed, interceptor=interceptor,
    )
    rec.reconfig_on_commit[(4, 8)] = _new_client_reconfig()
    rec.drain_until(lambda r: r.committed_at(0) >= 8, max_steps=1_000_000)
    rec.crash(2)
    rec.schedule_restart(2, 5_000)
    rec.drain_clients(max_steps=1_000_000)
    rec.drain_until(
        lambda r: all(
            r.machines[n].client_tracker.client(NEW) is not None
            for n in range(4)
        ),
        max_steps=1_000_000,
    )
    epochs = {rec.machines[n].epoch_tracker.current_epoch.number for n in range(4)}
    assert all(e >= 1 for e in epochs), epochs
    assert len({rec.node_states[n].app_chain for n in range(4)}) == 1
    return rec


_MATRIX = {
    "add-client": _drive_add_client,
    "add-node": _drive_add_node,
    "remove-node": _drive_remove_node,
    "shrink-then-grow": _drive_shrink_then_grow,
    "reconfig-with-epoch-change": _drive_reconfig_with_epoch_change,
}


def _run_logged(drive, seed):
    log = []

    def interceptor(node, now, event):
        log.append(b"%d|%d|" % (node, now) + pb.encode(event))

    drive(seed, interceptor)
    return b"\x00".join(log)


@pytest.mark.parametrize("case", sorted(_MATRIX))
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_reconfig_matrix_seed_pinned_byte_identical(case, seed):
    drive = _MATRIX[case]
    first = _run_logged(drive, seed)
    second = _run_logged(drive, seed)
    assert first, f"{case} seed {seed} produced an empty event log"
    assert first == second, (
        f"{case} seed {seed}: two runs diverged "
        f"({len(first)} vs {len(second)} log bytes)"
    )


def test_stop_watermark_halts_allocation_while_reconfig_pending():
    """Invariant, checked at every event of a full grow run: while a
    reconfiguration is pending adoption the stop watermark shortens to one
    checkpoint interval above the low watermark (commitstate.reinitialize /
    apply_checkpoint_result), and commits never outrun it."""
    holder = {}
    pending_seen = [0]

    def interceptor(node, now, event):
        rec = holder.get("rec")
        if rec is None:
            return
        machine = rec.machines.get(node)
        if machine is None or machine.commit_state is None:
            return
        cs = machine.commit_state
        if cs.active_state is None:
            return
        ci = cs.active_state.config.checkpoint_interval
        assert cs.highest_commit <= cs.stop_at_seq_no, (
            f"node {node} committed {cs.highest_commit} past stop "
            f"{cs.stop_at_seq_no}"
        )
        assert cs.stop_at_seq_no <= cs.low_watermark + 2 * ci
        if cs.active_state.pending_reconfigurations:
            pending_seen[0] += 1
            assert cs.stop_at_seq_no <= cs.low_watermark + ci, (
                f"node {node}: pending reconfig but stop "
                f"{cs.stop_at_seq_no} > low {cs.low_watermark} + ci {ci}"
            )

    rec = BasicRecorder(
        node_count=5, client_count=2, reqs_per_client=40, batch_size=2,
        network_state=_grow_state(), deferred_nodes=(4,),
        interceptor=interceptor,
    )
    holder["rec"] = rec
    rec.reconfig_on_commit[(10, 2)] = [pb.Reconfiguration(type=_FIVE_NODE_CONFIG)]
    rec.drain_until(lambda r: 4 in _active_nodes(r, 0), max_steps=500_000)
    seq = _reconfig_checkpoint(rec, 0, want_member=True)
    assert seq is not None
    rec.provision_node(4, from_node=0, seq_no=seq, delay=50)
    rec.drain_clients(max_steps=2_000_000)
    # Vacuity guard: the invariant must actually have been exercised in
    # the pending-window state, not merely in steady state.
    assert pending_seen[0] > 0, "no event ever observed a pending reconfig"
