"""mircat-equivalent CLI gate (VERDICT r2 item 9; reference:
mircat/main.go:419-563): filter, summarize, replay-to-status, and diff."""

import io

from mirbft_tpu import pb
from mirbft_tpu.cat import main, text
from mirbft_tpu.eventlog import EngineLog, write_log
from mirbft_tpu.testengine import BasicRecorder


def _record_run(tmp_path, name="run.gz", seed=0):
    path = str(tmp_path / name)
    log = EngineLog(path)
    r = BasicRecorder(
        node_count=4,
        client_count=2,
        reqs_per_client=3,
        seed=seed,
        interceptor=log.interceptor,
    )
    r.drain_clients(max_steps=100000)
    log.close()
    return path, log.events


def test_text_truncates_bytes():
    rendered = text(pb.RequestAck(client_id=1, req_no=2, digest=b"\xaa" * 32))
    assert "aaaaaaaa…(32B)" in rendered
    assert "client_id=1" in rendered


def test_list_and_filters(tmp_path):
    path, events = _record_run(tmp_path)
    out = io.StringIO()
    assert main([path], out=out) == 0
    listing = out.getvalue()
    assert f"# {len(events)}/{len(events)} events shown" in listing

    out = io.StringIO()
    main([path, "--node", "0", "--event-type", "EventStep"], out=out)
    for line in out.getvalue().splitlines():
        if line.startswith("#"):
            continue
        assert "node=0" in line and "EventStep" in line

    out = io.StringIO()
    main([path, "--msg-type", "Preprepare"], out=out)
    body = [l for l in out.getvalue().splitlines() if not l.startswith("#")]
    assert body and all("Preprepare" in line for line in body)


def test_summary(tmp_path):
    path, events = _record_run(tmp_path)
    out = io.StringIO()
    main([path, "--summary"], out=out)
    summary = out.getvalue()
    assert f"# events: {len(events)}" in summary
    for node in range(4):
        assert f"# node {node}:" in summary


def test_status_replay(tmp_path):
    path, _events = _record_run(tmp_path)
    out = io.StringIO()
    main([path, "--status-at", "-1"], out=out)
    status = out.getvalue()
    for node in range(4):
        assert f"=== node {node} " in status
    assert '"' in status  # JSON body

    out = io.StringIO()
    main([path, "--status-at", "-1", "--pretty"], out=out)
    assert "===" in out.getvalue()


def test_diff(tmp_path):
    path_a, events_a = _record_run(tmp_path, "a.gz")
    path_b, _ = _record_run(tmp_path, "b.gz")
    out = io.StringIO()
    assert main(["--diff", path_a, path_b], out=out) == 0
    assert "identical" in out.getvalue()

    # Mutate one event and re-write: divergence reported at its index.
    mutated = [
        (e.node_id, e.time_ms + (7 if i == 10 else 0), e.state_event)
        for i, e in enumerate(events_a)
    ]
    path_c = str(tmp_path / "c.gz")
    write_log(path_c, mutated, redact=False)
    out = io.StringIO()
    assert main(["--diff", path_a, path_c], out=out) == 1
    assert "first divergence at event 10" in out.getvalue()


def test_timing_report(tmp_path):
    path, events = _record_run(tmp_path)
    out = io.StringIO()
    assert main([path, "--timing"], out=out) == 0
    report = out.getvalue()
    for node in range(4):
        assert f"# node {node}: " in report
    assert "us/event" in report


def test_actions_replay(tmp_path):
    """--actions-at replays the log and prints the Actions the state
    machine emitted at the chosen indices (the reference CLI's aggregated
    actions printing, mircat/main.go:419-503)."""
    from mirbft_tpu import pb

    path, events = _record_run(tmp_path)
    # Pick a Propose event (emits a hash action) and a Step event.
    propose_idx = next(
        i for i, e in enumerate(events)
        if isinstance(
            e.state_event.type, (pb.EventPropose, pb.EventProposeBatch)
        )
    )
    out = io.StringIO()
    assert main([path, "--actions-at", str(propose_idx)], out=out) == 0
    report = out.getvalue()
    assert f"=== actions @ event {propose_idx}" in report
    assert "hash" in report  # a propose emits its digest request

    # An index beyond the log is reported, not crashed on.
    out = io.StringIO()
    assert main([path, "--actions-at", str(len(events) + 5)], out=out) == 0
    assert "beyond the log" in out.getvalue()
