"""The replicated application layer (mirbft_tpu/app/, docs/APP.md).

Four clusters of coverage:

- the KV state machine: op codec, deterministic apply, versions as
  apply indexes, snapshot round-trip;
- the commit stream: ordered exactly-once delivery, restart resume,
  snapshot-install fast-forward, bounded-queue backpressure, the
  read-index barrier, and the SIGKILL atomicity of the applied-index +
  snapshot blob (the double-apply-after-restart regression);
- the client-facing service seam: framing, the full KvService/KvClient
  socket loopback, and a tier-1 InProcessCluster KV smoke;
- the linearizable-reads audit and the KV loadgen plumbing (client
  model knobs, Zipf key skew, workload step results, SLO artifact and
  diff series).
"""

import os
import signal
import socket
import struct
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from mirbft_tpu import pb
from mirbft_tpu.app import kvstore
from mirbft_tpu.app.kvstore import KvStore
from mirbft_tpu.app.service import (
    KvClient,
    KvFrontend,
    KvService,
    recv_frame,
    send_frame,
)
from mirbft_tpu.app.stream import (
    CommitStream,
    decode_state,
    encode_state,
    state_binding,
)
from mirbft_tpu.chaos.invariants import (
    InvariantViolation,
    check_linearizable_reads,
)


# ---------------------------------------------------------------------------
# KV state machine
# ---------------------------------------------------------------------------


def test_kv_op_codec_roundtrip():
    put = kvstore.decode_op(kvstore.encode_put("alpha", b"\x00\xffv"))
    assert put == {"kind": "put", "key": "alpha", "value": b"\x00\xffv"}
    delete = kvstore.decode_op(kvstore.encode_delete("beta"))
    assert delete == {"kind": "delete", "key": "beta"}
    cas = kvstore.decode_op(kvstore.encode_cas("gamma", 7, b"new"))
    assert cas == {
        "kind": "cas",
        "key": "gamma",
        "expect_version": 7,
        "value": b"new",
    }
    assert kvstore.decode_op(kvstore.encode_noop()) == {"kind": "noop"}


def test_kv_malformed_ops_decode_to_none():
    assert kvstore.decode_op(b"") is None
    assert kvstore.decode_op(b"\x09\x00\x01x") is None  # unknown kind
    # Truncated put value: declared length runs past the payload.
    good = kvstore.encode_put("k", b"0123456789")
    assert kvstore.decode_op(good[:-4]) is None


def test_kv_apply_versions_are_apply_indexes():
    store = KvStore()
    r1 = store.apply(1, 0, 1, 10, kvstore.encode_put("k", b"a"))
    assert r1 == {"outcome": "ok", "version": 10}
    assert store.get("k") == (b"a", 10)
    # cas against the stale version loses and reports the current one.
    r2 = store.apply(1, 1, 2, 11, kvstore.encode_cas("k", 3, b"x"))
    assert r2 == {"outcome": "cas_conflict", "version": 10}
    assert store.get("k") == (b"a", 10)
    r3 = store.apply(1, 2, 3, 12, kvstore.encode_cas("k", 10, b"b"))
    assert r3 == {"outcome": "ok", "version": 12}
    assert store.get("k") == (b"b", 12)
    r4 = store.apply(2, 0, 4, 13, kvstore.encode_delete("k"))
    assert r4["outcome"] == "ok"
    assert store.get("k") == (None, 0)
    r5 = store.apply(2, 1, 5, 14, kvstore.encode_delete("k"))
    assert r5["outcome"] == "not_found"
    # Malformed bytes apply as a deterministic no-op, not a fork.
    r6 = store.apply(2, 2, 6, 15, b"\xff\xff\xff")
    assert r6 == {"outcome": "malformed", "version": 0}


def test_kv_apply_is_deterministic_across_replicas():
    ops = [
        kvstore.encode_put("a", b"1"),
        kvstore.encode_put("b", b"2"),
        kvstore.encode_cas("a", 1, b"3"),
        kvstore.encode_delete("b"),
        b"garbage-op",
        kvstore.encode_put("c", b"\x00" * 64),
    ]
    stores = [KvStore(), KvStore()]
    for store in stores:
        for index, data in enumerate(ops, start=1):
            store.apply(1, index, index, index, data)
    assert stores[0].snapshot() == stores[1].snapshot()
    assert stores[0].digest() == stores[1].digest()


def test_kv_snapshot_restore_roundtrip():
    store = KvStore()
    store.apply(1, 0, 1, 1, kvstore.encode_put("x", b"one"))
    store.apply(1, 1, 2, 2, kvstore.encode_put("y", b""))
    clone = KvStore()
    clone.restore(store.snapshot())
    assert clone.get("x") == (b"one", 1)
    assert clone.get("y") == (b"", 2)
    assert len(clone) == 2
    assert clone.snapshot() == store.snapshot()
    with pytest.raises(ValueError):
        clone.restore(b"not-a-snapshot")


# ---------------------------------------------------------------------------
# Commit stream
# ---------------------------------------------------------------------------


class RecordingApp:
    """A state machine that records every delivery (order + index)."""

    def __init__(self, gate=None):
        self.applied = []
        self.gate = gate  # optional Event: apply blocks until set

    def apply(self, client_id, req_no, seq_no, apply_index, data):
        if self.gate is not None:
            self.gate.wait()
        self.applied.append((client_id, req_no, seq_no, apply_index, data))
        return {"outcome": "ok", "version": apply_index}

    def snapshot(self):
        return struct.pack(">I", len(self.applied))

    def restore(self, blob):
        self.applied = [None] * struct.unpack(">I", blob)[0]


def _entry(seq, *reqs):
    return pb.QEntry(
        seq_no=seq,
        digest=b"d%d" % seq,
        requests=[
            pb.RequestAck(client_id=cid, req_no=rno) for cid, rno in reqs
        ],
    )


def _data_source(table):
    return lambda ack: table.get((ack.client_id, ack.req_no), b"")


def test_commit_stream_delivers_ordered_exactly_once():
    app = RecordingApp()
    table = {(1, 0): b"a", (1, 1): b"b", (2, 0): b"c"}
    stream = CommitStream(app, data_source=_data_source(table))
    try:
        stream.apply(_entry(1, (1, 0), (1, 1)))
        stream.apply(_entry(2))  # empty batch advances the seq frontier
        stream.apply(_entry(3, (2, 0)))
        # WAL replay re-delivers committed entries; at-or-below the
        # frontier they must be skipped, not re-applied.
        stream.apply(_entry(1, (1, 0), (1, 1)))
        stream.apply(_entry(3, (2, 0)))
        assert stream.drain()
    finally:
        stream.close()
    assert app.applied == [
        (1, 0, 1, 1, b"a"),
        (1, 1, 1, 2, b"b"),
        (2, 0, 3, 3, b"c"),
    ]
    assert stream.applied_seq == 3
    assert stream.applied_index == 3


def test_commit_stream_waiter_resolves_with_apply_result():
    store = KvStore()
    table = {(5, 0): kvstore.encode_put("k", b"v")}
    stream = CommitStream(store, data_source=_data_source(table))
    try:
        waiter = stream.register_waiter(5, 0)
        stream.apply(_entry(1, (5, 0)))
        got = waiter.wait(5.0)
        assert got is not None
        index, result = got
        assert index == 1
        assert result == {"outcome": "ok", "version": 1}
        # A waiter for an op that never commits times out and is
        # cancellable without leaking.
        stale = stream.register_waiter(5, 99)
        assert stale.wait(0.05) is None
        stream.cancel_waiter(5, 99)
        assert stream.status()["waiters"] == 0
    finally:
        stream.close()


def test_commit_stream_read_barrier_covers_frontier():
    gate = threading.Event()
    app = RecordingApp(gate=gate)
    table = {(1, 0): b"a"}
    stream = CommitStream(app, data_source=_data_source(table))
    try:
        stream.apply(_entry(1, (1, 0)))
        # The op is enqueued but not applied: a committed read must wait.
        ok, _waited, applied = stream.read_barrier(timeout=0.05)
        assert not ok
        gate.set()
        ok, _waited, applied = stream.read_barrier(timeout=5.0)
        assert ok
        assert applied >= 1
        # min_index above the frontier forces a wait past it.
        ok, _waited, _ = stream.read_barrier(min_index=99, timeout=0.05)
        assert not ok
    finally:
        stream.close()


def test_commit_stream_restart_resumes_applied_index(tmp_path):
    path = str(tmp_path / "app.state")
    table = {
        (1, 0): kvstore.encode_put("k0", b"a"),
        (1, 1): kvstore.encode_put("k1", b"b"),
        (1, 2): kvstore.encode_put("k2", b"c"),
    }
    store = KvStore()
    stream = CommitStream(store, state_path=path, data_source=_data_source(table))
    try:
        stream.apply(_entry(1, (1, 0), (1, 1)))
        value = stream.snap(None, None)
        assert state_binding(stream.last_snapshot_blob) == value
    finally:
        stream.close()

    # Restart: a fresh store + stream over the same state path resumes
    # the frontier; WAL replay of the snapshotted prefix is skipped and
    # new entries continue the apply-index sequence.
    store2 = KvStore()
    stream2 = CommitStream(
        store2, state_path=path, data_source=_data_source(table)
    )
    try:
        assert stream2.applied_seq == 1
        assert stream2.applied_index == 2
        assert store2.get("k0") == (b"a", 1)
        assert store2.get("k1") == (b"b", 2)
        assert store2.applies == 0  # restored, not re-applied
        stream2.apply(_entry(1, (1, 0), (1, 1)))  # replayed entry: skipped
        stream2.apply(_entry(2, (1, 2)))
        assert stream2.drain()
        assert store2.applies == 1
        assert store2.get("k2") == (b"c", 3)
    finally:
        stream2.close()


def test_commit_stream_snapshot_install_fast_forwards(tmp_path):
    table = {
        (1, n): kvstore.encode_put("k%d" % n, b"v%d" % n) for n in range(6)
    }
    donor_store = KvStore()
    donor = CommitStream(donor_store, data_source=_data_source(table))
    try:
        for seq in range(1, 7):
            donor.apply(_entry(seq, (1, seq - 1)))
        value = donor.snap(None, None)
        blob = donor.snapshot_blob(value)
        assert blob is not None
        assert blob == donor.last_snapshot_blob
    finally:
        donor.close()

    lagger_store = KvStore()
    lagger_path = str(tmp_path / "lagger.state")
    lagger = CommitStream(
        lagger_store, state_path=lagger_path, data_source=_data_source(table)
    )
    try:
        # A blob that doesn't bind to the certified value is refused.
        assert not lagger.install(blob, b"\x00" * 32, 6)
        assert not lagger.install(b"torn", state_binding(b"torn"), 6)
        assert lagger.install(blob, value, 6)
        assert lagger.applied_seq == 6
        assert lagger.applied_index == 6
        assert lagger.installs == 1
        assert lagger_store.get("k5") == (b"v5", 6)
        assert lagger_store.applies == 0  # adopted, never applied
        # The skipped range replayed from the WAL stays skipped; new
        # commits continue above the installed frontier.
        lagger.apply(_entry(3, (1, 2)))
        lagger.apply(_entry(7, (1, 0)))
        assert lagger.drain()
        assert lagger.applied_index == 7
        # The install also persisted: a restart resumes at the snapshot.
        status = lagger.status()
        assert status["applied_seq"] == 7
    finally:
        lagger.close()
    rebooted_store = KvStore()
    rebooted = CommitStream(
        rebooted_store, state_path=lagger_path, data_source=_data_source(table)
    )
    try:
        assert rebooted.applied_seq == 6
        assert rebooted_store.get("k0") == (b"v0", 1)
    finally:
        rebooted.close()


def test_commit_stream_backpressure_bounds_the_queue():
    gate = threading.Event()
    app = RecordingApp(gate=gate)
    table = {(1, n): b"x%d" % n for n in range(8)}
    stream = CommitStream(
        app, queue_depth=2, data_source=_data_source(table)
    )
    try:
        done = threading.Event()

        def producer():
            for seq in range(1, 9):
                stream.apply(_entry(seq, (1, seq - 1)))
            done.set()

        thread = threading.Thread(target=producer, daemon=True)
        thread.start()
        # With the app blocked, the producer must stall on the bounded
        # queue instead of buffering all 8 ops.
        assert not done.wait(0.3)
        assert stream.status()["queue_len"] <= 2
        gate.set()
        assert done.wait(5.0)
        thread.join(timeout=5.0)
        assert stream.drain()
    finally:
        stream.close()
    assert [item[3] for item in app.applied] == list(range(1, 9))


def test_app_state_blob_codec_rejects_garbage():
    blob = encode_state(7, 42, b"chain", b"app-bytes")
    assert decode_state(blob) == (7, 42, b"chain", b"app-bytes")
    assert decode_state(b"XXXX" + blob) is None
    assert decode_state(blob[:10]) is None
    assert state_binding(blob) != state_binding(blob + b"x")


def test_sigkill_between_apply_and_snapshot_cannot_double_apply(tmp_path):
    """The applied index is persisted inside the app snapshot as one
    atomic write: SIGKILL at any point leaves a blob whose index
    describes exactly the state it travels with, so the restored store
    always equals the reference prefix of that length — never one op
    more or less (the double-apply / lost-apply regression)."""
    state_path = str(tmp_path / "app.state")
    child_src = textwrap.dedent(
        """
        import sys
        from mirbft_tpu import pb
        from mirbft_tpu.app import kvstore
        from mirbft_tpu.app.kvstore import KvStore
        from mirbft_tpu.app.stream import CommitStream

        state_path = sys.argv[1]
        table = {}
        stream = CommitStream(
            KvStore(),
            state_path=state_path,
            data_source=lambda ack: table[(ack.client_id, ack.req_no)],
        )
        seq = 0
        while True:
            seq += 1
            table[(1, seq)] = kvstore.encode_put(
                "k%d" % (seq % 4), bytes([seq % 256]) * 8
            )
            stream.apply(
                pb.QEntry(
                    seq_no=seq,
                    digest=b"d",
                    requests=[pb.RequestAck(client_id=1, req_no=seq)],
                )
            )
            stream.snap(None, None)
            print(seq, flush=True)
        """
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-c", child_src, state_path],
        stdout=subprocess.PIPE,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env,
    )
    try:
        last = 0
        deadline = time.monotonic() + 60.0
        while last < 5 and time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            last = int(line)
        assert last >= 5, "child never reached 5 snapshots"
        proc.send_signal(signal.SIGKILL)
    finally:
        proc.kill()
        proc.wait(timeout=30)

    from mirbft_tpu.runtime.storage import read_app_state

    blob = read_app_state(state_path)
    assert blob is not None, "no app state survived the kill"
    decoded = decode_state(blob)
    assert decoded is not None, "torn app-state blob (non-atomic write)"
    applied_seq, applied_index, _chain, app_blob = decoded
    assert applied_seq == applied_index  # one op per entry in the child
    assert applied_index >= 5
    restored = KvStore()
    restored.restore(app_blob)
    reference = KvStore()
    for seq in range(1, applied_index + 1):
        reference.apply(
            1, seq, seq, seq,
            kvstore.encode_put("k%d" % (seq % 4), bytes([seq % 256]) * 8),
        )
    assert restored.snapshot() == reference.snapshot()


# ---------------------------------------------------------------------------
# Service seam
# ---------------------------------------------------------------------------


def test_service_framing_roundtrip_and_bounds():
    a, b = socket.socketpair()
    try:
        rfile = b.makefile("rb")
        send_frame(a, {"id": 1, "op": "get", "key": "k"})
        assert recv_frame(rfile) == {"id": 1, "op": "get", "key": "k"}
        # An oversized length prefix is refused, not allocated.
        a.sendall(struct.pack(">I", 1 << 30))
        assert recv_frame(rfile) is None
    finally:
        a.close()
        b.close()


class _LoopbackConsensus:
    """propose() that commits immediately through the commit stream —
    consensus reduced to its post-condition, for service-seam tests."""

    def __init__(self):
        self.table = {}
        self.seq = 0
        self.store = KvStore()
        self.stream = CommitStream(
            self.store, data_source=_data_source(self.table)
        )

    def propose(self, request):
        self.table[(request.client_id, request.req_no)] = request.data
        self.seq += 1
        self.stream.apply(_entry(self.seq, (request.client_id, request.req_no)))

    def close(self):
        self.stream.close()


def test_kv_service_socket_loopback_full_surface():
    consensus = _LoopbackConsensus()
    frontend = KvFrontend(consensus.stream, consensus.store, consensus.propose)
    service = KvService(frontend)
    client = KvClient({0: service.address}, client_id=9, home=0)
    try:
        put = client.put("alpha", b"v1", timeout=5.0)
        assert put["status"] == "ok"
        assert put["version"] == 1
        assert client.req_no == 1  # use-then-increment from 0

        got = client.get("alpha", timeout=5.0)
        assert got["status"] == "ok"
        assert bytes.fromhex(got["value"]) == b"v1"
        assert got["version"] == put["version"]

        stale = client.get("alpha", mode="stale", timeout=5.0)
        assert stale["status"] == "ok"

        conflict = client.cas("alpha", 999, b"nope", timeout=5.0)
        assert conflict["status"] == "cas_conflict"
        winner = client.cas("alpha", put["version"], b"v2", timeout=5.0)
        assert winner["status"] == "ok"
        assert winner["version"] > put["version"]

        gone = client.delete("alpha", timeout=5.0)
        assert gone["status"] == "ok"
        missing = client.get("alpha", timeout=5.0)
        assert missing["status"] == "not_found"

        # The session's high-water index tracked every response.
        assert client.session_index >= winner["version"]
    finally:
        client.close()
        service.close()
        consensus.close()


def test_kv_frontend_rejects_malformed_requests():
    consensus = _LoopbackConsensus()
    frontend = KvFrontend(consensus.stream, consensus.store, consensus.propose)
    try:
        assert frontend.execute({"op": "bogus"}) == {"status": "bad_request"}
        assert frontend.execute(
            {"op": "put", "key": "k", "value": "zz-not-hex", "client_id": 1,
             "req_no": 0}
        )["status"] == "bad_request"
        status = frontend.execute({"op": "status"})
        assert status["status"] == "ok"
        assert "applied_index" in status["app"]
    finally:
        consensus.close()


def test_inprocess_cluster_kv_smoke():
    """Tier-1: a 4-node in-process cluster serving the replicated KV —
    read-your-writes through the committed read barrier, cas, and a
    cross-node stale read."""
    from mirbft_tpu.loadgen import InProcessCluster

    with InProcessCluster(node_count=4, client_ids=[1, 2], app="kv") as cluster:
        s1 = cluster.kv_session(1, home=0)
        put = s1.put("alpha", b"v1", timeout=30.0)
        assert put["status"] == "ok", put
        got = s1.get("alpha", timeout=30.0)
        assert got["status"] == "ok", got
        assert bytes.fromhex(got["value"]) == b"v1"
        assert got["version"] == put["version"]

        cas = s1.cas("alpha", put["version"], b"v2", timeout=30.0)
        assert cas["status"] == "ok", cas

        # A second session homed on another node: its committed read
        # barriers on that node's own frontier.
        s2 = cluster.kv_session(2, home=1)
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            other = s2.get("alpha", timeout=30.0)
            if other.get("status") == "ok" and other.get("version") == cas["version"]:
                break
            time.sleep(0.05)
        assert other["status"] == "ok", other
        assert bytes.fromhex(other["value"]) == b"v2"
        cluster.check()


# ---------------------------------------------------------------------------
# The linearizable-reads audit
# ---------------------------------------------------------------------------


def _op(client, op, key, t0, t1, outcome="ok", version=0, value=None):
    entry = {
        "client_id": client,
        "op": op,
        "key": key,
        "invoke_ns": t0,
        "return_ns": t1,
        "outcome": outcome,
        "version": version,
    }
    if value is not None:
        entry["value"] = value
    return entry


def test_linearizable_reads_passes_on_clean_overlapping_history():
    history = [
        _op(1, "put", "k", 0, 10, version=5, value="aa"),
        _op(2, "get", "k", 5, 15, version=5, value="aa"),
        _op(1, "put", "k", 20, 30, version=9, value="bb"),
        _op(2, "get", "k", 25, 40, version=9, value="bb"),
    ]
    tally = check_linearizable_reads(history)
    assert tally == {"reads": 2, "writes": 2, "overlaps": 2}


def test_linearizable_reads_detects_fork():
    history = [
        _op(1, "put", "k", 0, 10, version=5, value="aa"),
        _op(2, "get", "k", 5, 15, version=5, value="bb"),  # same version!
    ]
    with pytest.raises(InvariantViolation, match="fork"):
        check_linearizable_reads(history)


def test_linearizable_reads_detects_duplicate_write_versions():
    history = [
        _op(1, "put", "k", 0, 10, version=5, value="aa"),
        _op(2, "put", "k", 5, 15, version=5, value="aa"),
        _op(1, "get", "k", 6, 20, version=5, value="aa"),
    ]
    with pytest.raises(InvariantViolation, match="share"):
        check_linearizable_reads(history)


def test_linearizable_reads_detects_backwards_read():
    history = [
        _op(1, "put", "k", 0, 100, version=7, value="aa"),
        _op(2, "get", "k", 10, 20, version=7, value="aa"),
        _op(2, "get", "k", 30, 40, version=3, value="zz"),  # went back
        _op(3, "put", "k", 25, 35, version=3, value="zz"),
    ]
    with pytest.raises(InvariantViolation, match="backwards"):
        check_linearizable_reads(history)


def test_linearizable_reads_enforces_read_your_writes():
    history = [
        _op(1, "put", "k", 0, 10, version=8, value="bb"),
        _op(1, "get", "k", 20, 30, version=2, value="aa"),  # below own write
        _op(2, "get", "k", 5, 12, version=8, value="bb"),
    ]
    with pytest.raises(InvariantViolation, match="backwards"):
        check_linearizable_reads(history)


def test_linearizable_reads_vacuity_guard():
    with pytest.raises(InvariantViolation, match="vacuous"):
        check_linearizable_reads(
            [_op(1, "put", "k", 0, 10, version=1, value="aa")]
        )
    # Reads and writes that never overlap in time prove nothing.
    with pytest.raises(InvariantViolation, match="vacuous"):
        check_linearizable_reads(
            [
                _op(1, "put", "k", 0, 10, version=1, value="aa"),
                _op(2, "get", "k", 50, 60, version=1, value="aa"),
            ]
        )


# ---------------------------------------------------------------------------
# KV loadgen plumbing
# ---------------------------------------------------------------------------


def test_client_model_kv_knob_validation():
    from mirbft_tpu.loadgen import ClientModel

    with pytest.raises(ValueError):
        ClientModel(read_ratio=1.5)
    with pytest.raises(ValueError):
        ClientModel(key_space=0)
    with pytest.raises(ValueError):
        ClientModel(key_dist="pareto")
    with pytest.raises(ValueError):
        ClientModel(key_dist="zipf", zipf_s=0.0)


def test_client_model_zipf_draw_is_skewed_and_seeded():
    import random

    from mirbft_tpu.loadgen import ClientModel

    model = ClientModel(read_ratio=0.5, key_space=8, key_dist="zipf")
    counts: dict = {}
    rng = random.Random(7)
    for _ in range(2000):
        key = model.key(rng)
        counts[key] = counts.get(key, 0) + 1
    assert set(counts) <= {"k%d" % n for n in range(8)}
    assert max(counts, key=counts.get) == "k0"  # rank-1 hottest
    # Same seed, same draw sequence.
    again = [model.key(random.Random(7)) for _ in range(3)]
    assert again == [model.key(random.Random(7)) for _ in range(3)]


def test_kv_client_models_mixes_uniform_and_zipf():
    from mirbft_tpu.loadgen import kv_client_models

    models = kv_client_models([1, 2, 3, 4], read_ratio=0.7)
    assert sorted(models) == [1, 2, 3, 4]
    assert all(m.read_ratio == 0.7 for m in models.values())
    dists = {models[n].key_dist for n in (1, 2)}
    assert dists == {"uniform", "zipf"}


def test_kv_workload_step_feeds_slo_artifact_and_diff(tmp_path):
    from mirbft_tpu.loadgen import (
        InProcessCluster,
        KvWorkload,
        kv_client_models,
        slo,
    )
    from mirbft_tpu.obsv.diff import extract_series

    with InProcessCluster(node_count=4, client_ids=[1, 2], app="kv") as cluster:
        sessions = {
            1: cluster.kv_session(1, home=0),
            2: cluster.kv_session(2, home=1),
        }
        workload = KvWorkload(sessions, kv_client_models([1, 2]), seed=3)
        step = workload.run_step("kv-smoke", ops_per_session=12,
                                 op_timeout_s=30.0)
        cluster.check()

    assert step.submitted == 24
    assert step.reads + step.writes == 24
    assert step.committed > 0
    assert step.timed_out == 0, "writes timed out in-process"
    assert workload.history and len(workload.history) == 24

    doc = slo.artifact([step], cluster="inproc", nodes=4, sessions=2)
    (entry,) = doc["steps"]
    for key in ("reads", "writes", "read_p50_ms", "write_p99_ms",
                "read_goodput_per_sec", "write_goodput_per_sec"):
        assert key in entry, key
    assert doc["meta"]["cluster"] == "inproc"

    # The bench payload embeds the doc under loadgen_app; obsv --diff
    # must flatten the read/write splits into gated series.
    series = extract_series({"unit": 1.0, "loadgen_app": doc})
    assert "loadgen_app.step.kv-smoke.read_p50_ms" in series
    assert "loadgen_app.step.kv-smoke.write_p99_ms" in series
    assert "loadgen_app.step.kv-smoke.write_goodput_per_sec" in series


# ---------------------------------------------------------------------------
# KV chaos scenarios (full mp matrix: slow)
# ---------------------------------------------------------------------------


def test_kv_mp_matrix_derives_from_the_smoke_pair():
    from mirbft_tpu.cluster.chaos_mp import (
        KV_MP_SMOKE_NAMES,
        kv_mp_matrix,
    )

    scenarios = {s.name: s for s in kv_mp_matrix()}
    assert sorted(scenarios) == sorted(KV_MP_SMOKE_NAMES)
    for scenario in scenarios.values():
        assert scenario.notes["app"] == "kv"
        assert "kv" in scenario.tags
        assert scenario.notes["kv_sessions"] >= 2


@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.parametrize("name", ["kv-crash-restart", "kv-partition-minority"])
def test_kv_mp_chaos_scenario_linearizable_reads(name):
    from mirbft_tpu.cluster.chaos_mp import kv_mp_matrix, run_mp_scenario

    scenario = next(s for s in kv_mp_matrix() if s.name == name)
    result = run_mp_scenario(scenario, seed=0, budget_s=240.0)
    assert result.passed, result.violation
    assert result.counters["kv_reads"] > 0
    assert result.counters["kv_writes"] > 0
    assert result.counters["kv_overlaps"] > 0
