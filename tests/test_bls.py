"""BLS12-381: host pairing oracle + device G1 quorum-cert aggregation.

Rung-4 gates: bilinearity of the pairing, aggregate signature semantics
(any missing or forged voter breaks the certificate), and the device
aggregation kernel bit-equal to the host fold."""

import pytest

from mirbft_tpu.crypto import bls_host as bls


def test_generators_and_orders():
    assert bls.g1_on_curve(bls.G1)
    assert bls.g2_on_curve(bls.G2)
    assert bls.pt_mul(bls.FP, bls.R, bls.G1) is None
    assert bls.pt_mul(bls.FP2, bls.R, bls.G2) is None


@pytest.mark.slow
def test_pairing_bilinearity():
    e_base = bls.pairing(bls.G1, bls.G2)
    e_2g1 = bls.pairing(bls.pt_mul(bls.FP, 2, bls.G1), bls.G2)
    e_2g2 = bls.pairing(bls.G1, bls.pt_mul(bls.FP2, 2, bls.G2))
    assert e_2g1 == bls.f12_mul(e_base, e_base)
    assert e_2g1 == e_2g2
    assert e_base != bls.F12_ONE  # non-degenerate


@pytest.mark.slow
def test_quorum_certificate_end_to_end():
    """2f+1 of 4 replicas sign the same checkpoint statement; the
    aggregate verifies, and any tampering breaks it."""
    msg = b"checkpoint seq=40 value=ab12"
    seeds = [bytes([i]) * 4 for i in range(4)]
    pks = [bls.public_key(s) for s in seeds]
    quorum = [0, 1, 3]  # 2f+1 = 3 of 4
    sigs = [bls.sign(seeds[i], msg) for i in quorum]
    asig = bls.aggregate_g1(sigs)
    assert bls.verify_aggregate([pks[i] for i in quorum], msg, asig)
    # Wrong statement.
    assert not bls.verify_aggregate([pks[i] for i in quorum], msg + b"!", asig)
    # Claimed quorum doesn't match the signers.
    assert not bls.verify_aggregate([pks[i] for i in (0, 1, 2)], msg, asig)
    # Dropped signature.
    assert not bls.verify_aggregate(
        [pks[i] for i in quorum], msg, bls.aggregate_g1(sigs[:2])
    )


@pytest.mark.slow
def test_device_aggregation_matches_host():
    from mirbft_tpu.ops.bls_g1 import aggregate_signatures

    msg = b"batch digest"
    certs, expected = [], []
    for b in range(4):
        seeds = [bytes([b, i]) for i in range(6)]
        sigs = [bls.sign(s, msg) for s in seeds]
        if b == 1:
            sigs[2] = None  # absent voter mid-certificate
        if b == 2:
            sigs = sigs[:1]  # single-voter certificate
        certs.append(sigs)
        expected.append(
            bls.aggregate_g1([s for s in sigs if s is not None])
        )
    assert aggregate_signatures(certs) == expected
    # All-absent certificate aggregates to infinity.
    assert aggregate_signatures([[None, None]]) == [None]


@pytest.mark.slow
def test_checkpoint_certs_from_consensus_run():
    """Protocol integration: a 4-node testengine run produces BLS quorum
    certificates for its stable checkpoints — votes collected from the
    actual Checkpoint broadcasts, aggregated on the device, verified with
    one pairing per certificate."""
    from mirbft_tpu.testengine import BasicRecorder
    from mirbft_tpu.testengine.certs import CheckpointCertPlane

    plane = CheckpointCertPlane(quorum=3)  # 2f+1 at n=4, f=1
    # 120 requests at batch 2 drive sequences well past several ci=20
    # checkpoint boundaries.
    r = BasicRecorder(
        node_count=4, client_count=2, reqs_per_client=60, batch_size=2,
        checkpoint_certs=plane,
    )
    r.drain_clients(max_steps=400000)
    certs = plane.certificates()
    assert certs, "no checkpoint reached a vote quorum"
    # Every certificate verifies; a tampered statement does not.
    (seq_no, value), (signers, asig) = next(iter(sorted(certs.items())))
    assert len(signers) == 3
    assert CheckpointCertPlane.verify(seq_no, value, signers, asig)
    assert not CheckpointCertPlane.verify(seq_no + 1, value, signers, asig)
    assert not CheckpointCertPlane.verify(
        seq_no, value + b"x", signers, asig
    )
    # Certificates exist for multiple checkpoint windows: 120 requests at
    # batch 2 drive sequences past several ci=20 boundaries.
    assert len(certs) >= 2


@pytest.mark.slow
def test_device_aggregate_verifies_as_quorum_cert():
    """The full rung-4 flow: sign on 2f+1 replicas, aggregate on the
    device, verify the certificate with one pairing equation on the host."""
    from mirbft_tpu.ops.bls_g1 import aggregate_signatures

    msg = b"epoch=3 seq=60 digest=77aa"
    seeds = [bytes([i]) * 3 for i in range(4)]
    sigs = [bls.sign(s, msg) for s in seeds]
    pks = [bls.public_key(s) for s in seeds]
    (asig,) = aggregate_signatures([sigs[:3]])
    assert bls.verify_aggregate(pks[:3], msg, asig)
    assert not bls.verify_aggregate(pks[:3], msg + b"x", asig)
