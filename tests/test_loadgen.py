"""Load generator gates: arrival determinism, client models, the SLO
artifact + absolute gate, the ``obsv --diff`` relative gate (nonzero exit
on an injected p95 regression), the generator's retry/timeout accounting
against a scripted cluster, and the tier-1 in-process smoke — including
the deterministic retry-storm dedup test (every unique request commits
exactly once while ``mirbft_request_duplicates_total`` accounts for the
absorbed resubmissions)."""

import json
import time

import pytest

from mirbft_tpu import pb
from mirbft_tpu.loadgen import (
    BurstyArrivals,
    ClientModel,
    DiurnalArrivals,
    InProcessCluster,
    LoadGenerator,
    PoissonArrivals,
    StepResult,
    percentile_ms,
    slo,
    standard_client_models,
)
from mirbft_tpu.obsv import hooks
from mirbft_tpu.obsv.__main__ import main as obsv_main


# -- arrival processes -------------------------------------------------------


def test_poisson_arrivals_deterministic_sorted_and_rate_shaped():
    a = PoissonArrivals(rate_per_sec=200.0, seed=3)
    first = a.offsets(5.0)
    assert first == PoissonArrivals(200.0, seed=3).offsets(5.0)
    assert first == sorted(first)
    assert all(0.0 <= t < 5.0 for t in first)
    # ~1000 expected; Poisson sd ~32, so a wide band is still a real check.
    assert 700 < len(first) < 1300
    assert PoissonArrivals(200.0, seed=4).offsets(5.0) != first


def test_poisson_rejects_nonpositive_rate():
    with pytest.raises(ValueError):
        PoissonArrivals(0.0)


def test_bursty_arrivals_land_only_in_on_windows():
    a = BurstyArrivals(20.0, burst_factor=4.0, on_s=0.5, off_s=1.0, seed=1)
    offsets = a.offsets(6.0)
    assert offsets == sorted(offsets)
    assert offsets, "six seconds of bursts must produce arrivals"
    period = a.on_s + a.off_s
    for t in offsets:
        assert (t % period) < a.on_s, f"arrival {t} inside an off window"


def test_diurnal_arrivals_follow_the_ramp():
    a = DiurnalArrivals(5.0, 100.0, period_s=4.0, seed=2)
    offsets = a.offsets(8.0)
    assert offsets == sorted(offsets)
    assert offsets == DiurnalArrivals(5.0, 100.0, period_s=4.0, seed=2).offsets(8.0)
    # Peak half-periods (phase around period/2) must see far more arrivals
    # than trough half-periods (phase around 0).
    trough = sum(1 for t in offsets if (t % 4.0) < 1.0 or (t % 4.0) > 3.0)
    peak = sum(1 for t in offsets if 1.0 <= (t % 4.0) <= 3.0)
    assert peak > 3 * max(trough, 1)
    assert a.rate_at(0.0) == pytest.approx(5.0)
    assert a.rate_at(2.0) == pytest.approx(100.0)


# -- client models -----------------------------------------------------------


def test_client_model_payload_sizes_and_determinism():
    import random

    fixed = ClientModel(payload_bytes=64)
    assert len(fixed.payload(random.Random(0), 7)) == 64
    # Same (client, req_no) must produce identical bytes: dedup depends on
    # resubmissions hashing to the same digest.
    assert fixed.payload(random.Random(0), 7) == fixed.payload(random.Random(9), 7)

    mixed = ClientModel(payload_choices=(16, 256))
    sizes = {len(mixed.payload(random.Random(i), i)) for i in range(32)}
    assert sizes <= {16, 256} and len(sizes) == 2


def test_client_model_validation():
    with pytest.raises(ValueError):
        ClientModel(payload_bytes=0)
    with pytest.raises(ValueError):
        ClientModel(submit_lag_s=-0.1)
    with pytest.raises(ValueError):
        ClientModel(retry_timeout_s=0.0)
    with pytest.raises(ValueError):
        ClientModel(retry_fanout=0)


def test_standard_client_models_cover_the_three_behaviours():
    models = standard_client_models([1, 2, 3, 4])
    assert set(models) == {1, 2, 3, 4}
    assert models[1] == ClientModel()  # honest
    assert models[2].payload_choices and models[2].submit_lag_s > 0  # slow+mixed
    assert models[3].retry_timeout_s is not None  # stormy
    assert models[4] == models[1]  # round-robin wraps


# -- percentiles and the SLO artifact ---------------------------------------


def test_percentile_nearest_rank():
    assert percentile_ms([], 0.95) == 0.0
    sample = list(range(1, 101))  # 1..100
    assert percentile_ms(sample, 0.50) == 50
    assert percentile_ms(sample, 0.95) == 95
    assert percentile_ms(sample, 0.99) == 99
    assert percentile_ms([42.0], 0.99) == 42.0


def _step(name, p95=100.0, committed=90, offered=50.0, timed_out=0):
    step = StepResult(name=name, offered_rate_per_sec=offered, duration_s=2.0)
    step.submitted = committed + timed_out
    step.committed = committed
    step.timed_out = timed_out
    step.goodput_per_sec = committed / step.duration_s
    step.p50_ms = p95 / 2
    step.p95_ms = p95
    step.p99_ms = p95 * 1.2
    return step


def test_slo_artifact_roundtrip_and_absolute_gate(tmp_path):
    doc = slo.artifact([_step("poisson-50")], cluster="test", nodes=4)
    assert doc["schema"] == slo.SCHEMA
    assert doc["meta"] == {"cluster": "test", "nodes": 4}
    path = tmp_path / "slo.json"
    slo.write_artifact(str(path), doc)
    assert slo.load_artifact(str(path)) == doc

    with pytest.raises(ValueError):
        bad = tmp_path / "bad.json"
        bad.write_text("{}")
        slo.load_artifact(str(bad))

    assert slo.check_slo(doc, p95_ms=200.0, min_goodput_ratio=0.5) == []
    violations = slo.check_slo(
        slo.artifact([_step("hot", p95=500.0, committed=10, timed_out=3)]),
        p95_ms=200.0,
        p99_ms=250.0,
        min_goodput_ratio=0.5,
        max_timed_out=0,
    )
    assert len(violations) == 4  # p95, p99, goodput floor, stranded reqs
    assert any("p95" in v for v in violations)
    assert any("never committed" in v for v in violations)


# -- the relative gate: obsv --diff on SLO artifacts -------------------------


def test_diff_gate_exits_nonzero_on_injected_p95_regression(tmp_path, capsys):
    baseline = tmp_path / "a.json"
    candidate = tmp_path / "b.json"
    slo.write_artifact(
        str(baseline), slo.artifact([_step("poisson-50", p95=100.0)])
    )
    slo.write_artifact(
        str(candidate), slo.artifact([_step("poisson-50", p95=180.0)])
    )
    rc = obsv_main(["--diff", str(baseline), str(candidate), "--threshold", "10"])
    out = capsys.readouterr().out
    assert rc == 1, out
    report = json.loads(out.strip().splitlines()[-1])
    regressed = {entry["series"] for entry in report["regressions"]}
    assert "step.poisson-50.p95_ms" in regressed

    # Identical artifacts pass.
    assert obsv_main(["--diff", str(baseline), str(baseline)]) == 0
    capsys.readouterr()

    # A p95 *improvement* must not gate (direction awareness).
    slo.write_artifact(
        str(candidate), slo.artifact([_step("poisson-50", p95=50.0)])
    )
    assert obsv_main(["--diff", str(baseline), str(candidate)]) == 0
    capsys.readouterr()

    # A goodput drop gates in the other direction.
    slo.write_artifact(
        str(candidate), slo.artifact([_step("poisson-50", p95=100.0, committed=40)])
    )
    rc = obsv_main(["--diff", str(baseline), str(candidate)])
    out = capsys.readouterr().out
    assert rc == 1
    report = json.loads(out.strip().splitlines()[-1])
    regressed = {entry["series"] for entry in report["regressions"]}
    assert "step.poisson-50.goodput_per_sec" in regressed


def test_diff_gate_reads_the_slo_artifact_embedded_in_bench_json(tmp_path, capsys):
    """bench.py embeds the live_mp artifact under ``loadgen``; a p95
    regression inside it must fail the whole-bench diff."""
    base = {"metric": 1000.0, "loadgen": slo.artifact([_step("mp", p95=100.0)])}
    cand = {"metric": 1000.0, "loadgen": slo.artifact([_step("mp", p95=400.0)])}
    a, b = tmp_path / "bench_a.json", tmp_path / "bench_b.json"
    a.write_text(json.dumps(base))
    b.write_text(json.dumps(cand))
    rc = obsv_main(["--diff", str(a), str(b)])
    out = capsys.readouterr().out
    assert rc == 1
    report = json.loads(out.strip().splitlines()[-1])
    regressed = {entry["series"] for entry in report["regressions"]}
    assert "loadgen.step.mp.p95_ms" in regressed


# -- the generator against a scripted cluster --------------------------------


class _ScriptedCluster:
    """Commits a request on its Nth submission — deterministic retry bait."""

    def __init__(self, commit_on_send: int):
        self.node_ids = [0, 1, 2, 3]
        self.commit_on_send = commit_on_send
        self.sends: dict = {}
        self._commits: list = []

    def submit(self, node_id, request):
        key = (request.client_id, request.req_no)
        self.sends[key] = self.sends.get(key, 0) + 1
        if self.sends[key] == self.commit_on_send:
            self._commits.append(
                (node_id, request.client_id, request.req_no, 1, time.monotonic_ns())
            )

    def poll_commits(self):
        out = self._commits
        self._commits = []
        return out


def test_generator_retry_storm_counts_duplicates_not_goodput():
    # First submission broadcasts to 4 nodes; commit_on_send=5 means no
    # request commits until its first retry lands — every commit is
    # retry-won, and every retry is accounted as a duplicate.
    cluster = _ScriptedCluster(commit_on_send=5)
    models = {1: ClientModel(retry_timeout_s=0.05, retry_fanout=2)}
    gen = LoadGenerator(cluster, models, seed=5)
    result = gen.run_step(
        "storm", PoissonArrivals(40.0, seed=5), duration_s=0.4, drain_s=5.0
    )
    assert result.submitted > 0
    assert result.committed == result.submitted
    assert result.timed_out == 0
    assert result.duplicates > 0
    # Latency is first-submit to commit: at least one retry timeout long.
    assert result.p50_ms >= 40.0
    assert result.goodput_per_sec == pytest.approx(
        result.committed / result.duration_s
    )


def test_generator_counts_never_committed_requests_as_timed_out():
    cluster = _ScriptedCluster(commit_on_send=10**9)
    gen = LoadGenerator(cluster, {1: ClientModel()}, seed=0)
    result = gen.run_step(
        "dead", PoissonArrivals(50.0, seed=1), duration_s=0.2, drain_s=0.1
    )
    assert result.submitted > 0
    assert result.committed == 0
    assert result.timed_out == result.submitted
    assert result.goodput_per_sec == 0.0


def test_generator_requires_a_client_model():
    with pytest.raises(ValueError):
        LoadGenerator(_ScriptedCluster(1), {})


def test_generator_req_nos_persist_across_steps():
    cluster = _ScriptedCluster(commit_on_send=1)
    gen = LoadGenerator(cluster, {1: ClientModel()}, seed=0)
    first = gen.run_step("s1", PoissonArrivals(30.0, seed=2), 0.2, drain_s=2.0)
    second = gen.run_step("s2", PoissonArrivals(30.0, seed=3), 0.2, drain_s=2.0)
    assert first.submitted and second.submitted
    req_nos = sorted(q for (_c, q) in cluster.sends)
    assert req_nos == list(range(first.submitted + second.submitted))


# -- in-process cluster smoke (the tier-1 end-to-end path) -------------------


def _wait_for(predicate, timeout_s, what):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def test_inprocess_loadgen_smoke():
    """The full open-loop pipeline — arrivals, broadcast submission,
    commit observation, latency tracking — against four real runtime
    nodes in one process."""
    with InProcessCluster(node_count=4, client_ids=[1, 2]) as cluster:
        gen = LoadGenerator(
            cluster, {1: ClientModel(), 2: ClientModel()}, seed=7
        )
        result = gen.run_step(
            "smoke", PoissonArrivals(25.0, seed=7), duration_s=1.0, drain_s=30.0
        )
        cluster.check()
    assert result.submitted > 0
    assert result.committed == result.submitted, (
        f"{result.timed_out} of {result.submitted} requests never committed"
    )
    assert result.timed_out == 0
    assert len(result.latencies_ms) == result.committed
    assert all(lat >= 0.0 for lat in result.latencies_ms)
    assert result.p95_ms >= result.p50_ms > 0.0
    assert result.goodput_per_sec > 0.0


def test_retry_storm_commits_exactly_once_and_accounts_duplicates():
    """Satellite gate: a deterministic retry storm — every request
    resubmitted to every node after committing — must change nothing
    (exactly-once per node) while ``mirbft_request_duplicates_total``
    records the absorbed resubmissions."""
    metrics, _tracer = hooks.enable()

    def dup_total():
        fam = metrics.snapshot().get("mirbft_request_duplicates_total")
        return sum(s["value"] for s in fam["series"]) if fam else 0

    try:
        with InProcessCluster(node_count=4, client_ids=[1, 2]) as cluster:
            requests = [
                pb.Request(
                    client_id=client_id,
                    req_no=req_no,
                    data=b"%d:%d" % (client_id, req_no),
                )
                for client_id in (1, 2)
                for req_no in range(4)
            ]
            expected = {(r.client_id, r.req_no) for r in requests}
            for request in requests:
                for node_id in cluster.node_ids:
                    cluster.submit(node_id, request)

            def committed_everywhere():
                cluster.check()
                return all(
                    {(c, q) for (c, q, _s) in rep.app_log.commits} >= expected
                    for rep in cluster.replicas
                )

            _wait_for(committed_everywhere, 60.0, "initial commits")
            before = dup_total()

            # The storm: two more full broadcast rounds of every request.
            for _round in range(2):
                for request in requests:
                    for node_id in cluster.node_ids:
                        cluster.submit(node_id, request)

            # Every storm submission is absorbed by dedup, and the
            # absorption is visible in the catalog counter.
            _wait_for(
                lambda: dup_total() - before >= len(requests),
                30.0,
                "duplicate accounting",
            )
            time.sleep(0.3)  # grace: a wrongly re-proposed request would commit now
            cluster.check()
            for rep in cluster.replicas:
                pairs = [(c, q) for (c, q, _s) in rep.app_log.commits]
                assert len(pairs) == len(set(pairs)), (
                    f"node {rep.node_id} committed a request twice"
                )
                assert set(pairs) == expected
    finally:
        hooks.disable()
