"""Chaos campaign harness (mirbft_tpu/chaos/): the seeded scenario matrix,
the invariant checker, and the partition mangler.

The three-smoke subset (partition + heal, crash + restart, device-plane
failure) runs in tier-1; the full matrix rides the slow lane alongside
``python -m mirbft_tpu.chaos``."""

import dataclasses

import pytest

from mirbft_tpu import pb
from mirbft_tpu.chaos import (
    ADVERSARY_SMOKE_NAMES,
    CrashSnapshot,
    InvariantViolation,
    adversary_matrix,
    adversary_smoke_matrix,
    check_censorship_liveness,
    check_corruption_rejected,
    check_durable_prefix,
    check_flood_bounded,
    check_no_fork,
    check_no_fork_under_equivocation,
    matrix,
    run_campaign,
    run_scenario,
    smoke_matrix,
    SMOKE_NAMES,
)
from mirbft_tpu.testengine import BasicRecorder
from mirbft_tpu.testengine.manglers import partition

BY_NAME = {s.name: s for s in matrix()}
ADV_BY_NAME = {s.name: s for s in adversary_matrix()}


# ---------------------------------------------------------------------------
# Partition mangler semantics
# ---------------------------------------------------------------------------


def _step_event(source):
    return pb.StateEvent(
        type=pb.EventStep(source=source, msg=pb.Msg(type=pb.Suspect(epoch=0)))
    )


def test_partition_blocks_only_cross_group_inside_window():
    r = BasicRecorder(node_count=4, client_count=1, reqs_per_client=1)
    m = partition([[0], [1, 2, 3]], from_ms=1000, until_ms=5000)

    cross = _step_event(source=1)  # 1 -> 0 crosses the cut
    intra = _step_event(source=2)  # 2 -> 3 stays inside a group
    tick = pb.StateEvent(type=pb.EventTick())

    assert m(r, 500, 0, cross) == (500, 0, cross)  # before the split
    assert m(r, 1000, 0, cross) is None  # split is live
    assert m(r, 4999, 1, _step_event(source=0)) is None  # both directions
    assert m(r, 3000, 3, intra) == (3000, 3, intra)  # same side flows
    assert m(r, 3000, 0, tick) == (3000, 0, tick)  # local events flow
    assert m(r, 5000, 0, cross) == (5000, 0, cross)  # healed
    assert m.dropped == 2


def test_partition_ignores_unlisted_nodes():
    r = BasicRecorder(node_count=4, client_count=1, reqs_per_client=1)
    m = partition([[0], [1]], from_ms=0, until_ms=10_000)
    from_unlisted = _step_event(source=2)
    to_unlisted = _step_event(source=0)
    assert m(r, 100, 0, from_unlisted) == (100, 0, from_unlisted)
    assert m(r, 100, 2, to_unlisted) == (100, 2, to_unlisted)
    assert m.dropped == 0


# ---------------------------------------------------------------------------
# Invariant checker detects violations (on doctored evidence)
# ---------------------------------------------------------------------------


def _tiny_converged_recorder():
    r = BasicRecorder(node_count=4, client_count=1, reqs_per_client=3)
    r.drain_clients(max_steps=200_000)
    return r


def test_no_fork_passes_then_detects_doctored_fork():
    r = _tiny_converged_recorder()
    canonical = check_no_fork(r)
    assert canonical  # something committed

    client, req_no, seq = r.node_states[1].committed_reqs[0]
    r.node_states[1].committed_reqs[0] = (client, req_no + 1000, seq)
    with pytest.raises(InvariantViolation, match="fork at seq"):
        check_no_fork(r)


def test_no_fork_detects_duplicate_commit():
    r = _tiny_converged_recorder()
    r.node_states[2].committed_reqs.append(
        r.node_states[2].committed_reqs[-1]
    )
    with pytest.raises(InvariantViolation):
        check_no_fork(r)


def test_durable_prefix_detects_lost_and_rewritten_commits():
    r = _tiny_converged_recorder()
    final = r.node_states[0].committed_reqs
    good = CrashSnapshot(node=0, at_ms=100, committed=list(final[:2]))
    check_durable_prefix(r, [good])  # a true prefix passes

    lost = CrashSnapshot(
        node=0, at_ms=100, committed=list(final) + [(99, 99, 999)]
    )
    with pytest.raises(InvariantViolation, match="lost commits"):
        check_durable_prefix(r, [lost])

    rewritten = CrashSnapshot(
        node=0, at_ms=100, committed=[(98, 98, 998)] + list(final[1:2])
    )
    with pytest.raises(InvariantViolation, match="rewrote durable history"):
        check_durable_prefix(r, [rewritten])


# ---------------------------------------------------------------------------
# Byzantine invariants detect doctored evidence (and vacuous scenarios)
# ---------------------------------------------------------------------------


def test_corruption_rejected_requires_exactly_100_percent():
    check_corruption_rejected(rejections=5, corrupted=5)
    with pytest.raises(InvariantViolation, match="rejected 4 of 5"):
        check_corruption_rejected(4, 5)
    with pytest.raises(InvariantViolation, match="rejected 6 of 5"):
        check_corruption_rejected(6, 5)
    with pytest.raises(InvariantViolation, match="vacuous"):
        check_corruption_rejected(0, 0)


def test_no_fork_under_equivocation_detects_divergence_and_vacuity():
    r = _tiny_converged_recorder()
    variants = {(1, 1): ((b"real",), (b"variant",))}
    check_no_fork_under_equivocation(r, variants)

    with pytest.raises(InvariantViolation, match="vacuous"):
        check_no_fork_under_equivocation(r, {})
    # A quiet run never left the boot epoch, so demanding suspicion
    # evidence must fail — the regression net for the epoch-1 baseline.
    with pytest.raises(InvariantViolation, match="never suspected"):
        check_no_fork_under_equivocation(r, variants, expect_suspicion=True)

    r.node_states[1].app_chain = "doctored-divergent-chain"
    with pytest.raises(InvariantViolation, match="diverge"):
        check_no_fork_under_equivocation(r, variants)


def test_censorship_liveness_detects_starvation_lateness_and_vacuity():
    r = _tiny_converged_recorder()
    cid = next(iter(r.clients))
    censored = {(cid, 0)}
    check_censorship_liveness(r, censored, {(cid, 0): 1}, k=3)

    with pytest.raises(InvariantViolation, match="vacuous"):
        check_censorship_liveness(r, set(), {}, k=3)
    with pytest.raises(InvariantViolation, match="never committed"):
        check_censorship_liveness(r, {(cid, 999)}, {}, k=3)
    with pytest.raises(InvariantViolation, match="more than 3 epoch"):
        check_censorship_liveness(r, censored, {(cid, 0): 5}, k=3)
    # Every censored request committing without any rotation means the
    # censor never owned a victim bucket — vacuous, not a pass.
    with pytest.raises(InvariantViolation, match="vacuous"):
        check_censorship_liveness(r, censored, {(cid, 0): 0}, k=3)


def test_flood_bounded_detects_duplicates_and_unbounded_growth():
    r = _tiny_converged_recorder()
    check_flood_bounded(r, flooded=10)

    with pytest.raises(InvariantViolation, match="vacuous"):
        check_flood_bounded(r, flooded=0)
    with pytest.raises(InvariantViolation, match="checkpoint truncation"):
        check_flood_bounded(r, flooded=10, wal_bound=0)

    r.node_states[2].committed_reqs.append(
        r.node_states[2].committed_reqs[-1]
    )
    with pytest.raises(InvariantViolation, match="exactly-once"):
        check_flood_bounded(r, flooded=10)


# ---------------------------------------------------------------------------
# The tier-1 smoke subset: one scenario per disruption family
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_smoke_partition_heals():
    result = run_scenario(BY_NAME["partition-minority"], seed=0)
    assert result.passed, result.violation
    assert result.counters["partition_drops"] > 0


@pytest.mark.chaos
def test_smoke_crash_restart_durable():
    result = run_scenario(BY_NAME["crash-restart"], seed=1)
    assert result.passed, result.violation
    assert result.counters["crashes"] == 1


@pytest.mark.chaos
def test_smoke_device_plane_failure_does_not_stall():
    result = run_scenario(BY_NAME["device-digest-dies"], seed=2)
    assert result.passed, result.violation
    # The injected device loss tripped the breaker, work fell back to the
    # host oracle, and a recovery probe re-closed the circuit.
    assert result.counters["device_errors"] > 0
    assert result.counters["fallback_digests"] > 0
    assert result.counters["breaker_trips"] >= 1
    assert result.counters["breaker"] == "closed"


@pytest.mark.chaos
def test_smoke_names_cover_three_disruption_families():
    names = set(SMOKE_NAMES)
    assert {s.name for s in smoke_matrix()} == names
    assert any("partition" in n for n in names)
    assert any("crash" in n for n in names)
    assert any("device" in n for n in names)


@pytest.mark.chaos
def test_smoke_campaign_reproducible_from_seed():
    first = run_campaign(smoke_matrix(), seed=42)
    second = run_campaign(smoke_matrix(), seed=42)
    assert first.passed and second.passed
    for a, b in zip(first.results, second.results):
        assert (a.name, a.events, a.sim_ms, a.commits) == (
            b.name,
            b.events,
            b.sim_ms,
            b.commits,
        )


# ---------------------------------------------------------------------------
# Replay-idempotency regression: the bug the campaign caught
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_restart_replay_does_not_reapply_committed_batches():
    """A node that crashes with commits beyond its last stable checkpoint
    — while a concurrent partition keeps the network from moving past GC,
    so recovery replays instead of state-transferring — must not re-apply
    batches its durable app already executed."""
    result = run_scenario(BY_NAME["partition-plus-crash"], seed=14)
    assert result.passed, result.violation


# ---------------------------------------------------------------------------
# Epoch-change-targeted and signed-mode scenarios (deterministic engine)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_leader_isolation_forces_epoch_change_exactly_once():
    """Leader 0 held isolated far past the suspect timeout under 5% loss:
    the survivors must change epochs, re-propose the suspect's in-flight
    sequences, and commit every request exactly once (check_no_fork
    inside the runner rejects duplicates and forks; ``passed`` carries
    that proof).  Seeded, so the exact message-loss pattern replays."""
    result = run_scenario(BY_NAME["leader-isolation-epoch-change"], seed=7)
    assert result.passed, result.violation
    assert result.counters["epoch"] >= 1
    assert result.commits > 0


@pytest.mark.chaos
def test_signed_mode_verifier_death_walks_breaker_to_recovery():
    """Signed mode: the signature device dies mid-run; the breaker trips,
    verification falls back to the host oracle, and a later probe
    re-closes the circuit — all without stalling commits."""
    result = run_scenario(BY_NAME["signed-verifier-dies"], seed=3)
    assert result.passed, result.violation
    assert result.counters["sig_device_errors"] >= 1
    assert result.counters["sig_fallbacks"] >= 1
    assert result.counters["sig_breaker"] == "closed"


# ---------------------------------------------------------------------------
# The tier-1 adversary smoke: equivocation + duplication flood
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_adversary_smoke_equivocation_forces_suspicion():
    """Leader 0 tells conflicting Preprepares to a follower majority: no
    digest reaches quorum, the honest nodes suspect the liar and change
    epochs, and every sequence commits exactly once (the runner's
    equivocation audit demands both the no-fork proof and the epoch
    rotation)."""
    result = run_scenario(ADV_BY_NAME["equivocate-majority-suspect"], seed=0)
    assert result.passed, result.violation
    assert result.counters["equivocated"] > 0
    assert result.counters["epoch"] >= 2  # beyond the boot epoch


@pytest.mark.chaos
def test_adversary_smoke_flood_commits_exactly_once():
    """The paper's request-duplication attack: 75% of submissions
    delivered 4x; dedup must commit exactly once with bounded request
    store and WAL (audited by check_flood_bounded inside the runner)."""
    result = run_scenario(ADV_BY_NAME["flood-duplicate-proposes"], seed=1)
    assert result.passed, result.violation
    assert result.counters["flooded"] > 0


@pytest.mark.chaos
def test_adversary_smoke_names_cover_two_attack_families():
    assert {s.name for s in adversary_smoke_matrix()} == set(
        ADVERSARY_SMOKE_NAMES
    )
    assert len(ADVERSARY_SMOKE_NAMES) == 2


# ---------------------------------------------------------------------------
# Epoch-baseline regression: the vacuity hole the adversary work exposed
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_expect_epoch_change_rejects_boot_epoch():
    """Every run negotiates epoch 1 at boot (the seed WAL's FEntry ends
    epoch 0), so 'reached epoch 1' is not evidence of a forced change.
    Before the adversary campaign, an expect_epoch_change scenario whose
    cluster sat quietly in the boot epoch passed vacuously; now it must
    fail."""
    quiet = dataclasses.replace(
        BY_NAME["partition-minority"],
        name="quiet-expect-epoch-change",
        partitions=(),
        expect_epoch_change=True,
    )
    result = run_scenario(quiet, seed=0)
    assert not result.passed
    assert "boot epoch" in result.violation


# ---------------------------------------------------------------------------
# The full matrices (slow lane; also: python -m mirbft_tpu.chaos)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
@pytest.mark.slow
def test_full_campaign_passes_all_invariants():
    campaign = run_campaign(seed=0)
    assert len(campaign.results) >= 12
    assert campaign.passed, campaign.report()


@pytest.mark.chaos
@pytest.mark.slow
def test_full_adversary_campaign_passes_all_invariants():
    """All four attack families — corrupt, equivocate, censor, flood —
    across the seeded deterministic matrix."""
    campaign = run_campaign(adversary_matrix(), seed=0)
    assert len(campaign.results) >= 10
    assert campaign.passed, campaign.report()
