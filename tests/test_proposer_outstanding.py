"""Gates for proposer batching (valid-after gating, null preference),
the outstanding-reqs in-order checker, and the batch tracker fetch path."""

import pytest

from mirbft_tpu import pb
from mirbft_tpu.core.batch_tracker import BatchTracker
from mirbft_tpu.core.client_tracker import ClientTracker
from mirbft_tpu.core.msgbuffers import NodeBuffers
from mirbft_tpu.core.outstanding import InvalidPreprepare, OutstandingReqs
from mirbft_tpu.core.persisted import Persisted
from mirbft_tpu.core.preimage import host_digest, request_hash_data
from mirbft_tpu.core.proposer import Proposer
from mirbft_tpu.core.sequence import Sequence, SeqState


def network_state(n=4, f=1, ci=5, buckets=2, clients=((7, 20),)):
    return pb.NetworkState(
        config=pb.NetworkConfig(
            nodes=list(range(n)),
            f=f,
            number_of_buckets=buckets,
            checkpoint_interval=ci,
            max_epoch_length=50,
        ),
        clients=[
            pb.NetworkClient(id=cid, width=w, low_watermark=0)
            for cid, w in clients
        ],
    )


def make_tracker(state):
    persisted = Persisted()
    persisted.add_c_entry(
        pb.CEntry(seq_no=0, checkpoint_value=b"g", network_state=state)
    )
    my = pb.InitialParameters(id=0, batch_size=2, buffer_size=1 << 20)
    ct = ClientTracker(persisted, NodeBuffers(my), my)
    ct.reinitialize()
    return ct, my, persisted


def make_ready(ct, client_id, req_no, data=b"tx"):
    """Run a request through propose + acks until it's on the ready list."""
    r = pb.Request(client_id=client_id, req_no=req_no, data=data)
    ack = pb.RequestAck(
        client_id=client_id,
        req_no=req_no,
        digest=host_digest(request_hash_data(r)),
    )
    ct.apply_request_digest(ack, r.data)
    for node in (0, 1, 2):
        ct.step(node, pb.Msg(type=ack))
    return ack


def test_proposer_only_owns_my_buckets():
    state = network_state()
    ct, my, _ = make_tracker(state)
    proposer = Proposer(0, 5, my, ct, buckets={0: 0, 1: 1})
    assert set(proposer.proposal_buckets) == {0}


def test_proposer_batches_in_bucket_order():
    state = network_state()
    ct, my, _ = make_tracker(state)
    # client 7: req_no r -> bucket (7 + r) % 2 -> odd reqs to bucket 0.
    proposer = Proposer(0, 5, my, ct, buckets={0: 0, 1: 1})
    for rn in range(4):
        make_ready(ct, 7, rn)
    proposer.advance(1)
    bucket = proposer.proposal_bucket(0)
    assert bucket.has_pending(1)  # batch_size=2: reqs 1 and 3
    batch = bucket.next_batch()
    assert [cr.ack.req_no for cr in batch] == [1, 3]
    assert not bucket.has_outstanding(1)


def test_proposer_valid_after_gating():
    state = network_state(clients=((7, 4),))
    ct, my, _ = make_tracker(state)
    # Fully commit the first window (0..4) through the seq-5 checkpoint.
    for rn in range(5):
        ct.mark_committed(7, rn, rn + 1)
    ct.commits_completed_for_checkpoint_window(5)
    ct.garbage_collect(5)
    # Newly allocated reqs 5, 6 are valid only after seq 10 (5 + ci).
    make_ready(ct, 7, 5)
    make_ready(ct, 7, 6)
    proposer = Proposer(5, 5, my, ct, buckets={0: 0, 1: 0})
    b5 = proposer.proposal_bucket((7 + 5) % 2)
    b6 = proposer.proposal_bucket((7 + 6) % 2)
    proposer.advance(6)  # still inside the checkpoint window ending at 10
    assert not b5.has_outstanding(6) and not b6.has_outstanding(6)
    # Crossing the checkpoint boundary unlocks them.
    assert b5.has_outstanding(10) and b6.has_outstanding(10)
    assert [cr.ack.req_no for cr in b5.next_batch()] == [5]
    assert [cr.ack.req_no for cr in b6.next_batch()] == [6]


def test_proposer_prefers_null_on_conflict():
    state = network_state(buckets=1)
    ct, my, _ = make_tracker(state)
    r_a = pb.Request(client_id=7, req_no=0, data=b"a")
    ack_a = pb.RequestAck(
        client_id=7, req_no=0, digest=host_digest(request_hash_data(r_a))
    )
    null_ack = pb.RequestAck(client_id=7, req_no=0)
    ct.apply_request_digest(ack_a, r_a.data)
    # Strong cert for BOTH the real request and the null request.
    for node in (0, 1, 2):
        ct.step(node, pb.Msg(type=ack_a))
    crn = ct.client(7).req_no(0)
    for node in (0, 1, 2):
        crn.apply_request_ack(node, null_ack)
    crn.my_requests[b""] = crn.client_req(null_ack)
    proposer = Proposer(0, 5, my, ct, buckets={0: 0})
    proposer.advance(1)
    bucket = proposer.proposal_bucket(0)
    assert bucket.has_outstanding(1)  # fills pending from the ready queue
    batch = bucket.next_batch()
    assert [cr.ack.digest for cr in batch] == [b""]


def test_outstanding_enforces_client_order():
    state = network_state(buckets=1)
    ct, my, persisted = make_tracker(state)
    outstanding = OutstandingReqs(ct, state)
    seq = Sequence(
        owner=1,
        epoch=0,
        seq_no=1,
        persisted=persisted,
        network_config=state.config,
        my_config=my,
    )
    ack1 = pb.RequestAck(client_id=7, req_no=1, digest=b"d1")
    with pytest.raises(InvalidPreprepare):
        outstanding.apply_acks(0, seq, [ack1])  # req 0 must come first


def test_outstanding_waits_for_unavailable_request():
    state = network_state(buckets=1)
    ct, my, persisted = make_tracker(state)
    outstanding = OutstandingReqs(ct, state)
    seq = Sequence(
        owner=1,
        epoch=0,
        seq_no=1,
        persisted=persisted,
        network_config=state.config,
        my_config=my,
    )
    r = pb.Request(client_id=7, req_no=0, data=b"tx")
    ack = pb.RequestAck(
        client_id=7, req_no=0, digest=host_digest(request_hash_data(r))
    )
    actions = outstanding.apply_acks(0, seq, [ack])
    # The request is unknown: sequence allocated but pending the request.
    assert seq.state == SeqState.PENDING_REQUESTS
    seq.apply_batch_hash_result(b"batch-digest")
    assert seq.state == SeqState.PENDING_REQUESTS
    # Now the request becomes available (weak quorum + stored).
    ct.apply_request_digest(ack, r.data)
    ct.step(1, pb.Msg(type=ack))
    ct.step(2, pb.Msg(type=ack))
    actions = outstanding.advance_requests()
    assert seq.state == SeqState.PREPREPARED
    [send] = actions.sends
    assert isinstance(send.msg.type, pb.Prepare)


def test_outstanding_skips_committed_reqnos():
    state = network_state(buckets=1)
    ct, my, persisted = make_tracker(state)
    ct.mark_committed(7, 0, 1)
    outstanding = OutstandingReqs(ct, state)
    cursor = outstanding.buckets[0][7]
    assert cursor.next_req_no == 1  # skipped committed 0


def test_batch_tracker_fetch_verify_cycle():
    persisted = Persisted()
    bt = BatchTracker(persisted)
    acks = [pb.RequestAck(client_id=7, req_no=0, digest=b"\xaa" * 32)]
    digest = host_digest([a.digest for a in acks])

    actions = bt.fetch_batch(5, digest, [1, 2])
    [send] = actions.sends
    assert isinstance(send.msg.type, pb.FetchBatch)
    # Duplicate fetch for same (seq, digest) suppressed.
    assert bt.fetch_batch(5, digest, [1, 2]).is_empty()

    # Unsolicited forward dropped.
    assert bt.apply_forward_batch(2, 5, b"other", acks).is_empty()

    actions = bt.apply_forward_batch(2, 5, digest, acks)
    [hr] = actions.hashes
    assert isinstance(hr.origin.type, pb.HashOriginVerifyBatch)

    bt.apply_verify_batch_hash_result(digest, hr.origin.type)
    assert not bt.has_fetch_in_flight()
    assert bt.get_batch(digest) is not None
    assert 5 in bt.get_batch(digest).observed_sequences

    # A byzantine forward (hash mismatch) is dropped without crashing and
    # leaves any in-flight fetch untouched.
    bt2 = BatchTracker(persisted)
    bt2.fetch_batch(9, digest, [1, 2])
    bt2.apply_verify_batch_hash_result(
        b"wrong",
        pb.HashOriginVerifyBatch(expected_digest=digest, request_acks=acks),
    )
    assert bt2.has_fetch_in_flight()
    assert bt2.get_batch(digest) is None


def test_batch_tracker_retransmits_in_flight_fetches():
    persisted = Persisted()
    bt = BatchTracker(persisted)
    acks = [pb.RequestAck(client_id=7, req_no=0, digest=b"\xbb" * 32)]
    digest = host_digest([a.digest for a in acks])

    bt.fetch_batch(5, digest, [1, 2])
    [send] = bt.retransmit_fetches().sends
    assert send.targets == [1, 2]
    assert isinstance(send.msg.type, pb.FetchBatch)
    assert send.msg.type.seq_no == 5 and send.msg.type.digest == digest

    # A satisfied fetch stops retransmitting.
    bt.add_batch(5, digest, acks)
    assert bt.retransmit_fetches().is_empty()
    assert not bt.fetch_sources


def test_batch_tracker_reinit_and_truncate():
    persisted = Persisted()
    bt = BatchTracker(persisted)
    persisted.add_c_entry(
        pb.CEntry(
            seq_no=0,
            checkpoint_value=b"g",
            network_state=network_state(),
        )
    )
    persisted.add_q_entry(pb.QEntry(seq_no=1, digest=b"d1", requests=[]))
    persisted.add_q_entry(pb.QEntry(seq_no=2, digest=b"d2", requests=[]))
    bt.reinitialize()
    assert bt.get_batch(b"d1") and bt.get_batch(b"d2")
    bt.truncate(2)
    assert bt.get_batch(b"d1") is None
    assert bt.get_batch(b"d2") is not None


def test_batch_tracker_replies_to_fetch():
    persisted = Persisted()
    bt = BatchTracker(persisted)
    acks = [pb.RequestAck(client_id=7, req_no=0, digest=b"x")]
    bt.add_batch(3, b"bd", acks)
    actions = bt.reply_fetch_batch(2, 3, b"bd")
    [send] = actions.sends
    assert send.targets == [2]
    fwd = send.msg.type
    assert isinstance(fwd, pb.ForwardBatch)
    assert fwd.request_acks == acks
    # Unknown digest: silently ignored.
    assert bt.reply_fetch_batch(2, 3, b"unknown").is_empty()
