"""Ed25519: host oracle (RFC 8032), batched kernel, signed-request mode.

BASELINE ladder rung 3 gates: the kernel's accept/reject must be
bit-equivalent to the host oracle on valid, corrupted, and structurally
invalid signatures, and a signed testengine run must authenticate every
request at ingress — dropping forged ones — while still reaching full
commitment with identical chains across nodes.
"""

import os
import random

import numpy as np
import pytest

from mirbft_tpu.crypto import ed25519_host as host


# -- host oracle ------------------------------------------------------------


def test_rfc8032_vectors():
    seed1 = bytes.fromhex(
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60"
    )
    assert host.public_key(seed1).hex() == (
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a"
    )
    assert host.sign(seed1, b"").hex() == (
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e0652249015"
        "55fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b"
    )
    seed2 = bytes.fromhex(
        "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb"
    )
    msg2 = bytes.fromhex("72")
    assert host.sign(seed2, msg2).hex() == (
        "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69d"
        "a085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00"
    )
    assert host.verify(host.public_key(seed2), msg2, host.sign(seed2, msg2))


def test_host_verify_rejects():
    seed = b"\x05" * 32
    pk, msg = host.public_key(seed), b"payload"
    sig = host.sign(seed, msg)
    assert host.verify(pk, msg, sig)
    assert not host.verify(pk, msg + b"!", sig)
    assert not host.verify(pk, msg, sig[:32] + sig[33:] + b"\x00")
    flipped = sig[:5] + bytes([sig[5] ^ 1]) + sig[6:]
    assert not host.verify(pk, msg, flipped)
    other = host.public_key(b"\x06" * 32)
    assert not host.verify(other, msg, sig)


# -- field arithmetic -------------------------------------------------------


def test_field_ops_exact_vs_bigints():
    import jax.numpy as jnp

    from mirbft_tpu.ops import ed25519 as k

    rng = random.Random(0)
    vals = [0, 1, 19, host.P - 1, host.P, host.P + 1, 2**255 - 1, 2**260 - 1]
    vals += [rng.randrange(2**260) for _ in range(16)]
    a_np = np.stack([k.int_to_limbs(v) for v in vals])
    b_np = np.stack([k.int_to_limbs(v) for v in reversed(vals)])
    a, b = jnp.asarray(a_np), jnp.asarray(b_np)
    m, s, d = k._mul(a, b), k._add(a, b), k._sub(a, b)
    c = k._canonical(k._carry(a))
    for i, (x, y) in enumerate(zip(vals, reversed(vals))):
        assert k.limbs_to_int(m[i]) % host.P == (x * y) % host.P
        assert k.limbs_to_int(s[i]) % host.P == (x + y) % host.P
        assert k.limbs_to_int(d[i]) % host.P == (x - y) % host.P
        assert k.limbs_to_int(c[i]) == x % host.P


# -- batched kernel vs oracle ----------------------------------------------


def _signed_corpus(n, rng):
    pks, msgs, sigs, expect = [], [], [], []
    for i in range(n):
        seed = bytes(rng.randrange(256) for _ in range(32))
        msg = bytes(rng.randrange(256) for _ in range(rng.randrange(1, 80)))
        pk, sig = host.public_key(seed), host.sign(seed, msg)
        kind = i % 4
        if kind == 1:  # corrupted R
            sig = bytes([sig[0] ^ 2]) + sig[1:]
        elif kind == 2:  # corrupted S
            sig = sig[:40] + bytes([sig[40] ^ 8]) + sig[41:]
        elif kind == 3:  # wrong message
            msg = msg + b"?"
        pks.append(pk)
        msgs.append(msg)
        sigs.append(sig)
        expect.append(host.verify(pk, msg, sig))
    return pks, msgs, sigs, expect


def test_kernel_matches_oracle():
    from mirbft_tpu.ops.ed25519 import verify_batch

    rng = random.Random(42)
    pks, msgs, sigs, expect = _signed_corpus(6, rng)
    # Structural invalids: host-rejected, never reach the device.
    pks += [b"\x00" * 31, host.public_key(b"\x01" * 32)]
    msgs += [b"x", b"x"]
    sigs += [b"\x00" * 64, b"\xff" * 64]  # bad pk len; S >= L
    expect += [False, False]
    got = verify_batch(pks, msgs, sigs)
    assert got.tolist() == expect
    assert any(expect) and not all(expect)  # corpus covers both outcomes


def test_kernel_chunked_pipeline_matches_oracle():
    """Multi-chunk reassembly: chunk=3 forces several in-flight launches
    with valid, device-rejected, and host-structural-rejected rows
    straddling chunk boundaries; results must land on the right rows."""
    from mirbft_tpu.ops.ed25519 import verify_batch

    pks, msgs, sigs, expect = [], [], [], []
    for i in range(11):
        seed, msg = bytes([i]) * 32, b"p-%d" % i
        pk, sig = host.public_key(seed), host.sign(seed, msg)
        if i % 3 == 1:
            msg += b"!"  # wrong message -> device reject
        if i == 7:
            sig = sig[:32] + b"\xff" * 32  # S >= L -> host reject
        pks.append(pk)
        msgs.append(msg)
        sigs.append(sig)
        expect.append(host.verify(pk, msg, sig))
    got = verify_batch(pks, msgs, sigs, chunk=3)
    assert got.tolist() == expect
    assert any(expect) and not all(expect)


# -- signed testengine runs -------------------------------------------------


def _chains(recorder):
    return {
        n: recorder.node_states[n].app_chain.hex()
        for n in range(recorder.node_count)
        if not recorder.node_states[n].crashed
    }


def test_signed_run_host_verifier():
    from mirbft_tpu import pb
    from mirbft_tpu.testengine import BasicRecorder
    from mirbft_tpu.testengine.signing import (
        SignaturePlane,
        host_verifier,
        make_signer,
    )

    plane = SignaturePlane(verifier=host_verifier)
    r = BasicRecorder(
        node_count=4,
        client_count=2,
        reqs_per_client=5,
        signer=make_signer(),
        signature_plane=plane,
    )
    # Inject a forged request: right shape, garbage signature.
    forged = pb.Request(
        client_id=4, req_no=99, data=b"evil" + b"\x01" * 96
    )
    for node in range(4):
        r._schedule(
            0, node, pb.StateEvent(type=pb.EventPropose(request=forged))
        )
    r.drain_clients(max_steps=200000)
    assert len(set(_chains(r).values())) == 1
    # Authentication actually ran, batched.
    assert plane.flush_sizes and max(plane.flush_sizes) >= 4
    # The forged request was dropped at ingress on every node: req_no 99
    # never commits anywhere.
    for state in r.node_states.values():
        assert all(rn != 99 for (_c, rn, _s) in state.committed_reqs)


@pytest.mark.slow
def test_signed_run_kernel_verifier_identical():
    """The kernel-authenticated run commits the same chains as the
    host-authenticated one (determinism carries over the verify seam)."""
    from mirbft_tpu.testengine import BasicRecorder
    from mirbft_tpu.testengine.signing import (
        SignaturePlane,
        host_verifier,
        kernel_verifier,
        make_signer,
    )

    runs = {}
    for name, verifier in (
        ("host", host_verifier),
        ("kernel", kernel_verifier),
    ):
        r = BasicRecorder(
            node_count=4,
            client_count=2,
            reqs_per_client=4,
            signer=make_signer(),
            signature_plane=SignaturePlane(verifier=verifier),
        )
        count = r.drain_clients(max_steps=200000)
        runs[name] = (count, tuple(sorted(_chains(r).values())))
    assert runs["host"] == runs["kernel"]


def _host_launch_rows(rows, sublanes=16):
    """CPU stand-in for ops.ed25519_pallas.launch_rows with the same
    contract (marshal_light rows -> forcible verdict array): checks
    [S]B == R + [k]A with the host point arithmetic.  Lets the async
    plane's wave/chunk machinery run under the CPU-pinned test conftest
    (Mosaic has no CPU lowering)."""
    out = []
    for pk, r32, s, k in rows:
        a = host.decompress(pk)
        r = host.decompress(r32)
        if a is None or r is None:
            out.append(False)
            continue
        lhs = host.scalar_mult(s, host.to_extended(host.BASE))
        rhs = host.point_add(r, host.scalar_mult(k, a))
        out.append(host.point_equal(lhs, rhs))
    return np.array(out, dtype=bool)


def test_async_plane_device_waves_match_sync():
    """AsyncSignaturePlane (proactive wave launches at time boundaries,
    verdicts forced at first delivery) produces the identical run to the
    synchronous demand-flush plane: same event count, same chains; forged
    requests still die at ingress — now at submit time."""
    from mirbft_tpu import pb
    from mirbft_tpu.testengine import BasicRecorder
    from mirbft_tpu.testengine.signing import (
        AsyncSignaturePlane,
        SignaturePlane,
        host_verifier,
        make_signer,
    )

    def run(plane):
        r = BasicRecorder(
            node_count=4,
            client_count=2,
            reqs_per_client=5,
            signer=make_signer(),
            signature_plane=plane,
        )
        forged = pb.Request(
            client_id=4, req_no=99, data=b"evil" + b"\x01" * 96
        )
        for node in range(4):
            r._schedule(
                0, node, pb.StateEvent(type=pb.EventPropose(request=forged))
            )
        count = r.drain_clients(max_steps=200000)
        for state in r.node_states.values():
            assert all(rn != 99 for (_c, rn, _s) in state.committed_reqs)
        return count, tuple(sorted(_chains(r).values()))

    async_plane = AsyncSignaturePlane(
        min_device_rows=4, launch_fn=_host_launch_rows
    )
    sync_run = run(SignaturePlane(verifier=host_verifier))
    async_run = run(async_plane)
    assert async_run == sync_run
    # The async plane actually launched waves ahead of demand.
    assert async_plane.overlapped_launches >= 1
    assert async_plane.device_verifies >= 10
    assert async_plane.host_verifies == 0


def test_async_plane_sub_tile_host_fallback():
    """Waves below min_device_rows never launch; a demanded verdict
    host-verifies the pending wave synchronously (the straggler path)."""
    from mirbft_tpu.testengine import BasicRecorder
    from mirbft_tpu.testengine.signing import AsyncSignaturePlane, make_signer

    def no_launch(rows, sublanes=16):
        raise AssertionError("sub-tile wave must not reach the device")

    plane = AsyncSignaturePlane(min_device_rows=10**6, launch_fn=no_launch)
    r = BasicRecorder(
        node_count=4,
        client_count=2,
        reqs_per_client=3,
        signer=make_signer(),
        signature_plane=plane,
    )
    r.drain_clients(max_steps=200000)
    assert len(set(_chains(r).values())) == 1
    assert plane.host_verifies >= 6
    assert plane.overlapped_launches == 0


def test_async_plane_rejects_before_launch():
    """Structural garbage and client-identity mismatches are rejected at
    submit time without consuming kernel work."""
    from mirbft_tpu.testengine.signing import (
        AsyncSignaturePlane,
        make_signer,
        signing_message,
    )

    def no_launch(rows, sublanes=16):
        raise AssertionError("rejected rows must not reach a wave")

    plane = AsyncSignaturePlane(launch_fn=no_launch)
    # Too short for the sig+pk trailer.
    plane.submit(7, 0, b"tiny")
    assert plane.valid(7, 0, b"tiny") is False
    # Right shape, wrong public key for the claimed client id.
    wrong_pk = host.public_key(b"\x09" * 32)
    sig = host.sign(b"\x09" * 32, signing_message(7, 1, b"payload"))
    assert plane.valid(7, 1, b"payload" + sig + wrong_pk) is False
    # Correct key but corrupted signature: this one DOES need crypto —
    # and a sub-tile host flush resolves it (no launch).
    plane2 = AsyncSignaturePlane(
        min_device_rows=10**6, launch_fn=no_launch
    )
    signer = make_signer()
    good = signer(7, 2, b"payload")
    corrupted = bytes([good[0] ^ 1]) + good[1:]
    assert plane2.valid(7, 2, corrupted) is False
    assert plane2.valid(7, 2, good) is True
    assert plane2.host_verifies == 2


# -- Pallas kernels (ops/ed25519_pallas.py) ---------------------------------


def test_pallas_field_ops_exact_vs_bigints():
    """The slab field helpers (mul/sqr/add/sub/canonical) against host
    bigints, in interpret mode on tiny (1, 8) tiles — fast enough for
    every run; the full ladder is validated on real hardware by the
    TPU-gated test below."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from mirbft_tpu.ops import ed25519_pallas as kp
    from mirbft_tpu.ops.ed25519 import NLIMB, int_to_limbs, limbs_to_int

    rng = random.Random(1)
    vals = [0, 1, 19, host.P - 1, host.P, host.P + 1, 2**255 - 1]
    vals += [rng.randrange(2**260) for _ in range(1)]
    assert len(vals) == 8

    def kernel(a_ref, b_ref, mul_ref, sqr_ref, add_ref, sub_ref, can_ref):
        a = [a_ref[i, :, :] for i in range(NLIMB)]
        b = [b_ref[i, :, :] for i in range(NLIMB)]
        for i, v in enumerate(kp._mul(a, b)):
            mul_ref[i, :, :] = v
        for i, v in enumerate(kp._sqr(a)):
            sqr_ref[i, :, :] = v
        for i, v in enumerate(kp._add(a, b)):
            add_ref[i, :, :] = v
        for i, v in enumerate(kp._sub(a, b)):
            sub_ref[i, :, :] = v
        for i, v in enumerate(kp._canonical(kp._carry(a))):
            can_ref[i, :, :] = v

    def tile(ints):
        arr = np.stack([int_to_limbs(v) for v in ints]).astype(np.int32)
        return jnp.moveaxis(jnp.asarray(arr), 0, 1).reshape(NLIMB, 1, 8)

    shape = jax.ShapeDtypeStruct((NLIMB, 1, 8), jnp.int32)
    outs = pl.pallas_call(
        kernel,
        out_shape=(shape,) * 5,
        interpret=True,
    )(tile(vals), tile(list(reversed(vals))))
    mul, sqr, add, sub, can = (
        np.moveaxis(np.asarray(o).reshape(NLIMB, 8), 0, 1) for o in outs
    )
    for i, (x, y) in enumerate(zip(vals, reversed(vals))):
        assert limbs_to_int(mul[i]) % host.P == (x * y) % host.P
        assert limbs_to_int(sqr[i]) % host.P == (x * x) % host.P
        assert limbs_to_int(add[i]) % host.P == (x + y) % host.P
        assert limbs_to_int(sub[i]) % host.P == (x - y) % host.P
        assert limbs_to_int(can[i]) == x % host.P


@pytest.mark.skipif(
    not os.environ.get("MIRBFT_TPU_TPU_TESTS"),
    reason="Mosaic compile of the full ladder takes minutes on first run; "
    "set MIRBFT_TPU_TPU_TESTS=1 to run on a real TPU",
)
@pytest.mark.slow
def test_pallas_verify_pipeline_matches_oracle():
    """Full device pipeline (decompression + windowed ladder) vs the host
    oracle on a mixed corpus, including host-structural rejects.

    Mosaic has no CPU lowering and the test conftest pins JAX to the CPU
    platform, so under pytest this skips unless a TPU backend is visible;
    run it standalone (JAX_PLATFORMS unset) on real hardware.  The bench's
    built-in validity cross-check covers the same path on every run."""
    import jax

    from mirbft_tpu.ops.ed25519_pallas import verify_batch_pallas

    try:
        tpu = jax.devices("tpu")[0]
    except RuntimeError:
        pytest.skip("no TPU backend available")

    rng = random.Random(7)
    pks, msgs, sigs, expect = _signed_corpus(61, rng)
    pks += [b"\x00" * 31, host.public_key(b"\x01" * 32)]
    msgs += [b"x", b"x"]
    sigs += [b"\x00" * 64, b"\xff" * 64]  # bad pk len; S >= L
    expect += [False, False]
    with jax.default_device(tpu):
        got = verify_batch_pallas(pks, msgs, sigs)
    assert got.tolist() == expect
    assert any(expect) and not all(expect)


def test_launch_rows_rejects_an_empty_batch():
    """launch_rows pads a batch by replicating rows[0]; an empty list
    must fail loudly instead of raising IndexError mid-padding."""
    from mirbft_tpu.ops.ed25519_pallas import launch_rows

    with pytest.raises(ValueError, match="at least one"):
        launch_rows([])
