"""Gate for core.quorum: quorum math, bitmask, buckets, new-view selection
(reference behaviors: stateless.go:18-309)."""

import pytest

from mirbft_tpu import pb
from mirbft_tpu.core import quorum
from mirbft_tpu.core.epoch_change import parse_epoch_change


def config(n=4, f=1, buckets=4, ci=5, max_epoch_len=50):
    return pb.NetworkConfig(
        nodes=list(range(n)),
        f=f,
        number_of_buckets=buckets,
        checkpoint_interval=ci,
        max_epoch_length=max_epoch_len,
    )


def test_quorum_sizes():
    # (n + f + 2) // 2 == ceil((n+f+1)/2)
    assert quorum.intersection_quorum(config(4, 1)) == 3
    assert quorum.some_correct_quorum(config(4, 1)) == 2
    assert quorum.intersection_quorum(config(1, 0)) == 1
    assert quorum.some_correct_quorum(config(1, 0)) == 1
    assert quorum.intersection_quorum(config(7, 2)) == 5
    assert quorum.intersection_quorum(config(10, 3)) == 7


def test_bucket_mapping():
    nc = config(buckets=4)
    assert quorum.seq_to_bucket(0, nc) == 0
    assert quorum.seq_to_bucket(7, nc) == 3
    assert quorum.client_req_to_bucket(2, 3, nc) == 1
    # Consecutive reqs from one client rotate through buckets.
    buckets = [quorum.client_req_to_bucket(9, r, nc) for r in range(4)]
    assert sorted(buckets) == [0, 1, 2, 3]


def test_bitmask_msb_first():
    mask = quorum.make_bitmask(12)
    assert len(mask) == 2
    quorum.set_bit(mask, 0)
    assert bytes(mask) == b"\x80\x00"
    quorum.set_bit(mask, 7)
    assert bytes(mask) == b"\x81\x00"
    quorum.set_bit(mask, 8)
    assert bytes(mask) == b"\x81\x80"
    assert quorum.bit_is_set(mask, 0)
    assert not quorum.bit_is_set(mask, 1)
    assert quorum.bit_is_set(mask, 8)
    # Out-of-range reads are False, writes raise.
    assert not quorum.bit_is_set(mask, 100)
    with pytest.raises(IndexError):
        quorum.set_bit(mask, 16)


# ---------------------------------------------------------------------------
# construct_new_epoch_config
# ---------------------------------------------------------------------------


def _ec(new_epoch, checkpoints, p_set=(), q_set=()):
    return parse_epoch_change(
        pb.EpochChange(
            new_epoch=new_epoch,
            checkpoints=[pb.Checkpoint(seq_no=s, value=v) for s, v in checkpoints],
            p_set=[
                pb.EpochChangeSetEntry(epoch=e, seq_no=s, digest=d)
                for e, s, d in p_set
            ],
            q_set=[
                pb.EpochChangeSetEntry(epoch=e, seq_no=s, digest=d)
                for e, s, d in q_set
            ],
        )
    )


def test_new_epoch_config_idle_network():
    """All nodes at the same checkpoint with nothing in flight → config
    starts there with no final preprepares."""
    nc = config(4, 1, ci=5, max_epoch_len=50)
    changes = {i: _ec(1, [(20, b"cp20")]) for i in range(4)}
    result = quorum.construct_new_epoch_config(nc, [0, 1, 2, 3], changes)
    assert result is not None
    assert result.config.number == 1
    assert result.config.leaders == [0, 1, 2, 3]
    assert result.config.planned_expiration == 20 + 50
    assert result.starting_checkpoint == pb.Checkpoint(seq_no=20, value=b"cp20")
    assert result.final_preprepares == []


def test_new_epoch_config_insufficient_changes():
    nc = config(4, 1)
    changes = {0: _ec(1, [(20, b"cp20")])}  # only 1 of 4; need 3 reachable
    assert quorum.construct_new_epoch_config(nc, [0], changes) is None


def test_new_epoch_config_selects_highest_supported_checkpoint():
    nc = config(4, 1, ci=5, max_epoch_len=50)
    changes = {
        0: _ec(1, [(20, b"cp20"), (25, b"cp25")]),
        1: _ec(1, [(20, b"cp20"), (25, b"cp25")]),
        2: _ec(1, [(20, b"cp20")]),
        3: _ec(1, [(20, b"cp20")]),
    }
    result = quorum.construct_new_epoch_config(nc, [0, 1, 2, 3], changes)
    # 25 has f+1=2 supporters and all low watermarks are 20 <= 25.
    assert result.starting_checkpoint.seq_no == 25


def test_new_epoch_config_condition_a_selects_prepared_digest():
    nc = config(4, 1, ci=5, max_epoch_len=50)
    d = b"\xaa" * 32
    # Three nodes prepared seq 21 digest d in epoch 0; they also preprepared
    # it (qSet).  Fourth node is silent.
    changes = {
        i: _ec(1, [(20, b"cp")], p_set=[(0, 21, d)], q_set=[(0, 21, d)])
        for i in range(3)
    }
    result = quorum.construct_new_epoch_config(nc, [0, 1, 2, 3], changes)
    assert result is not None
    assert len(result.final_preprepares) == 2 * nc.checkpoint_interval
    assert result.final_preprepares[0] == d  # seq 21 = offset 0
    assert all(fp == b"" for fp in result.final_preprepares[1:])


def test_new_epoch_config_condition_b_nulls_unprepared():
    nc = config(4, 1, ci=5, max_epoch_len=50)
    # Nobody prepared anything: every in-flight slot nulls out.
    changes = {i: _ec(1, [(20, b"cp")]) for i in range(3)}
    result = quorum.construct_new_epoch_config(nc, [0, 1, 2, 3], changes)
    assert result is not None
    assert result.final_preprepares == []


def test_new_epoch_config_waits_when_a_and_b_unsatisfiable():
    nc = config(4, 1, ci=5, max_epoch_len=50)
    d = b"\xbb" * 32
    # One node prepared seq 21; without qSet backing (a2 < f+1) condition A
    # fails, and with only 3 changes condition B (needs 3 without the entry,
    # but node 0 has it) counts 2 < 3 → must wait.
    changes = {i: _ec(1, [(20, b"cp")]) for i in range(1, 3)}
    changes[0] = _ec(1, [(20, b"cp")], p_set=[(0, 21, d)])
    assert quorum.construct_new_epoch_config(nc, [0, 1, 2, 3], changes) is None


def test_new_epoch_config_divergent_checkpoints_raise():
    nc = config(4, 1)
    changes = {
        0: _ec(1, [(20, b"value-A")]),
        1: _ec(1, [(20, b"value-A")]),
        2: _ec(1, [(20, b"value-B")]),
        3: _ec(1, [(20, b"value-B")]),
    }
    with pytest.raises(quorum.DivergentCheckpointError):
        quorum.construct_new_epoch_config(nc, [0, 1, 2, 3], changes)


def test_new_epoch_config_single_node_network():
    nc = config(1, 0, buckets=1, ci=1, max_epoch_len=10)
    changes = {0: _ec(1, [(0, b"genesis")])}
    result = quorum.construct_new_epoch_config(nc, [0], changes)
    assert result is not None
    assert result.starting_checkpoint.seq_no == 0
    assert result.config.planned_expiration == 10
