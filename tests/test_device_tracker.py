"""Gates for the device-resident ack plane (core/device_tracker.py):
plane selection (Config / env / clean fallback without a jax backend),
scalar-reference equivalence of the jitted bitmask kernels, the
divergence oracle catching an injected device-side bit flip within one
sampler stride (with a flight-recorder dump), a 10k-client scalar vs
device parity sweep under a seeded ack storm, and the ack-plane metrics
both planes emit (docs/DEVICE_TRACKER.md, docs/OBSERVABILITY.md).
"""

import numpy as np
import pytest

from mirbft_tpu import pb
from mirbft_tpu.core import device_tracker
from mirbft_tpu.core.client_tracker import _NULL, ClientTracker
from mirbft_tpu.core.msgbuffers import NodeBuffers
from mirbft_tpu.core.persisted import Persisted
from mirbft_tpu.core.preimage import host_digest, request_hash_data
from mirbft_tpu.obsv import hooks, shadow
from mirbft_tpu.obsv.metrics import ACK_BATCH_BUCKETS, CATALOG, Registry
from mirbft_tpu.obsv.recorder import FlightRecorder
from mirbft_tpu.runtime.config import Config

needs_device = pytest.mark.skipif(
    not device_tracker.device_plane_available(),
    reason="no usable jax backend",
)


# -- tracker scaffolding (same idiom as test_device_obsv) --------------------


def network_state(clients=((7, 100),), n=4, f=1, ci=5):
    return pb.NetworkState(
        config=pb.NetworkConfig(
            nodes=list(range(n)),
            f=f,
            number_of_buckets=n,
            checkpoint_interval=ci,
            max_epoch_length=50,
        ),
        clients=[
            pb.NetworkClient(id=cid, width=width, low_watermark=0)
            for cid, width in clients
        ],
    )


def make_tracker(state=None, ack_plane=None, ack_flush_rows=None):
    persisted = Persisted()
    persisted.add_c_entry(
        pb.CEntry(
            seq_no=0,
            checkpoint_value=b"genesis",
            network_state=state if state is not None else network_state(),
        )
    )
    my = pb.InitialParameters(id=0, buffer_size=1 << 20)
    ct = ClientTracker(
        persisted, NodeBuffers(my), my, ack_plane=ack_plane,
        ack_flush_rows=ack_flush_rows,
    )
    ct.reinitialize()
    return ct


def req(client_id=7, req_no=0, data=b"tx"):
    r = pb.Request(client_id=client_id, req_no=req_no, data=data)
    digest = host_digest(request_hash_data(r))
    return r, pb.RequestAck(client_id=client_id, req_no=req_no, digest=digest)


def ack_msg(ack):
    return pb.Msg(type=ack)


def build_device_tracker(n_reqs=40):
    """Device-plane tracker after a three-source ack storm over reqs
    0..n_reqs-1 (every slot ends with a strong certificate)."""
    ct = make_tracker(ack_plane="device")
    assert ct._device_ok
    acks = [req(req_no=i)[1] for i in range(n_reqs)]
    for source in (1, 2, 3):
        ct.step_ack_many(source, [ack_msg(a) for a in acks])
    assert ct._device is not None, "device plane never built"
    assert ct._fast is None, "host mirror must not coexist with the plane"
    return ct, acks


# -- plane selection ----------------------------------------------------------


def test_config_validates_ack_plane_and_shadow_stride():
    Config(id=0, ack_plane="device", shadow_stride=4, ack_flush_rows=4096)
    with pytest.raises(ValueError, match="ack_plane"):
        Config(id=0, ack_plane="gpu")
    with pytest.raises(ValueError, match="shadow_stride"):
        Config(id=0, shadow_stride=0)
    with pytest.raises(ValueError, match="ack_flush_rows"):
        Config(id=0, ack_flush_rows=0)


def test_resolve_ack_plane_explicit_env_default(monkeypatch):
    monkeypatch.delenv("MIRBFT_ACK_PLANE", raising=False)
    assert device_tracker.resolve_ack_plane() == "host"
    monkeypatch.setenv("MIRBFT_ACK_PLANE", "device")
    assert device_tracker.resolve_ack_plane() == "device"
    # Explicit config beats the env knob.
    assert device_tracker.resolve_ack_plane("host") == "host"
    with pytest.raises(ValueError, match="ack_plane"):
        device_tracker.resolve_ack_plane("tpu")
    monkeypatch.setenv("MIRBFT_ACK_PLANE", "bogus")
    with pytest.raises(ValueError, match="ack_plane"):
        device_tracker.resolve_ack_plane()


def test_resolve_stride_explicit_env_default(monkeypatch):
    monkeypatch.delenv("MIRBFT_SHADOW_STRIDE", raising=False)
    assert shadow.resolve_stride() == shadow.DEFAULT_STRIDE
    monkeypatch.setenv("MIRBFT_SHADOW_STRIDE", "3")
    assert shadow.resolve_stride() == 3
    assert shadow.resolve_stride(7) == 7  # explicit wins
    assert shadow.ShadowSampler(stride=5).stride == 5


def test_resolve_flush_rows_explicit_env_default(monkeypatch):
    monkeypatch.delenv("MIRBFT_ACK_FLUSH_ROWS", raising=False)
    assert device_tracker.resolve_flush_rows() == 1
    monkeypatch.setenv("MIRBFT_ACK_FLUSH_ROWS", "4096")
    assert device_tracker.resolve_flush_rows() == 4096
    assert device_tracker.resolve_flush_rows(8) == 8  # explicit wins
    with pytest.raises(ValueError, match="ack_flush_rows"):
        device_tracker.resolve_flush_rows(0)
    monkeypatch.setenv("MIRBFT_ACK_FLUSH_ROWS", "zap")
    with pytest.raises(ValueError, match="ack_flush_rows"):
        device_tracker.resolve_flush_rows()


def test_device_plane_falls_back_cleanly_without_backend(monkeypatch):
    """The tier-1 guard: ack_plane="device" with no usable jax backend
    (or a plane whose construction dies) must keep full host-path
    semantics — same quorum state, no divergences, no crash."""
    monkeypatch.setattr(
        device_tracker, "device_plane_available", lambda: False
    )
    ct = make_tracker(ack_plane="device")
    assert not ct._device_ok
    acks = [req(req_no=i)[1] for i in range(40)]
    for source in (1, 2, 3):
        ct.step_ack_many(source, [ack_msg(a) for a in acks])
    assert ct._device is None
    assert ct._fast is not None  # host columnar mirror took over
    crn = ct.client(7).req_no(0)
    assert acks[0].digest in crn.strong_requests
    assert shadow.audit_tracker(ct) == []


def test_device_plane_falls_back_when_construction_raises(monkeypatch):
    monkeypatch.setattr(
        device_tracker, "device_plane_available", lambda: True
    )
    monkeypatch.setattr(
        device_tracker,
        "DeviceClientPlane",
        type(
            "Boom",
            (),
            {"__init__": lambda self, *a, **k: 1 / 0},
        ),
    )
    ct = make_tracker(ack_plane="device")
    assert ct._device_ok  # optimistic until the first build attempt
    acks = [req(req_no=i)[1] for i in range(40)]
    ct.step_ack_many(1, [ack_msg(a) for a in acks])
    assert ct._device is None and not ct._device_ok
    ct.step_ack_many(2, [ack_msg(a) for a in acks])
    ct.step_ack_many(3, [ack_msg(a) for a in acks])
    crn = ct.client(7).req_no(0)
    assert acks[0].digest in crn.strong_requests
    assert shadow.audit_tracker(ct) == []


# -- scalar-reference equivalence --------------------------------------------


@needs_device
def test_device_plane_matches_scalar_reference():
    ct, acks = build_device_tracker()
    dev = ct._device
    assert dev.acks_fallback == 0, "clean storm must not fall back"
    crn = ct.client(7).req_no(0)
    assert acks[0].digest in crn.weak_requests
    assert acks[0].digest in crn.strong_requests
    assert shadow.audit_tracker(ct) == []
    certs = dev.quorum_sweep()
    assert certs == {"weak_certs": 40, "strong_certs": 40, "committed": 0}


@needs_device
def test_conflicting_digest_falls_back_to_scalar_path():
    """A second distinct digest for an adopted slot cannot be a dense
    row: the kernel flags it, the scalar reference path absorbs it, and
    the slot goes host-authoritative with no divergence."""
    ct, acks = build_device_tracker(n_reqs=4)
    evil = req(req_no=0, data=b"conflicting")[1]
    # Source 0 never voted in build_device_tracker, so the scalar spam
    # guard (one non-null vote per node) does not apply to this row.
    ct.step_ack_many(0, [ack_msg(evil)])
    assert ct._device.acks_fallback >= 1
    crn = ct.client(7).req_no(0)
    assert evil.digest in crn.requests  # scalar path recorded the vote
    assert acks[0].digest in crn.strong_requests  # canonical unharmed
    assert shadow.audit_tracker(ct) == []


@needs_device
def test_committed_slots_drop_acks_on_device():
    ct, acks = build_device_tracker(n_reqs=4)
    ct.mark_committed(7, 0, seq_no=1)
    dropped = ct._device.acks_dropped
    ct.step_ack_many(1, [ack_msg(acks[0])])
    assert ct._device.acks_dropped > dropped
    assert shadow.audit_tracker(ct) == []


@needs_device
def test_mixed_null_digest_and_out_of_window_frame():
    """One frame carrying a null-digest row (filtered out of the dense
    submit) AND a later out-of-window row: the out-row indices returned
    by submit_columns refer to the FILTERED subset, so the replay must
    map them back through it.  Replaying against the original frame
    double-applies an in-window ack and silently drops the real
    out-of-window ack — node state depending on transport framing."""
    ct, acks = build_device_tracker(n_reqs=4)
    null_ack = pb.RequestAck(client_id=7, req_no=1, digest=b"")
    oow = pb.RequestAck(client_id=7, req_no=150, digest=b"\x07" * 32)
    frame = [ack_msg(null_ack), ack_msg(acks[2]), ack_msg(oow)]
    buf = ct.msg_buffers[0]
    assert len(buf) == 0
    ct.step_ack_many(0, frame)
    # The out-of-window ack is FUTURE: buffered, never dropped.
    assert [m.type.req_no for m, _ in buf.msgs] == [150]
    # The null-digest ack took the scalar path into slot (7, 1).
    crn1 = ct.client(7).req_no(1)
    assert _NULL in crn1.requests
    assert crn1.requests[_NULL].agreements & 1  # node 0's vote
    # The dense row (source 0's vote for the canonical digest of slot
    # (7, 2)) went through the kernel exactly once.
    dev = ct._device
    dev.sync_slot(7, 2)
    crn2 = ct.client(7).req_no(2)
    assert crn2.requests[acks[2].digest].agreements == 0b1111
    assert shadow.audit_tracker(ct) == []


@needs_device
def test_sync_slot_drains_buffered_events_from_column_ingest():
    """The public submit_columns ingest (the bench's native driver)
    buffers boundary events when flushed without a drain target;
    sync_slot must drain queued batches AND those buffered events into
    the owning tracker before staging the slot, or the next staged
    re-derivation rebuilds the row from vote-less objects and the
    applied acks vanish."""
    ct = make_tracker(ack_plane="device")
    assert ct._device_ok
    dev = ct._build_device()
    assert dev is not None
    acks = [req(req_no=i)[1] for i in range(4)]
    ids = np.array([a.client_id for a in acks], dtype=np.int64)
    rnos = np.array([a.req_no for a in acks], dtype=np.int64)
    dig_mat = np.frombuffer(
        b"".join(a.digest for a in acks), dtype=np.uint8
    ).reshape(len(acks), 32)
    # Two sources flushed without a drain target, a third left queued.
    for s in (1, 2):
        assert len(dev.submit_columns(s, ids, rnos, dig_mat)) == 0
        dev.flush(drain=None)
    assert len(dev.submit_columns(3, ids, rnos, dig_mat)) == 0
    assert dev._events and dev._pending_rows == 4
    # A host path syncs the slot between submit and drain: the buffered
    # adoptions/crossings must land in the objects BEFORE the slot goes
    # host-authoritative.
    dev.sync_slot(7, 0)
    assert not dev._events and dev._pending_rows == 0
    crn = ct.client(7).req_no(0)
    assert acks[0].digest in crn.requests
    assert crn.requests[acks[0].digest].agreements == 0b1110
    assert acks[0].digest in crn.weak_requests
    assert acks[0].digest in crn.strong_requests
    assert crn.non_null_voters == 0b1110
    assert shadow.audit_tracker(ct) == []


@needs_device
def test_small_frame_coalescing_defers_flush_until_sync_points():
    """ack_flush_rows > 1 coalesces small frames in the pending queue:
    no kernel launch until the row threshold, with scalar-mutation sync
    (sync_slot) and the tick boundary forcing an earlier flush+drain so
    the observable object state stays frame-equivalent."""
    ct = make_tracker(ack_plane="device", ack_flush_rows=16)
    acks = [req(req_no=i)[1] for i in range(8)]
    frame = [ack_msg(a) for a in acks]
    ct.step_ack_many(1, frame)
    dev = ct._device
    assert dev is not None
    assert dev.flush_rows == 16
    assert dev.batches == 0 and dev._pending_rows == 8  # deferred
    crn = ct.client(7).req_no(0)
    assert acks[0].digest not in crn.requests  # not yet materialized
    ct.step_ack_many(2, frame)  # 16 rows reach the threshold
    assert dev.batches == 1 and dev._pending_rows == 0
    assert acks[0].digest in crn.weak_requests  # events drained at flush
    ct.step_ack_many(3, frame)  # deferred again (8 < 16)
    assert dev.batches == 1 and dev._pending_rows == 8
    assert acks[0].digest not in crn.strong_requests
    # Scalar mutation forces the sync flush before the slot stages.
    ct.step_ack(3, ack_msg(acks[0]))
    assert dev.batches == 2 and dev._pending_rows == 0
    assert acks[0].digest in crn.strong_requests
    assert shadow.audit_tracker(ct) == []
    # The tick boundary flushes whatever is still queued.
    acks2 = [req(req_no=8 + i)[1] for i in range(4)]
    ct.step_ack_many(1, [ack_msg(a) for a in acks2])
    assert dev.batches == 2 and dev._pending_rows == 4
    ct.tick()
    assert dev.batches == 3 and dev._pending_rows == 0
    crn8 = ct.client(7).req_no(8)
    assert crn8.requests[acks2[0].digest].agreements == 0b0010
    assert shadow.audit_tracker(ct) == []


# -- injected divergence ------------------------------------------------------


@needs_device
def test_injected_device_bitflip_caught_within_stride(tmp_path):
    """Flip one agreement bit in the device bitmask (a vote the scalar
    state never saw): the sampling shadow must catch it within one
    stride of touched frames and dump the flight recorder."""
    ct, acks = build_device_tracker(n_reqs=8)
    dev = ct._device
    # Bit-flip: remove node 3's recorded vote for slot (7, 0) directly
    # in the device array — popcount drops below the strong quorum while
    # the object-level strong_requests membership stands.
    slot = dev.slot_of(7, 0)
    ci, w = slot // dev.w_pad, slot % dev.w_pad
    limb = np.uint32(dev._dev[0][ci, w, 0])
    dev._dev[0] = dev._dev[0].at[ci, w, 0].set(limb & ~np.uint32(1 << 3))
    dev._snapshot = None

    reg = Registry()
    rec = FlightRecorder("device-shadow-test", dump_dir=str(tmp_path))
    sampler = shadow.ShadowSampler(stride=2, registry=reg, recorder=rec)
    hooks.shadow = sampler
    try:
        # Duplicate canonical acks touch the poisoned slot without
        # mutating it, so the divergence persists until a sampled frame
        # audits the touched set.
        frames = 0
        while not sampler.divergences and frames < 8:
            ct.step_ack_many(1, [ack_msg(acks[0])])
            frames += 1
        assert sampler.divergences, "sampler never saw the bit flip"
        assert frames <= sampler.stride, "not caught within one stride"
        comps = {d["component"] for d in sampler.divergences}
        assert "strong" in comps
        snap = reg.snapshot()
        total = sum(
            s["value"] for s in snap["mirbft_divergence_total"]["series"]
        )
        assert total >= 1
        assert sampler._dumped
        assert any(tmp_path.iterdir()), "no flight-recorder dump written"
    finally:
        hooks.shadow = None


# -- 10k-client parity sweep --------------------------------------------------


@needs_device
def test_parity_sweep_10k_clients_under_seeded_ack_storm():
    """Host plane and device plane absorb the identical seeded ack storm
    (shuffled frames, duplicates, conflicting digests, out-of-window
    rows) at 10k clients; sampled slots must agree object-for-object and
    the oracle must find nothing."""
    n_clients = 10_000
    frame = 2048
    rng = np.random.default_rng(0xD1CE)
    state = [
        network_state(clients=tuple((cid, 1) for cid in range(n_clients)))
        for _ in range(2)
    ]
    host = make_tracker(state[0], ack_plane="host")
    devt = make_tracker(state[1], ack_plane="device")
    assert devt._device_ok

    digests = {}

    def storm_ack(cid, data=b"tx"):
        r = pb.Request(client_id=int(cid), req_no=0, data=data)
        d = digests.get((int(cid), data))
        if d is None:
            d = host_digest(request_hash_data(r))
            digests[(int(cid), data)] = d
        return pb.RequestAck(client_id=int(cid), req_no=0, digest=d)

    conflicted = set(
        rng.choice(n_clients, size=100, replace=False).tolist()
    )
    for source in (1, 2, 3):
        order = rng.permutation(n_clients)
        msgs = []
        for cid in order.tolist():
            if source == 3 and cid in conflicted:
                msgs.append(ack_msg(storm_ack(cid, data=b"fork")))
            else:
                msgs.append(ack_msg(storm_ack(cid)))
        # Sprinkle duplicates and out-of-window rows into every storm.
        for cid in rng.choice(n_clients, size=64, replace=False).tolist():
            msgs.append(ack_msg(storm_ack(cid)))
            msgs.append(
                pb.Msg(
                    type=pb.RequestAck(
                        client_id=int(cid),
                        req_no=50,
                        digest=b"\x07" * 32,
                    )
                )
            )
        for lo in range(0, len(msgs), frame):
            chunk = msgs[lo : lo + frame]
            host.step_ack_many(source, chunk)
            devt.step_ack_many(source, chunk)

    dev = devt._device
    assert dev is not None

    # Certificate totals from one device pass: every unconflicted client
    # reached the strong quorum; conflicted slots went host-authoritative
    # (SLOW) and are excluded from the dense tally by design.
    certs = dev.quorum_sweep()
    assert certs["strong_certs"] == n_clients - len(conflicted)
    assert certs["committed"] == 0

    # Sampled object-level parity: sync pulls the device-authoritative
    # masks into the objects, then the two trackers must agree exactly.
    sample = rng.choice(n_clients, size=1500, replace=False)
    for cid in sample.tolist():
        dev.sync_slot(cid, 0)
        h = host.clients[cid].req_no_map[0]
        d = devt.clients[cid].req_no_map[0]
        assert set(h.requests) == set(d.requests), cid
        assert set(h.weak_requests) == set(d.weak_requests), cid
        assert set(h.strong_requests) == set(d.strong_requests), cid
        assert h.non_null_voters == d.non_null_voters, cid
        for digest, hreq in h.requests.items():
            assert hreq.agreements == d.requests[digest].agreements, cid

    # Oracle sweep over a fresh sample (sync staged the parity sample).
    audit = rng.choice(n_clients, size=1500, replace=False)
    slots = [int(c) * dev.w_pad for c in audit.tolist()]
    assert shadow.audit_tracker(devt, slots=slots) == []


# -- metrics ------------------------------------------------------------------


@needs_device
def test_ack_metrics_emitted_from_both_planes():
    assert "mirbft_ack_events_total" in CATALOG
    assert "mirbft_ack_batch_size" in CATALOG
    reg = Registry()
    hooks.enable(registry=reg)
    try:
        acks = [req(req_no=i)[1] for i in range(40)]
        host = make_tracker(ack_plane="host")
        host.step_ack_many(1, [ack_msg(a) for a in acks])
        devt = make_tracker(ack_plane="device")
        devt.step_ack_many(1, [ack_msg(a) for a in acks])
        assert devt._device is not None
    finally:
        hooks.disable()
    snap = reg.snapshot()
    events = {
        s["labels"]["plane"]: s["value"]
        for s in snap["mirbft_ack_events_total"]["series"]
    }
    assert events == {"host": 40, "device": 40}
    batches = {
        s["labels"]["plane"]: s["count"]
        for s in snap["mirbft_ack_batch_size"]["series"]
    }
    assert batches == {"host": 1, "device": 1}
    assert ACK_BATCH_BUCKETS[0] == 1  # single-ack frames stay observable
