"""State transfer exercised end-to-end (VERDICT r2 item 5; reference:
commitstate.go:103-116, mirbft_test.go:157-170 late-start scenario): a node
that falls behind past garbage collection must emit a transfer request,
adopt a peer checkpoint, and converge to the common chain."""

from mirbft_tpu import pb
from mirbft_tpu.testengine import BasicRecorder
from mirbft_tpu.testengine.manglers import (
    after_events,
    is_step,
    once,
    rule,
    to_node,
)


def test_late_starting_node_adopts_state():
    """The reference's late-start scenario: node 3 is down from t=0 while
    the other three commit 80 requests (4 checkpoint windows — far past
    GC); on reboot it must state-transfer, not replay."""
    r = BasicRecorder(node_count=4, client_count=2, reqs_per_client=40)
    r.crash(3)
    r.schedule_restart(3, 40_000)
    r.drain_clients(max_steps=1_000_000)

    total = 2 * 40
    r.drain_until(lambda rec: rec.committed_at(3) >= total, max_steps=1_000_000)

    # A transfer was actually adopted (not replayed commit-by-commit).
    adopted = [
        (t, n)
        for (t, n, e) in r.recorded_events
        if isinstance(e.type, pb.EventTransfer)
        and e.type.c_entry.network_state is not None
    ]
    assert adopted and all(n == 3 for _t, n in adopted)

    chains = {n: r.node_states[n].app_chain for n in range(4)}
    assert len(set(chains.values())) == 1 and chains[3] != b""


def test_crash_past_gc_then_restart_transfers():
    """Crash a node mid-run, keep the network going past GC, restart:
    the rebooted node transfers forward instead of stalling."""
    r = BasicRecorder(node_count=4, client_count=2, reqs_per_client=40)

    # Let everyone commit a little, then take node 2 down.
    r.drain_until(lambda rec: rec.committed_at(2) >= 10, max_steps=1_000_000)
    r.crash(2)
    r.schedule_restart(2, 60_000)
    r.drain_clients(max_steps=1_000_000)

    total = 2 * 40
    r.drain_until(lambda rec: rec.committed_at(2) >= total, max_steps=1_000_000)
    chains = {n: r.node_states[n].app_chain for n in range(4)}
    assert len(set(chains.values())) == 1


def test_crash_and_restart_dsl_past_gc_transfers():
    """The mangler DSL's crash_and_restart_after interacting with state
    transfer: the crash fires from inside the mangling pipeline (not a
    test-driven crash()), the network garbage-collects past the victim's
    log during the 60s outage, and the reboot must recover by adopting a
    peer checkpoint — with no lost or re-applied commits."""
    r = BasicRecorder(
        node_count=4,
        client_count=2,
        reqs_per_client=40,
        manglers=[
            rule(to_node(2), is_step(), after_events(120), once())
            .crash_and_restart_after(60_000)
        ],
    )
    r.drain_clients(max_steps=1_000_000)
    total = 2 * 40
    r.drain_until(lambda rec: rec.committed_at(2) >= total, max_steps=1_000_000)

    adopted = [
        (t, n)
        for (t, n, e) in r.recorded_events
        if isinstance(e.type, pb.EventTransfer)
        and e.type.c_entry.network_state is not None
    ]
    assert adopted and all(n == 2 for _t, n in adopted)

    chains = {n: r.node_states[n].app_chain for n in range(4)}
    assert len(set(chains.values())) == 1

    # Replay/transfer must not double-apply: every (client, req_no) at
    # most once per node.
    for n in range(4):
        pairs = [(c, q) for c, q, _s in r.node_states[n].committed_reqs]
        assert len(pairs) == len(set(pairs))
