"""Bit-exactness gate for the TPU SHA-256 kernel vs hashlib (SURVEY §7
stage 4 gate), including fuzzed lengths across block boundaries and the
protocol preimage layouts."""

import hashlib
import os
import random

import numpy as np
import pytest

from mirbft_tpu import pb
from mirbft_tpu.core import preimage
from mirbft_tpu.ops import sha256, sha256_many
from mirbft_tpu.ops.batching import next_pow2, pack_preimages, sha256_pad


def test_next_pow2():
    assert next_pow2(1) == 1
    assert next_pow2(3) == 4
    assert next_pow2(4) == 4
    assert next_pow2(5, floor=8) == 8
    assert next_pow2(1000) == 1024


def test_sha256_pad_lengths():
    for n in [0, 1, 54, 55, 56, 63, 64, 65, 119, 120, 128]:
        padded = sha256_pad(b"x" * n)
        assert len(padded) % 64 == 0
        assert padded[n] == 0x80


def test_empty_message():
    assert sha256(b"") == hashlib.sha256(b"").digest()


def test_known_vectors():
    for msg in [b"abc", b"hello world", b"a" * 1000]:
        assert sha256(msg) == hashlib.sha256(msg).digest()


def test_block_boundary_fuzz():
    rng = random.Random(42)
    # Every length near block boundaries plus random lengths (capped so the
    # block-axis bucket stays small: compile time, not correctness).
    lengths = list(range(0, 130)) + [rng.randrange(0, 1024) for _ in range(40)]
    messages = [bytes(rng.getrandbits(8) for _ in range(n)) for n in lengths]
    digests = sha256_many(messages)
    for msg, digest in zip(messages, digests):
        assert digest == hashlib.sha256(msg).digest(), f"len={len(msg)}"


def test_protocol_preimages_match_host_oracle():
    rng = random.Random(7)
    messages = []
    for _ in range(32):
        req = pb.Request(
            client_id=rng.randrange(2**32),
            req_no=rng.randrange(2**32),
            data=bytes(rng.getrandbits(8) for _ in range(rng.randrange(0, 500))),
        )
        messages.append(b"".join(preimage.request_hash_data(req)))
        acks = [
            pb.RequestAck(digest=bytes(rng.getrandbits(8) for _ in range(32)))
            for _ in range(rng.randrange(1, 30))
        ]
        messages.append(b"".join(preimage.batch_hash_data(acks)))
    digests = sha256_many(messages)
    for msg, digest in zip(messages, digests):
        assert digest == preimage.host_digest([msg])


def test_packing_shapes_are_bucketed():
    batch = pack_preimages([b"x"] * 5)
    assert batch.blocks.shape == (8, 1, 16)  # batch 5→8, 1 block
    batch = pack_preimages([b"x" * 200, b"y"])
    # 200 bytes → 208 padded → 4 blocks; bucket stays 4.
    assert batch.blocks.shape == (8, 4, 16)
    assert list(batch.n_blocks[:2]) == [4, 1]
    assert list(batch.n_blocks[2:]) == [0] * 6


# NOTE: there is deliberately no interpret-mode CI test for the Pallas
# kernel: the fully-unrolled 112-step body takes >10 minutes to compile
# under CPU XLA even for a single small batch (measured; the same
# explosion ops/sha256.py avoids with scans).  Coverage comes from the
# env-gated Mosaic test below and the bit-exactness assertion built into
# every bench run.
@pytest.mark.skipif(
    not os.environ.get("MIRBFT_TPU_TPU_TESTS"),
    reason="compiles via Mosaic on the tunneled TPU (no CPU path; see "
    "note above); set MIRBFT_TPU_TPU_TESTS=1 to run",
)
def test_pallas_kernel_bit_exact_on_tpu():
    import jax

    from mirbft_tpu.ops.sha256_pallas import sha256_digest_words_pallas

    try:
        tpu = jax.devices("tpu")[0]
    except RuntimeError:
        pytest.skip("no TPU backend available")
    msgs = [bytes([i % 256]) * (i % 300) for i in range(64)]
    packed = pack_preimages(msgs)
    # conftest pins the default device to CPU; this test explicitly
    # targets the TPU (Mosaic has no CPU lowering).
    with jax.default_device(tpu):
        words = np.asarray(
            sha256_digest_words_pallas(
                packed.blocks, packed.n_blocks, interpret=False
            )
        )
    for i, m in enumerate(msgs):
        assert (
            words[i].astype(">u4").tobytes() == hashlib.sha256(m).digest()
        )
