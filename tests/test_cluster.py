"""Multi-process cluster runner gates.

Tier-1: the WAN profile map, the atomic handshake-file helpers, and the
mp chaos matrix's shape (including the unsupported-fault rejections).
Slow: a real supervisor lifecycle — spawn four worker processes, readiness
handshake, commit under broadcast submission, SIGKILL + restart-from-disk,
teardown — and one full mp chaos scenario.  The slow tests fork real
``python -m mirbft_tpu.cluster`` processes, so they stay out of tier-1.
"""

import os
import time

import pytest

from mirbft_tpu import pb
from mirbft_tpu.cluster import (
    MP_SMOKE_NAMES,
    WAN_PROFILES,
    ClusterSupervisor,
    mp_matrix,
    profile_latency,
    retry_storm_scenario,
)
from mirbft_tpu.cluster.chaos_mp import _reject_unsupported, run_mp_scenario
from mirbft_tpu.cluster.worker import read_json, write_json_atomic
from mirbft_tpu.chaos.scenarios import Scenario, StorageFault


# -- tier-1: profiles, handshake files, matrix shape -------------------------


def test_wan_profiles_lower_to_per_link_latency_maps():
    assert set(WAN_PROFILES) == {"lan", "wan", "geo"}
    assert profile_latency("lan", 4) == {}  # loopback baseline: no emulation
    wan = profile_latency("wan", 4)
    assert set(wan) == {0, 1, 2, 3}
    assert wan[2] == {"delay_ms": 30.0, "jitter_ms": 5.0}
    geo = profile_latency("geo", 3)
    assert geo[0]["delay_ms"] > wan[0]["delay_ms"]
    with pytest.raises(ValueError):
        profile_latency("lunar", 4)


def test_handshake_files_are_atomic_and_torn_reads_are_none(tmp_path):
    path = str(tmp_path / "address.json")
    assert read_json(path) is None  # absent
    write_json_atomic(path, {"pid": 42, "transport_port": 9})
    assert read_json(path) == {"pid": 42, "transport_port": 9}
    assert not os.path.exists(path + ".tmp")  # no droppings
    # A torn/partial file (a non-atomic writer mid-flight) reads as None
    # rather than raising into the poll loop.
    with open(path, "w", encoding="utf-8") as fh:
        fh.write('{"pid": 4')
    assert read_json(path) is None


def test_mp_matrix_is_the_smoke_pair_plus_the_dedup_storm():
    names = [scenario.name for scenario in mp_matrix()]
    assert names[: len(MP_SMOKE_NAMES)] == list(MP_SMOKE_NAMES)
    assert "retry-storm-dedup" in names
    storm = retry_storm_scenario()
    assert storm.node_count == 4
    assert not storm.crashes and not storm.partitions


def test_mp_driver_rejects_faults_it_cannot_lower():
    with pytest.raises(ValueError):
        _reject_unsupported(
            Scenario(
                name="storage",
                description="",
                storage_faults=(
                    StorageFault(at_ms=0, node=0, restart_delay_ms=1000),
                ),
            )
        )
    with pytest.raises(ValueError):
        _reject_unsupported(Scenario(name="signed", description="", signed=True))
    with pytest.raises(ValueError):
        _reject_unsupported(Scenario(name="lossy", description="", drop_pct=10))
    for scenario in mp_matrix():
        _reject_unsupported(scenario)  # the shipped matrix must be clean


# -- slow: real worker processes ---------------------------------------------


def _wait_for(predicate, timeout_s, what):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def test_supervisor_submit_teardown_race_is_a_clean_error(tmp_path):
    """Regression for the submit/teardown TOCTOU (flagged by the C201
    guarded-by checker): submit() runs on load-generator threads while
    teardown() closes and None-s the client transport on the driver
    thread.  The handle must be snapshotted under the supervisor lock, so
    a loser of the race sees RuntimeError (or a harmless propose into a
    closing transport) — never an AttributeError off a None handle."""
    import threading

    sup = ClusterSupervisor(
        node_count=2, client_ids=[1], root=str(tmp_path / "cluster")
    )
    request = pb.Request(client_id=1, req_no=0, data=b"race")
    # Unstarted: the clean error, not AttributeError.
    with pytest.raises(RuntimeError):
        sup.submit(0, request)

    class _StubTransport:
        def propose(self, node_id, req):
            pass

        def close(self, node_id):
            pass

    errors = []
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                sup.submit(0, request)
            except RuntimeError:
                pass
            except BaseException as exc:  # AttributeError == the old bug
                errors.append(exc)
                return

    worker = threading.Thread(target=hammer)
    worker.start()
    try:
        for _ in range(300):
            with sup._lock:
                sup._client_transport = _StubTransport()
            sup.teardown()  # closes + None-s the handle, no nodes to stop
    finally:
        stop.set()
        worker.join(timeout=10.0)
    assert not errors, errors
    with pytest.raises(RuntimeError):
        sup.submit(0, request)


def test_cluster_lock_acquisition_graph_is_acyclic(tmp_path, monkeypatch):
    """Dynamic lock-order harness (docs/ANALYSIS.md): submit threads
    drive the supervisor's client TcpTransport (reconnect backoff
    against a dead peer included) while the driver thread tears down,
    with every threading primitive in the supervisor and transport
    instrumented; the cross-thread lock graph must stay cycle-free."""
    import socket
    import sys
    import threading
    from pathlib import Path

    sys.path.insert(
        0, str(Path(__file__).resolve().parent.parent / "tools")
    )
    from analysis.lockorder import LockMonitor, _InstrumentedLock

    from mirbft_tpu.cluster import supervisor as supervisor_mod
    from mirbft_tpu.runtime import transport as transport_mod

    monitor = LockMonitor()
    proxy = monitor.threading_proxy()
    monkeypatch.setattr(supervisor_mod, "threading", proxy)
    monkeypatch.setattr(transport_mod, "threading", proxy)

    sup = ClusterSupervisor(
        node_count=2, client_ids=[1], root=str(tmp_path / "cluster")
    )
    assert isinstance(sup._lock, _InstrumentedLock)
    client = transport_mod.TcpTransport(
        supervisor_mod._CLIENT_NODE_ID,
        port=0,
        backoff_base=0.01,
        backoff_cap=0.05,
        dial_timeout=0.2,
    )
    assert isinstance(client._lock, _InstrumentedLock)
    # A bound-but-not-listening port refuses connections deterministically,
    # so sends exercise the channel cv's reconnect-backoff waits.
    dead = socket.socket()
    dead.bind(("127.0.0.1", 0))
    try:
        client.connect(0, dead.getsockname())
        with sup._lock:
            sup._client_transport = client
        request = pb.Request(client_id=1, req_no=0, data=b"lockorder")

        def hammer():
            for _ in range(50):
                try:
                    sup.submit(0, request)
                except RuntimeError:
                    return
                time.sleep(0.001)

        threads = [threading.Thread(target=hammer) for _ in range(2)]
        for t in threads:
            t.start()
        time.sleep(0.05)
        sup.teardown()
        for t in threads:
            t.join(timeout=10.0)
    finally:
        dead.close()
        client.close(0)
    monitor.assert_no_cycles()


@pytest.mark.slow
def test_supervisor_boot_commit_kill_restart_teardown(tmp_path):
    sup = ClusterSupervisor(
        node_count=4, client_ids=[1], root=str(tmp_path / "cluster")
    )
    try:
        sup.start(timeout_s=120.0)
        # Readiness handshake: every node's /healthz reports ready.
        for node_id in sup.node_ids:
            health = sup.healthz(node_id)
            assert health and health.get("ready") is True, health

        # A broadcast submission commits on every node.
        request = pb.Request(client_id=1, req_no=0, data=b"mp-smoke")
        for node_id in sup.node_ids:
            sup.submit(node_id, request)

        def all_committed():
            return all(
                (1, 0) in {(c, q) for (c, q, _s) in sup.committed(n)}
                for n in sup.node_ids
            )

        _wait_for(all_committed, 60.0, "commit on all four nodes")

        # SIGKILL one node: process dies, the rest stay up.
        sup.kill(3, graceful=False)
        _wait_for(lambda: 3 not in sup.alive_nodes(), 10.0, "node 3 death")
        assert sup.healthz(3) is None
        assert sorted(sup.alive_nodes()) == [0, 1, 2]

        # The victim couldn't say why it died, but its black box can:
        # autoflush left a committed flight segment, and the reap
        # annotated it with the real cause.
        import json

        dumps = sup.flight_dumps()
        assert 3 in dumps, dumps
        victim = json.loads(open(dumps[3]).read())
        assert victim["reason"] == "sigkill-reaped"
        assert victim["entries"]

        # Restart from disk: the worker reboots via Node.restart, re-binds
        # its original transport port, and reports ready again.
        sup.restart(3)
        _wait_for(lambda: 3 in sup.alive_nodes(), 10.0, "node 3 restart")
        health = sup.healthz(3)
        assert health and health.get("ready") is True

        # The restarted node still converges: a fresh request commits
        # everywhere, including on node 3's recovered log.
        request2 = pb.Request(client_id=1, req_no=1, data=b"post-restart")
        for node_id in sup.node_ids:
            sup.submit(node_id, request2)

        def node3_caught_up():
            return (1, 1) in {(c, q) for (c, q, _s) in sup.committed(3)}

        _wait_for(node3_caught_up, 60.0, "post-restart commit on node 3")
    finally:
        sup.teardown()
    assert sup.alive_nodes() == []

    # Acceptance: the dumps on disk reconstruct a merged cross-node
    # timeline after every process is gone.
    from mirbft_tpu.obsv.recorder import postmortem

    result = postmortem(str(tmp_path / "cluster"))
    assert set(result["nodes"]) == {0, 1, 2, 3}
    assert result["timeline"].splitlines()


@pytest.mark.slow
@pytest.mark.chaos
def test_mp_chaos_crash_restart_scenario():
    crash = next(s for s in mp_matrix() if s.name == "crash-restart")
    result = run_mp_scenario(crash, seed=0, budget_s=240.0)
    assert result.passed, result.violation
