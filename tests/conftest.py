"""Test configuration.

Tests run on a virtual 8-device CPU mesh so that every sharding path is
exercised without TPU hardware (the driver separately dry-run-compiles the
multi-chip path via __graft_entry__.dryrun_multichip).  The env vars must be
set before jax is imported anywhere in the test process.
"""

import os
import sys

# Force (not default): the ambient environment may export JAX_PLATFORMS=axon
# (the tunneled TPU), and running the suite's many tiny kernel dispatches
# through the tunnel is both slow and non-hermetic.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Persistent XLA compilation cache: the SHA-256 kernel shapes are stable
# across test runs, so paying the compile cost once keeps the suite fast.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/mirbft_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running scale tests")
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection campaign tests (the smoke subset runs in "
        "tier-1; the full matrix is also marked slow)",
    )
    # The axon TPU plugin IGNORES JAX_PLATFORMS=cpu (the default backend
    # stays "tpu" and default-placed arrays go through the tunnel, whose
    # latency weather makes kernel-path stress tests flaky).  Pin the
    # default device to a real host CPU device explicitly.
    import jax

    jax.config.update("jax_default_device", jax.devices("cpu")[0])
