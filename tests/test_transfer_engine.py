"""The snapshot state-transfer subsystem (runtime/transfer.py), tested
deterministically — fake clock, in-memory queued "ducts", no sockets:
blob/frame codecs, the digest chain, donor serve/NACK, the fetch state
machine (timeout, retry, donor failover), certificate verification as
the adoption authority, and crash-resume from the staged blob.  The
slow section drives the same subsystem under fire: a fresh process
joining a loaded multi-process cluster through a partition, and a live
adversary corrupting the transfer stream on real TCP sockets."""

import types

import pytest

from mirbft_tpu import pb
from mirbft_tpu.chaos.invariants import (
    InvariantViolation,
    check_bounded_catchup,
    check_transfer_corruption_rejected,
)
from mirbft_tpu.core.actions import StateTarget
from mirbft_tpu.core.checkpoints import CheckpointTracker
from mirbft_tpu.core.msgbuffers import NodeBuffers
from mirbft_tpu.core.persisted import Persisted
from mirbft_tpu.runtime.config import Config
from mirbft_tpu.runtime.msgfilter import MalformedMessage, check_snapshot_chunk
from mirbft_tpu.runtime.storage import read_snapshot_file, write_snapshot_file
from mirbft_tpu.runtime.transfer import (
    Snapshot,
    TransferEngine,
    chain_next,
    chain_seed,
    decode_frame,
    decode_snapshot,
    encode_chunk,
    encode_request,
    encode_snapshot,
    split_chunks,
)


# -- harness -----------------------------------------------------------------


class _Clock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += dt


class _Mesh:
    """Queued loopback ducts: sends enqueue, ``deliver`` flushes — the
    queue models the transport's cross-thread hop, so an engine never
    re-enters its own lock the way a synchronous callback would."""

    def __init__(self):
        self.engines = {}
        self.pending = []  # [(src, dest, body)]
        self.log = []  # every send ever, for traffic assertions
        self.cut = set()  # (src, dest) pairs to drop

    def duct(self, src):
        mesh = self

        class _Duct:
            def send(self, dest, body):
                mesh.log.append((src, dest, body))
                mesh.pending.append((src, dest, body))

        return _Duct()

    def add(self, engine):
        self.engines[engine.node_id] = engine

    def deliver(self, mangle=None):
        while self.pending:
            src, dest, body = self.pending.pop(0)
            if (src, dest) in self.cut:
                continue
            engine = self.engines.get(dest)
            if engine is None:
                continue
            engine.on_frame(src, mangle(body) if mangle else body)


def _network_state():
    return pb.NetworkState(
        config=pb.NetworkConfig(
            nodes=[0, 1, 2, 3],
            f=1,
            number_of_buckets=4,
            checkpoint_interval=5,
            max_epoch_length=50,
        )
    )


def _snapshot(seq_no=10, value=b"cp10", app=b"app-state"):
    requests = [
        (pb.RequestAck(client_id=1, req_no=3, digest=b"d" * 8), b"payload"),
        (pb.RequestAck(client_id=2, req_no=0, digest=b"e" * 8), b""),
    ]
    return Snapshot(seq_no, value, _network_state(), app, requests)


def _engine(mesh, clock, tmp_path, node_id, peers=(), **kw):
    staging = tmp_path / f"n{node_id}"
    staging.mkdir(exist_ok=True)
    sink = types.SimpleNamespace(completed=[], failed=[])
    engine = TransferEngine(
        node_id,
        mesh.duct(node_id),
        staging_dir=str(staging),
        peers=peers,
        complete=lambda target, ns: sink.completed.append((target, ns)),
        failed=lambda target: sink.failed.append(target),
        chunk_timeout_s=1.0,
        clock=clock,
        **kw,
    )
    mesh.add(engine)
    return engine, sink


def _pump(mesh, fetcher, clock, rounds=40, dt=1.1):
    """Advance time past any timeout/backoff and poll until the fetch
    leaves the state machine (installed or failed)."""
    for _ in range(rounds):
        fetcher.poll()
        mesh.deliver()
        fetcher.poll()
        if not fetcher.transferring():
            return
        clock.advance(dt)
    raise AssertionError(f"fetch never settled: {fetcher.status()}")


# -- codecs ------------------------------------------------------------------


def test_snapshot_blob_round_trips():
    snap = _snapshot()
    blob = encode_snapshot(snap)
    out = decode_snapshot(blob)
    assert out.seq_no == snap.seq_no
    assert out.value == snap.value
    assert out.app_bytes == snap.app_bytes
    assert out.network_state.config.nodes == [0, 1, 2, 3]
    assert [(a.client_id, a.req_no, a.digest) for a, _d in out.requests] == [
        (1, 3, b"d" * 8),
        (2, 0, b"e" * 8),
    ]
    assert [d for _a, d in out.requests] == [b"payload", b""]


def test_snapshot_blob_rejects_malformation():
    blob = encode_snapshot(_snapshot())
    with pytest.raises(ValueError):
        decode_snapshot(blob + b"\x00")  # trailing bytes
    with pytest.raises(ValueError):
        decode_snapshot(blob[:-1])  # truncation
    with pytest.raises(ValueError):
        decode_snapshot(b"")


def test_transfer_frames_round_trip():
    req = encode_request(40, b"cert-value", 3)
    assert decode_frame(req) == ("request", 40, b"cert-value", 3)
    digest = chain_seed(40, b"cert-value")
    chunk = encode_chunk(40, 2, 7, digest, b"chunk-payload")
    assert decode_frame(chunk) == (
        "chunk",
        40,
        2,
        7,
        digest,
        b"chunk-payload",
    )
    with pytest.raises(ValueError):
        decode_frame(b"\x7f")  # unknown kind
    with pytest.raises(ValueError):
        decode_frame(chunk[:10])  # truncated mid-frame


def test_chunk_split_and_digest_chain():
    blob = bytes(range(256)) * 10
    payloads = split_chunks(blob, 1000)
    assert b"".join(payloads) == blob
    assert max(len(p) for p in payloads) <= 1000
    assert split_chunks(b"", 64) == [b""]  # empty blob still round-trips
    with pytest.raises(ValueError):
        split_chunks(blob, 0)
    # The chain is anchored to the certified target: any other
    # (seq_no, value) produces a different seed, so chunk 0 already
    # fails verification when served for the wrong certificate.
    assert chain_seed(10, b"a") != chain_seed(11, b"a")
    assert chain_seed(10, b"a") != chain_seed(10, b"b")
    d = chain_seed(10, b"a")
    assert chain_next(d, b"x") != chain_next(d, b"y")


# -- donor side --------------------------------------------------------------


def test_donor_serves_matching_request_and_nacks_unknown(tmp_path):
    mesh, clock = _Mesh(), _Clock()
    donor, _ = _engine(mesh, clock, tmp_path, 1)
    snap = _snapshot()
    donor.note_checkpoint(
        snap.seq_no, snap.value, snap.network_state, snap.app_bytes,
        snap.requests,
    )

    donor.on_frame(0, encode_request(snap.seq_no, snap.value, 0))
    frames = [decode_frame(b) for _s, _d, b in mesh.log]
    chunks = [f for f in frames if f[0] == "chunk"]
    assert chunks and b"".join(f[5] for f in chunks) == encode_snapshot(snap)
    assert donor.counters["snapshots_served"] == 1

    # Unknown seq_no and certificate-value mismatch both NACK so the
    # fetcher fails over immediately instead of burning a timeout.
    mesh.log.clear()
    donor.on_frame(0, encode_request(999, snap.value, 0))
    donor.on_frame(0, encode_request(snap.seq_no, b"other-cert", 0))
    assert [decode_frame(b)[0] for _s, _d, b in mesh.log] == ["nack", "nack"]
    assert donor.counters["snapshots_nacked"] == 2


def test_donor_retains_only_newest_snapshots(tmp_path):
    mesh, clock = _Mesh(), _Clock()
    donor, _ = _engine(mesh, clock, tmp_path, 1)
    for seq in (10, 20, 30, 40, 50, 60):
        snap = _snapshot(seq_no=seq, value=b"cp%d" % seq)
        donor.note_checkpoint(
            seq, snap.value, snap.network_state, snap.app_bytes, snap.requests
        )
    assert donor.status()["cached_snapshots"] == [30, 40, 50, 60]


# -- fetcher: the happy path -------------------------------------------------


def test_fetch_installs_verified_snapshot(tmp_path):
    mesh, clock = _Mesh(), _Clock()
    donor, _ = _engine(mesh, clock, tmp_path, 1)
    fetcher, sink = _engine(mesh, clock, tmp_path, 0, peers=(1,))
    snap = _snapshot()
    donor.note_checkpoint(
        snap.seq_no, snap.value, snap.network_state, snap.app_bytes,
        snap.requests,
    )

    fetcher.begin(StateTarget(seq_no=snap.seq_no, value=snap.value))
    _pump(mesh, fetcher, clock)

    assert fetcher.counters["snapshots_installed"] == 1
    assert fetcher.counters["chunks_rejected_corrupt"] == 0
    (target, network_state), = sink.completed
    assert (target.seq_no, target.value) == (snap.seq_no, snap.value)
    assert network_state.config.nodes == [0, 1, 2, 3]
    assert not sink.failed
    # The staged blob is consumed on install — a later restart must not
    # resurrect an already-adopted snapshot.
    assert read_snapshot_file(fetcher.staging_path) is None


def test_begin_is_idempotent_for_inflight_target(tmp_path):
    mesh, clock = _Mesh(), _Clock()
    fetcher, _ = _engine(mesh, clock, tmp_path, 0, peers=(1, 2))
    target = StateTarget(seq_no=10, value=b"cp10")
    fetcher.begin(target)
    fetcher.poll()  # sends the first request
    sent = len(mesh.log)
    fetcher.begin(StateTarget(seq_no=10, value=b"cp10"))
    fetcher.poll()
    assert len(mesh.log) == sent  # no duplicate stream started


# -- fetcher: corruption, certificates, bounds -------------------------------


def test_corrupted_chunk_rejected_with_evidence(tmp_path):
    """Every mangled frame breaks the digest chain and is refused —
    nothing corrupt is ever staged or installed."""
    mesh, clock = _Mesh(), _Clock()
    donor, _ = _engine(mesh, clock, tmp_path, 1)
    fetcher, sink = _engine(
        mesh, clock, tmp_path, 0, peers=(1,), donor_rounds=1
    )
    snap = _snapshot()
    donor.note_checkpoint(
        snap.seq_no, snap.value, snap.network_state, snap.app_bytes,
        snap.requests,
    )

    def flip_payload_tail(body):
        if decode_frame(body)[0] != "chunk":
            return body
        return body[:-1] + bytes([body[-1] ^ 0xFF])

    fetcher.begin(StateTarget(seq_no=snap.seq_no, value=snap.value))
    fetcher.poll()
    mesh.deliver(mangle=flip_payload_tail)
    for _ in range(10):
        fetcher.poll()
        clock.advance(1.1)

    assert fetcher.counters["chunks_rejected_corrupt"] >= 1
    assert fetcher.counters["snapshots_installed"] == 0
    assert read_snapshot_file(fetcher.staging_path) is None
    assert sink.failed and not sink.completed


def test_chain_valid_but_wrong_blob_rejected_at_certificate(tmp_path):
    """A byzantine donor can chain arbitrary bytes to the right anchor;
    the decoded blob must still carry the certified (seq_no, value) —
    the 2f+1 certificate, not the chain, is the adoption authority."""
    mesh, clock = _Mesh(), _Clock()
    fetcher, sink = _engine(
        mesh, clock, tmp_path, 0, peers=(1,), donor_rounds=1
    )
    target = StateTarget(seq_no=10, value=b"cp10")
    fetcher.begin(target)
    fetcher.poll()  # now fetching from donor 1

    wrong = encode_snapshot(_snapshot(seq_no=11, value=b"cp11"))
    digest = chain_seed(target.seq_no, target.value)
    payloads = split_chunks(wrong, 64)
    for index, payload in enumerate(payloads):
        digest = chain_next(digest, payload)
        fetcher.on_frame(
            1, encode_chunk(target.seq_no, index, len(payloads), digest, payload)
        )
    assert fetcher.counters["chunks_received"] == len(payloads)

    for _ in range(10):
        fetcher.poll()
        clock.advance(1.1)
    assert fetcher.counters["chunks_rejected_corrupt"] >= 1
    assert fetcher.counters["snapshots_installed"] == 0
    assert sink.failed and not sink.completed


def test_oversized_chunk_rejected_at_ingress(tmp_path):
    mesh, clock = _Mesh(), _Clock()
    limits = types.SimpleNamespace(
        max_snapshot_chunk_bytes=8, max_snapshot_bytes=64
    )
    fetcher, _ = _engine(
        mesh, clock, tmp_path, 0, peers=(1,), donor_rounds=1, limits=limits
    )
    target = StateTarget(seq_no=10, value=b"cp10")
    fetcher.begin(target)
    fetcher.poll()
    digest = chain_next(chain_seed(10, b"cp10"), b"x" * 100)
    fetcher.on_frame(1, encode_chunk(10, 0, 1, digest, b"x" * 100))
    assert fetcher.counters["chunks_rejected_oversized"] == 1
    assert fetcher.counters["chunks_received"] == 0


def test_stale_and_unsolicited_chunks_dropped(tmp_path):
    mesh, clock = _Mesh(), _Clock()
    fetcher, _ = _engine(mesh, clock, tmp_path, 0, peers=(1, 2))
    digest = chain_next(chain_seed(10, b"cp10"), b"p")
    # No fetch in flight at all: unsolicited chunk.
    fetcher.on_frame(1, encode_chunk(10, 0, 1, digest, b"p"))
    assert fetcher.counters["chunks_stale"] == 1
    # In flight, but from a node that is not the current donor.
    fetcher.begin(StateTarget(seq_no=10, value=b"cp10"))
    fetcher.poll()
    donor = fetcher.status()["donor"]
    other = 2 if donor == 1 else 1
    fetcher.on_frame(other, encode_chunk(10, 0, 1, digest, b"p"))
    assert fetcher.counters["chunks_stale"] == 2
    assert fetcher.counters["chunks_received"] == 0


# -- fetcher: timeout, retry, failover, failure ------------------------------


def test_donor_failover_after_timeouts(tmp_path):
    """The first donor is unreachable: per-chunk timeouts burn its
    attempts, the fetch fails over, and the second donor completes it."""
    mesh, clock = _Mesh(), _Clock()
    donor1, _ = _engine(mesh, clock, tmp_path, 1)
    donor2, _ = _engine(mesh, clock, tmp_path, 2)
    fetcher, sink = _engine(mesh, clock, tmp_path, 0, peers=(1, 2))
    snap = _snapshot()
    for donor in (donor1, donor2):
        donor.note_checkpoint(
            snap.seq_no, snap.value, snap.network_state, snap.app_bytes,
            snap.requests,
        )

    fetcher.begin(StateTarget(seq_no=snap.seq_no, value=snap.value))
    fetcher.poll()
    first = fetcher.status()["donor"]
    mesh.cut.add((0, first))  # requests to the first donor vanish

    _pump(mesh, fetcher, clock)
    assert fetcher.counters["snapshots_installed"] == 1
    assert fetcher.counters["request_timeouts"] >= 2
    assert fetcher.counters["retries"] >= 1  # same-donor retry first
    assert fetcher.counters["donor_failovers"] >= 1
    assert sink.completed and not sink.failed


def test_nack_fails_over_without_waiting_for_timeout(tmp_path):
    """Only the donor the shuffle did NOT pick first holds the snapshot:
    the first donor NACKs, and the rotation happens on the NACK itself —
    the clock never advances, so no timeout can be responsible."""
    mesh, clock = _Mesh(), _Clock()
    donor1, _ = _engine(mesh, clock, tmp_path, 1)
    donor2, _ = _engine(mesh, clock, tmp_path, 2)
    fetcher, sink = _engine(mesh, clock, tmp_path, 0, peers=(1, 2))
    snap = _snapshot()
    fetcher.begin(StateTarget(seq_no=snap.seq_no, value=snap.value))
    fetcher.poll()
    first = fetcher.status()["donor"]
    nacker = donor1 if first == 1 else donor2
    holder = donor2 if first == 1 else donor1
    holder.note_checkpoint(
        snap.seq_no, snap.value, snap.network_state, snap.app_bytes,
        snap.requests,
    )
    # One flush settles the whole exchange: request -> NACK -> rotated
    # request -> chunks; then one poll installs.
    mesh.deliver()
    fetcher.poll()
    assert fetcher.counters["snapshots_installed"] == 1
    assert fetcher.counters["request_timeouts"] == 0
    assert fetcher.counters["donor_failovers"] == 1
    assert nacker.counters["snapshots_nacked"] == 1
    assert sink.completed and not sink.failed


def test_all_donors_exhausted_reports_failure_and_recovers(tmp_path):
    mesh, clock = _Mesh(), _Clock()
    fetcher, sink = _engine(
        mesh, clock, tmp_path, 0, peers=(1, 2), donor_rounds=2
    )
    target = StateTarget(seq_no=10, value=b"cp10")
    fetcher.begin(target)
    _pump(mesh, fetcher, clock)  # nobody answers: every round times out
    assert fetcher.counters["snapshots_failed"] == 1
    assert sink.failed == [target] and not sink.completed
    assert fetcher.status()["phase"] == "idle"

    # failed() is a retry contract, not a dead end: the core re-emits
    # state_transfer and begin() must start a fresh fetch.
    donor, _ = _engine(mesh, clock, tmp_path, 1)
    snap = _snapshot()
    donor.note_checkpoint(
        snap.seq_no, snap.value, snap.network_state, snap.app_bytes,
        snap.requests,
    )
    fetcher.begin(target)
    _pump(mesh, fetcher, clock)
    assert fetcher.counters["snapshots_installed"] == 1


# -- crash-resume from the staged blob ---------------------------------------


def test_restart_resumes_from_staged_blob_without_network(tmp_path):
    """Crash between staging and install: the restarted engine finds the
    staged blob for the re-emitted target and completes with zero
    network traffic."""
    mesh, clock = _Mesh(), _Clock()
    snap = _snapshot()
    blob = encode_snapshot(snap)
    staging = tmp_path / "n0"
    staging.mkdir()
    write_snapshot_file(str(staging / "snapshot.staged"), blob)

    fetcher, sink = _engine(mesh, clock, tmp_path, 0, peers=(1, 2))
    fetcher.begin(StateTarget(seq_no=snap.seq_no, value=snap.value))
    fetcher.poll()
    assert fetcher.counters["snapshots_resumed_staged"] == 1
    assert fetcher.counters["snapshots_installed"] == 1
    assert sink.completed and not sink.failed
    assert mesh.log == []  # completed locally: no request ever sent
    assert read_snapshot_file(fetcher.staging_path) is None


def test_stale_staged_blob_discarded_and_fetched_fresh(tmp_path):
    """A staged blob for a different target (an older, superseded fetch)
    must not be adopted: it is discarded and the network fetch begins."""
    mesh, clock = _Mesh(), _Clock()
    stale = encode_snapshot(_snapshot(seq_no=5, value=b"cp5"))
    staging = tmp_path / "n0"
    staging.mkdir()
    write_snapshot_file(str(staging / "snapshot.staged"), stale)

    donor, _ = _engine(mesh, clock, tmp_path, 1)
    snap = _snapshot()
    donor.note_checkpoint(
        snap.seq_no, snap.value, snap.network_state, snap.app_bytes,
        snap.requests,
    )
    fetcher, sink = _engine(mesh, clock, tmp_path, 0, peers=(1,))
    fetcher.begin(StateTarget(seq_no=snap.seq_no, value=snap.value))
    _pump(mesh, fetcher, clock)
    assert fetcher.counters["snapshots_resumed_staged"] == 0
    assert fetcher.counters["snapshots_installed"] == 1
    (target, _ns), = sink.completed
    assert target.seq_no == snap.seq_no  # the new target, not the stale one


# -- ingress bounds and config validation ------------------------------------


def test_check_snapshot_chunk_bounds():
    limits = types.SimpleNamespace(
        max_snapshot_chunk_bytes=1024, max_snapshot_bytes=16 * 1024
    )
    check_snapshot_chunk(1024, 16, limits)  # exactly at both caps
    with pytest.raises(MalformedMessage) as err:
        check_snapshot_chunk(1025, 1, limits)
    assert err.value.kind == "oversized_snapshot_chunk"
    with pytest.raises(MalformedMessage):
        check_snapshot_chunk(0, 0, limits)  # zero chunks is malformed
    with pytest.raises(MalformedMessage):
        check_snapshot_chunk(10, 17, limits)  # reassembly could exceed cap


def test_config_validates_snapshot_bounds():
    Config(id=0)  # defaults are self-consistent
    with pytest.raises(ValueError):
        Config(id=0, max_snapshot_chunk_bytes=0)
    with pytest.raises(ValueError):
        Config(id=0, max_snapshot_bytes=1, max_snapshot_chunk_bytes=2)


# -- the certified-above-window trigger and the lag gauge --------------------


def _tracker():
    persisted = Persisted()
    persisted.add_c_entry(
        pb.CEntry(
            seq_no=0,
            checkpoint_value=b"genesis",
            network_state=_network_state(),
        )
    )
    my = pb.InitialParameters(id=0, buffer_size=1 << 20)
    tracker = CheckpointTracker(persisted, NodeBuffers(my), my)
    tracker.reinitialize()
    return tracker


def test_certified_above_window_needs_intersection_quorum():
    t = _tracker()
    high = t.high_watermark()
    seq = high + 25
    t.step(1, pb.Msg(type=pb.Checkpoint(seq_no=seq, value=b"cert")))
    t.step(2, pb.Msg(type=pb.Checkpoint(seq_no=seq, value=b"cert")))
    # 2 < 2f+1 = 3: not yet a transfer trigger, lag gauge stays flat.
    assert t.certified_above_window() is None
    assert t.lag_seqnos() == 0
    # A duplicate vote from the same node must not fake a quorum.
    t.step(2, pb.Msg(type=pb.Checkpoint(seq_no=seq, value=b"cert")))
    assert t.certified_above_window() is None
    t.step(3, pb.Msg(type=pb.Checkpoint(seq_no=seq, value=b"cert")))
    assert t.certified_above_window() == (seq, b"cert")
    assert t.lag_seqnos() == seq - high


def test_split_votes_never_certify():
    t = _tracker()
    seq = t.high_watermark() + 25
    t.step(1, pb.Msg(type=pb.Checkpoint(seq_no=seq, value=b"a")))
    t.step(2, pb.Msg(type=pb.Checkpoint(seq_no=seq, value=b"b")))
    t.step(3, pb.Msg(type=pb.Checkpoint(seq_no=seq, value=b"c")))
    assert t.certified_above_window() is None
    assert t.lag_seqnos() == 0


# -- the new chaos invariants ------------------------------------------------


def test_bounded_catchup_invariant():
    check_bounded_catchup(1000, 5000, 10_000)
    with pytest.raises(InvariantViolation):
        check_bounded_catchup(1000, None, 10_000)  # never caught up
    with pytest.raises(InvariantViolation):
        check_bounded_catchup(1000, 12_001, 10_000)  # blew the bound


def test_transfer_corruption_invariant():
    check_transfer_corruption_rejected(rejections=3, corrupted=5)
    with pytest.raises(InvariantViolation):
        check_transfer_corruption_rejected(rejections=0, corrupted=5)
    with pytest.raises(InvariantViolation):
        # Zero frames touched means the scenario proved nothing.
        check_transfer_corruption_rejected(rejections=0, corrupted=0)


# -- reconfiguration under fire (slow: real processes / real sockets) --------


@pytest.mark.slow
@pytest.mark.chaos
def test_mp_join_under_partition():
    """A fresh node process joins a loaded 5-process cluster mid-run,
    state-transfers through a partition that splits it from part of the
    quorum, and reaches the commit frontier within the bound — with
    snapshot-install evidence, so the join cannot pass vacuously."""
    from mirbft_tpu.cluster.chaos_mp import (
        join_under_partition_scenario,
        run_mp_scenario,
    )

    result = run_mp_scenario(
        join_under_partition_scenario(), seed=0, budget_s=300.0
    )
    assert result.passed, result.violation
    assert result.counters["snapshots_installed"] >= 1
    assert result.counters["catchup_ms"] >= 0


@pytest.mark.slow
@pytest.mark.chaos
def test_mp_remove_under_partition():
    """Removing a node while a partition isolates it must not cost the
    survivors liveness or durable-prefix agreement."""
    from mirbft_tpu.cluster.chaos_mp import (
        remove_under_partition_scenario,
        run_mp_scenario,
    )

    result = run_mp_scenario(
        remove_under_partition_scenario(), seed=0, budget_s=300.0
    )
    assert result.passed, result.violation
    assert result.counters["removed"] == 1


@pytest.mark.slow
@pytest.mark.chaos
def test_live_transfer_corrupt_stream_rejected_on_real_sockets():
    """An adversary proxy corrupts/truncates SnapshotChunk frames on the
    wire while a rebooted replica state-transfers: every touched stream
    is refused with evidence and the transfer still completes via clean
    donors — zero forks."""
    from mirbft_tpu.chaos.live import run_live_scenario
    from mirbft_tpu.chaos.scenarios import transfer_corrupt_scenario

    result = run_live_scenario(
        transfer_corrupt_scenario(), seed=0, budget_s=90.0
    )
    assert result.passed, result.violation
    assert result.counters["transfer_corrupted"] > 0
    assert result.counters["transfer_rejected"] >= 1
    assert result.commits > 0
