"""Event-log recorder/reader/player gate (VERDICT r2 item 3; reference:
eventlog/interceptor.go, testengine/player.go, eventlog_test.go's
non-determinism finder): round-trip, redaction, replay-to-identical-status,
first-divergence diff, and the async runtime recorder."""

from mirbft_tpu import pb
from mirbft_tpu.eventlog import (
    EngineLog,
    Player,
    RecordedEvent,
    Recorder,
    first_divergence,
    read_log,
    redact_event,
    write_log,
)
from mirbft_tpu.status import state_machine_status
from mirbft_tpu.testengine import BasicRecorder


def _sample_events():
    return [
        (
            0,
            10,
            pb.StateEvent(
                type=pb.EventPropose(
                    request=pb.Request(client_id=4, req_no=1, data=b"payload")
                )
            ),
        ),
        (
            1,
            20,
            pb.StateEvent(
                type=pb.EventStep(
                    source=0,
                    msg=pb.Msg(
                        type=pb.RequestAck(
                            client_id=4, req_no=1, digest=b"\xaa" * 32
                        )
                    ),
                )
            ),
        ),
        (0, 30, pb.StateEvent(type=pb.EventTick())),
    ]


def test_round_trip(tmp_path):
    path = str(tmp_path / "log.gz")
    write_log(path, _sample_events(), redact=False)
    events = read_log(path)
    assert [e.node_id for e in events] == [0, 1, 0]
    assert [e.time_ms for e in events] == [10, 20, 30]
    assert events[0].state_event.type.request.data == b"payload"
    assert isinstance(events[2].state_event.type, pb.EventTick)


def test_redaction(tmp_path):
    path = str(tmp_path / "log.gz")
    write_log(path, _sample_events())  # redact=True default
    events = read_log(path)
    # Payload dropped, identity and digests kept.
    req = events[0].state_event.type.request
    assert req.data == b"" and req.client_id == 4 and req.req_no == 1
    assert events[1].state_event.type.msg.type.digest == b"\xaa" * 32

    fwd = pb.StateEvent(
        type=pb.EventStep(
            source=2,
            msg=pb.Msg(
                type=pb.ForwardRequest(
                    request_ack=pb.RequestAck(
                        client_id=4, req_no=1, digest=b"\xbb" * 32
                    ),
                    request_data=b"secret",
                )
            ),
        )
    )
    red = redact_event(fwd)
    assert red.type.msg.type.request_data == b""
    assert red.type.msg.type.request_ack.digest == b"\xbb" * 32
    # Original untouched (copy semantics).
    assert fwd.type.msg.type.request_data == b"secret"


def test_replay_matches_live_run(tmp_path):
    """The foundation property (SURVEY §4): a recorded run replayed against
    fresh StateMachines reaches the identical status at every node."""
    path = str(tmp_path / "run.gz")
    log = EngineLog(path)
    r = BasicRecorder(
        node_count=4, client_count=2, reqs_per_client=5, interceptor=log.interceptor
    )
    r.drain_clients(max_steps=100000)
    log.close()

    events = read_log(path)
    assert len(events) == r.event_count

    player = Player(events)
    player.play()
    for node_id, live_machine in r.machines.items():
        replayed = player.nodes[node_id].machine
        assert state_machine_status(replayed) == state_machine_status(
            live_machine
        ), f"replayed status diverged at node {node_id}"


def test_replay_to_index_is_prefix_consistent(tmp_path):
    path = str(tmp_path / "run.gz")
    log = EngineLog(path)
    r = BasicRecorder(
        node_count=1, client_count=1, reqs_per_client=3, interceptor=log.interceptor
    )
    r.drain_clients(max_steps=20000)
    log.close()
    events = read_log(path)

    player = Player(events)
    player.play(upto=len(events) // 2)
    assert player.position == len(events) // 2
    player.play()
    assert player.position == len(events)
    assert state_machine_status(player.nodes[0].machine) == state_machine_status(
        r.machines[0]
    )


def test_replay_of_crash_restart_run(tmp_path):
    """A recorded run containing a crash + reboot replays cleanly: the
    second EventInitialize on a node means 'fresh StateMachine', exactly as
    the live engine restart did."""
    from mirbft_tpu.testengine.manglers import (
        after_events,
        is_step,
        once,
        rule,
        to_node,
    )

    log = EngineLog()
    r = BasicRecorder(
        node_count=4,
        client_count=2,
        reqs_per_client=8,
        interceptor=log.interceptor,
        manglers=[
            rule(to_node(1), is_step(), after_events(30), once())
            .crash_and_restart_after(5000)
        ],
    )
    r.drain_clients(max_steps=600000)

    player = Player(log.events)
    player.play()
    for node_id, live in r.machines.items():
        assert state_machine_status(
            player.nodes[node_id].machine
        ) == state_machine_status(live)


def test_torn_log_yields_intact_prefix(tmp_path):
    """A log whose writer died mid-stream (no gzip trailer / torn record)
    must still yield its intact prefix — the reader exists for exactly the
    runs that ended badly."""
    import pytest

    path = str(tmp_path / "log.gz")
    write_log(path, _sample_events(), redact=False)
    raw = open(path, "rb").read()

    torn = str(tmp_path / "torn.gz")
    with open(torn, "wb") as f:
        f.write(raw[:-5])  # chop the gzip trailer + part of the last record
    events = read_log(torn)
    assert 1 <= len(events) <= 3
    assert events[0].node_id == 0

    with pytest.raises((EOFError, OSError, ValueError)):
        read_log(torn, strict=True)


def test_first_divergence():
    log_a = EngineLog()
    r1 = BasicRecorder(
        node_count=1, client_count=1, reqs_per_client=3, interceptor=log_a.interceptor
    )
    r1.drain_clients(max_steps=20000)

    log_b = EngineLog()
    r2 = BasicRecorder(
        node_count=1, client_count=1, reqs_per_client=3, interceptor=log_b.interceptor
    )
    r2.drain_clients(max_steps=20000)

    # Same seed -> byte-identical logs.
    assert first_divergence(log_a.events, log_b.events) is None

    # A mutated copy diverges at exactly the mutation point.
    mutated = list(log_b.events)
    mutated[5] = RecordedEvent(
        node_id=mutated[5].node_id,
        time_ms=mutated[5].time_ms + 1,
        state_event=mutated[5].state_event,
    )
    div = first_divergence(log_a.events, mutated)
    assert div is not None and div[0] == 5

    # A truncated copy diverges at the missing tail.
    div = first_divergence(log_a.events, log_a.events[:-2])
    assert div is not None and div[0] == len(log_a.events) - 2
    assert div[2] is None


def test_async_recorder_runtime(tmp_path):
    """The runtime interceptor: buffered, off-thread, and the resulting log
    replays to the node's final state."""
    from mirbft_tpu.runtime.node import standard_initial_network_state
    from tests.test_runtime import (
        Replica,
        ThreadTransport,
        await_commits,
        make_requests,
    )

    recorder = Recorder(str(tmp_path / "node0.gz"))
    transport = ThreadTransport()
    state = standard_initial_network_state(1, [1])
    replica = Replica(
        0,
        transport,
        tmp_path,
        initial_state=state,
        event_interceptor=recorder.interceptor(0),
    )
    try:
        proposer = replica.node.client_proposer(1)
        requests = make_requests(1, 5)
        for request in requests:
            proposer.propose(request)
        await_commits([replica], {(1, r.req_no) for r in requests})
    finally:
        replica.stop()
    recorder.close()
    assert recorder.dropped == 0

    events = read_log(str(tmp_path / "node0.gz"))
    assert len(events) > 0
    player = Player(events)
    player.play()
    assert state_machine_status(player.nodes[0].machine) == state_machine_status(
        replica.node._machine
    )
