"""Gates for core.preimage (golden byte layouts), core.actions (contract
algebra), core.persisted (log mirror, truncation, epoch-change
reconstruction), and core.epoch_change (parsing + certs)."""

import hashlib

import pytest

from mirbft_tpu import pb
from mirbft_tpu.core import actions as act
from mirbft_tpu.core import preimage
from mirbft_tpu.core.epoch_change import (
    EpochChangeCert,
    MalformedEpochChange,
    parse_epoch_change,
)
from mirbft_tpu.core.persisted import Persisted


# ---------------------------------------------------------------------------
# preimage: golden layouts
# ---------------------------------------------------------------------------


def test_request_preimage_golden():
    req = pb.Request(client_id=1, req_no=0x0102, data=b"payload")
    chunks = preimage.request_hash_data(req)
    assert chunks == [
        b"\x01\x00\x00\x00\x00\x00\x00\x00",
        b"\x02\x01\x00\x00\x00\x00\x00\x00",
        b"payload",
    ]
    assert preimage.host_digest(chunks) == hashlib.sha256(
        b"".join(chunks)
    ).digest()


def test_batch_preimage_is_ack_digest_concat():
    acks = [
        pb.RequestAck(client_id=1, req_no=1, digest=b"\xaa" * 32),
        pb.RequestAck(client_id=2, req_no=9, digest=b"\xbb" * 32),
    ]
    assert preimage.batch_hash_data(acks) == [b"\xaa" * 32, b"\xbb" * 32]


def test_epoch_change_preimage_golden():
    ec = pb.EpochChange(
        new_epoch=3,
        checkpoints=[pb.Checkpoint(seq_no=20, value=b"v")],
        p_set=[pb.EpochChangeSetEntry(epoch=2, seq_no=21, digest=b"p")],
        q_set=[pb.EpochChangeSetEntry(epoch=2, seq_no=22, digest=b"q")],
    )
    chunks = preimage.epoch_change_hash_data(ec)
    assert chunks == [
        preimage.u64le(3),
        preimage.u64le(20),
        b"v",
        preimage.u64le(2),
        preimage.u64le(21),
        b"p",
        preimage.u64le(2),
        preimage.u64le(22),
        b"q",
    ]


# ---------------------------------------------------------------------------
# actions algebra
# ---------------------------------------------------------------------------


def test_actions_concat_clear_empty():
    a = act.Actions()
    assert a.is_empty()
    a.send([0, 1], pb.Msg(type=pb.Suspect(epoch=1)))
    a.persist(0, pb.Persistent(type=pb.ECEntry(epoch_number=1)))
    b = act.Actions()
    b.hash([b"x"], pb.HashResult(digest=b"", type=pb.HashOriginBatch()))
    b.state_transfer = act.StateTarget(seq_no=5, value=b"v")
    a.concat(b)
    assert len(a.sends) == 1 and len(a.write_ahead) == 1 and len(a.hashes) == 1
    assert a.state_transfer is not None
    assert not a.is_empty()
    # Two concurrent state transfers must be rejected.
    c = act.Actions()
    c.state_transfer = act.StateTarget(seq_no=6, value=b"w")
    with pytest.raises(AssertionError):
        a.concat(c)
    a.clear()
    assert a.is_empty()


def test_results_to_event_copies_digest_into_origin():
    origin = pb.HashResult(
        digest=b"",
        type=pb.HashOriginBatch(source=0, epoch=0, seq_no=5, request_acks=[]),
    )
    hr = act.HashResult(
        digest=b"\x01" * 32, request=act.HashRequest(data=[b"d"], origin=origin)
    )
    cr = act.CheckpointResult(
        checkpoint=act.CheckpointReq(
            seq_no=20,
            network_config=pb.NetworkConfig(nodes=[0], number_of_buckets=1),
            clients_state=[pb.NetworkClient(id=1, width=10)],
        ),
        value=b"cpv",
        reconfigurations=[],
    )
    event = act.results_to_event(
        act.ActionResults(digests=[hr], checkpoints=[cr])
    )
    assert event.digests[0].digest == b"\x01" * 32
    assert isinstance(event.digests[0].type, pb.HashOriginBatch)
    assert event.checkpoints[0].seq_no == 20
    assert event.checkpoints[0].value == b"cpv"
    assert event.checkpoints[0].network_state.clients[0].id == 1


# ---------------------------------------------------------------------------
# persisted log
# ---------------------------------------------------------------------------


def _centry(seq, value=b"cp", n=4):
    return pb.Persistent(
        type=pb.CEntry(
            seq_no=seq,
            checkpoint_value=value,
            network_state=pb.NetworkState(
                config=pb.NetworkConfig(nodes=list(range(n)), number_of_buckets=n)
            ),
        )
    )


def _nentry(seq, epoch):
    return pb.Persistent(
        type=pb.NEntry(
            seq_no=seq,
            epoch_config=pb.EpochConfig(number=epoch, leaders=[0]),
        )
    )


def test_persisted_append_emits_persist_actions_with_increasing_indexes():
    p = Persisted()
    a1 = p.add_c_entry(_centry(0).type)
    a2 = p.add_p_entry(pb.PEntry(seq_no=1, digest=b"d"))
    assert a1.write_ahead[0].append.index == 0
    assert a2.write_ahead[0].append.index == 1
    assert p.next_index == 2


def test_persisted_initial_load_checks_contiguity():
    p = Persisted()
    p.append_initial_load(5, _centry(0))
    p.append_initial_load(6, _nentry(1, 0))
    assert p.next_index == 7
    with pytest.raises(ValueError):
        p.append_initial_load(9, _nentry(2, 0))


def test_persisted_truncate_to_centry():
    p = Persisted()
    p.add_c_entry(_centry(0).type)
    p.add_n_entry(_nentry(1, 0).type)
    p.add_q_entry(pb.QEntry(seq_no=1, digest=b"d1"))
    p.add_c_entry(_centry(20).type)
    p.add_q_entry(pb.QEntry(seq_no=21, digest=b"d21"))

    actions = p.truncate(20)
    # Truncates to the index of the CEntry(20): index 3.
    assert len(actions.write_ahead) == 1
    assert actions.write_ahead[0].truncate == 3
    kinds = [type(e.type).__name__ for _, e in p.entries()]
    assert kinds == ["CEntry", "QEntry"]
    # Truncating again to the same watermark is a no-op.
    assert p.truncate(20).is_empty()


def test_persisted_truncate_nentry_rule():
    # NEntry requires seq_no strictly greater than the watermark.
    p = Persisted()
    p.add_c_entry(_centry(0).type)
    p.add_n_entry(_nentry(20, 0).type)  # NEntry at exactly the watermark: skip
    p.add_n_entry(_nentry(21, 0).type)
    actions = p.truncate(20)
    assert actions.write_ahead[0].truncate == 2


def test_construct_epoch_change_basic():
    p = Persisted()
    p.add_c_entry(_centry(0, b"genesis").type)
    p.add_n_entry(_nentry(1, 0).type)
    p.add_q_entry(pb.QEntry(seq_no=1, digest=b"q1"))
    p.add_p_entry(pb.PEntry(seq_no=1, digest=b"q1"))
    p.add_c_entry(_centry(5, b"cp5").type)

    ec = p.construct_epoch_change(1)
    assert ec.new_epoch == 1
    assert [(c.seq_no, c.value) for c in ec.checkpoints] == [
        (0, b"genesis"),
        (5, b"cp5"),
    ]
    assert [(e.epoch, e.seq_no, e.digest) for e in ec.p_set] == [(0, 1, b"q1")]
    assert [(e.epoch, e.seq_no, e.digest) for e in ec.q_set] == [(0, 1, b"q1")]


def test_construct_epoch_change_dedups_pset_keeping_last():
    p = Persisted()
    p.add_c_entry(_centry(0).type)
    p.add_n_entry(_nentry(1, 0).type)
    p.add_p_entry(pb.PEntry(seq_no=1, digest=b"old"))
    p.add_n_entry(_nentry(1, 1).type)  # epoch 1 starts
    p.add_p_entry(pb.PEntry(seq_no=1, digest=b"new"))

    ec = p.construct_epoch_change(2)
    assert [(e.epoch, e.seq_no, e.digest) for e in ec.p_set] == [(1, 1, b"new")]


def test_construct_epoch_change_stops_at_new_epoch():
    p = Persisted()
    p.add_c_entry(_centry(0).type)
    p.add_n_entry(_nentry(1, 0).type)
    p.add_q_entry(pb.QEntry(seq_no=1, digest=b"in-epoch-0"))
    p.add_n_entry(_nentry(6, 3).type)  # jumps to epoch 3 >= target 2
    p.add_q_entry(pb.QEntry(seq_no=6, digest=b"in-epoch-3"))

    ec = p.construct_epoch_change(2)
    digests = [e.digest for e in ec.q_set]
    assert digests == [b"in-epoch-0"]


# ---------------------------------------------------------------------------
# epoch change parsing + certs
# ---------------------------------------------------------------------------


def test_parse_epoch_change_rejects_malformed():
    with pytest.raises(MalformedEpochChange):
        parse_epoch_change(pb.EpochChange(new_epoch=1))  # no checkpoints
    with pytest.raises(MalformedEpochChange):
        parse_epoch_change(
            pb.EpochChange(
                new_epoch=1,
                checkpoints=[
                    pb.Checkpoint(seq_no=5, value=b"a"),
                    pb.Checkpoint(seq_no=5, value=b"b"),
                ],
            )
        )
    with pytest.raises(MalformedEpochChange):
        parse_epoch_change(
            pb.EpochChange(
                new_epoch=1,
                checkpoints=[pb.Checkpoint(seq_no=5, value=b"a")],
                p_set=[
                    pb.EpochChangeSetEntry(epoch=0, seq_no=6, digest=b"x"),
                    pb.EpochChangeSetEntry(epoch=0, seq_no=6, digest=b"y"),
                ],
            )
        )


def test_parse_epoch_change_low_watermark_is_min_checkpoint():
    parsed = parse_epoch_change(
        pb.EpochChange(
            new_epoch=1,
            checkpoints=[
                pb.Checkpoint(seq_no=25, value=b"b"),
                pb.Checkpoint(seq_no=20, value=b"a"),
            ],
            q_set=[
                pb.EpochChangeSetEntry(epoch=0, seq_no=21, digest=b"x"),
                pb.EpochChangeSetEntry(epoch=1, seq_no=21, digest=b"y"),
            ],
        )
    )
    assert parsed.low_watermark == 20
    assert parsed.q_set[21] == {0: b"x", 1: b"y"}


def test_epoch_change_cert_strong_cert_at_intersection_quorum():
    nc = pb.NetworkConfig(nodes=[0, 1, 2, 3], f=1, number_of_buckets=4)
    ec_msg = pb.EpochChange(
        new_epoch=1, checkpoints=[pb.Checkpoint(seq_no=0, value=b"g")]
    )
    cert = EpochChangeCert(network_config=nc)
    cert.add_msg(0, ec_msg, b"digest")
    cert.add_msg(1, ec_msg, b"digest")
    assert cert.strong_cert is None
    cert.add_msg(1, ec_msg, b"digest")  # duplicate ack: no change
    assert cert.strong_cert is None
    cert.add_msg(2, ec_msg, b"digest")
    assert cert.strong_cert == b"digest"
    # Malformed variants are ignored entirely.
    cert2 = EpochChangeCert(network_config=nc)
    cert2.add_msg(0, pb.EpochChange(new_epoch=1), b"bad")
    assert cert2.parsed_by_digest == {}
