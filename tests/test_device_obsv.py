"""Gates for the device-plane telemetry (obsv/device.py) and the
scalar/vector divergence oracle (obsv/shadow.py): kernel histogram
round-trips through the strict catalog, retrace-budget detection on
shape-polymorphic callers, oracle regression coverage for the
forward-request promotion and small-frame tick-refresh bugs, injected
divergences caught by the sampling shadow within a stride, and the
diff gate / journal recovery that make the bench artifact crash-proof.
"""

import json
import sys

import numpy as np

import bench
from mirbft_tpu import pb
from mirbft_tpu.core.client_tracker import ClientTracker
from mirbft_tpu.core.msgbuffers import NodeBuffers
from mirbft_tpu.core.persisted import Persisted
from mirbft_tpu.core.preimage import host_digest, request_hash_data
from mirbft_tpu.obsv import device, hooks, shadow
from mirbft_tpu.obsv.__main__ import main as obsv_main
from mirbft_tpu.obsv.diff import (
    apply_device_gate,
    diff_files,
    extract_series,
    load_artifact,
)
from mirbft_tpu.obsv.metrics import CATALOG, CATALOG_LABELS, Registry
from mirbft_tpu.obsv.recorder import FlightRecorder


# -- tracker scaffolding (same idiom as test_client_tracker) ----------------


def network_state(clients=((7, 100),), n=4, f=1, ci=5):
    return pb.NetworkState(
        config=pb.NetworkConfig(
            nodes=list(range(n)),
            f=f,
            number_of_buckets=n,
            checkpoint_interval=ci,
            max_epoch_length=50,
        ),
        clients=[
            pb.NetworkClient(id=cid, width=width, low_watermark=0)
            for cid, width in clients
        ],
    )


def make_tracker(state=None):
    persisted = Persisted()
    persisted.add_c_entry(
        pb.CEntry(
            seq_no=0,
            checkpoint_value=b"genesis",
            network_state=state if state is not None else network_state(),
        )
    )
    my = pb.InitialParameters(id=0, buffer_size=1 << 20)
    ct = ClientTracker(persisted, NodeBuffers(my), my)
    ct.reinitialize()
    return ct


def req(client_id=7, req_no=0, data=b"tx"):
    r = pb.Request(client_id=client_id, req_no=req_no, data=data)
    digest = host_digest(request_hash_data(r))
    return r, pb.RequestAck(client_id=client_id, req_no=req_no, digest=digest)


def ack_msg(ack):
    return pb.Msg(type=ack)


def build_mirror():
    """Tracker with a live _FastAcks mirror: one large frame from node 1
    (first-vote rows fall back per row, which refreshes each slot)."""
    ct = make_tracker()
    assert ct._fast_ok
    acks = [req(req_no=i)[1] for i in range(40)]
    ct.step_ack_many(1, [ack_msg(a) for a in acks])
    assert ct._fast is not None
    return ct, acks


# -- device instrumentation --------------------------------------------------


def test_instrument_is_passthrough_when_capture_off(monkeypatch):
    device.reset()
    monkeypatch.setattr(hooks, "enabled", False)

    @device.instrument("toy")
    def f(x):
        return x * 2

    assert f(3) == 6
    # No capture registry, hooks off: nothing recorded anywhere.
    assert device.report(Registry())["retraces"] == {}


def test_kernel_histogram_roundtrips_through_strict_catalog():
    device.reset()
    reg = Registry()  # strict: undeclared names/labels raise KeyError
    device.start_capture(reg)
    try:

        @device.instrument("toy_kernel")
        def f(x):
            return x + 1

        a = np.zeros(16, dtype=np.uint32)
        f(a)
        f(a)
        rep = device.report(reg)
        kern = rep["kernel_seconds"]["toy_kernel"]
        assert kern["count"] == 2
        assert kern["total_s"] >= 0.0
        assert kern["mean_ms"] >= 0.0
        # Transfer estimate: args in, result out, both per call.
        assert rep["transfer_bytes"]["h2d"] == 2 * a.nbytes
        assert rep["transfer_bytes"]["d2h"] == 2 * a.nbytes
        # One abstract signature -> exactly one retrace, no breach.
        assert rep["retraces"] == {"f": 1}
        assert rep["retrace_breaches"] == []
        snap = reg.snapshot()
        series = snap["mirbft_device_kernel_seconds"]["series"]
        assert series[0]["labels"] == {"kernel": "toy_kernel"}
    finally:
        device.stop_capture()
        device.reset()


def test_device_metrics_are_cataloged_with_declared_labels():
    expected = {
        "mirbft_device_kernel_seconds": ("kernel",),
        "mirbft_device_retraces_total": ("fn",),
        "mirbft_device_transfer_bytes_total": ("direction",),
        "mirbft_device_live_buffers": (),
        "mirbft_device_live_buffer_bytes": (),
        "mirbft_device_hbm_bytes": (),
        "mirbft_divergence_total": ("component",),
    }
    for name, labels in expected.items():
        assert name in CATALOG, name
        assert CATALOG_LABELS[name] == labels, name


def test_shape_polymorphic_caller_trips_retrace_budget():
    device.reset()
    reg = Registry()
    device.start_capture(reg, retrace_budget=2)
    try:

        @device.instrument("poly", fn_name="poly")
        def g(x):
            return x

        for n in range(1, 5):  # four distinct shapes -> four signatures
            g(np.zeros(n, dtype=np.uint8))
        rep = device.report(reg)
        assert rep["retraces"]["poly"] == 4
        assert rep["retrace_budget"] == 2
        assert "poly" in rep["retrace_breaches"]
        # The breach is an absolute diff-gate failure.
        report = {"ok": True}
        apply_device_gate(report, {"device": rep})
        assert report["ok"] is False
        [failure] = report["device_failures"]
        assert failure["kind"] == "retrace_budget"
        assert failure["series"] == "device.poly.retraces"
    finally:
        device.stop_capture()
        device.reset()


def test_sequence_lengths_bucket_to_pow2_signatures():
    device.reset()
    reg = Registry()
    device.start_capture(reg)
    try:

        @device.instrument("seqy", fn_name="seqy")
        def g(items):
            return items

        for n in (5, 6, 7, 8):  # all bucket to 8: one signature
            g(list(range(n)))
        assert device.report(reg)["retraces"]["seqy"] == 1
        g(list(range(9)))  # bucket 16: a genuine retrace
        assert device.report(reg)["retraces"]["seqy"] == 2
    finally:
        device.stop_capture()
        device.reset()


def test_memory_sample_matches_jax_presence():
    sample = device.memory_sample()
    if "jax" not in sys.modules:
        assert sample is None
    elif sample is not None:
        assert set(sample) == {"live_buffers", "live_buffer_bytes", "hbm_bytes"}
        assert all(isinstance(v, int) for v in sample.values())


# -- divergence oracle -------------------------------------------------------


def test_oracle_clean_on_converged_tracker():
    ct, acks = build_mirror()
    ct.step_ack_many(2, [ack_msg(a) for a in acks[:3]])  # loop path
    ct.step_ack_many(3, [ack_msg(a) for a in acks])  # vector path
    assert shadow.audit_tracker(ct) == []


def test_forward_request_promotion_leaves_no_divergence():
    """Regression (oracle form): apply_forward_request must run the full
    weak/strong promotion when agreements cross a quorum, not only on
    exact-threshold hits — any missed promotion is a 'weak' divergence."""
    ct, acks = build_mirror()
    r, ack = req(req_no=0)
    fwd = pb.Msg(
        type=pb.ForwardRequest(request_ack=ack, request_data=r.data)
    )
    ct.step(2, fwd)
    ct.step(3, fwd)
    crn = ct.client(7).req_no(0)
    assert ack.digest in crn.weak_requests
    assert ack.digest in crn.strong_requests
    assert shadow.audit_tracker(ct) == []


def test_oracle_catches_missed_weak_promotion():
    """The old apply_forward_request bug's end state — votes accumulated
    on the agreement mask without the weak/strong promotion — must be a
    reported divergence, or the oracle proves nothing."""
    ct, acks = build_mirror()
    crn = ct.client(7).req_no(0)
    reqobj = crn.requests[acks[0].digest]
    # Bump the (mirror-attached) mask past both quorums out-of-band.
    reqobj.agreements |= (1 << 2) | (1 << 3)
    divs = shadow.audit_tracker(ct)
    comps = {d["component"] for d in divs}
    assert "weak" in comps and "strong" in comps
    [weak] = [d for d in divs if d["component"] == "weak"]
    assert weak["client_id"] == 7 and weak["req_no"] == 0


def test_oracle_catches_stale_tick_class():
    """The old small-frame bug left mirror slots with a stale tick class
    after the python loop mutated the objects; the oracle must flag the
    mirror/reference mismatch."""
    ct, acks = build_mirror()
    ct.step_ack_many(2, [ack_msg(acks[0])])  # weak crossing -> TICK_PYTHON
    fast = ct._fast
    slot = fast.slot_of(7, 0)
    assert fast.tick_class[slot] == fast.TICK_PYTHON
    assert shadow.audit_tracker(ct, [slot]) == []
    fast.tick_class[slot] = fast.TICK_INERT  # simulate the missed refresh
    divs = shadow.audit_tracker(ct, [slot])
    assert [d["component"] for d in divs] == ["tick_class"]


def test_shadow_sampler_catches_injected_divergence_within_stride(tmp_path):
    ct, acks = build_mirror()
    reg = Registry()
    rec = FlightRecorder("shadow-test", dump_dir=str(tmp_path))
    sampler = shadow.ShadowSampler(stride=2, registry=reg, recorder=rec)
    hooks.shadow = sampler
    try:
        crn = ct.client(7).req_no(0)
        reqobj = crn.requests[acks[0].digest]
        reqobj.agreements |= (1 << 2) | (1 << 3)
        # A second distinct-digest vote from node 1 hits the spam guard:
        # each frame touches the poisoned slot but mutates nothing, so
        # the divergence persists until a sampled frame audits it.
        touch = req(req_no=0, data=b"conflicting")[1]
        frames = 0
        while not sampler.divergences and frames < 8:
            ct.step_ack_many(1, [ack_msg(touch)])
            frames += 1
        assert sampler.divergences, "sampler never saw the divergence"
        assert frames <= sampler.stride, "divergence not caught in one stride"
        snap = reg.snapshot()
        total = sum(
            s["value"] for s in snap["mirbft_divergence_total"]["series"]
        )
        assert total >= 1
        # First divergence dumps the flight-recorder ring for post-mortem.
        assert sampler._dumped
        assert any(tmp_path.iterdir()), "no flight-recorder dump written"
    finally:
        hooks.shadow = None


# -- diff gate and journal recovery -----------------------------------------


def _write(tmp_path, name, doc):
    path = tmp_path / name
    path.write_text(json.dumps(doc))
    return path


def _device_section(**overrides):
    section = {
        "kernel_seconds": {},
        "retraces": {},
        "retrace_budget": 8,
        "retrace_breaches": [],
        "transfer_bytes": {},
        "divergence_total": 0,
    }
    section.update(overrides)
    return section


def test_diff_gate_fails_on_breach_divergence_and_soak(tmp_path):
    base = {"metric": "bench", "sha_per_sec": 10.0}
    pa = _write(tmp_path, "a.json", dict(base, device=_device_section()))
    assert obsv_main(["--diff", str(pa), str(pa)]) == 0

    breach = dict(
        base,
        device=_device_section(retraces={"poly": 9}, retrace_breaches=["poly"]),
    )
    divergent = dict(base, device=_device_section(divergence_total=3))
    soaked = dict(base, soak={"divergence": 2})
    for bad in (breach, divergent, soaked):
        pb_path = _write(tmp_path, "b.json", bad)
        report = diff_files(pa, pb_path)
        assert report["ok"] is False
        assert report["device_failures"]
        assert obsv_main(["--diff", str(pa), str(pb_path)]) == 1


def test_device_series_extraction_gates_retraces_not_calls():
    doc = {
        "device": _device_section(
            retraces={"fn_a": 3},
            kernel_seconds={
                "k": {"count": 7, "total_s": 0.7, "mean_ms": 100.0}
            },
            transfer_bytes={"h2d": 1024},
        )
    }
    series = extract_series(doc)
    assert series["device.fn_a.retraces"] == 3.0
    assert series["device.k.mean_ms"] == 100.0
    assert series["device.k.calls"] == 7.0
    from mirbft_tpu.obsv.diff import direction

    assert direction("device.fn_a.retraces") == "lower"
    assert direction("device.k.mean_ms") == "lower"
    # Launch counts vary run-to-run and must never gate.
    assert direction("device.k.calls") is None


def test_load_artifact_prefers_journal_final_line(tmp_path):
    payload = {"metric": "bench", "sha_per_sec": 10.0}
    lines = [
        {"schema": "mirbft-bench-stream/1", "kind": "header", "pid": 123},
        {"kind": "stage", "stage": "sha", "seconds": 1.5, "status": "ok"},
        {"kind": "final", "payload": payload},
    ]
    path = tmp_path / "BENCH_stream.jsonl"
    path.write_text("\n".join(json.dumps(l) for l in lines) + "\n")
    assert load_artifact(path) == payload


def test_load_artifact_rebuilds_killed_run_from_stage_lines(tmp_path):
    lines = [
        json.dumps(
            {"schema": "mirbft-bench-stream/1", "kind": "header", "pid": 99}
        ),
        json.dumps(
            {"kind": "stage", "stage": "sha", "seconds": 1.5, "status": "ok"}
        ),
        json.dumps(
            {"kind": "stage", "stage": "ed", "seconds": 2.5, "status": "ok"}
        ),
    ]
    path = tmp_path / "BENCH_stream.jsonl"
    # SIGKILL mid-write: the tail line is torn and must be skipped.
    path.write_text("\n".join(lines) + "\n" + '{"kind": "stage", "sta')
    doc = load_artifact(path)
    assert doc["recovered"] is True
    assert doc["schema"].startswith("mirbft-bench-recovered")
    assert doc["pid"] == 99
    assert doc["stages"]["sha"]["seconds"] == 1.5
    series = extract_series(doc)
    assert series["stage.sha.seconds"] == 1.5
    assert series["stage.ed.seconds"] == 2.5


def test_bench_recover_cli_prints_recovered_json(tmp_path, capsys):
    payload = {"metric": "bench", "sha_per_sec": 10.0}
    path = tmp_path / "BENCH_stream.jsonl"
    path.write_text(
        json.dumps({"schema": "mirbft-bench-stream/1", "kind": "header"})
        + "\n"
        + json.dumps({"kind": "final", "payload": payload})
        + "\n"
    )
    assert bench.recover_main([str(path)]) == 0
    assert json.loads(capsys.readouterr().out) == payload
    assert bench.recover_main([str(tmp_path / "missing.jsonl")]) == 1
    assert "error" in json.loads(capsys.readouterr().out)


def test_bench_budget_clamps_to_harness_timeout():
    grace = bench.WATCHDOG_GRACE_S + bench.HARNESS_MARGIN_S
    env = {"BENCH_BUDGET_S": "100000", "BENCH_HARNESS_TIMEOUT_S": "870"}
    assert bench.effective_budget_s(env) == 870.0 - grace
    env = {"BENCH_BUDGET_S": "120", "BENCH_HARNESS_TIMEOUT_S": "870"}
    assert bench.effective_budget_s(env) == 120.0
    # The defaults already fit under the harness timeout with margin.
    assert bench.effective_budget_s({}) == bench.DEFAULT_BUDGET_S
