"""Protocol core: the single-threaded, deterministic Mir state machine.

Everything in this package is pure, I/O-free, clock-free logic — the rebuild
of the reference's L1 layer (reference: docs/StateMachine.md, the determinism
discipline).  All compute (hashing, signature verification) is *requested*
via the Actions contract in ``actions`` and executed by the runtime/TPU
compute plane, never performed here.
"""
