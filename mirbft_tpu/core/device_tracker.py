"""Device-resident client/quorum plane: dense bitmask ack tracking with
on-device popcount quorums, sharded across chips.

Mir's multi-leader design makes ack/quorum bookkeeping the per-request
hot path — every RequestAck from every node touches it, O(n^2)
applications per request — and PBFT's quorum rules are pure
popcount-over-bitmask logic.  This module moves that plane onto the
accelerator: per-slot agreement/non-null masks, canonical digests,
committed flags and tick classes live as dense ``(clients × window)``
jax arrays, and one jitted ``step_ack_batch`` kernel absorbs a whole
columnar ack batch — canonical adoption, the one-non-null-vote spam
guard, mask OR, popcount quorum crossings and the tick reclassification
— in a single fused program.  The client axis is sharded across chips
via ``parallel.sharding``'s Mesh + shard_map (each chip owns a
contiguous block of clients; per-row outputs merge with a psum).

``DeviceClientPlane`` is the host facade.  The authority contract
(mirroring ``_FastAcks``, which remains the host-side reference
implementation):

- While the plane is live, per-slot vote masks (``agree``/``nonnull``)
  and canonical digests are authoritative ON DEVICE.  The owning
  ``ClientRequest``/``ClientReqNo`` objects hold stale lower bounds.
- Only *boundary outputs* materialize back to the host ``ClientTracker``
  after each kernel run: canonical adoptions (the slot's first vote),
  weak/strong quorum crossings (newly-available requests, certificate
  completion), ready-mark hits, and rows the dense representation cannot
  express (fallback rows replay through the scalar ``step_ack`` path).
- The tracker keeps canonical ownership of windows and allocation.  Any
  host path that reads or mutates a slot's ack state calls
  ``sync_slot``: the device row is pulled into the objects and the slot
  becomes host-authoritative (``staged``) until the next flush
  re-derives it object→device — the exact analogue of
  ``_FastAcks.refresh``.
- Window-structure changes (checkpoint allocation, GC, reinitialize)
  ``drop()`` the plane; it rebuilds lazily, like the host mirror.

Shapes are fixed per plane: the window axis is padded to a power of two
and ack batches are padded to power-of-two row buckets, so the jit
cache sees a handful of signatures for the whole run (asserted by the
``obsv.device`` retrace budget).  docs/DEVICE_TRACKER.md documents the
array layouts, the pad policy and this boundary contract.

This is the single module inside ``mirbft_tpu/core/`` allowed to import
jax (lint rule W16); the purity auditor treats it as a boundary module
(tools/analysis/rules_d.py), like ``obsv.device``.
"""

from __future__ import annotations

import os
import time

import numpy as np

from ..obsv import hooks
from ..obsv.bqueue import QueueTelemetry

# Tick classes and flags shared with _FastAcks (same values, same meaning).
COMMITTED = 1
SLOW = 2
TICK_INERT = 0
TICK_STEADY = 1
TICK_PYTHON = 2

#: Batch rows are padded to the next power of two, floored here, so the
#: whole run compiles at most log2(max/min)+1 batch signatures.
MIN_BATCH_ROWS = 1024
MAX_BATCH_ROWS = 65536


def resolve_ack_plane(explicit: str | None = None) -> str:
    """Resolve the ack-plane selection: explicit config wins, then the
    ``MIRBFT_ACK_PLANE`` environment knob, then the host default."""
    plane = explicit if explicit is not None else os.environ.get(
        "MIRBFT_ACK_PLANE", "host"
    )
    if plane not in ("host", "device"):
        raise ValueError(f"ack_plane must be host|device, got {plane!r}")
    return plane


def resolve_flush_rows(explicit: int | None = None) -> int:
    """Resolve the frame-coalescing threshold: the plane defers its
    kernel flush until at least this many ack rows are queued (1 keeps
    the synchronous flush-per-frame default).  Explicit config wins,
    then the ``MIRBFT_ACK_FLUSH_ROWS`` environment knob."""
    if explicit is None:
        raw = os.environ.get("MIRBFT_ACK_FLUSH_ROWS", "1")
        try:
            explicit = int(raw)
        except ValueError:
            raise ValueError(
                f"ack_flush_rows must be an integer, got {raw!r}"
            ) from None
    if explicit < 1:
        raise ValueError(f"ack_flush_rows must be >= 1, got {explicit}")
    return explicit


def device_plane_available() -> bool:
    """True when jax imports and exposes at least one device.  The
    tracker calls this once per reinitialize; a False (missing jax,
    broken platform plugin) cleanly falls back to the host path."""
    try:
        import jax

        return len(jax.devices()) > 0
    except Exception:
        return False


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def classify_tick_device(
    committed: bool, slow: bool, count: int, held: bool,
    my_or_weak: bool, weak_q: int,
) -> int:
    """The device plane's tick-class contract (the reference the oracle
    audits the ``tick_class`` array against).  Committed slots are inert;
    slots the dense representation cannot express (``slow``) take the
    python path whenever any request state exists; otherwise the class is
    pure popcount: a held canonical rebroadcasts on the steady cadence, a
    weak-quorum canonical we do not hold needs fetch ticks (python), and
    anything below the weak quorum with nothing held is inert."""
    if committed:
        return TICK_INERT
    if slow:
        return TICK_PYTHON if my_or_weak else TICK_INERT
    if held:
        return TICK_STEADY
    if count >= weak_q:
        return TICK_PYTHON
    return TICK_INERT


def digest_words(dig_mat: np.ndarray) -> np.ndarray:
    """(rows, 32) uint8 digest matrix -> (rows, 8) little-endian uint32
    words (the device-side digest representation)."""
    return np.ascontiguousarray(dig_mat).view("<u4")


def words_to_digest(words: np.ndarray) -> bytes:
    return np.ascontiguousarray(words, dtype="<u4").tobytes()


def _combine_limbs(row: np.ndarray) -> int:
    value = 0
    for limb in range(row.shape[0] - 1, -1, -1):
        value = (value << 32) | int(row[limb])
    return value


def _split_limbs(value: int, limbs: int) -> list:
    mask = (1 << 32) - 1
    return [(value >> (32 * i)) & mask for i in range(limbs)]


# ---------------------------------------------------------------------------
# The jitted ack kernel
# ---------------------------------------------------------------------------


def _build_step_kernel(mesh, *, c_pad, w_pad, limbs, weak_q, strong_q):
    """Compile-time factory for ``step_ack_batch``: one fused program
    that applies a columnar ack batch against the dense slot state.

    State arrays are sharded over the client axis (``P(AXIS)``); batch
    columns are replicated and each shard applies the rows belonging to
    its client block, so the only collective is the psum that merges the
    per-row boundary outputs (each row has exactly one owner shard)."""
    import jax
    import jax.numpy as jnp

    from ..obsv import device as _device
    from ..parallel.sharding import AXIS, _CHECK_OFF, _shard_map

    n_shards = mesh.devices.size
    block = c_pad // n_shards
    s_loc = block * w_pad
    keys_total = s_loc * limbs

    def local(agree, nonnull, canon, canon_ok, flags, held, tick,
              ci, w, src, dig, valid):
        ax = jax.lax.axis_index(AXIS)
        lci = ci - ax * block
        mine = valid & (lci >= 0) & (lci < block)
        flat = jnp.where(mine, lci, 0) * w_pad + jnp.where(mine, w, 0)

        agree_f = agree.reshape(s_loc, limbs)
        nonnull_f = nonnull.reshape(s_loc, limbs)
        canon_f = canon.reshape(s_loc, 8)
        cok_f = canon_ok.reshape(s_loc)
        tick_f = tick.reshape(s_loc)
        fl = flags.reshape(s_loc)[flat]

        committed = mine & ((fl & COMMITTED) != 0)
        slow = mine & ((fl & SLOW) != 0)
        live = mine & ~committed & ~slow

        n_rows = ci.shape[0]
        idx = jnp.arange(n_rows, dtype=jnp.int32)

        # Canonical adoption: the first live row (batch order) targeting
        # a virgin slot adopts its digest — the scalar path's "first
        # vote creates the entry" rule, done as a scatter-min race.
        virgin = live & ~cok_f[flat]
        first = jnp.full((s_loc,), n_rows, dtype=jnp.int32).at[flat].min(
            jnp.where(virgin, idx, n_rows)
        )
        adopt = virgin & (first[flat] == idx)
        tgt = jnp.where(adopt, flat, s_loc)  # out-of-range rows drop
        canon_f = canon_f.at[tgt].set(dig, mode="drop")
        cok_f = cok_f.at[tgt].set(True, mode="drop")

        match = live & (canon_f[flat] == dig).all(axis=1)

        # Spam guard against pre-batch masks: a voter whose non-null vote
        # went to a different digest gets no second vote.  (Same-source
        # same-slot conflicts inside one batch always involve a
        # non-canonical digest, which lands in the fallback path.)
        limb = src >> 5
        bit = (jnp.uint32(1) << (src & 31).astype(jnp.uint32))
        old_a_limb = agree_f[flat, limb]
        old_n_limb = nonnull_f[flat, limb]
        dup = (old_a_limb & bit) != 0
        foreign = ((old_n_limb & bit) != 0) & ~dup
        apply_r = match & ~foreign
        fallback = mine & ~committed & ~apply_r

        # Segment-OR the batch into the masks: lex-sort rows by
        # (slot·limb key, source), drop duplicate (key, bit) pairs, and
        # sum distinct bits per segment (sum of distinct bits == OR).
        key = jnp.where(apply_r, flat * limbs + limb, keys_total)
        o1 = jnp.argsort(src, stable=True)
        o2 = jnp.argsort(key[o1], stable=True)
        order = o1[o2]
        k_s = key[order]
        b_s = bit[order]
        a_s = apply_r[order]
        prev_k = jnp.concatenate([jnp.full((1,), -1, k_s.dtype), k_s[:-1]])
        prev_b = jnp.concatenate([jnp.zeros((1,), b_s.dtype), b_s[:-1]])
        dup_in_batch = (k_s == prev_k) & (b_s == prev_b)
        contrib = jnp.where(a_s & ~dup_in_batch, b_s, jnp.uint32(0))
        seg_start = k_s != prev_k
        seg_id = jnp.cumsum(seg_start.astype(jnp.int32)) - 1
        delta = jnp.zeros((n_rows,), jnp.uint32).at[seg_id].add(contrib)

        oldrow = agree_f[flat]  # (rows, limbs), pre-update

        row_delta = delta[seg_id]
        tgt_keys = jnp.where(seg_start & a_s, k_s, keys_total)
        safe = jnp.minimum(tgt_keys, keys_total - 1)
        agree_lin = agree_f.reshape(keys_total)
        nn_lin = nonnull_f.reshape(keys_total)
        add_a = row_delta & ~agree_lin[safe]
        add_n = row_delta & ~nn_lin[safe]
        agree_f = agree_lin.at[tgt_keys].add(add_a, mode="drop").reshape(
            s_loc, limbs
        )
        nonnull_f = nn_lin.at[tgt_keys].add(add_n, mode="drop").reshape(
            s_loc, limbs
        )

        # Popcount quorum tallies: counts are per-slot (all limbs), and
        # every row of a crossing slot reports the crossing — the host
        # dedupes by slot when materializing.
        pc = jax.lax.population_count
        oldcount = jnp.where(
            apply_r, pc(oldrow).sum(axis=1, dtype=jnp.int32), 0
        )
        newcount = jnp.where(
            apply_r, pc(agree_f[flat]).sum(axis=1, dtype=jnp.int32), 0
        )
        weak_x = apply_r & (oldcount < weak_q) & (newcount >= weak_q)
        strong_x = apply_r & (oldcount < strong_q) & (newcount >= strong_q)

        # Tick reclassification by popcount (classify_tick_device's
        # non-slow branch; slow slots never reach here).
        h = held.reshape(s_loc)[flat]
        new_tick = jnp.where(
            h,
            jnp.uint8(TICK_STEADY),
            jnp.where(
                newcount >= weak_q,
                jnp.uint8(TICK_PYTHON),
                jnp.uint8(TICK_INERT),
            ),
        )
        tick_f = tick_f.at[jnp.where(apply_r, flat, s_loc)].set(
            new_tick, mode="drop"
        )

        def merged(x, dtype=jnp.int32):
            return jax.lax.psum(x.astype(dtype), AXIS)

        outs = (
            merged(apply_r),
            merged(fallback),
            merged(committed),
            merged(adopt),
            merged(weak_x),
            merged(strong_x),
            merged(newcount),
        )
        return (
            agree_f.reshape(block, w_pad, limbs),
            nonnull_f.reshape(block, w_pad, limbs),
            canon_f.reshape(block, w_pad, 8),
            cok_f.reshape(block, w_pad),
            flags,
            held,
            tick_f.reshape(block, w_pad),
        ) + outs

    from jax.sharding import PartitionSpec as P

    state_spec = (P(AXIS),) * 7
    batch_spec = (P(),) * 5
    fn = jax.jit(
        _shard_map(
            local,
            mesh=mesh,
            in_specs=state_spec + batch_spec,
            out_specs=state_spec + (P(),) * 7,
            # Per-row outputs are psum-merged to replicated; varying-
            # manual-axes checking would demand pcasts for no semantic
            # gain (same rationale as sharded_sha256).
            **_CHECK_OFF,
        )
    )
    return _device.instrument(
        "device_ack_step", fn_name="device_ack_step"
    )(fn)


def _build_sweep_kernel(mesh, *, c_pad, w_pad, limbs, weak_q, strong_q):
    """(clients × window) digest-agreement reduction: quorum-certificate
    tallies for every leader bucket in one pass, plus a full tick_class
    recompute from the same popcounts."""
    import jax
    import jax.numpy as jnp

    from ..obsv import device as _device
    from ..parallel.sharding import AXIS, _CHECK_OFF, _shard_map
    from jax.sharding import PartitionSpec as P

    def local(agree, canon_ok, flags, held, tick0):
        counts = jax.lax.population_count(agree).sum(
            axis=2, dtype=jnp.int32
        )
        live = canon_ok & (flags == 0)
        weak = live & (counts >= weak_q)
        strong = live & (counts >= strong_q)
        committed = (flags & COMMITTED) != 0
        # Non-live rows (SLOW / committed) keep their host-derived class:
        # the device popcounts cannot reconstruct the my_or_weak knowledge
        # that picked it.
        tick = jnp.where(
            live,
            jnp.where(
                held,
                jnp.uint8(TICK_STEADY),
                jnp.where(
                    weak,
                    jnp.uint8(TICK_PYTHON),
                    jnp.uint8(TICK_INERT),
                ),
            ),
            jnp.where(committed, jnp.uint8(TICK_INERT), tick0),
        )

        def total(x):
            return jax.lax.psum(x.astype(jnp.int32).sum(), AXIS)

        return total(weak), total(strong), total(committed), tick

    fn = jax.jit(
        _shard_map(
            local,
            mesh=mesh,
            in_specs=(P(AXIS),) * 5,
            out_specs=(P(), P(), P(), P(AXIS)),
            **_CHECK_OFF,
        )
    )
    return _device.instrument(
        "device_quorum_sweep", fn_name="device_quorum_sweep"
    )(fn)


# ---------------------------------------------------------------------------
# Host facade
# ---------------------------------------------------------------------------


class DeviceClientPlane:
    """Batches incoming acks, runs the device kernel, and materializes
    only the boundary outputs back into the host ``ClientTracker``.

    Built from a live tracker (same construction contract as
    ``_FastAcks``); dropped on any window-structure change."""

    def __init__(self, tracker, mesh=None):
        import jax

        from ..parallel import sharding as _sharding
        from .quorum import intersection_quorum, some_correct_quorum

        if mesh is None:
            mesh = _sharding.make_mesh()
        self.mesh = mesh
        nc = tracker.network_config
        self.weak_q = some_correct_quorum(nc)
        self.strong_q = intersection_quorum(nc)
        self.limbs = ((max(nc.nodes) >> 5) + 1) if nc.nodes else 1

        clients = tracker.clients
        cids = sorted(clients)
        self.cid0 = cids[0]
        self.n_clients = cids[-1] - cids[0] + 1
        n_shards = mesh.devices.size
        self.c_pad = max(_pow2(self.n_clients), n_shards)
        w_max = 1
        for cid in cids:
            c = clients[cid]
            w_max = max(w_max, c.high_watermark - c.low_watermark + 1)
        self.w_pad = _pow2(w_max)
        self.total = self.c_pad * self.w_pad

        # Host-owned window metadata (windows never move during the
        # plane's lifetime: structure changes drop it).
        self.base_arr = np.zeros(self.n_clients + 1, dtype=np.int64)
        self.low_arr = np.zeros(self.n_clients + 1, dtype=np.int64)
        self.high_arr = np.full(self.n_clients + 1, -1, dtype=np.int64)
        self.nrm_arr = np.full(self.n_clients + 1, -1, dtype=np.int64)
        self.clients: list = [None] * (self.n_clients + 1)
        self.canon_req: list = [None] * self.total
        self.canon_crn: list = [None] * self.total

        agree = np.zeros((self.total, self.limbs), dtype=np.uint32)
        nonnull = np.zeros((self.total, self.limbs), dtype=np.uint32)
        canon = np.zeros((self.total, 8), dtype=np.uint32)
        canon_ok = np.zeros(self.total, dtype=bool)
        flags = np.zeros(self.total, dtype=np.uint8)
        held = np.zeros(self.total, dtype=bool)
        tick = np.zeros(self.total, dtype=np.uint8)

        # Phantom rows (window padding and the dense-id gaps) are SLOW so
        # no kernel row can ever apply against them.
        flags[:] = SLOW
        for cid in cids:
            ci = cid - self.cid0
            client = clients[cid]
            self.clients[ci] = client
            self.base_arr[ci] = client.low_watermark
            self.low_arr[ci] = client.low_watermark
            self.high_arr[ci] = client.high_watermark
            self.nrm_arr[ci] = client.next_ready_mark
            size = client.high_watermark - client.low_watermark + 1
            offset = ci * self.w_pad
            for i in range(size):
                slot = offset + i
                crn = client.req_no_map.get(client.low_watermark + i)
                self.canon_crn[slot] = crn
                (
                    agree[slot], nonnull[slot], canon[slot],
                    canon_ok[slot], flags[slot], held[slot], tick[slot],
                    self.canon_req[slot],
                ) = self._derive_row(crn)

        shape3 = (self.c_pad, self.w_pad)
        row = _sharding.client_axis_sharding(mesh)
        put = jax.device_put
        self._dev = [
            put(agree.reshape(shape3 + (self.limbs,)), row),
            put(nonnull.reshape(shape3 + (self.limbs,)), row),
            put(canon.reshape(shape3 + (8,)), row),
            put(canon_ok.reshape(shape3), row),
            put(flags.reshape(shape3), row),
            put(held.reshape(shape3), row),
            put(tick.reshape(shape3), row),
        ]
        self._batch_sharding = _sharding.replicated_sharding(mesh)
        self._step = _build_step_kernel(
            mesh, c_pad=self.c_pad, w_pad=self.w_pad, limbs=self.limbs,
            weak_q=self.weak_q, strong_q=self.strong_q,
        )
        self._sweep = _build_sweep_kernel(
            mesh, c_pad=self.c_pad, w_pad=self.w_pad, limbs=self.limbs,
            weak_q=self.weak_q, strong_q=self.strong_q,
        )

        # The owning tracker: the drain target for flushes forced by
        # sync points (sync_slot, quorum_sweep) that have no tracker in
        # their signature.  Same lifetime as the plane itself — the
        # tracker drops the plane before any window-structure change.
        self._tracker = tracker
        # Frame coalescing: apply_frame defers the kernel flush until
        # this many rows are queued (1 = flush every frame).
        self.flush_rows = getattr(tracker, "_ack_flush_rows", 1)
        self._staged: dict = {}  # slot -> True (host-authoritative)
        self._snapshot: dict | None = None
        self._pending: list = []  # [(src, ci, w, rno, dig_words, msgs?)]
        self._pending_rows = 0
        # Staged-frame backpressure telemetry: depth = queued ack rows,
        # wait = first-staged-row age at flush, saturated = flushes
        # forced by the coalescing threshold (vs sync-point flushes).
        self.telemetry = QueueTelemetry("device.ack_stage")
        self._stage_started = 0.0
        self._events: list = []  # flush boundary outputs awaiting drain
        # Cumulative plane counters (bench/report surface).
        self.acks_applied = 0
        self.acks_dropped = 0
        self.acks_fallback = 0
        self.batches = 0

    # -- slot math -----------------------------------------------------------

    def slot_of(self, client_id: int, req_no: int) -> int | None:
        ci = client_id - self.cid0
        if not (0 <= ci < self.n_clients):
            return None
        if not (self.low_arr[ci] <= req_no <= self.high_arr[ci]):
            return None
        return ci * self.w_pad + int(req_no - self.base_arr[ci])

    def _ident(self, slot: int) -> tuple:
        ci = slot // self.w_pad
        return ci + self.cid0, int(self.base_arr[ci]) + slot % self.w_pad

    # -- object -> device (staged refresh) -----------------------------------

    def _derive_row(self, crn):
        """Re-derive one slot's dense row from the authoritative objects
        (the device analogue of ``_FastAcks._refresh_slot``)."""
        from .client_tracker import _NULL

        za = np.zeros(self.limbs, dtype=np.uint32)
        zc = np.zeros(8, dtype=np.uint32)
        if crn is None:
            return za, za, zc, False, SLOW, False, TICK_INERT, None
        if crn.committed is not None:
            return za, za, zc, False, COMMITTED, False, TICK_INERT, None
        requests = crn.requests
        if not requests:
            # Virgin slot: the kernel may adopt its first digest.
            return za, za, zc, False, 0, False, TICK_INERT, None
        canonical = len(requests) == 1 and _NULL not in requests
        if canonical:
            (digest,) = requests
            req = requests[digest]
            fetchy = any(
                (not cr.stored) or cr.fetching
                for cr in crn.weak_requests.values()
            )
            if not fetchy:
                agree = np.asarray(
                    _split_limbs(req.agreements, self.limbs), dtype=np.uint32
                )
                nonnull = np.asarray(
                    _split_limbs(crn.non_null_voters, self.limbs),
                    dtype=np.uint32,
                )
                canon = digest_words(
                    np.frombuffer(digest, dtype=np.uint8)
                ).reshape(8)
                held = digest in crn.my_requests and crn.acks_sent > 0
                count = req.agreements.bit_count()
                tick = classify_tick_device(
                    False, False, count, held, True, self.weak_q
                )
                return agree, nonnull, canon, True, 0, held, tick, req
        # Conflicting digests, a null request in play, or fetch machinery
        # in motion: the slot is host-authoritative (rows fall back).
        my_or_weak = bool(crn.my_requests or crn.weak_requests)
        tick = classify_tick_device(
            False, True, 0, False, my_or_weak, self.weak_q
        )
        return za, za, zc, False, SLOW, False, tick, None

    def sync_slot(self, client_id: int, req_no: int) -> None:
        """Hand one slot back to the objects: pull the device masks into
        the owning request/req-no, then mark the slot staged so the next
        flush re-derives it object→device.  Idempotent until that flush.

        Queued batches AND buffered boundary events are drained into the
        owning tracker first: staging a slot whose adoption/crossing
        events are still buffered would leave ``canon_req`` unset, so
        the masks pulled below would land nowhere and the next
        ``_flush_staged`` would re-derive the row from vote-less objects
        — silently losing applied acks."""
        slot = self.slot_of(client_id, req_no)
        if slot is None:
            return
        if slot in self._staged:
            return
        if self._pending_rows or self._events:
            self.flush(drain=self._tracker)
        self._staged[slot] = True
        snap = self.host_snapshot()
        if snap["canon_ok"][slot] and not snap["flags"][slot]:
            req = self.canon_req[slot]
            crn = self.canon_crn[slot]
            if req is not None:
                req._agreements = _combine_limbs(snap["agree"][slot])
            if crn is not None:
                crn._non_null_voters = _combine_limbs(snap["nonnull"][slot])

    def mark_committed(self, client_id: int, req_no: int) -> None:
        slot = self.slot_of(client_id, req_no)
        if slot is not None:
            self._staged[slot] = True

    def _flush_staged(self) -> None:
        if not self._staged:
            return
        import jax.numpy as jnp

        slots = np.fromiter(
            self._staged, dtype=np.int64, count=len(self._staged)
        )
        self._staged = {}
        k = len(slots)
        agree = np.zeros((k, self.limbs), dtype=np.uint32)
        nonnull = np.zeros((k, self.limbs), dtype=np.uint32)
        canon = np.zeros((k, 8), dtype=np.uint32)
        canon_ok = np.zeros(k, dtype=bool)
        flags = np.zeros(k, dtype=np.uint8)
        held = np.zeros(k, dtype=bool)
        tick = np.zeros(k, dtype=np.uint8)
        for i, slot in enumerate(slots.tolist()):
            cid, rno = self._ident(slot)
            client = self.clients[slot // self.w_pad]
            crn = client.req_no_map.get(rno) if client is not None else None
            self.canon_crn[slot] = crn
            (
                agree[i], nonnull[i], canon[i], canon_ok[i], flags[i],
                held[i], tick[i], self.canon_req[slot],
            ) = self._derive_row(crn)
        ci = slots // self.w_pad
        w = slots % self.w_pad
        dev = self._dev
        dev[0] = dev[0].at[ci, w].set(jnp.asarray(agree))
        dev[1] = dev[1].at[ci, w].set(jnp.asarray(nonnull))
        dev[2] = dev[2].at[ci, w].set(jnp.asarray(canon))
        dev[3] = dev[3].at[ci, w].set(jnp.asarray(canon_ok))
        dev[4] = dev[4].at[ci, w].set(jnp.asarray(flags))
        dev[5] = dev[5].at[ci, w].set(jnp.asarray(held))
        dev[6] = dev[6].at[ci, w].set(jnp.asarray(tick))
        self._snapshot = None

    # -- device -> host ------------------------------------------------------

    def host_snapshot(self) -> dict:
        """Host numpy view of the dense state (one transfer, cached until
        the next flush or staged write invalidates it)."""
        snap = self._snapshot
        if snap is None:
            names = (
                "agree", "nonnull", "canon", "canon_ok", "flags", "held",
                "tick_class",
            )
            snap = {
                name: np.asarray(arr).reshape((self.total,) + arr.shape[2:])
                for name, arr in zip(names, self._dev)
            }
            self._snapshot = snap
        return snap

    # -- batch ingest --------------------------------------------------------

    def submit_columns(self, source, ids, rnos, dig_mat, msgs=None):
        """Queue one columnar ack batch (the plane's native ingest: the
        boundary between transport framing and the dense state is these
        four columns).  ``msgs`` carries the originating pb messages when
        available so fallback rows can replay through the scalar path;
        column-only callers must not produce fallback rows (asserted by
        the bench rung's zero-fallback gate).

        Rows outside the dense window (unknown clients, out-of-window
        req_nos) are returned as an index array for the caller to route
        through the tracker's buffering rules."""
        ci = np.asarray(ids, dtype=np.int64) - self.cid0
        rnos = np.asarray(rnos, dtype=np.int64)
        known = (ci >= 0) & (ci < self.n_clients)
        cis = np.where(known, ci, self.n_clients)
        in_win = (rnos >= self.low_arr[cis]) & (rnos <= self.high_arr[cis])
        out_rows = np.flatnonzero(~in_win)
        keep = in_win if len(out_rows) else slice(None)
        w = (rnos - self.base_arr[cis])[keep]
        self._pending.append(
            (
                int(source),
                cis[keep].astype(np.int32),
                w.astype(np.int32),
                rnos[keep],
                digest_words(dig_mat[keep]),
                [msgs[i] for i in np.flatnonzero(in_win)]
                if (msgs is not None and len(out_rows))
                else msgs,
            )
        )
        was_empty = not self._pending_rows
        self._pending_rows += int(in_win.sum()) if len(out_rows) else len(
            rnos
        )
        if hooks.enabled:
            if was_empty and self._pending_rows:
                self._stage_started = time.perf_counter()
            self.telemetry.depth(self._pending_rows)
        return out_rows

    def flush(self, drain) -> None:
        """Run the kernel over everything queued; buffer the boundary
        outputs (drained into the tracker by ``drain_events``, or
        immediately when ``drain`` is the owning tracker)."""
        if not self._pending_rows:
            if drain is not None:
                self.drain_events(drain)
            return
        import jax

        if hooks.enabled:
            if self._stage_started:
                self.telemetry.wait(
                    max(0.0, time.perf_counter() - self._stage_started)
                )
            self._stage_started = 0.0
            self.telemetry.depth(0)
        self._flush_staged()
        pending, self._pending = self._pending, []
        n = self._pending_rows
        self._pending_rows = 0

        ci = np.concatenate([p[1] for p in pending])
        w = np.concatenate([p[2] for p in pending])
        rnos = np.concatenate([p[3] for p in pending])
        dig = np.concatenate([p[4] for p in pending])
        src = np.concatenate(
            [np.full(len(p[1]), p[0], dtype=np.int32) for p in pending]
        )
        rows = min(max(_pow2(n), MIN_BATCH_ROWS), MAX_BATCH_ROWS)
        while rows < n:
            rows <<= 1
        valid = np.zeros(rows, dtype=bool)
        valid[:n] = True
        pad = rows - n

        def padded(a, fill=0):
            if not pad:
                return a
            return np.concatenate(
                [a, np.full((pad,) + a.shape[1:], fill, dtype=a.dtype)]
            )

        put = jax.device_put
        bs = self._batch_sharding
        out = self._step(
            *self._dev,
            put(padded(ci), bs),
            put(padded(w), bs),
            put(padded(src), bs),
            put(padded(dig), bs),
            put(valid, bs),
        )
        self._dev = list(out[:7])
        self._snapshot = None
        applied, fb, dropped, adopt, weak_x, strong_x, newcount = (
            np.asarray(o)[:n].astype(
                bool if i < 6 else np.int32
            )
            for i, o in enumerate(out[7:])
        )
        self.batches += 1
        self.acks_applied += int(applied.sum())
        self.acks_dropped += int(dropped.sum())
        self.acks_fallback += int(fb.sum())
        if hooks.enabled:
            hooks.record_ack_batch("device", n)

        # Ready-mark hits are detected host-side (next_ready_mark is
        # host-owned and moves during drains).
        nrm_hit = applied & (rnos == self.nrm_arr[ci]) & (
            newcount >= self.strong_q
        )
        slots = ci.astype(np.int64) * self.w_pad + w
        msgs_rows = None
        if any(p[5] is not None for p in pending):
            msgs_rows = []
            for p in pending:
                if p[5] is None:
                    msgs_rows.extend([None] * len(p[1]))
                else:
                    msgs_rows.extend(
                        (p[0], m) for m in p[5]
                    )
        self._events.append(
            {
                "slots": slots,
                "rnos": rnos,
                "dig": dig,
                "applied": applied,
                "adopt": adopt,
                "weak": weak_x,
                "strong": strong_x,
                "nrm_hit": nrm_hit,
                "msgs": msgs_rows,
                "fallback": fb,
            }
        )
        if drain is not None:
            self.drain_events(drain)

    def drain_events(self, tracker) -> None:
        """Materialize buffered boundary outputs into the host objects:
        adopted canonicals become ``ClientRequest`` entries, weak
        crossings feed the available list, strong crossings complete
        certificates and may advance the ready mark, and fallback rows
        replay through the scalar reference path."""
        if not self._events:
            return
        from . import client_tracker as _ct
        from .. import pb

        events, self._events = self._events, []
        w_pad = self.w_pad
        canon_req = self.canon_req
        canon_crn = self.canon_crn
        for ev in events:
            slots = ev["slots"]
            rnos = ev["rnos"]
            adopt_rows = np.flatnonzero(ev["adopt"])
            for r in adopt_rows.tolist():
                slot = int(slots[r])
                crn = canon_crn[slot]
                if crn is None:
                    continue
                digest = words_to_digest(ev["dig"][r])
                req = crn.requests.get(digest)
                if req is None:
                    req = _ct.ClientRequest(
                        ack=pb.RequestAck(
                            client_id=crn.client_id,
                            req_no=crn.req_no,
                            digest=digest,
                        )
                    )
                    crn.requests[digest] = req
                canon_req[slot] = req

            snap = None
            for name, member in (("weak", "weak_requests"),
                                 ("strong", "strong_requests")):
                cross = np.flatnonzero(ev[name])
                if not len(cross):
                    continue
                if snap is None:
                    snap = self.host_snapshot()
                seen = set()
                for r in cross.tolist():
                    slot = int(slots[r])
                    if slot in seen:
                        continue
                    seen.add(slot)
                    req = canon_req[slot]
                    crn = canon_crn[slot]
                    if req is None or crn is None:
                        continue
                    digest = req.ack.digest
                    bucket = getattr(crn, member)
                    if digest in bucket:
                        continue
                    bucket[digest] = req
                    # Crossings carry the mask back to the object so
                    # fetch targeting sees the voters the device saw.
                    req._agreements = _combine_limbs(snap["agree"][slot])
                    if name == "weak" and not req.garbage:
                        tracker.available_list.push_back(req)

            applied_slots = np.unique(slots[ev["applied"]])
            for slot in applied_slots.tolist():
                client = self.clients[slot // w_pad]
                if client is not None:
                    client._tick_pending.add(
                        int(self.base_arr[slot // w_pad]) + slot % w_pad
                    )

            for r in np.flatnonzero(ev["nrm_hit"]).tolist():
                slot = int(slots[r])
                crn = canon_crn[slot]
                client = self.clients[slot // w_pad]
                if crn is not None and client is not None:
                    if crn.strong_requests:
                        tracker.check_ready(client, crn)

            fb_rows = np.flatnonzero(ev["fallback"])
            if len(fb_rows):
                msgs_rows = ev["msgs"]
                if msgs_rows is None:
                    raise AssertionError(
                        "column-only ingest produced fallback rows; "
                        "replay needs the originating messages"
                    )
                for r in fb_rows.tolist():
                    entry = msgs_rows[r]
                    if entry is None:
                        continue
                    source, msg = entry
                    # step_ack syncs the slot itself via the tracker's
                    # device branch.
                    tracker.step_ack(source, msg)

    # -- tracker entry points ------------------------------------------------

    def apply_frame(self, tracker, source: int, msgs: list) -> None:
        """One inbound ack frame: columnize and queue; the kernel flush
        runs once ``flush_rows`` ack rows are queued (1 = every frame,
        the default).  Sync points — ``sync_slot`` before any scalar
        mutation, the tracker's tick boundary, the oracle audits,
        ``drop`` — force an earlier flush+drain, so coalescing only ever
        delays materialization, never loses it.  Out-of-window rows take
        the tracker's buffering rules immediately (the same verdicts the
        scalar path reaches); they never need the kernel."""
        from .client_tracker import _frame_columns

        ids, rnos, dig_mat, irregular = _frame_columns(msgs)
        if irregular is not None:
            # Null/odd-length digests cannot be dense rows; replay them
            # through the scalar path after the vector rows (the same
            # ordering relaxation _step_ack_vector documents).
            keep = np.ones(len(msgs), dtype=bool)
            keep[irregular] = False
            kept_msgs = [m for i, m in enumerate(msgs) if keep[i]]
            out_rows = self.submit_columns(
                source, ids[keep], rnos[keep], dig_mat[keep],
                msgs=kept_msgs,
            )
            tail = [msgs[i] for i in irregular]
        else:
            kept_msgs = msgs
            out_rows = self.submit_columns(
                source, ids, rnos, dig_mat, msgs=msgs
            )
            tail = []
        if self._pending_rows >= self.flush_rows:
            # Coalescing threshold hit: the staging buffer is "full" in
            # the backpressure sense (vs a sync-point-forced flush).
            self.telemetry.saturated()
            self.flush(drain=tracker)
        # out_rows index the SUBMITTED subset, not the original frame:
        # replay through kept_msgs so a filtered null-digest row can
        # never misroute a later out-of-window ack onto the wrong
        # message (node state must not depend on transport framing).
        for r in np.asarray(out_rows).tolist():
            tracker.step_ack(source, kept_msgs[r])  # buffers / drops
        for msg in tail:
            tracker.step_ack(source, msg)

    def quorum_sweep(self) -> dict:
        """Tally quorum certificates across every (client, window) bucket
        in one device pass; refreshes the tick_class plane from the same
        popcounts.  Coalesced frames still in the queue are flushed (and
        their boundary events drained) first so the tally never lags the
        ingested acks."""
        if self._pending_rows or self._events:
            self.flush(drain=self._tracker)
        self._flush_staged()
        weak, strong, committed, tick = self._sweep(
            self._dev[0], self._dev[3], self._dev[4], self._dev[5],
            self._dev[6],
        )
        self._dev[6] = tick
        self._snapshot = None
        return {
            "weak_certs": int(weak),
            "strong_certs": int(strong),
            "committed": int(committed),
        }

    def mark_committed_bulk(self, slots: np.ndarray) -> None:
        """Flag many slots committed in one scatter (bench/commit-drain
        path; the per-request path stages through ``mark_committed``)."""
        import jax.numpy as jnp

        slots = np.asarray(slots, dtype=np.int64)
        ci = slots // self.w_pad
        w = slots % self.w_pad
        dev = self._dev
        dev[4] = dev[4].at[ci, w].set(np.uint8(COMMITTED))
        dev[6] = dev[6].at[ci, w].set(np.uint8(TICK_INERT))
        dev[3] = dev[3].at[ci, w].set(False)
        self._snapshot = None

    def drop(self, tracker) -> None:
        """Materialize everything back into the objects before the plane
        is discarded (window moves, GC, reinitialize) — the device
        analogue of ``ClientTracker._drop_fast``."""
        self.flush(drain=tracker)
        snap = self.host_snapshot()
        canon_ok = snap["canon_ok"]
        flags = snap["flags"]
        agree = snap["agree"]
        nonnull = snap["nonnull"]
        for slot in np.flatnonzero(canon_ok & (flags == 0)).tolist():
            if slot in self._staged:
                continue  # objects already authoritative
            req = self.canon_req[slot]
            crn = self.canon_crn[slot]
            if req is not None:
                req._agreements = _combine_limbs(agree[slot])
            if crn is not None:
                crn._non_null_voters = _combine_limbs(nonnull[slot])
