"""Pure protocol math: quorum sizes, committed bitmasks, bucket partitioning,
and the PBFT new-view selection function.

Rebuild of the reference's stateless helpers (reference: stateless.go:18-309).
Everything here is a pure function; determinism rules (docs/StateMachine.md)
are enforced by iterating node sets in config order and sorting any
dict-derived iteration.
"""

from __future__ import annotations

from .. import pb


# ---------------------------------------------------------------------------
# Quorum math (reference: stateless.go:90-101)
# ---------------------------------------------------------------------------


def intersection_quorum(config: pb.NetworkConfig) -> int:
    """Number of nodes such that any two such sets intersect in a correct
    node: ceil((n+f+1)/2)."""
    return (len(config.nodes) + config.f + 2) // 2


def some_correct_quorum(config: pb.NetworkConfig) -> int:
    """Number of nodes such that at least one is correct: f+1."""
    return config.f + 1


# ---------------------------------------------------------------------------
# Bucket partitioning (reference: stateless.go:103-109)
# ---------------------------------------------------------------------------


def req_bucket(client_id: int, req_no: int, num_buckets: int) -> int:
    return (client_id + req_no) % num_buckets


def client_req_to_bucket(client_id: int, req_no: int, config: pb.NetworkConfig) -> int:
    return req_bucket(client_id, req_no, config.number_of_buckets)


def seq_to_bucket(seq_no: int, config: pb.NetworkConfig) -> int:
    return seq_no % config.number_of_buckets


# ---------------------------------------------------------------------------
# Committed bitmask (reference: stateless.go:18-88)
#
# MSB-first within each byte: bit 0 of the mask is 0x80 of byte 0.  This is
# the format of NetworkClient.committed_mask, so it is part of the
# checkpoint-value contract.
# ---------------------------------------------------------------------------


def mask_ids(mask: int) -> list:
    """Node IDs set in an int bitmask, ascending."""
    ids = []
    i = 0
    while mask:
        if mask & 1:
            ids.append(i)
        mask >>= 1
        i += 1
    return ids


def make_bitmask(n_bits: int) -> bytearray:
    return bytearray((n_bits + 7) // 8)


def bit_is_set(mask: bytes, bit_index: int) -> bool:
    byte_index = bit_index // 8
    if byte_index >= len(mask):
        return False
    return bool(mask[byte_index] & (0x80 >> (bit_index % 8)))


def set_bit(mask: bytearray, bit_index: int) -> None:
    byte_index = bit_index // 8
    if byte_index >= len(mask):
        raise IndexError(
            f"bit {bit_index} out of range for {len(mask)}-byte mask"
        )
    mask[byte_index] |= 0x80 >> (bit_index % 8)


# ---------------------------------------------------------------------------
# New-epoch config selection (reference: stateless.go:111-309)
#
# The PBFT new-view computation, adapted to Mir: pick the highest checkpoint
# supported by f+1 nodes and reachable by an intersection quorum, then for
# every in-flight sequence above it select a digest by condition A (an
# intersection quorum agrees via their pSets, backed by f+1 qSet entries) or
# condition B (an intersection quorum never prepared it → null request).
# Returns None when neither condition can yet be satisfied (must wait for
# more epoch-change messages).
# ---------------------------------------------------------------------------


class DivergentCheckpointError(Exception):
    """Two f+1-supported quorums hold different values for the same seq_no —
    the byzantine assumption (f < n/3) has been exceeded."""


def construct_new_epoch_config(
    config: pb.NetworkConfig,
    new_leaders: list,
    epoch_changes: dict,
) -> pb.NewEpochConfig | None:
    """epoch_changes maps node_id -> parsed epoch change (an object with
    ``underlying`` (pb.EpochChange), ``low_watermark`` (int), ``p_set``
    (dict seq_no -> pb.EpochChangeSetEntry), and ``q_set`` (dict seq_no ->
    dict epoch -> digest)); see core.epoch_change.ParsedEpochChange."""

    # Tally checkpoint support in deterministic node order.
    checkpoint_support: dict[tuple[int, bytes], list] = {}
    new_epoch_number = 0
    for node_id in config.nodes:
        change = epoch_changes.get(node_id)
        if change is None:
            continue
        new_epoch_number = change.underlying.new_epoch
        for checkpoint in change.underlying.checkpoints:
            key = (checkpoint.seq_no, checkpoint.value)
            checkpoint_support.setdefault(key, []).append(node_id)

    # ordered_changes: deterministic iteration for the commutative counts.
    ordered_changes = [epoch_changes[k] for k in sorted(epoch_changes)]

    max_checkpoint: tuple[int, bytes] | None = None
    for key in sorted(checkpoint_support, key=lambda k: (k[0], k[1])):
        supporters = checkpoint_support[key]
        if len(supporters) < some_correct_quorum(config):
            continue
        reachable = sum(
            1 for change in ordered_changes if change.low_watermark <= key[0]
        )
        if reachable < intersection_quorum(config):
            continue
        if max_checkpoint is not None and max_checkpoint[0] == key[0]:
            raise DivergentCheckpointError(
                f"two correct quorums hold different checkpoints for seq_no "
                f"{key[0]}: {max_checkpoint[1]!r} != {key[1]!r}"
            )
        if max_checkpoint is None or key[0] > max_checkpoint[0]:
            max_checkpoint = key

    if max_checkpoint is None:
        return None

    start_seq, start_value = max_checkpoint

    final_preprepares: list[bytes] = [b""] * (2 * config.checkpoint_interval)
    any_selected = False

    for offset in range(len(final_preprepares)):
        seq_no = start_seq + offset + 1

        selected_digest: bytes | None = None
        for node_id in config.nodes:
            change = epoch_changes.get(node_id)
            if change is None:
                continue
            entry = change.p_set.get(seq_no)
            if entry is None:
                continue

            # Condition A1: an intersection quorum either never prepared
            # seq_no at an epoch >= this entry's, or prepared this digest.
            a1 = 0
            for other in ordered_changes:
                if other.low_watermark >= seq_no:
                    continue
                other_entry = other.p_set.get(seq_no)
                if other_entry is None or other_entry.epoch < entry.epoch:
                    a1 += 1
                elif other_entry.epoch == entry.epoch and other_entry.digest == entry.digest:
                    a1 += 1
            if a1 < intersection_quorum(config):
                continue

            # Condition A2: f+1 nodes preprepared this digest at an
            # epoch >= the entry's epoch.
            a2 = 0
            for other in ordered_changes:
                epoch_digests = other.q_set.get(seq_no)
                if not epoch_digests:
                    continue
                for epoch, digest in epoch_digests.items():
                    if epoch >= entry.epoch and digest == entry.digest:
                        a2 += 1
                        break
            if a2 < some_correct_quorum(config):
                continue

            selected_digest = entry.digest
            break

        if selected_digest is not None:
            final_preprepares[offset] = selected_digest
            any_selected = True
            continue

        # Condition B: an intersection quorum (of nodes whose logs cover
        # seq_no) never prepared anything there → safe to null it.
        b_count = sum(
            1
            for other in ordered_changes
            if other.low_watermark < seq_no and seq_no not in other.p_set
        )
        if b_count < intersection_quorum(config):
            return None  # cannot satisfy A or B yet; wait for more changes

    if not any_selected:
        final_preprepares = []

    return pb.NewEpochConfig(
        config=pb.EpochConfig(
            number=new_epoch_number,
            leaders=list(new_leaders),
            planned_expiration=start_seq + config.max_epoch_length,
        ),
        starting_checkpoint=pb.Checkpoint(seq_no=start_seq, value=start_value),
        final_preprepares=final_preprepares,
    )
