"""Top-level epoch lifecycle: which epoch we're in, when to change, how to
resume after a crash.

Rebuild of the reference's epoch tracker (reference: epoch_tracker.go:17-436).
Holds exactly one current EpochTarget; when it reaches DONE (graceful end,
suspicion quorum, or the f+1-higher-epoch jump rule) the tracker constructs
our EpochChange deterministically from the persisted log, persists an
ECEntry, broadcasts, and starts the next target.  On reinitialize, the log's
last NEntry/FEntry/ECEntry decide between resuming an active epoch (with a
precautionary Suspect), converting a graceful end into the next epoch
change, or continuing an in-flight epoch change.
"""

from __future__ import annotations

from .. import pb
from ..obsv import hooks
from .actions import Actions
from .batch_tracker import BatchTracker
from .client_tracker import ClientTracker
from .commitstate import CommitState
from .epoch_change import parse_epoch_change
from .epoch_target import EpochTarget, TargetState
from .msgbuffers import Applyable, MsgBuffer, NodeBuffers
from .persisted import Persisted
from .quorum import some_correct_quorum

_EPOCH_JUMP_TICKS = 10  # ticks behind an f+1-correct higher epoch before jumping


def epoch_for_msg(msg: pb.Msg) -> int:
    inner = msg.type
    if isinstance(inner, (pb.Preprepare, pb.Prepare, pb.Commit, pb.Suspect)):
        return inner.epoch
    if isinstance(inner, pb.EpochChange):
        return inner.new_epoch
    if isinstance(inner, pb.EpochChangeAck):
        return inner.epoch_change.new_epoch
    if isinstance(inner, pb.NewEpoch):
        return inner.new_config.config.number
    if isinstance(inner, (pb.NewEpochEcho, pb.NewEpochReady)):
        return inner.new_epoch_config.config.number
    raise AssertionError(f"not an epoch message: {type(inner).__name__}")


class EpochTracker:
    def __init__(
        self,
        persisted: Persisted,
        node_buffers: NodeBuffers,
        commit_state: CommitState,
        my_config: pb.InitialParameters,
        batch_tracker: BatchTracker,
        client_tracker: ClientTracker,
        logger=None,
    ):
        self.persisted = persisted
        self.node_buffers = node_buffers
        self.commit_state = commit_state
        self.my_config = my_config
        self.batch_tracker = batch_tracker
        self.client_tracker = client_tracker
        self.logger = logger

        self.current_epoch: EpochTarget | None = None
        self.network_config: pb.NetworkConfig | None = None
        self.future_msgs: dict[int, MsgBuffer] = {}
        self.max_epochs: dict[int, int] = {}  # node -> highest epoch claimed
        self.max_correct_epoch = 0
        self.ticks_out_of_correct_epoch = 0

    def _new_target(self, number: int) -> EpochTarget:
        return EpochTarget(
            number=number,
            persisted=self.persisted,
            node_buffers=self.node_buffers,
            commit_state=self.commit_state,
            client_tracker=self.client_tracker,
            batch_tracker=self.batch_tracker,
            network_config=self.network_config,
            my_config=self.my_config,
            logger=self.logger,
        )

    # -- lifecycle -----------------------------------------------------------

    def reinitialize(self) -> Actions:
        self.network_config = self.commit_state.active_state.config

        new_future = {}
        for node in self.network_config.nodes:
            buffer = self.future_msgs.get(node)
            if buffer is None:
                buffer = MsgBuffer(
                    "future-epochs", self.node_buffers.node_buffer(node)
                )
            new_future[node] = buffer
        self.future_msgs = new_future

        actions = Actions()
        last_n = last_ec = last_f = None
        highest_preprepared = 0

        def on_n(entry):
            nonlocal last_n
            last_n = entry

        def on_f(entry):
            nonlocal last_f
            last_f = entry

        def on_ec(entry):
            nonlocal last_ec
            last_ec = entry

        def on_q(entry):
            nonlocal highest_preprepared
            highest_preprepared = max(highest_preprepared, entry.seq_no)

        def on_c(entry):
            # After state transfer we may hold a CEntry beyond any QEntry.
            nonlocal highest_preprepared
            highest_preprepared = max(highest_preprepared, entry.seq_no)

        self.persisted.iterate(
            {
                pb.NEntry: on_n,
                pb.FEntry: on_f,
                pb.ECEntry: on_ec,
                pb.QEntry: on_q,
                pb.CEntry: on_c,
            }
        )

        if last_n is None and last_f is None:
            raise AssertionError("no epoch markers in the log")

        if last_n is not None and (
            last_ec is None or last_ec.epoch_number <= last_n.epoch_config.number
        ):
            # Crashed during an active epoch: resume it, but announce our
            # suspicion so the network can change epochs if it moved on.
            self.current_epoch = self._new_target(last_n.epoch_config.number)
            ci = self.network_config.checkpoint_interval
            starting = highest_preprepared + 1
            # Round up to the first sequence after a checkpoint boundary so
            # we never re-consent to sequences we already consented on.
            # ((s - 1) % ci == 0 — the reference's `s % ci != 1` loop spins
            # forever for ci == 1, epoch_tracker.go:142.)
            while (starting - 1) % ci != 0:
                starting += 1
            self.current_epoch.starting_seq_no = starting
            self.current_epoch.state = TargetState.RESUMING
            # The resume path never receives a NewEpoch; the READY branch
            # instantiates the active epoch from the resumed config.
            self.current_epoch.network_new_epoch = pb.NewEpochConfig(
                config=last_n.epoch_config
            )
            suspect = pb.Suspect(epoch=last_n.epoch_config.number)
            actions.concat(self.persisted.add_suspect(suspect))
            actions.send(self.network_config.nodes, pb.Msg(type=suspect))
        else:
            if last_f is not None and (
                last_ec is None
                or last_ec.epoch_number <= last_f.ends_epoch_config.number
            ):
                # Graceful end, epoch change not yet begun: begin it.
                last_ec = pb.ECEntry(
                    epoch_number=last_f.ends_epoch_config.number + 1
                )
                actions.concat(self.persisted.add_ec_entry(last_ec))

            if (
                self.current_epoch is not None
                and self.current_epoch.number == last_ec.epoch_number
                and self.current_epoch.network_config
                == self.network_config
            ):
                # Reinitialized mid-epoch-change: continue it.  (Only while
                # the network config is unchanged — a reconfiguration that
                # altered the node set / f must rebuild the target so its
                # quorum math and send lists use the new config.)
                return actions.concat(self.current_epoch.advance_state())

            epoch_change = self.persisted.construct_epoch_change(
                last_ec.epoch_number
            )
            self.current_epoch = self._new_target(last_ec.epoch_number)
            self.current_epoch.my_epoch_change = parse_epoch_change(epoch_change)
            # Leader choice on boot: honor the FEntry's leader set when it
            # names one — a provisioned-but-absent member (cluster join:
            # the node set includes a replica that has not started yet)
            # must not be elected leader at epoch 0, or its buckets stall
            # the whole network until the first suspicion round.  Every
            # pre-existing FEntry names all nodes, so behavior there is
            # unchanged; later epoch changes revert to all nodes
            # (advance_state below).
            leaders = list(self.network_config.nodes)
            if last_f is not None:
                from_f = [
                    n
                    for n in last_f.ends_epoch_config.leaders
                    if n in self.network_config.nodes
                ]
                if from_f:
                    leaders = from_f
            self.current_epoch.my_leader_choice = leaders

        for node in self.network_config.nodes:
            self.future_msgs[node].iterate(
                self.filter,
                lambda src, msg: actions.concat(self.apply_msg(src, msg)),
            )
        return actions

    def advance_state(self) -> Actions:
        if self.current_epoch.state < TargetState.DONE:
            return self.current_epoch.advance_state()

        if self.commit_state.checkpoint_pending:
            # Wait for outstanding checkpoints before changing epochs.
            return Actions()

        new_number = max(self.current_epoch.number + 1, self.max_correct_epoch)
        epoch_change = self.persisted.construct_epoch_change(new_number)

        # Fetches issued for the dead target are stale: the next target's
        # FETCHING phase issues its own, and retransmit_fetches must not
        # keep re-broadcasting abandoned ones forever.
        self.batch_tracker.abandon_fetches()
        self.current_epoch = self._new_target(new_number)
        self.current_epoch.my_epoch_change = parse_epoch_change(epoch_change)
        # Leader choice: all nodes (multi-leader; refinement of the set on
        # failures is future policy — the reference marks its own choices
        # as placeholders, epoch_tracker.go:199-202,249).
        self.current_epoch.my_leader_choice = list(self.network_config.nodes)
        self.ticks_out_of_correct_epoch = 0
        if hooks.enabled:
            hooks.epoch_milestone(
                "epoch.changing", self.my_config.id, new_number
            )

        actions = self.persisted.add_ec_entry(
            pb.ECEntry(epoch_number=new_number)
        ).send(self.network_config.nodes, pb.Msg(type=epoch_change))

        for node in self.network_config.nodes:
            self.future_msgs[node].iterate(
                self.filter,
                lambda src, msg: actions.concat(self.apply_msg(src, msg)),
            )
        return actions

    # -- message routing -----------------------------------------------------

    def filter(self, _source: int, msg: pb.Msg) -> Applyable:
        number = epoch_for_msg(msg)
        if number < self.current_epoch.number:
            return Applyable.PAST
        if number > self.current_epoch.number:
            return Applyable.FUTURE
        return Applyable.CURRENT

    def step(self, source: int, msg: pb.Msg) -> Actions:
        number = epoch_for_msg(msg)
        if number < self.current_epoch.number:
            return Actions()
        if number > self.current_epoch.number:
            if self.max_epochs.get(source, 0) < number:
                self.max_epochs[source] = number
            self.future_msgs[source].store(msg)
            return Actions()
        return self.apply_msg(source, msg)

    def apply_msg(self, source: int, msg: pb.Msg) -> Actions:
        target = self.current_epoch
        inner = msg.type
        if isinstance(inner, (pb.Preprepare, pb.Prepare, pb.Commit)):
            return target.step(source, msg)
        if isinstance(inner, pb.Suspect):
            target.apply_suspect_msg(source)
            return Actions()
        if isinstance(inner, pb.EpochChange):
            return target.apply_epoch_change_msg(source, inner)
        if isinstance(inner, pb.EpochChangeAck):
            return target.apply_epoch_change_ack(
                source, inner.originator, inner.epoch_change
            )
        if isinstance(inner, pb.NewEpoch):
            nodes = self.network_config.nodes
            leader = nodes[inner.new_config.config.number % len(nodes)]
            if leader != source:
                return Actions()  # not from the epoch's leader
            return target.apply_new_epoch_msg(inner)
        if isinstance(inner, pb.NewEpochEcho):
            return target.apply_new_epoch_echo_msg(
                source, inner
            )
        if isinstance(inner, pb.NewEpochReady):
            return target.apply_new_epoch_ready_msg(
                source, inner
            )
        raise AssertionError(f"unexpected epoch msg {type(inner).__name__}")

    # -- results / ticks -----------------------------------------------------

    def apply_batch_hash_result(
        self, epoch: int, seq_no: int, digest: bytes
    ) -> Actions:
        if (
            epoch != self.current_epoch.number
            or self.current_epoch.state != TargetState.IN_PROGRESS
        ):
            return Actions()
        return self.current_epoch.active_epoch.apply_batch_hash_result(
            seq_no, digest
        )

    def apply_epoch_change_digest(
        self, origin_info: pb.HashOriginEpochChange, digest: bytes
    ) -> Actions:
        target_number = origin_info.epoch_change.new_epoch
        if target_number < self.current_epoch.number:
            return Actions()  # stale
        if target_number > self.current_epoch.number:
            raise AssertionError(
                f"epoch change digest for future epoch {target_number} "
                f"while processing {self.current_epoch.number}"
            )
        return self.current_epoch.apply_epoch_change_digest(origin_info, digest)

    def move_low_watermark(self, seq_no: int) -> Actions:
        return self.current_epoch.move_low_watermark(seq_no)

    def tick(self) -> Actions:
        # f+1 nodes claiming a higher epoch, observed for long enough,
        # forces a jump (we are partitioned or slow).  The claimants must be
        # f+1 *distinct remote* nodes — counting ourselves (as the
        # reference does, epoch_tracker.go:376-382) would let f byzantine
        # nodes poison the jump target.
        # sorted() keeps the scan order replay-stable (D104): the final
        # max_correct_epoch is order-independent, but a deterministic
        # trace must not depend on set iteration order.
        for max_epoch in sorted(set(self.max_epochs.values())):
            if max_epoch <= self.max_correct_epoch:
                continue
            matches = sum(1 for m in self.max_epochs.values() if m >= max_epoch)
            if matches < some_correct_quorum(self.network_config):
                continue
            self.max_correct_epoch = max_epoch

        if self.max_correct_epoch > self.current_epoch.number:
            self.ticks_out_of_correct_epoch += 1
            if self.ticks_out_of_correct_epoch > _EPOCH_JUMP_TICKS:
                self.current_epoch.state = TargetState.DONE

        return self.current_epoch.tick()
