"""Per-bucket, per-client in-order consumption of client requests by
preprepared batches.

Rebuild of the reference's outstanding-requests checker (reference:
outstanding.go:15-139).  Each bucket owns a rotating subsequence of every
client's request numbers ((client_id + req_no) mod buckets); a preprepare
for a bucket must consume each client's requests *in that order* or it is
invalid.  Batch requests we haven't replicated yet are recorded as
outstanding against their sequence, which is satisfied as the requests
become available (weak quorum + stored locally).
"""

from __future__ import annotations

from .. import pb
from .actions import Actions
from .client_tracker import ClientTracker
from .quorum import client_req_to_bucket


class InvalidPreprepare(Exception):
    """The batch violates the per-bucket client-order contract."""


class _ClientCursor:
    def __init__(self, client, next_req_no: int, num_buckets: int):
        self.client = client
        self.next_req_no = next_req_no
        self.num_buckets = num_buckets

    def advance(self) -> None:
        """Skip already-committed request numbers."""
        while self.next_req_no <= self.client.high_watermark:
            crn = self.client.req_no_map.get(self.next_req_no)
            if crn is not None and crn.committed is not None:
                self.next_req_no += self.num_buckets
                continue
            break


class OutstandingReqs:
    def __init__(
        self,
        client_tracker: ClientTracker,
        network_state: pb.NetworkState,
        logger=None,
    ):
        self.logger = logger
        self.correct_requests: dict[bytes, pb.RequestAck] = {}
        self.outstanding_requests: dict[bytes, object] = {}  # digest -> Sequence
        self.available_iterator = client_tracker.available_list.iterator()

        config = network_state.config
        num_buckets = config.number_of_buckets
        self.buckets: dict[int, dict[int, _ClientCursor]] = {}
        for bucket_id in range(num_buckets):
            cursors = {}
            for client_state in network_state.clients:
                first = client_state.low_watermark
                for j in range(num_buckets):
                    req_no = client_state.low_watermark + j
                    if client_req_to_bucket(client_state.id, req_no, config) == bucket_id:
                        first = req_no
                        break
                cursor = _ClientCursor(
                    client=client_tracker.client(client_state.id),
                    next_req_no=first,
                    num_buckets=num_buckets,
                )
                cursor.advance()
                cursors[client_state.id] = cursor
            self.buckets[bucket_id] = cursors

        self.advance_requests()

    def advance_requests(self) -> Actions:
        """Match newly available requests against waiting sequences."""
        actions = Actions()
        while self.available_iterator.has_next():
            client_request = self.available_iterator.next()
            key = client_request.ack.digest
            seq = self.outstanding_requests.pop(key, None)
            if seq is not None:
                actions.concat(seq.satisfy_outstanding(client_request.ack))
                continue
            self.correct_requests[key] = client_request.ack
        return actions

    def apply_acks(self, bucket_id: int, seq, batch: list) -> Actions:
        """Validate a preprepare's batch for this bucket and allocate the
        sequence, recording not-yet-available requests as outstanding.
        Raises InvalidPreprepare on client-order violations (the reference
        leaves 'suspect the leader' as a TODO at epoch_active.go:281-284;
        callers treat this as grounds for suspicion)."""
        cursors = self.buckets.get(bucket_id)
        if cursors is None:
            raise AssertionError(f"no bucket {bucket_id}")

        outstanding = set()
        for ack in batch:
            cursor = cursors.get(ack.client_id)
            if cursor is None:
                raise InvalidPreprepare(f"no such client {ack.client_id}")
            if cursor.next_req_no != ack.req_no:
                raise InvalidPreprepare(
                    f"client {ack.client_id} bucket {bucket_id}: expected "
                    f"req_no {cursor.next_req_no}, got {ack.req_no}"
                )

            if ack.digest in self.correct_requests:
                del self.correct_requests[ack.digest]
            else:
                self.outstanding_requests[ack.digest] = seq
                outstanding.add(ack.digest)

            cursor.next_req_no += cursor.num_buckets
            cursor.advance()

        return seq.allocate(batch, outstanding)
