"""Client request tracking: windows, ACK certificates, replication.

Rebuild of the reference's largest and subtlest component (reference:
client_tracker.go:19-1267; the design essay at :19-115 is the spec).  In
brief:

- Requests enter either locally (Propose → verified → digest → ACK
  broadcast) or via a weak quorum (f+1) of RequestAcks proving some correct
  replica validated them.  A strong quorum (2f+1) makes a request safe to
  propose.
- Each client has a sliding window of request numbers [low_watermark,
  low_watermark + width]; windows advance only at checkpoint boundaries,
  with the *previous* checkpoint's width consumption throttling how much of
  the new window is usable before the next checkpoint
  (``valid_after_seq_no`` — see commits_completed_for_checkpoint_window).
- A client observed submitting two distinct correct requests for one req_no
  is (accidentally or deliberately) byzantine: replicas then advocate a
  *null request* for that req_no, consuming it without committing data.
- Correct-but-missing requests are fetched from their ackers after a few
  ticks, refetched on timeout, and ACKs are rebroadcast with linear backoff
  so a stalled client's request eventually reaches everyone.

Deliberate deviations from the reference:
- digests are replayed in true byte order on reinitialize (the reference's
  comparator at client_tracker.go:759-761 compares indices, not values,
  yielding map-order nondeterminism);
- the committed-mask bit during window rebuild is read at
  ``req_no - high_state.low_watermark`` — correct for any low/high state
  pair, where the reference's ``i + high_offset`` (client_tracker.go:1109)
  is only right because it always passes the same state for both;
- a fully consumed client window is re-extended at the checkpoint boundary
  instead of stalling (see Client.allocate).
"""

from __future__ import annotations

from dataclasses import dataclass

from .. import pb
from ..obsv import hooks
from .actions import Actions
from .msgbuffers import Applyable, MsgBuffer, NodeBuffers
from .persisted import Persisted
from .preimage import request_hash_data
from .quorum import bit_is_set, intersection_quorum, make_bitmask, mask_ids, set_bit, some_correct_quorum

_NULL = b""  # digest key of the null request

# Shared no-op result for hot paths; MUST never be mutated (callers only
# ever concat it into their own Actions).
from .actions import EMPTY_ACTIONS as _EMPTY_ACTIONS  # noqa: E402  (shared hot-path empty)

_CORRECT_FETCH_TICKS = 4
_FETCH_TIMEOUT_TICKS = 4
_ACK_RESEND_TICKS = 20


# ---------------------------------------------------------------------------
# Stable lists: append-only linked lists whose iterators survive removal of
# elements by other iterators (reference: client_tracker.go:117-284).  The
# proposer holds a long-lived iterator over the ready list across GC.
# ---------------------------------------------------------------------------


class _StableNode:
    __slots__ = ("value", "next")

    def __init__(self, value=None):
        self.value = value
        self.next = None


_TOMBSTONE = object()


class StableList:
    """Singly linked append-only list.  Removal tombstones the node; the
    next traversal splices tombstone runs out of the chain so they can be
    collected.  Live iterators already holding a spliced node keep walking
    its ``next`` chain; fresh iterators never see it."""

    def __init__(self):
        self._head = _StableNode()  # sentinel
        self._tail = self._head

    def push_back(self, value) -> None:
        node = _StableNode(value)
        self._tail.next = node
        self._tail = node

    def iterator(self) -> "StableIterator":
        return StableIterator(self, self._head)


class StableIterator:
    def __init__(self, lst: StableList, start: _StableNode):
        self._list = lst
        self._prev = start  # last node we returned (or sentinel)

    def _peek(self):
        node = self._prev.next
        while node is not None and node.value is _TOMBSTONE:
            node = node.next
        if node is not self._prev.next and self._prev.value is not _TOMBSTONE:
            # Splice the tombstone run out of the chain so the nodes can be
            # collected (tombstoning alone leaks one node per removed
            # request, forever).  Safe for concurrent iterators: a spliced
            # node keeps its own ``next`` pointer, so anyone parked on it
            # rejoins the live chain here.  Only a live anchor may splice —
            # a tombstoned ``_prev`` may itself already be off-chain, and
            # writing through it (or pointing ``_tail`` at it) would orphan
            # the suffix.
            if node is None:
                self._list._tail = self._prev
            self._prev.next = node
        return node

    def has_next(self) -> bool:
        return self._peek() is not None

    def next(self):
        node = self._peek()
        if node is None:
            raise StopIteration
        self._prev = node
        return node.value

    def remove_last(self) -> None:
        """Tombstone the element most recently returned by next()."""
        self._prev.value = _TOMBSTONE


class ReadyList:
    """Requests with a strong cert that we hold locally, in discovery order
    — the proposer's input queue."""

    def __init__(self):
        self._list = StableList()

    def push_back(self, crn: "ClientReqNo") -> None:
        self._list.push_back(crn)

    def iterator(self) -> StableIterator:
        return self._list.iterator()

    def garbage_collect(self, seq_no: int) -> None:
        it = self._list.iterator()
        while it.has_next():
            crn = it.next()
            if crn.committed is not None and crn.committed <= seq_no:
                it.remove_last()


class AvailableList:
    """Requests with f+1 ACKs whose data we hold (correct + persisted)."""

    def __init__(self):
        self._list = StableList()

    def push_back(self, cr: "ClientRequest") -> None:
        self._list.push_back(cr)

    def iterator(self) -> StableIterator:
        return self._list.iterator()

    def garbage_collect(self, _seq_no: int) -> None:
        it = self._list.iterator()
        while it.has_next():
            if it.next().garbage:
                it.remove_last()


# ---------------------------------------------------------------------------
# Per-request state
# ---------------------------------------------------------------------------


class ClientRequest:
    """One candidate request (digest) for a (client, req_no).

    ``agreements`` is the node-id bitmask of ackers (bit i = node i
    acked).  While the request is the canonical entry of a _FastAcks
    mirror slot, the mask lives in the mirror's uint64 limb arrays and
    the property reads/writes through — one storage, no sync loops; when
    detached (mirror dropped, conflict, GC) the value materializes back
    into ``_agreements``."""

    __slots__ = (
        "ack",
        "_agreements",
        "_owner",
        "_slot",
        "garbage",
        "stored",
        "fetching",
        "ticks_fetching",
        "ticks_correct",
    )

    def __init__(self, ack: pb.RequestAck, agreements: int = 0):
        self.ack = ack
        self._agreements = agreements
        self._owner = None  # the owning _FastAcks while mirrored
        self._slot = 0
        self.garbage = False  # some request for this (client, req_no) committed
        self.stored = False  # persisted locally
        self.fetching = False
        self.ticks_fetching = 0
        self.ticks_correct = 0

    @property
    def agreements(self) -> int:
        owner = self._owner
        if owner is None:
            return self._agreements
        return owner.combine_agree(self._slot)

    @agreements.setter
    def agreements(self, value: int) -> None:
        owner = self._owner
        if owner is None:
            self._agreements = value
        else:
            owner.set_agree(self._slot, value)

    def fetch(self) -> Actions:
        if self.fetching:
            return Actions()
        self.fetching = True
        self.ticks_fetching = 0
        return Actions().send(
            mask_ids(self.agreements),
            pb.Msg(
                type=pb.FetchRequest(
                    client_id=self.ack.client_id,
                    req_no=self.ack.req_no,
                    digest=self.ack.digest,
                )
            ),
        )


class ClientReqNo:
    """ACK accumulation and correctness determination for one (client,
    req_no) (reference: client_tracker.go:711-1016; the doc comment there
    explains the null-request byzantine fallback)."""

    __slots__ = (
        "client_id",
        "req_no",
        "valid_after_seq_no",
        "network_config",
        "committed",
        "_non_null_voters",
        "_nn_owner",
        "_nn_slot",
        "requests",
        "weak_requests",
        "strong_requests",
        "my_requests",
        "acks_sent",
        "ticks_since_ack",
        "_weak_quorum",
        "_strong_quorum",
    )

    def __init__(
        self,
        client_id: int,
        req_no: int,
        valid_after_seq_no: int,
        network_config: pb.NetworkConfig | None = None,
        committed: int | None = None,
    ):
        self.client_id = client_id
        self.req_no = req_no
        self.valid_after_seq_no = valid_after_seq_no
        self.network_config = network_config
        self.committed = committed
        # Non-null-voter bitmask; like ClientRequest.agreements it lives in
        # the _FastAcks limb arrays while this req_no is a canonical
        # mirror slot and reads/writes through the property.
        self._nn_owner = None
        self._nn_slot = 0
        self.non_null_voters = 0  # bitmask over node id
        self.requests: dict[bytes, ClientRequest] = {}  # all observed
        self.weak_requests: dict[bytes, ClientRequest] = {}  # f+1 correct
        self.strong_requests: dict[bytes, ClientRequest] = {}  # 2f+1
        self.my_requests: dict[bytes, ClientRequest] = {}  # persisted locally
        self.acks_sent = 0
        self.ticks_since_ack = 0
        # Cached quorum thresholds: recomputing them per ack dominated the
        # ladder profile (the ack fan-in is the hottest path in the core).
        if network_config is not None:
            self._weak_quorum = some_correct_quorum(network_config)
            self._strong_quorum = intersection_quorum(network_config)
        else:
            # Set by reinitialize() before any ack can be applied.
            self._weak_quorum = self._strong_quorum = None

    @property
    def non_null_voters(self) -> int:
        owner = self._nn_owner
        if owner is None:
            return self._non_null_voters
        return owner.combine_nonnull(self._nn_slot)

    @non_null_voters.setter
    def non_null_voters(self, value: int) -> None:
        owner = self._nn_owner
        if owner is None:
            self._non_null_voters = value
        else:
            owner.set_nonnull(self._nn_slot, value)

    def reinitialize(self, network_config: pb.NetworkConfig) -> None:
        self.network_config = network_config
        self._weak_quorum = some_correct_quorum(network_config)
        self._strong_quorum = intersection_quorum(network_config)
        old_requests = self.requests
        self.non_null_voters = 0
        self.requests = {}
        self.weak_requests = {}
        self.strong_requests = {}
        self.my_requests = {}

        for digest in sorted(old_requests):
            old_req = old_requests[digest]
            for node_id in network_config.nodes:
                if old_req.agreements & (1 << node_id):
                    self.apply_request_ack(node_id, old_req.ack, force=True)
            if old_req.stored:
                new_req = self.client_req(old_req.ack)
                new_req.stored = True
                self.my_requests[digest] = new_req

    def client_req(self, ack: pb.RequestAck) -> ClientRequest:
        key = ack.digest or _NULL
        req = self.requests.get(key)
        if req is None:
            req = ClientRequest(ack=ack)
            self.requests[key] = req
        return req

    def apply_request_digest(
        self, ack: pb.RequestAck, data: bytes, out: Actions | None = None
    ) -> Actions:
        """Our own verified copy of the request (via Propose hash or a
        verified forward): persist it and ACK it to the network.  ``out``
        lets the hot result-processing loop accumulate into one Actions
        instead of allocating + concatenating one per request."""
        actions = out if out is not None else Actions()
        if ack.digest in self.my_requests:
            # Race between a forward and a local proposal; already persisted.
            return actions

        req = self.client_req(ack)
        req.stored = True
        self.my_requests[ack.digest] = req

        actions.store_request(
            pb.ForwardRequest(request_ack=ack, request_data=data)
        )

        if len(self.my_requests) == 1:
            self.acks_sent = 1
            self.ticks_since_ack = 0
            return actions.send(
                self.network_config.nodes, pb.Msg(type=ack)
            )

        # Multiple distinct requests persisted → advocate the null request.
        if _NULL in self.my_requests:
            return actions  # already advocating

        null_ack = pb.RequestAck(client_id=self.client_id, req_no=self.req_no)
        null_req = self.client_req(null_ack)
        null_req.stored = True
        self.my_requests[_NULL] = null_req
        self.acks_sent = 1
        self.ticks_since_ack = 0
        return actions.send(
            self.network_config.nodes, pb.Msg(type=null_ack)
        ).store_request(pb.ForwardRequest(request_ack=null_ack))

    def apply_request_ack(
        self, source: int, ack: pb.RequestAck, force: bool = False
    ) -> None:
        """Count one node's ACK.  A node gets one non-null vote ever (the
        spam guard from the design essay — the reference documents this but
        leaves its live path unguarded, client_tracker.go:379), except when
        ``force`` marks the digest known-correct (weak quorum during
        three-phase commit, or epoch change)."""
        requests = self.requests
        bit = 1 << source
        if ack.digest:
            key = ack.digest
            if not force and self.non_null_voters & bit:
                existing = requests.get(key)
                if existing is None or not existing.agreements & bit:
                    return  # second distinct non-null vote: ignored
            self.non_null_voters |= bit
        else:
            key = _NULL

        req = requests.get(key)
        if req is None:
            req = ClientRequest(ack=ack)
            requests[key] = req
        agreements = req.agreements | bit
        req.agreements = agreements

        count = agreements.bit_count()
        if count < self._weak_quorum:
            return
        self.weak_requests[key] = req
        if count < self._strong_quorum:
            return
        self.strong_requests[key] = req

    def tick(self) -> Actions:
        if self.committed is not None:
            # Hot path: every live reqNo of every client ticks every tick;
            # the shared empty saves ~1M allocations on ladder-scale runs.
            # Callers only concat tick results (never mutate them).
            return _EMPTY_ACTIONS
        if not self.my_requests and not self.weak_requests:
            # Acks below the weak quorum and nothing held locally: no
            # section of the tick logic can fire (rebroadcast requires
            # acks_sent > 0, which implies a held request).
            return _EMPTY_ACTIONS

        my = self.my_requests
        weak = self.weak_requests
        actions = None
        n_weak = len(weak)

        # 1. Conflicting correct requests and no commit → promote null.
        if n_weak > 1 and _NULL not in my:
            null_ack = pb.RequestAck(
                client_id=self.client_id, req_no=self.req_no
            )
            null_req = self.client_req(null_ack)
            null_req.stored = True
            my[_NULL] = null_req
            self.acks_sent = 1
            self.ticks_since_ack = 0
            actions = Actions().send(
                self.network_config.nodes, pb.Msg(type=null_ack)
            ).store_request(pb.ForwardRequest(request_ack=null_ack))

        # 2+3. Fetch machinery — only when some correct request is not
        # held locally or has a fetch in flight (in the steady state every
        # weak request is stored and this whole block is one scan).
        needs_fetch_scan = False
        for cr in weak.values():
            if (not cr.stored) or cr.fetching:
                needs_fetch_scan = True
                break
        if needs_fetch_scan:
            if actions is None:
                actions = Actions()
            # 2. Exactly one correct request we don't hold: fetch it after
            # a few ticks of patience.
            if n_weak == 1:
                (cr,) = weak.values()
                if not cr.stored and not cr.fetching:
                    if cr.ticks_correct <= _CORRECT_FETCH_TICKS:
                        cr.ticks_correct += 1
                    else:
                        actions.concat(cr.fetch())
            # 3. Refetch correct requests whose fetch timed out.
            to_fetch = []
            for cr in weak.values():
                if not cr.fetching:
                    continue
                if cr.ticks_fetching <= _FETCH_TIMEOUT_TICKS:
                    cr.ticks_fetching += 1
                    continue
                cr.fetching = False
                to_fetch.append(cr)
            to_fetch.sort(key=lambda cr: cr.ack.digest, reverse=True)
            for cr in to_fetch:
                actions.concat(cr.fetch())

        # 4. Rebroadcast our ACK with linear backoff.
        acks_sent = self.acks_sent
        if acks_sent == 0:
            return actions if actions is not None else _EMPTY_ACTIONS
        if self.ticks_since_ack != acks_sent * _ACK_RESEND_TICKS:
            self.ticks_since_ack += 1
            return actions if actions is not None else _EMPTY_ACTIONS

        n_my = len(my)
        if n_my > 1:
            ack = my[_NULL].ack
        elif n_my == 1:
            (only,) = my.values()
            ack = only.ack
        else:
            raise AssertionError("acks sent but no request held")

        self.acks_sent = acks_sent + 1
        self.ticks_since_ack = 0
        if actions is None:
            actions = Actions()
        actions.send(self.network_config.nodes, pb.Msg(type=ack))
        return actions


# ---------------------------------------------------------------------------
# Columnar ack fast path
# ---------------------------------------------------------------------------

# One-deep cache of the last frame's column decomposition, keyed by the
# msgs list object.  The engine delivers one coalesced frame to many
# receivers back to back; holding a strong reference to the list keeps the
# identity check sound.
_FRAME_COLS: list = [None, None]


def _frame_columns(msgs: list):
    """msgs -> (client_ids int64[n], req_nos int64[n], digest matrix
    uint8[n, 32], irregular row indices or None).  Rows whose digest is
    not 32 bytes (null acks) zero-fill the matrix and appear in
    ``irregular`` so the vector path routes them to the fallback."""
    cached = _FRAME_COLS
    if cached[0] is msgs:
        return cached[1]
    import numpy as np

    n = len(msgs)
    ids = np.empty(n, dtype=np.int64)
    rnos = np.empty(n, dtype=np.int64)
    digs = [None] * n
    irregular = None
    for i, msg in enumerate(msgs):
        ack = msg.type
        ids[i] = ack.client_id
        rnos[i] = ack.req_no
        d = ack.digest
        if len(d) != 32:
            d = b"\x00" * 32
            if irregular is None:
                irregular = []
            irregular.append(i)
        digs[i] = d
    dig_mat = np.frombuffer(b"".join(digs), dtype=np.uint8).reshape(n, 32)
    cols = (ids, rnos, dig_mat, irregular)
    cached[0] = msgs
    cached[1] = cols
    return cols


class _FastAcks:
    """Vectorized mirror of every client window's ack-certificate state.

    The ack fan-in is the hottest loop in the framework: every request
    draws one RequestAck from every node at every node — O(n^2)
    applications per request, arriving in coalesced frames of thousands.
    This mirror lets ``step_ack_many`` apply a whole frame as a handful
    of numpy ops (bitwise OR + popcount over uint64 masks, digest
    equality over a (slots, 32) byte matrix) instead of ~12 dict/attr
    operations per ack.

    Authority contract: the arrays are authoritative only INSIDE one
    ``step_ack_many`` call — every row it changes is written back to the
    owning ``ClientRequest``/``ClientReqNo`` objects before returning, so
    all other code keeps reading and mutating objects exactly as before.
    Paths that mutate ack state elsewhere refresh the touched slot
    (``refresh``) or drop the whole mirror (``ClientTracker._fast =
    None``; it lazily rebuilds).  Window-structure changes (checkpoint
    allocation, GC, reinitialize) drop it.

    Only configs whose node ids fit a uint64 mask (< 64) build a mirror;
    larger networks keep the plain loop.

    Per-slot flags: COMMITTED (drop acks early), SLOW (anything the
    vector path cannot express: missing slot, conflicting digests, a
    null request, or no canonical digest yet — those rows take the
    original per-ack path and the slot refreshes afterwards).
    """

    COMMITTED = 1
    SLOW = 2

    __slots__ = (
        "limbs",
        "cid0",
        "n_clients",
        "offset_arr",
        "base_arr",
        "low_arr",
        "high_arr",
        "nrm_arr",
        "clients",
        "client_of",
        "agree",
        "nonnull",
        "flags",
        "canon_mat",
        "canon_ok",
        "canon_req",
        "canon_crn",
        "tick_dirty",
        "tick_class",
        "tsa",
        "tgt",
        "canon_mat_dirty",
        "weak_q",
        "strong_q",
    )

    def __init__(self, tracker: "ClientTracker"):
        import numpy as np

        # uint64 limbs per node-id mask (limb i covers ids [64i, 64i+64)).
        self.limbs = tracker._mask_limbs
        clients = tracker.clients
        cids = sorted(clients)
        self.cid0 = cids[0]
        self.n_clients = cids[-1] - cids[0] + 1
        # Dense index over [cid0, cid_last]; ids outside or in gaps resolve
        # to a sentinel client slot with an empty window (rows fall back).
        self.offset_arr = np.zeros(self.n_clients + 1, dtype=np.int64)
        self.base_arr = np.zeros(self.n_clients + 1, dtype=np.int64)
        self.low_arr = np.zeros(self.n_clients + 1, dtype=np.int64)
        self.high_arr = np.full(self.n_clients + 1, -1, dtype=np.int64)
        self.nrm_arr = np.full(self.n_clients + 1, -1, dtype=np.int64)
        self.clients: list = [None] * (self.n_clients + 1)

        total = 0
        metas = []
        for cid in cids:
            client = clients[cid]
            ci = cid - self.cid0
            size = client.high_watermark - client.low_watermark + 1
            self.offset_arr[ci] = total
            self.base_arr[ci] = client.low_watermark
            self.low_arr[ci] = client.low_watermark
            self.high_arr[ci] = client.high_watermark
            self.nrm_arr[ci] = client.next_ready_mark
            self.clients[ci] = client
            metas.append((client, ci, total, size))
            total += size

        self.agree = np.zeros((total, self.limbs), dtype=np.uint64)
        self.nonnull = np.zeros((total, self.limbs), dtype=np.uint64)
        self.flags = np.zeros(total, dtype=np.uint8)
        self.canon_mat = np.zeros((total, 32), dtype=np.uint8)
        self.canon_ok = np.zeros(total, dtype=bool)
        self.canon_req: list = [None] * total
        self.canon_crn: list = [None] * total
        self.client_of = np.zeros(total, dtype=np.int64)
        # Slots whose ack activity has not yet been pushed into the owning
        # client's _tick_pending set (drained lazily at tick time — the
        # per-ack set.add was a measurable fraction of the old loop).
        self.tick_dirty = np.zeros(total, dtype=bool)
        # Vectorized tick state: INERT slots cannot fire, STEADY slots only
        # advance the rebroadcast backoff counter (held authoritatively in
        # ``tsa`` between syncs; ``crn.tick`` is its only reader and gets a
        # sync immediately before any call), PYTHON slots (fetch machinery
        # in motion, pending null promotion) take the per-slot path.
        self.tick_class = np.zeros(total, dtype=np.uint8)
        self.tsa = np.zeros(total, dtype=np.int64)
        self.tgt = np.zeros(total, dtype=np.int64)
        # Deferred canonical-digest rows: writing one 32-byte canon_mat row
        # per refresh costs ~1.2us in frombuffer+scatter; batching them into
        # one fancy-indexed write at the next vector read halves the
        # per-refresh cost on the hot store-request path.
        self.canon_mat_dirty: list = []

        nc = tracker.network_config
        self.weak_q = some_correct_quorum(nc)
        self.strong_q = intersection_quorum(nc)

        # Bulk build: gather per-slot values into Python lists and assign
        # each column once (per-element numpy scalar writes made the
        # per-slot _refresh_slot ~6x slower at build scale).
        agree_l = [0] * total
        nonnull_l = [0] * total
        flags_l = [0] * total
        dig_l = [b"\x00" * 32] * total
        ok_l = [False] * total
        tick_l = [0] * total
        tsa_l = [0] * total
        tgt_l = [0] * total
        attach_list = []
        canon_req = self.canon_req
        canon_crn = self.canon_crn
        for client, ci, offset, size in metas:
            base = client.low_watermark
            req_no_map = client.req_no_map
            self.client_of[offset : offset + size] = ci
            for i in range(size):
                slot = offset + i
                crn = req_no_map.get(base + i)
                if crn is None:
                    flags_l[slot] = self.SLOW
                    continue
                canon_crn[slot] = crn
                if crn.committed is not None:
                    flags_l[slot] = self.COMMITTED
                    continue
                requests = crn.requests
                if len(requests) == 1 and _NULL not in requests:
                    (digest,) = requests
                    req = requests[digest]
                    dig_l[slot] = digest
                    ok_l[slot] = True
                    canon_req[slot] = req
                    agree_l[slot] = req.agreements
                    nonnull_l[slot] = crn.non_null_voters
                    attach_list.append((slot, req, crn))
                else:
                    flags_l[slot] = self.SLOW
                tick_cls = self._classify_tick(crn)
                tick_l[slot] = tick_cls
                if tick_cls == self.TICK_STEADY:
                    tsa_l[slot] = crn.ticks_since_ack
                    tgt_l[slot] = crn.acks_sent * _ACK_RESEND_TICKS
        mask64 = (1 << 64) - 1
        for limb in range(self.limbs):
            shift = 64 * limb
            self.agree[:, limb] = [(v >> shift) & mask64 for v in agree_l]
            self.nonnull[:, limb] = [
                (v >> shift) & mask64 for v in nonnull_l
            ]
        self.flags[:] = flags_l
        self.canon_ok[:] = ok_l
        self.tick_class[:] = tick_l
        self.tsa[:] = tsa_l
        self.tgt[:] = tgt_l
        self.canon_mat[:] = np.frombuffer(
            b"".join(dig_l), dtype=np.uint8
        ).reshape(total, 32)
        # Attach canonical objects to their slots (arrays already seeded
        # by the column writes above): their mask properties now read and
        # write through this mirror.
        for slot, req, crn in attach_list:
            req._owner = self
            req._slot = slot
            crn._nn_owner = self
            crn._nn_slot = slot

    def combine_agree(self, slot: int) -> int:
        if self.limbs == 1:
            return int(self.agree[slot, 0])
        value = 0
        for limb in range(self.limbs - 1, -1, -1):
            value = (value << 64) | int(self.agree[slot, limb])
        return value

    def set_agree(self, slot: int, value: int) -> None:
        if self.limbs == 1:
            self.agree[slot, 0] = value
            return
        mask64 = (1 << 64) - 1
        for limb in range(self.limbs):
            self.agree[slot, limb] = (value >> (64 * limb)) & mask64

    def combine_nonnull(self, slot: int) -> int:
        if self.limbs == 1:
            return int(self.nonnull[slot, 0])
        value = 0
        for limb in range(self.limbs - 1, -1, -1):
            value = (value << 64) | int(self.nonnull[slot, limb])
        return value

    def set_nonnull(self, slot: int, value: int) -> None:
        if self.limbs == 1:
            self.nonnull[slot, 0] = value
            return
        mask64 = (1 << 64) - 1
        for limb in range(self.limbs):
            self.nonnull[slot, limb] = (value >> (64 * limb)) & mask64

    def _attach(self, slot: int, req, crn) -> None:
        """Make this mirror slot the storage for the canonical request's
        agreements and the crn's non-null-voter mask (the properties on
        those objects read/write through while attached)."""
        if req._owner is not self or req._slot != slot:
            value = req._agreements if req._owner is None else req.agreements
            req._owner = self
            req._slot = slot
            self.set_agree(slot, value)
        if crn._nn_owner is not self or crn._nn_slot != slot:
            value = (
                crn._non_null_voters
                if crn._nn_owner is None
                else crn.non_null_voters
            )
            crn._nn_owner = self
            crn._nn_slot = slot
            self.set_nonnull(slot, value)

    def _detach(self, slot: int) -> None:
        req = self.canon_req[slot]
        if req is not None and req._owner is self and req._slot == slot:
            req._agreements = self.combine_agree(slot)
            req._owner = None
        crn = self.canon_crn[slot]
        if (
            crn is not None
            and crn._nn_owner is self
            and crn._nn_slot == slot
        ):
            crn._non_null_voters = self.combine_nonnull(slot)
            crn._nn_owner = None

    def detach_all(self) -> None:
        """Materialize every attached mask back into its object (before
        the mirror is dropped or rebuilt)."""
        for slot in range(len(self.canon_req)):
            self._detach(slot)

    def drain_tick_dirty(self) -> None:
        """Push deferred ack activity into the clients' _tick_pending sets
        (must run before any tick iteration and before the mirror drops)."""
        import numpy as np

        idx = np.flatnonzero(self.tick_dirty)
        if not len(idx):
            return
        self.tick_dirty[idx] = False
        clients = self.clients
        offset_arr = self.offset_arr
        base_arr = self.base_arr
        client_of = self.client_of
        for slot in idx.tolist():
            ci = client_of[slot]
            clients[ci]._tick_pending.add(
                int(base_arr[ci]) + slot - int(offset_arr[ci])
            )

    def slot_of(self, client_id: int, req_no: int) -> int | None:
        ci = client_id - self.cid0
        if not (0 <= ci < self.n_clients):
            return None
        if not (self.low_arr[ci] <= req_no <= self.high_arr[ci]):
            return None
        return int(self.offset_arr[ci]) + req_no - int(self.base_arr[ci])

    # Tick classes (see the tick_class array comment above).
    TICK_INERT = 0
    TICK_STEADY = 1
    TICK_PYTHON = 2

    def refresh(
        self, client_id: int, req_no: int, tick_obj_authoritative: bool = False
    ) -> None:
        """Re-derive one slot's mirror from the authoritative objects.

        ``tick_obj_authoritative``: the caller just mutated the crn's tick
        counters (ticks_since_ack/acks_sent), so skip the array→object
        writeback that normally preserves a STEADY slot's advanced backoff
        counter."""
        slot = self.slot_of(client_id, req_no)
        if slot is None:
            return
        ci = client_id - self.cid0
        client = self.clients[ci]
        self._refresh_slot(
            slot,
            client.req_no_map.get(req_no),
            tick_obj_authoritative=tick_obj_authoritative,
        )

    def _refresh_slot(
        self,
        slot: int,
        crn: "ClientReqNo | None",
        tick_obj_authoritative: bool = False,
    ) -> None:
        # For STEADY slots the backoff counter lives in the array between
        # syncs; push it back before re-deriving from the object (unless
        # the caller just wrote a newer value there).
        if (
            not tick_obj_authoritative
            and self.tick_class[slot] == self.TICK_STEADY
        ):
            old_crn = self.canon_crn[slot]
            if old_crn is not None:
                old_crn.ticks_since_ack = int(self.tsa[slot])

        if crn is None:
            self._detach(slot)
            self.flags[slot] = self.SLOW
            self.canon_crn[slot] = None
            self.canon_req[slot] = None
            self.canon_ok[slot] = False
            self.tick_class[slot] = self.TICK_INERT
            return
        requests = crn.requests
        canonical = (
            crn.committed is None
            and len(requests) == 1
            and _NULL not in requests
        )
        if canonical:
            (digest,) = requests
            req = requests[digest]
            old_req = self.canon_req[slot]
            if old_req is not None and old_req is not req:
                self._detach(slot)
            self.canon_crn[slot] = crn
            self.canon_mat_dirty.append((slot, digest))
            self.canon_ok[slot] = True
            self.canon_req[slot] = req
            # The slot becomes (or stays) the live storage for the masks;
            # the objects' properties read/write through it.
            self._attach(slot, req, crn)
            self.flags[slot] = 0
        else:
            # Committed, no votes yet (first ack adopts its digest via the
            # per-row fallback, which then refreshes this slot), or
            # conflicting digests / a null request in play: masks move
            # back to the objects.
            self._detach(slot)
            self.canon_crn[slot] = crn
            self.canon_ok[slot] = False
            self.canon_req[slot] = None
            if crn.committed is not None:
                self.flags[slot] = self.COMMITTED
                self.tick_class[slot] = self.TICK_INERT
                return
            self.flags[slot] = self.SLOW
        self.tick_class[slot] = self._classify_tick(crn)
        if self.tick_class[slot] == self.TICK_STEADY:
            self.tsa[slot] = crn.ticks_since_ack
            self.tgt[slot] = crn.acks_sent * _ACK_RESEND_TICKS

    def flush_canon_rows(self) -> None:
        """Apply deferred canonical-digest rows (one batched write)."""
        dirty = self.canon_mat_dirty
        if not dirty:
            return
        import numpy as np

        self.canon_mat_dirty = []
        slots = np.fromiter(
            (s for s, _d in dirty), dtype=np.int64, count=len(dirty)
        )
        rows = np.frombuffer(
            b"".join(d for _s, d in dirty), dtype=np.uint8
        ).reshape(len(dirty), 32)
        # Later entries for the same slot win (list order == apply order).
        self.canon_mat[slots] = rows

    def _classify_tick(self, crn: "ClientReqNo") -> int:
        """Mirror of ClientReqNo.tick's control flow (that method stays the
        semantic reference): which slots can the vectorized tick skip or
        batch-advance?"""
        my = crn.my_requests
        weak = crn.weak_requests
        if not my and not weak:
            return self.TICK_INERT
        if len(weak) > 1 and _NULL not in my:
            return self.TICK_PYTHON  # null promotion pending
        for cr in weak.values():
            if (not cr.stored) or cr.fetching:
                return self.TICK_PYTHON  # fetch machinery in motion
        if crn.acks_sent == 0:
            return self.TICK_INERT  # nothing held: rebroadcast gate closed
        return self.TICK_STEADY

    def writeback_tick(self) -> None:
        """Sync every STEADY slot's array-held backoff counter back to its
        crn (before the mirror drops or the python tick path runs)."""
        import numpy as np

        idx = np.flatnonzero(self.tick_class == self.TICK_STEADY)
        canon_crn = self.canon_crn
        tsa = self.tsa
        for s in idx.tolist():
            crn = canon_crn[s]
            if crn is not None:
                crn.ticks_since_ack = int(tsa[s])

    def mark_committed(self, client_id: int, req_no: int) -> None:
        slot = self.slot_of(client_id, req_no)
        if slot is not None:
            self.flags[slot] = self.COMMITTED
            self.tick_class[slot] = self.TICK_INERT


# ---------------------------------------------------------------------------
# Per-client window
# ---------------------------------------------------------------------------


@dataclass
class ClientWaiter:
    """Watermark snapshot the runtime uses to backpressure proposers; a new
    waiter is issued whenever the window moves and the old one is marked
    expired (the runtime layer maps this onto real synchronization)."""

    low_watermark: int
    high_watermark: int
    expired: bool = False


class Client:
    __slots__ = (
        "logger",
        "client_state",
        "network_config",
        "low_watermark",
        "high_watermark",
        "next_ready_mark",
        "req_no_map",
        "client_waiter",
        "_tick_pending",
    )

    def __init__(self, logger=None):
        self.logger = logger
        self.client_state: pb.NetworkClient | None = None
        self.network_config: pb.NetworkConfig | None = None
        self.low_watermark = 0
        self.high_watermark = 0
        self.next_ready_mark = 0
        self.req_no_map: dict[int, ClientReqNo] = {}
        self.client_waiter: ClientWaiter | None = None
        # req_nos with tick-relevant activity (acks observed or a local
        # copy held).  Untouched window slots — the vast majority at any
        # instant — are skipped by tick() entirely; entries are discarded
        # lazily once committed or garbage collected.
        self._tick_pending: set = set()

    def req_nos(self):
        """All live ClientReqNos in req_no order."""
        return [
            self.req_no_map[r]
            for r in range(self.low_watermark, self.high_watermark + 1)
            if r in self.req_no_map
        ]

    def reinitialize(
        self,
        network_config: pb.NetworkConfig,
        low_seq_no: int,
        high_seq_no: int,
        low_state: pb.NetworkClient,
        high_state: pb.NetworkClient,
    ) -> None:
        """Rebuild the window from the low/high CEntry pair: [low_state's
        watermark, +width], marking req_nos the high state knows committed
        (below its watermark or set in its committed mask), and gating the
        tail of the window (width consumed last checkpoint) on the next
        checkpoint (reference: client_tracker.go:1081-1144)."""
        low_watermark = low_state.low_watermark
        width = low_state.width

        old_map = self.req_no_map
        self.client_state = high_state
        self.network_config = network_config
        self.low_watermark = low_watermark
        self.high_watermark = low_watermark + width
        self.next_ready_mark = low_watermark
        self.req_no_map = {}
        if self.client_waiter is not None:
            self.client_waiter.expired = True
        self.client_waiter = ClientWaiter(
            low_watermark=self.low_watermark,
            high_watermark=self.high_watermark,
        )

        for i in range(width + 1):
            req_no = low_watermark + i

            committed = None
            # Fix vs reference (see module docstring): the high state's mask
            # is indexed relative to the high state's own low watermark.
            mask_idx = req_no - high_state.low_watermark
            if req_no < high_state.low_watermark or (
                mask_idx >= 0
                and bit_is_set(high_state.committed_mask, mask_idx)
            ):
                committed = high_seq_no  # conservatively GC-able later

            if i <= width - low_state.width_consumed_last_checkpoint:
                valid_after = low_seq_no
            else:
                valid_after = low_seq_no + network_config.checkpoint_interval

            crn = old_map.get(req_no)
            if crn is not None:
                crn.committed = committed
            else:
                crn = ClientReqNo(
                    client_id=low_state.id,
                    req_no=req_no,
                    valid_after_seq_no=valid_after,
                    committed=committed,
                )
            crn.reinitialize(network_config)
            self.req_no_map[req_no] = crn

        self._tick_pending = {
            req_no
            for req_no, crn in self.req_no_map.items()
            if crn.committed is None
            and (crn.my_requests or crn.weak_requests or crn.requests)
        }

    def allocate(self, starting_at_seq_no: int, state: pb.NetworkClient) -> None:
        """Extend the window at a checkpoint boundary; the newly usable tail
        only becomes proposable after the *next* checkpoint (reference:
        client_tracker.go:1146-1175).  Allocation starts from our current
        high watermark rather than the reference's intermediate-watermark
        arithmetic: equivalent in the partial-commit case, and it also
        re-extends a *fully* consumed window (where the reference stalls —
        its all-committed branch at client_tracker.go:507-517 never
        allocates, and its own assert would reject the state if it did)."""
        new_high = state.low_watermark + state.width
        if new_high < self.high_watermark:
            raise AssertionError(
                f"window must not shrink: new high {new_high} < current "
                f"high {self.high_watermark}"
            )

        for req_no in range(self.high_watermark + 1, new_high + 1):
            crn = ClientReqNo(
                client_id=state.id,
                req_no=req_no,
                valid_after_seq_no=starting_at_seq_no
                + self.network_config.checkpoint_interval,
                network_config=self.network_config,
            )
            self.req_no_map[req_no] = crn

        self.high_watermark = new_high
        self.client_waiter.expired = True
        self.client_waiter = ClientWaiter(
            low_watermark=self.low_watermark,
            high_watermark=self.high_watermark,
        )

    def move_low_watermark(self, max_seq_no: int) -> None:
        for req_no in range(self.low_watermark, self.high_watermark + 1):
            crn = self.req_no_map.get(req_no)
            if crn is None:
                continue
            if crn.committed is None or crn.committed > max_seq_no:
                break
            if crn.req_no >= self.next_ready_mark:
                # A request can commit without us ever marking it ready
                # (it was correct elsewhere); move the mark *past* it — it is
                # being garbage collected and can never become ready.  (The
                # reference sets the mark to req_no itself,
                # client_tracker.go:1187-1191, which strands the ready path
                # one slot behind and trips advanceReady's missing-req
                # assert after this entry is deleted.)
                self.next_ready_mark = crn.req_no + 1
            for cr in crn.requests.values():
                cr.garbage = True
            del self.req_no_map[req_no]
        self.low_watermark = min(self.req_no_map) if self.req_no_map else (
            self.high_watermark + 1
        )

    def ack(self, source: int, ack: pb.RequestAck, force: bool = False):
        """``force`` marks the digest known-correct (epoch-change batch
        selection), bypassing the one-non-null-vote spam guard."""
        crn = self.req_no_map.get(ack.req_no)
        if crn is None:
            raise AssertionError(
                f"client {ack.client_id}: ack for req_no {ack.req_no} outside "
                f"window [{self.low_watermark}, {self.high_watermark}]"
            )
        key = ack.digest or _NULL
        was_weak = key in crn.weak_requests
        crn.apply_request_ack(source, ack, force=force)
        newly_correct = not was_weak and key in crn.weak_requests
        self._tick_pending.add(ack.req_no)
        return crn.requests.get(key), crn, newly_correct

    def in_watermarks(self, req_no: int) -> bool:
        return self.low_watermark <= req_no <= self.high_watermark

    def req_no(self, req_no: int) -> ClientReqNo:
        crn = self.req_no_map.get(req_no)
        if crn is None:
            raise AssertionError(f"req_no {req_no} not tracked")
        return crn

    def tick(self) -> Actions:
        if not self._tick_pending:
            return _EMPTY_ACTIONS
        actions = None
        done = None
        for req_no in sorted(self._tick_pending):
            crn = self.req_no_map.get(req_no)
            if crn is None or crn.committed is not None:
                if done is None:
                    done = []
                done.append(req_no)
                continue
            crn_actions = crn.tick()
            if crn_actions is not _EMPTY_ACTIONS:
                if actions is None:
                    actions = crn_actions
                else:
                    actions.concat(crn_actions)
        if done is not None:
            self._tick_pending.difference_update(done)
        return actions if actions is not None else _EMPTY_ACTIONS


# ---------------------------------------------------------------------------
# The tracker
# ---------------------------------------------------------------------------


class ClientTracker:
    def __init__(
        self,
        persisted: Persisted,
        node_buffers: NodeBuffers,
        my_config: pb.InitialParameters,
        logger=None,
        ack_plane: str | None = None,
        ack_flush_rows: int | None = None,
    ):
        self.persisted = persisted
        self.node_buffers = node_buffers
        self.my_config = my_config
        self.logger = logger

        self.clients: dict[int, Client] = {}
        self.client_states: list = []
        self.network_config: pb.NetworkConfig | None = None
        self.msg_buffers: dict[int, MsgBuffer] = {}
        self.ready_list = ReadyList()
        self.available_list = AvailableList()
        # Columnar ack mirror (see _FastAcks), built lazily by
        # step_ack_many when the config supports it.
        self._fast: _FastAcks | None = None
        self._fast_ok = False
        self._mask_limbs = 1
        # Device-resident ack plane (core.device_tracker): selected via
        # Config.ack_plane / the MIRBFT_ACK_PLANE env knob, built lazily
        # by step_ack_many like the host mirror, dropped on any
        # window-structure change.
        from .device_tracker import resolve_ack_plane, resolve_flush_rows

        self._ack_plane = resolve_ack_plane(ack_plane)
        # Device-plane frame coalescing (Config.ack_flush_rows /
        # MIRBFT_ACK_FLUSH_ROWS): kernel flushes defer until this many
        # ack rows are queued; 1 keeps the flush-per-frame default.
        self._ack_flush_rows = resolve_flush_rows(ack_flush_rows)
        self._device = None
        self._device_ok = False

    def _drop_fast(self) -> None:
        """Invalidate the columnar mirror (draining deferred tick activity
        and syncing array-held backoff counters first so no rebroadcast/
        fetch bookkeeping is lost)."""
        if self._fast is not None:
            self._fast.drain_tick_dirty()
            self._fast.writeback_tick()
            self._fast.detach_all()
            self._fast = None

    def _drop_device(self) -> None:
        """Materialize the device ack plane back into the objects and
        discard it (window-structure changes invalidate its dense shapes,
        exactly like the host mirror)."""
        if self._device is not None:
            dev, self._device = self._device, None
            dev.drop(self)

    def _build_device(self):
        """Build the device plane lazily; any failure (jax missing, no
        usable device, platform init error) permanently falls back to the
        host path for this tracker incarnation."""
        from .device_tracker import DeviceClientPlane

        try:
            dev = DeviceClientPlane(self)
        except Exception:
            self._device_ok = False
            return None
        self._device = dev
        return dev

    # -- lifecycle -----------------------------------------------------------

    def reinitialize(self) -> None:
        self._drop_device()
        self._drop_fast()
        low_c = high_c = None

        def on_c(c_entry):
            nonlocal low_c, high_c
            if low_c is None:
                low_c = c_entry
            high_c = c_entry

        self.persisted.iterate({pb.CEntry: on_c})
        if low_c is None:
            raise AssertionError("log must contain a checkpoint")

        latest_states = {cs.id: cs for cs in high_c.network_state.clients}

        self.network_config = low_c.network_state.config
        self.available_list = AvailableList()
        self.ready_list = ReadyList()

        old_clients = self.clients
        self.clients = {}
        self.client_states = high_c.network_state.clients
        for client_state in self.client_states:
            client = old_clients.get(client_state.id) or Client(self.logger)
            self.clients[client_state.id] = client
            client.reinitialize(
                low_c.network_state.config,
                low_c.seq_no,
                high_c.seq_no,
                client_state,
                latest_states[client_state.id],
            )
            # Re-seed the fresh available list: correct (weak-quorum)
            # requests whose data we hold survived the reinitialize inside
            # the window but their list membership did not — without this,
            # sequences referencing requests disseminated *before* a
            # reconfiguration or state transfer can never match their
            # outstanding requests, and every post-reinitialize epoch
            # starves into suspicion.
            for req_no in range(
                client.low_watermark, client.high_watermark + 1
            ):
                crn = client.req_no_map.get(req_no)
                if crn is None or crn.committed is not None:
                    continue
                for digest in sorted(crn.weak_requests):
                    cr = crn.weak_requests[digest]
                    if cr.stored and not cr.garbage:
                        self.available_list.push_back(cr)
            self.advance_ready(client)

        old_buffers = self.msg_buffers
        self.msg_buffers = {}
        for node_id in low_c.network_state.config.nodes:
            buffer = old_buffers.get(node_id)
            if buffer is None:
                buffer = MsgBuffer(
                    "clients", self.node_buffers.node_buffer(node_id)
                )
            self.msg_buffers[node_id] = buffer

        # The vector ack path splits node-id masks into uint64 limbs
        # (one frame only ever touches its source's limb) and needs a
        # dense-ish client id range (the mirror indexes [cid0, cid_last]).
        nodes = self.network_config.nodes
        cids = [cs.id for cs in self.client_states]
        self._mask_limbs = ((max(nodes) >> 6) + 1) if nodes else 1
        self._fast_ok = bool(
            nodes
            and self._mask_limbs <= 8  # up to 512-node ids
            and cids
            and (max(cids) - min(cids) + 1) <= 4 * len(cids) + 1024
        )
        # The device plane shares the mirror's preconditions (dense-ish
        # ids, bounded node masks) and additionally needs a live jax
        # backend; absent one it cleanly stays on the host path.
        self._device_ok = False
        if self._ack_plane == "device" and self._fast_ok:
            from .device_tracker import device_plane_available

            self._device_ok = device_plane_available()

    def tick(self) -> Actions:
        dev = self._device
        if dev is not None:
            # Tick boundary: run the kernel over any coalesced frames
            # and drain the buffered boundary events — the scalar tick
            # logic below reads _tick_pending and object-side ack state,
            # both of which deferred flushing leaves behind.
            dev.flush(drain=self)
            # The scalar tick logic reads and mutates object-side ack
            # state (fetch targeting over agreements, rebroadcast
            # counters): hand every pending slot back to the objects
            # before it runs.
            for client_state in self.client_states:
                client = self.clients[client_state.id]
                for req_no in client._tick_pending:
                    dev.sync_slot(client_state.id, req_no)
        fast = self._fast
        if fast is not None:
            fast.drain_tick_dirty()
            return self._tick_vector(fast)
        actions = Actions()
        for client_state in self.client_states:
            actions.concat(self.clients[client_state.id].tick())
        return actions

    def _tick_vector(self, fast: "_FastAcks") -> Actions:
        """Vectorized tick sweep over the mirror: INERT slots skip, quiet
        STEADY slots advance their backoff counter in one array op, and
        only firing/PYTHON slots run ClientReqNo.tick (the semantic
        reference for all of this)."""
        import numpy as np

        steady = fast.tick_class == _FastAcks.TICK_STEADY
        fire = steady & (fast.tsa == fast.tgt)
        quiet = steady & ~fire
        fast.tsa[quiet] += 1

        todo = np.flatnonzero(
            fire | (fast.tick_class == _FastAcks.TICK_PYTHON)
        )
        if not len(todo):
            return _EMPTY_ACTIONS
        actions = None
        canon_crn = fast.canon_crn
        for s in todo.tolist():
            crn = canon_crn[s]
            if crn is None:
                continue
            if fire[s]:
                # The array held the authoritative backoff counter; sync it
                # so crn.tick sees the fire condition.
                crn.ticks_since_ack = int(fast.tsa[s])
            crn_actions = crn.tick()
            # crn.tick mutated its own counters (fire reset, fetch
            # progress, possibly a null promotion): re-derive the slot.
            fast._refresh_slot(s, crn, tick_obj_authoritative=True)
            if crn_actions is not _EMPTY_ACTIONS:
                if actions is None:
                    actions = crn_actions
                else:
                    actions.concat(crn_actions)
        return actions if actions is not None else _EMPTY_ACTIONS

    # -- message handling ----------------------------------------------------

    def filter(self, _source: int, msg: pb.Msg) -> Applyable:
        inner = msg.type
        cls = inner.__class__  # exact types only: pb classes have no subclasses
        if cls is pb.RequestAck:
            ack = inner
        elif cls is pb.ForwardRequest:
            ack = inner.request_ack
        elif cls is pb.FetchRequest:
            return Applyable.CURRENT
        else:
            raise AssertionError(
                f"unexpected client message {type(inner).__name__}"
            )
        client = self.clients.get(ack.client_id)
        if client is None:
            return Applyable.FUTURE  # client may appear via reconfiguration
        if client.low_watermark > ack.req_no:
            return Applyable.PAST
        if client.high_watermark < ack.req_no:
            return Applyable.FUTURE
        return Applyable.CURRENT

    def step_ack(self, source: int, msg: pb.Msg) -> Actions:
        """Fast path for RequestAck — the dominant message at ladder scale
        (n^2 per request).  Equivalent to step() with the filter/apply_msg/
        ack/Client.ack chain flattened into one frame; ``ack()`` below stays
        the semantic reference for this logic."""
        ack = msg.type
        client = self.clients.get(ack.client_id)
        if client is None:
            # Client may appear via reconfiguration: buffer as FUTURE.
            self.msg_buffers[source].store(msg)
            return _EMPTY_ACTIONS
        req_no = ack.req_no
        if req_no < client.low_watermark:
            return _EMPTY_ACTIONS
        if req_no > client.high_watermark:
            self.msg_buffers[source].store(msg)
            return _EMPTY_ACTIONS
        crn = client.req_no_map.get(req_no)
        if crn is None:
            raise AssertionError(
                f"client {ack.client_id}: req_no {req_no} missing inside "
                f"window [{client.low_watermark}, {client.high_watermark}]"
            )
        if crn.committed is not None:
            # Same late-ack drop as step_ack_many: the two delivery paths
            # must agree so node state never depends on transport framing.
            return _EMPTY_ACTIONS
        if self._device is not None:
            # Scalar mutation ahead: pull the device-authoritative masks
            # into the objects first (the slot stays host-authoritative
            # until the next device flush re-derives it).
            self._device.sync_slot(ack.client_id, req_no)
        key = ack.digest or _NULL
        weak = crn.weak_requests
        was_weak = key in weak
        crn.apply_request_ack(source, ack)
        client._tick_pending.add(req_no)
        if not was_weak and key in weak:
            self.available_list.push_back(crn.requests.get(key))
        if req_no == client.next_ready_mark and crn.strong_requests:
            self.check_ready(client, crn)
        if self._fast is not None:
            self._fast.refresh(ack.client_id, req_no)
        return _EMPTY_ACTIONS

    def step_ack_many(self, source: int, msgs: list) -> None:
        """Bulk form of step_ack for one inbound frame: identical semantics,
        per-frame rather than per-msg frame setup.  ``msgs`` must all carry
        RequestAck payloads.

        Large frames on vector-capable configs take the columnar path:
        the whole frame becomes numpy column arrays (cached on the frame,
        so the other receivers of the same coalesced delivery reuse them)
        and applies as bitwise OR + popcount over the _FastAcks mirror.
        Rows the mirror cannot express — unknown clients, out-of-window,
        null acks, conflicting digests, first vote for a slot — fall back
        to step_ack per row (note the fallback rows apply AFTER the
        vectorized rows rather than in strict frame-interleaved order;
        both orders are deterministic, and inter-row order within one
        frame was never a protocol guarantee)."""
        dev = self._device
        if dev is None and self._device_ok:
            dev = self._build_device()
        if dev is not None:
            # Device-resident plane: every frame (any size) goes through
            # the kernel — the scalar loop would mutate objects whose
            # masks are device-authoritative.  The plane emits its own
            # {plane="device"} ack metrics at flush.
            dev.apply_frame(self, source, msgs)
        elif len(msgs) >= 32 and self._fast_ok:
            fast = self._fast
            if fast is None:
                fast = self._fast = _FastAcks(self)
            self._step_ack_vector(source, msgs, fast)
        else:
            self._step_ack_loop(source, msgs)
        if dev is None and hooks.enabled:
            hooks.record_ack_batch("host", len(msgs))
        # Divergence oracle (obsv.shadow): every Nth frame replays the
        # scalar rules against the mirror for the slots this frame touched.
        sh = hooks.shadow
        if sh is not None:
            sh.on_frame(self, msgs)

    def _step_ack_vector(
        self, source: int, msgs: list, fast: "_FastAcks"
    ) -> None:
        import numpy as np

        fast.flush_canon_rows()
        ids, rnos, dig_mat, irregular = _frame_columns(msgs)
        n = len(msgs)

        ci = ids - fast.cid0
        known = (ci >= 0) & (ci < fast.n_clients)
        cis = np.where(known, ci, fast.n_clients)  # sentinel: empty window
        in_win = (rnos >= fast.low_arr[cis]) & (rnos <= fast.high_arr[cis])
        slot = np.where(
            in_win, fast.offset_arr[cis] + rnos - fast.base_arr[cis], 0
        )
        fl = fast.flags[slot]
        live = in_win & (fl == 0)
        canon_match = fast.canon_ok[slot] & (
            fast.canon_mat[slot] == dig_mat
        ).all(axis=1)
        vec = live & canon_match
        if irregular is not None:
            vec[irregular] = False

        # Late acks for committed slots drop outright (same early-out as
        # the loop); everything else the mirror cannot express — buffering,
        # conflicts, canonical adoption — takes the original per-ack path
        # after the vectorized rows, with a slot refresh each.
        fb_rows = np.flatnonzero(
            ~vec & ~(in_win & (fl == _FastAcks.COMMITTED))
        )

        vrows = np.flatnonzero(vec)
        if len(vrows):
            # One frame carries one source, so only that source's mask
            # limb is touched — the hot path stays single-limb at any
            # network size.
            limb = source >> 6
            bit = np.uint64(1 << (source & 63))
            vslot = slot[vrows]
            old = fast.agree[vslot, limb]
            nn = fast.nonnull[vslot, limb]
            dup = (old & bit) != np.uint64(0)
            # A voter whose non-null vote went to a different digest gets
            # no second vote (the spam guard).
            foreign = ((nn & bit) != np.uint64(0)) & ~dup
            apply_m = ~foreign
            new = old | bit
            nn_new = nn | bit
            ap = np.flatnonzero(apply_m)
            ap_slots = vslot[ap]
            # Duplicate slots within one frame all OR the same source bit,
            # so last-write-wins scatter is exact.
            fast.agree[ap_slots, limb] = new[ap]
            fast.nonnull[ap_slots, limb] = nn_new[ap]
            fast.tick_dirty[ap_slots] = True

            if fast.limbs == 1:
                counts = np.bitwise_count(new)
            else:
                # Full-row popcount (post-scatter: duplicate slots carry
                # identical final values).
                counts = np.bitwise_count(fast.agree[vslot]).sum(
                    axis=1, dtype=np.int64
                )
            # No object writeback: the canonical request/crn masks READ
            # AND WRITE through the mirror arrays while attached (see
            # ClientRequest.agreements / ClientReqNo.non_null_voters).
            changed = apply_m & ~dup
            canon_req = fast.canon_req
            canon_crn = fast.canon_crn

            # Quorum crossings (one bit per frame per slot: equality is
            # exact).  Rare relative to acks — plain Python per crossing.
            weak_cross = np.flatnonzero(changed & (counts == fast.weak_q))
            if len(weak_cross):
                available_push = self.available_list.push_back
                for j in weak_cross.tolist():
                    s = int(vslot[j])
                    req = canon_req[s]
                    crn = canon_crn[s]
                    # A duplicate ack in the same frame reads the same
                    # pre-scatter state and lands here twice; the dict
                    # membership check keeps the available push single
                    # (the loop path's was_weak guard).
                    if req.ack.digest in crn.weak_requests:
                        continue
                    crn.weak_requests[req.ack.digest] = req
                    available_push(req)
                    # Weak membership feeds the tick classification (an
                    # unstored newly-weak request needs fetch ticks); the
                    # canonical mirror state is untouched by the crossing,
                    # so only the tick class is re-derived.
                    old_cls = fast.tick_class[s]
                    new_cls = fast._classify_tick(crn)
                    if new_cls != old_cls:
                        if old_cls == _FastAcks.TICK_STEADY:
                            crn.ticks_since_ack = int(fast.tsa[s])
                        fast.tick_class[s] = new_cls
                        if new_cls == _FastAcks.TICK_STEADY:
                            fast.tsa[s] = crn.ticks_since_ack
                            fast.tgt[s] = (
                                crn.acks_sent * _ACK_RESEND_TICKS
                            )
            strong_cross = np.flatnonzero(changed & (counts == fast.strong_q))
            if len(strong_cross):
                for j in strong_cross.tolist():
                    s = int(vslot[j])
                    req = canon_req[s]
                    crn = canon_crn[s]
                    crn.strong_requests[req.ack.digest] = req

            # Ready-mark checks: applied rows sitting exactly at their
            # client's next_ready_mark (advance_ready self-advances, so one
            # call per hit is enough; nrm_arr is synced by advance_ready).
            cand = np.flatnonzero(
                apply_m & (rnos[vrows] == fast.nrm_arr[cis[vrows]])
            )
            for j in cand.tolist():
                s = int(vslot[j])
                crn = canon_crn[s]
                if crn.strong_requests:
                    self.check_ready(fast.clients[int(cis[vrows[j]])], crn)

        if len(fb_rows):
            step_ack = self.step_ack
            for r in fb_rows.tolist():
                step_ack(source, msgs[r])  # refreshes the mirror itself

    def _step_ack_loop(self, source: int, msgs: list) -> None:
        """The reference per-ack path (also the semantic spec for the
        vectorized form above)."""
        clients_get = self.clients.get
        available_push = self.available_list.push_back
        bit = 1 << source
        fast = self._fast
        for msg in msgs:
            ack = msg.type
            client = clients_get(ack.client_id)
            if client is None:
                self.msg_buffers[source].store(msg)
                continue
            req_no = ack.req_no
            if req_no < client.low_watermark:
                continue
            if req_no > client.high_watermark:
                self.msg_buffers[source].store(msg)
                continue
            crn = client.req_no_map.get(req_no)
            if crn is None:
                raise AssertionError(
                    f"client {ack.client_id}: req_no {req_no} missing inside "
                    f"window [{client.low_watermark}, "
                    f"{client.high_watermark}]"
                )
            if crn.committed is not None:
                # Late ack for an already-committed req_no: its vote can no
                # longer influence anything (the request ordered; fetches
                # and null promotion are moot).  Dropping it here skips the
                # accounting the slow path would still perform.
                continue
            # Inlined ClientReqNo.apply_request_ack (force=False) — that
            # method stays the semantic reference for this logic.
            digest = ack.digest
            requests = crn.requests
            if digest:
                key = digest
                if crn.non_null_voters & bit:
                    existing = requests.get(key)
                    if existing is None or not existing.agreements & bit:
                        continue  # second distinct non-null vote: ignored
                else:
                    crn.non_null_voters |= bit
            else:
                key = _NULL
            weak = crn.weak_requests
            was_weak = key in weak
            req = requests.get(key)
            if req is None:
                req = ClientRequest(ack=ack)
                requests[key] = req
            agreements = req.agreements | bit
            req.agreements = agreements
            count = agreements.bit_count()
            if count >= crn._weak_quorum:
                weak[key] = req
                if count >= crn._strong_quorum:
                    crn.strong_requests[key] = req
                if not was_weak:
                    available_push(req)
            client._tick_pending.add(req_no)
            if req_no == client.next_ready_mark and crn.strong_requests:
                self.check_ready(client, crn)
            if fast is not None:
                # A live mirror (left over from large-frame deliveries) must
                # see every small-frame mutation too, or its tick_class goes
                # stale vs the python tick path (step_ack keeps the same
                # invariant one ack at a time).
                fast.refresh(ack.client_id, req_no)

    def step(self, source: int, msg: pb.Msg) -> Actions:
        verdict = self.filter(source, msg)
        if verdict is Applyable.PAST:
            return _EMPTY_ACTIONS
        if verdict is Applyable.FUTURE:
            self.msg_buffers[source].store(msg)
            return _EMPTY_ACTIONS
        return self.apply_msg(source, msg)

    def apply_msg(self, source: int, msg: pb.Msg) -> Actions:
        inner = msg.type
        if inner.__class__ is pb.RequestAck:
            self.ack(source, inner)
            return _EMPTY_ACTIONS
        if isinstance(inner, pb.FetchRequest):
            return self.reply_fetch_request(
                source, inner.client_id, inner.req_no, inner.digest
            )
        if isinstance(inner, pb.ForwardRequest):
            if source == self.my_config.id:
                return Actions()  # our own forward, already processed
            return self.apply_forward_request(source, inner)
        raise AssertionError(f"unexpected client message {type(inner).__name__}")

    # -- request arrival paths ----------------------------------------------

    def apply_request_digest(
        self, ack: pb.RequestAck, data: bytes, out: Actions | None = None
    ) -> Actions:
        client = self.clients.get(ack.client_id)
        if client is None:
            # Client removed since the request was hashed.
            return out if out is not None else Actions()
        if not client.in_watermarks(ack.req_no):
            # Already committed / out of window.
            if hooks.enabled and ack.req_no < client.low_watermark:
                # Retry-storm dedup: the window already retired this
                # req_no, so the resubmission is absorbed without effect.
                hooks.metrics.counter(
                    "mirbft_request_duplicates_total", reason="retired"
                ).inc()
            return out if out is not None else Actions()
        client._tick_pending.add(ack.req_no)
        crn = client.req_no(ack.req_no)
        if hooks.enabled:
            if crn.committed is not None:
                hooks.metrics.counter(
                    "mirbft_request_duplicates_total", reason="committed"
                ).inc()
            elif ack.digest in crn.my_requests:
                hooks.metrics.counter(
                    "mirbft_request_duplicates_total", reason="stored"
                ).inc()
        if self._device is not None:
            self._device.sync_slot(ack.client_id, ack.req_no)
        had_my = len(crn.my_requests)
        actions = crn.apply_request_digest(ack, data, out)
        if self._fast is not None:
            # May have created the slot's first (or a conflicting) request
            # entry and reset the rebroadcast counters: re-derive the
            # mirror's canonical + tick view.  The tick counters were only
            # touched if something was actually stored (the already-
            # persisted early return leaves them alone, and the mirror's
            # advanced copy must then survive the refresh).
            self._fast.refresh(
                ack.client_id,
                ack.req_no,
                tick_obj_authoritative=len(crn.my_requests) != had_my,
            )
        return actions

    def reply_fetch_request(
        self, source: int, client_id: int, req_no: int, digest: bytes
    ) -> Actions:
        client = self.clients.get(client_id)
        if client is None or not client.in_watermarks(req_no):
            return Actions()
        crn = client.req_no(req_no)
        if self._device is not None:
            self._device.sync_slot(client_id, req_no)
        req = crn.requests.get(digest or _NULL)
        if req is None or not req.agreements & (1 << self.my_config.id):
            return Actions()
        return Actions().forward_request(
            [source],
            pb.RequestAck(client_id=client_id, req_no=req_no, digest=digest),
        )

    def apply_forward_request(
        self, source: int, msg: pb.ForwardRequest
    ) -> Actions:
        client = self.clients.get(msg.request_ack.client_id)
        if client is None:
            return Actions()
        crn = client.req_no(msg.request_ack.req_no)
        if self._device is not None:
            self._device.sync_slot(
                msg.request_ack.client_id, msg.request_ack.req_no
            )
        req = crn.requests.get(msg.request_ack.digest or _NULL)
        if req is None:
            # We don't know this digest to be correct yet; drop (the weak
            # quorum will trigger a fetch if it becomes correct).
            return Actions()
        if req.agreements & (1 << self.my_config.id):
            return Actions()  # we already hold + acked it
        req.agreements |= 1 << source
        # Same quorum bookkeeping as apply_request_ack: this out-of-band
        # agreement bump can cross the weak/strong thresholds, and the
        # vector path only promotes on *exact* crossings it applies itself —
        # a skipped crossing here would never be retried (refresh re-derives
        # the canonical/tick view, not quorum membership).
        key = msg.request_ack.digest or _NULL
        count = req.agreements.bit_count()
        if count >= crn._weak_quorum:
            was_weak = key in crn.weak_requests
            crn.weak_requests[key] = req
            if count >= crn._strong_quorum:
                crn.strong_requests[key] = req
            if not was_weak:
                self.available_list.push_back(req)
            client._tick_pending.add(msg.request_ack.req_no)
            self.check_ready(client, crn)
        if self._fast is not None:
            self._fast.refresh(
                msg.request_ack.client_id, msg.request_ack.req_no
            )
        return Actions().hash(
            request_hash_data(
                pb.Request(
                    client_id=msg.request_ack.client_id,
                    req_no=msg.request_ack.req_no,
                    data=msg.request_data,
                )
            ),
            pb.HashResult(
                digest=b"",
                type=pb.HashOriginVerifyRequest(
                    source=source,
                    request_ack=msg.request_ack,
                    request_data=msg.request_data,
                ),
            ),
        )

    # -- ack accounting ------------------------------------------------------

    def ack(self, source: int, ack: pb.RequestAck, force: bool = False) -> ClientRequest:
        client = self.clients.get(ack.client_id)
        if client is None:
            raise AssertionError("step filter must delay unknown clients")
        if self._device is not None:
            self._device.sync_slot(ack.client_id, ack.req_no)
        cr, crn, newly_correct = client.ack(source, ack, force=force)
        if newly_correct:
            self.available_list.push_back(cr)
        self.check_ready(client, crn)
        if self._fast is not None:
            self._fast.refresh(ack.client_id, ack.req_no)
        return cr

    def check_ready(self, client: Client, crn: ClientReqNo) -> None:
        if crn.req_no != client.next_ready_mark:
            return
        if not crn.strong_requests:
            return
        for digest in crn.strong_requests:
            if digest in crn.my_requests:
                self.advance_ready(client)
                return

    def advance_ready(self, client: Client) -> None:
        for req_no in range(client.next_ready_mark, client.high_watermark + 1):
            if req_no != client.next_ready_mark:
                return  # previous iteration failed to advance
            crn = client.req_no_map.get(req_no)
            if crn is None:
                raise AssertionError(
                    f"client {client.client_state.id} missing req_no {req_no}"
                )
            for digest in crn.strong_requests:
                if digest in crn.my_requests:
                    self.ready_list.push_back(crn)
                    client.next_ready_mark = req_no + 1
                    if self._fast is not None:
                        ci = client.client_state.id - self._fast.cid0
                        if 0 <= ci < self._fast.n_clients:
                            self._fast.nrm_arr[ci] = req_no + 1
                    if self._device is not None:
                        ci = client.client_state.id - self._device.cid0
                        if 0 <= ci < self._device.n_clients:
                            self._device.nrm_arr[ci] = req_no + 1
                    break

    # -- checkpoint interplay ------------------------------------------------

    def commits_completed_for_checkpoint_window(self, seq_no: int) -> list:
        """Compute each client's next window state at a checkpoint boundary
        and allocate the newly usable request numbers (reference:
        client_tracker.go:482-550; the doc comment there works the
        width-consumed example)."""
        new_states = []
        for old_state in self.client_states:
            client = self.clients[old_state.id]

            first_uncommitted = last_committed = None
            for crn in client.req_nos():
                if crn.committed is not None:
                    if crn.committed > seq_no:
                        raise AssertionError(
                            "commit sequence after current checkpoint"
                        )
                    last_committed = crn.req_no
                elif first_uncommitted is None:
                    first_uncommitted = crn.req_no

            if last_committed is None:
                new_states.append(old_state)
                continue

            if first_uncommitted is None:
                if last_committed != client.high_watermark:
                    raise AssertionError(
                        "all committed implies committed through high mark"
                    )
                # Entire window consumed: the whole next window is gated on
                # the next checkpoint (width_consumed = full width).
                state = pb.NetworkClient(
                    id=old_state.id,
                    width=old_state.width,
                    width_consumed_last_checkpoint=old_state.width,
                    low_watermark=last_committed + 1,
                )
                new_states.append(state)
                client.allocate(seq_no, state)
                continue

            mask = make_bitmask(last_committed - first_uncommitted + 1)
            for i in range(last_committed - first_uncommitted + 1):
                req_no = first_uncommitted + i
                if client.req_no(req_no).committed is None:
                    continue
                if i == 0:
                    raise AssertionError(
                        "first uncommitted cannot be committed"
                    )
                set_bit(mask, i)

            state = pb.NetworkClient(
                id=old_state.id,
                width=old_state.width,
                width_consumed_last_checkpoint=first_uncommitted
                - old_state.low_watermark,
                low_watermark=first_uncommitted,
                committed_mask=bytes(mask),
            )
            new_states.append(state)
            client.allocate(seq_no, state)

        self.client_states = new_states
        self._drop_device()  # windows advanced: dense shapes are stale
        self._drop_fast()  # windows advanced: mirror shape is stale
        return new_states

    def drain(self) -> Actions:
        """Re-apply buffered messages after watermark movement."""
        actions = Actions()
        for node_id in self.network_config.nodes:
            self.msg_buffers[node_id].iterate(
                self.filter,
                lambda source, msg: actions.concat(self.apply_msg(source, msg)),
            )
        return actions

    def fetch_request(self, cr: ClientRequest) -> Actions:
        """Fetch a known-correct request (epoch-change path); mediated
        here so the fetching-state flip reclassifies the mirror slot."""
        if self._device is not None:
            # fetch() targets mask_ids(agreements): the device-held votes
            # must be in the object before the send list is computed.
            self._device.sync_slot(cr.ack.client_id, cr.ack.req_no)
        actions = cr.fetch()
        if self._fast is not None:
            self._fast.refresh(cr.ack.client_id, cr.ack.req_no)
        return actions

    def mark_committed(self, client_id: int, req_no: int, seq_no: int) -> None:
        """Called by commit state as batches are applied."""
        self.clients[client_id].req_no(req_no).committed = seq_no
        if self._fast is not None:
            self._fast.mark_committed(client_id, req_no)
        if self._device is not None:
            self._device.mark_committed(client_id, req_no)

    def garbage_collect(self, seq_no: int) -> None:
        self._drop_device()  # windows slide: dense slots remap
        self._drop_fast()  # windows slide: mirror slots remap
        for client_state in self.client_states:
            self.clients[client_state.id].move_low_watermark(seq_no)
        self.available_list.garbage_collect(seq_no)
        self.ready_list.garbage_collect(seq_no)

    def client(self, client_id: int) -> Client | None:
        return self.clients.get(client_id)
