"""Hash-preimage byte layouts: the contract between the protocol core and
the TPU digest kernels.

Every digest in the protocol is SHA-256 over the concatenation of a list of
byte chunks.  The chunk layouts here are the canonical formats every node
must agree on — a digest computed on TPU (ops.sha256) and one computed with
hashlib must be bit-identical for the same logical value.

Layouts (reference equivalents):
- request:       [u64le(client_id), u64le(req_no), data]
                 (reference: state_machine.go:313-317)
- batch:         [ack_digest, ...] one chunk per request ack
                 (reference: sequence.go:154-157)
- epoch change:  [u64le(new_epoch)] + per checkpoint [u64le(seq_no), value]
                 + per pSet entry [u64le(epoch), u64le(seq_no), digest]
                 + per qSet entry [u64le(epoch), u64le(seq_no), digest]
                 (reference: stateless.go:311-340)

Integers are 8-byte little-endian (reference: proposer.go:16-20).
"""

from __future__ import annotations

import hashlib

from .. import pb


def u64le(value: int) -> bytes:
    return value.to_bytes(8, "little")


def request_hash_data(request: pb.Request) -> list:
    return [u64le(request.client_id), u64le(request.req_no), request.data]


def batch_hash_data(request_acks: list) -> list:
    return [ack.digest for ack in request_acks]


def epoch_change_hash_data(epoch_change: pb.EpochChange) -> list:
    chunks = [u64le(epoch_change.new_epoch)]
    for cp in epoch_change.checkpoints:
        chunks.append(u64le(cp.seq_no))
        chunks.append(cp.value)
    for entry in epoch_change.p_set:
        chunks.append(u64le(entry.epoch))
        chunks.append(u64le(entry.seq_no))
        chunks.append(entry.digest)
    for entry in epoch_change.q_set:
        chunks.append(u64le(entry.epoch))
        chunks.append(u64le(entry.seq_no))
        chunks.append(entry.digest)
    return chunks


def host_digest(chunks: list) -> bytes:
    """Reference SHA-256 over concatenated chunks, computed on the host.

    This is the correctness oracle for the TPU kernel (ops.sha256) and the
    digest path for tiny/latency-sensitive work not worth a device round
    trip."""
    h = hashlib.sha256()
    for chunk in chunks:
        h.update(chunk)
    return h.digest()
