"""The Actions/Results contract: the complete work-order vocabulary between
the deterministic protocol core and the executor (runtime / TPU compute
plane).

Rebuild of the reference's consumer contract (reference: actions.go:18-261).
The state machine emits an ``Actions`` value from every applied event; the
executor performs the work — persist, send, hash (on TPU), commit — and
feeds ``ActionResults`` back in as a state event.  This seam is what lets
the hot crypto be batched and dispatched to the accelerator without the
protocol core ever touching a device.

Safety ordering contract for executors (reference: docs/Processor.md:24-28):
requests stored and WAL writes fsynced *before* any network send; hashing is
order-free; commits independent of persistence.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .. import pb


@dataclass(slots=True)
class WalAppend:
    index: int
    data: pb.Persistent


@dataclass(slots=True)
class WalWrite:
    """Exactly one of truncate/append is set (reference: actions.go:128-137).
    ``truncate`` removes every entry with index below the given value."""

    truncate: int | None = None
    append: WalAppend | None = None


@dataclass(slots=True)
class Send:
    """``targets`` is stored by reference (callers pass the shared
    network-config node list or a fresh sorted list) and must not be
    mutated after the Send is emitted."""

    targets: list  # node IDs, including self
    msg: pb.Msg


@dataclass(slots=True)
class Forward:
    """Like Send, but the executor must first fetch the request data from its
    request store and wrap it in a ForwardRequest message."""

    targets: list
    request_ack: pb.RequestAck


@dataclass(slots=True)
class HashRequest:
    """A digest the executor must compute: SHA-256 over the concatenation of
    ``data`` chunks (layouts in core.preimage).  ``origin`` is a pb.HashResult
    with an empty digest and a populated type; the executor fills in the
    digest and returns the completed pb.HashResult."""

    data: list  # [bytes]
    origin: pb.HashResult


@dataclass(slots=True)
class CheckpointReq:
    """A request for the application to compute a checkpoint value over its
    state at seq_no (reference: actions.go:181-205).  The value must be a
    pure function of the application state + network state — NOT the epoch —
    since different nodes may commit the same checkpoint in different
    epochs."""

    seq_no: int
    network_config: pb.NetworkConfig
    clients_state: list  # [pb.NetworkClient]


@dataclass(slots=True)
class CommitAction:
    """Either a totally-ordered batch to apply, or a checkpoint request.
    Exactly one is set."""

    batch: pb.QEntry | None = None
    checkpoint: CheckpointReq | None = None


@dataclass(slots=True)
class StateTarget:
    seq_no: int
    value: bytes


@dataclass(slots=True)
class Actions:
    sends: list = field(default_factory=list)  # [Send]
    hashes: list = field(default_factory=list)  # [HashRequest]
    write_ahead: list = field(default_factory=list)  # [WalWrite]
    commits: list = field(default_factory=list)  # [CommitAction]
    store_requests: list = field(default_factory=list)  # [pb.ForwardRequest]
    forward_requests: list = field(default_factory=list)  # [Forward]
    state_transfer: StateTarget | None = None

    def send(self, targets: list, msg: pb.Msg) -> "Actions":
        self.sends.append(Send(targets=targets, msg=msg))
        return self

    def hash(self, data: list, origin: pb.HashResult) -> "Actions":
        self.hashes.append(HashRequest(data=data, origin=origin))
        return self

    def persist(self, index: int, entry: pb.Persistent) -> "Actions":
        self.write_ahead.append(
            WalWrite(append=WalAppend(index=index, data=entry))
        )
        return self

    def truncate(self, index: int) -> "Actions":
        self.write_ahead.append(WalWrite(truncate=index))
        return self

    def store_request(self, request: pb.ForwardRequest) -> "Actions":
        self.store_requests.append(request)
        return self

    def forward_request(self, targets: list, ack: pb.RequestAck) -> "Actions":
        self.forward_requests.append(
            Forward(targets=targets, request_ack=ack)
        )
        return self

    def is_empty(self) -> bool:
        return (
            not self.sends
            and not self.hashes
            and not self.write_ahead
            and not self.commits
            and not self.store_requests
            and not self.forward_requests
            and self.state_transfer is None
        )

    def clear(self) -> None:
        self.sends = []
        self.hashes = []
        self.write_ahead = []
        self.commits = []
        self.store_requests = []
        self.forward_requests = []
        self.state_transfer = None

    def concat(self, other: "Actions") -> "Actions":
        # Truthiness guards: most concats carry nothing, and this runs on
        # every event of every simulated node — skip the empty extends.
        if other.sends:
            self.sends.extend(other.sends)
        if other.hashes:
            self.hashes.extend(other.hashes)
        if other.write_ahead:
            self.write_ahead.extend(other.write_ahead)
        if other.commits:
            self.commits.extend(other.commits)
        if other.store_requests:
            self.store_requests.extend(other.store_requests)
        if other.forward_requests:
            self.forward_requests.extend(other.forward_requests)
        if other.state_transfer is not None:
            if self.state_transfer is not None:
                raise AssertionError(
                    "two concurrent state transfer requests"
                )
            self.state_transfer = other.state_transfer
        return self


# The shared hot-path empty: returned by handlers with nothing to emit so
# callers can skip both the allocation and the concat via an identity check.
# Must never be mutated — callers only read/concat it.
EMPTY_ACTIONS = Actions()


# ---------------------------------------------------------------------------
# Results (reference: actions.go:216-261).  The runtime converts these to the
# wire-level pb.HashResult / pb.CheckpointResult carried by the AddResults
# state event (reference: mirbft.go:391-421).
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class HashResult:
    digest: bytes
    request: HashRequest


@dataclass(slots=True)
class CheckpointResult:
    checkpoint: CheckpointReq
    value: bytes
    # Ordered reconfigurations that committed within this checkpoint window;
    # applied starting at the *next* checkpoint.
    reconfigurations: list = field(default_factory=list)  # [pb.Reconfiguration]


@dataclass(slots=True)
class ActionResults:
    digests: list = field(default_factory=list)  # [HashResult]
    checkpoints: list = field(default_factory=list)  # [CheckpointResult]


def results_to_event(results: ActionResults) -> pb.EventActionResults:
    """Convert runtime-level results into the serializable state event
    (reference: mirbft.go:392-413)."""
    digests = []
    for hr in results.digests:
        # The origin IS a pb.HashResult with an empty digest, created by the
        # state machine solely for this round trip: fill it in place rather
        # than allocating a copy (hundreds of thousands per ladder run).
        origin = hr.request.origin
        origin.digest = hr.digest
        digests.append(origin)
    checkpoints = []
    for cr in results.checkpoints:
        checkpoints.append(
            pb.CheckpointResult(
                seq_no=cr.checkpoint.seq_no,
                value=cr.value,
                network_state=pb.NetworkState(
                    config=cr.checkpoint.network_config,
                    clients=cr.checkpoint.clients_state,
                    pending_reconfigurations=list(cr.reconfigurations),
                ),
            )
        )
    return pb.EventActionResults(digests=digests, checkpoints=checkpoints)
