"""The view-change FSM: negotiating, verifying, and activating one epoch.

Rebuild of the reference's epoch target (reference: epoch_target.go:20-766).
State flow:

    PREPENDING  sent our EpochChange, collecting a quorum of strong-certified
                changes
    PENDING     quorum reached; leader computed/sent NewEpoch, others await it
    VERIFYING   got the leader's NewEpoch; recompute the config from the
                referenced changes and compare (byzantine-leader check)
    FETCHING    valid NewEpoch; fetch missing batches/requests it references
    ECHOING     state held; persisted NEntry/QEntries; Bracha echo broadcast
    READYING    echo quorum; persisted PEntries; Bracha ready broadcast
    RESUMING    ready quorum (or crash-resume); waiting for the commit state
                to line up with the epoch's starting sequence
    READY       commit state aligned; instantiate the active epoch
    IN_PROGRESS normal-case ordering (active_epoch)
    ENDING/DONE gracefully ended at planned expiration / ended by suspicion

The Bracha echo/ready broadcast of the NewEpochConfig is what makes epoch
activation reliable despite a byzantine leader.
"""

from __future__ import annotations

import enum

from .. import pb
from ..obsv import hooks
from .actions import Actions
from .active_epoch import ActiveEpoch
from .batch_tracker import BatchTracker
from .client_tracker import ClientTracker
from .commitstate import CommitState
from .epoch_change import EpochChangeCert, ParsedEpochChange
from .msgbuffers import Applyable, MsgBuffer, NodeBuffers
from .persisted import Persisted
from .quorum import (
    construct_new_epoch_config,
    intersection_quorum,
    some_correct_quorum,
)


class TargetState(enum.IntEnum):
    PREPENDING = 0
    PENDING = 1
    VERIFYING = 2
    FETCHING = 3
    ECHOING = 4
    READYING = 5
    RESUMING = 6
    READY = 7
    IN_PROGRESS = 8
    ENDING = 9
    DONE = 10


class EpochTarget:
    def __init__(
        self,
        number: int,
        persisted: Persisted,
        node_buffers: NodeBuffers,
        commit_state: CommitState,
        client_tracker: ClientTracker,
        batch_tracker: BatchTracker,
        network_config: pb.NetworkConfig,
        my_config: pb.InitialParameters,
        logger=None,
    ):
        self.number = number
        self.persisted = persisted
        self.node_buffers = node_buffers
        self.commit_state = commit_state
        self.client_tracker = client_tracker
        self.batch_tracker = batch_tracker
        self.network_config = network_config
        self.my_config = my_config
        self.logger = logger

        self.state = TargetState.PREPENDING
        self.state_ticks = 0
        self.starting_seq_no = 0
        # hash-preimage bytes -> digest: computed-digest memo for the ack
        # fan-in (see apply_epoch_change_ack); scope is this target's
        # lifetime.
        self._ack_digest_memo: dict[bytes, bytes] = {}
        # origin node -> EpochChangeCert (digest variants + ACKs)
        self.changes: dict[int, EpochChangeCert] = {}
        # origin node -> ParsedEpochChange with a strong cert
        self.strong_changes: dict[int, ParsedEpochChange] = {}
        # encoded NewEpochConfig -> (config, voter set)
        self.echos: dict[bytes, tuple] = {}
        self.readies: dict[bytes, tuple] = {}
        self.suspicions: set = set()
        self.active_epoch: ActiveEpoch | None = None
        self.my_new_epoch: pb.NewEpoch | None = None  # computed locally
        self.my_epoch_change: ParsedEpochChange | None = None
        self.my_leader_choice: list = []
        self.leader_new_epoch: pb.NewEpoch | None = None  # from the leader
        self.network_new_epoch: pb.NewEpochConfig | None = None  # via Bracha
        # Epoch leader is selected from the node *list*, not by assuming IDs
        # are contiguous 0..n-1 (the reference assumes contiguity; this holds
        # for any ID set).
        self.is_leader = (
            network_config.nodes[number % len(network_config.nodes)]
            == my_config.id
        )
        self.prestart_buffers = {
            node: MsgBuffer(
                f"epoch-{number}-prestart", node_buffers.node_buffer(node)
            )
            for node in network_config.nodes
        }

    # -- three-phase messages ------------------------------------------------

    def step(self, source: int, msg: pb.Msg) -> Actions:
        if self.state < TargetState.IN_PROGRESS:
            self.prestart_buffers[source].store(msg)
            return Actions()
        if self.state == TargetState.DONE:
            return Actions()
        return self.active_epoch.step(source, msg)

    # -- epoch change collection ---------------------------------------------

    def apply_epoch_change_msg(self, source: int, msg: pb.EpochChange) -> Actions:
        actions = Actions()
        if source != self.my_config.id:
            # ACK everyone else's change; ours is already rebroadcast whole.
            actions.send(
                self.network_config.nodes,
                pb.Msg(
                    type=pb.EpochChangeAck(originator=source, epoch_change=msg)
                ),
            )
        # The originator's own message counts as its ACK.
        return actions.concat(self.apply_epoch_change_ack(source, source, msg))

    def apply_epoch_change_ack(
        self, source: int, origin: int, msg: pb.EpochChange
    ) -> Actions:
        # The ack scheme is O(n^3) messages per epoch change.  The digest
        # of one origin's change is independent of who acked it; once
        # computed (via the executor round trip below), further acks of a
        # byte-identical change apply synchronously — near O(n^2)
        # processing.  The memo is keyed by the hash preimage — a pure
        # function of the message value — so live runs and event-log
        # replays take identical paths (an object-identity key would
        # diverge under replay).  Acks keep accumulating even after a
        # strong cert forms: an equivocating origin's *other* digest
        # variants may still need their f+1 for new-epoch verification.
        from .preimage import epoch_change_hash_data

        data = epoch_change_hash_data(msg)
        key = b"".join(data)
        digest = self._ack_digest_memo.get(key)
        if digest is not None:
            return self._apply_change_digest(source, origin, msg, digest)
        # ACK certification is over the *digest* of the change; request the
        # hash from the executor, result returns via apply_epoch_change_digest.
        return Actions().hash(
            data,
            pb.HashResult(
                digest=b"",
                type=pb.HashOriginEpochChange(
                    source=source, origin=origin, epoch_change=msg
                ),
            ),
        )

    def apply_epoch_change_digest(
        self, origin_info: pb.HashOriginEpochChange, digest: bytes
    ) -> Actions:
        msg = origin_info.epoch_change
        from .preimage import epoch_change_hash_data

        key = b"".join(epoch_change_hash_data(msg))
        if key not in self._ack_digest_memo:
            self._ack_digest_memo[key] = digest
        return self._apply_change_digest(
            origin_info.source, origin_info.origin, msg, digest
        )

    def _apply_change_digest(
        self, source: int, origin: int, msg: pb.EpochChange, digest: bytes
    ) -> Actions:
        cert = self.changes.get(origin)
        if cert is None:
            cert = EpochChangeCert(network_config=self.network_config)
            self.changes[origin] = cert
        cert.add_msg(source, msg, digest)

        if cert.strong_cert is None or origin in self.strong_changes:
            return Actions()
        self.strong_changes[origin] = cert.parsed_by_digest[cert.strong_cert]
        return self.advance_state()

    def check_epoch_quorum(self) -> Actions:
        if (
            len(self.strong_changes) < intersection_quorum(self.network_config)
            or self.my_epoch_change is None
        ):
            return Actions()

        self.my_new_epoch = self.construct_new_epoch(self.my_leader_choice)
        if self.my_new_epoch is None:
            return Actions()

        self.state_ticks = 0
        self.state = TargetState.PENDING

        if self.is_leader:
            return Actions().send(
                self.network_config.nodes,
                pb.Msg(type=self.my_new_epoch),
            )
        return Actions()

    def construct_new_epoch(self, new_leaders: list) -> pb.NewEpoch | None:
        filtered = {
            node: change
            for node, change in self.strong_changes.items()
            if change.underlying is not None
        }
        if len(filtered) < intersection_quorum(self.network_config):
            return None
        new_config = construct_new_epoch_config(
            self.network_config, new_leaders, filtered
        )
        if new_config is None:
            return None

        remote_changes = [
            pb.RemoteEpochChange(
                node_id=node, digest=self.changes[node].strong_cert
            )
            for node in self.network_config.nodes
            if node in self.strong_changes
        ]
        return pb.NewEpoch(new_config=new_config, epoch_changes=remote_changes)

    # -- new epoch verification / fetch --------------------------------------

    def apply_new_epoch_msg(self, msg: pb.NewEpoch) -> Actions:
        if (
            self.leader_new_epoch is not None
            and self.state < TargetState.ENDING
            and pb.encode(msg.new_config)
            == pb.encode(self.leader_new_epoch.new_config)
        ):
            # A retransmitted NewEpoch means the leader is still stuck
            # short of its echo/ready quorum — some votes were lost on
            # the wire.  Re-send ours (the vote tables dedup by source),
            # closing the Bracha exchange's retransmission loop: the
            # leader re-broadcasts its proposal on a tick cadence, and
            # every recipient re-responds here.  Without this, a single
            # dropped NewEpochReady can wedge the change forever: the
            # epoch leader never suspects its own epoch, so a stuck
            # leader plus a prepending laggard leaves the suspicion set
            # one short of quorum.
            actions = Actions()
            config = self.leader_new_epoch.new_config
            if self.state >= TargetState.ECHOING:
                actions.send(
                    self.network_config.nodes,
                    pb.Msg(type=pb.NewEpochEcho(new_epoch_config=config)),
                )
            if self.state >= TargetState.READYING:
                actions.send(
                    self.network_config.nodes,
                    pb.Msg(type=pb.NewEpochReady(new_epoch_config=config)),
                )
            return actions.concat(self.advance_state())
        self.leader_new_epoch = msg
        return self.advance_state()

    def verify_new_epoch_state(self) -> Actions:
        """Recompute the new-epoch config from the changes the leader cites
        and require byte equality (reference: epoch_target.go:158-195)."""
        epoch_changes: dict[int, ParsedEpochChange] = {}
        for remote in self.leader_new_epoch.epoch_changes:
            if remote.node_id in epoch_changes:
                return Actions()  # malformed: duplicate origin
            cert = self.changes.get(remote.node_id)
            if cert is None:
                return Actions()  # not enough info yet (or leader lying)
            parsed = cert.parsed_by_digest.get(remote.digest)
            if parsed is None or len(parsed.acks) < some_correct_quorum(
                self.network_config
            ):
                return Actions()
            epoch_changes[remote.node_id] = parsed

        computed = construct_new_epoch_config(
            self.network_config,
            self.leader_new_epoch.new_config.config.leaders,
            epoch_changes,
        )
        if computed != self.leader_new_epoch.new_config:
            return Actions()  # byzantine leader

        self.state = TargetState.FETCHING
        return self.advance_state()

    def fetch_new_epoch_state(self) -> Actions:
        """Gather every batch/request the new config's final preprepares
        reference (reference: epoch_target.go:197-350)."""
        new_config = self.leader_new_epoch.new_config

        if self.commit_state.transferring:
            return Actions()  # wait for state transfer first

        if new_config.starting_checkpoint.seq_no > self.commit_state.highest_commit:
            return self.commit_state.transfer_to(
                new_config.starting_checkpoint.seq_no,
                new_config.starting_checkpoint.value,
            )

        actions = Actions()
        fetch_pending = False

        for i, digest in enumerate(new_config.final_preprepares):
            if not digest:
                continue
            seq_no = new_config.starting_checkpoint.seq_no + i + 1
            if seq_no <= self.commit_state.highest_commit:
                continue

            sources = []
            for remote in self.leader_new_epoch.epoch_changes:
                parsed = self.changes[remote.node_id].parsed_by_digest[
                    remote.digest
                ]
                for q_digest in parsed.q_set.get(seq_no, {}).values():
                    if q_digest == digest:
                        sources.append(remote.node_id)
                        break
            if len(sources) < some_correct_quorum(self.network_config):
                raise AssertionError(
                    f"selected digest for seq {seq_no} lacks f+1 qSet sources"
                )

            batch = self.batch_tracker.get_batch(digest)
            if batch is None:
                actions.concat(
                    self.batch_tracker.fetch_batch(seq_no, digest, sources)
                )
                fetch_pending = True
                continue
            batch.observed_sequences.add(seq_no)

            for ack in batch.request_acks:
                cr = None
                for node in sources:
                    # Known-correct via f+1 qSets: force past the spam guard.
                    cr = self.client_tracker.ack(node, ack, force=True)
                if cr is None or cr.agreements & (1 << self.my_config.id):
                    continue
                fetch_pending = True
                actions.concat(self.client_tracker.fetch_request(cr))

        if fetch_pending:
            return actions

        if new_config.starting_checkpoint.seq_no > self.commit_state.low_watermark:
            # Committed through the checkpoint but it hasn't computed yet.
            return actions

        self.state = TargetState.ECHOING

        # Reconfiguration boundary (the spot the reference leaves as a
        # panic, epoch_target.go:282-300): final preprepares extending past
        # a reconfiguration stop are handled downstream — check_ready_quorum
        # defers over-stop replay commits until our checkpoint result
        # extends the stop (commit_state.defer_replay), so nothing special
        # is needed here.  A correct replica only prepared beyond the stop
        # once that checkpoint was stable, so the extension is guaranteed.

        actions.concat(
            self.persisted.add_n_entry(
                pb.NEntry(
                    seq_no=new_config.starting_checkpoint.seq_no + 1,
                    epoch_config=new_config.config,
                )
            )
        )
        ci = self.network_config.checkpoint_interval
        for i, digest in enumerate(new_config.final_preprepares):
            seq_no = new_config.starting_checkpoint.seq_no + i + 1
            if not digest:
                actions.concat(
                    self.persisted.add_q_entry(pb.QEntry(seq_no=seq_no))
                )
            else:
                batch = self.batch_tracker.get_batch(digest)
                if batch is None:
                    if seq_no > self.commit_state.highest_commit:
                        raise AssertionError("batch vanished after fetch")
                    # Already committed locally and pruned by checkpoint GC
                    # (the fetch pass rightly skipped it, so it was never
                    # re-fetched).  Persist the digest-only QEntry: the
                    # epoch-change recomputation needs only (seq, digest),
                    # and the ready-quorum replay skips sequences at or
                    # below the low watermark while digest-matching any
                    # still in the commit window.
                    actions.concat(
                        self.persisted.add_q_entry(
                            pb.QEntry(seq_no=seq_no, digest=digest)
                        )
                    )
                else:
                    actions.concat(
                        self.persisted.add_q_entry(
                            pb.QEntry(
                                seq_no=seq_no,
                                digest=digest,
                                requests=batch.request_acks,
                            )
                        )
                    )
            if seq_no % ci == 0 and seq_no < self.commit_state.stop_at_seq_no:
                actions.concat(
                    self.persisted.add_n_entry(
                        pb.NEntry(
                            seq_no=seq_no + 1, epoch_config=new_config.config
                        )
                    )
                )

        self.starting_seq_no = (
            new_config.starting_checkpoint.seq_no
            + len(new_config.final_preprepares)
            + 1
        )

        return actions.send(
            self.network_config.nodes,
            pb.Msg(type=pb.NewEpochEcho(new_epoch_config=new_config)),
        )

    # -- Bracha echo / ready -------------------------------------------------

    def _vote(self, table: dict, config: pb.NewEpochConfig, source: int):
        key = pb.encode(config)
        entry = table.get(key)
        if entry is None:
            entry = (config, set())
            table[key] = entry
        entry[1].add(source)
        return entry[1]

    def apply_new_epoch_echo_msg(
        self, source: int, msg: pb.NewEpochEcho
    ) -> Actions:
        self._vote(self.echos, msg.new_epoch_config, source)
        return self.advance_state()

    def check_echo_quorum(self) -> Actions:
        actions = Actions()
        for config, voters in self.echos.values():
            if len(voters) < intersection_quorum(self.network_config):
                continue
            self.state = TargetState.READYING
            for i, digest in enumerate(config.final_preprepares):
                seq_no = config.starting_checkpoint.seq_no + i + 1
                actions.concat(
                    self.persisted.add_p_entry(
                        pb.PEntry(seq_no=seq_no, digest=digest)
                    )
                )
            return actions.send(
                self.network_config.nodes,
                pb.Msg(type=pb.NewEpochReady(new_epoch_config=config)),
            )
        return actions

    def apply_new_epoch_ready_msg(
        self, source: int, msg: pb.NewEpochReady
    ) -> Actions:
        if self.state > TargetState.READYING:
            return Actions()  # already accepted the config

        voters = self._vote(self.readies, msg.new_epoch_config, source)

        if len(voters) < some_correct_quorum(self.network_config):
            return Actions()

        if self.state < TargetState.ECHOING:
            return self.advance_state()

        if self.state < TargetState.READYING:
            # f+1 readies let us skip straight to ready (Bracha amplify).
            self.state = TargetState.READYING
            return Actions().send(
                self.network_config.nodes,
                pb.Msg(type=pb.NewEpochReady(new_epoch_config=msg.new_epoch_config)),
            )

        return self.advance_state()

    def check_ready_quorum(self) -> None:
        for config, voters in self.readies.values():
            if len(voters) < intersection_quorum(self.network_config):
                continue
            self.state = TargetState.RESUMING
            self.network_new_epoch = config

            # Replay our own QEntries from this epoch-change window as
            # commits (they were selected into the new epoch).
            current_epoch = False

            def on_q(q_entry):
                if not current_epoch:
                    return
                if q_entry.seq_no <= self.commit_state.stop_at_seq_no:
                    self.commit_state.commit(q_entry)
                else:
                    # Beyond our (stale, pre-reconfiguration) stop: a
                    # correct peer only prepared past the stop once that
                    # checkpoint was stable, so hold the commit until our
                    # own checkpoint result extends the stop.
                    self.commit_state.defer_replay(q_entry)

            def on_ec(ec_entry):
                nonlocal current_epoch
                if ec_entry.epoch_number < config.config.number:
                    return
                if ec_entry.epoch_number > config.config.number:
                    raise AssertionError(
                        "epoch-change entries cannot exceed the target epoch"
                    )
                current_epoch = True

            self.persisted.iterate({pb.QEntry: on_q, pb.ECEntry: on_ec})
            return

    def check_epoch_resumed(self) -> None:
        if self.commit_state.stop_at_seq_no < self.starting_seq_no:
            return  # waiting for the outstanding checkpoint to commit
        if self.commit_state.low_watermark + 1 != self.starting_seq_no:
            return  # waiting for state transfer
        self.state = TargetState.READY

    # -- the FSM loop --------------------------------------------------------

    def advance_state(self) -> Actions:
        actions = Actions()
        while True:
            old_state = self.state
            if self.state == TargetState.PREPENDING:
                actions.concat(self.check_epoch_quorum())
            elif self.state == TargetState.PENDING:
                if self.leader_new_epoch is None:
                    return actions
                self.state = TargetState.VERIFYING
            elif self.state == TargetState.VERIFYING:
                actions.concat(self.verify_new_epoch_state())
            elif self.state == TargetState.FETCHING:
                actions.concat(self.fetch_new_epoch_state())
            elif self.state == TargetState.ECHOING:
                actions.concat(self.check_echo_quorum())
            elif self.state == TargetState.READYING:
                self.check_ready_quorum()
            elif self.state == TargetState.RESUMING:
                self.check_epoch_resumed()
            elif self.state == TargetState.READY:
                self.active_epoch = ActiveEpoch(
                    self.network_new_epoch.config,
                    self.persisted,
                    self.node_buffers,
                    self.commit_state,
                    self.client_tracker,
                    self.my_config,
                    self.logger,
                )
                actions.concat(self.active_epoch.advance())
                self.state = TargetState.IN_PROGRESS
                if hooks.enabled:
                    hooks.epoch_milestone(
                        "epoch.active", self.my_config.id, self.number
                    )
                for node in self.network_config.nodes:
                    self.prestart_buffers[node].iterate(
                        lambda *_: Applyable.CURRENT,  # drain everything
                        lambda src, msg: actions.concat(
                            self.active_epoch.step(src, msg)
                        ),
                    )
                actions.concat(self.active_epoch.drain_buffers())
            elif self.state == TargetState.IN_PROGRESS:
                actions.concat(
                    self.active_epoch.outstanding_reqs.advance_requests()
                )
                actions.concat(self.active_epoch.advance())
                if self.active_epoch.suspect_bucket_violation:
                    self.active_epoch.suspect_bucket_violation = False
                    suspect = pb.Suspect(epoch=self.number)
                    actions.send(
                        self.network_config.nodes, pb.Msg(type=suspect)
                    )
                    actions.concat(self.persisted.add_suspect(suspect))
            else:  # ENDING / DONE
                pass
            if self.state == old_state:
                return actions

    def move_low_watermark(self, seq_no: int) -> Actions:
        if self.state != TargetState.IN_PROGRESS:
            return Actions()
        actions, done = self.active_epoch.move_low_watermark(seq_no)
        if done:
            self.state = TargetState.DONE
        return actions

    def apply_suspect_msg(self, source: int) -> None:
        self.suspicions.add(source)
        if len(self.suspicions) >= intersection_quorum(self.network_config):
            self.state = TargetState.DONE

    # -- ticks ---------------------------------------------------------------

    def tick(self) -> Actions:
        self.state_ticks += 1
        if self.state == TargetState.PREPENDING:
            return self._tick_prepending()
        if self.state <= TargetState.RESUMING:
            return self._tick_pending()
        if self.state <= TargetState.IN_PROGRESS:
            return self.active_epoch.tick()
        return Actions()

    def _repeat_epoch_change(self) -> Actions:
        return Actions().send(
            self.network_config.nodes,
            pb.Msg(type=self.my_epoch_change.underlying),
        )

    def _tick_prepending(self) -> Actions:
        if self.my_new_epoch is None:
            half = max(self.my_config.new_epoch_timeout_ticks // 2, 1)
            if self.state_ticks % half == 0:
                return self._repeat_epoch_change()
            return Actions()
        if self.is_leader:
            return Actions().send(
                self.network_config.nodes, pb.Msg(type=self.my_new_epoch)
            )
        return Actions()

    def _tick_pending(self) -> Actions:
        timeout = max(self.my_config.new_epoch_timeout_ticks, 2)
        pending_ticks = self.state_ticks % timeout
        actions = Actions()
        if self.state == TargetState.FETCHING and self.state_ticks % 2 == 0:
            # Lost or byzantine FetchBatch replies must not stall the epoch
            # change; re-ask the known holders.
            actions.concat(self.batch_tracker.retransmit_fetches())
        if self.is_leader:
            if self.my_new_epoch is not None and pending_ticks % 2 == 0:
                actions.send(
                    self.network_config.nodes, pb.Msg(type=self.my_new_epoch)
                )
                return actions
        else:
            if pending_ticks == 0:
                # In the crash-resume path we never computed a NewEpoch;
                # suspect our own target number instead (the reference
                # nil-derefs here, epoch_target.go:417-419).
                epoch = (
                    self.my_new_epoch.new_config.config.number
                    if self.my_new_epoch is not None
                    else self.number
                )
                suspect = pb.Suspect(epoch=epoch)
                actions.send(self.network_config.nodes, pb.Msg(type=suspect))
                return actions.concat(self.persisted.add_suspect(suspect))
            if self.my_epoch_change is not None and pending_ticks % 2 == 0:
                return actions.concat(self._repeat_epoch_change())
        return actions
