"""Ordering committed batches for the application and driving checkpoints.

Rebuild of the reference's commit state (reference: commitstate.go:17-279).
Two checkpoint windows of commits are held in half-interval ring buffers;
when every sequence up to a checkpoint boundary has been applied, a
checkpoint request is emitted to the application, and commits for the next
window proceed while it computes (checkpoint-interval pipelining).
``stop_at_seq_no`` throttles how far ordering may run ahead: two intervals
normally, one when a reconfiguration is pending (the network must quiesce
into the reconfigured state).

State transfer: when the node must catch up, a TEntry is persisted and a
state-transfer action emitted; a crash mid-transfer is detected on
reinitialize by a TEntry newer than the last CEntry.
"""

from __future__ import annotations

from dataclasses import replace

from .. import pb
from .actions import Actions, CheckpointReq, CommitAction, StateTarget
from .persisted import Persisted


def next_network_config(
    starting_state: pb.NetworkState, client_configs: list, logger=None
):
    """Apply pending reconfigurations to produce the next config + client
    set (reference: commitstate.go:192-226).

    Applications must be idempotent: after a reconfiguration reinitialize
    (or a crash replay) the same pending list is re-applied over client
    states that may already reflect it — so an add of an existing id and a
    remove of an absent id are skipped.  Skips are logged: on *first*
    application they indicate a bad app-issued reconfiguration."""
    if not starting_state.pending_reconfigurations:
        return starting_state.config, client_configs

    next_config = replace(starting_state.config)
    next_clients = list(client_configs)
    for reconfig in starting_state.pending_reconfigurations:
        change = reconfig.type
        if isinstance(change, pb.ReconfigNewClient):
            if all(c.id != change.id for c in next_clients):
                next_clients.append(
                    pb.NetworkClient(id=change.id, width=change.width)
                )
            elif logger is not None:
                logger.warn(
                    "skipping reconfiguration: client already exists "
                    "(replay, or a conflicting app-issued add)",
                    client_id=change.id,
                )
        elif isinstance(change, pb.ReconfigRemoveClient):
            remaining = [
                c for c in next_clients if c.id != change.client_id
            ]
            if len(remaining) == len(next_clients) and logger is not None:
                logger.warn(
                    "skipping reconfiguration: client to remove not "
                    "present (replay, or a bad app-issued remove)",
                    client_id=change.client_id,
                )
            next_clients = remaining
        elif isinstance(change, pb.NetworkConfig):
            next_config = change
        else:
            raise AssertionError(f"unknown reconfiguration {change!r}")
    return next_config, next_clients


class CommitState:
    def __init__(self, persisted: Persisted, client_tracker, logger=None):
        self.persisted = persisted
        self.client_tracker = client_tracker
        self.logger = logger

        self.low_watermark = 0
        self.last_applied_commit = 0
        self.highest_commit = 0
        self.stop_at_seq_no = 0
        self.active_state: pb.NetworkState | None = None
        self.lower_half: list = []
        self.upper_half: list = []
        self.checkpoint_pending = False
        self.transferring = False
        self.transfer_target: StateTarget | None = None
        # Set when a checkpoint result activates a pending reconfiguration:
        # the dispatcher must reinitialize every tracker from the log so the
        # new config/client set takes effect (the "common reconfiguration /
        # state transfer path" the reference aspires to at
        # state_machine.go:124 but never wires up — reconfig is its known
        # WIP hole; this rebuild closes it).
        self.reconfigured = False
        self.highest_persisted_checkpoint = 0
        # Epoch-change replay commits beyond the current stop: a correct
        # peer only prepared past a reconfiguration stop after that
        # checkpoint went stable, so these are guaranteed to become
        # committable once our own checkpoint result extends the stop —
        # hold them here until it does (drain flushes them).  The reference
        # has no equivalent and would panic in commit() in this scenario.
        self.deferred_replays: list = []  # [pb.QEntry], ascending

    # -- lifecycle -----------------------------------------------------------

    def reinitialize(self) -> Actions:
        last_c = None
        last_t = None

        def on_c(c_entry):
            nonlocal last_c
            last_c = c_entry

        def on_t(t_entry):
            nonlocal last_t
            last_t = t_entry

        self.persisted.iterate({pb.CEntry: on_c, pb.TEntry: on_t})

        # The newest checkpoint is authoritative (reference:
        # commitstate.go:85-100).  In particular, a checkpoint whose
        # predecessor carried pending reconfigurations already embodies the
        # *applied* new configuration (next_network_config ran when it was
        # computed), so every tracker must reinitialize into it — an
        # earlier revision rolled back to the pre-reconfig state here
        # "until the network quiesces", which silently stranded the epoch
        # tracker and member set on the old node set forever (the
        # activation checkpoint was then recomputed and the first-sight
        # guard suppressed the second activation).
        self.active_state = last_c.network_state
        self.low_watermark = last_c.seq_no

        ci = self.active_state.config.checkpoint_interval
        if not self.active_state.pending_reconfigurations:
            self.stop_at_seq_no = last_c.seq_no + 2 * ci
        else:
            self.stop_at_seq_no = last_c.seq_no + ci

        self.last_applied_commit = last_c.seq_no
        self.highest_commit = last_c.seq_no
        self.lower_half = [None] * ci
        self.upper_half = [None] * ci
        self.checkpoint_pending = False
        self.reconfigured = False
        self.highest_persisted_checkpoint = last_c.seq_no
        # Deferred replays were persisted as QEntries before being deferred;
        # the continued epoch change re-replays them from the log, so stale
        # in-memory copies (possibly from an abandoned target) must not
        # survive a reinitialize.
        self.deferred_replays = []

        if last_t is None or last_c.seq_no >= last_t.seq_no:
            self.transferring = False
            return Actions()

        # Crashed mid state-transfer: resume it.
        self.transferring = True
        self.transfer_target = StateTarget(
            seq_no=last_t.seq_no, value=last_t.value
        )
        actions = Actions()
        actions.state_transfer = self.transfer_target
        return actions

    def transfer_to(self, seq_no: int, value: bytes) -> Actions:
        if self.transferring:
            raise AssertionError("concurrent state transfers not supported")
        self.transferring = True
        self.transfer_target = StateTarget(seq_no=seq_no, value=value)
        actions = self.persisted.add_t_entry(
            pb.TEntry(seq_no=seq_no, value=value)
        )
        actions.state_transfer = self.transfer_target
        return actions

    def retry_transfer(self) -> Actions:
        """Re-request the in-flight transfer after the consumer reported
        failure (the target may have been garbage collected everywhere)."""
        if not self.transferring or self.transfer_target is None:
            raise AssertionError("no transfer in flight to retry")
        actions = Actions()
        actions.state_transfer = self.transfer_target
        return actions

    def retarget_transfer(self, seq_no: int, value: bytes) -> Actions:
        """Chase a newer certified checkpoint after the in-flight target
        failed.  A failed fetch usually means every donor GC'd the target
        because the network moved on; retrying the dead target forever
        wedges the node (observed as a replica stuck at seq 0 while the
        frontier runs away).  The caller passes the newest
        intersection-quorum-certified checkpoint — the same adoption
        authority the ordinary lag trigger uses — so jumping is safe."""
        if not self.transferring or self.transfer_target is None:
            raise AssertionError("no transfer in flight to retarget")
        if seq_no <= self.transfer_target.seq_no:
            raise AssertionError(
                f"retarget {seq_no} not beyond current target "
                f"{self.transfer_target.seq_no}"
            )
        self.transfer_target = StateTarget(seq_no=seq_no, value=value)
        actions = self.persisted.add_t_entry(
            pb.TEntry(seq_no=seq_no, value=value)
        )
        actions.state_transfer = self.transfer_target
        return actions

    # -- checkpoint results --------------------------------------------------

    def apply_checkpoint_result(
        self, epoch_config, result: pb.CheckpointResult
    ) -> Actions:
        ci = self.active_state.config.checkpoint_interval

        if self.transferring:
            return Actions()

        if result.seq_no != self.low_watermark + ci:
            raise AssertionError(
                f"checkpoint result for {result.seq_no}, expected "
                f"{self.low_watermark + ci}"
            )

        if not result.network_state.pending_reconfigurations:
            self.stop_at_seq_no = result.seq_no + 2 * ci
        # else: pending reconfigurations — do not extend the stop.

        activates_reconfig = bool(self.active_state.pending_reconfigurations)
        self.active_state = result.network_state
        self.lower_half = self.upper_half
        self.upper_half = [None] * ci
        self.low_watermark = result.seq_no
        self.checkpoint_pending = False

        actions = Actions()
        if result.seq_no > self.highest_persisted_checkpoint:
            if activates_reconfig:
                # This result was computed via next_network_config over the
                # pending reconfigurations: the new config/client set is
                # now active, pending a full tracker reinitialize.  Only on
                # first sight of this checkpoint — the post-reinitialize
                # recompute of the same seq_no must not re-trigger, or
                # activation would loop forever.
                self.reconfigured = True
            actions.concat(
                self.persisted.add_c_entry(
                    pb.CEntry(
                        seq_no=result.seq_no,
                        checkpoint_value=result.value,
                        network_state=result.network_state,
                    )
                )
            )
            self.highest_persisted_checkpoint = result.seq_no
        # else: recomputed after a reconfiguration reinitialize — the CEntry
        # is already durable; re-appending would duplicate it in the log.
        actions.send(
            self.active_state.config.nodes,
            pb.Msg(type=pb.Checkpoint(seq_no=result.seq_no, value=result.value)),
        )
        return actions.concat(self.client_tracker.drain())

    # -- commits -------------------------------------------------------------

    def commit(self, q_entry: pb.QEntry) -> None:
        if self.transferring:
            raise AssertionError("must never commit during state transfer")
        if q_entry.seq_no > self.stop_at_seq_no:
            raise AssertionError(
                f"commit {q_entry.seq_no} exceeds stop {self.stop_at_seq_no}"
            )
        if q_entry.seq_no <= self.low_watermark:
            # Replayed commits during epoch change: already applied.
            return

        if self.highest_commit < q_entry.seq_no:
            if self.highest_commit + 1 != q_entry.seq_no:
                raise AssertionError(
                    f"commit {q_entry.seq_no} skips ahead of highest "
                    f"{self.highest_commit}"
                )
            self.highest_commit = q_entry.seq_no

        ci = self.active_state.config.checkpoint_interval
        upper = q_entry.seq_no - self.low_watermark > ci
        offset = (q_entry.seq_no - (self.low_watermark + 1)) % ci
        commits = self.upper_half if upper else self.lower_half

        existing = commits[offset]
        if existing is not None:
            if existing.digest != q_entry.digest:
                raise AssertionError(
                    f"seq_no {q_entry.seq_no} previously committed "
                    f"{existing.digest!r} but now {q_entry.digest!r}"
                )
        else:
            commits[offset] = q_entry

    def defer_replay(self, q_entry: pb.QEntry) -> None:
        """Hold an epoch-change replay commit that is beyond the current
        stop until the stop extends (see deferred_replays above).  Newest
        wins per sequence: a later epoch change may legitimately select a
        different digest for the same seq_no than an abandoned one did."""
        self.deferred_replays = [
            d for d in self.deferred_replays if d.seq_no != q_entry.seq_no
        ]
        self.deferred_replays.append(q_entry)
        self.deferred_replays.sort(key=lambda q: q.seq_no)

    def drain(self) -> list:
        """All in-order commits ready for the application, interleaved with
        checkpoint requests at window boundaries (reference:
        commitstate.go:229-279)."""
        while (
            self.deferred_replays
            and self.deferred_replays[0].seq_no <= self.stop_at_seq_no
            and not self.transferring
        ):
            self.commit(self.deferred_replays.pop(0))

        ci = self.active_state.config.checkpoint_interval
        result: list[CommitAction] = []

        while self.last_applied_commit < self.low_watermark + 2 * ci:
            if (
                self.last_applied_commit == self.low_watermark + ci
                and not self.checkpoint_pending
            ):
                client_state = (
                    self.client_tracker.commits_completed_for_checkpoint_window(
                        self.last_applied_commit
                    )
                )
                network_config, client_configs = next_network_config(
                    self.active_state, client_state, self.logger
                )
                result.append(
                    CommitAction(
                        checkpoint=CheckpointReq(
                            seq_no=self.last_applied_commit,
                            network_config=network_config,
                            clients_state=client_configs,
                        )
                    )
                )
                self.checkpoint_pending = True

            next_commit = self.last_applied_commit + 1
            upper = next_commit - self.low_watermark > ci
            offset = (next_commit - (self.low_watermark + 1)) % ci
            commits = self.upper_half if upper else self.lower_half
            q_entry = commits[offset]
            if q_entry is None:
                break
            if q_entry.seq_no != next_commit:
                raise AssertionError(
                    f"out of order commit: {q_entry.seq_no} != {next_commit}"
                )

            result.append(CommitAction(batch=q_entry))
            for ack in q_entry.requests:
                self.client_tracker.mark_committed(
                    ack.client_id, ack.req_no, q_entry.seq_no
                )
            self.last_applied_commit = next_commit

        return result
