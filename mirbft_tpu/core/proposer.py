"""Batch assembly for the buckets this node leads.

Rebuild of the reference's proposer (reference: proposer.go:22-159).  The
proposer drains the client tracker's ready list (strongly certified requests
we hold locally) into per-owned-bucket queues, gated by each request's
``valid_after_seq_no`` — requests in the tail of a client's window only
become proposable after the next checkpoint (the readyList/nextReadyList
swap).  The active epoch cuts a batch when BatchSize requests are pending
(or any are pending, for heartbeat flushes).
"""

from __future__ import annotations

from .. import pb
from .client_tracker import ClientTracker
from .quorum import req_bucket

_NULL = b""


class ProposalBucket:
    def __init__(
        self,
        bucket_id: int,
        base_checkpoint: int,
        checkpoint_interval: int,
        batch_size: int,
    ):
        self.bucket_id = bucket_id
        self.checkpoint_interval = checkpoint_interval
        self.batch_size = batch_size
        # Advanced as the caller's sequence number crosses checkpoints; the
        # next_ready queue unlocks one checkpoint interval at a time.
        self.current_checkpoint = base_checkpoint
        self.ready: list = []  # proposable now
        self.next_ready: list = []  # proposable after the next checkpoint
        self.pending: list = []  # accumulating batch

    def queue_request(self, valid_after_seq_no: int, cr) -> None:
        if self.current_checkpoint >= valid_after_seq_no:
            self.ready.append(cr)
        else:
            if valid_after_seq_no != self.current_checkpoint + self.checkpoint_interval:
                raise AssertionError(
                    "requests never become ready beyond the next checkpoint"
                )
            self.next_ready.append(cr)

    def advance(self, to_seq_no: int) -> None:
        if to_seq_no >= self.current_checkpoint + self.checkpoint_interval:
            self.current_checkpoint += self.checkpoint_interval
            self.ready.extend(self.next_ready)
            self.next_ready = []
        while len(self.pending) < self.batch_size and self.ready:
            self.pending.append(self.ready.pop(0))

    def has_outstanding(self, for_seq_no: int) -> bool:
        """Anything at all to propose (heartbeat flush)."""
        self.advance(for_seq_no)
        return len(self.pending) > 0

    def has_pending(self, for_seq_no: int) -> bool:
        """A full batch to propose."""
        self.advance(for_seq_no)
        return 0 < len(self.pending) == self.batch_size

    def next_batch(self) -> list:
        result = self.pending
        self.pending = []
        return result


class Proposer:
    def __init__(
        self,
        base_checkpoint: int,
        checkpoint_interval: int,
        my_config: pb.InitialParameters,
        client_tracker: ClientTracker,
        buckets: dict,  # bucket_id -> leader node_id
    ):
        self.my_config = my_config
        self.total_buckets = len(buckets)
        self.proposal_buckets = {
            bucket_id: ProposalBucket(
                bucket_id=bucket_id,
                base_checkpoint=base_checkpoint,
                checkpoint_interval=checkpoint_interval,
                batch_size=my_config.batch_size,
            )
            for bucket_id, leader in buckets.items()
            if leader == my_config.id
        }
        self.ready_iterator = client_tracker.ready_list.iterator()

    def advance(self, to_seq_no: int) -> None:
        """Drain newly ready requests into our buckets' queues."""
        while self.ready_iterator.has_next():
            crn = self.ready_iterator.next()
            if crn.committed is not None:
                # Committed in a previous view but not yet GC'd.
                continue

            bucket_id = req_bucket(crn.client_id, crn.req_no, self.total_buckets)
            bucket = self.proposal_buckets.get(bucket_id)
            if bucket is None:
                continue  # not ours to propose

            bucket.advance(to_seq_no)

            if len(crn.strong_requests) > 1:
                null_req = crn.strong_requests.get(_NULL)
                if null_req is None:
                    raise AssertionError(
                        "multiple strong requests require a null request"
                    )
                bucket.queue_request(crn.valid_after_seq_no, null_req)
            else:
                if len(crn.strong_requests) != 1:
                    raise AssertionError("exactly one strong request expected")
                (only,) = crn.strong_requests.values()
                bucket.queue_request(crn.valid_after_seq_no, only)

    def proposal_bucket(self, bucket_id: int) -> ProposalBucket | None:
        return self.proposal_buckets.get(bucket_id)
