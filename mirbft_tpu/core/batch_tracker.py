"""Tracking and fetching of preprepared batches for epoch change.

Rebuild of the reference's batch tracker (reference: batch_tracker.go).
Every QEntry's batch is remembered (by digest, with the sequences that
referenced it) so that during epoch change a node can serve FetchBatch
requests from peers that selected a digest they don't hold; forwarded
batches are verified by rehashing before acceptance.
"""

from __future__ import annotations

from .. import pb
from .actions import Actions
from .persisted import Persisted


class _Batch:
    __slots__ = ("observed_sequences", "request_acks")

    def __init__(self, request_acks):
        self.observed_sequences = set()
        self.request_acks = request_acks


class BatchTracker:
    def __init__(self, persisted: Persisted, logger=None):
        self.persisted = persisted
        self.logger = logger
        self.batches_by_digest: dict[bytes, _Batch] = {}
        self.fetch_in_flight: dict[bytes, list] = {}  # digest -> [seq_no]
        self.fetch_sources: dict[bytes, list] = {}  # digest -> [node]

    def reinitialize(self) -> None:
        # Stale in-flight fetches would both re-broadcast forever and
        # suppress (via dedup) the re-issued fetches of the rebuilt epoch
        # target.
        self.abandon_fetches()
        self.persisted.iterate(
            {
                pb.QEntry: lambda q: self.add_batch(
                    q.seq_no, q.digest, q.requests
                )
            }
        )

    def step(self, source: int, msg: pb.Msg) -> Actions:
        inner = msg.type
        if isinstance(inner, pb.FetchBatch):
            return self.reply_fetch_batch(source, inner.seq_no, inner.digest)
        if isinstance(inner, pb.ForwardBatch):
            return self.apply_forward_batch(
                source, inner.seq_no, inner.digest, inner.request_acks
            )
        raise AssertionError(f"unexpected batch msg {type(inner).__name__}")

    def truncate(self, seq_no: int) -> None:
        for digest in list(self.batches_by_digest):
            batch = self.batches_by_digest[digest]
            batch.observed_sequences = {
                s for s in batch.observed_sequences if s >= seq_no
            }
            if not batch.observed_sequences:
                del self.batches_by_digest[digest]

    def add_batch(self, seq_no: int, digest: bytes, request_acks: list) -> None:
        batch = self.batches_by_digest.get(digest)
        if batch is None:
            batch = _Batch(request_acks)
            self.batches_by_digest[digest] = batch
        for in_flight_seq in self.fetch_in_flight.pop(digest, ()):
            batch.observed_sequences.add(in_flight_seq)
        self.fetch_sources.pop(digest, None)
        batch.observed_sequences.add(seq_no)

    def fetch_batch(self, seq_no: int, digest: bytes, sources: list) -> Actions:
        in_flight = self.fetch_in_flight.setdefault(digest, [])
        known = self.fetch_sources.setdefault(digest, [])
        for node in sources:
            if node not in known:
                known.append(node)
        if seq_no in in_flight:
            return Actions()
        in_flight.append(seq_no)
        return Actions().send(
            sources, pb.Msg(type=pb.FetchBatch(seq_no=seq_no, digest=digest))
        )

    def abandon_fetches(self) -> None:
        """Drop all in-flight fetches (the epoch target that wanted them is
        dead; its successor re-issues whatever it still needs)."""
        self.fetch_in_flight.clear()
        self.fetch_sources.clear()

    def retransmit_fetches(self) -> Actions:
        """Re-send every in-flight FetchBatch to its known holders (driven
        from the epoch target's FETCHING tick).  Without this, one lost or
        byzantine reply would stall the epoch change forever."""
        actions = Actions()
        for digest in sorted(self.fetch_in_flight):
            sources = self.fetch_sources.get(digest)
            if not sources:
                continue
            for seq_no in self.fetch_in_flight[digest]:
                actions.send(
                    list(sources),  # snapshot: the live list may grow later
                    pb.Msg(type=pb.FetchBatch(seq_no=seq_no, digest=digest)),
                )
        return actions

    def reply_fetch_batch(self, source: int, seq_no: int, digest: bytes) -> Actions:
        batch = self.batches_by_digest.get(digest)
        if batch is None:
            return Actions()  # not necessarily byzantine; just don't have it
        return Actions().send(
            [source],
            pb.Msg(
                type=pb.ForwardBatch(
                    seq_no=seq_no,
                    request_acks=batch.request_acks,
                    digest=digest,
                )
            ),
        )

    def apply_forward_batch(
        self, source: int, seq_no: int, digest: bytes, request_acks: list
    ) -> Actions:
        if digest not in self.fetch_in_flight:
            return Actions()  # unsolicited; can't trust it
        return Actions().hash(
            [ack.digest for ack in request_acks],
            pb.HashResult(
                digest=b"",
                type=pb.HashOriginVerifyBatch(
                    source=source,
                    seq_no=seq_no,
                    request_acks=request_acks,
                    expected_digest=digest,
                ),
            ),
        )

    def apply_verify_batch_hash_result(
        self, digest: bytes, verify: pb.HashOriginVerifyBatch
    ) -> None:
        if verify.expected_digest != digest:
            # A byzantine peer forwarded a batch that doesn't hash to the
            # digest we fetched.  Drop it and leave the fetch in flight so
            # retransmit_fetches (the epoch target's FETCHING tick) retries
            # the known holders.  (The reference panics here; a remote peer
            # must never crash us.)
            if self.logger is not None:
                self.logger.warn(
                    "dropping forwarded batch: does not hash to its "
                    "claimed digest",
                    source=verify.source,
                    seq_no=verify.seq_no,
                )
            return
        in_flight = self.fetch_in_flight.pop(digest, None)
        self.fetch_sources.pop(digest, None)
        if in_flight is None:
            return  # duplicate response; already satisfied
        batch = self.batches_by_digest.get(digest)
        if batch is None:
            batch = _Batch(verify.request_acks)
            self.batches_by_digest[digest] = batch
        batch.observed_sequences.update(in_flight)

    def has_fetch_in_flight(self) -> bool:
        return bool(self.fetch_in_flight)

    def get_batch(self, digest: bytes) -> _Batch | None:
        return self.batches_by_digest.get(digest)
