"""Byte-budgeted buffering of not-yet-applyable messages, shared per remote
node across all protocol components.

Rebuild of the reference's msg buffers (reference: msgbuffers.go:17-161).
Each component classifies a message as PAST (drop), CURRENT (apply), FUTURE
(buffer until watermarks move), or INVALID (drop); one byte budget per
remote node (InitialParameters.buffer_size) is shared by all components'
buffers so a spammy peer can't hold unbounded memory.  On overflow the
oldest buffered message is dropped first.
"""

from __future__ import annotations

import enum

from .. import pb


class Applyable(enum.Enum):
    PAST = 0
    CURRENT = 1
    FUTURE = 2
    INVALID = 3


class NodeBuffers:
    """Tracks one shared byte budget per remote node."""

    def __init__(self, my_config: pb.InitialParameters, logger=None):
        self.my_config = my_config
        self.logger = logger
        self._nodes: dict[int, NodeBuffer] = {}

    def node_buffer(self, source: int) -> "NodeBuffer":
        nb = self._nodes.get(source)
        if nb is None:
            nb = NodeBuffer(source, self.my_config, self.logger)
            self._nodes[source] = nb
        return nb


class NodeBuffer:
    def __init__(self, node_id: int, my_config: pb.InitialParameters, logger=None):
        self.node_id = node_id
        self.my_config = my_config
        self.logger = logger
        self.total_size = 0

    def over_capacity(self) -> bool:
        return self.total_size > self.my_config.buffer_size


class MsgBuffer:
    """One component's FIFO of buffered messages from one node."""

    def __init__(self, component: str, node_buffer: NodeBuffer):
        self.component = component
        self.node_buffer = node_buffer
        # Public backing list: consensus hot paths (active_epoch.drain_buffers)
        # test emptiness via attribute access, which profiles meaningfully
        # faster than a __len__ dispatch per bucket per event.
        self.msgs: list[tuple[pb.Msg, int]] = []

    def __len__(self) -> int:
        return len(self.msgs)

    def store(self, msg: pb.Msg) -> None:
        size = len(pb.encode(msg))
        while self.node_buffer.over_capacity() and self.msgs:
            _, old_size = self.msgs.pop(0)
            self.node_buffer.total_size -= old_size
            if self.node_buffer.logger is not None:
                self.node_buffer.logger.warn(
                    "dropping buffered msg",
                    component=self.component,
                    node=self.node_buffer.node_id,
                )
        self.msgs.append((msg, size))
        self.node_buffer.total_size += size

    def next(self, filter_fn):
        """Remove and return the first CURRENT message; drop PAST/INVALID
        encountered on the way; leave FUTURE in place."""
        i = 0
        while i < len(self.msgs):
            msg, size = self.msgs[i]
            verdict = filter_fn(self.node_buffer.node_id, msg)
            if verdict is Applyable.FUTURE:
                i += 1
                continue
            del self.msgs[i]
            self.node_buffer.total_size -= size
            if verdict is Applyable.CURRENT:
                return msg
            # PAST / INVALID: dropped, keep scanning.
        return None

    def iterate(self, filter_fn, apply_fn) -> None:
        """Apply every CURRENT message, drop PAST/INVALID, keep FUTURE."""
        i = 0
        while i < len(self.msgs):
            msg, size = self.msgs[i]
            verdict = filter_fn(self.node_buffer.node_id, msg)
            if verdict is Applyable.FUTURE:
                i += 1
                continue
            del self.msgs[i]
            self.node_buffer.total_size -= size
            if verdict is Applyable.CURRENT:
                apply_fn(self.node_buffer.node_id, msg)
