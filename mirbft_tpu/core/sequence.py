"""The per-(seq_no, epoch) three-phase commit cell.

Rebuild of the reference's sequence FSM (reference: sequence.go:15-359).
State flow:

    UNINITIALIZED --allocate--> ALLOCATED
      (empty batch: straight to READY with a nil digest)
    ALLOCATED -> PENDING_REQUESTS  (batch digest requested via Actions.hash)
    PENDING_REQUESTS --all outstanding requests present--> READY
    READY --digest known--> PREPREPARED
      (persist QEntry; owner broadcasts Preprepare + forwards request data
       to nodes that haven't ACKed; followers broadcast Prepare)
    PREPREPARED --2f+1 prepares incl. own--> PREPARED
      (persist PEntry; broadcast Commit)
    PREPARED --2f+1 commits incl. own--> COMMITTED

Quorums are intersection quorums (2f+1 out of 3f+1): the owner's Preprepare
counts as its Prepare, and our own vote is required before advancing past
PREPREPARED/PREPARED so that the QEntry/PEntry is durable before we
participate (the persist→send safety contract, docs/Processor.md).
"""

from __future__ import annotations

import enum

from .. import pb
from ..obsv import hooks
from .actions import Actions
from .persisted import Persisted
from .quorum import intersection_quorum, seq_to_bucket


class SeqState(enum.IntEnum):
    UNINITIALIZED = 0
    ALLOCATED = 1
    PENDING_REQUESTS = 2
    READY = 3
    PREPREPARED = 4
    PREPARED = 5
    COMMITTED = 6


class _NodeState(enum.IntEnum):
    UNINITIALIZED = 0
    PREPREPARED = 1
    PREPARED = 2


class _NodeChoice:
    """What one node has already claimed about this sequence — the
    equivocation guard (reference: sequence.go:27-38)."""

    __slots__ = ("state", "digest")

    def __init__(self):
        self.state = _NodeState.UNINITIALIZED
        self.digest = None


class Sequence:
    def __init__(
        self,
        owner: int,
        epoch: int,
        seq_no: int,
        persisted: Persisted,
        network_config: pb.NetworkConfig,
        my_config: pb.InitialParameters,
        logger=None,
    ):
        self.owner = owner
        self.epoch = epoch
        self.seq_no = seq_no
        self.persisted = persisted
        self.network_config = network_config
        self.my_config = my_config
        self.logger = logger

        self.state = SeqState.UNINITIALIZED
        self.q_entry: pb.QEntry | None = None
        # Set only when we own this sequence and proposed the batch ourselves;
        # items expose .ack (pb.RequestAck) and .agreements (node-id bitmask).
        self.client_requests: list | None = None
        self.batch: list | None = None  # [pb.RequestAck]
        self.outstanding_reqs: set | None = None  # digests not yet available
        self.digest: bytes | None = None
        self._node_choices: dict[int, _NodeChoice] = {}
        self._prepares: dict[bytes, int] = {}
        self._commits: dict[bytes, int] = {}

    def _node_choice(self, source: int) -> _NodeChoice:
        choice = self._node_choices.get(source)
        if choice is None:
            choice = _NodeChoice()
            self._node_choices[source] = choice
        return choice

    # -- state advancement ---------------------------------------------------

    def advance_state(self) -> Actions:
        actions = Actions()
        while True:
            old_state = self.state
            if self.state == SeqState.PENDING_REQUESTS:
                self._check_requests()
            elif self.state == SeqState.READY:
                if self.digest is not None or not self.batch:
                    actions.concat(self._prepare())
            elif self.state == SeqState.PREPREPARED:
                actions.concat(self._check_prepare_quorum())
            elif self.state == SeqState.PREPARED:
                self._check_commit_quorum()
            if self.state == old_state:
                return actions

    # -- allocation ----------------------------------------------------------

    def allocate_as_owner(self, client_requests: list) -> Actions:
        self.client_requests = client_requests
        return self.allocate([cr.ack for cr in client_requests], None)

    def allocate(self, request_acks: list, outstanding_reqs: set | None) -> Actions:
        if self.state != SeqState.UNINITIALIZED:
            raise AssertionError(
                f"seq_no={self.seq_no} must be uninitialized to allocate"
            )

        self.state = SeqState.ALLOCATED
        self.batch = request_acks
        self.outstanding_reqs = outstanding_reqs
        if hooks.enabled:
            hooks.milestone(
                "seq.allocated",
                self.my_config.id,
                self.seq_no,
                epoch=self.epoch,
                bucket=seq_to_bucket(self.seq_no, self.network_config),
            )

        if not request_acks:
            # Null batch: nothing to digest.
            self.state = SeqState.READY
            return self.apply_batch_hash_result(None)

        actions = Actions().hash(
            [ack.digest for ack in request_acks],
            pb.HashResult(
                digest=b"",
                type=pb.HashOriginBatch(
                    source=self.owner,
                    epoch=self.epoch,
                    seq_no=self.seq_no,
                    request_acks=request_acks,
                ),
            ),
        )

        self.state = SeqState.PENDING_REQUESTS
        return actions.concat(self.advance_state())

    def satisfy_outstanding(self, ack: pb.RequestAck) -> Actions:
        if ack.digest not in self.outstanding_reqs:
            raise AssertionError(
                f"request {ack.digest!r} satisfied but never awaited"
            )
        self.outstanding_reqs.discard(ack.digest)
        return self.advance_state()

    def _check_requests(self) -> None:
        if self.outstanding_reqs:
            return
        self.state = SeqState.READY

    # -- preprepare / prepare ------------------------------------------------

    def apply_batch_hash_result(self, digest: bytes | None) -> Actions:
        self.digest = digest
        return self.apply_prepare_msg(self.owner, digest)

    def _prepare(self) -> Actions:
        self.q_entry = pb.QEntry(
            seq_no=self.seq_no,
            digest=self.digest or b"",
            requests=self.batch,
        )
        self.state = SeqState.PREPREPARED
        if hooks.enabled:
            hooks.milestone(
                "seq.preprepared",
                self.my_config.id,
                self.seq_no,
                epoch=self.epoch,
                bucket=seq_to_bucket(self.seq_no, self.network_config),
            )

        actions = Actions()
        if self.owner == self.my_config.id:
            # Forward request data to nodes that haven't ACKed having it.
            for cr in self.client_requests or ():
                agreements = cr.agreements
                missing = [
                    node_id
                    for node_id in self.network_config.nodes
                    if not agreements & (1 << node_id)
                ]
                actions.forward_request(missing, cr.ack)
            actions.send(
                self.network_config.nodes,
                pb.Msg(
                    type=pb.Preprepare(
                        seq_no=self.seq_no, epoch=self.epoch, batch=self.batch
                    )
                ),
            )
        else:
            actions.send(
                self.network_config.nodes,
                pb.Msg(
                    type=pb.Prepare(
                        seq_no=self.seq_no,
                        epoch=self.epoch,
                        digest=self.digest or b"",
                    )
                ),
            )
        return actions.concat(self.persisted.add_q_entry(self.q_entry))

    def apply_prepare_msg(self, source: int, digest: bytes | None) -> Actions:
        choice = self._node_choice(source)
        # Duplicate-prepare guard for every source.  (The reference exempts
        # the owner, sequence.go:263-269, which lets the owner's vote be
        # counted twice at its own node — once from the batch hash result
        # and once from the self-delivered Preprepare — shaving a node off
        # the effective prepare quorum there.)
        if choice.state > _NodeState.UNINITIALIZED:
            return Actions()
        choice.state = _NodeState.PREPREPARED
        choice.digest = digest
        key = digest or b""
        self._prepares[key] = self._prepares.get(key, 0) + 1
        return self.advance_state()

    def _check_prepare_quorum(self) -> Actions:
        key = self.digest or b""
        agreements = self._prepares.get(key, 0)

        # Our own prepare must be in (ensures our QEntry persist was issued).
        my_choice = self._node_choice(self.my_config.id)
        if my_choice.state < _NodeState.PREPREPARED:
            return Actions()
        if (my_choice.digest or b"") != key:
            # The network agreed on a different digest than ours; we cannot
            # participate further in this sequence.
            return Actions()

        if agreements < intersection_quorum(self.network_config):
            return Actions()

        self.state = SeqState.PREPARED
        if hooks.enabled:
            hooks.milestone(
                "seq.prepared",
                self.my_config.id,
                self.seq_no,
                epoch=self.epoch,
                bucket=seq_to_bucket(self.seq_no, self.network_config),
            )

        actions = Actions().send(
            self.network_config.nodes,
            pb.Msg(
                type=pb.Commit(
                    seq_no=self.seq_no, epoch=self.epoch, digest=key
                )
            ),
        )
        return actions.concat(
            self.persisted.add_p_entry(
                pb.PEntry(seq_no=self.seq_no, digest=key)
            )
        )

    # -- commit --------------------------------------------------------------

    def apply_commit_msg(self, source: int, digest: bytes | None) -> Actions:
        choice = self._node_choice(source)
        if choice.state > _NodeState.PREPREPARED:
            return Actions()
        choice.state = _NodeState.PREPARED
        key = digest or b""
        self._commits[key] = self._commits.get(key, 0) + 1
        return self.advance_state()

    def _check_commit_quorum(self) -> None:
        key = self.digest or b""
        agreements = self._commits.get(key, 0)

        # Do not commit until we've sent our own commit (PEntry persisted).
        my_choice = self._node_choice(self.my_config.id)
        if my_choice.state < _NodeState.PREPARED:
            return

        if agreements < intersection_quorum(self.network_config):
            return

        self.state = SeqState.COMMITTED
        if hooks.enabled:
            hooks.milestone(
                "seq.commit_quorum",
                self.my_config.id,
                self.seq_no,
                epoch=self.epoch,
                bucket=seq_to_bucket(self.seq_no, self.network_config),
            )
