"""Normal-case multi-leader ordering within one epoch.

Rebuild of the reference's active epoch (reference: epoch_active.go:21-526).
The sequence-number space is partitioned into buckets, each owned by a
leader; disjoint leaders drive disjoint partitions concurrently — Mir's
throughput idea.  Responsibilities:

- bucket→leader assignment, rotating non-leader buckets onto the leader set
  (overflow assignment);
- sequence allocation one checkpoint interval at a time (each row preceded
  by an NEntry persist), bounded by the epoch's planned expiration and the
  commit state's stop-at throttle;
- strict in-order admission of each bucket's preprepares (a per-bucket
  next-seq cursor; later preprepares buffer until their predecessor
  applies);
- fan-in of prepares/commits to the sequence FSMs and in-order drain of
  committed sequences into the commit state;
- proposer invocation for owned buckets; heartbeat (null-batch) fill and
  suspect-on-stall ticks.
"""

from __future__ import annotations

from .. import pb
from .actions import Actions
from .client_tracker import ClientTracker
from .commitstate import CommitState
from .msgbuffers import Applyable, MsgBuffer, NodeBuffers
from .outstanding import InvalidPreprepare, OutstandingReqs
from .persisted import Persisted
from .proposer import Proposer
from .quorum import seq_to_bucket
from .sequence import Sequence, SeqState


def assign_buckets(
    epoch_number: int, leaders: list, network_config: pb.NetworkConfig
) -> dict:
    """bucket_id -> leader node, rotating by epoch number; buckets whose
    rotation lands on a non-leader overflow onto the leader set round-robin
    (reference: epoch_active.go:52-69)."""
    leader_set = set(leaders)
    nodes = network_config.nodes
    buckets = {}
    overflow = 0
    for i in range(network_config.number_of_buckets):
        candidate = nodes[(i + epoch_number) % len(nodes)]
        if candidate in leader_set:
            buckets[i] = candidate
        else:
            buckets[i] = leaders[overflow % len(leaders)]
            overflow += 1
    return buckets


class _PreprepareBuffer:
    __slots__ = ("next_seq_no", "buffer")

    def __init__(self, next_seq_no: int, buffer: MsgBuffer):
        self.next_seq_no = next_seq_no
        self.buffer = buffer


class ActiveEpoch:
    def __init__(
        self,
        epoch_config: pb.EpochConfig,
        persisted: Persisted,
        node_buffers: NodeBuffers,
        commit_state: CommitState,
        client_tracker: ClientTracker,
        my_config: pb.InitialParameters,
        logger=None,
    ):
        self.epoch_config = epoch_config
        self.network_config = commit_state.active_state.config
        self.my_config = my_config
        self.logger = logger
        self.persisted = persisted
        self.commit_state = commit_state

        starting_seq_no = commit_state.highest_commit

        self.outstanding_reqs = OutstandingReqs(
            client_tracker, commit_state.active_state, logger
        )
        self.buckets = assign_buckets(
            epoch_config.number, epoch_config.leaders, self.network_config
        )

        n_buckets = len(self.buckets)
        self.lowest_unallocated = [0] * n_buckets
        for i in range(n_buckets):
            first_seq_no = starting_seq_no + i + 1
            self.lowest_unallocated[
                seq_to_bucket(first_seq_no, self.network_config)
            ] = first_seq_no

        self.lowest_uncommitted = starting_seq_no + 1

        self.proposer = Proposer(
            starting_seq_no,
            self.network_config.checkpoint_interval,
            my_config,
            client_tracker,
            self.buckets,
        )

        self.preprepare_buffers = [
            _PreprepareBuffer(
                next_seq_no=self.lowest_unallocated[i],
                buffer=MsgBuffer(
                    f"epoch-{epoch_config.number}-preprepare",
                    node_buffers.node_buffer(self.buckets[i]),
                ),
            )
            for i in range(n_buckets)
        ]
        self.other_buffers = {
            node: MsgBuffer(
                f"epoch-{epoch_config.number}-other",
                node_buffers.node_buffer(node),
            )
            for node in self.network_config.nodes
        }

        # Rows of checkpoint_interval sequences; row 0 starts at low
        # watermark.
        self.sequences: list[list[Sequence]] = []

        self.last_committed_at_tick = 0
        self.ticks_since_progress = 0
        # Set when a preprepare fails the in-order client contract — grounds
        # for suspicion (the reference panics with a TODO here,
        # epoch_active.go:281-284).
        self.suspect_bucket_violation = False

    # -- watermarks / lookup -------------------------------------------------

    def low_watermark(self) -> int:
        return self.sequences[0][0].seq_no

    def high_watermark(self) -> int:
        if not self.sequences:
            return self.commit_state.low_watermark
        return self.sequences[-1][-1].seq_no

    def in_watermarks(self, seq_no: int) -> bool:
        return self.low_watermark() <= seq_no <= self.high_watermark()

    def seq_bucket(self, seq_no: int) -> int:
        return seq_to_bucket(seq_no, self.network_config)

    def sequence(self, seq_no: int) -> Sequence:
        ci = self.network_config.checkpoint_interval
        index = (seq_no - self.low_watermark()) // ci
        offset = (seq_no - self.low_watermark()) % ci
        seq = self.sequences[index][offset]
        if seq.seq_no != seq_no:
            raise AssertionError(f"sequence table corrupt at {seq_no}")
        return seq

    # -- message handling ----------------------------------------------------

    def filter(self, source: int, msg: pb.Msg) -> Applyable:
        inner = msg.type
        if isinstance(inner, pb.Preprepare):
            seq_no = inner.seq_no
            bucket = self.seq_bucket(seq_no)
            if self.buckets[bucket] != source:
                return Applyable.INVALID
            if seq_no > self.epoch_config.planned_expiration:
                return Applyable.INVALID
            if seq_no > self.high_watermark():
                return Applyable.FUTURE
            if seq_no < self.low_watermark():
                return Applyable.PAST
            next_preprepare = self.preprepare_buffers[bucket].next_seq_no
            if seq_no < next_preprepare:
                return Applyable.PAST
            if seq_no > next_preprepare:
                return Applyable.FUTURE
            return Applyable.CURRENT
        if isinstance(inner, pb.Prepare):
            seq_no = inner.seq_no
            if self.buckets[self.seq_bucket(seq_no)] == source:
                return Applyable.INVALID  # owners never send Prepare
            if seq_no > self.epoch_config.planned_expiration:
                return Applyable.INVALID
        elif isinstance(inner, pb.Commit):
            seq_no = inner.seq_no
            if seq_no > self.epoch_config.planned_expiration:
                return Applyable.INVALID
        else:
            raise AssertionError(f"unexpected msg {type(inner).__name__}")
        if seq_no < self.low_watermark():
            return Applyable.PAST
        if seq_no > self.high_watermark():
            return Applyable.FUTURE
        return Applyable.CURRENT

    def step(self, source: int, msg: pb.Msg) -> Actions:
        verdict = self.filter(source, msg)
        if verdict is Applyable.CURRENT:
            return self.apply(source, msg)
        if verdict is Applyable.FUTURE:
            if isinstance(msg.type, pb.Preprepare):
                bucket = self.seq_bucket(msg.type.seq_no)
                self.preprepare_buffers[bucket].buffer.store(msg)
            else:
                self.other_buffers[source].store(msg)
        return Actions()

    def apply(self, source: int, msg: pb.Msg) -> Actions:
        actions = Actions()
        inner = msg.type
        if isinstance(inner, pb.Preprepare):
            bucket = self.seq_bucket(inner.seq_no)
            pp_buffer = self.preprepare_buffers[bucket]
            next_msg = msg
            while next_msg is not None:
                pp = next_msg.type
                actions.concat(
                    self.apply_preprepare_msg(source, pp.seq_no, pp.batch)
                )
                pp_buffer.next_seq_no += len(self.buckets)
                next_msg = pp_buffer.buffer.next(self.filter)
        elif isinstance(inner, pb.Prepare):
            actions.concat(
                self.sequence(inner.seq_no).apply_prepare_msg(
                    source, inner.digest
                )
            )
        elif isinstance(inner, pb.Commit):
            actions.concat(
                self.apply_commit_msg(source, inner.seq_no, inner.digest)
            )
        else:
            raise AssertionError(f"unexpected msg {type(inner).__name__}")
        return actions

    def apply_preprepare_msg(
        self, source: int, seq_no: int, batch: list
    ) -> Actions:
        seq = self.sequence(seq_no)

        if seq.owner == self.my_config.id:
            # Our own self-delivered Preprepare: the allocation path already
            # advanced the cursors and counted our vote; the sequence's
            # duplicate guard makes this a no-op.
            return seq.apply_prepare_msg(source, seq.digest)

        bucket = self.seq_bucket(seq_no)
        if seq_no != self.lowest_unallocated[bucket]:
            raise AssertionError(
                "step must defer all but the next expected preprepare"
            )
        self.lowest_unallocated[bucket] += len(self.buckets)

        try:
            return self.outstanding_reqs.apply_acks(bucket, seq, batch)
        except InvalidPreprepare:
            # The leader equivocated or broke client order: grounds for
            # suspicion.  The epoch target turns this flag into a Suspect.
            self.suspect_bucket_violation = True
            return Actions()

    def apply_commit_msg(self, source: int, seq_no: int, digest: bytes) -> Actions:
        seq = self.sequence(seq_no)
        # The commit can be the very event that advances a lagging sequence
        # through its prepare transitions (real transports deliver peers'
        # commits while we are still preparing), and those transitions emit
        # persists and sends — dropping them skips WAL indices.
        actions = seq.apply_commit_msg(source, digest)
        if seq.state != SeqState.COMMITTED or seq_no != self.lowest_uncommitted:
            return actions

        while self.lowest_uncommitted <= self.high_watermark():
            seq = self.sequence(self.lowest_uncommitted)
            if seq.state != SeqState.COMMITTED:
                break
            self.commit_state.commit(seq.q_entry)
            self.lowest_uncommitted += 1
        return actions

    def apply_batch_hash_result(self, seq_no: int, digest: bytes) -> Actions:
        if not self.in_watermarks(seq_no):
            return Actions()  # benign after state transfer
        return self.sequence(seq_no).apply_batch_hash_result(digest)

    # -- watermark movement / allocation -------------------------------------

    def move_low_watermark(self, seq_no: int):
        """Returns (actions, epoch_done)."""
        if seq_no == self.epoch_config.planned_expiration:
            return Actions(), True
        if seq_no == self.commit_state.stop_at_seq_no:
            return Actions(), True

        actions = self.advance()
        # The epoch may legitimately hold no rows (e.g. freshly activated
        # after a reconfiguration with its allocation already at the stop);
        # there is then nothing to slide past.
        while self.sequences and seq_no > self.low_watermark():
            self.sequences.pop(0)
        return actions, False

    def drain_buffers(self) -> Actions:
        actions = Actions()
        # Hot path: this runs once per event per bucket/node, and the
        # buffers are nearly always empty — test MsgBuffer's public backing
        # list to skip without a method call.
        for bucket in range(len(self.buckets)):
            pp_buffer = self.preprepare_buffers[bucket]
            if not pp_buffer.buffer.msgs:
                continue
            source = self.buckets[bucket]
            next_msg = pp_buffer.buffer.next(self.filter)
            if next_msg is not None:
                # apply() loops consecutive preprepares internally.
                actions.concat(self.apply(source, next_msg))
        for node in self.network_config.nodes:
            buffer = self.other_buffers[node]
            if not buffer.msgs:
                continue
            buffer.iterate(
                self.filter,
                lambda src, msg: actions.concat(self.apply(src, msg)),
            )
        return actions

    def advance(self) -> Actions:
        """Allocate sequence rows up to the epoch/stop bounds, drain
        buffers, and cut batches for owned buckets."""
        actions = Actions()

        ci = self.network_config.checkpoint_interval
        while (
            self.high_watermark() < self.epoch_config.planned_expiration
            and self.high_watermark() < self.commit_state.stop_at_seq_no
        ):
            base = self.high_watermark()
            actions.concat(
                self.persisted.add_n_entry(
                    pb.NEntry(seq_no=base + 1, epoch_config=self.epoch_config)
                )
            )
            row = []
            for i in range(ci):
                seq_no = base + 1 + i
                row.append(
                    Sequence(
                        owner=self.buckets[self.seq_bucket(seq_no)],
                        epoch=self.epoch_config.number,
                        seq_no=seq_no,
                        persisted=self.persisted,
                        network_config=self.network_config,
                        my_config=self.my_config,
                        logger=self.logger,
                    )
                )
            self.sequences.append(row)

        actions.concat(self.drain_buffers())

        self.proposer.advance(self.lowest_uncommitted)

        for bucket, owner in self.buckets.items():
            if owner != self.my_config.id:
                continue
            prb = self.proposer.proposal_bucket(bucket)
            while True:
                seq_no = self.lowest_unallocated[bucket]
                if seq_no > self.high_watermark():
                    break
                if not prb.has_pending(seq_no):
                    break
                seq = self.sequence(seq_no)
                actions.concat(seq.allocate_as_owner(prb.next_batch()))
                self.lowest_unallocated[bucket] += len(self.buckets)
        return actions

    # -- ticks ---------------------------------------------------------------

    def _export_bucket_backlog(self) -> None:
        """Per-bucket backlog gauges, sampled on tick: sequences past
        UNINITIALIZED but not yet COMMITTED inside the active window.
        A persistently lopsided backlog is the skewed-traffic signal —
        one leader's bucket absorbing the hot clients while the others
        idle (status.py surfaces the max/median ratio)."""
        from ..obsv import hooks

        if not hooks.enabled:
            return
        backlog = self.bucket_backlog()
        m = hooks.metrics
        for bucket, depth in enumerate(backlog):
            m.gauge("mirbft_bucket_backlog", bucket=str(bucket)).set(depth)

    def bucket_backlog(self) -> list:
        """In-flight (allocated-but-uncommitted) sequence count per
        bucket over the active window."""
        backlog = [0] * len(self.buckets)
        for seq_no in range(self.low_watermark(), self.high_watermark() + 1):
            state = self.sequence(seq_no).state
            if state not in (SeqState.UNINITIALIZED, SeqState.COMMITTED):
                backlog[self.seq_bucket(seq_no)] += 1
        return backlog

    def tick(self) -> Actions:
        self._export_bucket_backlog()
        if self.last_committed_at_tick < self.commit_state.highest_commit:
            self.last_committed_at_tick = self.commit_state.highest_commit
            self.ticks_since_progress = 0
            return Actions()

        self.ticks_since_progress += 1
        actions = Actions()

        if self.ticks_since_progress > self.my_config.suspect_ticks:
            suspect = pb.Suspect(epoch=self.epoch_config.number)
            actions.send(self.network_config.nodes, pb.Msg(type=suspect))
            actions.concat(self.persisted.add_suspect(suspect))

        if (
            self.my_config.heartbeat_ticks == 0
            or self.ticks_since_progress % self.my_config.heartbeat_ticks != 0
        ):
            return actions

        # Heartbeat: fill our unallocated owned sequences with (possibly
        # empty) batches so followers see progress.
        for bucket, unallocated in enumerate(self.lowest_unallocated):
            if unallocated > self.high_watermark():
                continue
            if self.buckets[bucket] != self.my_config.id:
                continue
            seq = self.sequence(unallocated)
            prb = self.proposer.proposal_bucket(bucket)
            client_reqs = []
            if prb.has_outstanding(unallocated):
                client_reqs = prb.next_batch()
            actions.concat(seq.allocate_as_owner(client_reqs))
            self.lowest_unallocated[bucket] += len(self.buckets)
        return actions
