"""The deterministic protocol state machine: the framework's L1 entry point.

Rebuild of the reference's dispatcher (reference: state_machine.go:95-476).
The contract (docs/StateMachine.md discipline): a single-threaded, I/O-free,
clock-free function from StateEvents to Actions.  Every input — inbound
message, local proposal, tick, hash/checkpoint result, WAL replay — is a
serializable event, which is what makes every run recordable and replayable.

Lifecycle: Initialize → LoadEntry* → LoadRequest* → CompleteInitialization
(the runtime's bootstrap WAL synthesizes the initial CEntry+FEntry for fresh
starts, reference: mirbft.go:162-190).  After every event the dispatcher
garbage-collects if a checkpoint became stable, then runs the commit-drain +
epoch-advance fixed point until quiescent.
"""

from __future__ import annotations

import enum
import time

from .. import pb
from ..obsv import hooks
from .actions import Actions
from .batch_tracker import BatchTracker
from .checkpoints import CheckpointTracker
from .client_tracker import ClientTracker
from .commitstate import CommitState
from .epoch_target import TargetState
from .epoch_tracker import EpochTracker
from .msgbuffers import NodeBuffers
from .persisted import Persisted
from .preimage import request_hash_data

from .actions import EMPTY_ACTIONS as _EMPTY_ACTIONS  # shared hot-path empty


class _SMState(enum.Enum):
    UNINITIALIZED = 0
    LOADING = 1
    INITIALIZED = 2


class StateMachine:
    def __init__(self, logger=None, ack_plane=None, ack_flush_rows=None):
        self.logger = logger
        # Ack-plane selection is operational (not consensus state), so it
        # rides here rather than in pb.InitialParameters — the serialized
        # parameter record stays wire-compatible across deployments that
        # mix host- and device-plane nodes.
        self.ack_plane = ack_plane
        self.ack_flush_rows = ack_flush_rows
        self._state = _SMState.UNINITIALIZED

        self.my_config: pb.InitialParameters | None = None
        self.persisted: Persisted | None = None
        self.node_buffers: NodeBuffers | None = None
        self.checkpoint_tracker: CheckpointTracker | None = None
        self.client_tracker: ClientTracker | None = None
        self.commit_state: CommitState | None = None
        self.batch_tracker: BatchTracker | None = None
        self.epoch_tracker: EpochTracker | None = None
        self._loaded_reqs: list = []
        # Active member set; messages from non-members (e.g. a node removed
        # by reconfiguration that has not yet stopped sending) are dropped
        # at ingress — per-source buffers and quorum maps are keyed by the
        # active config and must never see foreign ids.
        self._members: frozenset = frozenset()
        # Set when an adopted configuration no longer includes this node:
        # the embedder should drain and shut the process down cleanly (the
        # survivors already drop our messages at ingress).
        self.retired = False
        self.reconfigs_adopted = 0

    # -- lifecycle -----------------------------------------------------------

    def _initialize(self, parameters: pb.InitialParameters) -> None:
        if self._state is not _SMState.UNINITIALIZED:
            raise AssertionError("state machine already initialized")
        self.my_config = parameters
        self._state = _SMState.LOADING

        self.persisted = Persisted(self.logger)
        self.node_buffers = NodeBuffers(parameters, self.logger)
        self.checkpoint_tracker = CheckpointTracker(
            self.persisted, self.node_buffers, parameters, self.logger
        )
        self.client_tracker = ClientTracker(
            self.persisted, self.node_buffers, parameters, self.logger,
            ack_plane=self.ack_plane,
            ack_flush_rows=self.ack_flush_rows,
        )
        self.commit_state = CommitState(
            self.persisted, self.client_tracker, self.logger
        )
        self.batch_tracker = BatchTracker(self.persisted, self.logger)
        self.epoch_tracker = EpochTracker(
            self.persisted,
            self.node_buffers,
            self.commit_state,
            parameters,
            self.batch_tracker,
            self.client_tracker,
            self.logger,
        )

    def _complete_initialization(self) -> Actions:
        if self._state is not _SMState.LOADING:
            raise AssertionError("not loading")
        self._state = _SMState.INITIALIZED
        return self._reinitialize()

    def _reinitialize(self) -> Actions:
        """Rebuild every tracker from the persisted log (start, state
        transfer, or reconfiguration)."""
        actions = self._recover_log()
        self.client_tracker.reinitialize()

        for ack in self._loaded_reqs:
            # Requests found uncommitted in the request store at startup.
            self.client_tracker.apply_request_digest(ack, b"")
        self._loaded_reqs = []

        actions.concat(self.commit_state.reinitialize())
        self._members = frozenset(
            self.commit_state.active_state.config.nodes
        )
        if self.my_config is not None and self.my_config.id not in self._members:
            self.retired = True
        self.checkpoint_tracker.reinitialize()
        self.batch_tracker.reinitialize()
        return actions.concat(self.epoch_tracker.reinitialize())

    def _recover_log(self) -> Actions:
        """Resume an interrupted FEntry truncation (reference:
        state_machine.go:292-310)."""
        last_c_entry = None
        actions = Actions()

        def on_c(entry):
            nonlocal last_c_entry
            last_c_entry = entry

        def on_f(_entry):
            if last_c_entry is None:
                raise AssertionError("FEntry without CEntry: corrupt log")
            actions.concat(self.persisted.truncate(last_c_entry.seq_no))

        self.persisted.iterate({pb.CEntry: on_c, pb.FEntry: on_f})
        if last_c_entry is None:
            raise AssertionError("no checkpoints in the log")
        return actions

    # -- the event loop ------------------------------------------------------

    def apply_event(self, event: pb.StateEvent) -> Actions:
        # The contract stays clock-free: the observed wrapper reads
        # perf_counter for telemetry only; nothing feeds back into the
        # protocol.  When obsv is off this is one branch.
        if not hooks.enabled:
            return self._apply_event(event)
        t0 = time.perf_counter()
        actions = self._apply_event(event)
        m = hooks.metrics
        m.histogram("mirbft_sm_apply_seconds").observe(
            time.perf_counter() - t0
        )
        m.counter(
            "mirbft_sm_events_total", type=type(event.type).__name__
        ).inc()
        if not actions.is_empty():
            for kind, emitted in (
                ("send", actions.sends),
                ("hash", actions.hashes),
                ("commit", actions.commits),
                ("persist", actions.write_ahead),
                ("store_request", actions.store_requests),
                ("forward_request", actions.forward_requests),
            ):
                if emitted:
                    m.counter("mirbft_sm_actions_total", kind=kind).inc(
                        len(emitted)
                    )
        return actions

    def _apply_event(self, event: pb.StateEvent) -> Actions:
        inner = event.type
        # Exact-type dispatch ordered by frequency (pb event classes have
        # no subclasses; this chain runs once per event of every node).
        inner_type = type(inner)

        if inner_type is pb.EventPropose:
            # Fast path: a propose only emits its hash action — it cannot
            # make a checkpoint collectable or advance the epoch, so the
            # GC/fixed-point epilogue below is statically a no-op for it.
            if self._state is not _SMState.INITIALIZED:
                raise AssertionError(
                    "cannot apply EventPropose before initialization"
                )
            return self._propose(inner.request)

        if inner_type is pb.EventProposeBatch:
            # Same fast path, batched: one delivery carrying many local
            # proposals emits one hash action per request and nothing else
            # (exactly as if each arrived as its own EventPropose in list
            # order).
            if self._state is not _SMState.INITIALIZED:
                raise AssertionError(
                    "cannot apply EventProposeBatch before initialization"
                )
            batch_actions = Actions()
            my_id = self.my_config.id
            for request in inner.requests:
                batch_actions.hash(
                    request_hash_data(request),
                    pb.HashResult(
                        digest=b"",
                        type=pb.HashOriginRequest(
                            source=my_id, request=request
                        ),
                    ),
                )
            return batch_actions

        actions = Actions()

        if inner_type is pb.EventInitialize:
            self._initialize(inner.initial_parms)
            return Actions()
        if inner_type is pb.EventLoadEntry:
            if self._state is not _SMState.LOADING:
                raise AssertionError("not loading")
            self.persisted.append_initial_load(inner.index, inner.data)
            return Actions()
        if inner_type is pb.EventLoadRequest:
            self._loaded_reqs.append(inner.request_ack)
            return Actions()
        if inner_type is pb.EventCompleteInitialization:
            actions = self._complete_initialization()
        elif inner_type is pb.EventActionsReceived:
            # No-op marker tying action results to the actions that caused
            # them in recorded logs.
            return Actions()
        else:
            if self._state is not _SMState.INITIALIZED:
                raise AssertionError(
                    f"cannot apply {type(inner).__name__} before initialization"
                )
            if inner_type is pb.EventStep:
                if inner.source not in self._members:
                    return _EMPTY_ACTIONS  # non-member (e.g. removed node)
                stepped = self._step(inner.source, inner.msg)
                if stepped is not _EMPTY_ACTIONS:
                    actions.concat(stepped)
            elif inner_type is pb.EventStepBatch:
                # One transport frame, several messages: apply in list order,
                # exactly as if each arrived as its own EventStep.  RequestAck
                # dispatch is inlined: acks dominate batch contents at scale
                # and their handler never emits actions.
                source = inner.source
                if source not in self._members:
                    return _EMPTY_ACTIONS  # non-member (e.g. removed node)
                msgs = inner.msgs
                ack_cls = pb.RequestAck
                step = self._step
                step_ack_many = self.client_tracker.step_ack_many
                i = 0
                n = len(msgs)
                while i < n:
                    if msgs[i].type.__class__ is ack_cls:
                        # Bulk-apply the run of consecutive acks (frames
                        # are overwhelmingly pure ack runs at scale).
                        j = i + 1
                        while j < n and msgs[j].type.__class__ is ack_cls:
                            j += 1
                        step_ack_many(
                            source, msgs if j - i == n else msgs[i:j]
                        )
                        i = j
                        continue
                    stepped = step(source, msgs[i])
                    if stepped is not _EMPTY_ACTIONS:
                        actions.concat(stepped)
                    i += 1
            elif inner_type is pb.EventTick:
                actions.concat(self.client_tracker.tick())
                actions.concat(self.epoch_tracker.tick())
            elif inner_type is pb.EventPropose:
                actions.concat(self._propose(inner.request))
            elif inner_type is pb.EventActionResults:
                actions.concat(self._process_results(inner))
            elif inner_type is pb.EventTransfer:
                if not self.commit_state.transferring:
                    raise AssertionError(
                        "transfer event without a requested transfer"
                    )
                if inner.c_entry.network_state is None:
                    # Transfer failed — usually because every donor GC'd
                    # the target while the network moved on.  If an
                    # intersection quorum has since certified a newer
                    # checkpoint, chase that instead: retrying the dead
                    # target forever wedges the node, since the ordinary
                    # lag trigger (_maybe_request_transfer) stands down
                    # while a transfer is in flight.  (The reference would
                    # trip addCEntry's network-state assertion here,
                    # state_machine.go:211-217 with mirbft.go:446-459.)
                    certified = (
                        self.checkpoint_tracker.certified_above_window()
                    )
                    target = self.commit_state.transfer_target
                    if (
                        certified is not None
                        and target is not None
                        and certified[0] > target.seq_no
                    ):
                        actions.concat(
                            self.commit_state.retarget_transfer(*certified)
                        )
                    else:
                        actions.concat(self.commit_state.retry_transfer())
                else:
                    actions.concat(self.persisted.add_c_entry(inner.c_entry))
                    actions.concat(self._reinitialize())
            else:
                raise AssertionError(
                    f"unknown state event {type(inner).__name__}"
                )

        # At most one watermark movement is possible per event (a new
        # checkpoint of our own can only follow the previous checkpoint
        # result).  Truncation requires an ACTIVE epoch: between an ECEntry
        # (or a reconfiguration reinitialize) and the next epoch becoming
        # active, the log must stay intact so an identical epoch change can
        # be recomputed after a crash — and so the log never degenerates to
        # a bare CEntry with no epoch marker (the reference states this
        # discipline in docs/WALMovement.md:34-36 but does not enforce it).
        epoch_active = (
            self.epoch_tracker.current_epoch is not None
            and self.epoch_tracker.current_epoch.state
            == TargetState.IN_PROGRESS
        )
        if self.checkpoint_tracker.garbage_collectable and epoch_active:
            new_low = self.checkpoint_tracker.garbage_collect()
            actions.concat(self.persisted.truncate(new_low))
            self.client_tracker.garbage_collect(new_low)
            ci = self.checkpoint_tracker.network_config.checkpoint_interval
            if new_low > ci:
                # Keep one extra checkpoint interval of batches for epoch
                # change.
                self.batch_tracker.truncate(new_low - ci)
            actions.concat(self.epoch_tracker.move_low_watermark(new_low))

        # Fixed point: drain commits and advance the epoch until quiescent.
        while True:
            actions.commits.extend(self.commit_state.drain())
            loop_actions = self.epoch_tracker.advance_state()
            if loop_actions.is_empty():
                break
            actions.concat(loop_actions)

        return actions

    # -- event handlers ------------------------------------------------------

    def _propose(self, request: pb.Request) -> Actions:
        return Actions().hash(
            request_hash_data(request),
            pb.HashResult(
                digest=b"",
                type=pb.HashOriginRequest(
                    source=self.my_config.id, request=request
                ),
            ),
        )

    def _step(self, source: int, msg: pb.Msg) -> Actions:
        # Exact-type checks ordered by frequency (RequestAcks dominate all
        # traffic at ladder scale; pb classes have no subclasses).
        cls = msg.type.__class__
        if cls is pb.RequestAck:
            return self.client_tracker.step_ack(source, msg)
        if cls is pb.FetchRequest or cls is pb.ForwardRequest:
            return self.client_tracker.step(source, msg)
        if cls is pb.Checkpoint:
            self.checkpoint_tracker.step(source, msg)
            return self._maybe_request_transfer()
        if cls is pb.FetchBatch or cls is pb.ForwardBatch:
            return self.batch_tracker.step(source, msg)
        # Everything else is epoch-scoped.
        return self.epoch_tracker.step(source, msg)

    def _maybe_request_transfer(self) -> Actions:
        """Lag check after every Checkpoint message: when an intersection
        quorum certifies a checkpoint far enough above our window that the
        network has GC'd past anything ordinary replay can fetch, request
        state transfer to the certified target.  Also exports the lag
        gauge, so dashboards see a node falling behind before the
        transfer fires."""
        tracker = self.checkpoint_tracker
        certified = tracker.certified_above_window()
        if hooks.enabled:
            lag = (
                certified[0] - tracker.high_watermark() if certified else 0
            )
            hooks.metrics.gauge("mirbft_checkpoint_lag_seqnos").set(lag)
        if certified is None or self.commit_state.transferring:
            return _EMPTY_ACTIONS
        seq_no, value = certified
        # Hysteresis: within two checkpoint windows of the frontier,
        # peers still retain the batches (they GC to their own low
        # watermark) and retransmission catches us up while we keep
        # ordering.  Transferring eagerly here preempts normal
        # participation — seen as a perpetual adopt-loop in the node-set
        # growth scenario, where the freshly provisioned member chased
        # every new certificate instead of executing batches.  Beyond
        # the horizon, replay is impossible and transfer is the only way
        # forward; a node stuck inside the horizon self-corrects, since
        # the frontier keeps moving while it does not.
        horizon = 2 * tracker.network_config.checkpoint_interval
        if seq_no <= tracker.high_watermark() + horizon:
            return _EMPTY_ACTIONS
        if seq_no <= self.commit_state.highest_commit:
            return _EMPTY_ACTIONS
        return self.commit_state.transfer_to(seq_no, value)

    def _process_results(self, results: pb.EventActionResults) -> Actions:
        actions = Actions()

        for checkpoint_result in results.checkpoints:
            epoch_config = None
            current = self.epoch_tracker.current_epoch
            if current is not None and current.active_epoch is not None:
                epoch_config = current.active_epoch.epoch_config
            actions.concat(
                self.commit_state.apply_checkpoint_result(
                    epoch_config, checkpoint_result
                )
            )
            if self.commit_state.reconfigured:
                # A pending reconfiguration just activated: the CEntry with
                # the new network state is in the log; rebuild every tracker
                # from it.  (The resumed epoch sends a precautionary
                # Suspect, so the network rolls into a fresh epoch under
                # the new configuration.)
                self.commit_state.reconfigured = False
                self.reconfigs_adopted += 1
                if hooks.enabled:
                    hooks.metrics.counter(
                        "mirbft_reconfig_adopted_total"
                    ).inc()
                actions.concat(self._reinitialize())

        for hash_result in results.digests:
            origin = hash_result.type
            digest = hash_result.digest
            if isinstance(origin, pb.HashOriginBatch):
                self.batch_tracker.add_batch(
                    origin.seq_no, digest, origin.request_acks
                )
                actions.concat(
                    self.epoch_tracker.apply_batch_hash_result(
                        origin.epoch, origin.seq_no, digest
                    )
                )
            elif isinstance(origin, pb.HashOriginRequest):
                req = origin.request
                self.client_tracker.apply_request_digest(
                    pb.RequestAck(
                        client_id=req.client_id,
                        req_no=req.req_no,
                        digest=digest,
                    ),
                    req.data,
                    out=actions,
                )
            elif isinstance(origin, pb.HashOriginVerifyRequest):
                if origin.request_ack.digest != digest:
                    # A byzantine peer forwarded request data that does not
                    # hash to the ack's digest.  Drop it — the fetch/refetch
                    # tick machinery retries against other ackers.  (The
                    # reference panics here, marked "XXX this should not
                    # panic"; a remote peer must never crash the node.)
                    if self.logger is not None:
                        self.logger.warn(
                            "dropping forwarded request: data does not "
                            "match its ack digest",
                            source=origin.source,
                            client_id=origin.request_ack.client_id,
                            req_no=origin.request_ack.req_no,
                        )
                else:
                    self.client_tracker.apply_request_digest(
                        origin.request_ack, origin.request_data, out=actions
                    )
            elif isinstance(origin, pb.HashOriginEpochChange):
                actions.concat(
                    self.epoch_tracker.apply_epoch_change_digest(origin, digest)
                )
            elif isinstance(origin, pb.HashOriginVerifyBatch):
                self.batch_tracker.apply_verify_batch_hash_result(digest, origin)
                if (
                    not self.batch_tracker.has_fetch_in_flight()
                    and self.epoch_tracker.current_epoch.state
                    == TargetState.FETCHING
                ):
                    actions.concat(
                        self.epoch_tracker.current_epoch.fetch_new_epoch_state()
                    )
            else:
                raise AssertionError("hash result with no origin type")

        return actions
