"""Checkpoint agreement tracking.

Rebuild of the reference's checkpoint tracker (reference:
checkpoints.go:19-319).  Value-agreement rules per checkpoint seq_no:

- f+1 nodes on one value → the network committed it (``committed_value``);
- our own value plus an intersection quorum on the committed value →
  ``stable``: watermarks may slide, the WAL may truncate, trackers GC.

Three checkpoint windows stay active; messages above the high watermark are
buffered *and* tallied into a per-node highest-checkpoint map, which is how
a lagging node detects it needs state transfer.  One deliberate departure
from the reference: votes are deduplicated per (node, value) — the
reference double-counts a vote that arrives above the window and is then
re-applied from the buffer after the window slides (checkpoints.go:124-134
with :269-275), which lets a single node inflate agreement counts.
"""

from __future__ import annotations

from .. import pb
from ..obsv import hooks
from .msgbuffers import Applyable, MsgBuffer, NodeBuffers
from .persisted import Persisted
from .quorum import intersection_quorum, some_correct_quorum


class CheckpointDivergenceError(Exception):
    """Our computed checkpoint value disagrees with the network's committed
    value — byzantine assumptions exceeded or the application is
    non-deterministic."""


class Checkpoint:
    """Agreement state for one checkpoint seq_no (reference:
    checkpoints.go:257-304)."""

    def __init__(self, seq_no: int, network_config, my_id: int):
        self.seq_no = seq_no
        self.network_config = network_config
        self.my_id = my_id
        self.votes: dict[bytes, set] = {}  # value -> node IDs
        self.committed_value: bytes | None = None
        self.my_value: bytes | None = None
        self.stable = False

    def apply_checkpoint_msg(self, source: int, value: bytes) -> None:
        nodes = self.votes.setdefault(value, set())
        nodes.add(source)

        if (
            self.committed_value is None
            and len(nodes) >= some_correct_quorum(self.network_config)
        ):
            self.committed_value = value

        if source == self.my_id:
            self.my_value = value

        if (
            self.my_value is not None
            and self.committed_value is not None
            and not self.stable
        ):
            if self.my_value != self.committed_value:
                raise CheckpointDivergenceError(
                    f"seq_no {self.seq_no}: our value {self.my_value!r} != "
                    f"network committed {self.committed_value!r}"
                )
            if len(self.votes[self.committed_value]) >= intersection_quorum(
                self.network_config
            ):
                self.stable = True
                if hooks.enabled:
                    hooks.milestone("ckpt.stable", self.my_id, self.seq_no)


class CheckpointTracker:
    def __init__(
        self,
        persisted: Persisted,
        node_buffers: NodeBuffers,
        my_config: pb.InitialParameters,
        logger=None,
    ):
        self.persisted = persisted
        self.node_buffers = node_buffers
        self.my_config = my_config
        self.logger = logger

        self.garbage_collectable = False
        self.network_config = None
        self.checkpoint_map: dict[int, Checkpoint] = {}
        self.active: list[Checkpoint] = []  # ascending seq_no, >= 3 entries
        self.highest_checkpoints: dict[int, int] = {}  # node -> seq_no
        self.msg_buffers: dict[int, MsgBuffer] = {}

    # -- lifecycle -----------------------------------------------------------

    def reinitialize(self) -> None:
        old_map = self.checkpoint_map
        old_buffers = self.msg_buffers

        self.garbage_collectable = False
        self.network_config = None
        self.checkpoint_map = {}
        self.active = []
        self.highest_checkpoints = {}
        self.msg_buffers = {}

        def on_c_entry(c_entry):
            if self.network_config is None:
                self.network_config = c_entry.network_state.config
            cp = self.checkpoint(c_entry.seq_no)
            cp.apply_checkpoint_msg(self.my_config.id, c_entry.checkpoint_value)
            self.active.append(cp)

        self.persisted.iterate({pb.CEntry: on_c_entry})

        if not self.active:
            raise AssertionError("no checkpoints in the log")
        self.active[0].stable = True

        valid_nodes = set(self.network_config.nodes)
        for node_id in self.network_config.nodes:
            buffer = old_buffers.get(node_id)
            if buffer is None:
                buffer = MsgBuffer(
                    "checkpoints", self.node_buffers.node_buffer(node_id)
                )
            self.msg_buffers[node_id] = buffer

        # Replay surviving votes from before the reinitialization.
        for seq_no in sorted(old_map):
            if seq_no < self.low_watermark():
                continue
            for value in sorted(old_map[seq_no].votes):
                for node in sorted(old_map[seq_no].votes[value]):
                    if node in valid_nodes:
                        self.apply_checkpoint_msg(node, seq_no, value)

        self.garbage_collect()

    # -- watermarks ----------------------------------------------------------

    def low_watermark(self) -> int:
        return self.active[0].seq_no

    def high_watermark(self) -> int:
        return self.active[-1].seq_no

    def checkpoint(self, seq_no: int) -> Checkpoint:
        cp = self.checkpoint_map.get(seq_no)
        if cp is None:
            cp = Checkpoint(seq_no, self.network_config, self.my_config.id)
            self.checkpoint_map[seq_no] = cp
        return cp

    # -- message handling ----------------------------------------------------

    def filter(self, _source: int, msg: pb.Msg) -> Applyable:
        cp_msg = msg.type
        if cp_msg.seq_no < self.low_watermark():
            return Applyable.PAST
        if cp_msg.seq_no > self.high_watermark():
            return Applyable.FUTURE
        return Applyable.CURRENT

    def step(self, source: int, msg: pb.Msg) -> None:
        if source not in self.msg_buffers:
            # A member of a newer config we have not adopted yet (node-set
            # reconfiguration in flight — e.g. a freshly joined replica
            # broadcasting checkpoints before we activate the grown
            # config).  Its vote cannot count toward any quorum in *our*
            # config, and after we adopt the new config the reinitialize
            # rebuilds tallies from current members' retransmissions.
            return
        verdict = self.filter(source, msg)
        if verdict is Applyable.PAST:
            return
        if verdict is Applyable.FUTURE:
            # Buffer for re-application after the window slides, but also
            # tally now so highest-checkpoint tracking (state-transfer
            # detection) sees it.  Vote dedup makes the re-application safe.
            self.msg_buffers[source].store(msg)
        self.apply_msg(source, msg)

    def apply_msg(self, source: int, msg: pb.Msg) -> None:
        cp_msg = msg.type
        if not isinstance(cp_msg, pb.Checkpoint):
            raise AssertionError(f"unexpected msg type {type(cp_msg).__name__}")
        self.apply_checkpoint_msg(source, cp_msg.seq_no, cp_msg.value)

    def apply_checkpoint_msg(self, source: int, seq_no: int, value: bytes) -> None:
        above_high = seq_no > self.high_watermark()
        if above_high:
            highest = self.highest_checkpoints.get(source)
            if highest is not None and highest >= seq_no:
                # We already hold an equal-or-newer above-window claim from
                # this node; the buffered copy of this message will still be
                # applied when the window slides.
                return
            self.highest_checkpoints[source] = seq_no

        cp = self.checkpoint(seq_no)
        cp.apply_checkpoint_msg(source, value)

        if cp.stable and seq_no > self.low_watermark() and not above_high:
            self.garbage_collectable = True
            return

        if not above_high:
            return

        # GC above-window checkpoint objects no node references anymore.
        referenced = {c.seq_no for c in self.active}
        referenced.update(self.highest_checkpoints.values())
        for sn in list(self.checkpoint_map):
            if sn not in referenced:
                del self.checkpoint_map[sn]

    # -- state-transfer lag signal -------------------------------------------

    def certified_above_window(self) -> tuple[int, bytes] | None:
        """Highest above-window checkpoint holding an intersection quorum
        (2f+1) on a single value, as ``(seq_no, value)`` — or None.

        This is the state-transfer trigger *and* the adoption authority:
        a value 2f+1 nodes vouch for intersects every other quorum in at
        least one correct node, so a lagging replica may adopt a snapshot
        anchored at it without replaying the log it missed.  f+1 would
        prove some correct node holds the value, but not that the rest of
        the network can make progress from it."""
        best = None
        high = self.high_watermark()
        quorum = intersection_quorum(self.network_config)
        for seq_no, cp in self.checkpoint_map.items():
            if seq_no <= high:
                continue
            if best is not None and seq_no <= best[0]:
                continue
            for value, nodes in cp.votes.items():
                if len(nodes) >= quorum:
                    best = (seq_no, value)
                    break
        return best

    def lag_seqnos(self) -> int:
        """How far the network's newest certified frontier sits above our
        own window (0 when caught up) — exported as the
        ``mirbft_checkpoint_lag_seqnos`` gauge."""
        certified = self.certified_above_window()
        if certified is None:
            return 0
        return certified[0] - self.high_watermark()

    # -- garbage collection --------------------------------------------------

    def garbage_collect(self) -> int:
        """Slide the window past the highest stable checkpoint; returns the
        new low watermark.  Caller (StateMachine) truncates the WAL and GCs
        the other trackers with it."""
        highest_stable_idx = 0
        for i, cp in enumerate(self.active):
            if not cp.stable:
                break
            highest_stable_idx = i

        for cp in self.active[:highest_stable_idx]:
            self.checkpoint_map.pop(cp.seq_no, None)
        self.active = self.active[highest_stable_idx:]

        ci = self.network_config.checkpoint_interval
        while len(self.active) < 3:
            self.active.append(self.checkpoint(self.high_watermark() + ci))

        for node_id in self.network_config.nodes:
            self.msg_buffers[node_id].iterate(self.filter, self.apply_msg)

        self.garbage_collectable = False
        return self.active[0].seq_no
