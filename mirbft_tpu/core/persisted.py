"""In-memory mirror of the durable write-ahead log.

Rebuild of the reference's persisted log (reference: persisted.go:15-317).
Appends emit persist actions for the executor's durable WAL; on restart the
runtime replays the durable log back in via ``append_initial_load``.  The
log's entry grammar doubles as the source from which epoch-change messages
are deterministically *recomputed* rather than persisted (reference:
docs/WALMovement.md:59-61) — see ``construct_epoch_change``.

Truncation discipline (reference: persisted.go:152-184, docs/WALMovement.md):
the log may only be truncated to a CEntry at-or-above the low watermark, or
to an NEntry above it, and never while an epoch change is in flight (the
ECEntry pins the tail, enforced by callers simply not calling truncate).
"""

from __future__ import annotations

from dataclasses import dataclass

from .. import pb
from .actions import Actions


@dataclass
class _LogEntry:
    index: int
    entry: pb.Persistent


class Persisted:
    def __init__(self, logger=None):
        self._log: list[_LogEntry] = []  # always index-contiguous
        self._head = 0  # offset of the logical head within _log
        self.next_index = 0
        self.logger = logger

    # -- startup loading ----------------------------------------------------

    def append_initial_load(self, index: int, entry: pb.Persistent) -> None:
        has_entries = len(self._log) > self._head
        if has_entries and self.next_index != index:
            raise ValueError(
                f"WAL indexes out of order: expected {self.next_index}, "
                f"got {index} — corrupted WAL?"
            )
        self._log.append(_LogEntry(index=index, entry=entry))
        self.next_index = index + 1

    # -- appends (emit persist actions) -------------------------------------

    def _append(self, entry: pb.Persistent) -> Actions:
        self._log.append(_LogEntry(index=self.next_index, entry=entry))
        actions = Actions().persist(self.next_index, entry)
        self.next_index += 1
        return actions

    def add_q_entry(self, q_entry: pb.QEntry) -> Actions:
        return self._append(pb.Persistent(type=q_entry))

    def add_p_entry(self, p_entry: pb.PEntry) -> Actions:
        return self._append(pb.Persistent(type=p_entry))

    def add_c_entry(self, c_entry: pb.CEntry) -> Actions:
        if c_entry.network_state is None:
            raise AssertionError("CEntry requires network state")
        return self._append(pb.Persistent(type=c_entry))

    def add_n_entry(self, n_entry: pb.NEntry) -> Actions:
        return self._append(pb.Persistent(type=n_entry))

    def add_f_entry(self, f_entry: pb.FEntry) -> Actions:
        return self._append(pb.Persistent(type=f_entry))

    def add_ec_entry(self, ec_entry: pb.ECEntry) -> Actions:
        return self._append(pb.Persistent(type=ec_entry))

    def add_t_entry(self, t_entry: pb.TEntry) -> Actions:
        return self._append(pb.Persistent(type=t_entry))

    def add_suspect(self, suspect: pb.Suspect) -> Actions:
        return self._append(pb.Persistent(type=suspect))

    # -- truncation ---------------------------------------------------------

    def truncate(self, low_watermark: int) -> Actions:
        """Truncate the head to the first CEntry with seq_no >= low_watermark
        or NEntry with seq_no > low_watermark (reference: persisted.go:152-184)."""
        for offset in range(self._head, len(self._log)):
            entry = self._log[offset].entry.type
            if isinstance(entry, pb.CEntry):
                if entry.seq_no < low_watermark:
                    continue
            elif isinstance(entry, pb.NEntry):
                if entry.seq_no <= low_watermark:
                    continue
            else:
                continue

            if offset == self._head:
                break

            self._head = offset
            # Compact occasionally so memory stays bounded without churning
            # the list on every truncate.
            if self._head > 4096:
                del self._log[: self._head]
                self._head = 0
            return Actions().truncate(self._log[self._head].index)

        return Actions()

    # -- iteration ----------------------------------------------------------

    def entries(self):
        """Iterate (index, pb.Persistent) from the logical head."""
        for le in self._log[self._head :]:
            yield le.index, le.entry

    def iterate(self, handlers: dict, should_exit=None) -> None:
        """Dispatch each live entry to handlers[type(entry)] if present
        (reference: persisted.go:198-242)."""
        for _, persistent in self.entries():
            handler = handlers.get(type(persistent.type))
            if handler is not None:
                handler(persistent.type)
            if should_exit is not None and should_exit():
                break

    # -- deterministic epoch-change reconstruction --------------------------

    def construct_epoch_change(self, new_epoch: int) -> pb.EpochChange:
        """Recompute the EpochChange message for new_epoch from the log
        (reference: persisted.go:244-317).

        Entries are scoped to the epoch of the preceding NEntry/FEntry; the
        scan stops once the log's epoch reaches new_epoch.  The pSet keeps
        only the *last* PEntry per seq_no (a sequence re-prepared in a later
        epoch supersedes the earlier prepare); the qSet keeps every QEntry
        (one per (seq, epoch) by construction); checkpoints collect every
        CEntry seen."""
        checkpoints: list[pb.Checkpoint] = []
        # seq_no -> (epoch, digest); later entries overwrite earlier ones,
        # implementing the reference's two-pass "skip all but last" dedup in
        # a single pass.  p_order tracks *last*-occurrence order, matching
        # where the reference's second pass emits the surviving entry.
        p_latest: dict[int, tuple[int, bytes]] = {}
        p_order: list[int] = []
        q_set: list[pb.EpochChangeSetEntry] = []

        log_epoch: int | None = None
        for _, persistent in self.entries():
            if log_epoch is not None and log_epoch >= new_epoch:
                break
            entry = persistent.type
            if isinstance(entry, pb.NEntry):
                log_epoch = entry.epoch_config.number
            elif isinstance(entry, pb.FEntry):
                log_epoch = entry.ends_epoch_config.number
            elif isinstance(entry, pb.PEntry):
                if log_epoch is None:
                    raise ValueError(
                        f"PEntry for seq_no {entry.seq_no} precedes any "
                        f"NEntry/FEntry epoch marker — corrupt log"
                    )
                if entry.seq_no in p_latest:
                    p_order.remove(entry.seq_no)
                p_order.append(entry.seq_no)
                p_latest[entry.seq_no] = (log_epoch, entry.digest)
            elif isinstance(entry, pb.QEntry):
                if log_epoch is None:
                    raise ValueError(
                        f"QEntry for seq_no {entry.seq_no} precedes any "
                        f"NEntry/FEntry epoch marker — corrupt log"
                    )
                q_set.append(
                    pb.EpochChangeSetEntry(
                        epoch=log_epoch, seq_no=entry.seq_no, digest=entry.digest
                    )
                )
            elif isinstance(entry, pb.CEntry):
                if checkpoints and checkpoints[-1].seq_no == entry.seq_no:
                    # A checkpoint recomputed after a reconfiguration
                    # reinitialize can appear twice; keep the newest (the
                    # reference emits the duplicate — its parse-side dup
                    # check is a no-op bug, epoch_change.go:70-78).
                    checkpoints.pop()
                checkpoints.append(
                    pb.Checkpoint(seq_no=entry.seq_no, value=entry.checkpoint_value)
                )

        p_set = [
            pb.EpochChangeSetEntry(
                epoch=p_latest[seq][0], seq_no=seq, digest=p_latest[seq][1]
            )
            for seq in p_order
        ]

        return pb.EpochChange(
            new_epoch=new_epoch,
            checkpoints=checkpoints,
            p_set=p_set,
            q_set=q_set,
        )
