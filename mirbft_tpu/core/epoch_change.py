"""Parsing and ACK-certification of EpochChange messages.

Rebuild of the reference's epoch-change bookkeeping (reference:
epoch_change.go:18-116).  An EpochChange travels the network alongside
hash-attested ACKs (EpochChangeAck); a strong certificate (intersection
quorum of ACKs on one digest) is what lets the new epoch's leader safely
include it in a NewEpoch message.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .. import pb
from .quorum import intersection_quorum


class MalformedEpochChange(ValueError):
    pass


@dataclass
class ParsedEpochChange:
    """A structurally validated EpochChange with its pSet/qSet indexed for
    the new-view computation (quorum.construct_new_epoch_config)."""

    underlying: pb.EpochChange
    low_watermark: int
    # seq_no -> pb.EpochChangeSetEntry (at most one prepared digest per seq)
    p_set: dict = field(default_factory=dict)
    # seq_no -> {epoch -> digest} (one preprepared digest per (seq, epoch))
    q_set: dict = field(default_factory=dict)
    # node IDs that ACKed this exact epoch-change digest
    acks: set = field(default_factory=set)


def parse_epoch_change(underlying: pb.EpochChange) -> ParsedEpochChange:
    if not underlying.checkpoints:
        raise MalformedEpochChange("epoch change contains no checkpoints")

    seen_checkpoints = set()
    low_watermark = underlying.checkpoints[0].seq_no
    for checkpoint in underlying.checkpoints:
        if checkpoint.seq_no in seen_checkpoints:
            raise MalformedEpochChange(
                f"duplicate checkpoint seq_no {checkpoint.seq_no}"
            )
        seen_checkpoints.add(checkpoint.seq_no)
        low_watermark = min(low_watermark, checkpoint.seq_no)

    p_set = {}
    for entry in underlying.p_set:
        if entry.seq_no in p_set:
            raise MalformedEpochChange(
                f"duplicate pSet entry for seq_no {entry.seq_no}"
            )
        p_set[entry.seq_no] = entry

    q_set = {}
    for entry in underlying.q_set:
        epochs = q_set.setdefault(entry.seq_no, {})
        if entry.epoch in epochs:
            raise MalformedEpochChange(
                f"duplicate qSet entry for seq_no {entry.seq_no} "
                f"epoch {entry.epoch}"
            )
        epochs[entry.epoch] = entry.digest

    return ParsedEpochChange(
        underlying=underlying,
        low_watermark=low_watermark,
        p_set=p_set,
        q_set=q_set,
    )


@dataclass
class EpochChangeCert:
    """Collects (digest, msg) variants of one node's EpochChange and the ACKs
    for each, promoting the first digest to reach an intersection quorum to
    ``strong_cert`` (reference: epoch_change.go:29-52)."""

    network_config: pb.NetworkConfig
    parsed_by_digest: dict = field(default_factory=dict)  # digest -> ParsedEpochChange
    strong_cert: bytes | None = None

    def add_msg(self, source: int, msg: pb.EpochChange, digest: bytes) -> None:
        parsed = self.parsed_by_digest.get(digest)
        if parsed is None:
            try:
                parsed = parse_epoch_change(msg)
            except MalformedEpochChange:
                return
            self.parsed_by_digest[digest] = parsed

        parsed.acks.add(source)

        if self.strong_cert is None and len(parsed.acks) >= intersection_quorum(
            self.network_config
        ):
            self.strong_cert = digest
