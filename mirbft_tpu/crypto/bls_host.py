"""Pure-Python BLS12-381 multi-signatures — the host reference.

BASELINE ladder rung 4 calls for BLS quorum-certificate aggregation: 2f+1
replicas sign the same (seq_no, digest) statement, the aggregate signature
is the sum of the G1 signature points, the aggregate public key the sum of
the G2 key points, and one pairing equation verifies the whole quorum:

    e(asig, G2gen) == e(H(m), apk)

This module implements the curve from the public parameters: the Fp2/Fp6/
Fp12 tower, affine group law on E(Fp): y^2 = x^3 + 4 and the twist
E'(Fp2): y^2 = x^3 + 4(1+u), the optimal-ate Miller loop with the
untwist into E(Fp12), and a naive final exponentiation.  Hashing to G1 is
try-and-increment with cofactor clearing (structurally sound; not the
IETF hash-to-curve ciphersuite — fine for an oracle and test signer, do
not use as a production ciphersuite).  Nothing here is constant-time.

The device side (ops/bls_g1.py) aggregates G1 points in batch; this
module is its correctness oracle and performs the pairing verification
(a host-sized job: two pairings per certificate, independent of quorum
size)."""

from __future__ import annotations

import hashlib

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
# BLS parameter x (the curve was generated from it); negative.
X_ABS = 0xD201000000010000
H1_COFACTOR = 0x396C8C005555E1568C00AAAB0000AAAB

G1_X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
G1_Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1
G2_X = (
    0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
    0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
)
G2_Y = (
    0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
    0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
)


# -- Fp ---------------------------------------------------------------------


def _inv(a: int) -> int:
    return pow(a, P - 2, P)


# -- Fp2 = Fp[u] / (u^2 + 1) ------------------------------------------------


def f2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def f2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def f2_mul(a, b):
    return (
        (a[0] * b[0] - a[1] * b[1]) % P,
        (a[0] * b[1] + a[1] * b[0]) % P,
    )


def f2_neg(a):
    return ((-a[0]) % P, (-a[1]) % P)


def f2_inv(a):
    d = _inv((a[0] * a[0] + a[1] * a[1]) % P)
    return (a[0] * d % P, (-a[1]) * d % P)


F2_ZERO = (0, 0)
F2_ONE = (1, 0)
XI = (1, 1)  # the sextic-twist non-residue 1 + u


# -- Fp6 = Fp2[v] / (v^3 - xi);  Fp12 = Fp6[w] / (w^2 - v) -------------------
# Elements: Fp6 = (c0, c1, c2) of Fp2; Fp12 = (c0, c1) of Fp6.


def f6_add(a, b):
    return tuple(f2_add(x, y) for x, y in zip(a, b))


def f6_sub(a, b):
    return tuple(f2_sub(x, y) for x, y in zip(a, b))


def f6_neg(a):
    return tuple(f2_neg(x) for x in a)


def f6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = f2_mul(a0, b0)
    t1 = f2_mul(a1, b1)
    t2 = f2_mul(a2, b2)
    c0 = f2_add(t0, f2_mul(XI, f2_sub(f2_mul(f2_add(a1, a2), f2_add(b1, b2)), f2_add(t1, t2))))
    c1 = f2_add(
        f2_sub(f2_mul(f2_add(a0, a1), f2_add(b0, b1)), f2_add(t0, t1)),
        f2_mul(XI, t2),
    )
    c2 = f2_add(f2_sub(f2_mul(f2_add(a0, a2), f2_add(b0, b2)), f2_add(t0, t2)), t1)
    return (c0, c1, c2)


def f6_mul_by_xi(a):
    # v * (c0 + c1 v + c2 v^2) = xi*c2 + c0 v + c1 v^2
    return (f2_mul(XI, a[2]), a[0], a[1])


def f6_inv(a):
    a0, a1, a2 = a
    c0 = f2_sub(f2_mul(a0, a0), f2_mul(XI, f2_mul(a1, a2)))
    c1 = f2_sub(f2_mul(XI, f2_mul(a2, a2)), f2_mul(a0, a1))
    c2 = f2_sub(f2_mul(a1, a1), f2_mul(a0, a2))
    t = f2_add(
        f2_mul(a0, c0),
        f2_mul(XI, f2_add(f2_mul(a2, c1), f2_mul(a1, c2))),
    )
    ti = f2_inv(t)
    return (f2_mul(c0, ti), f2_mul(c1, ti), f2_mul(c2, ti))


F6_ZERO = (F2_ZERO, F2_ZERO, F2_ZERO)
F6_ONE = (F2_ONE, F2_ZERO, F2_ZERO)


def f12_add(a, b):
    return (f6_add(a[0], b[0]), f6_add(a[1], b[1]))


def f12_sub(a, b):
    return (f6_sub(a[0], b[0]), f6_sub(a[1], b[1]))


def f12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = f6_mul(a0, b0)
    t1 = f6_mul(a1, b1)
    c0 = f6_add(t0, f6_mul_by_xi(t1))
    c1 = f6_sub(f6_sub(f6_mul(f6_add(a0, a1), f6_add(b0, b1)), t0), t1)
    return (c0, c1)


def f12_inv(a):
    a0, a1 = a
    t = f6_sub(f6_mul(a0, a0), f6_mul_by_xi(f6_mul(a1, a1)))
    ti = f6_inv(t)
    return (f6_mul(a0, ti), f6_neg(f6_mul(a1, ti)))


def f12_conj(a):
    return (a[0], f6_neg(a[1]))  # a^(p^6)


F12_ONE = (F6_ONE, F6_ZERO)


def f12_pow(a, e: int):
    out = F12_ONE
    base = a
    while e:
        if e & 1:
            out = f12_mul(out, base)
        base = f12_mul(base, base)
        e >>= 1
    return out


def _f12_scalar(c: int):
    return (((c % P, 0), F2_ZERO, F2_ZERO), F6_ZERO)


def _f12_from_f2(c):
    return ((c, F2_ZERO, F2_ZERO), F6_ZERO)


# w and its powers (w = (0, 1) in the Fp6[w] tower).
W = (F6_ZERO, F6_ONE)


# -- affine group law (generic over a field given by ops) --------------------


class _Field:
    """Operation bundle so one group law serves Fp, Fp2 and Fp12."""

    def __init__(self, add, sub, mul, inv, neg, zero, one):
        self.add, self.sub, self.mul, self.inv, self.neg = add, sub, mul, inv, neg
        self.zero, self.one = zero, one


FP = _Field(
    lambda a, b: (a + b) % P,
    lambda a, b: (a - b) % P,
    lambda a, b: a * b % P,
    _inv,
    lambda a: (-a) % P,
    0,
    1,
)
FP2 = _Field(f2_add, f2_sub, f2_mul, f2_inv, f2_neg, F2_ZERO, F2_ONE)
FP12 = _Field(f12_add, f12_sub, f12_mul, f12_inv, lambda a: f12_sub((F6_ZERO, F6_ZERO), a), (F6_ZERO, F6_ZERO), F12_ONE)


def pt_add(field: _Field, p1, p2):
    """Affine addition; None is the point at infinity."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if y1 == field.neg(y2) or y1 != y2:
            return None
        # doubling: lambda = 3 x^2 / 2 y  (a = 0)
        num = field.mul(field.mul(x1, x1), _three(field))
        den = field.inv(field.add(y1, y1))
    else:
        num = field.sub(y2, y1)
        den = field.inv(field.sub(x2, x1))
    lam = field.mul(num, den)
    x3 = field.sub(field.sub(field.mul(lam, lam), x1), x2)
    y3 = field.sub(field.mul(lam, field.sub(x1, x3)), y1)
    return (x3, y3)


def _three(field: _Field):
    return field.add(field.add(field.one, field.one), field.one)


def _jac_double(field: _Field, p):
    """Jacobian doubling on y^2 = x^3 + b (a = 0; dbl-2009-l)."""
    x, y, z = p
    a = field.mul(x, x)
    b = field.mul(y, y)
    c = field.mul(b, b)
    t = field.add(x, b)
    d = field.sub(field.sub(field.mul(t, t), a), c)
    d = field.add(d, d)
    e = field.add(field.add(a, a), a)
    f = field.mul(e, e)
    x3 = field.sub(f, field.add(d, d))
    c8 = field.add(c, c)
    c8 = field.add(c8, c8)
    c8 = field.add(c8, c8)
    y3 = field.sub(field.mul(e, field.sub(d, x3)), c8)
    z3 = field.mul(field.add(y, y), z)
    return (x3, y3, z3)


def _jac_add_affine(field: _Field, p, q):
    """Jacobian p + affine q (madd-2007-bl); q must not be infinity."""
    x1, y1, z1 = p
    x2, y2 = q
    z1z1 = field.mul(z1, z1)
    u2 = field.mul(x2, z1z1)
    s2 = field.mul(field.mul(y2, z1), z1z1)
    if u2 == x1:
        if s2 == y1:
            return _jac_double(field, p)
        return None  # p + (-p)
    h = field.sub(u2, x1)
    hh = field.mul(h, h)
    i = field.add(field.add(hh, hh), field.add(hh, hh))
    j = field.mul(h, i)
    r = field.sub(s2, y1)
    r = field.add(r, r)
    v = field.mul(x1, i)
    x3 = field.sub(field.sub(field.mul(r, r), j), field.add(v, v))
    y1j = field.mul(y1, j)
    y3 = field.sub(
        field.mul(r, field.sub(v, x3)), field.add(y1j, y1j)
    )
    z3 = field.mul(field.add(z1, z1), h)
    return (x3, y3, z3)


def pt_mul(field: _Field, scalar: int, point):
    """Double-and-add in Jacobian coordinates (one inversion at the end —
    the affine group law pays a field inversion per addition, which makes
    signing/keygen ~20x slower)."""
    if point is None or scalar == 0:
        return None
    acc = None  # Jacobian accumulator; None is infinity
    for i in range(scalar.bit_length() - 1, -1, -1):
        if acc is not None:
            acc = _jac_double(field, acc)
        if (scalar >> i) & 1:
            if acc is None:
                acc = (point[0], point[1], field.one)
            else:
                acc = _jac_add_affine(field, acc, point)
        if acc is not None and acc[2] == field.zero:
            acc = None
    if acc is None:
        return None
    zi = field.inv(acc[2])
    zi2 = field.mul(zi, zi)
    return (
        field.mul(acc[0], zi2),
        field.mul(acc[1], field.mul(zi2, zi)),
    )


def pt_neg(field: _Field, point):
    if point is None:
        return None
    return (point[0], field.neg(point[1]))


G1 = (G1_X, G1_Y)
G2 = (G2_X, G2_Y)


def g1_on_curve(point) -> bool:
    if point is None:
        return True
    x, y = point
    return (y * y - (x * x * x + 4)) % P == 0


def g2_on_curve(point) -> bool:
    if point is None:
        return True
    x, y = point
    b = f2_mul((4, 0), XI)  # 4(1 + u)
    return f2_sub(f2_mul(y, y), f2_add(f2_mul(x, f2_mul(x, x)), b)) == F2_ZERO


# -- untwist E'(Fp2) -> E(Fp12) ----------------------------------------------
# The twist is M-type with b' = 4*xi and w^6 = xi in this tower, so
# psi(x', y') = (x' / w^2, y' / w^3): then y^2 = y'^2/xi = (x'^3 + 4 xi)/xi
# = x^3 + 4 (checked at import below).

_W2_INV = f12_inv(f12_pow(W, 2))
_W3_INV = f12_inv(f12_pow(W, 3))


def _untwist(q):
    if q is None:
        return None
    x = f12_mul(_f12_from_f2(q[0]), _W2_INV)
    y = f12_mul(_f12_from_f2(q[1]), _W3_INV)
    return (x, y)


def _on_e_fp12(point) -> bool:
    x, y = point
    return f12_sub(
        f12_mul(y, y), f12_add(f12_mul(x, f12_mul(x, x)), _f12_scalar(4))
    ) == (F6_ZERO, F6_ZERO)


assert _on_e_fp12(_untwist(G2)), "untwist map does not land on E(Fp12)"


# -- pairing -----------------------------------------------------------------


def _line(field: _Field, a, b, point):
    """Evaluate the line through a and b (or the tangent at a, when a==b)
    at `point`; a, b must not be inverses of each other."""
    xa, ya = a
    xb, yb = b
    xp, yp = point
    if xa == xb and ya == yb:
        num = field.mul(field.mul(xa, xa), _three(field))
        den = field.add(ya, ya)
    else:
        num = field.sub(yb, ya)
        den = field.sub(xb, xa)
    if den == field.zero:
        # vertical line: x - xa
        return field.sub(xp, xa)
    lam = field.mul(num, field.inv(den))
    return field.sub(field.sub(yp, ya), field.mul(lam, field.sub(xp, xa)))


def _miller_loop(q12, p12):
    f = F12_ONE
    t = q12
    for i in range(X_ABS.bit_length() - 2, -1, -1):
        f = f12_mul(f12_mul(f, f), _line(FP12, t, t, p12))
        t = pt_add(FP12, t, t)
        if (X_ABS >> i) & 1:
            f = f12_mul(f, _line(FP12, t, q12, p12))
            t = pt_add(FP12, t, q12)
    # x is negative for BLS12-381: conjugate the result.
    return f12_conj(f)


def pairing(p, q) -> tuple:
    """e(p, q) for p in G1(Fp) affine, q in G2'(Fp2) affine; None inputs
    (infinity) give the identity."""
    if p is None or q is None:
        return F12_ONE
    p12 = (_f12_scalar(p[0]), _f12_scalar(p[1]))
    f = _miller_loop(_untwist(q), p12)
    # final exponentiation: (p^12 - 1) / r, easy part then naive hard part
    f = f12_mul(f12_conj(f), f12_inv(f))  # f^(p^6 - 1)
    f = f12_mul(f12_pow(f, P * P), f)  # ^(p^2 + 1)
    return f12_pow(f, (P**4 - P**2 + 1) // R)


# -- keys, signing, aggregation ---------------------------------------------


def secret_key(seed: bytes) -> int:
    return int.from_bytes(hashlib.sha512(b"bls-sk" + seed).digest(), "big") % R


def public_key(seed: bytes):
    """pk = [sk]G2 (affine Fp2 pair)."""
    return pt_mul(FP2, secret_key(seed), G2)


def hash_to_g1(message: bytes):
    """Try-and-increment with cofactor clearing (not the IETF suite)."""
    ctr = 0
    while True:
        x = (
            int.from_bytes(
                hashlib.sha256(b"bls-h2c" + ctr.to_bytes(4, "big") + message).digest(),
                "big",
            )
            % P
        )
        rhs = (x * x * x + 4) % P
        y = pow(rhs, (P + 1) // 4, P)
        if y * y % P == rhs:
            point = (x, min(y, P - y))
            return pt_mul(FP, H1_COFACTOR, point)
        ctr += 1


def sign(seed: bytes, message: bytes):
    return pt_mul(FP, secret_key(seed), hash_to_g1(message))


def aggregate_g1(points):
    out = None
    for point in points:
        out = pt_add(FP, out, point)
    return out


def aggregate_g2(points):
    out = None
    for point in points:
        out = pt_add(FP2, out, point)
    return out


def verify_aggregate(pks, message: bytes, asig) -> bool:
    """Quorum-cert check: everyone signed the same message.
    e(asig, G2) == e(H(m), apk)."""
    if asig is None or not g1_on_curve(asig):
        return False
    # Subgroup check: an on-curve point with a cofactor component would be
    # accepted by the pairing equation's bilinear structure; require
    # r·asig = O so the signature is in the order-r subgroup.
    if pt_mul(FP, R, asig) is not None:
        return False
    apk = aggregate_g2(pks)
    if apk is None:
        return False
    return pairing(asig, G2) == pairing(hash_to_g1(message), apk)


def verify(pk, message: bytes, sig) -> bool:
    return verify_aggregate([pk], message, sig)
