"""Per-link MAC authenticators for the replica-to-replica plane.

PBFT's scaling argument (Castro & Liskov, OSDI '99 §2) is that
public-key signatures belong only on client requests and certificates;
everything replicas say to each other rides symmetric MAC
authenticators, three orders of magnitude cheaper.  This module is the
sanctioned seam for that machinery: pairwise session keys derived from
a cluster secret, fixed-width HMAC-SHA256 tags appended to transport
frames, and constant-time verification at ingress.

Key schedule: ``link_key(secret, a, b)`` is symmetric in (a, b) — one
session key per undirected link, matching TCP's one-socket-per-peer
model in `runtime/transport.py`.  A real deployment would run a key
exchange; the harness derives keys from a shared ``auth_secret`` so
every node computes the same schedule without a handshake, which is
exactly the MAC trust model (authenticity between the two honest
endpoints, no third-party verifiability — why certificates still need
signatures).

Everything here is host-side ``hmac``/``hashlib``; lint rule W21 confines
those primitives to this package, `mirbft_tpu/ops/`, and
`testengine/signing.py`.
"""

from __future__ import annotations

import hashlib
import hmac

# Tag width in bytes.  16 (128-bit) matches the forgery bound of the
# RLC batch verifier and halves frame overhead vs a full SHA-256 tag.
TAG_LEN = 16

_KEY_CONTEXT = b"mirbft-link-mac-v1"


def link_key(secret: bytes, a: int, b: int) -> bytes:
    """Derive the symmetric session key for the undirected link {a, b}."""
    lo, hi = (a, b) if a <= b else (b, a)
    ctx = _KEY_CONTEXT + lo.to_bytes(8, "little") + hi.to_bytes(8, "little")
    return hmac.new(secret, ctx, hashlib.sha256).digest()


def tag(key: bytes, payload: bytes) -> bytes:
    """MAC tag over a frame payload (truncated HMAC-SHA256)."""
    return hmac.new(key, payload, hashlib.sha256).digest()[:TAG_LEN]


def verify(key: bytes, payload: bytes, tag_bytes: bytes) -> bool:
    """Constant-time tag check."""
    if len(tag_bytes) != TAG_LEN:
        return False
    expected = hmac.new(key, payload, hashlib.sha256).digest()[:TAG_LEN]
    return hmac.compare_digest(expected, tag_bytes)


class LinkAuthenticator:
    """One node's view of the pairwise key schedule.

    ``seal`` appends a tag for the link to ``peer``; ``open`` checks and
    strips the tag of an inbound frame claiming to come from ``peer``.
    Keys are derived lazily and cached — the schedule is O(peers), not
    O(n^2), per node.
    """

    def __init__(self, node_id: int, secret: bytes):
        self.node_id = node_id
        self._secret = secret
        self._keys: dict[int, bytes] = {}

    def _key(self, peer: int) -> bytes:
        key = self._keys.get(peer)
        if key is None:
            key = link_key(self._secret, self.node_id, peer)
            self._keys[peer] = key
        return key

    def seal(self, peer: int, payload: bytes) -> bytes:
        return payload + tag(self._key(peer), payload)

    def open(self, peer: int, payload: bytes):
        """Verified payload without its tag, or None on a bad/short tag."""
        if len(payload) <= TAG_LEN:
            return None
        body, tag_bytes = payload[:-TAG_LEN], payload[-TAG_LEN:]
        if not verify(self._key(peer), body, tag_bytes):
            return None
        return body
