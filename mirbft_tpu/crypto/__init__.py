"""Host-side cryptographic reference implementations.

The accelerator kernels in ``ops/`` are bit-exactness-gated against these
(the same discipline as ops.sha256 vs hashlib).
"""

from . import ed25519_host as ed25519_host
