"""Aggregate quorum certificates: the sanctioned BLS seam.

A quorum certificate proves 2f+1 replicas vouched for the same
statement.  Naively that is 2f+1 signature verifications per
certificate; with BLS aggregation the certificate carries **one** G1
point and costs one pairing check regardless of quorum size — the
second leg of the three-tier authentication model (docs/CRYPTO.md).

This module wraps the host reference (`bls_host`) and the device
aggregation kernel (`ops/bls_g1`) behind a small vote/aggregate/verify
API so consumers (testengine/certs.py, the chaos cert audits) never
touch raw pairing primitives — lint rule W21 enforces that boundary.
Verification outcomes are mirrored to
``mirbft_cert_aggregate_verifies_total{outcome}`` when hooks are live.
"""

from __future__ import annotations

from ..obsv import hooks
from . import bls_host


def secret_key(seed: bytes) -> int:
    return bls_host.secret_key(seed)


def public_key(seed: bytes):
    """Voter public key ([sk]G2) for a vote seed."""
    return bls_host.public_key(seed)


def sign_vote(seed: bytes, statement: bytes):
    """One replica's G1 vote share over a certificate statement."""
    return bls_host.sign(seed, statement)


def verify_vote(pk, statement: bytes, sig) -> bool:
    """Individual vote check — the descent primitive when an aggregate
    fails and the votes are still at hand."""
    return bls_host.verify(pk, statement, sig)


def aggregate(sigs, use_device: bool = True):
    """Collapse vote shares into one aggregate signature point.

    The device path batches the masked G1 sums through `ops/bls_g1`
    (bit-equal to host aggregation); the host path is authoritative when
    no accelerator is attached.  Accepts a list of G1 points, returns
    one G1 point.
    """
    if use_device:
        try:
            from ..ops import bls_g1

            return bls_g1.aggregate_signatures([list(sigs)])[0]
        except Exception:
            pass
    return bls_host.aggregate_g1(list(sigs))


def _record(outcome: str) -> None:
    if hooks.enabled:
        hooks.metrics.counter(
            "mirbft_cert_aggregate_verifies_total", outcome=outcome
        ).inc()


def verify_cert(pks, statement: bytes, asig) -> bool:
    """One-shot certificate check: pairing equation over the aggregate.

    ``pks`` are the signer public keys (the certificate's signer
    bitmap resolved to keys), ``statement`` the certified bytes, and
    ``asig`` the aggregate G1 point.  A mismatched signer set, tampered
    statement, or forged point all fail the single pairing check — no
    per-vote work.
    """
    try:
        ok = bool(bls_host.verify_aggregate(list(pks), statement, asig))
    except Exception:
        ok = False
    _record("ok" if ok else "rejected")
    return ok
