"""Host-side batched Ed25519 verification via random linear combination.

The per-request host oracle (`ed25519_host.verify`) costs two full
double-and-add scalar multiplications per signature — ~5 ms each on a
commodity core, which is what drove BENCH_r04's rung3 verify p99 to
seconds.  Batch verification collapses a whole wave into **one**
multi-scalar multiplication:

    accept the batch  iff  [sum z_i s_i mod L] B
                           == sum [z_i] R_i + sum [z_i k_i mod L] A_i

where ``z_i`` are deterministic ~128-bit Fiat-Shamir coefficients bound
to the entire batch transcript.  A forged item survives only if the
adversary can predict the transcript hash — the standard RLC soundness
argument (probability <= 2^-127).  For an all-valid batch each term is
the identity exactly (the oracle demands equality, not cofactored
equality), so there are **no false rejections**: when the combined check
fails, a binary-split descent isolates the offenders and every verdict
it emits is bit-identical to ``ed25519_host.verify``.

The multi-scalar multiplication uses Pippenger's bucket method over the
same extended twisted-Edwards arithmetic as the host oracle — this
module adds no new curve code, only a different schedule over
`ed25519_host.point_add`.  Cost is roughly ``ceil(b/w) * (n + 2^w)``
point additions for ``n`` terms of ``b``-bit scalars, i.e. well under a
millisecond per signature at wave sizes the rung3 harness produces,
against 5+ ms for the sequential oracle.

Authority contract (see docs/CRYPTO.md): this is the *host* batch
authority — the accelerator path (`ops/ed25519.py`) holds authority only
when a real device backend (tpu/gpu) is attached; on CPU-only hosts the
planes fall back here, never to XLA-on-CPU.
"""

from __future__ import annotations

import hashlib

from . import ed25519_host as host

# Number of random-linear-combination coefficient bits.  128 keeps the
# forgery bound at 2^-127 while halving the MSM windows the R_i terms
# occupy relative to full-width scalars.
Z_BITS = 128

_L = host.L
_B_EXT = host.to_extended(host.BASE)


def _marshal(pk: bytes, message: bytes, signature: bytes):
    """Structural admission, mirroring the oracle's early-outs.

    Returns ``(s, k, A_ext, R_ext)`` or None when the item can never
    verify (bad lengths, non-decodable points, s >= L) — such items are
    rejected on the host without joining the combined check, exactly as
    `ops.ed25519.marshal_signature` rejects them before device launch.
    """
    if len(pk) != 32 or len(signature) != 64:
        return None
    A = host.decompress(pk)
    if A is None:
        return None
    R = host.decompress(signature[:32])
    if R is None:
        return None
    s = int.from_bytes(signature[32:], "little")
    if s >= _L:
        return None
    k = (
        int.from_bytes(
            hashlib.sha512(signature[:32] + pk + message).digest(), "little"
        )
        % _L
    )
    return s, k, A, R


def _coefficients(items) -> list:
    """Deterministic Fiat-Shamir RLC coefficients.

    One SHA-512 transcript binds every (pk, sig, message) in the batch;
    per-item coefficients are derived from the transcript root and the
    item index.  Deterministic derivation keeps the deterministic engine
    replayable; binding the full batch means an adversary choosing any
    item has no freedom over its own (or its neighbours') coefficient.
    """
    root = hashlib.sha512()
    root.update(b"mirbft-ed25519-rlc-v1")
    for pk, message, signature in items:
        root.update(pk)
        root.update(signature)
        root.update(hashlib.sha512(message).digest())
    seed = root.digest()
    out = []
    for i in range(len(items)):
        z = int.from_bytes(
            hashlib.sha512(seed + i.to_bytes(8, "little")).digest(), "little"
        )
        # Top bit forced so every coefficient is full-width and nonzero.
        out.append((z % (1 << Z_BITS)) | (1 << (Z_BITS - 1)))
    return out


def msm(pairs) -> tuple:
    """Pippenger multi-scalar multiplication: sum [scalar] point.

    ``pairs`` is a sequence of ``(scalar, extended_point)``; returns an
    extended point.  Window width adapts to the term count; windows above
    a term's scalar width never touch it, so the 128-bit R-coefficients
    cost half the windows of the 253-bit s/k terms.
    """
    pairs = [(s, p) for s, p in pairs if s]
    if not pairs:
        return host.IDENTITY
    max_bits = max(s.bit_length() for s, _ in pairs)
    n = len(pairs)
    # Balance ceil(b/w)*n window additions against ceil(b/w)*2^w bucket
    # collapses; near-optimal w tracks log2(n).
    w = max(2, min(12, n.bit_length() - 1))
    mask = (1 << w) - 1
    windows = (max_bits + w - 1) // w
    acc = host.IDENTITY
    for win in range(windows - 1, -1, -1):
        if acc is not host.IDENTITY:
            for _ in range(w):
                acc = host.point_add(acc, acc)
        shift = win * w
        buckets = [None] * (mask + 1)
        for s, p in pairs:
            idx = (s >> shift) & mask
            if not idx:
                continue
            cur = buckets[idx]
            buckets[idx] = p if cur is None else host.point_add(cur, p)
        running = host.IDENTITY
        total = host.IDENTITY
        for idx in range(mask, 0, -1):
            b = buckets[idx]
            if b is not None:
                running = host.point_add(running, b)
            if running is not host.IDENTITY:
                total = host.point_add(total, running)
        acc = host.point_add(acc, total)
    return acc


def _combined_check(marshalled, coefficients) -> bool:
    """The one-MSM batch equation over already-marshalled items."""
    c = 0
    pairs = []
    for (s, k, A_ext, R_ext), z in zip(marshalled, coefficients):
        c = (c + z * s) % _L
        pairs.append((z, host.point_negate(R_ext)))
        pairs.append(((z * k) % _L, host.point_negate(A_ext)))
    pairs.append((c, _B_EXT))
    return host.point_equal(msm(pairs), host.IDENTITY)


def _descend(items, marshalled, verdicts, indices) -> None:
    """Binary-split isolation of failing items inside a failed batch.

    Each leaf (single item) is decided by the exact oracle equation, so
    descent verdicts match `ed25519_host.verify` bit-for-bit.
    """
    if len(indices) == 1:
        i = indices[0]
        s, k, A_ext, R_ext = marshalled[i]
        lhs = msm([(s, _B_EXT), (k, host.point_negate(A_ext))])
        verdicts[i] = host.point_equal(lhs, R_ext)
        return
    sub_items = [items[i] for i in indices]
    sub_marshalled = [marshalled[i] for i in indices]
    if _combined_check(sub_marshalled, _coefficients(sub_items)):
        for i in indices:
            verdicts[i] = True
        return
    mid = len(indices) // 2
    _descend(items, marshalled, verdicts, indices[:mid])
    _descend(items, marshalled, verdicts, indices[mid:])


def verify_batch(items, chunk: int = 64) -> list:
    """Batch-verify ``[(pk, message, signature), ...]`` -> list of bool.

    Verdicts are equivalent to calling `ed25519_host.verify` per item
    (identical on every input the descent touches; the all-valid fast
    path accepts exactly the sets the oracle accepts).  ``chunk`` bounds
    the wave a single combined check covers, which bounds the wall time
    of one verification burst — the rung3 p99 ledger measures these
    bursts, so the default keeps each under the 100 ms SLO on a
    commodity core while retaining most of the amortization.
    """
    verdicts = [False] * len(items)
    live: list[int] = []
    marshalled: dict[int, tuple] = {}
    for i, (pk, message, signature) in enumerate(items):
        m = _marshal(bytes(pk), bytes(message), bytes(signature))
        if m is None:
            continue
        marshalled[i] = m
        live.append(i)
    for base in range(0, len(live), chunk):
        indices = live[base : base + chunk]
        sub_items = [items[i] for i in indices]
        sub_marshalled = [marshalled[i] for i in indices]
        if _combined_check(sub_marshalled, _coefficients(sub_items)):
            for i in indices:
                verdicts[i] = True
        else:
            _descend(
                [items[i] for i in range(len(items))],
                [marshalled.get(i) for i in range(len(items))],
                verdicts,
                indices,
            )
    return verdicts
