"""Pure-Python Ed25519 (RFC 8032) — the host reference implementation.

This is the correctness oracle for the batched TPU verifier
(ops/ed25519.py), and the signer used by test harnesses (signing is a
client-side operation; replicas only ever verify — reference: the library
leaves request authentication to the consumer, mirbft.go:297-301, which is
exactly the seam BASELINE.md rung 3 fills with batched sig-verify).

Implemented straight from the RFC 8032 specification over Python bigints.
Not constant-time — fine for a verifier oracle and test signer; never use
for production signing keys.
"""

from __future__ import annotations

import hashlib

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P

_BY = (4 * pow(5, P - 2, P)) % P
_BX = None  # computed below


def _inv(x: int) -> int:
    return pow(x, P - 2, P)


def _sqrt_ratio(u: int, v: int) -> int | None:
    """x with x^2 * v == u (mod P), or None (RFC 8032 §5.1.3)."""
    cand = (u * pow(v, 3, P)) % P * pow((u * pow(v, 7, P)) % P, (P - 5) // 8, P) % P
    if (v * cand * cand) % P == u % P:
        return cand
    if (v * cand * cand) % P == (-u) % P:
        return (cand * pow(2, (P - 1) // 4, P)) % P
    return None


def _recover_x(y: int, sign: int) -> int | None:
    if y >= P:
        return None
    u = (y * y - 1) % P
    v = (D * y * y + 1) % P
    x = _sqrt_ratio(u, v)
    if x is None:
        return None
    if x == 0 and sign == 1:
        return None
    if x % 2 != sign:
        x = P - x
    return x


_BX = _recover_x(_BY, 0)
BASE = (_BX, _BY)  # the standard base point B


# -- point arithmetic (extended twisted Edwards, a = -1) ---------------------


def point_add(p, q):
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = 2 * t1 * t2 * D % P
    d = 2 * z1 * z2 % P
    e, f, g, h = b - a, d - c, d + c, b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


IDENTITY = (0, 1, 1, 0)


def to_extended(affine):
    x, y = affine
    return (x, y, 1, x * y % P)


def scalar_mult(scalar: int, point) -> tuple:
    acc = IDENTITY
    addend = point
    while scalar:
        if scalar & 1:
            acc = point_add(acc, addend)
        addend = point_add(addend, addend)
        scalar >>= 1
    return acc


def point_negate(p):
    x, y, z, t = p
    return ((-x) % P, y, z, (-t) % P)


def point_equal(p, q) -> bool:
    x1, y1, z1, _ = p
    x2, y2, z2, _ = q
    return (x1 * z2 - x2 * z1) % P == 0 and (y1 * z2 - y2 * z1) % P == 0


def compress(p) -> bytes:
    x, y, z, _ = p
    zi = _inv(z)
    x, y = x * zi % P, y * zi % P
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def decompress(data: bytes):
    """Encoded point -> extended coordinates, or None if invalid."""
    if len(data) != 32:
        return None
    raw = int.from_bytes(data, "little")
    sign = raw >> 255
    y = raw & ((1 << 255) - 1)
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % P)


# -- RFC 8032 keygen / sign / verify ----------------------------------------


def _clamp(h: bytes) -> int:
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a


def public_key(seed: bytes) -> bytes:
    h = hashlib.sha512(seed).digest()
    return compress(scalar_mult(_clamp(h), to_extended(BASE)))


def sign(seed: bytes, message: bytes) -> bytes:
    h = hashlib.sha512(seed).digest()
    a = _clamp(h)
    pk = compress(scalar_mult(a, to_extended(BASE)))
    r = int.from_bytes(hashlib.sha512(h[32:] + message).digest(), "little") % L
    r_enc = compress(scalar_mult(r, to_extended(BASE)))
    k = (
        int.from_bytes(
            hashlib.sha512(r_enc + pk + message).digest(), "little"
        )
        % L
    )
    s = (r + k * a) % L
    return r_enc + int.to_bytes(s, 32, "little")


def verify(pk: bytes, message: bytes, signature: bytes) -> bool:
    if len(signature) != 64:
        return False
    a = decompress(pk)
    r = decompress(signature[:32])
    if a is None or r is None:
        return False
    s = int.from_bytes(signature[32:], "little")
    if s >= L:
        return False
    k = (
        int.from_bytes(
            hashlib.sha512(signature[:32] + pk + message).digest(), "little"
        )
        % L
    )
    # [s]B == R + [k]A  <=>  [s]B + [k](-A) == R
    lhs = point_add(
        scalar_mult(s, to_extended(BASE)),
        scalar_mult(k, point_negate(a)),
    )
    return point_equal(lhs, r)
